"""Exercise whole-stage fusion end-to-end in all three modes (CPU jax,
Pallas in interpreter mode).

    JAX_PLATFORMS=cpu python dev/fusion_exercise.py

Two TPC-H-shaped stages, each run under `ballista.tpu.fusion.mode` =
staged, fused_xla, fused_pallas — every mode in a fresh subprocess so
compile caches can't bleed between modes:

- **q1** (scan filter → projection arithmetic → partial aggregate over a
  2-key dictionary domain, money measures). Asserts staged and fused_xla
  are BYTE-IDENTICAL, fused reports `fused_spans >= 2`, staged reports
  its per-span split — and that the fused_pallas request LADDERS DOWN to
  fused_xla (exact int64 money sums are outside the kernel family; the
  fallback must land on-device, not on the CPU engine).
- **syn** (lineitem-shaped: dictionary category keys, f64 measures, a
  selective filter). fused_pallas genuinely runs the Pallas hash-
  aggregate here; counts must be exact and f32 sums within kernel
  tolerance of the staged oracle.

Prints per-mode RunStats deltas (fusion_mode, fused_spans,
fused_kernel_s, trace/compile/exec, staged's span_s) and exits non-zero
on any divergence. The CPU-interpreter run is the correctness rig for
the same code path a real TPU executes; expect fused_pallas to be slow
here, not fast.
"""

import json
import os
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

STATS_MARK = "FUSION_EXERCISE_STATS "
MODES = ("staged", "fused_xla", "fused_pallas")
SYN_SQL = ("select cat, sum(w * (1 - disc)) rev, sum(w) s, count(*) c "
           "from syn where qty < 24 group by cat order by cat")


def q1_sql() -> str:
    with open(os.path.join(ROOT, "benchmarks", "tpch", "queries", "q1.sql")) as f:
        return f.read()


def _save(data_dir: str, tag: str, mode: str, table) -> None:
    import pyarrow.ipc as ipc

    path = os.path.join(data_dir, f"result_{tag}_{mode}.arrow")
    with ipc.new_file(path, table.schema) as sink:
        sink.write_table(table.combine_chunks())


def child(data_dir: str, mode: str) -> None:
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import (
        BallistaConfig,
        EXECUTOR_ENGINE,
        TPU_FUSION_MODE,
        TPU_MIN_ROWS,
    )
    from ballista_tpu.ops.tpu import stage_compiler
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0,
                          TPU_FUSION_MODE: mode})
    ctx = SessionContext(cfg)
    register_tpch(ctx, data_dir)
    ctx.register_parquet("syn", os.path.join(data_dir, "syn.parquet"))

    stats = {}
    for tag, sql in (("q1", q1_sql()), ("syn", SYN_SQL)):
        stage_compiler.RUN_STATS.clear()
        out = ctx.sql(sql).collect()
        if out.num_rows == 0:
            raise SystemExit(f"[{mode}/{tag}] produced no rows")
        _save(data_dir, tag, mode, out)
        stats[tag] = stage_compiler.RUN_STATS.snapshot()
    print(STATS_MARK + json.dumps(stats))


def spawn(data_dir: str, mode: str) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", data_dir, mode],
        env=env, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise SystemExit(f"[{mode}] child failed:\n{proc.stdout}\n{proc.stderr}")
    for line in proc.stdout.splitlines():
        if line.startswith(STATS_MARK):
            return json.loads(line[len(STATS_MARK):])
    raise SystemExit(f"[{mode}] child printed no stats:\n{proc.stdout}")


def load(data_dir: str, tag: str, mode: str):
    import pyarrow.ipc as ipc

    with ipc.open_file(os.path.join(data_dir, f"result_{tag}_{mode}.arrow")) as f:
        return f.read_all()


def report(tag: str, mode: str, stats: dict) -> None:
    print(f"[{tag}/{mode:12s}] fusion_mode={stats.get('fusion_mode')} "
          f"fused_spans={stats.get('fused_spans')} "
          f"fused_kernel_s={stats.get('fused_kernel_s', 0.0):.4f} "
          f"trace_s={stats.get('trace_s', 0.0):.3f} "
          f"compile_s={stats.get('compile_s', 0.0):.3f} "
          f"exec_s={stats.get('exec_s', 0.0):.3f}")
    if stats.get("span_s"):
        spans = "  ".join(f"{k}={v:.4f}s" for k, v in stats["span_s"].items())
        print(f"[{tag}/{mode:12s}]   span_s: {spans}")
    print(f"[{tag}/{mode:12s}]   reason: {stats.get('fusion_reason')}")


def gen_synthetic(data_dir: str) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(42)
    n = 60_000
    pq.write_table(pa.table({
        # 50 categories → G = 64: inside the staged/unrolled budget, so all
        # three modes run their native form (multi-tile G > 128 is covered
        # by tests/test_tpu_fusion.py)
        "cat": rng.choice([f"c{i:03d}" for i in range(50)], n),
        "w": rng.uniform(0.0, 10.0, n),
        "disc": rng.uniform(0.0, 0.1, n),
        "qty": rng.integers(1, 50, n),
    }), os.path.join(data_dir, "syn.parquet"))


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child(sys.argv[2], sys.argv[3])
        return
    import numpy as np

    from ballista_tpu.testing.tpchgen import generate_tpch

    with tempfile.TemporaryDirectory(prefix="fusion-tpch-") as d:
        print(f"generating TPC-H sf0.01 + synthetic under {d} ...")
        generate_tpch(d, scale=0.01, seed=42, files_per_table=2)
        gen_synthetic(d)
        stats = {m: spawn(d, m) for m in MODES}
        results = {(t, m): load(d, t, m) for m in MODES for t in ("q1", "syn")}

    for tag in ("q1", "syn"):
        for m in MODES:
            report(tag, m, stats[m][tag])

    # -- mode routing ------------------------------------------------------
    for tag in ("q1", "syn"):
        for m in ("staged", "fused_xla"):
            got = stats[m][tag].get("fusion_mode")
            if got != m:
                raise SystemExit(f"[{tag}/{m}] ran as {got!r}, not as requested")
    got = stats["fused_pallas"]["q1"].get("fusion_mode")
    if got != "fused_xla":
        raise SystemExit(
            f"[q1/fused_pallas] expected the ladder to land on fused_xla "
            f"(money sums are kernel-ineligible), got {got!r}")
    print("[ladder] q1 fused_pallas request correctly laddered to fused_xla")
    got = stats["fused_pallas"]["syn"].get("fusion_mode")
    if got != "fused_pallas":
        raise SystemExit(f"[syn/fused_pallas] ran as {got!r}, kernel never used")

    # -- span accounting ---------------------------------------------------
    if stats["fused_xla"]["q1"].get("fused_spans", 0) < 2:
        raise SystemExit(
            f"[q1/fused_xla] filter→project→agg stage reported fused_spans="
            f"{stats['fused_xla']['q1'].get('fused_spans')} (< 2)")
    for tag in ("q1", "syn"):
        if not stats["staged"][tag].get("span_s"):
            raise SystemExit(f"[{tag}/staged] no per-span timings recorded")

    # -- parity ------------------------------------------------------------
    for tag in ("q1", "syn"):
        if not results[(tag, "staged")].equals(results[(tag, "fused_xla")]):
            raise SystemExit(
                f"DIVERGENCE: {tag} staged vs fused_xla not byte-identical")
    print("[parity] staged == fused_xla (byte-identical, q1 and syn)")

    ref, pal = results[("syn", "staged")], results[("syn", "fused_pallas")]
    if ref.column_names != pal.column_names or ref.num_rows != pal.num_rows:
        raise SystemExit("DIVERGENCE: syn fused_pallas result shape differs")
    for name in ref.column_names:
        a, b = ref.column(name).to_pandas(), pal.column(name).to_pandas()
        try:
            af, bf = a.astype(float), b.astype(float)
        except (ValueError, TypeError):
            if not a.equals(b):
                raise SystemExit(f"DIVERGENCE: syn column {name} differs")
            continue
        if not np.allclose(af, bf, rtol=2e-5, equal_nan=True):
            raise SystemExit(
                f"DIVERGENCE: syn fused_pallas column {name} beyond kernel "
                f"tolerance (max rel "
                f"{np.nanmax(np.abs(af - bf) / np.maximum(np.abs(bf), 1e-12)):.2e})")
    print("[parity] syn fused_pallas within kernel tolerance (f32 sums)")
    print("fusion exercise passed")


if __name__ == "__main__":
    main()
