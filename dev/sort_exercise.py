"""Exercise the on-device sort / window / top-k family end-to-end in all
three fusion modes (CPU jax, Pallas in interpreter mode).

    JAX_PLATFORMS=cpu python dev/sort_exercise.py

Three stage shapes over an adversarial synthetic table (NaN, ±0.0, NULL
keys, duplicate string values, ties at the LIMIT cut), each run under
`ballista.tpu.fusion.mode` = staged, fused_xla, fused_pallas — every
mode in a fresh subprocess so compile caches can't bleed between modes —
plus one CPU-engine leg that is the byte-parity oracle:

- **ord**: multi-key ORDER BY (ASC string, DESC NULLS FIRST float, int
  tiebreak). Every device mode must match the CPU engine bitwise.
- **topk**: single-key ORDER BY ... LIMIT. Under fused_pallas the fused
  top-k kernel must fire WITHOUT materializing the full sort
  (`topk_invocations` up, `sort_full_materializations` unchanged);
  staged/fused_xla take the full-sort-plus-slice path and must say so.
- **win**: row_number/rank/sum/count OVER (PARTITION BY ... ORDER BY ...)
  with a nullable int measure, then a total-order outer sort so the
  result is deterministic enough to compare bitwise.

Parity is asserted per column over Arrow IPC stream bytes — bitwise
(NaN payloads, ±0.0 signs) without the chunk-slicing layout artifacts a
whole-table stream picks up from `Table.slice`. Prints per-mode counter
deltas and fusion decisions; exits non-zero on any divergence. The
CPU-interpreter run is the correctness rig for the same code path a real
TPU executes; expect fused_pallas to be slow here, not fast.
"""

import io
import json
import os
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

STATS_MARK = "SORT_EXERCISE_STATS "
CPU = "cpu"
MODES = ("staged", "fused_xla", "fused_pallas")
TOPK_K = 37

QUERIES = {
    "ord": ("SELECT g, f, i FROM s "
            "ORDER BY g ASC, f DESC NULLS FIRST, i ASC"),
    "topk": f"SELECT f, i, g FROM s ORDER BY f DESC LIMIT {TOPK_K}",
    "win": ("SELECT g, i, f, "
            "row_number() OVER (PARTITION BY g ORDER BY f DESC) rn, "
            "rank() OVER (PARTITION BY g ORDER BY i) rk, "
            "sum(i) OVER (PARTITION BY g ORDER BY i) ws, "
            "count(i) OVER (PARTITION BY g ORDER BY i) wc "
            "FROM s ORDER BY g, rn"),
}


def gen_table(data_dir: str, n: int = 4000) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(7)
    f = rng.integers(-50, 50, n).astype(np.float64)
    f[::9] = np.nan
    f[::13] = 0.0
    f[1::13] = -0.0
    fl = f.tolist()
    for j in range(0, n, 17):
        fl[j] = None
    pq.write_table(pa.table({
        "g": pa.array([["aa", "b", "aa", "zz", "m", "q"][j % 6]
                       for j in range(n)]),
        "f": pa.array(fl, pa.float64()),
        "i": pa.array([None if j % 11 == 0 else int(v) for j, v in
                       enumerate(rng.integers(0, 9, n))], pa.int32()),
    }), os.path.join(data_dir, "s.parquet"))


def _save(data_dir: str, tag: str, mode: str, table) -> None:
    import pyarrow.ipc as ipc

    path = os.path.join(data_dir, f"result_{tag}_{mode}.arrow")
    with ipc.new_file(path, table.schema) as sink:
        sink.write_table(table.combine_chunks())


def child(data_dir: str, mode: str) -> None:
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import (
        BallistaConfig,
        EXECUTOR_ENGINE,
        TPU_FUSION_MODE,
        TPU_MIN_ROWS,
    )
    from ballista_tpu.ops.tpu import stage_compiler
    from ballista_tpu.ops.tpu.sort_window import counters_snapshot

    if mode == CPU:
        cfg = BallistaConfig({EXECUTOR_ENGINE: "cpu"})
    else:
        cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0,
                              TPU_FUSION_MODE: mode})
    ctx = SessionContext(cfg)
    ctx.register_parquet("s", os.path.join(data_dir, "s.parquet"))

    stats = {}
    for tag, sql in QUERIES.items():
        stage_compiler.RUN_STATS.clear()
        before = counters_snapshot()
        out = ctx.sql(sql).collect()
        if out.num_rows == 0:
            raise SystemExit(f"[{mode}/{tag}] produced no rows")
        _save(data_dir, tag, mode, out)
        after = counters_snapshot()
        run = stage_compiler.RUN_STATS.snapshot()
        stats[tag] = {
            "delta": {k: round(after[k] - before[k], 4) for k in after},
            "fusion_mode": run.get("fusion_mode"),
            "fusion_reason": run.get("fusion_reason"),
            "device_bytes": run.get("device_bytes"),
        }
    print(STATS_MARK + json.dumps(stats))


def spawn(data_dir: str, mode: str) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", data_dir, mode],
        env=env, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise SystemExit(f"[{mode}] child failed:\n{proc.stdout}\n{proc.stderr}")
    for line in proc.stdout.splitlines():
        if line.startswith(STATS_MARK):
            return json.loads(line[len(STATS_MARK):])
    raise SystemExit(f"[{mode}] child printed no stats:\n{proc.stdout}")


def load(data_dir: str, tag: str, mode: str):
    import pyarrow.ipc as ipc

    with ipc.open_file(os.path.join(data_dir, f"result_{tag}_{mode}.arrow")) as f:
        return f.read_all()


def column_bytes(tbl) -> list:
    import pyarrow as pa
    import pyarrow.ipc as ipc

    out = []
    for c in tbl.column_names:
        one = pa.table({c: tbl.column(c).combine_chunks()})
        buf = io.BytesIO()
        with ipc.new_stream(buf, one.schema) as w:
            w.write_table(one)
        out.append(buf.getvalue())
    return out


def report(tag: str, mode: str, st: dict) -> None:
    d = st["delta"]
    print(f"[{tag}/{mode:12s}] fusion_mode={st.get('fusion_mode')} "
          f"sort={d.get('sort_invocations', 0)} "
          f"topk={d.get('topk_invocations', 0)} "
          f"win={d.get('window_invocations', 0)} "
          f"full_mat={d.get('sort_full_materializations', 0)} "
          f"kept={d.get('topk_rows_kept', 0)} "
          f"parts={d.get('window_partitions', 0)} "
          f"kernel_s={d.get('sort_kernel_s', 0.0):.4f}")
    print(f"[{tag}/{mode:12s}]   reason: {st.get('fusion_reason')}")


def run_exercise() -> dict:
    with tempfile.TemporaryDirectory(prefix="sort-exercise-") as d:
        print(f"generating adversarial table under {d} ...")
        gen_table(d)
        stats = {m: spawn(d, m) for m in (CPU,) + MODES}
        results = {(t, m): load(d, t, m)
                   for m in (CPU,) + MODES for t in QUERIES}

    for tag in QUERIES:
        for m in MODES:
            report(tag, m, stats[m][tag])

    # -- mode routing ------------------------------------------------------
    for tag in QUERIES:
        for m in MODES:
            got = stats[m][tag].get("fusion_mode")
            if got != m:
                raise SystemExit(f"[{tag}/{m}] ran as {got!r}, not as requested")
        if stats[CPU][tag]["delta"].get("sort_invocations") or \
                stats[CPU][tag]["delta"].get("window_invocations"):
            raise SystemExit(f"[{tag}/cpu] CPU oracle leg touched device code")

    # -- counters: stage family actually ran on the requested rung ---------
    for m in MODES:
        if stats[m]["ord"]["delta"].get("sort_invocations", 0) < 1:
            raise SystemExit(f"[ord/{m}] device sort never ran")
        if stats[m]["win"]["delta"].get("window_invocations", 0) < 1:
            raise SystemExit(f"[win/{m}] device window scan never ran")
        if stats[m]["win"]["delta"].get("window_partitions", 0) < 1:
            raise SystemExit(f"[win/{m}] no window partitions counted")

    # -- the tentpole claim: fused top-k never materializes the full sort --
    d = stats["fused_pallas"]["topk"]["delta"]
    if d.get("topk_invocations", 0) < 1:
        raise SystemExit("[topk/fused_pallas] fused top-k kernel never fired")
    if d.get("sort_full_materializations", 0) != 0:
        raise SystemExit(
            "[topk/fused_pallas] LIMIT sort materialized the full sort "
            f"({d['sort_full_materializations']} times) — the fused cut "
            "was bypassed")
    if d.get("topk_rows_kept", 0) != TOPK_K:
        raise SystemExit(
            f"[topk/fused_pallas] kept {d.get('topk_rows_kept')} rows, "
            f"wanted {TOPK_K}")
    print(f"[topk] fused_pallas kept exactly {TOPK_K} rows with zero "
          "full-sort materializations")
    for m in ("staged", "fused_xla"):
        if stats[m]["topk"]["delta"].get("sort_full_materializations", 0) < 1:
            raise SystemExit(
                f"[topk/{m}] expected the full-sort-plus-slice path")

    # -- parity: every device rung bitwise-matches the CPU engine ----------
    for tag in QUERIES:
        ref = column_bytes(results[(tag, CPU)])
        for m in MODES:
            got = column_bytes(results[(tag, m)])
            if ref != got:
                bad = [results[(tag, m)].column_names[j]
                       for j in range(len(ref)) if ref[j] != got[j]]
                raise SystemExit(
                    f"DIVERGENCE: {tag}/{m} vs cpu engine differs in "
                    f"column(s) {bad}")
    print("[parity] all device rungs byte-identical to the CPU engine "
          "(ord, topk, win)")
    print("sort exercise passed")
    return stats


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child(sys.argv[2], sys.argv[3])
        return
    run_exercise()


if __name__ == "__main__":
    main()
