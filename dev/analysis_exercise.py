"""End-to-end exercise of the engine invariant analyzer (ISSUE 11).

Runs (1) the full AST lint suite over the repo and (2) the static plan
verifier over real planner output: the TPC-H q3 stage DAG, its
ExecutionGraph, and a mesh-fused q1 DAG — then proves the verifier has
teeth by corrupting each plan and requiring a rejection.

Usage: python dev/analysis_exercise.py   (exit 0 = everything holds)
"""

from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))


def _stages(ctx, n: int, job: str):
    from ballista_tpu.scheduler.planner import DistributedPlanner

    with open(os.path.join(ROOT, "benchmarks", "tpch", "queries", f"q{n}.sql"),
              encoding="utf-8") as f:
        sql = f.read()
    physical = ctx.create_physical_plan(ctx.sql(sql).plan)
    return DistributedPlanner(job).plan_query_stages(physical)


def main() -> int:
    from tpch_plan_stability.fixtures import stats_context

    from ballista_tpu.analysis import Analyzer
    from ballista_tpu.analysis.plan_check import verify_graph, verify_stages
    from ballista_tpu.config import (
        EXECUTOR_ENGINE,
        TPU_MESH_ENABLED,
        TPU_MIN_ROWS,
        BallistaConfig,
    )
    from ballista_tpu.scheduler.planner import merge_mesh_stages
    from ballista_tpu.scheduler.state.execution_graph import ExecutionGraph

    failures = 0

    # 1. the lint suite
    report = Analyzer().run()
    print(report.render())
    if not report.ok:
        failures += 1

    # 2. plan verifier over the q3 stage DAG + its graph
    ctx = stats_context()
    stages = _stages(ctx, 3, "exercise-q3")
    v = verify_stages(stages)
    print(f"q3 stages: {len(stages)} stages, {len(v)} violation(s)")
    failures += bool(v)
    graph = ExecutionGraph("exercise-q3", "q3", "sess", stages)
    gv = verify_graph(graph)
    print(f"q3 graph: {len(gv)} violation(s)")
    failures += bool(gv)

    # 3. mesh-fused q1 DAG
    tctx = stats_context(engine="tpu")
    mesh_cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0,
                               TPU_MESH_ENABLED: True})
    merged = merge_mesh_stages(_stages(tctx, 1, "exercise-q1"), mesh_cfg)
    mv = verify_stages(merged)
    n_mesh = sum(1 for s in merged if s.mesh)
    print(f"q1 mesh-merged: {len(merged)} stages ({n_mesh} mesh), {len(mv)} violation(s)")
    failures += bool(mv) or not n_mesh

    # 4. the verifier must REJECT corrupted DAGs
    bad = _stages(ctx, 3, "exercise-bad")
    bad[0].mesh = True  # no exchange in that plan
    codes = {x.code for x in verify_stages(bad)}
    print(f"corrupted q3 (mesh flag): rejected with {sorted(codes)}")
    failures += "mesh-flag" not in codes

    bad2 = _stages(ctx, 3, "exercise-bad2")
    bad2[0].output_partitions += 1  # producer now disagrees with every reader
    codes2 = {x.code for x in verify_stages(bad2)}
    print(f"corrupted q3 (partitions): rejected with {sorted(codes2)}")
    failures += not codes2

    if failures:
        print(f"FAILED: {failures} front(s) broken", file=sys.stderr)
        return 1
    print("OK: lint suite clean, verifier accepts real plans and rejects corrupt ones")
    return 0


if __name__ == "__main__":
    sys.exit(main())
