"""Exercise the TPU out-of-core ladder end-to-end (CPU jax).

    JAX_PLATFORMS=cpu python dev/oom_exercise.py

Two legs:

1. grace — TPC-H q3 runs unconstrained to learn its join stage's working
   set W, then re-runs under an explicit HBM budget of W-1 bytes. The
   admission planner must grace-split the join build (`hbm_plan =
   grace_split`, `grace_splits > 0`) and the result must be
   byte-identical to the unconstrained run.
2. chaos — a standalone (executor-path) q3 with `chaos.mode = hbm_oom`
   injecting one synthetic RESOURCE_EXHAUSTED on the first device upload
   of each task. The runtime rung must spill + retry (`hbm_oom_retries
   ≥ 1`, nonzero spill counters) and still return the baseline bytes.

Exits non-zero if either leg fails.
"""

import os
import re
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

_WORKING_RE = re.compile(r"working set (\d+) B")


def q3_sql() -> str:
    with open(os.path.join(ROOT, "benchmarks", "tpch", "queries", "q3.sql")) as f:
        return f.read()


def _fresh():
    from ballista_tpu.ops.tpu import stage_compiler

    stage_compiler.clear_device_caches()
    stage_compiler.RUN_STATS.clear()


def _join_stage_recs(stages: dict) -> list[dict]:
    return [rec for rec in stages.values()
            if _WORKING_RE.search(str(rec.get("hbm_plan_reason", "")))]


def run_q3(data_dir: str, extra_cfg: dict | None = None, standalone: bool = False):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import EXECUTOR_ENGINE, TPU_MIN_ROWS, BallistaConfig
    from ballista_tpu.ops.tpu import stage_compiler
    from ballista_tpu.testing.tpchgen import register_tpch

    _fresh()
    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0,
                          **(extra_cfg or {})})
    ctx = (SessionContext.standalone(cfg, num_executors=1, vcores=2)
           if standalone else SessionContext(cfg))
    try:
        register_tpch(ctx, data_dir)
        out = ctx.sql(q3_sql()).collect()
    finally:
        if standalone:
            ctx.shutdown()
    if out.num_rows == 0:
        raise SystemExit("[q3] produced no rows")
    return out, stage_compiler.RUN_STATS.stages()


def leg_grace(data_dir: str) -> None:
    from ballista_tpu.config import TPU_HBM_BUDGET_BYTES
    from ballista_tpu.ops.tpu import hbm

    whole, stages = run_q3(data_dir)
    recs = _join_stage_recs(stages)
    if not recs:
        raise SystemExit(f"[grace] no join-stage hbm plan recorded: {stages}")
    working = max(int(_WORKING_RE.search(r["hbm_plan_reason"]).group(1))
                  for r in recs)

    graced, stages = run_q3(data_dir, {TPU_HBM_BUDGET_BYTES: working - 1})
    recs = [r for r in _join_stage_recs(stages)
            if r.get("hbm_plan") == hbm.GRACE_SPLIT]
    if not recs or not any(r.get("grace_splits", 0) > 0 for r in recs):
        raise SystemExit(f"[grace] budget {working - 1} B did not grace-split: "
                         f"{stages}")
    if not graced.equals(whole):
        raise SystemExit("[grace] grace-split result differs from the "
                         "unconstrained run")
    splits = max(r["grace_splits"] for r in recs)
    print(f"[grace] ok: working set {working} B, budget {working - 1} B → "
          f"{splits} sub-buckets, byte-identical")


def leg_chaos(data_dir: str) -> None:
    from ballista_tpu.config import CHAOS_ENABLED, CHAOS_MODE
    from ballista_tpu.ops.tpu import hbm

    baseline, _ = run_q3(data_dir, standalone=True)
    os.environ["BALLISTA_CHAOS_HBM_BUDGET"] = str(1 << 30)
    os.environ["BALLISTA_CHAOS_HBM_OOM_N"] = "1"
    try:
        chaotic, stages = run_q3(
            data_dir, {CHAOS_ENABLED: True, CHAOS_MODE: "hbm_oom"},
            standalone=True)
    finally:
        os.environ.pop("BALLISTA_CHAOS_HBM_BUDGET", None)
        os.environ.pop("BALLISTA_CHAOS_HBM_OOM_N", None)
        hbm.disarm_chaos()
    retries = max((int(r.get("hbm_oom_retries", 0)) for r in stages.values()),
                  default=0)
    spills = max((int(r.get("hbm_spill_events", 0)) for r in stages.values()),
                 default=0)
    if retries < 1:
        raise SystemExit(f"[chaos] injected OOM produced no spill+retry: {stages}")
    if not chaotic.equals(baseline):
        raise SystemExit("[chaos] post-OOM result differs from baseline")
    print(f"[chaos] ok: {retries} spill+retry stage re-run(s), "
          f"{spills} pool demotion(s), byte-identical")


def main() -> None:
    from ballista_tpu.testing.tpchgen import generate_tpch

    with tempfile.TemporaryDirectory(prefix="oom-tpch-") as d:
        print(f"generating TPC-H sf0.01 under {d} ...")
        generate_tpch(d, scale=0.01, seed=42, files_per_table=2)
        leg_grace(d)
        leg_chaos(d)
    print("oom exercise passed")


if __name__ == "__main__":
    main()
