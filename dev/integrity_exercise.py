"""Exercise end-to-end shuffle integrity on a tiny TPC-H dataset.

    JAX_PLATFORMS=cpu python dev/integrity_exercise.py

Two legs of TPC-H q3 (a multi-stage join + aggregation, so shuffle bytes
actually cross the Flight data plane both directions), all reads forced
remote so colocated in-proc executors can't short-circuit to local files:

1. clean — baseline run; result checked against the pandas oracle.
2. corrupt — the SAME run under chaos corrupt-once mode: the shared
   Flight server bit-flips the FIRST serve of every shuffle range
   (seeded, deterministic). Every fetch therefore sees corrupt bytes
   once, the reader's checksum verification catches each one, and the
   retry-once-in-place refetch heals it. The leg must produce the
   byte-identical result, and the integrity counters must show the
   corruption was actually seen and retried (not silently decoded).

Exits non-zero if either leg's result is wrong or the corrupt leg's
counters stayed at zero (which would mean the chaos never armed and the
leg proved nothing).
"""

import os
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

Q = 3


def run_leg(name: str, data_dir: str):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import (
        DEFAULT_SHUFFLE_PARTITIONS,
        SHUFFLE_READER_FORCE_REMOTE,
        BallistaConfig,
    )
    from ballista_tpu.shuffle.integrity import INTEGRITY
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 4,
                          SHUFFLE_READER_FORCE_REMOTE: True})
    before = INTEGRITY.snapshot()
    ctx = SessionContext.standalone(cfg, num_executors=2, vcores=2)
    register_tpch(ctx, data_dir)
    try:
        with open(os.path.join(ROOT, "benchmarks", "tpch", "queries",
                               f"q{Q}.sql")) as f:
            table = ctx.sql(f.read()).collect()
    finally:
        ctx.shutdown()
    after = INTEGRITY.snapshot()
    delta = {k: after[k] - before.get(k, 0) for k in after}
    print(f"[{name}] rows={table.num_rows}  integrity delta={delta}")
    return table, delta


def main() -> None:
    from ballista_tpu.testing.reference import compare_results, load_tables, run_reference
    from ballista_tpu.testing.tpchgen import generate_tpch

    with tempfile.TemporaryDirectory(prefix="integrity-tpch-") as d:
        print(f"generating TPC-H sf0.01 under {d} ...")
        generate_tpch(d, scale=0.01, seed=42, files_per_table=2)

        clean, clean_delta = run_leg("clean", d)
        if clean_delta.get("checksum_failures"):
            raise SystemExit("[clean] saw checksum failures without chaos — "
                             f"writer/reader disagree: {clean_delta}")
        ref = run_reference(Q, load_tables(d))
        problems = compare_results(clean, ref, Q)
        if problems:
            raise SystemExit(f"[clean] wrong result vs oracle: {problems}")

        # arm serve-time corruption BEFORE the cluster (the Flight server
        # reads these at construction); once-mode heals on the refetch
        os.environ["BALLISTA_CHAOS_CORRUPT_P"] = "1.0"
        os.environ["BALLISTA_CHAOS_CORRUPT_ONCE"] = "1"
        os.environ["BALLISTA_CHAOS_SEED"] = "7"
        try:
            corrupt, delta = run_leg("corrupt", d)
        finally:
            for k in ("BALLISTA_CHAOS_CORRUPT_P", "BALLISTA_CHAOS_CORRUPT_ONCE",
                      "BALLISTA_CHAOS_SEED"):
                os.environ.pop(k, None)

        problems = compare_results(corrupt, ref, Q)
        if problems:
            raise SystemExit(f"[corrupt] result diverged under healed "
                             f"corruption: {problems}")
        if delta.get("checksum_failures", 0) < 1 or delta.get("corruption_retries", 0) < 1:
            raise SystemExit(f"[corrupt] chaos never bit — counters {delta}; "
                             "the leg proved nothing")

    print("integrity exercise passed")


if __name__ == "__main__":
    main()
