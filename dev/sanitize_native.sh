#!/bin/sh
# Sanitizer leg for native/ (SURVEY §5: C++ in the data plane makes
# TSAN/ASAN necessary, not optional).
#
#   dev/sanitize_native.sh asan     # address+UB sanitizer (default)
#   dev/sanitize_native.sh tsan     # thread sanitizer
#
# Builds sanitized variants of the row router and the Flight shuffle
# server into native/sanitize/, then drives them through the SAME python
# wire-contract exercises the unit tests use: hash/route parity over
# random and adversarial inputs, and a client session against the
# sanitized Flight server (do_get both layouts, raw-block transport,
# containment rejections, job GC). A sanitizer report fails the script.
set -e
MODE="${1:-asan}"
case "$MODE" in
  asan) FLAGS="-fsanitize=address,undefined -fno-omit-frame-pointer -g -O1" ;;
  tsan) FLAGS="-fsanitize=thread -fno-omit-frame-pointer -g -O1" ;;
  *) echo "usage: $0 [asan|tsan]" >&2; exit 2 ;;
esac

cd "$(dirname "$0")/../native"
OUT="sanitize"
mkdir -p "$OUT"

g++ $FLAGS -shared -fPIC -o "$OUT/libballista_native_$MODE.so" row_router.cpp
echo "built $OUT/libballista_native_$MODE.so"

PYA="$(python -c 'import os, pyarrow; print(os.path.dirname(pyarrow.__file__))')"
AR_SO="$(ls "$PYA"/libarrow.so.* 2>/dev/null | head -1)"
FL_SO="$(ls "$PYA"/libarrow_flight.so.* 2>/dev/null | head -1)"
g++ -std=c++20 $FLAGS -I"$PYA/include" flight_shuffle.cpp \
    -o "$OUT/ballista-flight-server-$MODE" \
    -L"$PYA" -l:"$(basename "$AR_SO")" -l:"$(basename "$FL_SO")" \
    -Wl,-rpath,"$PYA"
echo "built $OUT/ballista-flight-server-$MODE"

cd ..
if [ "$MODE" = "asan" ]; then
  # ASAN inside a sanitized .so loaded by an unsanitized python needs the
  # runtime preloaded into the python process for the ROUTER leg.
  RT="$(g++ -print-file-name=libasan.so)"
  env SAN_MODE="$MODE" SAN_LEG=router PYTHONPATH="$(pwd)" \
      LD_PRELOAD="$RT" ASAN_OPTIONS="detect_leaks=0" \
      JAX_PLATFORMS=cpu python dev/sanitize_exercise.py
else
  echo "(tsan: router leg skipped — TSAN needs a whole-program build, and" \
       "preloading libtsan into CPython deadlocks; the multithreaded risk" \
       "surface is the Flight server, checked below in its own process)"
fi
# Flight server leg: the SERVER process is the sanitized one (its runtime
# links in at compile time); the python client stays unsanitized. TSAN
# needs suppressions for the unsanitized arrow/grpc libs (their internal
# synchronization is invisible to the tool).
TSAN_OPTIONS="suppressions=$(pwd)/dev/tsan_suppressions.txt exitcode=66 halt_on_error=0" \
    env SAN_MODE="$MODE" SAN_LEG=flight PYTHONPATH="$(pwd)" \
    JAX_PLATFORMS=cpu python dev/sanitize_exercise.py
echo "sanitizer leg ($MODE) PASSED"
