"""Exercise the coalesced, zero-copy shuffle fetch path end-to-end.

    JAX_PLATFORMS=cpu python dev/shuffle_exercise.py

Two legs:

1. correctness — TPC-H q5 on a 2-executor StandaloneCluster with every
   shuffle read forced over Arrow Flight, run with fetch coalescing ON
   and OFF; both runs must agree (the acceptance criterion for the
   coalesced wire protocol).
2. rpc-count — a direct writer→server→ShuffleReaderExec harness with
   M=8 map tasks and R=4 reduce partitions on one server (E=1); the
   coalesced run must make exactly R fetch RPCs (≤ E·R, i.e. at most
   one per executor per reduce partition) where the uncoalesced run
   makes M·R.

Exits non-zero if either leg fails.
"""

import os
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

M, R = 8, 4


def q5_sql() -> str:
    with open(os.path.join(ROOT, "benchmarks", "tpch", "queries", "q5.sql")) as f:
        return f.read()


def run_q5(data_dir: str, coalesce: bool):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import (
        DEFAULT_SHUFFLE_PARTITIONS,
        SHUFFLE_FETCH_COALESCE,
        SHUFFLE_READER_FORCE_REMOTE,
        BallistaConfig,
    )
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 4,
        SHUFFLE_READER_FORCE_REMOTE: True,
        SHUFFLE_FETCH_COALESCE: coalesce,
    })
    ctx = SessionContext.standalone(cfg, num_executors=2, vcores=2)
    register_tpch(ctx, data_dir)
    try:
        return ctx.sql(q5_sql()).collect()
    finally:
        ctx.shutdown()


def leg_correctness(data_dir: str) -> None:
    on = run_q5(data_dir, coalesce=True)
    off = run_q5(data_dir, coalesce=False)
    key = on.column_names[0]
    on = on.sort_by(key).to_pydict()
    off = off.sort_by(key).to_pydict()
    if on[key] != off[key]:
        raise SystemExit(f"[q5] group keys differ: {on[key]} vs {off[key]}")
    for col in on:
        for a, b in zip(on[col], off[col]):
            if isinstance(a, float):
                if abs(a - b) > 1e-6 * max(1.0, abs(a)):
                    raise SystemExit(f"[q5] {col}: {a} != {b}")
            elif a != b:
                raise SystemExit(f"[q5] {col}: {a} != {b}")
    print(f"[q5] ok: coalesced and uncoalesced agree ({on[key]})")


def read_all(work_dir: str, port: int, coalesce: bool) -> dict:
    """Run ShuffleReaderExec forced-remote over the server; return row count."""
    import pyarrow as pa

    from ballista_tpu.config import (
        SHUFFLE_FETCH_COALESCE,
        SHUFFLE_READER_FORCE_REMOTE,
        BallistaConfig,
    )
    from ballista_tpu.plan.physical import TaskContext
    from ballista_tpu.plan.schema import DFSchema
    from ballista_tpu.shuffle.reader import ShuffleReaderExec
    from ballista_tpu.shuffle.types import PartitionLocation, PartitionStats

    stage_dir = os.path.join(work_dir, "ex-job", "1")
    per_part: dict[int, list] = {p: [] for p in range(R)}
    for root, _, files in os.walk(stage_dir):
        for f in sorted(files):
            if f.endswith(".idx"):
                continue
            p = int(os.path.basename(root))
            per_part[p].append(os.path.join(root, f))
    locs = [
        [
            PartitionLocation(
                map_partition=m, job_id="ex-job", stage_id=1,
                output_partition=p, executor_id="e0", host="127.0.0.1",
                flight_port=port, path=path, layout="hash",
                stats=PartitionStats(0, 0, 0),
            )
            for m, path in enumerate(per_part[p])
        ]
        for p in range(R)
    ]
    schema = DFSchema.from_arrow(
        pa.schema([("k", pa.int64()), ("v", pa.int64())]), "t")
    ctx = TaskContext(BallistaConfig({
        SHUFFLE_READER_FORCE_REMOTE: True,
        SHUFFLE_FETCH_COALESCE: coalesce,
    }))
    rd = ShuffleReaderExec(schema, locs)
    rows = 0
    for p in range(R):
        for b in rd.execute(p, ctx):
            rows += b.num_rows
    return {"rows": rows}


def leg_rpc_count() -> None:
    import numpy as np
    import pyarrow as pa

    from ballista_tpu.config import SORT_SHUFFLE_ENABLED, BallistaConfig
    from ballista_tpu.flight.server import start_flight_server
    from ballista_tpu.plan.expressions import col
    from ballista_tpu.plan.physical import MemoryScanExec, TaskContext
    from ballista_tpu.plan.schema import DFSchema
    from ballista_tpu.shuffle.writer import ShuffleWriterExec

    rng = np.random.default_rng(3)
    batches = [
        pa.record_batch({"k": pa.array(rng.integers(0, 1 << 20, 2000)),
                         "v": pa.array(rng.integers(0, 100, 2000))})
        for _ in range(M)
    ]
    total = sum(b.num_rows for b in batches)
    with tempfile.TemporaryDirectory(prefix="shuffle-ex-") as work:
        scan = MemoryScanExec(DFSchema.from_arrow(batches[0].schema), batches,
                              partitions=M)
        writer = ShuffleWriterExec(scan, "ex-job", 1, R, [col("k")],
                                   sort_shuffle=False)
        wctx = TaskContext(BallistaConfig({SORT_SHUFFLE_ENABLED: False}),
                           work_dir=work)
        for m in range(M):
            for _ in writer.execute(m, wctx):
                pass
        server, port = start_flight_server(work, "127.0.0.1", 0)
        try:
            base = dict(server.stats)
            got = read_all(work, port, coalesce=False)
            uncoalesced = {k: server.stats[k] - base[k] for k in base}
            if got["rows"] != total:
                raise SystemExit(f"[rpc] uncoalesced read {got['rows']} rows, "
                                 f"expected {total}")

            base = dict(server.stats)
            got = read_all(work, port, coalesce=True)
            coalesced = {k: server.stats[k] - base[k] for k in base}
            if got["rows"] != total:
                raise SystemExit(f"[rpc] coalesced read {got['rows']} rows, "
                                 f"expected {total}")
        finally:
            server.shutdown()

    if uncoalesced["block_rpc"] != M * R:
        raise SystemExit(f"[rpc] expected {M * R} uncoalesced block RPCs, "
                         f"saw {uncoalesced['block_rpc']}")
    # one server == one executor, so the bound "≤ E·R" means exactly R here
    rpcs = coalesced["coalesced_rpc"]
    if rpcs != R or coalesced["block_rpc"] != 0:
        raise SystemExit(f"[rpc] expected {R} coalesced RPCs and 0 block RPCs, "
                         f"saw {coalesced}")
    print(f"[rpc] ok: M·R={M * R} RPCs uncoalesced → {rpcs} coalesced "
          f"(one per executor per reduce partition)")


def main() -> None:
    from ballista_tpu.testing.tpchgen import generate_tpch

    with tempfile.TemporaryDirectory(prefix="shuffle-tpch-") as d:
        print(f"generating TPC-H sf0.01 under {d} ...")
        generate_tpch(d, scale=0.01, seed=42, files_per_table=2)
        leg_correctness(d)

    leg_rpc_count()
    print("shuffle exercise passed")


if __name__ == "__main__":
    main()
