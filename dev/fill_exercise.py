"""Exercise the TPU stage cold path end-to-end (CPU jax).

    JAX_PLATFORMS=cpu python dev/fill_exercise.py

Two legs:

1. overlap — a cold TPC-H q1 with `ballista.tpu.compile.overlap` on must
   start compiling under the device fill: RUN_STATS reports
   `compile_overlap_s > 0` (chunked uploads stretch the fill enough to
   make the overlap deterministic on fast CPU backends).
2. restart — two fresh processes run the same q1 stage sharing one
   persistent compile cache dir (`BALLISTA_TPU_COMPILE_CACHE`). The warm
   process must fetch its XLA binary from disk: warm `xla_compile_s`
   ≤ 0.1× cold, warm `compile_s` strictly below cold, and the warm run
   reports persistent-cache hits.

Exits non-zero if either leg fails.
"""

import json
import os
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

STATS_MARK = "FILL_EXERCISE_STATS "


def q1_sql() -> str:
    with open(os.path.join(ROOT, "benchmarks", "tpch", "queries", "q1.sql")) as f:
        return f.read()


def run_q1(data_dir: str, extra_cfg: dict | None = None) -> dict:
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import EXECUTOR_ENGINE, BallistaConfig
    from ballista_tpu.ops.tpu import runtime, stage_compiler
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", **(extra_cfg or {})})
    ctx = SessionContext(cfg)
    register_tpch(ctx, data_dir)
    out = ctx.sql(q1_sql()).collect()
    if out.num_rows == 0:
        raise SystemExit("[q1] produced no rows")
    stats = stage_compiler.RUN_STATS.snapshot()
    stats["_cache"] = runtime.compile_cache_stats()
    return stats


def leg_overlap(data_dir: str) -> None:
    from ballista_tpu.config import TPU_FILL_CHUNK_ROWS

    stats = run_q1(data_dir, {TPU_FILL_CHUNK_ROWS: 4096})
    ov = stats.get("compile_overlap_s", 0.0)
    if ov <= 0:
        raise SystemExit(f"[overlap] no compile/fill overlap recorded: {stats}")
    serial_total = stats["fill_s"] + stats.get("compile_s", 0.0) + stats["exec_s"]
    print(f"[overlap] ok: compile_overlap_s={ov:.3f} hidden under "
          f"fill_s={stats['fill_s']:.3f} (serial total would be "
          f"~{serial_total:.3f}s, compile_s={stats.get('compile_s', 0.0):.3f})")


def child(data_dir: str) -> None:
    stats = run_q1(data_dir)
    print(STATS_MARK + json.dumps(stats))


def spawn(data_dir: str, cache_dir: str) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BALLISTA_TPU_COMPILE_CACHE"] = cache_dir
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", data_dir],
        env=env, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise SystemExit(f"[restart] child failed:\n{proc.stdout}\n{proc.stderr}")
    for line in proc.stdout.splitlines():
        if line.startswith(STATS_MARK):
            return json.loads(line[len(STATS_MARK):])
    raise SystemExit(f"[restart] child printed no stats:\n{proc.stdout}")


def leg_restart(data_dir: str) -> None:
    with tempfile.TemporaryDirectory(prefix="fill-xla-cache-") as cache_dir:
        cold = spawn(data_dir, cache_dir)
        if not os.listdir(cache_dir):
            raise SystemExit("[restart] cold run persisted nothing")
        warm = spawn(data_dir, cache_dir)
    cold_x, warm_x = cold.get("xla_compile_s", 0.0), warm.get("xla_compile_s", 0.0)
    if warm_x > 0.1 * cold_x:
        raise SystemExit(f"[restart] warm XLA compile not served from disk: "
                         f"cold={cold_x:.3f}s warm={warm_x:.3f}s")
    if warm.get("compile_s", 0.0) >= cold.get("compile_s", 0.0):
        raise SystemExit(f"[restart] warm compile_s {warm.get('compile_s')} not "
                         f"below cold {cold.get('compile_s')}")
    if warm["_cache"]["hits"] <= cold["_cache"]["hits"]:
        raise SystemExit(f"[restart] warm run reported no persistent-cache hits: "
                         f"cold={cold['_cache']} warm={warm['_cache']}")
    print(f"[restart] ok: xla_compile_s {cold_x:.3f}s cold → {warm_x:.3f}s warm "
          f"({warm['_cache']['hits']} disk hits; compile_s "
          f"{cold.get('compile_s', 0.0):.3f}s → {warm.get('compile_s', 0.0):.3f}s)")


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child(sys.argv[2])
        return
    from ballista_tpu.testing.tpchgen import generate_tpch

    with tempfile.TemporaryDirectory(prefix="fill-tpch-") as d:
        print(f"generating TPC-H sf0.01 under {d} ...")
        generate_tpch(d, scale=0.01, seed=42, files_per_table=2)
        leg_overlap(d)
        leg_restart(d)
    print("fill exercise passed")


if __name__ == "__main__":
    main()
