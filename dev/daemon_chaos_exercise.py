"""Exercise the device-runtime daemon's failure domain end-to-end (CPU jax).

    JAX_PLATFORMS=cpu python dev/daemon_chaos_exercise.py [--quick]

Chaos-kills the daemon out from under live TPC-H queries and checks the
one rule of the failure domain (docs/device_daemon.md#failure-domain):
a daemon death costs one retry, never the query, never a crash loop.

Legs (full mode; --quick runs one of each kind for the bench probe):

1. crash  — `daemon_crash` hard-exits the daemon (exit 137) at every
   arming point (pre/mid/post_execute) under q1 AND q3. The once-marker
   limits the fault to the first armed request, so the ladder must
   respawn, retry, and return bytes identical to the in-process
   baseline with daemon_crashes_detected/daemon_restarts nonzero.
2. hang   — `daemon_hang` wedges the execute thread; the per-request
   watchdog (deadline floor ballista.tpu.daemon.execute.timeout.s)
   must convert the hang into a diagnosed death and the ladder must
   recover byte-identically with watchdog_kills nonzero.
3. watchdog post-mortem — a hang with respawn disabled, so the
   <socket>.crash.json artifact survives for inspection: it must name
   the offending request (tag) and carry every thread's stack, and the
   query must still complete in-process, byte-identical.
4. poison — `daemon_crash` WITHOUT the once-marker: every incarnation
   dies on the stage, the second crash per fingerprint quarantines it
   (<socket>.poison.json), the stage demotes in-process
   byte-identically, and a rerun must touch no daemon at all (the
   crash-loop check: zero new crashes).

Exits non-zero on any divergence. bench.py's device leg runs the
--quick variant as a sanity probe when BALLISTA_BENCH_DAEMON_CHAOS=1.
"""

import io
import json
import os
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

ARM_POINTS = ("pre_execute", "mid_execute", "post_execute")
HANG_TIMEOUT_S = 12  # watchdog floor for hang legs: roomy enough that the
                     # retry's recompile+execute fits, short enough to test


def _sql(name: str) -> str:
    with open(os.path.join(ROOT, "benchmarks", "tpch", "queries",
                           f"{name}.sql")) as f:
        return f.read()


def _ipc_bytes(tbl) -> bytes:
    import pyarrow as pa

    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, tbl.schema) as w:
        w.write_table(tbl)
    return sink.getvalue()


def _run(data_dir: str, sql: str, extra_cfg: dict | None = None):
    """One query in THIS process; returns (result bytes, stats snapshot)."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import EXECUTOR_ENGINE, BallistaConfig
    from ballista_tpu.ops.tpu import stage_compiler as sc
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", **(extra_cfg or {})})
    ctx = SessionContext(cfg)
    register_tpch(ctx, data_dir)
    sc.RUN_STATS.clear()
    out = ctx.sql(sql).collect()
    if out.num_rows == 0:
        raise SystemExit("query produced no rows")
    return _ipc_bytes(out), sc.RUN_STATS.snapshot()


def _chaos_cfg(sock: str, mode: str, arm: str, once: bool,
               spawn: bool = True, **extra) -> dict:
    from ballista_tpu.config import (
        CHAOS_DAEMON_ARM,
        CHAOS_DAEMON_ONCE,
        CHAOS_ENABLED,
        CHAOS_MODE,
        TPU_DAEMON_ATTACH_TIMEOUT_MS,
        TPU_DAEMON_ENABLED,
        TPU_DAEMON_SOCKET,
        TPU_DAEMON_SPAWN,
    )

    return {TPU_DAEMON_ENABLED: True, TPU_DAEMON_SOCKET: sock,
            TPU_DAEMON_SPAWN: spawn, TPU_DAEMON_ATTACH_TIMEOUT_MS: 60_000,
            CHAOS_ENABLED: True, CHAOS_MODE: mode,
            CHAOS_DAEMON_ARM: arm, CHAOS_DAEMON_ONCE: once, **extra}


def _shutdown(sock: str) -> None:
    from ballista_tpu.device_daemon import client as dclient

    try:
        dclient.DaemonClient(sock, timeout_s=5.0).shutdown()
    except Exception:  # noqa: BLE001 — a corpse is the expected case here
        pass
    dclient.reset_attach_cache()


def _check(leg: str, cond: bool, msg: str) -> None:
    if not cond:
        raise SystemExit(f"[{leg}] FAILED: {msg}")


def _crash_leg(d: str, data_dir: str, query: str, baseline: bytes,
               mode: str, arm: str) -> None:
    from ballista_tpu.device_daemon import client as dclient

    leg = f"{mode}@{arm}/{query}"
    sock = os.path.join(d, f"{mode}-{arm}-{query}.sock")
    extra = {}
    if mode == "daemon_hang":
        from ballista_tpu.config import TPU_DAEMON_EXECUTE_TIMEOUT_S

        extra[TPU_DAEMON_EXECUTE_TIMEOUT_S] = HANG_TIMEOUT_S
    dclient.reset_failure_counters()
    try:
        blob, stats = _run(data_dir, _sql(query),
                           _chaos_cfg(sock, mode, arm, once=True, **extra))
        c = dclient.failure_counters()
        _check(leg, blob == baseline, "result bytes diverged from baseline")
        _check(leg, c["daemon_crashes_detected"] >= 1,
               f"no crash detected (counters {c})")
        _check(leg, c["daemon_restarts"] >= 1,
               f"crash was not recovered by respawn ({c})")
        _check(leg, c["poisoned_stages"] == 0,
               f"once-armed fault must not quarantine ({c})")
        if mode == "daemon_hang":
            _check(leg, c["watchdog_kills"] >= 1,
                   f"hang was not classified as a watchdog kill ({c})")
        _check(leg, stats.get("daemon_restarts", 0) >= 1,
               "recovery counters did not reach the stats snapshot")
        print(f"[{leg}] ok: byte-identical, counters {c}")
    finally:
        _shutdown(sock)


def _watchdog_postmortem_leg(d: str, data_dir: str, baseline: bytes) -> None:
    from ballista_tpu.device_daemon import client as dclient
    from ballista_tpu.device_daemon import protocol as dproto

    leg = "watchdog-postmortem"
    sock = os.path.join(d, "postmortem.sock")
    from ballista_tpu.config import TPU_DAEMON_EXECUTE_TIMEOUT_S

    dclient.reset_failure_counters()
    proc = dclient.spawn_daemon(sock, parent_pid=os.getpid())
    try:
        dclient.DaemonClient(sock).wait_ready(timeout_s=120)
        # spawn OFF: the corpse stays a corpse, so its crash report does
        # too — and the query must finish in-process anyway
        blob, stats = _run(
            data_dir, _sql("q1"),
            _chaos_cfg(sock, "daemon_hang", "mid_execute", once=True,
                       spawn=False,
                       **{TPU_DAEMON_EXECUTE_TIMEOUT_S: HANG_TIMEOUT_S}))
        _check(leg, blob == baseline, "result bytes diverged from baseline")
        _check(leg, proc.wait(timeout=30) == 4,
               f"daemon exit code {proc.returncode}, expected 4")
        report = dclient.read_crash_report(sock)
        _check(leg, report is not None, "no <socket>.crash.json post-mortem")
        _check(leg, report.get("kind") == "watchdog",
               f"post-mortem kind {report.get('kind')!r}")
        tag = str(report.get("request", {}).get("tag", ""))
        _check(leg, bool(tag), "post-mortem names no offending request tag")
        _check(leg, bool(report.get("stacks")), "post-mortem has no stacks")
        c = dclient.failure_counters()
        _check(leg, c["watchdog_kills"] >= 1, f"no watchdog kill counted ({c})")
        _check(leg, c["daemon_restarts"] == 0,
               f"spawn=off leg must not respawn ({c})")
        print(f"[{leg}] ok: exit 4, post-mortem names {tag!r}, "
              f"{len(report['stacks'])}B of stacks, counters {c}")
    finally:
        _shutdown(sock)
        if proc.poll() is None:
            proc.kill()


def _poison_leg(d: str, data_dir: str, baseline: bytes) -> None:
    from ballista_tpu.device_daemon import client as dclient
    from ballista_tpu.device_daemon import protocol as dproto

    leg = "poison"
    sock = os.path.join(d, "poison.sock")
    dclient.reset_failure_counters()
    try:
        # no once-marker: every incarnation dies until the quarantine bites
        blob, stats = _run(data_dir, _sql("q1"),
                           _chaos_cfg(sock, "daemon_crash", "mid_execute",
                                      once=False))
        c = dclient.failure_counters()
        _check(leg, blob == baseline, "result bytes diverged from baseline")
        _check(leg, c["daemon_crashes_detected"] >= 2,
               f"quarantine needs two crashes ({c})")
        _check(leg, c["poisoned_stages"] >= 1, f"nothing quarantined ({c})")
        _check(leg, stats.get("daemon_failover") == "poisoned",
               f"failover outcome {stats.get('daemon_failover')!r}")
        entries = {}
        try:
            entries = json.load(
                open(dproto.poison_path(sock))).get("entries", {})
        except (OSError, ValueError):
            pass
        _check(leg, bool(entries), "no on-disk quarantine entries")
        # the crash-loop check: a rerun demotes from quarantine WITHOUT
        # touching a daemon — no new crashes, no respawn storm
        crashes_before = c["daemon_crashes_detected"]
        blob2, stats2 = _run(data_dir, _sql("q1"),
                             _chaos_cfg(sock, "daemon_crash", "mid_execute",
                                        once=False))
        c2 = dclient.failure_counters()
        _check(leg, blob2 == baseline, "quarantined rerun diverged")
        _check(leg, stats2.get("daemon_mode") == "in_process",
               f"quarantined rerun mode {stats2.get('daemon_mode')!r}")
        _check(leg, c2["daemon_crashes_detected"] == crashes_before,
               f"quarantined rerun crashed daemons again ({c2})")
        print(f"[{leg}] ok: quarantined {list(entries)}, demoted "
              f"byte-identically, crash loop broken")
    finally:
        _shutdown(sock)
        from ballista_tpu.device_daemon import client as dclient2

        dclient2.clear_poison(sock)


def main(quick: bool = False) -> None:
    from ballista_tpu.testing.tpchgen import generate_tpch

    with tempfile.TemporaryDirectory(prefix="daemon-chaos-") as d:
        data_dir = os.path.join(d, "tpch")
        print(f"generating TPC-H sf0.01 under {data_dir} ...")
        generate_tpch(data_dir, scale=0.01, seed=42, files_per_table=2)

        baselines = {}
        queries = ["q1"] if quick else ["q1", "q3"]
        for q in queries:
            print(f"[baseline] {q} in-process ...")
            baselines[q], _ = _run(data_dir, _sql(q))

        crash_arms = [("mid_execute",)] if quick else [(a,) for a in ARM_POINTS]
        for q in queries:
            for (arm,) in crash_arms:
                _crash_leg(d, data_dir, q, baselines[q], "daemon_crash", arm)
        hang_arms = ["mid_execute"] if quick else list(ARM_POINTS)
        for arm in hang_arms:
            _crash_leg(d, data_dir, "q1", baselines["q1"], "daemon_hang", arm)
        _watchdog_postmortem_leg(d, data_dir, baselines["q1"])
        _poison_leg(d, data_dir, baselines["q1"])

    mode = "quick" if quick else "full"
    print(f"daemon chaos exercise passed ({mode}): every injected daemon "
          "death cost one retry, never the query, never a crash loop")


if __name__ == "__main__":
    main(quick="--quick" in sys.argv[1:])
