"""Exercise the executor lifecycle & storage failure domain end-to-end.

    JAX_PLATFORMS=cpu python dev/lifecycle_exercise.py [--quick]

Drains executors out from under live TPC-H queries and checks the rules
of the lifecycle failure domain (docs/lifecycle.md): a graceful drain
hands shuffle outputs to survivors with ZERO upstream-stage reruns; a
hard kill mid-drain falls back to recompute; injected ENOSPC fails
tasks typed + retryable, never the job.

Legs (full mode; --quick drops the drain_kill leg for the bench probe):

1. drain          — mid-flight drain of a 2-executor per-work-dir fleet
   under q3: the victim's committed map outputs migrate to the survivor
   over the real migrate_pull Flight path, every stage stays at
   attempt 0, and the result matches the pandas reference oracle.
2. drain_kill     — BALLISTA_CHAOS_DRAIN_KILL_AFTER=1 aborts the
   migration after one committed location: the scheduler must fall
   back to the executor-lost recompute path and the job must still
   produce correct results (status "drain-killed" in the ledger).
3. disk_full      — chaos mode=disk_full at p=1.0/once-mode: every
   task's first shuffle write ENOSPCs with a typed retryable
   DiskExhausted, every retry heals, and the query converges — no job
   failure, no quarantine of the only executor.
4. rolling_restart — drain each of a 3-executor fleet's original nodes
   one at a time (adding a replacement after each) while q6 runs in a
   loop: every query must keep succeeding with oracle-correct results
   and the handoffs must migrate real partitions.

Exits non-zero on any divergence. bench.py runs the --quick variant as
a sanity probe when BALLISTA_BENCH_LIFECYCLE=1.
"""

import os
import sys
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _sql(name: str) -> str:
    with open(os.path.join(ROOT, "benchmarks", "tpch", "queries",
                           f"{name}.sql")) as f:
        return f.read()


def _check(leg: str, cond: bool, msg: str) -> None:
    if not cond:
        raise SystemExit(f"[{leg}] FAILED: {msg}")


def _slow_engine():
    """Stretches every task by a few ms so a drain reliably lands while
    the job is mid-flight (upstream outputs committed, consumers pending)."""
    from ballista_tpu.executor.executor import ExecutionEngine

    class SlowEngine(ExecutionEngine):
        def create_query_stage_exec(self, plan, config, stage_attempt=0):
            time.sleep(0.05)
            return super().create_query_stage_exec(plan, config, stage_attempt)

    return SlowEngine


def _drain_cluster(data_dir, cfg, num_executors=2):
    """SessionContext over a per-executor-work-dir standalone fleet: each
    executor owns its work-dir subtree and Flight server, so drain
    migration moves real bytes between data planes."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.executor.standalone import StandaloneCluster
    from ballista_tpu.testing.tpchgen import register_tpch

    ctx = SessionContext.standalone(cfg, num_executors=num_executors)
    ctx._cluster = StandaloneCluster(
        num_executors, 4, config=cfg, per_executor_work_dirs=True,
        engine_factory=_slow_engine())
    register_tpch(ctx, data_dir)
    return ctx


def _drain_midflight(ctx, cfg, sql):
    """Submit sql, wait until some executor holds committed map outputs
    while the job is still running, then drain that executor."""
    cluster = ctx._cluster
    sched = cluster.scheduler
    sid = sched.sessions.create_or_update(cfg.to_key_value_pairs(), "s-lifecycle")
    job_id = sched.submit_sql(sql, sid)
    victim = None
    deadline = time.time() + 60
    while time.time() < deadline and victim is None:
        for eid in list(cluster.executors):
            if sched._locations_on(eid):
                victim = eid
                break
        else:
            time.sleep(0.01)
    _check("drain", victim is not None, "no committed map outputs ever appeared")
    res = sched.drain_executor(victim, timeout_s=60)
    status = sched.wait_for_job(job_id, timeout=120)
    return job_id, res, status


def _drain_leg(data_dir, ref_tables, kill: bool) -> None:
    from ballista_tpu.client.context import fetch_job_results
    from ballista_tpu.config import DEFAULT_SHUFFLE_PARTITIONS, BallistaConfig
    from ballista_tpu.testing.reference import compare_results, run_reference

    leg = "drain_kill" if kill else "drain"
    if kill:
        os.environ["BALLISTA_CHAOS_DRAIN_KILL_AFTER"] = "1"
    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 4})
    ctx = _drain_cluster(data_dir, cfg)
    sched = ctx._cluster.scheduler
    try:
        job_id, res, status = _drain_midflight(ctx, cfg, _sql("q3"))
        _check(leg, status["state"] == "successful",
               f"job failed: {status.get('error')}")
        want = "drain-killed" if kill else "drained"
        _check(leg, res["status"] == want, f"drain result {res}")
        if not kill:
            _check(leg, res["migrated_partitions"] > 0 and res["migrated_bytes"] > 0,
                   f"nothing migrated: {res}")
            g = sched.jobs.get(job_id)
            attempts = {sid: s.attempt for sid, s in g.stages.items()}
            _check(leg, all(a == 0 for a in attempts.values()),
                   f"stage reruns happened: {attempts}")
        out = fetch_job_results(status, cfg)
        problems = compare_results(out, run_reference(3, ref_tables), 3)
        _check(leg, not problems, "; ".join(problems))
        drained = sched.executors.drained_snapshot()
        _check(leg, drained.get(res["executor_id"], {}).get("reason") == want,
               f"ledger {drained}")
        print(f"[{leg}] ok: {res['migrated_partitions']} partitions "
              f"({res['migrated_bytes']}B) handed off, job successful, "
              "oracle-correct")
    finally:
        if kill:
            del os.environ["BALLISTA_CHAOS_DRAIN_KILL_AFTER"]
        ctx.shutdown()


def _disk_full_leg(data_dir) -> None:
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import (
        CHAOS_ENABLED,
        CHAOS_MODE,
        CHAOS_PROBABILITY,
        CHAOS_SEED,
        DEFAULT_SHUFFLE_PARTITIONS,
        BallistaConfig,
    )
    from ballista_tpu.executor import chaos
    from ballista_tpu.testing.tpchgen import register_tpch

    leg = "disk_full"
    chaos._DISK_FULL_FIRED.clear()
    # p=1.0 + once-mode is DETERMINISTIC: every task's first shuffle write
    # ENOSPCs and every retry heals, with the per-stage task count (2)
    # safely under the stage retry budget
    cfg = BallistaConfig({
        CHAOS_ENABLED: True, CHAOS_MODE: "disk_full",
        CHAOS_PROBABILITY: 1.0, CHAOS_SEED: 11,
        DEFAULT_SHUFFLE_PARTITIONS: 2,
    })
    ctx = SessionContext.standalone(cfg, num_executors=1, vcores=4)
    register_tpch(ctx, data_dir)
    # every task fails exactly once by design; don't let the health ledger
    # quarantine the only executor over the injected faults
    ctx._ensure_cluster().scheduler.executors.quarantine_threshold = 2.0
    try:
        out = ctx.sql(
            "select n_name, count(*) as c from nation group by n_name order by n_name"
        ).collect()
        fired = len(chaos._DISK_FULL_FIRED)
        _check(leg, fired > 0, "no ENOSPC ever injected — leg vacuous")
        _check(leg, out.num_rows == 25, f"{out.num_rows} rows, expected 25")
        _check(leg, all(c == 1 for c in out.column("c").to_pylist()),
               "wrong counts after retry")
        print(f"[{leg}] ok: {fired} injected ENOSPCs, every retry healed, "
              "job never failed")
    finally:
        ctx.shutdown()
        chaos._DISK_FULL_FIRED.clear()


def _rolling_restart_leg(data_dir, ref_tables) -> None:
    from ballista_tpu.config import DEFAULT_SHUFFLE_PARTITIONS, BallistaConfig
    from ballista_tpu.testing.reference import compare_results, run_reference

    leg = "rolling_restart"
    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 4})
    ctx = _drain_cluster(data_dir, cfg, num_executors=3)
    cluster = ctx._cluster
    sched = cluster.scheduler
    originals = list(cluster.executors)
    results, errors = [], []
    stop = threading.Event()

    def load():
        while not stop.is_set():
            try:
                results.append(ctx.sql(_sql("q6")).collect())
            except Exception as e:  # noqa: BLE001 — surfaced as a leg failure
                errors.append(e)
                return

    t = threading.Thread(target=load, daemon=True, name="query-load")
    t.start()
    try:
        for eid in originals:
            # drain only once this node actually holds shuffle outputs, so
            # every handoff in the rolling restart moves real data
            deadline = time.time() + 30
            while time.time() < deadline and not sched._locations_on(eid):
                time.sleep(0.01)
            res = sched.drain_executor(eid, timeout_s=60)
            _check(leg, res["status"] == "drained", f"drain result {res}")
            cluster.add_executor(vcores=4, config=cfg,
                                 engine_factory=_slow_engine())
        _check(leg, sched.lifecycle_stats["migrated_partitions"] > 0,
               "rolling restart migrated nothing")
        stop.set()
        t.join(timeout=120)
        _check(leg, not errors, f"query load failed: {errors}")
        _check(leg, bool(results), "load thread never completed a query")
        ref = run_reference(6, ref_tables)
        for out in results:
            problems = compare_results(out, ref, 6)
            _check(leg, not problems, "; ".join(problems))
        _check(leg, len(sched.executors.alive_executors()) == 3,
               "fleet size drifted")
        _check(leg, sched.lifecycle_stats["drains"] == 3, "drain count drifted")
        print(f"[{leg}] ok: 3 nodes drained+replaced under load, "
              f"{len(results)} queries all oracle-correct, "
              f"{sched.lifecycle_stats['migrated_partitions']} partitions migrated")
    finally:
        stop.set()
        ctx.shutdown()


def main(quick: bool = False) -> None:
    import tempfile

    from ballista_tpu.testing.reference import load_tables
    from ballista_tpu.testing.tpchgen import generate_tpch

    with tempfile.TemporaryDirectory(prefix="lifecycle-") as d:
        data_dir = os.path.join(d, "tpch")
        print(f"generating TPC-H sf0.01 under {data_dir} ...")
        generate_tpch(data_dir, scale=0.01, seed=42, files_per_table=2)
        ref_tables = load_tables(data_dir)

        _drain_leg(data_dir, ref_tables, kill=False)
        if not quick:
            _drain_leg(data_dir, ref_tables, kill=True)
        _disk_full_leg(data_dir)
        _rolling_restart_leg(data_dir, ref_tables)

    mode = "quick" if quick else "full"
    print(f"lifecycle exercise passed ({mode}): drains cost zero reruns, "
          "ENOSPC cost one retry, the fleet rolled without a wrong answer")


if __name__ == "__main__":
    main(quick="--quick" in sys.argv[1:])
