"""Regenerate docs/configs.md from the config.py registry.

Usage: python dev/gen_configs.py [--check]

--check exits 1 without writing if the committed file is stale (the same
comparison the knob-sync analysis pass and tests/test_ops.py run in CI).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ballista_tpu.config import generate_config_docs  # noqa: E402


def main(argv: list[str]) -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "docs", "configs.md")
    expected = generate_config_docs()
    if "--check" in argv:
        try:
            with open(path, encoding="utf-8") as f:
                actual = f.read()
        except OSError:
            actual = None
        if actual != expected:
            print(f"{path} is stale; run `python dev/gen_configs.py`", file=sys.stderr)
            return 1
        print(f"{path} is up to date")
        return 0
    with open(path, "w", encoding="utf-8") as f:
        f.write(expected)
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
