"""Exercise mesh-wide stage execution end-to-end on the virtual CPU mesh.

    python dev/mesh_exercise.py

One TPC-H-shaped aggregate+join query through the real standalone
scheduler in three modes, each in a fresh subprocess (8 virtual devices via XLA_FLAGS, so
compile caches and RUN_STATS can't bleed between modes):

- **off**  — `ballista.tpu.mesh.enabled=false`: the baseline file
  shuffle (ShuffleWriter → Arrow IPC files → ShuffleReader).
- **mesh** — the planner fuses the hash-exchange edge into ONE
  mesh-wide stage and the repartition runs as an on-device
  `all_to_all`. Asserts the result is BYTE-IDENTICAL to `off`, the
  stage DAG shrank, `mesh_mode_reason == "mesh"` with ≥2 devices and
  nonzero `exchange_bytes_on_device`, and the eliminated producer stage
  wrote ZERO shuffle files (its work-dir directory must not exist).
- **demote** — mesh enabled but `exchange.capacity.rows=1`: the
  host-side capacity gate must refuse the collective
  (`mesh_mode_reason == "demoted:capacity"`) and the host split must
  still be byte-identical to `off`.

Prints per-mode stats and exits non-zero on any divergence.
"""

import json
import os
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

STATS_MARK = "MESH_EXERCISE_STATS "
MODES = ("off", "mesh", "demote")
# aggregate THROUGH a broadcast join: the fused mesh stage carries
# scan → filter → join probe → partial aggregate, and the hash exchange
# feeding the final aggregate is the edge that goes on-device
SQL = ("select d.grp, sum(t.v) rev, count(*) c, min(t.q) mn "
       "from t join d on t.k = d.k where t.q < 700 "
       "group by d.grp order by d.grp")


def _table():
    import numpy as np
    import pyarrow as pa
    import pyarrow.compute as pc

    rng = np.random.default_rng(42)
    n = 30_000
    k = rng.choice([f"key{i:03d}" for i in range(80)], n)
    v = rng.uniform(-50, 50, n)
    kmask = rng.random(n) < 0.03
    fact = pa.table({
        "k": pc.if_else(pa.array(kmask), pa.nulls(n, pa.string()), pa.array(k)),
        "v": pa.array(v),
        "q": pa.array(rng.integers(0, 1000, n), type=pa.int64()),
    })
    dim = pa.table({
        "k": pa.array([f"key{i:03d}" for i in range(80)]),
        # 40 groups: the partial-aggregate output still puts ≥2 rows on
        # some (sender, dest) pair, so the demote leg's capacity=1 gate
        # trips deterministically (pigeonhole over 8 destinations)
        "grp": pa.array([f"g{i % 40:02d}" for i in range(80)]),
    })
    return fact, dim


def _save(data_dir: str, mode: str, table) -> None:
    import pyarrow.ipc as ipc

    path = os.path.join(data_dir, f"result_{mode}.arrow")
    with ipc.new_file(path, table.schema) as sink:
        sink.write_table(table.combine_chunks())


def load(data_dir: str, mode: str):
    import pyarrow.ipc as ipc

    with ipc.open_file(os.path.join(data_dir, f"result_{mode}.arrow")) as f:
        return f.read_all()


def child(data_dir: str, mode: str) -> None:
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import (
        EXECUTOR_ENGINE,
        TPU_MESH_ENABLED,
        TPU_MESH_EXCHANGE_CAPACITY,
        TPU_MIN_ROWS,
        BallistaConfig,
    )
    from ballista_tpu.ops.tpu import stage_compiler

    settings = {EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0,
                TPU_MESH_ENABLED: mode != "off"}
    if mode == "demote":
        settings[TPU_MESH_EXCHANGE_CAPACITY] = 1
    ctx = SessionContext.standalone(BallistaConfig(settings),
                                    num_executors=1, vcores=2)
    try:
        fact, dim = _table()
        ctx.register_arrow_table("t", fact, partitions=4)
        ctx.register_arrow_table("d", dim, partitions=1)
        stage_compiler.RUN_STATS.clear()
        out = ctx.sql(SQL).collect()
        if out.num_rows == 0:
            raise SystemExit(f"[{mode}] produced no rows")
        _save(data_dir, mode, out)
        sched = ctx._cluster.scheduler
        with sched._jobs_lock:
            graph = list(sched.jobs.values())[-1]
        job_dir = os.path.join(ctx._cluster.work_dir, graph.job_id)
        file_stages = sorted(
            int(d) for d in os.listdir(job_dir) if d.isdigit()
        ) if os.path.isdir(job_dir) else []
        print(STATS_MARK + json.dumps({
            "stats": stage_compiler.RUN_STATS.snapshot(),
            "graph_stages": sorted(graph.stages),
            "file_stages": file_stages,
        }))
    finally:
        ctx.shutdown()


def spawn(data_dir: str, mode: str) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", data_dir, mode],
        env=env, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        raise SystemExit(f"[{mode}] child failed:\n{proc.stdout}\n{proc.stderr}")
    for line in proc.stdout.splitlines():
        if line.startswith(STATS_MARK):
            return json.loads(line[len(STATS_MARK):])
    raise SystemExit(f"[{mode}] child printed no stats:\n{proc.stdout}")


def report(mode: str, info: dict) -> None:
    s = info["stats"]
    print(f"[{mode:6s}] stages={info['graph_stages']} "
          f"file_stages={info['file_stages']} "
          f"mesh_mode_reason={s.get('mesh_mode_reason')} "
          f"mesh_devices={s.get('mesh_devices')} "
          f"exchange_bytes_on_device={s.get('exchange_bytes_on_device')} "
          f"exchange_s={s.get('exchange_s')}")


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child(sys.argv[2], sys.argv[3])
        return

    with tempfile.TemporaryDirectory(prefix="mesh-exercise-") as d:
        info = {m: spawn(d, m) for m in MODES}
        results = {m: load(d, m) for m in MODES}

    for m in MODES:
        report(m, info[m])

    # -- parity: every mode byte-identical to the file shuffle -------------
    for m in ("mesh", "demote"):
        if not results[m].equals(results["off"]):
            raise SystemExit(f"DIVERGENCE: {m} result != off (file shuffle)")
    print("[parity] mesh == demote == off (byte-identical)")

    # -- the fused edge really vanished ------------------------------------
    off, mesh = info["off"], info["mesh"]
    if len(mesh["graph_stages"]) >= len(off["graph_stages"]):
        raise SystemExit("mesh run did not shrink the stage DAG")
    gone = set(off["graph_stages"]) - set(mesh["graph_stages"])
    if not gone:
        raise SystemExit("no producer stage was eliminated in mesh mode")
    if gone & set(mesh["file_stages"]):
        raise SystemExit(
            f"mesh run wrote shuffle files for the fused edge: stages {sorted(gone)}")
    if not gone <= set(off["file_stages"]):
        raise SystemExit(
            "baseline run wrote no files for the fused edge — assertion is vacuous")
    print(f"[files] fused stage(s) {sorted(gone)} wrote ZERO shuffle files "
          f"(baseline wrote {off['file_stages']})")

    # -- mode routing -------------------------------------------------------
    s = mesh["stats"]
    if s.get("mesh_mode_reason") != "mesh":
        raise SystemExit(f"[mesh] ran as {s.get('mesh_mode_reason')!r}, not 'mesh'")
    if s.get("mesh_devices", 0) < 2:
        raise SystemExit(f"[mesh] mesh_devices={s.get('mesh_devices')} (< 2)")
    if s.get("exchange_bytes_on_device", 0) <= 0:
        raise SystemExit("[mesh] exchange_bytes_on_device not recorded")
    got = info["demote"]["stats"].get("mesh_mode_reason")
    if got != "demoted:capacity":
        raise SystemExit(f"[demote] expected 'demoted:capacity', got {got!r}")
    if info["off"]["stats"].get("mesh_mode_reason") is not None:
        raise SystemExit("[off] mesh exchange ran with the flag disabled")
    print("[ladder] mesh ran on-device; capacity=1 demoted to the host split")
    print("mesh exercise passed")


if __name__ == "__main__":
    main()
