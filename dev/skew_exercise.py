"""Exercise the AQE skew defenses end-to-end on a tiny skewed join.

    JAX_PLATFORMS=cpu python dev/skew_exercise.py

Three legs, each in its own subprocess so the process-global AQE
counters (ops/tpu/aqe_stats.py) start from zero:

1. split — chaos skew mode piles ~70% of fact rows onto one reduce
   bucket; the resolution-time replan must split it into partition-slice
   tasks (skew_splits >= 1) and the merged result must be byte-identical
   to the unsplit oracle (AQE skew off, same chaos seed).
2. coalesce — the same join without chaos: AQE must still bin-pack the
   cold reduce partitions (coalesced_partitions >= 1) with the result
   byte-identical to a non-adaptive run.
3. mesh-demote — apply_aqe over a mesh-fused stage: a hot bucket
   demotes the fused exchange (mesh_mode_reason=demoted:aqe:skew), a
   uniformly small input replans the device bucket count instead.

Exits non-zero if any leg fails a counter or byte-parity check.
Mechanism docs: docs/aqe.md.
"""

import json
import os
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

JOIN_SQL = "select fact.k, v, s, x from fact join dim on fact.k = dim.k"


def write_tables(d: str) -> None:
    """Parquet join inputs with nulls, strings and duplicate keys. Multiple
    fact files matter: slicing needs >= 2 map outputs per hot bucket, and a
    single-file scan would collapse to one map task."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(11)
    os.makedirs(f"{d}/fact", exist_ok=True)
    os.makedirs(f"{d}/dim", exist_ok=True)
    for i in range(4):
        n = 15_000
        pq.write_table(pa.table({
            "k": rng.integers(0, 2000, n),
            "v": rng.integers(0, 100, n),
            "s": pa.array([f"row{j % 97}" if j % 13 else None for j in range(n)]),
        }), f"{d}/fact/part{i}.parquet")
    for i in range(2):
        pq.write_table(pa.table({
            "k": np.arange(i * 1000, (i + 1) * 1000),
            "x": rng.integers(0, 200, 1000),
        }), f"{d}/dim/part{i}.parquet")


def counters() -> dict:
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

    snap = RUN_STATS.snapshot()
    return {k: int(snap.get(k, 0) or 0)
            for k in ("skew_splits", "coalesced_partitions",
                      "broadcast_promotions", "broadcast_demotions",
                      "aqe_mesh_replans")}


def run_join(d: str, *, chaos: bool, adaptive: bool, skew_aqe: bool):
    """One standalone run of the skewed join; returns (table, graph)."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import (
        AQE_SKEW_ENABLED,
        AQE_SKEW_MIN_BYTES,
        AQE_TARGET_PARTITION_BYTES,
        BROADCAST_JOIN_ROWS_THRESHOLD,
        CHAOS_ENABLED,
        CHAOS_MODE,
        CHAOS_SEED,
        CHAOS_SKEW_FRACTION,
        DEBUG_PLAN_VERIFY,
        DEFAULT_SHUFFLE_PARTITIONS,
        PLANNER_ADAPTIVE_ENABLED,
        BallistaConfig,
    )

    cfg = BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 8,
        PLANNER_ADAPTIVE_ENABLED: adaptive,
        BROADCAST_JOIN_ROWS_THRESHOLD: 100,  # keep the join partitioned
        CHAOS_ENABLED: chaos, CHAOS_MODE: "skew", CHAOS_SEED: 5,
        CHAOS_SKEW_FRACTION: 0.7,
        AQE_SKEW_ENABLED: skew_aqe,
        AQE_SKEW_MIN_BYTES: 1024,
        AQE_TARGET_PARTITION_BYTES: 64 * 1024,
        DEBUG_PLAN_VERIFY: True,  # plan_check gates every resolution
    })
    ctx = SessionContext.standalone(cfg, num_executors=1, vcores=4)
    ctx.register_parquet("fact", f"{d}/fact")
    ctx.register_parquet("dim", f"{d}/dim")
    try:
        out = ctx.sql(JOIN_SQL).collect()
        sched = ctx._cluster.scheduler
        with sched._jobs_lock:
            g = list(sched.jobs.values())[-1]
        if g.status.value != "successful":
            raise SystemExit(f"join run failed:\n{g.display()}")
        return out, g
    finally:
        ctx.shutdown()


def leg_split(d: str) -> None:
    out, g = run_join(d, chaos=True, adaptive=True, skew_aqe=True)
    ctr = counters()
    reports = [s.skew_report for s in g.stages.values() if s.skew_report]
    if ctr["skew_splits"] < 1 or not reports:
        raise SystemExit(f"[split] no skew split fired: {ctr}")
    if not all(len(s.partitions) >= 2 for r in reports for s in r.splits):
        raise SystemExit("[split] a hot partition produced fewer than 2 slices")
    oracle, og = run_join(d, chaos=True, adaptive=True, skew_aqe=False)
    if any(s.skew_report for s in og.stages.values()):
        raise SystemExit("[split] oracle run split despite skew AQE off")
    if not out.to_pandas().equals(oracle.to_pandas()):
        raise SystemExit("[split] DIVERGED from the unsplit oracle")
    print(f"[split] ok: rows={out.num_rows} counters={json.dumps(ctr)}")


def leg_coalesce(d: str) -> None:
    out, _ = run_join(d, chaos=False, adaptive=True, skew_aqe=True)
    ctr = counters()
    if ctr["coalesced_partitions"] < 1:
        raise SystemExit(f"[coalesce] nothing coalesced: {ctr}")
    if ctr["skew_splits"]:
        raise SystemExit("[coalesce] split fired without injected skew")
    oracle, _ = run_join(d, chaos=False, adaptive=False, skew_aqe=False)
    if not out.to_pandas().equals(oracle.to_pandas()):
        raise SystemExit("[coalesce] DIVERGED from the non-adaptive oracle")
    print(f"[coalesce] ok: rows={out.num_rows} counters={json.dumps(ctr)}")


def leg_mesh_demote(d: str) -> None:
    import numpy as np
    import pyarrow as pa

    from ballista_tpu.config import (
        AQE_SKEW_MIN_BYTES,
        AQE_TARGET_PARTITION_BYTES,
        PLANNER_ADAPTIVE_ENABLED,
        BallistaConfig,
    )
    from ballista_tpu.ops.tpu.mesh_stage import MeshExchangeExec
    from ballista_tpu.plan.expressions import Column
    from ballista_tpu.plan.physical import MemoryScanExec
    from ballista_tpu.plan.schema import DFSchema
    from ballista_tpu.scheduler.aqe.rules import InputStageStats, apply_aqe
    from ballista_tpu.shuffle.writer import ShuffleWriterExec

    cfg = BallistaConfig({
        PLANNER_ADAPTIVE_ENABLED: True,
        AQE_SKEW_MIN_BYTES: 1024,
        AQE_TARGET_PARTITION_BYTES: 64 * 1024,
    })

    def mesh_plan(buckets=8):
        t = pa.table({"k": np.arange(64, dtype="int64")})
        scan = MemoryScanExec(DFSchema.from_arrow(t.schema), t.to_batches(), 4)
        return ShuffleWriterExec(MeshExchangeExec(scan, [Column("k")], buckets),
                                 "jm", 2, buckets, [Column("k")])

    def stats(bucket_bytes):
        return {1: InputStageStats(
            stage_id=1, total_rows=sum(bucket_bytes) // 8,
            total_bytes=sum(bucket_bytes), bucket_bytes=list(bucket_bytes),
            broadcast=False)}

    def exchanges(plan):
        found, stack = [], [plan]
        while stack:
            n = stack.pop()
            if isinstance(n, MeshExchangeExec):
                found.append(n)
            stack.extend(getattr(n, "children", lambda: [])())
        return found

    # hot bucket → the fused edge demotes rather than splitting under it
    out, new_parts, report = apply_aqe(
        mesh_plan(), stats([4096] * 7 + [1 << 20]), cfg, stage_partitions=8)
    (ex,) = exchanges(out)
    if new_parts is not None or report is not None or ex.demote_reason != "aqe:skew":
        raise SystemExit(f"[mesh-demote] hot bucket did not demote: "
                         f"reason={ex.demote_reason!r} parts={new_parts}")

    # uniformly small input → bucket-count replan, no demotion
    out, new_parts, report = apply_aqe(
        mesh_plan(), stats([8192] * 8), cfg, stage_partitions=8)
    (ex,) = exchanges(out)
    if report is not None or not new_parts or new_parts > 4 \
            or ex.file_partitions != new_parts or ex.demote_reason:
        raise SystemExit(f"[mesh-demote] uniform input did not replan: "
                         f"parts={new_parts} reason={ex.demote_reason!r}")

    ctr = counters()
    if ctr["aqe_mesh_replans"] != 2:
        raise SystemExit(f"[mesh-demote] expected 2 mesh replans: {ctr}")
    print(f"[mesh-demote] ok: counters={json.dumps(ctr)}")


LEGS = {"split": leg_split, "coalesce": leg_coalesce,
        "mesh-demote": leg_mesh_demote}


def main() -> None:
    if len(sys.argv) > 2 and sys.argv[1] == "--leg":
        LEGS[sys.argv[2]](sys.argv[3])
        return

    with tempfile.TemporaryDirectory(prefix="skew-join-") as d:
        print(f"generating skewed join tables under {d} ...")
        write_tables(d)
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        failed = []
        for name in LEGS:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--leg", name, d],
                env=env, cwd=ROOT, timeout=600)
            if r.returncode != 0:
                failed.append(name)
        if failed:
            raise SystemExit(f"skew exercise FAILED: {failed}")

    print("skew exercise passed")


if __name__ == "__main__":
    main()
