"""Exercise the high-QPS serving tier end-to-end on a tiny TPC-H dataset.

    JAX_PLATFORMS=cpu python dev/qps_exercise.py

Two identical workloads — N concurrent sessions each firing repeated
short parameterized queries at a 2-executor StandaloneCluster — run
twice: once with the serving tier enabled (plan cache + result cache +
fast lane) and once fully disabled (the legacy queued path). The run
reports sustained QPS and p50/p99 latency for both, then enforces:

1. correctness — every query's result bytes are identical across modes
   and across repeats (zero wrong results);
2. caches engaged — nonzero plan-cache hits and fast-lane executions in
   serving mode, nothing cached in legacy mode;
3. speedup — serving-mode sustained QPS >= 2x legacy and a lower p50;
   warm serving p99 must beat the uncached legacy p50.

A third leg exercises SCHEDULER scale-out instead of the serving tier:
the same executor fleet behind N=1 vs N=4 scheduler event-loop shards
(serving disabled, checkpointing FileJobState, multi-stage aggregation
queries), enforcing that N=4 sustains strictly more QPS than N=1 with
byte-identical results — the sharded loops overlap the GIL-releasing
checkpoint fsyncs a single loop serializes. A direct-dispatch probe then
runs the prepared-statement hot path through an executor lease
(`client/direct.py`), checks byte parity against the scheduler path, and
reports `direct_dispatch_rate`.

A fourth leg exercises incremental maintenance (docs/streaming.md): an
exact-accumulator aggregate is prepared and bootstrapped, rows are
appended between refreshes, and each maintained refresh (delta query
merged into cached state) must be byte-identical to — and in aggregate
faster than — a from-scratch execution in a caches-off session.

Exits non-zero if any check fails. `run_qps_comparison`,
`run_shard_comparison`, and `run_refresh_comparison` are importable
(bench.py's serving leg reuses them).
"""

import os
import statistics
import sys
import tempfile
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# one query SHAPE, many literals: every distinct literal is a fresh SQL
# text, so legacy mode re-parses and re-plans each one while serving mode
# binds into one cached template
QUERY = ("SELECT l_orderkey, l_partkey, l_quantity FROM lineitem "
         "WHERE l_quantity < {k}")
PARAMS = (2, 3, 4, 5)

SESSIONS = int(os.environ.get("QPS_SESSIONS", "4"))
REPEATS = int(os.environ.get("QPS_REPEATS", "6"))  # per param, per session


def _pct(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(p / 100.0 * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def _fingerprint(tbl) -> bytes:
    """Order-independent byte fingerprint of a result table."""
    import hashlib

    cols = sorted(tbl.column_names)
    rows = sorted(zip(*(tbl.column(c).to_pylist() for c in cols)))
    return hashlib.sha256(repr((cols, rows)).encode()).digest()


def qps_leg(data_dir: str, serving: bool) -> dict:
    """Run the workload against one cluster; returns latencies, QPS, the
    per-param result fingerprints, and the serving-tier snapshot."""
    from ballista_tpu.client.context import SessionContext, fetch_job_results
    from ballista_tpu.config import (
        DEFAULT_SHUFFLE_PARTITIONS,
        SERVING_FAST_LANE,
        SERVING_PLAN_CACHE,
        SERVING_RESULT_CACHE,
        BallistaConfig,
    )
    from ballista_tpu.executor.standalone import StandaloneCluster
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 2,
        SERVING_PLAN_CACHE: serving,
        SERVING_FAST_LANE: serving,
        SERVING_RESULT_CACHE: serving,
    })
    ctx = SessionContext(cfg)
    register_tpch(ctx, data_dir)
    cluster = StandaloneCluster(num_executors=2, vcores=4, config=cfg)
    scheduler = cluster.scheduler
    mode = "serving" if serving else "legacy"
    latencies: list[float] = []
    warm_latencies: list[float] = []  # repeats after each shape's first run
    fingerprints: dict[int, set] = {k: set() for k in PARAMS}
    errors: list[str] = []
    lock = threading.Lock()

    def session_worker(n: int) -> None:
        session_id = scheduler.sessions.create_or_update(
            cfg.to_key_value_pairs(), f"qps-{mode}-{n}")
        try:
            for rep in range(REPEATS):
                for k in PARAMS:
                    t0 = time.monotonic()
                    # inline_results: in-process caller, the contract the
                    # result cache requires (tables can't ride the proto)
                    job_id = scheduler.submit_sql(QUERY.format(k=k), session_id,
                                                  inline_results=True)
                    status = scheduler.wait_for_job(job_id, timeout=120)
                    if status["state"] != "successful":
                        raise RuntimeError(
                            f"job {job_id} {status['state']}: {status.get('error')}")
                    tbl = fetch_job_results(status, cfg)
                    dt = time.monotonic() - t0
                    with lock:
                        latencies.append(dt)
                        if rep > 0:
                            warm_latencies.append(dt)
                        fingerprints[k].add(_fingerprint(tbl))
        except Exception as e:  # noqa: BLE001 — collected and reported
            with lock:
                errors.append(f"session {n}: {e}")

    try:
        # warm the cluster once so neither mode pays executor cold-start
        # inside the timed window
        warm_sid = scheduler.sessions.create_or_update(
            cfg.to_key_value_pairs(), f"qps-{mode}-warmup")
        wj = scheduler.submit_sql(QUERY.format(k=PARAMS[0]), warm_sid)
        if scheduler.wait_for_job(wj, timeout=120)["state"] != "successful":
            raise SystemExit(f"[{mode}] warmup query failed")

        threads = [threading.Thread(target=session_worker, args=(i,))
                   for i in range(SESSIONS)]
        t_start = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t_start
    finally:
        cluster.shutdown()

    if errors:
        raise SystemExit(f"[{mode}] worker failures: {errors[:3]}")
    lat = sorted(latencies)
    warm = sorted(warm_latencies)
    return {
        "mode": mode,
        "queries": len(latencies),
        "wall_s": round(wall, 3),
        "qps": round(len(latencies) / wall, 2),
        "p50_ms": round(_pct(lat, 50) * 1000, 1),
        "p99_ms": round(_pct(lat, 99) * 1000, 1),
        "warm_p50_ms": round(_pct(warm, 50) * 1000, 1),
        "warm_p99_ms": round(_pct(warm, 99) * 1000, 1),
        "mean_ms": round(statistics.mean(lat) * 1000, 1),
        "fingerprints": fingerprints,
        "serving": scheduler.serving.snapshot(),
    }


def run_qps_comparison(data_dir: str) -> dict:
    """Serving vs legacy on the same data; asserts the acceptance bars and
    returns both legs' stats (without the raw fingerprints)."""
    legacy = qps_leg(data_dir, serving=False)
    served = qps_leg(data_dir, serving=True)

    # 1. zero wrong results: one fingerprint per param, identical across modes
    for k in PARAMS:
        fps = served["fingerprints"][k] | legacy["fingerprints"][k]
        if len(served["fingerprints"][k]) != 1 or len(fps) != 1:
            raise SystemExit(
                f"[qps] param {k}: results diverged across repeats/modes "
                f"(serving={len(served['fingerprints'][k])} distinct, "
                f"combined={len(fps)})")

    # 2. the caches actually engaged
    snap = served["serving"]
    if snap["plan_cache"]["hits"] == 0:
        raise SystemExit("[qps] serving mode recorded zero plan-cache hits — vacuous")
    if snap["fast_lane"]["executed"] == 0:
        raise SystemExit("[qps] fast lane never engaged on a single-stage query")
    if snap["result_cache"]["hits"] == 0:
        raise SystemExit("[qps] result cache recorded zero hits on repeats")
    lsnap = legacy["serving"]
    if lsnap["plan_cache"]["hits"] or lsnap["plan_cache"]["misses"]:
        raise SystemExit("[qps] disabled serving tier still touched the plan cache")

    # 3. the speedup bars
    if served["qps"] < 2.0 * legacy["qps"]:
        raise SystemExit(f"[qps] serving {served['qps']} QPS < 2x legacy "
                         f"{legacy['qps']} QPS")
    if served["p50_ms"] >= legacy["p50_ms"]:
        raise SystemExit(f"[qps] serving p50 {served['p50_ms']}ms not below "
                         f"legacy p50 {legacy['p50_ms']}ms")
    if served["warm_p99_ms"] >= legacy["p50_ms"]:
        raise SystemExit(f"[qps] warm serving p99 {served['warm_p99_ms']}ms not "
                         f"below uncached legacy p50 {legacy['p50_ms']}ms")

    out = {}
    for leg in (legacy, served):
        leg = dict(leg)
        leg.pop("fingerprints")
        out[leg["mode"]] = leg
    out["speedup_qps"] = round(served["qps"] / max(legacy["qps"], 1e-9), 2)
    out["speedup_p50"] = round(legacy["p50_ms"] / max(served["p50_ms"], 1e-9), 2)
    return out


# multi-stage shape for the shard leg: the GROUP BY forces a shuffle
# (partial agg stage -> final agg stage), so every job crosses the event
# loop several times and checkpoints at each stage transition
SHARD_QUERY = ("SELECT l_returnflag, COUNT(*) AS c, SUM(l_quantity) AS q "
               "FROM lineitem WHERE l_quantity < {k} GROUP BY l_returnflag")
SHARD_SESSIONS = int(os.environ.get("QPS_SHARD_SESSIONS", "24"))
SHARD_REPEATS = int(os.environ.get("QPS_SHARD_REPEATS", "4"))
# modeled commit RTT of the shared job-state store (see RemoteStoreJobState)
SHARD_COMMIT_MS = float(os.environ.get("QPS_SHARD_COMMIT_MS", "15"))


def _remote_store_job_state(state_dir: str, commit_latency_s: float):
    """FileJobState plus a modeled commit round trip.

    A multi-scheduler deployment checkpoints through a SHARED remote
    store (etcd/sled behind the reference's JobState trait); every
    `save_graph` pays that store's commit RTT — milliseconds of wall
    time during which the committing event loop holds no CPU. Standalone
    mode's local-file store understates this to microseconds, which
    would let a single loop checkpoint hundreds of jobs a second and
    hide exactly the serialization scheduler sharding removes. The
    sleep (GIL released, like the real socket wait) restores the
    deployment-shaped cost; everything else is the real FileJobState."""
    from ballista_tpu.scheduler.state.job_state import FileJobState

    class RemoteStoreJobState(FileJobState):
        def save_graph(self, graph) -> None:
            time.sleep(commit_latency_s)
            super().save_graph(graph)

    return RemoteStoreJobState(state_dir, fsync=True)


def shard_leg(data_dir: str, shards: int) -> dict:
    """One shard-count leg: concurrent sessions firing multi-stage jobs at
    a StandaloneCluster whose scheduler runs `shards` event loops over a
    checkpointing job-state store with a realistic commit RTT — the
    serialized wait the sharded loops overlap. The plan cache stays ON
    (planning happens once, off the event loop) and the result cache OFF
    (every job really executes), so the leg measures the scheduling path,
    not parse/optimize throughput."""
    from ballista_tpu.client.context import SessionContext, fetch_job_results
    from ballista_tpu.config import (
        DEFAULT_SHUFFLE_PARTITIONS,
        SERVING_FAST_LANE,
        SERVING_PLAN_CACHE,
        SERVING_RESULT_CACHE,
        BallistaConfig,
    )
    from ballista_tpu.executor.standalone import StandaloneCluster
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 2,
        SERVING_PLAN_CACHE: True,
        # fast lane can't take a 2-stage plan, but keep it off so a future
        # planner improvement doesn't silently reroute the leg off the loop
        SERVING_FAST_LANE: False,
        SERVING_RESULT_CACHE: False,
    })
    ctx = SessionContext(cfg)
    register_tpch(ctx, data_dir)
    state_dir = tempfile.mkdtemp(prefix=f"qps-shard{shards}-state-")
    cluster = StandaloneCluster(
        num_executors=2, vcores=8, config=cfg, shards=shards,
        job_state=_remote_store_job_state(state_dir, SHARD_COMMIT_MS / 1000.0))
    scheduler = cluster.scheduler
    latencies: list[float] = []
    fingerprints: dict[int, set] = {k: set() for k in PARAMS}
    errors: list[str] = []
    lock = threading.Lock()

    def session_worker(n: int) -> None:
        session_id = scheduler.sessions.create_or_update(
            cfg.to_key_value_pairs(), f"shard{shards}-{n}")
        try:
            for _rep in range(SHARD_REPEATS):
                for k in PARAMS:
                    t0 = time.monotonic()
                    job_id = scheduler.submit_sql(
                        SHARD_QUERY.format(k=k), session_id, inline_results=True)
                    status = scheduler.wait_for_job(job_id, timeout=120)
                    if status["state"] != "successful":
                        raise RuntimeError(
                            f"job {job_id} {status['state']}: {status.get('error')}")
                    tbl = fetch_job_results(status, cfg)
                    dt = time.monotonic() - t0
                    with lock:
                        latencies.append(dt)
                        fingerprints[k].add(_fingerprint(tbl))
        except Exception as e:  # noqa: BLE001 — collected and reported
            with lock:
                errors.append(f"session {n}: {e}")

    try:
        warm_sid = scheduler.sessions.create_or_update(
            cfg.to_key_value_pairs(), f"shard{shards}-warmup")
        wj = scheduler.submit_sql(SHARD_QUERY.format(k=PARAMS[0]), warm_sid)
        if scheduler.wait_for_job(wj, timeout=120)["state"] != "successful":
            raise SystemExit(f"[shards={shards}] warmup query failed")

        threads = [threading.Thread(target=session_worker, args=(i,))
                   for i in range(SHARD_SESSIONS)]
        t_start = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t_start
        shard_snap = scheduler.shards_snapshot()
    finally:
        cluster.shutdown()

    if errors:
        raise SystemExit(f"[shards={shards}] worker failures: {errors[:3]}")
    lat = sorted(latencies)
    return {
        "shards": shards,
        "queries": len(latencies),
        "wall_s": round(wall, 3),
        "qps": round(len(latencies) / wall, 2),
        "p50_ms": round(_pct(lat, 50) * 1000, 1),
        "p99_ms": round(_pct(lat, 99) * 1000, 1),
        "fingerprints": fingerprints,
        "shard_snapshot": shard_snap,
    }


def direct_probe(data_dir: str) -> dict:
    """Prepared-statement direct dispatch vs the scheduler path on one
    cluster: byte parity per param, plus the achieved direct rate."""
    from ballista_tpu.client.context import SessionContext, fetch_job_results
    from ballista_tpu.client.direct import DirectDispatcher, LocalLeaseTransport
    from ballista_tpu.config import (
        DEFAULT_SHUFFLE_PARTITIONS,
        BallistaConfig,
    )
    from ballista_tpu.executor.standalone import StandaloneCluster
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 2})
    ctx = SessionContext(cfg)
    register_tpch(ctx, data_dir)
    cluster = StandaloneCluster(num_executors=2, vcores=4, config=cfg)
    scheduler = cluster.scheduler
    try:
        session_id = scheduler.sessions.create_or_update(
            cfg.to_key_value_pairs(), "direct-probe")
        d = DirectDispatcher(scheduler, LocalLeaseTransport(cluster.executors),
                             session_id)
        # prepare takes concrete SQL; literal lifting parameterizes it
        d.prepare(QUERY.format(k=PARAMS[0]))
        for rep in range(3):
            for k in PARAMS:
                st_direct = d.execute((k,))
                direct_fp = _fingerprint(fetch_job_results(st_direct, cfg))
                jid = scheduler.execute_prepared(
                    d.statement_id, (k,), session_id=session_id)
                st_sched = scheduler.wait_for_job(jid, timeout=120)
                if st_sched["state"] != "successful":
                    raise SystemExit(f"[direct] scheduler path failed: {st_sched}")
                sched_fp = _fingerprint(fetch_job_results(st_sched, cfg))
                if direct_fp != sched_fp:
                    raise SystemExit(
                        f"[direct] param {k} rep {rep}: direct-dispatch bytes "
                        f"diverge from the scheduler path")
        rate = d.direct_dispatch_rate()
        if rate <= 0.0:
            raise SystemExit("[direct] every dispatch demoted — the lease "
                             "path never actually ran")
        return {"direct_dispatch_rate": round(rate, 3), "stats": dict(d.stats),
                "leases": scheduler.leases.snapshot()}
    finally:
        cluster.shutdown()


def run_shard_comparison(data_dir: str) -> dict:
    """N=1 vs N=4 scheduler shards over the same fleet, plus the
    direct-dispatch parity probe; asserts the scale-out acceptance bars.

    The shard legs run on their own TINY dataset (sf0.001): the leg
    measures control-plane throughput, and scan-heavy tasks on one core
    would put the ceiling at the data plane for both shard counts."""
    from ballista_tpu.testing.tpchgen import generate_tpch

    with tempfile.TemporaryDirectory(prefix="qps-shard-data-") as tiny:
        generate_tpch(tiny, scale=0.001, seed=42, files_per_table=1)
        n1 = shard_leg(tiny, shards=1)
        n4 = shard_leg(tiny, shards=4)

    # byte-identical results across shard counts and repeats
    for k in PARAMS:
        fps = n1["fingerprints"][k] | n4["fingerprints"][k]
        if len(fps) != 1:
            raise SystemExit(
                f"[shards] param {k}: results diverged across shard counts "
                f"({len(n4['fingerprints'][k])} distinct at N=4, "
                f"{len(fps)} combined)")

    # the loops actually sharded: every shard saw events
    snap = n4["shard_snapshot"]
    if len(snap) != 4 or any(s["handled"] == 0 for s in snap):
        raise SystemExit(f"[shards] N=4 leg left idle shards: {snap}")

    # scale-out bar: more event loops -> strictly more sustained QPS
    if n4["qps"] <= n1["qps"]:
        raise SystemExit(f"[shards] N=4 {n4['qps']} QPS not above N=1 "
                         f"{n1['qps']} QPS")

    direct = direct_probe(data_dir)
    out = {}
    for leg in (n1, n4):
        leg = dict(leg)
        leg.pop("fingerprints")
        out[f"shards_{leg['shards']}"] = leg
    out["scheduler_shards"] = 4
    out["shard_speedup_qps"] = round(n4["qps"] / max(n1["qps"], 1e-9), 2)
    out["direct_dispatch_rate"] = direct["direct_dispatch_rate"]
    out["direct"] = direct
    return out


# incremental-refresh leg: a q1-shaped grouped aggregate whose accumulators
# are all exact (COUNT, int64 SUM, MIN/MAX — the generator's monetary
# columns are float64, and float SUMs are ineligible by design), so the
# serving tier maintains the cached result from retained deltas instead of
# recomputing. The leg appends rows between refreshes and enforces that the
# maintained refresh is BOTH faster than a from-scratch execution and
# byte-identical to it (docs/streaming.md).
REFRESH_QUERY = (
    "SELECT l_returnflag, l_linestatus, COUNT(*) AS cnt, "
    "SUM(l_orderkey) AS sum_ok, MIN(l_quantity) AS min_qty, "
    "MAX(l_quantity) AS max_qty FROM lineitem WHERE l_quantity < 45 "
    "GROUP BY l_returnflag, l_linestatus ORDER BY l_returnflag, l_linestatus")
REFRESH_ROUNDS = int(os.environ.get("QPS_REFRESH_ROUNDS", "5"))
REFRESH_APPEND_ROWS = int(os.environ.get("QPS_REFRESH_APPEND_ROWS", "512"))


def run_refresh_comparison(data_dir: str) -> dict:
    """Append-then-refresh on one cluster: the maintained path (delta query
    merged into cached aggregation state) vs a from-scratch execution of
    the same statement in a caches-off session. Asserts byte identity per
    round, that the maintenance counters actually moved, and that the
    maintained refresh is faster in aggregate."""
    import glob

    import pyarrow.parquet as pq

    from ballista_tpu.client.context import SessionContext, fetch_job_results
    from ballista_tpu.config import (
        DEFAULT_SHUFFLE_PARTITIONS,
        SERVING_FAST_LANE,
        SERVING_PLAN_CACHE,
        SERVING_RESULT_CACHE,
        BallistaConfig,
    )
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 2,
        SERVING_PLAN_CACHE: True,
        SERVING_FAST_LANE: False,
        # the result cache (and with it the maintenance ladder) is opt-in
        SERVING_RESULT_CACHE: True,
    })
    ctx = SessionContext.standalone(config=cfg, num_executors=2, vcores=4)
    register_tpch(ctx, data_dir)

    # the appended rows: real lineitem rows re-sent, so every round changes
    # the aggregate and both paths must agree on the new answer
    src = sorted(glob.glob(os.path.join(data_dir, "lineitem", "*.parquet")))[0]
    pool = pq.read_table(src).slice(0, REFRESH_ROUNDS * REFRESH_APPEND_ROWS)
    if pool.num_rows < REFRESH_ROUNDS * REFRESH_APPEND_ROWS:
        raise SystemExit(f"[refresh] delta pool too small: {pool.num_rows} rows")

    maintained_s: list[float] = []
    full_s: list[float] = []
    try:
        stmt = ctx.prepare(REFRESH_QUERY)
        scheduler = ctx._cluster.scheduler

        # from-scratch leg: same scheduler, a session with the result cache
        # off, so every submit re-executes the full plan (appended rows are
        # still visible — the dispatch-time scan graft serves them). Copy
        # the context's config: table registrations ride the session config
        # as ballista.catalog.table.* pairs.
        full_cfg = ctx.config.copy()
        full_cfg.set(SERVING_RESULT_CACHE, "false")
        full_sid = scheduler.sessions.create_or_update(
            full_cfg.to_key_value_pairs(), "refresh-full")

        def full_exec():
            jid = scheduler.submit_sql(REFRESH_QUERY, full_sid,
                                       inline_results=True)
            status = scheduler.wait_for_job(jid, timeout=120)
            if status["state"] != "successful":
                raise SystemExit(f"[refresh] from-scratch execution failed: "
                                 f"{status.get('error')}")
            return fetch_job_results(status, full_cfg)

        # warm both paths outside the timed window: the first prepared
        # execution bootstraps the accumulator state, the first full run
        # pays executor compile
        t0 = time.monotonic()
        boot = stmt.execute()
        bootstrap_ms = round((time.monotonic() - t0) * 1000, 1)
        if _fingerprint(boot) != _fingerprint(full_exec()):
            raise SystemExit("[refresh] bootstrap bytes diverge from scratch")

        for r in range(REFRESH_ROUNDS):
            delta = pool.slice(r * REFRESH_APPEND_ROWS, REFRESH_APPEND_ROWS)
            ctx.append("lineitem", delta)
            t0 = time.monotonic()
            got = stmt.execute()
            maintained_s.append(time.monotonic() - t0)
            t0 = time.monotonic()
            full = full_exec()
            full_s.append(time.monotonic() - t0)
            if _fingerprint(got) != _fingerprint(full):
                raise SystemExit(f"[refresh] round {r}: maintained bytes "
                                 f"diverge from a from-scratch execution")

        snap = scheduler.serving.snapshot()["incremental"]
    finally:
        ctx.shutdown()

    # the cheap path actually ran: every refresh maintained, none recomputed
    if snap["maintained"] < REFRESH_ROUNDS:
        raise SystemExit(f"[refresh] only {snap['maintained']} of "
                         f"{REFRESH_ROUNDS} refreshes maintained: {snap}")
    if snap["bootstraps"] < 1 or snap["appends"] < REFRESH_ROUNDS:
        raise SystemExit(f"[refresh] counters implausible: {snap}")
    modes = {m["mode"] for m in snap["modes"].values()}
    if "aggregate" not in modes:
        raise SystemExit(f"[refresh] no template analyzed as aggregate: {snap}")

    m_total, f_total = sum(maintained_s), sum(full_s)
    if m_total >= f_total:
        raise SystemExit(f"[refresh] maintained refresh {m_total:.3f}s not "
                         f"faster than from-scratch {f_total:.3f}s")
    m_sorted, f_sorted = sorted(maintained_s), sorted(full_s)
    return {
        "rounds": REFRESH_ROUNDS,
        "append_rows": REFRESH_APPEND_ROWS,
        "bootstrap_ms": bootstrap_ms,
        "maintained_total_s": round(m_total, 3),
        "full_total_s": round(f_total, 3),
        "speedup": round(f_total / max(m_total, 1e-9), 2),
        "maintained_p50_ms": round(_pct(m_sorted, 50) * 1000, 1),
        "full_p50_ms": round(_pct(f_sorted, 50) * 1000, 1),
        "incremental": {k: snap[k] for k in
                        ("maintained", "bootstraps", "state_renders",
                         "recomputes", "appends", "appended_rows")},
    }


def main() -> None:
    from ballista_tpu.testing.tpchgen import generate_tpch

    with tempfile.TemporaryDirectory(prefix="qps-tpch-") as d:
        print(f"generating TPC-H sf0.01 under {d} ...")
        generate_tpch(d, scale=0.01, seed=42, files_per_table=2)
        stats = run_qps_comparison(d)
        for mode in ("legacy", "serving"):
            s = stats[mode]
            print(f"[{mode:8s}] {s['queries']} queries in {s['wall_s']}s "
                  f"-> {s['qps']} QPS  p50={s['p50_ms']}ms p99={s['p99_ms']}ms "
                  f"(warm p50={s['warm_p50_ms']}ms p99={s['warm_p99_ms']}ms)")
        srv = stats["serving"]["serving"]
        print(f"[caches  ] plan hits={srv['plan_cache']['hits']} "
              f"misses={srv['plan_cache']['misses']} "
              f"text_hits={srv['plan_cache']['text_hits']} "
              f"fast_lane={srv['fast_lane']}")
        print(f"qps exercise passed: {stats['speedup_qps']}x QPS, "
              f"{stats['speedup_p50']}x p50")

        shard_stats = run_shard_comparison(d)
        for key in ("shards_1", "shards_4"):
            s = shard_stats[key]
            print(f"[shards={s['shards']}] {s['queries']} queries in "
                  f"{s['wall_s']}s -> {s['qps']} QPS  "
                  f"p50={s['p50_ms']}ms p99={s['p99_ms']}ms")
        print(f"[direct  ] rate={shard_stats['direct_dispatch_rate']} "
              f"stats={shard_stats['direct']['stats']}")
        print(f"shard exercise passed: {shard_stats['shard_speedup_qps']}x QPS "
              f"at N=4, direct dispatch byte-identical")

        refresh = run_refresh_comparison(d)
        print(f"[refresh ] {refresh['rounds']} appends x "
              f"{refresh['append_rows']} rows: maintained "
              f"{refresh['maintained_total_s']}s vs from-scratch "
              f"{refresh['full_total_s']}s "
              f"(p50 {refresh['maintained_p50_ms']}ms vs "
              f"{refresh['full_p50_ms']}ms)  counters={refresh['incremental']}")
        print(f"refresh exercise passed: {refresh['speedup']}x, "
              f"maintained results byte-identical")


if __name__ == "__main__":
    main()
