"""Exercise the high-QPS serving tier end-to-end on a tiny TPC-H dataset.

    JAX_PLATFORMS=cpu python dev/qps_exercise.py

Two identical workloads — N concurrent sessions each firing repeated
short parameterized queries at a 2-executor StandaloneCluster — run
twice: once with the serving tier enabled (plan cache + result cache +
fast lane) and once fully disabled (the legacy queued path). The run
reports sustained QPS and p50/p99 latency for both, then enforces:

1. correctness — every query's result bytes are identical across modes
   and across repeats (zero wrong results);
2. caches engaged — nonzero plan-cache hits and fast-lane executions in
   serving mode, nothing cached in legacy mode;
3. speedup — serving-mode sustained QPS >= 2x legacy and a lower p50;
   warm serving p99 must beat the uncached legacy p50.

Exits non-zero if any check fails. `run_qps_comparison` is importable
(bench.py's serving leg reuses it).
"""

import os
import statistics
import sys
import tempfile
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# one query SHAPE, many literals: every distinct literal is a fresh SQL
# text, so legacy mode re-parses and re-plans each one while serving mode
# binds into one cached template
QUERY = ("SELECT l_orderkey, l_partkey, l_quantity FROM lineitem "
         "WHERE l_quantity < {k}")
PARAMS = (2, 3, 4, 5)

SESSIONS = int(os.environ.get("QPS_SESSIONS", "4"))
REPEATS = int(os.environ.get("QPS_REPEATS", "6"))  # per param, per session


def _pct(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(p / 100.0 * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def _fingerprint(tbl) -> bytes:
    """Order-independent byte fingerprint of a result table."""
    import hashlib

    cols = sorted(tbl.column_names)
    rows = sorted(zip(*(tbl.column(c).to_pylist() for c in cols)))
    return hashlib.sha256(repr((cols, rows)).encode()).digest()


def qps_leg(data_dir: str, serving: bool) -> dict:
    """Run the workload against one cluster; returns latencies, QPS, the
    per-param result fingerprints, and the serving-tier snapshot."""
    from ballista_tpu.client.context import SessionContext, fetch_job_results
    from ballista_tpu.config import (
        DEFAULT_SHUFFLE_PARTITIONS,
        SERVING_FAST_LANE,
        SERVING_PLAN_CACHE,
        SERVING_RESULT_CACHE,
        BallistaConfig,
    )
    from ballista_tpu.executor.standalone import StandaloneCluster
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 2,
        SERVING_PLAN_CACHE: serving,
        SERVING_FAST_LANE: serving,
        SERVING_RESULT_CACHE: serving,
    })
    ctx = SessionContext(cfg)
    register_tpch(ctx, data_dir)
    cluster = StandaloneCluster(num_executors=2, vcores=4, config=cfg)
    scheduler = cluster.scheduler
    mode = "serving" if serving else "legacy"
    latencies: list[float] = []
    warm_latencies: list[float] = []  # repeats after each shape's first run
    fingerprints: dict[int, set] = {k: set() for k in PARAMS}
    errors: list[str] = []
    lock = threading.Lock()

    def session_worker(n: int) -> None:
        session_id = scheduler.sessions.create_or_update(
            cfg.to_key_value_pairs(), f"qps-{mode}-{n}")
        try:
            for rep in range(REPEATS):
                for k in PARAMS:
                    t0 = time.monotonic()
                    # inline_results: in-process caller, the contract the
                    # result cache requires (tables can't ride the proto)
                    job_id = scheduler.submit_sql(QUERY.format(k=k), session_id,
                                                  inline_results=True)
                    status = scheduler.wait_for_job(job_id, timeout=120)
                    if status["state"] != "successful":
                        raise RuntimeError(
                            f"job {job_id} {status['state']}: {status.get('error')}")
                    tbl = fetch_job_results(status, cfg)
                    dt = time.monotonic() - t0
                    with lock:
                        latencies.append(dt)
                        if rep > 0:
                            warm_latencies.append(dt)
                        fingerprints[k].add(_fingerprint(tbl))
        except Exception as e:  # noqa: BLE001 — collected and reported
            with lock:
                errors.append(f"session {n}: {e}")

    try:
        # warm the cluster once so neither mode pays executor cold-start
        # inside the timed window
        warm_sid = scheduler.sessions.create_or_update(
            cfg.to_key_value_pairs(), f"qps-{mode}-warmup")
        wj = scheduler.submit_sql(QUERY.format(k=PARAMS[0]), warm_sid)
        if scheduler.wait_for_job(wj, timeout=120)["state"] != "successful":
            raise SystemExit(f"[{mode}] warmup query failed")

        threads = [threading.Thread(target=session_worker, args=(i,))
                   for i in range(SESSIONS)]
        t_start = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t_start
    finally:
        cluster.shutdown()

    if errors:
        raise SystemExit(f"[{mode}] worker failures: {errors[:3]}")
    lat = sorted(latencies)
    warm = sorted(warm_latencies)
    return {
        "mode": mode,
        "queries": len(latencies),
        "wall_s": round(wall, 3),
        "qps": round(len(latencies) / wall, 2),
        "p50_ms": round(_pct(lat, 50) * 1000, 1),
        "p99_ms": round(_pct(lat, 99) * 1000, 1),
        "warm_p50_ms": round(_pct(warm, 50) * 1000, 1),
        "warm_p99_ms": round(_pct(warm, 99) * 1000, 1),
        "mean_ms": round(statistics.mean(lat) * 1000, 1),
        "fingerprints": fingerprints,
        "serving": scheduler.serving.snapshot(),
    }


def run_qps_comparison(data_dir: str) -> dict:
    """Serving vs legacy on the same data; asserts the acceptance bars and
    returns both legs' stats (without the raw fingerprints)."""
    legacy = qps_leg(data_dir, serving=False)
    served = qps_leg(data_dir, serving=True)

    # 1. zero wrong results: one fingerprint per param, identical across modes
    for k in PARAMS:
        fps = served["fingerprints"][k] | legacy["fingerprints"][k]
        if len(served["fingerprints"][k]) != 1 or len(fps) != 1:
            raise SystemExit(
                f"[qps] param {k}: results diverged across repeats/modes "
                f"(serving={len(served['fingerprints'][k])} distinct, "
                f"combined={len(fps)})")

    # 2. the caches actually engaged
    snap = served["serving"]
    if snap["plan_cache"]["hits"] == 0:
        raise SystemExit("[qps] serving mode recorded zero plan-cache hits — vacuous")
    if snap["fast_lane"]["executed"] == 0:
        raise SystemExit("[qps] fast lane never engaged on a single-stage query")
    if snap["result_cache"]["hits"] == 0:
        raise SystemExit("[qps] result cache recorded zero hits on repeats")
    lsnap = legacy["serving"]
    if lsnap["plan_cache"]["hits"] or lsnap["plan_cache"]["misses"]:
        raise SystemExit("[qps] disabled serving tier still touched the plan cache")

    # 3. the speedup bars
    if served["qps"] < 2.0 * legacy["qps"]:
        raise SystemExit(f"[qps] serving {served['qps']} QPS < 2x legacy "
                         f"{legacy['qps']} QPS")
    if served["p50_ms"] >= legacy["p50_ms"]:
        raise SystemExit(f"[qps] serving p50 {served['p50_ms']}ms not below "
                         f"legacy p50 {legacy['p50_ms']}ms")
    if served["warm_p99_ms"] >= legacy["p50_ms"]:
        raise SystemExit(f"[qps] warm serving p99 {served['warm_p99_ms']}ms not "
                         f"below uncached legacy p50 {legacy['p50_ms']}ms")

    out = {}
    for leg in (legacy, served):
        leg = dict(leg)
        leg.pop("fingerprints")
        out[leg["mode"]] = leg
    out["speedup_qps"] = round(served["qps"] / max(legacy["qps"], 1e-9), 2)
    out["speedup_p50"] = round(legacy["p50_ms"] / max(served["p50_ms"], 1e-9), 2)
    return out


def main() -> None:
    from ballista_tpu.testing.tpchgen import generate_tpch

    with tempfile.TemporaryDirectory(prefix="qps-tpch-") as d:
        print(f"generating TPC-H sf0.01 under {d} ...")
        generate_tpch(d, scale=0.01, seed=42, files_per_table=2)
        stats = run_qps_comparison(d)
        for mode in ("legacy", "serving"):
            s = stats[mode]
            print(f"[{mode:8s}] {s['queries']} queries in {s['wall_s']}s "
                  f"-> {s['qps']} QPS  p50={s['p50_ms']}ms p99={s['p99_ms']}ms "
                  f"(warm p50={s['warm_p50_ms']}ms p99={s['warm_p99_ms']}ms)")
        srv = stats["serving"]["serving"]
        print(f"[caches  ] plan hits={srv['plan_cache']['hits']} "
              f"misses={srv['plan_cache']['misses']} "
              f"text_hits={srv['plan_cache']['text_hits']} "
              f"fast_lane={srv['fast_lane']}")
        print(f"qps exercise passed: {stats['speedup_qps']}x QPS, "
              f"{stats['speedup_p50']}x p50")


if __name__ == "__main__":
    main()
