"""Exercise the warm device-runtime daemon end-to-end (CPU jax).

    JAX_PLATFORMS=cpu python dev/daemon_exercise.py

One cold spawn, then N warm attaches, against TPC-H q1:

1. baseline — q1 runs fully in-process (no daemon) in THIS process; its
   result bytes are the parity reference.
2. cold — a daemon is spawned on a fresh socket; it claims the platform,
   runs `jax.devices()` and the first compile exactly once (probe report
   on disk next to the socket).
3. warm ×N — each attach leg is a FRESH subprocess that runs q1 against
   the daemon. Every leg must report `daemon_mode = "attached"`, must
   never import jax (`"jax" not in sys.modules` — zero platform inits in
   the attached process; the tiny final merge declines the device below
   TPU_MIN_ROWS before ensure_jax), and must return bytes identical to
   the baseline.
4. across the warm legs the daemon's pid and compile cache are stable:
   `compiled_entries` after leg 1 == after leg N (zero XLA recompiles on
   warm attach) and the init phase report never re-runs.

Exits non-zero on any divergence.
"""

import io
import json
import os
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

WARM_ATTACHES = 3


def q1_sql() -> str:
    with open(os.path.join(ROOT, "benchmarks", "tpch", "queries", "q1.sql")) as f:
        return f.read()


def _ipc_bytes(tbl) -> bytes:
    import pyarrow as pa

    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, tbl.schema) as w:
        w.write_table(tbl)
    return sink.getvalue()


def _run_q1(data_dir: str, extra_cfg: dict | None = None) -> bytes:
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import EXECUTOR_ENGINE, BallistaConfig
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", **(extra_cfg or {})})
    ctx = SessionContext(cfg)
    register_tpch(ctx, data_dir)
    out = ctx.sql(q1_sql()).collect()
    if out.num_rows == 0:
        raise SystemExit("[q1] produced no rows")
    return _ipc_bytes(out)


def attach_leg_main(data_dir: str, sock: str, out_path: str) -> None:
    """One warm attach, run in a fresh process: q1 against the daemon.
    Writes {mode, reason, jax_imported} JSON and the result IPC bytes."""
    from ballista_tpu.config import (
        TPU_DAEMON_ATTACH_TIMEOUT_MS,
        TPU_DAEMON_ENABLED,
        TPU_DAEMON_SOCKET,
    )
    from ballista_tpu.ops.tpu import stage_compiler as sc

    blob = _run_q1(data_dir, {
        TPU_DAEMON_ENABLED: True, TPU_DAEMON_SOCKET: sock,
        TPU_DAEMON_ATTACH_TIMEOUT_MS: 15_000,
    })
    stats = sc.RUN_STATS.snapshot()
    with open(out_path, "wb") as f:
        f.write(blob)
    with open(out_path + ".json", "w") as f:
        json.dump({
            "mode": stats.get("daemon_mode"),
            "reason": stats.get("daemon_mode_reason"),
            # the proof that the attached process did ZERO platform inits:
            # the device runtime was never even imported here
            "jax_imported": "jax" in sys.modules,
        }, f)


def main() -> None:
    from ballista_tpu.device_daemon import client as dclient
    from ballista_tpu.device_daemon import protocol as dproto
    from ballista_tpu.testing.tpchgen import generate_tpch

    with tempfile.TemporaryDirectory(prefix="daemon-ex-") as d:
        data_dir = os.path.join(d, "tpch")
        print(f"generating TPC-H sf0.01 under {data_dir} ...")
        generate_tpch(data_dir, scale=0.01, seed=42, files_per_table=2)

        print("[baseline] q1 in-process ...")
        baseline = _run_q1(data_dir)

        sock = os.path.join(d, "daemon.sock")
        print(f"[cold] spawning daemon on {sock} ...")
        dclient.spawn_daemon(sock, parent_pid=os.getpid())
        client = dclient.DaemonClient(sock)
        st = client.wait_ready(timeout_s=120)
        pid = st["pid"]
        phases = {p["name"]: p["status"] for p in st["init"]["phases"]}
        if not all(v == "ok" for v in phases.values()):
            raise SystemExit(f"[cold] init phases not ok: {phases}")
        report = json.load(open(dproto.probe_report_path(sock)))
        print(f"[cold] ok: pid {pid}, phases {phases}, "
              f"probe report ok={report['ok']}")

        compiled_after_first = None
        for i in range(1, WARM_ATTACHES + 1):
            out_path = os.path.join(d, f"warm{i}.arrow")
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--attach-leg", data_dir, sock, out_path],
                capture_output=True, text=True)
            if r.returncode != 0:
                raise SystemExit(f"[warm {i}] leg failed:\n{r.stdout}\n{r.stderr}")
            leg = json.load(open(out_path + ".json"))
            if leg["mode"] != "attached":
                raise SystemExit(f"[warm {i}] not attached: {leg}")
            if leg["jax_imported"]:
                raise SystemExit(f"[warm {i}] attached process imported jax — "
                                 "it performed platform work of its own")
            if open(out_path, "rb").read() != baseline:
                raise SystemExit(f"[warm {i}] result bytes differ from the "
                                 "in-process baseline")
            st = client.status()
            if st["pid"] != pid:
                raise SystemExit(f"[warm {i}] daemon restarted: pid {pid} → "
                                 f"{st['pid']}")
            if i == 1:
                compiled_after_first = st["compiled_entries"]
                if compiled_after_first < 1:
                    raise SystemExit("[warm 1] daemon compiled nothing")
            elif st["compiled_entries"] != compiled_after_first:
                raise SystemExit(
                    f"[warm {i}] compile cache grew "
                    f"({compiled_after_first} → {st['compiled_entries']}): "
                    "a warm attach recompiled")
            print(f"[warm {i}] ok: attached, jax-free client, byte-identical, "
                  f"compiled_entries={st['compiled_entries']}")

        client.shutdown()
    print(f"daemon exercise passed: 1 cold init, {WARM_ATTACHES} warm attaches, "
          "0 recompiles, 0 client platform inits")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--attach-leg":
        attach_leg_main(sys.argv[2], sys.argv[3], sys.argv[4])
    else:
        main()
