"""Regenerate the golden staged-plan snapshots.

    python dev/update_plan_stability.py

Rewrites tests/tpch_plan_stability/approved/{cpu,tpu}/qN.txt from the
current planner over dataless SF100-stats tables (reference:
dev/update-tpch-plan-stability.sh). Review the diff before committing —
every change is a stage-boundary / join-mode / broadcast decision change.
"""

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))


def main() -> None:
    from tpch_plan_stability.fixtures import query_path, staged_plan_text, stats_context

    for engine in ("cpu", "tpu"):
        ctx = stats_context(engine)
        out_dir = os.path.join(ROOT, "tests", "tpch_plan_stability", "approved", engine)
        os.makedirs(out_dir, exist_ok=True)
        for q in range(1, 23):
            with open(query_path(q)) as f:
                sql = f.read()
            text = staged_plan_text(ctx, sql)
            with open(os.path.join(out_dir, f"q{q}.txt"), "w") as f:
                f.write(text)
            print(f"{engine}/q{q}: {text.count('=== Stage')} stages")


if __name__ == "__main__":
    main()
