"""Exercise the straggler defenses end-to-end on a tiny TPC-H dataset.

    JAX_PLATFORMS=cpu python dev/straggler_exercise.py

Two legs, both on a 2-executor StandaloneCluster running TPC-H q6 with
chaos straggler mode pinning an 8 s nap on one scan partition:

1. speculation — the scheduler duplicates the straggling task on the
   other executor; the run must finish well under the nap and commit
   exactly one attempt's shuffle files.
2. deadline — speculation off, a 1 s per-task deadline instead; the
   straggling attempt times out, retries as attempt 1 (which escapes the
   chaos roll), and the run still converges.

Exits non-zero if either leg fails its wall-clock or bookkeeping check.
"""

import os
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

NAP_S = 8.0
Q6 = """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01'
  and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07
  and l_quantity < 24
"""


def base_config():
    from ballista_tpu.config import (
        CHAOS_ENABLED,
        CHAOS_MODE,
        CHAOS_PROBABILITY,
        CHAOS_SEED,
        CHAOS_STRAGGLER_DELAY_S,
        CHAOS_STRAGGLER_PARTITION,
        CHAOS_STRAGGLER_STAGE,
        DEFAULT_SHUFFLE_PARTITIONS,
        MAX_PARTITIONS_PER_TASK,
    )

    return {
        DEFAULT_SHUFFLE_PARTITIONS: 4,
        MAX_PARTITIONS_PER_TASK: 1,
        CHAOS_ENABLED: True,
        CHAOS_MODE: "straggler",
        CHAOS_SEED: 42,
        CHAOS_PROBABILITY: 1.0,
        CHAOS_STRAGGLER_DELAY_S: NAP_S,
        CHAOS_STRAGGLER_PARTITION: 1,
        # partition indices repeat across stages; pin the nap to the scan
        # stage so the single-task final stage can't re-hit it
        CHAOS_STRAGGLER_STAGE: 1,
    }


def run_leg(name: str, data_dir: str, extra_cfg: dict, budget_s: float) -> None:
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.executor.standalone import StandaloneCluster
    from ballista_tpu.scheduler.metrics import InMemoryMetricsCollector
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({**base_config(), **extra_cfg})
    ctx = SessionContext(cfg)
    register_tpch(ctx, data_dir)
    cluster = StandaloneCluster(num_executors=2, vcores=2, config=cfg)
    cluster.scheduler.metrics = InMemoryMetricsCollector()
    try:
        scheduler = cluster.scheduler
        session_id = scheduler.sessions.create_or_update(
            cfg.to_key_value_pairs(), f"straggler-{name}")
        t0 = time.time()
        job_id = scheduler.submit_sql(Q6, session_id)
        status = scheduler.wait_for_job(job_id, timeout=60)
        elapsed = time.time() - t0
        if status["state"] != "successful":
            raise SystemExit(f"[{name}] job failed: {status.get('error')}")
        if elapsed >= budget_s:
            raise SystemExit(
                f"[{name}] took {elapsed:.1f}s — defense did not beat the "
                f"{NAP_S:.0f}s straggler (budget {budget_s:.1f}s)")
        m = cluster.scheduler.metrics
        print(f"[{name}] ok: {elapsed:.2f}s  "
              f"speculative_launched={m.speculative_launched}  "
              f"task_timeouts={m.task_timeouts}")
        if name == "speculation" and m.speculative_launched < 1:
            raise SystemExit("[speculation] no speculative attempt was launched")
        if name == "deadline" and m.task_timeouts < 1:
            raise SystemExit("[deadline] no task timed out — deadline never fired")
        leftovers = [f for r, _, fs in os.walk(cluster.work_dir)
                     for f in fs if f.endswith(".tmp")]
        if leftovers:
            # aborted attempts sweep their own tmp files; give them a beat
            time.sleep(1.0)
            leftovers = [f for r, _, fs in os.walk(cluster.work_dir)
                         for f in fs if f.endswith(".tmp")]
        if leftovers:
            raise SystemExit(f"[{name}] torn shuffle tmp files left behind: {leftovers}")
    finally:
        cluster.shutdown()


def main() -> None:
    from ballista_tpu.config import (
        SPECULATION_ENABLED,
        SPECULATION_MIN_RUNTIME_S,
        SPECULATION_MULTIPLIER,
        SPECULATION_QUANTILE,
        TASK_DEADLINE_S,
    )
    from ballista_tpu.testing.tpchgen import generate_tpch

    with tempfile.TemporaryDirectory(prefix="straggler-tpch-") as d:
        print(f"generating TPC-H sf0.01 under {d} ...")
        generate_tpch(d, scale=0.01, seed=42, files_per_table=2)

        run_leg("speculation", d, {
            SPECULATION_QUANTILE: 0.5,
            SPECULATION_MIN_RUNTIME_S: 0.2,
            SPECULATION_MULTIPLIER: 1.5,
        }, budget_s=NAP_S - 1.5)

        run_leg("deadline", d, {
            SPECULATION_ENABLED: False,
            TASK_DEADLINE_S: 1.0,
        }, budget_s=NAP_S - 1.5)

    print("straggler exercise passed")


if __name__ == "__main__":
    main()
