"""Drive the SANITIZED native components through their wire contracts.

Invoked by dev/sanitize_native.sh with LD_PRELOAD pointing at the
sanitizer runtime: any ASAN/TSAN/UBSAN report aborts the process and
fails the leg.

- row router: hash parity vs the numpy hasher over random + adversarial
  inputs (nulls, negatives, huge ints, empty strings, multi-key), routing
  bounds over many K values.
- Flight server: both layouts via do_get, raw-block transport, path
  containment rejections, remove_job_data — against the sanitized binary.
"""

import ctypes
import json
import os
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pyarrow.flight as flight
import pyarrow.ipc as ipc

MODE = os.environ.get("SAN_MODE", "asan")
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def exercise_router() -> None:
    os.environ["BALLISTA_NATIVE_LIB"] = os.path.join(
        ROOT, "native", "sanitize", f"libballista_native_{MODE}.so")
    from ballista_tpu.ops import native
    from ballista_tpu.ops.hashing import hash_arrays

    rng = np.random.default_rng(11)
    cases = [
        [pa.array(rng.integers(-(2**62), 2**62, 10_000), pa.int64())],
        [pa.array(rng.random(5_000))],
        [pa.array(["x" * (i % 40) for i in range(3_000)])],
        [pa.array([None, 1, None, 2**60, -5], pa.int64())],
        [pa.array([True, None, False] * 100, pa.bool_())],
        [pa.array(np.arange(1000), pa.int64()),
         pa.array([f"k{i % 7}" for i in range(1000)])],
        [pa.array([], pa.int64())],
    ]
    for arrays in cases:
        got = native.hash_arrays_native(arrays)
        assert got is not None, "sanitized lib not used"
        want = hash_arrays(arrays)
        assert (got == want).all(), "hash parity broke under sanitizer build"
        if len(arrays[0]):
            for k in (1, 2, 7, 64, 1024):
                routed = native.route_native(got, k)
                if routed is not None:
                    pids, bounds, order = routed
                    assert pids.max() < k and pids.min() >= 0
                    assert bounds[-1] == len(arrays[0])
    print("row router: ok")


def exercise_flight() -> None:
    import tempfile

    work = tempfile.mkdtemp(prefix="san-flight-")
    batch = pa.record_batch({"x": pa.array(np.arange(1000), pa.int64())})
    d = os.path.join(work, "j1", "1", "0")
    os.makedirs(d)
    data = os.path.join(d, "data-t0.arrow")
    with open(data, "wb") as f:
        with ipc.new_stream(f, batch.schema) as w:
            w.write_batch(batch)

    bin_path = os.path.join(ROOT, "native", "sanitize", f"ballista-flight-server-{MODE}")
    env = dict(os.environ)
    env.pop("LD_PRELOAD", None)  # the server's sanitizer runtime is linked in
    stderr_path = os.path.join(work, "server.stderr")
    stderr_f = open(stderr_path, "wb")
    proc = subprocess.Popen(
        [bin_path, "--host", "127.0.0.1", "--port", "0", "--work-dir", work],
        stdout=subprocess.PIPE, stderr=stderr_f, text=True, env=env,
    )
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("PORT "), line
        port = int(line.split()[1])
        c = flight.FlightClient(f"grpc://127.0.0.1:{port}")
        t = flight.Ticket(json.dumps({"path": data, "layout": "hash", "output_partition": 0}).encode())
        got = sum(ch.data.num_rows for ch in c.do_get(t))
        assert got == 1000
        a = flight.Action("io_block_transport", json.dumps(
            {"path": data, "layout": "hash", "output_partition": 0}).encode())
        blob = b"".join(r.body.to_pybytes() for r in c.do_action(a))
        assert sum(b.num_rows for b in ipc.open_stream(pa.BufferReader(blob))) == 1000
        # containment rejection
        bad = flight.Ticket(json.dumps({"path": "/etc/hostname", "layout": "hash",
                                        "output_partition": 0}).encode())
        try:
            list(c.do_get(bad))
            raise AssertionError("containment did not reject")
        except flight.FlightError:
            pass
        except pa.ArrowInvalid:
            pass
        list(c.do_action(flight.Action("remove_job_data", json.dumps({"job_id": "j1"}).encode())))
        assert not os.path.exists(os.path.join(work, "j1"))
        c.close()
    finally:
        proc.terminate()
        try:
            # TSAN teardown (shadow cleanup + report symbolization) can take
            # tens of seconds on one loaded core
            rc = proc.wait(timeout=90)
        except subprocess.TimeoutExpired:
            if MODE != "tsan":
                raise  # only TSAN teardown legitimately stalls this long
            # a TSAN-instrumented gRPC server can wedge in its own shutdown
            # path when starved; the exercise itself already completed, so
            # kill and judge the run by its REPORT OUTPUT below, not exit
            print("(tsan server ignored SIGTERM for 90s; killing)")
            proc.kill()
            rc = proc.wait(timeout=30)
        stderr_f.close()
    # reports are the ground truth (a killed server never reaches the
    # sanitizer's exitcode path): scan captured stderr, then check rc —
    # SIGTERM (-15) / post-timeout SIGKILL (-9) are clean-shutdown outcomes
    with open(stderr_path, "rb") as f:
        err = f.read().decode(errors="replace")
    for marker in ("WARNING: ThreadSanitizer", "ERROR: AddressSanitizer",
                   "runtime error:"):
        assert marker not in err, f"sanitizer report:\n{err[-4000:]}"
    assert rc in (0, -15, -9), f"sanitized flight server exited {rc}:\n{err[-2000:]}"
    # TSAN exits with TSAN_OPTIONS exitcode=66 on an unsuppressed report
    print("flight server: ok")


if __name__ == "__main__":
    leg = os.environ.get("SAN_LEG", "all")
    if leg in ("router", "all"):
        exercise_router()
    if leg in ("flight", "all"):
        exercise_flight()
    print(f"sanitize exercise ({MODE}/{leg}): PASSED")
