"""Exercise the overload defenses end-to-end on a tiny TPC-H dataset.

    JAX_PLATFORMS=cpu python dev/overload_exercise.py

Three legs, all on a 2-executor StandaloneCluster running TPC-H q6:

1. admission — burst-submit far more jobs than a shrunken admission
   budget allows; the excess must be shed with typed ClusterOverloaded
   rejections carrying retry_after_ms hints, every ADMITTED job must
   complete, and the gate must drain back to zero (no leaked slots, no
   wedged jobs).
2. pressure — one executor's session pool is saturated before the job
   starts; its tasks bounce off the executor admission gate retryably
   and the retries land on the healthy executor.
3. posture — drive the overload state machine through
   shedding → draining → normal with synthetic depth and verify the
   quotas degrade and recover accordingly.

Exits non-zero if any leg fails its bookkeeping check.
"""

import os
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

Q6 = """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01'
  and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07
  and l_quantity < 24
"""

BURST = 12  # submissions thrown at a quota of 3


def _cluster(data_dir: str, cfg):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.executor.standalone import StandaloneCluster
    from ballista_tpu.scheduler.metrics import InMemoryMetricsCollector
    from ballista_tpu.testing.tpchgen import register_tpch

    ctx = SessionContext(cfg)
    register_tpch(ctx, data_dir)
    cluster = StandaloneCluster(num_executors=2, vcores=2, config=cfg)
    cluster.scheduler.metrics = InMemoryMetricsCollector()
    return cluster


def admission_leg(data_dir: str) -> None:
    from ballista_tpu.config import DEFAULT_SHUFFLE_PARTITIONS, BallistaConfig
    from ballista_tpu.errors import ClusterOverloaded
    from ballista_tpu.scheduler.admission import AdmissionController

    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 2})
    cluster = _cluster(data_dir, cfg)
    cluster.scheduler.admission = AdmissionController(
        enabled=True, max_pending=3, per_session_quota=3,
        shed_depth=3, drain_depth=6, min_retry_after_ms=50)
    try:
        scheduler = cluster.scheduler
        session_id = scheduler.sessions.create_or_update(
            cfg.to_key_value_pairs(), "overload-admission")
        admitted, shed = [], []
        for _ in range(BURST):
            try:
                admitted.append(scheduler.submit_sql(Q6, session_id))
            except ClusterOverloaded as e:
                if e.retry_after_ms < 50:
                    raise SystemExit(
                        f"[admission] hint below the floor: {e.retry_after_ms}ms")
                shed.append(e)
        if not shed:
            raise SystemExit(f"[admission] burst of {BURST} over quota 3 shed nothing")
        if len(admitted) < 3:
            raise SystemExit(f"[admission] only {len(admitted)} admitted — gate too eager")
        for job_id in admitted:
            status = scheduler.wait_for_job(job_id, timeout=60)
            if status["state"] != "successful":
                raise SystemExit(f"[admission] admitted job {job_id} "
                                 f"{status['state']}: {status.get('error')}")
        deadline = time.time() + 5
        while scheduler.admission.depth() > 0 and time.time() < deadline:
            time.sleep(0.05)
        if scheduler.admission.depth() != 0:
            raise SystemExit(f"[admission] {scheduler.admission.depth()} admission "
                             "slots leaked after all jobs finished")
        # drained: the gate admits again without any manual reset
        late = scheduler.submit_sql(Q6, session_id)
        if scheduler.wait_for_job(late, timeout=60)["state"] != "successful":
            raise SystemExit("[admission] post-drain submission failed")
        m = cluster.scheduler.metrics
        print(f"[admission] ok: admitted={len(admitted)} shed={len(shed)} "
              f"reasons={m.jobs_rejected} "
              f"hints={sorted({e.retry_after_ms for e in shed})}ms")
    finally:
        cluster.shutdown()


def pressure_leg(data_dir: str) -> None:
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import (
        DEFAULT_SHUFFLE_PARTITIONS,
        MAX_PARTITIONS_PER_TASK,
        BallistaConfig,
    )
    from ballista_tpu.executor.executor import Executor, ExecutorMetadata
    from ballista_tpu.executor.memory_pool import SessionPoolRegistry
    from ballista_tpu.executor.standalone import InProcessTaskLauncher
    from ballista_tpu.ids import new_executor_id
    from ballista_tpu.scheduler.metrics import InMemoryMetricsCollector
    from ballista_tpu.scheduler.server import SchedulerServer
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 2, MAX_PARTITIONS_PER_TASK: 1})
    ctx = SessionContext(cfg)
    register_tpch(ctx, data_dir)
    wd = tempfile.mkdtemp(prefix="overload-pressure-")
    # extra vcores bias the first offers onto the saturated executor
    choked = Executor(wd, ExecutorMetadata(id=str(new_executor_id()), vcores=4), config=cfg)
    healthy = Executor(wd, ExecutorMetadata(id=str(new_executor_id()), vcores=2), config=cfg)
    launcher = InProcessTaskLauncher({choked.metadata.id: choked,
                                      healthy.metadata.id: healthy})
    scheduler = SchedulerServer(launcher, InMemoryMetricsCollector(),
                                quarantine_threshold=0.5, quarantine_min_events=1.0,
                                sweep_interval_s=0.2)
    scheduler.start()
    scheduler.register_executor(choked.metadata)
    scheduler.register_executor(healthy.metadata)
    try:
        session_id = scheduler.sessions.create_or_update(
            cfg.to_key_value_pairs(), "overload-pressure")
        choked.session_pools = SessionPoolRegistry(capacity_per_session=64)
        choked.session_pools.get(session_id).grow_wait(64, timeout_s=0.0)
        job_id = scheduler.submit_sql(Q6, session_id)
        status = scheduler.wait_for_job(job_id, timeout=60)
        if status["state"] != "successful":
            raise SystemExit(f"[pressure] job failed: {status.get('error')}")
        if choked.pressure_rejections < 1:
            raise SystemExit("[pressure] choked executor never exercised — vacuous")
        if choked.tasks_run != 0:
            raise SystemExit(f"[pressure] saturated pool still ran "
                             f"{choked.tasks_run} tasks")
        if healthy.tasks_run < 1:
            raise SystemExit("[pressure] healthy executor ran nothing")
        print(f"[pressure] ok: rejections={choked.pressure_rejections} "
              f"retried_onto_healthy={healthy.tasks_run} "
              f"pool_pressure={choked.session_pools.aggregate_pressure():.2f}")
    finally:
        scheduler.stop()
        launcher.pool.shutdown(wait=False)


def posture_leg() -> None:
    from ballista_tpu.errors import ClusterOverloaded
    from ballista_tpu.scheduler.admission import (
        DRAINING,
        NORMAL,
        SHEDDING,
        AdmissionController,
    )

    ctl = AdmissionController(enabled=True, max_pending=100, per_session_quota=4,
                              shed_depth=4, drain_depth=8)
    for i in range(4):
        ctl.admit(f"s{i}", f"j{i}")
    if ctl.update(0.0, 0.0) != SHEDDING:
        raise SystemExit(f"[posture] depth 4 should shed, state={ctl.state}")
    try:
        ctl.admit("s0", "halved")   # s0 now at 2 = the halved quota of 4
        ctl.admit("s0", "halved2")  # must be shed
        raise SystemExit("[posture] shedding did not halve the session quota")
    except ClusterOverloaded as e:
        if e.reason != "shedding":
            raise SystemExit(f"[posture] wrong reason {e.reason}")
    for i in range(4, 8):
        ctl.admit(f"s{i}", f"j{i}")
    if ctl.update(0.0, 0.0) != DRAINING:
        raise SystemExit(f"[posture] depth 9 should drain, state={ctl.state}")
    for j in list(ctl._inflight):
        ctl.finish(j)
    ctl.update(0.0, 0.0)
    if ctl.state != NORMAL:
        raise SystemExit(f"[posture] empty gate should be normal, state={ctl.state}")
    print(f"[posture] ok: shed->drain->normal, rejected={ctl.snapshot()['rejected_total']}")


def main() -> None:
    from ballista_tpu.testing.tpchgen import generate_tpch

    posture_leg()
    with tempfile.TemporaryDirectory(prefix="overload-tpch-") as d:
        print(f"generating TPC-H sf0.01 under {d} ...")
        generate_tpch(d, scale=0.01, seed=42, files_per_table=2)
        admission_leg(d)
        pressure_leg(d)
    print("overload exercise passed")


if __name__ == "__main__":
    main()
