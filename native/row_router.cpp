// Shuffle row router: the host-side hot loop of the shuffle writer.
//
// Native rebuild of the role ballista's Rust repartitioner plays inside
// ShuffleWriterExec (reference: core/src/execution_plans/shuffle_writer.rs
// hash-repartitioning of record batches): computes the engine-wide row hash
// (splitmix64 per column + boost-style combine + FNV-1a for strings — the
// SAME bit contract as ballista_tpu/ops/hashing.py and the jax twin in
// ops/tpu/kernels.py) and builds partition-grouped selection vectors in ONE
// pass, so the Python writer does a single Arrow take() and slices.
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).
// Build: native/build.sh (g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstring>

extern "C" {

static inline uint64_t splitmix64(uint64_t x) {
    x += 0x9E3779B97F4A7C15ULL;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
    return x ^ (x >> 31);
}

static inline uint64_t hash_combine(uint64_t h, uint64_t v) {
    return h ^ (v + 0x9E3779B97F4A7C15ULL + (h << 6) + (h >> 2));
}

static const uint64_t NULL_TAG = 0x9E3779B97F4A7C15ULL;

// mix an int64-encoded column into the running row hashes.
// valid: optional validity bytes (1 = valid), may be null.
void hash_mix_i64(uint64_t* h, const int64_t* v, const uint8_t* valid, int64_t n) {
    if (valid == nullptr) {
        for (int64_t i = 0; i < n; i++)
            h[i] = hash_combine(h[i], splitmix64((uint64_t)v[i]));
    } else {
        for (int64_t i = 0; i < n; i++) {
            uint64_t hv = valid[i] ? splitmix64((uint64_t)v[i]) : NULL_TAG;
            h[i] = hash_combine(h[i], hv);
        }
    }
}

// mix a float64 column (normalizing -0.0 → 0.0 like the host hasher)
void hash_mix_f64(uint64_t* h, const double* v, const uint8_t* valid, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        uint64_t hv;
        if (valid != nullptr && !valid[i]) {
            hv = NULL_TAG;
        } else {
            double d = v[i] == 0.0 ? 0.0 : v[i];
            uint64_t bits;
            std::memcpy(&bits, &d, 8);
            hv = splitmix64(bits);
        }
        h[i] = hash_combine(h[i], hv);
    }
}

// float64 column hashed under the int64 contract is not a case the engine
// produces; kept out deliberately.

// mix a utf8/binary column: FNV-1a over each row's bytes
void hash_mix_bytes(uint64_t* h, const uint8_t* data, const int64_t* offsets,
                    const uint8_t* valid, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        uint64_t hv;
        if (valid != nullptr && !valid[i]) {
            hv = NULL_TAG;
        } else {
            uint64_t f = 0xCBF29CE484222325ULL;
            for (int64_t j = offsets[i]; j < offsets[i + 1]; j++)
                f = (f ^ data[j]) * 0x100000001B3ULL;
            // the host hasher treats the FNV value as the column's int64
            // encoding and splitmix-finalizes it — match exactly
            hv = splitmix64(f);
        }
        h[i] = hash_combine(h[i], hv);
    }
}

// route rows: pids[i] = h[i] % k; order = row indices grouped by partition
// (stable within a partition); bounds[p]..bounds[p+1] delimit partition p
// inside order. Returns 0.
int route(const uint64_t* h, int64_t n, uint32_t k, uint32_t* pids,
          int64_t* bounds /* k+1 */, uint32_t* order /* n */) {
    for (uint32_t p = 0; p <= k; p++) bounds[p] = 0;
    for (int64_t i = 0; i < n; i++) {
        uint32_t p = (uint32_t)(h[i] % k);
        pids[i] = p;
        bounds[p + 1]++;
    }
    for (uint32_t p = 0; p < k; p++) bounds[p + 1] += bounds[p];
    // stable counting-sort placement
    int64_t* cursor = new int64_t[k];
    for (uint32_t p = 0; p < k; p++) cursor[p] = bounds[p];
    for (int64_t i = 0; i < n; i++) order[cursor[pids[i]]++] = (uint32_t)i;
    delete[] cursor;
    return 0;
}

}  // extern "C"
