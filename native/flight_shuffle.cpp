// Native Arrow Flight shuffle server: the C++ executor data plane.
//
// Native rebuild of the reference's executor Flight service
// (ballista/executor/src/flight_service.rs:61,88,243,257) serving the SAME
// wire contract as ballista_tpu/flight/server.py, so Python and C++ servers
// are interchangeable behind the executor:
//   - DoGet(ticket JSON {path, layout, output_partition}): stream the
//     partition as decoded record batches (hash layout: whole file; sort
//     layout: byte range through the JSON index file).
//   - DoAction("io_block_transport"): raw 8 MiB block streaming of the
//     stored IPC bytes, no decode/re-encode (flight_service.rs:243). A
//     ticket with "want_crc": true gets a JSON header result {"nbytes",
//     "crc"?} prepended so the client can verify end to end.
//   - DoAction("io_coalesced_transport"): several map outputs of one
//     (executor, reduce partition) pair stream back-to-back in ONE call;
//     each location is framed by a JSON header result {"i": idx,
//     "nbytes": n, "crc"?: "…"} followed by its blocks. Locations open LAZILY inside
//     the stream so a lost file on location i fails after i-1 completed
//     and the client attributes the FetchFailed to the right map output.
//   - DoAction("remove_job_data"): GC a job's shuffle directory.
//
// Blocks are zero-copy slices of a memory map of the shuffle file
// (BALLISTA_SHUFFLE_MMAP=0 falls back to plain reads).
//
// Links against the Arrow C++ shipped inside the pyarrow wheel (C++20).
// Build: native/build.sh → native/ballista-flight-server.
// Protocol: stdout prints "PORT <n>" once bound (the executor process
// parses it), then serves until SIGTERM.

#include <arrow/api.h>
#include <arrow/buffer.h>
#include <arrow/flight/api.h>
#include <arrow/io/file.h>
#include <arrow/io/memory.h>
#include <arrow/ipc/reader.h>

#include <csignal>
#include <cstdio>
#include <cstring>
#include <filesystem>
#include <fstream>
#include <iostream>
#include <memory>
#include <string>

namespace fl = arrow::flight;
namespace fs = std::filesystem;

static constexpr int64_t kBlockSize = 8 * 1024 * 1024;

// ---- minimal JSON field extraction (tickets come from our own clients) ----

static void AppendUtf8(std::string& out, unsigned cp) {
  if (cp < 0x80) out.push_back((char)cp);
  else if (cp < 0x800) {
    out.push_back((char)(0xC0 | (cp >> 6)));
    out.push_back((char)(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out.push_back((char)(0xE0 | (cp >> 12)));
    out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back((char)(0x80 | (cp & 0x3F)));
  } else {
    out.push_back((char)(0xF0 | (cp >> 18)));
    out.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
    out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back((char)(0x80 | (cp & 0x3F)));
  }
}

static std::string JsonStr(const std::string& j, const std::string& key) {
  auto k = "\"" + key + "\"";
  auto p = j.find(k);
  if (p == std::string::npos) return "";
  p = j.find(':', p + k.size());
  if (p == std::string::npos) return "";
  p = j.find('"', p);
  if (p == std::string::npos) return "";
  auto e = p + 1;
  std::string out;
  while (e < j.size() && j[e] != '"') {
    char c = j[e];
    if (c != '\\' || e + 1 >= j.size()) {
      out.push_back(c);
      e++;
      continue;
    }
    char esc = j[e + 1];
    e += 2;
    switch (esc) {
      case 'n': out.push_back('\n'); break;
      case 't': out.push_back('\t'); break;
      case 'r': out.push_back('\r'); break;
      case 'b': out.push_back('\b'); break;
      case 'f': out.push_back('\f'); break;
      case 'u': {
        // \uXXXX (json.dumps default ensure_ascii) incl. surrogate pairs
        if (e + 4 > j.size()) break;
        unsigned cp = (unsigned)std::strtoul(j.substr(e, 4).c_str(), nullptr, 16);
        e += 4;
        if (cp >= 0xD800 && cp <= 0xDBFF && e + 6 <= j.size() &&
            j[e] == '\\' && j[e + 1] == 'u') {
          unsigned lo = (unsigned)std::strtoul(j.substr(e + 2, 4).c_str(), nullptr, 16);
          if (lo >= 0xDC00 && lo <= 0xDFFF) {
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            e += 6;
          }
        }
        AppendUtf8(out, cp);
        break;
      }
      default: out.push_back(esc); break;  // \" \\ \/ and friends
    }
  }
  return out;
}

static long long JsonInt(const std::string& j, const std::string& key, long long dflt) {
  auto k = "\"" + key + "\"";
  auto p = j.find(k);
  if (p == std::string::npos) return dflt;
  p = j.find(':', p + k.size());
  if (p == std::string::npos) return dflt;
  p++;
  while (p < j.size() && (j[p] == ' ' || j[p] == '\t')) p++;
  return std::strtoll(j.c_str() + p, nullptr, 10);
}

static bool JsonBool(const std::string& j, const std::string& key, bool dflt) {
  auto k = "\"" + key + "\"";
  auto p = j.find(k);
  if (p == std::string::npos) return dflt;
  p = j.find(':', p + k.size());
  if (p == std::string::npos) return dflt;
  p++;
  while (p < j.size() && (j[p] == ' ' || j[p] == '\t')) p++;
  return j.compare(p, 4, "true") == 0;
}

// index file: {"<partition>": [offset, length, ...], ...}
static bool IndexRange(const std::string& index_json, long long part,
                       long long* offset, long long* length) {
  auto key = "\"" + std::to_string(part) + "\"";
  auto p = index_json.find(key);
  if (p == std::string::npos) return false;
  p = index_json.find('[', p);
  if (p == std::string::npos) return false;
  char* end = nullptr;
  *offset = std::strtoll(index_json.c_str() + p + 1, &end, 10);
  while (*end == ',' || *end == ' ') end++;
  *length = std::strtoll(end, nullptr, 10);
  return true;
}

// optional 5th index-entry element: the range's checksum string ("c32:…" /
// "z32:…"); "" when the entry predates checksums or the knob was off
static std::string IndexCrc(const std::string& index_json, long long part) {
  auto key = "\"" + std::to_string(part) + "\"";
  auto p = index_json.find(key);
  if (p == std::string::npos) return "";
  p = index_json.find('[', p);
  if (p == std::string::npos) return "";
  auto e = index_json.find(']', p);
  if (e == std::string::npos) return "";
  auto q = index_json.find('"', p);
  if (q == std::string::npos || q > e) return "";
  auto q2 = index_json.find('"', q + 1);
  if (q2 == std::string::npos || q2 > e) return "";
  return index_json.substr(q + 1, q2 - q - 1);
}

// twin of ballista_tpu/shuffle/paths.py::index_path — "x.arrow" → "x.idx"
static std::string IndexPath(const std::string& data_path) {
  const std::string suffix = ".arrow";
  if (data_path.size() > suffix.size() &&
      data_path.compare(data_path.size() - suffix.size(), suffix.size(), suffix) == 0)
    return data_path.substr(0, data_path.size() - suffix.size()) + ".idx";
  return data_path + ".idx";
}

// The ticket path comes off the wire; it must not read outside the
// executor's own shuffle directory (twin of shuffle/paths.py
// contained_path — the reference builds paths server-side from structured
// ticket fields for the same reason, executor/src/flight_service.rs).
static arrow::Status CheckContained(const std::string& work_dir, const std::string& path) {
  if (work_dir.empty()) return arrow::Status::Invalid("server has no work dir; refusing reads");
  std::error_code ec;
  fs::path root = fs::weakly_canonical(fs::path(work_dir), ec);
  if (ec) return arrow::Status::IOError("bad work dir: ", work_dir);
  fs::path resolved = fs::weakly_canonical(fs::path(path), ec);
  if (ec) return arrow::Status::IOError("bad path: ", path);
  auto root_s = root.string();
  auto res_s = resolved.string();
  if (res_s != root_s &&
      (res_s.size() <= root_s.size() + 1 || res_s.compare(0, root_s.size(), root_s) != 0 ||
       res_s[root_s.size()] != fs::path::preferred_separator))
    return arrow::Status::Invalid("path escapes work dir: ", path);
  return arrow::Status::OK();
}

// twin of the python server's env gate: BALLISTA_SHUFFLE_CHECKSUM=0 stops
// SHIPPING checksums (clients then skip verification); default on
static bool ChecksumEnabled() {
  static const bool on = [] {
    const char* v = std::getenv("BALLISTA_SHUFFLE_CHECKSUM");
    if (!v) return true;
    std::string s(v);
    for (auto& c : s) c = (char)std::tolower((unsigned char)c);
    return !(s == "0" || s == "false" || s == "no" || s == "off");
  }();
  return on;
}

// twin of shuffle/paths.py::checksum_for — the stored checksum of the byte
// range a ticket addresses ("" = unchecked: knob off, pre-checksum writer,
// or unreadable sidecar/index; absence must never fail a fetch)
static std::string ChecksumFor(const std::string& ticket_json, const std::string& work_dir) {
  if (!ChecksumEnabled()) return "";
  std::string path = JsonStr(ticket_json, "path");
  if (!CheckContained(work_dir, path).ok()) return "";
  std::string layout = JsonStr(ticket_json, "layout");
  if (layout.rfind("sort", 0) == 0) {
    std::ifstream idx(IndexPath(path));
    if (!idx) return "";
    std::string index_json((std::istreambuf_iterator<char>(idx)),
                           std::istreambuf_iterator<char>());
    return IndexCrc(index_json, JsonInt(ticket_json, "output_partition", 0));
  }
  std::ifstream crc(path + ".crc");
  if (!crc) return "";
  std::string v((std::istreambuf_iterator<char>(crc)), std::istreambuf_iterator<char>());
  while (!v.empty() && (v.back() == '\n' || v.back() == '\r' || v.back() == ' '))
    v.pop_back();
  return v;
}

static bool ValidJobId(const std::string& job) {
  if (job.empty() || job == "." || job == "..") return false;
  return job.find('/') == std::string::npos && job.find('\\') == std::string::npos &&
         job.find('\0') == std::string::npos;
}

// One byte range of a shuffle file as a buffer — a zero-copy slice of a
// memory map by default (the OS page cache backs the stream; nothing is
// materialized in anonymous memory), plain pread when mmap is disabled
// or fails (exotic filesystems).
static arrow::Result<std::shared_ptr<arrow::Buffer>> OpenSlice(const std::string& path,
                                                               int64_t offset, int64_t length) {
  static const bool use_mmap = [] {
    const char* v = std::getenv("BALLISTA_SHUFFLE_MMAP");
    if (!v) return true;
    std::string s(v);
    for (auto& c : s) c = (char)std::tolower((unsigned char)c);
    return !(s == "0" || s == "false" || s == "no" || s == "off");
  }();
  if (length == 0) return arrow::Buffer::FromString("");
  if (use_mmap) {
    auto mm = arrow::io::MemoryMappedFile::Open(path, arrow::io::FileMode::READ);
    if (mm.ok()) return (*mm)->ReadAt(offset, length);
    if (!fs::exists(path)) return mm.status();  // lost output must ERROR
  }
  ARROW_ASSIGN_OR_RAISE(auto f, arrow::io::ReadableFile::Open(path));
  return f->ReadAt(offset, length);
}

static arrow::Result<std::shared_ptr<arrow::Buffer>> ReadRange(const std::string& ticket_json,
                                                               const std::string& work_dir) {
  std::string path = JsonStr(ticket_json, "path");
  ARROW_RETURN_NOT_OK(CheckContained(work_dir, path));
  std::string layout = JsonStr(ticket_json, "layout");
  if (layout.rfind("sort", 0) == 0) {
    std::ifstream idx(IndexPath(path));
    if (!idx)
      // missing index is an ERROR (lost output → FetchFailed/ResultLost
      // recovery on the reducer), matching the python server's behavior
      return arrow::Status::IOError("shuffle index not found: ", IndexPath(path));
    std::string index_json((std::istreambuf_iterator<char>(idx)),
                           std::istreambuf_iterator<char>());
    long long offset = 0, length = 0;
    if (!IndexRange(index_json, JsonInt(ticket_json, "output_partition", 0), &offset, &length))
      return arrow::Buffer::FromString("");  // partition absent = empty (contract)
    // truncation guard: an index pointing past EOF means the data file was
    // torn/truncated after commit — a read must not silently come up short
    std::error_code ec;
    auto size = fs::file_size(path, ec);
    if (ec) return arrow::Status::IOError("cannot stat shuffle file: ", path);
    if (offset + length > (long long)size)
      return arrow::Status::IOError(
          "shuffle file truncated: ", path, " has ", std::to_string((long long)size),
          " bytes, index range needs [", std::to_string(offset), ", ",
          std::to_string(offset + length), ")");
    return OpenSlice(path, offset, length);
  }
  std::error_code ec;
  auto size = fs::file_size(path, ec);
  if (ec) return arrow::Status::IOError("cannot stat shuffle file: ", path);
  return OpenSlice(path, 0, (int64_t)size);
}

// "locations": [ {…}, {…} ] → each element's raw JSON. String-aware
// brace-depth scan — braces inside quoted strings (paths) don't count.
static bool SplitLocations(const std::string& j, std::vector<std::string>* out) {
  auto p = j.find("\"locations\"");
  if (p == std::string::npos) return false;
  p = j.find('[', p);
  if (p == std::string::npos) return false;
  int depth = 0;
  size_t start = 0;
  bool in_str = false;
  for (size_t i = p + 1; i < j.size(); i++) {
    char c = j[i];
    if (in_str) {
      if (c == '\\') i++;
      else if (c == '"') in_str = false;
      continue;
    }
    if (c == '"') in_str = true;
    else if (c == '{') { if (depth == 0) start = i; depth++; }
    else if (c == '}') { if (--depth == 0) out->push_back(j.substr(start, i - start + 1)); }
    else if (c == ']' && depth == 0) return true;
  }
  return false;
}

// Streams every location of a coalesced ticket: header result, then the
// location's blocks, then the next location. Each location's buffer is
// opened on first touch INSIDE the stream, so the failure point in the
// result sequence identifies the lost map output.
class CoalescedResultStream : public fl::ResultStream {
 public:
  CoalescedResultStream(std::vector<std::string> locs, std::string work_dir)
      : locs_(std::move(locs)), work_dir_(std::move(work_dir)) {}

  arrow::Result<std::unique_ptr<fl::Result>> Next() override {
    if (cur_ && off_ < cur_->size()) {
      auto len = std::min(kBlockSize, cur_->size() - off_);
      auto slice = arrow::SliceBuffer(cur_, off_, len);
      off_ += len;
      return std::make_unique<fl::Result>(fl::Result{std::move(slice)});
    }
    if (idx_ >= locs_.size()) return nullptr;
    ARROW_ASSIGN_OR_RAISE(cur_, ReadRange(locs_[idx_], work_dir_));
    off_ = 0;
    std::string hdr = "{\"i\": " + std::to_string(idx_) +
                      ", \"nbytes\": " + std::to_string((long long)cur_->size());
    std::string crc = ChecksumFor(locs_[idx_], work_dir_);
    if (!crc.empty()) hdr += ", \"crc\": \"" + crc + "\"";
    hdr += "}";
    idx_++;
    return std::make_unique<fl::Result>(fl::Result{arrow::Buffer::FromString(hdr)});
  }

 private:
  std::vector<std::string> locs_;
  std::string work_dir_;
  std::shared_ptr<arrow::Buffer> cur_;
  int64_t off_ = 0;
  size_t idx_ = 0;
};

class ShuffleServer : public fl::FlightServerBase {
 public:
  explicit ShuffleServer(std::string work_dir) : work_dir_(std::move(work_dir)) {}

  arrow::Status DoGet(const fl::ServerCallContext&, const fl::Ticket& request,
                      std::unique_ptr<fl::FlightDataStream>* stream) override {
    ARROW_ASSIGN_OR_RAISE(auto buf, ReadRange(request.ticket, work_dir_));
    if (buf->size() == 0) {
      auto schema = arrow::schema({});
      ARROW_ASSIGN_OR_RAISE(
          auto reader, arrow::RecordBatchReader::Make({}, schema));
      *stream = std::make_unique<fl::RecordBatchStream>(reader);
      return arrow::Status::OK();
    }
    auto source = std::make_shared<arrow::io::BufferReader>(buf);
    ARROW_ASSIGN_OR_RAISE(auto reader, arrow::ipc::RecordBatchStreamReader::Open(source));
    *stream = std::make_unique<fl::RecordBatchStream>(reader);
    return arrow::Status::OK();
  }

  arrow::Status DoAction(const fl::ServerCallContext&, const fl::Action& action,
                         std::unique_ptr<fl::ResultStream>* result) override {
    std::string body = action.body ? action.body->ToString() : "";
    if (action.type == "io_block_transport") {
      ARROW_ASSIGN_OR_RAISE(auto buf, ReadRange(body, work_dir_));
      std::vector<fl::Result> results;
      if (JsonBool(body, "want_crc", false)) {
        // checksum-aware clients opt in; the header travels as the first
        // result so old clients (which never set want_crc) see no change
        std::string hdr = "{\"nbytes\": " + std::to_string((long long)buf->size());
        std::string crc = ChecksumFor(body, work_dir_);
        if (!crc.empty()) hdr += ", \"crc\": \"" + crc + "\"";
        hdr += "}";
        results.push_back(fl::Result{arrow::Buffer::FromString(hdr)});
      }
      for (int64_t off = 0; off < buf->size(); off += kBlockSize) {
        auto len = std::min(kBlockSize, buf->size() - off);
        results.push_back(fl::Result{arrow::SliceBuffer(buf, off, len)});
      }
      *result = std::make_unique<fl::SimpleResultStream>(std::move(results));
      return arrow::Status::OK();
    }
    if (action.type == "io_coalesced_transport") {
      std::vector<std::string> locs;
      if (!SplitLocations(body, &locs))
        return arrow::Status::Invalid("malformed coalesced ticket");
      *result = std::make_unique<CoalescedResultStream>(std::move(locs), work_dir_);
      return arrow::Status::OK();
    }
    if (action.type == "remove_job_data") {
      std::string job = JsonStr(body, "job_id");
      if (!ValidJobId(job)) return arrow::Status::Invalid("invalid job id: ", job);
      if (!work_dir_.empty()) {
        std::error_code ec;
        fs::remove_all(fs::path(work_dir_) / job, ec);  // best-effort GC
      }
      std::vector<fl::Result> results;
      results.push_back(fl::Result{arrow::Buffer::FromString("ok")});
      *result = std::make_unique<fl::SimpleResultStream>(std::move(results));
      return arrow::Status::OK();
    }
    return arrow::Status::Invalid("unknown action ", action.type);
  }

  arrow::Status ListActions(const fl::ServerCallContext&,
                            std::vector<fl::ActionType>* actions) override {
    *actions = {{"io_block_transport", "raw IPC block stream"},
                {"io_coalesced_transport", "framed multi-location raw IPC block stream"},
                {"remove_job_data", "GC a job's shuffle files"}};
    return arrow::Status::OK();
  }

 private:
  std::string work_dir_;
};

int main(int argc, char** argv) {
  std::string host = "0.0.0.0", work_dir;
  int port = 0;
  for (int i = 1; i < argc - 1; i++) {
    if (!std::strcmp(argv[i], "--port")) port = std::atoi(argv[++i]);
    else if (!std::strcmp(argv[i], "--host")) host = argv[++i];
    else if (!std::strcmp(argv[i], "--work-dir")) work_dir = argv[++i];
  }
  auto loc_res = fl::Location::ForGrpcTcp(host, port);
  if (!loc_res.ok()) { std::cerr << loc_res.status().ToString() << "\n"; return 1; }
  ShuffleServer server(work_dir);
  fl::FlightServerOptions options(*loc_res);
  auto st = server.Init(options);
  if (!st.ok()) { std::cerr << st.ToString() << "\n"; return 1; }
  // the executor process parses this line for the bound port
  std::printf("PORT %d\n", server.port());
  std::fflush(stdout);
  st = server.SetShutdownOnSignals({SIGTERM, SIGINT});
  if (!st.ok()) { std::cerr << st.ToString() << "\n"; return 1; }
  st = server.Serve();
  if (!st.ok()) { std::cerr << st.ToString() << "\n"; return 1; }
  return 0;
}
