#!/bin/sh
# Build the native runtime components:
#   libballista_native.so   — shuffle row router (ctypes, no deps)
#   ballista-flight-server  — C++ Flight shuffle data plane (links the
#                             Arrow C++ shipped inside the pyarrow wheel)
cd "$(dirname "$0")"
g++ -O3 -march=native -shared -fPIC -o libballista_native.so row_router.cpp
echo "built $(pwd)/libballista_native.so"

PYA="$(python -c 'import os, pyarrow; print(os.path.dirname(pyarrow.__file__))')"
AR_SO="$(ls "$PYA"/libarrow.so.* 2>/dev/null | head -1)"
FL_SO="$(ls "$PYA"/libarrow_flight.so.* 2>/dev/null | head -1)"
if [ -d "$PYA/include/arrow/flight" ] && [ -n "$AR_SO" ] && [ -n "$FL_SO" ]; then
  if g++ -std=c++20 -O2 -I"$PYA/include" flight_shuffle.cpp \
      -o ballista-flight-server \
      -L"$PYA" -l:"$(basename "$AR_SO")" -l:"$(basename "$FL_SO")" \
      -Wl,-rpath,"$PYA"; then
    echo "built $(pwd)/ballista-flight-server"
  else
    echo "flight server build failed (python data plane remains)" >&2
  fi
else
  echo "pyarrow flight headers/libs not found; skipping native flight server" >&2
fi
