#!/bin/sh
# Build the native runtime components → native/libballista_native.so
cd "$(dirname "$0")"
g++ -O3 -march=native -shared -fPIC -o libballista_native.so row_router.cpp
echo "built $(pwd)/libballista_native.so"
