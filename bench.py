"""Benchmark entry point (driver-run on real TPU hardware).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Workload: TPC-H q1 at SF10 (override with TPCH_SCALE) — the
scan→filter→project→group-aggregate pipeline that dominates analytic
engines, at a scale where device residency matters (~60M lineitem rows).
value = lineitem rows aggregated per second per chip on the TPU engine
(hot path: device-resident columns, compiled stage). vs_baseline = speedup
over this framework's CPU engine (pyarrow C++ operators) on the same host —
the "CPU-executor baseline" the north-star gate compares against
(BASELINE.json: ≥3x target at SF100/v5e-8).

Failure policy: a dead accelerator tunnel must NOT look like parity. The
device leg runs in a subprocess under a hard timeout; if it cannot run, the
JSON carries value=0, vs_baseline=0.0 and a "device_error" field with the
probe diagnostics, so the driver artifact records a loud, diagnosable
failure instead of "TPU == CPU".
"""

import json
import os
import subprocess
import sys
import tempfile
import time

_pt = os.environ.get("BENCH_PROBE_TIMEOUTS", "240,360")
PROBE_TIMEOUTS = tuple(int(x) for x in _pt.split(","))  # try, then retry
DEVICE_LEG_TIMEOUT = int(os.environ.get("BENCH_DEVICE_TIMEOUT", "1800"))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def best_time(engine: str, data_dir: str, sql: str, warmups: int, iters: int) -> tuple[float, int]:
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import BallistaConfig, EXECUTOR_ENGINE
    from ballista_tpu.testing.tpchgen import register_tpch

    ctx = SessionContext(BallistaConfig({EXECUTOR_ENGINE: engine}))
    register_tpch(ctx, data_dir)
    rows = ctx.catalog.get("lineitem").statistics().num_rows or 0
    for _ in range(warmups):
        ctx.sql(sql).collect()
    best = float("inf")
    for _ in range(iters):
        t0 = time.time()
        out = ctx.sql(sql).collect()
        best = min(best, time.time() - t0)
        assert out.num_rows > 0
    return best, rows


def probe_device() -> tuple[bool, str]:
    """Initialize the accelerator and run one tiny compiled op, in a
    subprocess under a hard timeout. Returns (ok, diagnostics)."""
    probe_src = (
        "import os, jax\n"
        "p = os.environ.get('JAX_PLATFORMS')\n"
        "if p: jax.config.update('jax_platforms', p)\n"
        "d = jax.devices()[0]\n"
        "import jax.numpy as jnp\n"
        "x = jnp.ones((256, 256), dtype=jnp.bfloat16)\n"
        "(x @ x).block_until_ready()\n"
        "print(d.platform, d.device_kind)\n"
    )
    notes = []
    for i, t in enumerate(PROBE_TIMEOUTS):
        try:
            probe = subprocess.run(
                [sys.executable, "-c", probe_src],
                capture_output=True, timeout=t, text=True,
            )
        except subprocess.TimeoutExpired:
            notes.append(f"attempt {i + 1}: device init TIMED OUT after {t}s "
                         f"(JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS')!r}; dead tunnel?)")
            log(notes[-1])
            continue
        if probe.returncode == 0:
            log(f"device probe ok: {probe.stdout.strip()}")
            return True, probe.stdout.strip()
        notes.append(f"attempt {i + 1}: probe exited {probe.returncode}: "
                     f"{(probe.stderr or probe.stdout).strip()[-500:]}")
        log(notes[-1])
    return False, " | ".join(notes)


def run_device_leg(data_dir: str, sql_path: str) -> tuple[float, str | None]:
    """TPU q1 in a subprocess with a hard timeout (a wedged device run must
    not hang the bench). Returns (best_seconds, error)."""
    with tempfile.NamedTemporaryFile("r", suffix=".json", delete=False) as f:
        out_path = f.name
    cmd = [sys.executable, os.path.abspath(__file__), "--device-leg", data_dir, sql_path, out_path]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=DEVICE_LEG_TIMEOUT, text=True)
    except subprocess.TimeoutExpired:
        return 0.0, f"device leg TIMED OUT after {DEVICE_LEG_TIMEOUT}s"
    if r.stderr:
        log(r.stderr[-1500:])
    if r.returncode != 0:
        return 0.0, f"device leg exited {r.returncode}: {(r.stderr or r.stdout).strip()[-500:]}"
    with open(out_path) as f:
        leg = json.load(f)
    return leg["best_s"], None


def device_leg_main(data_dir: str, sql_path: str, out_path: str) -> None:
    sql = open(sql_path).read()
    best, _rows = best_time("tpu", data_dir, sql, warmups=1, iters=3)
    with open(out_path, "w") as f:
        json.dump({"best_s": best}, f)


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--device-leg":
        device_leg_main(sys.argv[2], sys.argv[3], sys.argv[4])
        return

    scale = float(os.environ.get("TPCH_SCALE", "10"))
    sf_tag = f"sf{scale:g}".replace(".", "p")
    data_dir = os.environ.get("TPCH_DATA", f"/tmp/ballista_tpch_{sf_tag}")
    if not os.path.isdir(os.path.join(data_dir, "lineitem")):
        log(f"generating TPC-H sf={scale} at {data_dir} ...")
        from ballista_tpu.testing.tpchgen import generate_tpch

        t0 = time.time()
        generate_tpch(data_dir, scale=scale, files_per_table=8)
        log(f"datagen {time.time() - t0:.1f}s")

    sql_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "benchmarks", "tpch", "queries", "q1.sql")
    sql = open(sql_path).read()

    log("running cpu engine baseline ...")
    cpu_t, rows = best_time("cpu", data_dir, sql, warmups=1, iters=3)
    log(f"cpu q1 sf{scale:g}: {cpu_t:.3f}s ({rows / cpu_t:,.0f} rows/s)")

    device_ok, diag = probe_device()
    device_error = None
    tpu_t = 0.0
    if device_ok:
        log("running tpu engine ...")
        tpu_t, device_error = run_device_leg(data_dir, sql_path)
        if device_error is None:
            log(f"tpu q1 sf{scale:g}: {tpu_t:.3f}s ({cpu_t / tpu_t:.1f}x)")
    else:
        device_error = diag

    result = {
        "metric": f"tpch_q1_{sf_tag}_rows_per_sec_per_chip",
        "unit": "rows/s",
        "cpu_rows_per_sec": round(rows / cpu_t),
    }
    if device_error is None and tpu_t > 0:
        result["value"] = round(rows / tpu_t)
        result["vs_baseline"] = round((rows / tpu_t) / (rows / cpu_t), 2)
    else:
        # LOUD failure: never report the CPU number as the TPU number
        result["value"] = 0
        result["vs_baseline"] = 0.0
        result["device_error"] = device_error
    print(json.dumps(result))


if __name__ == "__main__":
    main()
