"""Benchmark entry point (driver-run on real TPU hardware).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Workload: TPC-H q1 at SF10 (override with TPCH_SCALE) — the
scan→filter→project→group-aggregate pipeline that dominates analytic
engines, at a scale where device residency matters (~60M lineitem rows).
value = lineitem rows aggregated per second per chip on the TPU engine
(hot path: device-resident columns, compiled stage). vs_baseline = speedup
over this framework's CPU engine (pyarrow C++ operators) on the same host —
the "CPU-executor baseline" the north-star gate compares against
(BASELINE.json: ≥3x target at SF100/v5e-8).

Tunnel-hostile design, round 5. Autopsy of the rounds-2-4 failure (three
driver runs, zero device data): the hang is inside `jax.devices()` — the
axon PJRT plugin's claim loop polls the loopback relay for a device grant
in 1 s nanosleep cycles (live /proc evidence: main thread in
clock_nanosleep, plugin's tokio worker in epoll_wait, no established TCP —
each poll is a short-lived request that completes; the pool simply never
grants). Design consequences:

  * A pending claim is NEVER killed-and-respawned: if the pool queues
    claims, a respawn forfeits queue position. Attempt 1 persists for the
    whole budget. (Rounds 2-4 killed the claim every 420 s — likely
    re-queueing at the back three times.)
  * Hedged claim: if no grant after HEDGE_AFTER, a SECOND leg spawns in
    parallel (covers a wedged first connection); the first leg to report
    devices_ok wins and every other leg is killed AT GRANT TIME, so the
    winner's timed iterations never share the host with a second leg.
  * Verbose relay/PJRT logging from attempt 1 (ADVICE r4) — the stderr
    tail is autopsy material, not a retry luxury.
  * Syscall-level autopsy: on failure the artifact carries, per leg, a
    /proc snapshot (thread comms, wchan, syscall numbers) taken while the
    claim is hung, a relay TCP probe result, and the stderr tail — enough
    to prove where it blocks without strace.
  * The leg is watched from spawn (ADVICE r4): a leg that DIES during
    datagen/CPU-baseline is respawned immediately (crash ≠ hang; crashes
    don't hold queue position).
  * Reduced-scale fallback: the parent generates BOTH SF<scale> and SF1
    data and times the CPU baseline on both. The ready-file hands the leg
    a `fallback_at` wall-clock: if the grant lands too late for the
    full-scale timed phase, the leg runs SF1 instead, so *some* hot-path
    device datum lands. A device OOM at full scale also retries at SF1.
  * Roofline evidence: each device iteration event nests the engine's
    RUN_STATS under "stats" (fill_s and its encode_s/upload_s split,
    device_bytes, trace_s/xla_compile_s/compile_s, compile_overlap_s,
    exec_s, persist_cache_hits/misses) so achieved HBM GB/s — and how much
    of the cold path was hidden by the fill/compile overlap — is computable
    from the artifact alone.

Round 6: the platform claim moved into the warm device-runtime daemon
(ballista_tpu/device_daemon/). Each leg spawns ONE daemon and merely
watches its supervised init state machine (platform probe →
jax.devices() → first compile, each phase wall-clock bounded, progress
re-emitted under the historical event names); the timed iterations then
run ATTACHED — the engine ships stages to the daemon over its unix
socket, so a daemon that survives init serves every warmup/iter without
re-paying the claim, and the leg process itself never touches the pool
(its own jax is pinned to CPU).

Failure policy: a dead accelerator pool must NOT look like parity. If the
device leg cannot produce a time, the JSON carries value=0,
vs_baseline=0.0, "device_error", the FULL init-event trail (iteration
events truncated, init events never — ADVICE r4), per-leg /proc autopsies
and stderr tails. "device_leg" states the leg's fate explicitly: "ok",
"error", or "init_failed" — the last when no daemon's claim landed within
INIT_PROBE_TIMEOUT (or every daemon died in a claim phase), in which case
the round degrades to a recorded CPU-only datum AND the artifact carries
each daemon's structured probe report under "init_probe": which phase,
how long, and a faulthandler stack snapshot of the hang — the claim is
diagnosed per-phase instead of re-timed-out (the retired
"skipped_init_timeout" state said only that time passed).

With BALLISTA_BENCH_DAEMON_CHAOS=1 the device leg additionally runs
`dev/daemon_chaos_exercise.py --quick` as a sanity probe before the
timed iterations: the daemon failure domain (crash recovery, execute
watchdog, poison quarantine — docs/device_daemon.md#failure-domain)
must hold on this machine before the bench trusts the daemon with the
real run. Divergence fails the leg (exit 5, chaos_smoke_failed event).

With BALLISTA_BENCH_LIFECYCLE=1 the bench additionally runs
`dev/lifecycle_exercise.py --quick` (CPU-only, own subprocess): the
executor lifecycle failure domain (graceful drain with zero-rerun
shuffle handoff, ENOSPC retry, rolling restart under load —
docs/lifecycle.md) is smoke-checked and its verdict recorded under
"lifecycle_smoke" in the artifact; a nonzero exit marks ok=false with
the output tail rather than discarding the round.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

DEVICE_LEG_TIMEOUT = int(os.environ.get("BENCH_DEVICE_TIMEOUT", "1500"))
HEDGE_AFTER = int(os.environ.get("BENCH_HEDGE_AFTER", "300"))
MAX_LEGS = int(os.environ.get("BENCH_INIT_ATTEMPTS", "3"))
# bounded init probe: if NO leg has reported devices_ok by this point the
# accelerator claim itself is hung (jax init / pool grant — the failure
# mode where backend init blocks forever inside a C extension). Stop
# waiting, record the round as CPU-only with device_leg="init_failed",
# keep the autopsies AND the daemons' per-phase probe reports.
INIT_PROBE_TIMEOUT = min(int(os.environ.get("BENCH_INIT_PROBE_TIMEOUT", "600")),
                         DEVICE_LEG_TIMEOUT)
# estimated seconds the full-scale device phase needs after data-ready
# (cache fill over the tunnel + 1 warmup + 3 iters); beyond this the leg
# drops to SF1 which needs ~1/10th of it
FULL_SCALE_PHASE_EST = int(os.environ.get("BENCH_FULL_PHASE_EST", "420"))
T0 = time.time()


def log(msg: str) -> None:
    print(f"[{time.time() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def best_time(engine: str, data_dir: str, sql: str, warmups: int, iters: int,
              progress=None, extra_cfg: dict | None = None) -> tuple[float, int]:
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import BallistaConfig, EXECUTOR_ENGINE
    from ballista_tpu.testing.tpchgen import register_tpch

    ctx = SessionContext(BallistaConfig({EXECUTOR_ENGINE: engine,
                                         **(extra_cfg or {})}))
    register_tpch(ctx, data_dir)
    rows = ctx.catalog.get("lineitem").statistics().num_rows or 0

    def run_stats():
        if engine != "tpu":
            return {}
        try:
            from ballista_tpu.ops.tpu import stage_compiler

            return stage_compiler.RUN_STATS.snapshot()
        except Exception:  # noqa: BLE001 — diagnostics only
            return {}

    def mesh_fields(stats: dict) -> dict:
        # surfaced as first-class event fields (not only nested under
        # "stats") so log scrapers can grep mesh adoption per iteration
        return {"mesh_mode": stats.get("mesh_mode_reason"),
                "exchange_bytes_on_device": stats.get("exchange_bytes_on_device")}

    for w in range(warmups):
        t0 = time.time()
        ctx.sql(sql).collect()
        if progress:
            st = run_stats()
            progress("warmup", i=w, s=round(time.time() - t0, 3),
                     stats=st, **mesh_fields(st))
    best = float("inf")
    for i in range(iters):
        t0 = time.time()
        out = ctx.sql(sql).collect()
        dt = time.time() - t0
        best = min(best, dt)
        if progress:
            st = run_stats()
            progress("iter", i=i, s=round(dt, 3), stats=st, **mesh_fields(st))
        assert out.num_rows > 0
    return best, rows


# ---------------------------------------------------------------- device leg

def device_leg_main(out_path: str, progress_path: str, ready_path: str,
                    parent_pid: str, attempt: str) -> None:
    """Runs in the subprocess. Phase 1: the device claim — now owned by the
    warm device-runtime daemon (ballista_tpu/device_daemon/): this leg
    spawns one daemon for the bench run and only WATCHES its supervised
    init state machine (platform probe → jax.devices() → first compile),
    mapping daemon phases onto the same progress events the parent has
    always keyed on. The leg process itself NEVER touches the pool: its
    own jax (the final-merge fallback path) is pinned to CPU, so a hung
    claim wedges only the daemon — which self-diagnoses (per-phase
    timeout + faulthandler stack into <socket>.probe.json) and exits,
    letting the next attempt retry instead of wedging the leg. Phase 2:
    wait for the parent's data-ready JSON. Phase 3: warmup (cache fill)
    + timed iterations with the engine ATTACHED to the daemon, full
    scale or SF1 fallback."""
    attempt = int(attempt)
    parent_pid = int(parent_pid)  # captured BEFORE spawn: survives re-parenting
    pf = open(progress_path, "a", buffering=1)

    def progress(event: str, **kw):
        kw.update(event=event, attempt=attempt, t=round(time.time() - T0, 1))
        pf.write(json.dumps(kw) + "\n")
        pf.flush()
        os.fsync(pf.fileno())

    progress("leg_start", pid=os.getpid())
    from ballista_tpu.device_daemon import client as dclient
    from ballista_tpu.device_daemon import protocol as dproto

    sock = os.path.join(os.path.dirname(out_path), f"daemon_a{attempt}.sock")
    probe_path = dproto.probe_report_path(sock)
    daemon_platforms = os.environ.get("JAX_PLATFORMS") or "(default)"
    progress("daemon_spawn", socket=sock, probe=probe_path)
    # spawn FIRST (the daemon inherits the real JAX_PLATFORMS and dies with
    # this leg), THEN pin this process's own jax to CPU: only the daemon
    # may claim the pool
    daemon_proc = dclient.spawn_daemon(sock, parent_pid=os.getpid())
    os.environ["JAX_PLATFORMS"] = "cpu"

    def parent_alive() -> bool:
        try:
            os.kill(parent_pid, 0)
            return True
        except OSError:
            return False

    def load_probe() -> dict:
        try:
            return json.load(open(probe_path))
        except (OSError, ValueError):
            return {}

    # watch the daemon's init phases; re-emit them under the historical
    # event names so the parent's grant/hedge/probe logic is unchanged
    client = dclient.DaemonClient(sock)
    progress("import_jax_start")
    phase_events = {"platform_probe": (None, "import_jax_ok"),
                    "jax_devices": ("devices_start", "devices_ok"),
                    "first_compile": (None, "first_compile_ok")}
    emitted: set = set()
    while True:
        if not parent_alive():
            progress("orphaned")
            sys.exit(3)
        if daemon_proc.poll() is not None:
            progress("daemon_init_failed", exit_code=daemon_proc.returncode,
                     report=load_probe())
            sys.exit(4)
        try:
            st = client.status()
        except Exception:  # noqa: BLE001 — socket not up yet
            time.sleep(0.5)
            continue
        init = st.get("init", {})
        for ph in init.get("phases", []):
            start_ev, ok_ev = phase_events.get(ph["name"], (None, None))
            if ph["status"] != "pending" and start_ev and start_ev not in emitted:
                emitted.add(start_ev)
                progress(start_ev)
            if ph["status"] == "ok" and ok_ev and ok_ev not in emitted:
                emitted.add(ok_ev)
                if ok_ev == "import_jax_ok":
                    progress(ok_ev, platforms=daemon_platforms)
                elif ok_ev == "devices_ok":
                    progress(ok_ev, platform=st.get("platform"),
                             kind=st.get("device_kind"),
                             init_s=round(ph["s"], 1))
                else:
                    progress(ok_ev, s=round(ph["s"], 1))
        if init.get("error"):
            progress("daemon_init_failed", report=load_probe())
            sys.exit(4)
        if st.get("ready"):
            break
        time.sleep(0.5)

    while not os.path.exists(ready_path):
        if not parent_alive():  # parent died before the sentinel: don't
            progress("orphaned")  # hold the accelerator forever
            sys.exit(3)
        time.sleep(1.0)
    ready = json.load(open(ready_path))
    now = time.time()
    use_fallback = now > ready["fallback_at"] and ready.get("fallback")
    leg_cfg = ready["fallback"] if use_fallback else ready["primary"]
    progress("data_ready_seen", scale=leg_cfg["scale"],
             fallback=bool(use_fallback))

    if os.environ.get("BALLISTA_BENCH_DAEMON_CHAOS") == "1":
        # opt-in sanity probe: the daemon failure domain (crash recovery,
        # watchdog, poison quarantine) must hold on THIS machine before
        # the timed iterations trust the daemon with the real run. The
        # probe runs in a subprocess on its own sockets — it never
        # touches this leg's daemon — and exits nonzero on divergence.
        progress("chaos_smoke_start")
        r = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "dev", "daemon_chaos_exercise.py"), "--quick"],
            capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        if r.returncode != 0:
            progress("chaos_smoke_failed", exit_code=r.returncode,
                     tail=(r.stdout + r.stderr)[-1500:])
            sys.exit(5)
        progress("chaos_smoke_ok")

    def run(cfg) -> float:
        from ballista_tpu.config import (
            TPU_DAEMON_ATTACH_TIMEOUT_MS,
            TPU_DAEMON_ENABLED,
            TPU_DAEMON_SOCKET,
        )

        sql = open(cfg["sql_path"]).read()
        best, _rows = best_time(
            "tpu", cfg["data_dir"], sql, warmups=1, iters=3,
            progress=progress,
            extra_cfg={TPU_DAEMON_ENABLED: True, TPU_DAEMON_SOCKET: sock,
                       TPU_DAEMON_ATTACH_TIMEOUT_MS: 10_000})
        return best

    try:
        best = run(leg_cfg)
    except Exception as e:  # noqa: BLE001 — one retry at reduced scale
        if leg_cfg is ready.get("fallback") or not ready.get("fallback"):
            raise
        progress("full_scale_failed", error=f"{type(e).__name__}: {e}"[:300])
        leg_cfg = ready["fallback"]
        progress("retry_at_fallback", scale=leg_cfg["scale"])
        best = run(leg_cfg)
    progress("leg_done", best_s=round(best, 3), scale=leg_cfg["scale"])
    tmp_out = out_path + f".a{attempt}"
    with open(tmp_out, "w") as f:
        json.dump({"best_s": best, "scale": leg_cfg["scale"],
                   "attempt": attempt}, f)
    try:
        os.link(tmp_out, out_path)  # atomic, FAILS if a winner exists:
    except FileExistsError:  # genuinely first-finisher-wins (rename
        pass  # would silently replace the full-scale datum with SF1)


# --------------------------------------------------------------- diagnostics

def proc_autopsy(pid: int) -> dict:
    """Snapshot where a (presumably hung) claim process is blocked, from
    /proc alone (no strace in the image): per-thread comm/state/wchan and
    current syscall number, plus the TCP connections THIS process holds
    (matched via its /proc/pid/fd socket inodes — net/tcp is namespace-
    wide and would otherwise show unrelated processes' sockets).
    nanosleep + no owned TCP = a poll loop the pool never answers."""
    out: dict = {"pid": pid, "threads": [], "tcp": []}
    base = f"/proc/{pid}"
    try:
        for tid in sorted(os.listdir(f"{base}/task")):
            t = f"{base}/task/{tid}"
            try:
                comm = open(f"{t}/comm").read().strip()
                wchan = open(f"{t}/wchan").read().strip()
                syscall = open(f"{t}/syscall").read().split()[0]
                state = open(f"{t}/stat").read().split()[2]
                out["threads"].append(
                    {"tid": int(tid), "comm": comm, "state": state,
                     "wchan": wchan, "syscall": syscall})
            except OSError:
                pass
        inodes = set()
        for fd in os.listdir(f"{base}/fd"):
            try:
                tgt = os.readlink(f"{base}/fd/{fd}")
            except OSError:
                continue
            if tgt.startswith("socket:["):
                inodes.add(tgt[8:-1])
        for line in open(f"{base}/net/tcp").read().splitlines()[1:]:
            f = line.split()
            if f[9] in inodes:
                out["tcp"].append({"local": f[1], "remote": f[2], "st": f[3]})
    except OSError as e:
        out["error"] = str(e)
    return out


# env vars whose VALUES are known non-secret config; anything else
# matching the prefixes is reported by key only (a pool credential in an
# AXON_*/TPU_* var must not leak into the printed artifact)
_SAFE_ENV = frozenset({
    "JAX_PLATFORMS", "PALLAS_AXON_TPU_GEN", "PALLAS_AXON_POOL_IPS",
    "PALLAS_AXON_REMOTE_COMPILE", "AXON_LOOPBACK_RELAY",
    "TPU_SKIP_MDS_QUERY", "TPU_WORKER_HOSTNAMES", "AXON_POOL_SVC_OVERRIDE",
})


RELAY_DATA_PORTS = (8082, 8092, 8102)  # the loopback relay's listener set


def relay_probe() -> dict:
    """Preflight the axon loopback relay: env summary + TCP connects to the
    harness port AND the relay's own data listeners — a dead relay (ports
    refusing) is an ENVIRONMENT failure the artifact must name, because the
    plugin's claim loop shows it only as an endless poll."""
    env = {}
    for k, v in os.environ.items():
        if k.startswith(("PALLAS_AXON", "AXON_", "TPU_", "JAX_PLATFORMS")):
            env[k] = v if k in _SAFE_ENV else f"<set, {len(v)} chars>"
    probe: dict = {"env": env}
    relay_mode = os.environ.get("AXON_LOOPBACK_RELAY") == "1"
    for port in (2024,) + (RELAY_DATA_PORTS if relay_mode else ()):
        s = socket.socket()
        s.settimeout(3)
        try:
            s.connect(("127.0.0.1", port))
            probe[f"relay_tcp_{port}"] = "connect_ok"
        except OSError as e:
            probe[f"relay_tcp_{port}"] = f"FAIL: {e}"
        finally:
            s.close()
    # only meaningful in loopback-relay mode: with direct pool access these
    # ports are legitimately closed (and not probed) — they say nothing
    # about the environment there
    probe["relay_listeners_down"] = relay_mode and all(
        str(probe.get(f"relay_tcp_{p}", "")).startswith("FAIL")
        for p in RELAY_DATA_PORTS)
    return probe


def _stderr_tail(path: str, n: int = 600) -> str:
    try:
        with open(path) as f:
            return f.read().strip()[-n:] or "(empty stderr)"
    except OSError:
        return "(no stderr captured)"


def read_progress(progress_path: str) -> list[dict]:
    events = []
    try:
        with open(progress_path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        pass
    except OSError:
        pass
    return events


def spawn_leg(tmp: str, attempt: int, paths: dict) -> subprocess.Popen:
    stderr_path = os.path.join(tmp, f"leg{attempt}.stderr")
    env = dict(os.environ)
    # verbose relay/PJRT logging from attempt 1 (ADVICE r4): if the claim
    # loop is stuck the stderr tail becomes the autopsy, and attempt 1 is
    # the attempt most likely to hold the best queue position
    env.setdefault("RUST_LOG", "info")
    env.setdefault("TPU_STDERR_LOG_LEVEL", "0")
    env.setdefault("TF_CPP_MIN_LOG_LEVEL", "0")
    with open(stderr_path, "w") as stderr_f:
        leg = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--device-leg",
             paths["out"], paths["progress"], paths["ready"],
             str(os.getpid()), str(attempt)],
            stdout=subprocess.DEVNULL, stderr=stderr_f, env=env,
        )
    log(f"device leg attempt {attempt} spawned (pid {leg.pid})")
    return leg


class LegPool:
    """All live device-leg processes. One persistent primary claim; a
    hedge leg after HEDGE_AFTER without a grant; crash-respawn anytime
    (crashed claims hold no queue position, so respawn is free)."""

    def __init__(self, tmp: str, paths: dict):
        self.tmp = tmp
        self.paths = paths
        self.legs: dict[int, subprocess.Popen] = {}
        self.next_attempt = 1
        self.errors: list[str] = []
        self.autopsies: list[dict] = []
        self.lock = threading.Lock()

    def spawn(self) -> None:
        with self.lock:
            if self.next_attempt > MAX_LEGS:
                return
            a = self.next_attempt
            self.next_attempt += 1
            self.legs[a] = spawn_leg(self.tmp, a, self.paths)

    def reap_crashes(self) -> None:
        """Respawn legs that exited without producing the result file."""
        with self.lock:
            dead = [(a, p) for a, p in self.legs.items()
                    if p.poll() is not None]
            for a, p in dead:
                del self.legs[a]
        for a, p in dead:
            if os.path.exists(self.paths["out"]):
                continue
            err = (f"attempt {a} exited {p.returncode}: "
                   f"{_stderr_tail(os.path.join(self.tmp, f'leg{a}.stderr'))}")
            log(err)
            self.errors.append(err)
            self.spawn()

    def autopsy_all(self, label: str) -> None:
        with self.lock:
            live = [(a, p) for a, p in self.legs.items() if p.poll() is None]
        for a, p in live:
            snap = proc_autopsy(p.pid)
            snap["attempt"] = a
            snap["label"] = label
            snap["stderr_tail"] = _stderr_tail(
                os.path.join(self.tmp, f"leg{a}.stderr"), 400)
            self.autopsies.append(snap)
            log(f"autopsy[{label}] attempt {a}: "
                + json.dumps(snap["threads"])[:300])

    def kill_except(self, winner_attempt: int) -> None:
        """A leg won the device grant: kill every OTHER leg immediately so
        the winner's timed iterations never contend with a second leg's
        host-side work (the same reason the CPU baseline blocks the legs).
        Also stops spawning: a hedge after a grant is pure contention."""
        with self.lock:
            self.next_attempt = MAX_LEGS + 1
            losers = [(a, p) for a, p in self.legs.items()
                      if a != winner_attempt]
            for a, _ in losers:
                del self.legs[a]
        for a, p in losers:
            log(f"killing losing leg attempt {a} (attempt "
                f"{winner_attempt} holds the grant)")
            try:
                p.send_signal(signal.SIGKILL)
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass

    def kill_all(self) -> None:
        with self.lock:
            legs = list(self.legs.values())
            self.legs.clear()
        for p in legs:
            try:
                p.send_signal(signal.SIGKILL)
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--device-leg":
        device_leg_main(*sys.argv[2:7])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--tpcds-child":
        tpcds_child(sys.argv[2], sys.argv[3])
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--tpcds-skew-child":
        tpcds_skew_child(sys.argv[2])
        return

    scale = float(os.environ.get("TPCH_SCALE", "10"))
    sf_tag = f"sf{scale:g}".replace(".", "p")
    data_dir = os.environ.get("TPCH_DATA", f"/tmp/ballista_tpch_{sf_tag}")
    fb_dir = os.environ.get("TPCH_DATA_SF1", "/tmp/ballista_tpch_sf1")
    sql_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "benchmarks", "tpch", "queries", "q1.sql")

    preflight = relay_probe()
    log(f"relay preflight: {json.dumps(preflight)[:400]}")

    # spawn the device leg FIRST: the claim starts at t=0 and overlaps
    # datagen + the CPU baselines below
    tmp = tempfile.mkdtemp(prefix="bench_leg_")
    paths = {
        "out": os.path.join(tmp, "leg.json"),
        "progress": os.path.join(tmp, "progress.jsonl"),
        "ready": os.path.join(tmp, "data_ready"),
    }
    pool = LegPool(tmp, paths)
    pool.spawn()
    deadline = T0 + DEVICE_LEG_TIMEOUT
    log(f"budget {DEVICE_LEG_TIMEOUT}s; hedge after {HEDGE_AFTER}s; "
        f"max legs {MAX_LEGS}")

    # watch the leg DURING datagen/baseline (ADVICE r4): crashes respawn
    # immediately instead of burning the post-data-ready window
    watcher_stop = threading.Event()

    def watcher():
        while not watcher_stop.wait(5.0):
            pool.reap_crashes()

    threading.Thread(target=watcher, daemon=True).start()

    device_error = None
    device_leg_state = None
    try:
        from ballista_tpu.testing.tpchgen import generate_tpch

        for d, s in ((data_dir, scale), (fb_dir, 1.0)):
            if s == scale and d != data_dir:
                continue
            if not os.path.isdir(os.path.join(d, "lineitem")):
                log(f"generating TPC-H sf={s:g} at {d} ...")
                t0 = time.time()
                generate_tpch(d, scale=s, files_per_table=8)
                log(f"datagen sf{s:g}: {time.time() - t0:.1f}s")

        sql = open(sql_path).read()
        log("running cpu engine baseline ...")
        cpu_t, rows = best_time("cpu", data_dir, sql, warmups=1, iters=3)
        log(f"cpu q1 sf{scale:g}: {cpu_t:.3f}s ({rows / cpu_t:,.0f} rows/s)")
        if scale != 1.0:
            cpu_t_fb, rows_fb = best_time("cpu", fb_dir, sql, warmups=1, iters=2)
            log(f"cpu q1 sf1: {cpu_t_fb:.3f}s ({rows_fb / cpu_t_fb:,.0f} rows/s)")
        else:
            cpu_t_fb, rows_fb = cpu_t, rows

        watcher_stop.set()
        # release the legs only now: their timed iterations must not
        # contend with the CPU baseline's timed iterations on the same
        # host (the claim and the baseline DID overlap — the point of the
        # early spawn). fallback_at: the wall-clock beyond which the
        # full-scale phase no longer fits the window.
        deadline = max(deadline, time.time() + DEVICE_LEG_TIMEOUT / 3)
        ready = {
            "primary": {"data_dir": data_dir, "scale": scale, "sql_path": sql_path},
            "fallback": ({"data_dir": fb_dir, "scale": 1.0, "sql_path": sql_path}
                         if scale != 1.0 else None),
            "fallback_at": deadline - FULL_SCALE_PHASE_EST,
        }
        with open(paths["ready"] + ".tmp", "w") as f:
            json.dump(ready, f)
        os.rename(paths["ready"] + ".tmp", paths["ready"])

        seen = 0
        devices_ok = False
        hedged = False
        mid_autopsy_done = False
        while True:
            events = read_progress(paths["progress"])
            for e in events[seen:]:
                log(f"device: {json.dumps(e)}")
                if e.get("event") == "devices_ok" and not devices_ok:
                    devices_ok = True
                    pool.kill_except(int(e.get("attempt", 1)))
            seen = len(events)
            pool.reap_crashes()
            now = time.time()
            if os.path.exists(paths["out"]):
                break
            with pool.lock:
                any_live = any(p.poll() is None for p in pool.legs.values())
            if not any_live and pool.next_attempt > MAX_LEGS:
                device_error = ("all device legs crashed: "
                                + "; ".join(pool.errors[-3:]))
                break
            if not devices_ok and not hedged and now - T0 > HEDGE_AFTER:
                # hedge: a SECOND claim in parallel — never kill the
                # first (it may hold a queue position)
                hedged = True
                log("no grant yet — spawning hedge leg (primary stays up)")
                pool.spawn()
            if not devices_ok and not mid_autopsy_done and now - T0 > 2 * HEDGE_AFTER:
                mid_autopsy_done = True
                pool.autopsy_all("mid")
            if not devices_ok and now - T0 > INIT_PROBE_TIMEOUT:
                # no daemon ever got past backend init: don't burn the rest
                # of the budget waiting on a hung claim — degrade to a
                # recorded CPU-only round WITH the daemons' per-phase probe
                # reports (which phase, how long, stack snapshot) in the
                # artifact
                pool.autopsy_all("init_timeout")
                stage = events[-1]["event"] if events else "no progress at all"
                device_error = (
                    f"no devices_ok within init probe window "
                    f"({INIT_PROBE_TIMEOUT}s); last progress: {stage}; "
                    f"crashes: {pool.errors[-2:]}")
                device_leg_state = "init_failed"
                log(device_error)
                break
            if now > deadline:
                pool.autopsy_all("deadline")
                stage = events[-1]["event"] if events else "no progress at all"
                relay_now = relay_probe()
                relay_note = (
                    " RELAY DOWN: the loopback relay's data listeners refuse "
                    "connections — the tunnel process is dead, this is an "
                    "environment failure, not an engine one."
                    if relay_now.get("relay_listeners_down") else "")
                device_error = (
                    f"device leg(s) produced no result in {round(now - T0)}s "
                    f"(budget {DEVICE_LEG_TIMEOUT}s); last progress: {stage};"
                    f"{relay_note} crashes: {pool.errors[-2:]}")
                log(device_error)
                break
            time.sleep(2.0)
    finally:
        watcher_stop.set()
        pool.kill_all()  # never leave an orphan polling for the sentinel

    tpu_t, leg_scale = 0.0, scale
    if device_error is None or os.path.exists(paths["out"]):
        try:
            with open(paths["out"]) as f:
                leg_out = json.load(f)
            tpu_t = leg_out["best_s"]
            leg_scale = leg_out.get("scale", scale)
            device_error = None
        except (OSError, ValueError, KeyError) as e:
            if device_error is None:
                device_error = f"device leg produced no output: {e}"

    # pick the CPU baseline matching the scale the device leg actually ran
    if leg_scale == scale:
        base_t, base_rows, base_tag = cpu_t, rows, sf_tag
    else:
        base_t, base_rows, base_tag = cpu_t_fb, rows_fb, "sf1"

    trail = read_progress(paths["progress"])
    # structured init evidence: every leg's daemon wrote a per-phase probe
    # report next to its socket (phase timings; faulthandler stack on a
    # hang) — collect them whether the leg won or wedged
    init_probes = {}
    for e in trail:
        if e.get("event") == "daemon_spawn" and e.get("probe"):
            try:
                init_probes[f"a{e.get('attempt', '?')}"] = json.load(
                    open(e["probe"]))
            except (OSError, ValueError):
                pass
    if device_error is not None and device_leg_state is None and any(
            e.get("event") == "daemon_init_failed" for e in trail):
        # every leg died IN the claim (daemon init phase timeout/crash):
        # that is an init failure with a diagnosis, not a generic error
        device_leg_state = "init_failed"

    result = {
        "metric": f"tpch_q1_{base_tag}_rows_per_sec_per_chip",
        "unit": "rows/s",
        "cpu_rows_per_sec": round(base_rows / base_t),
    }
    if device_error is None and tpu_t > 0:
        log(f"tpu q1 {base_tag}: {tpu_t:.3f}s ({base_t / tpu_t:.1f}x)")
        result["device_leg"] = "ok"
        result["value"] = round(base_rows / tpu_t)
        result["vs_baseline"] = round((base_rows / tpu_t) / (base_rows / base_t), 2)
        if leg_scale != scale:
            result["note"] = f"reduced-scale fallback: device ran sf{leg_scale:g}"
    else:
        # LOUD failure: never report the CPU number as the TPU number
        result["device_leg"] = device_leg_state or "error"
        result["value"] = 0
        result["vs_baseline"] = 0.0
        result["device_error"] = device_error
        result["relay_preflight"] = preflight
        result["autopsies"] = pool.autopsies
        if init_probes:
            result["init_probe"] = init_probes
    # partial evidence survives either way. Init-stage events are few and
    # load-bearing — keep ALL of them; only warmup/iter events truncate
    # (ADVICE r4).
    if trail:
        init_ev = [e for e in trail if e.get("event") not in ("warmup", "iter")]
        run_ev = [e for e in trail if e.get("event") in ("warmup", "iter")]
        result["device_progress"] = init_ev + run_ev[-40:]

    if os.environ.get("BALLISTA_BENCH_LIFECYCLE") == "1":
        result["lifecycle_smoke"] = lifecycle_smoke_leg()

    if os.environ.get("BENCH_SERVING", "1") == "1":
        result["serving"] = serving_leg()

    if os.environ.get("BENCH_TPCDS", "1") == "1":
        result["tpcds"] = tpcds_leg()
        result["tpcds_skew"] = tpcds_skew_leg()

    print(json.dumps(result))


def lifecycle_smoke_leg() -> dict:
    """Opt-in lifecycle probe (BALLISTA_BENCH_LIFECYCLE=1): run
    dev/lifecycle_exercise.py --quick in a CPU-pinned subprocess — the
    drain/disk_full/rolling-restart failure domain must hold on this
    machine. The verdict lands in the artifact; a failure does NOT
    zero the round (the timed numbers are still real), it just marks
    the smoke as failed with the output tail."""
    log("lifecycle smoke: dev/lifecycle_exercise.py --quick ...")
    t0 = time.time()
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "dev", "lifecycle_exercise.py"), "--quick"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    out = {"ok": r.returncode == 0, "exit_code": r.returncode,
           "elapsed_s": round(time.time() - t0, 1)}
    if r.returncode != 0:
        out["tail"] = (r.stdout + r.stderr)[-1500:]
    log(f"lifecycle smoke: {'ok' if out['ok'] else 'FAILED'} "
        f"({out['elapsed_s']}s)")
    return out


def serving_leg() -> dict:
    """High-QPS serving-tier leg (CPU-only, own sf0.01 dataset): plan
    cache + fast lane + result cache vs the legacy queued path, concurrent
    sessions, sustained QPS and p50/p99. Failures are recorded, never
    fatal — this leg must not sink the device benchmark's result."""
    log("running serving-tier QPS leg ...")
    try:
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "dev"))
        from qps_exercise import (
            run_qps_comparison,
            run_refresh_comparison,
            run_shard_comparison,
        )

        from ballista_tpu.testing.tpchgen import generate_tpch

        refresh_stats = None
        with tempfile.TemporaryDirectory(prefix="bench_qps_") as qd:
            generate_tpch(qd, scale=0.01, seed=42, files_per_table=2)
            stats = run_qps_comparison(qd)
            shard_stats = run_shard_comparison(qd)
            if os.environ.get("BENCH_INCREMENTAL", "1") == "1":
                refresh_stats = run_refresh_comparison(qd)
        out = {
            "speedup_qps": stats["speedup_qps"],
            "speedup_p50": stats["speedup_p50"],
        }
        for mode in ("legacy", "serving"):
            s = stats[mode]
            out[mode] = {k: s[k] for k in
                         ("queries", "wall_s", "qps", "p50_ms", "p99_ms",
                          "warm_p50_ms", "warm_p99_ms")}
        out["caches"] = {
            "plan_cache": stats["serving"]["serving"]["plan_cache"],
            "result_cache": stats["serving"]["serving"]["result_cache"],
            "fast_lane": stats["serving"]["serving"]["fast_lane"],
        }
        # scheduler scale-out: N=1 vs N=4 event-loop shards over the same
        # fleet + the direct-dispatch parity probe
        out["scheduler_shards"] = shard_stats["scheduler_shards"]
        out["shard_speedup_qps"] = shard_stats["shard_speedup_qps"]
        out["direct_dispatch_rate"] = shard_stats["direct_dispatch_rate"]
        for key in ("shards_1", "shards_4"):
            s = shard_stats[key]
            out[key] = {k: s[k] for k in
                        ("queries", "wall_s", "qps", "p50_ms", "p99_ms")}
        # incremental maintenance: append-then-refresh, maintained vs
        # from-scratch, byte-identical (skip with BENCH_INCREMENTAL=0)
        if refresh_stats is not None:
            out["refresh"] = refresh_stats
        log(f"serving leg: {out['speedup_qps']}x QPS, {out['speedup_p50']}x p50, "
            f"shard scale-out {out['shard_speedup_qps']}x, "
            f"direct rate {out['direct_dispatch_rate']}"
            + (f", refresh {refresh_stats['speedup']}x maintained"
               if refresh_stats else ""))
        return out
    except (Exception, SystemExit) as e:  # noqa: BLE001 — recorded, not fatal
        log(f"serving leg failed: {e}")
        return {"error": str(e)}


TPCDS_QUERIES = (36, 47, 67, 86, 98)


def tpcds_child(data_dir: str, engine: str) -> None:
    """Run the sort/window-heavy TPC-DS subset under one engine and print
    per-query best-of-2 times plus the device sort/window counters."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import (
        BallistaConfig,
        EXECUTOR_ENGINE,
        TPU_MIN_ROWS,
    )
    from ballista_tpu.ops.tpu.sort_window import counters_snapshot
    from ballista_tpu.testing.tpcdsgen import register_tpcds

    settings = {EXECUTOR_ENGINE: engine}
    if engine == "tpu":
        settings[TPU_MIN_ROWS] = 0
    ctx = SessionContext(BallistaConfig(settings))
    register_tpcds(ctx, data_dir)

    root = os.path.dirname(os.path.abspath(__file__))
    out = {"engine": engine, "queries": {}}
    before = counters_snapshot()
    for q in TPCDS_QUERIES:
        sql = open(os.path.join(
            root, "benchmarks", "tpcds", "queries", f"q{q}.sql")).read()
        ctx.sql(sql).collect()  # warmup: parse/plan/compile out of the timing
        best, rows = float("inf"), 0
        for _ in range(2):
            t0 = time.time()
            res = ctx.sql(sql).collect()
            best = min(best, time.time() - t0)
            rows = res.num_rows
        out["queries"][f"q{q}"] = {"best_s": round(best, 4), "rows": rows}
    delta = {k: round(v - before[k], 4)
             for k, v in counters_snapshot().items()}
    out["counters"] = {k: v for k, v in delta.items() if v}
    print("TPCDS_CHILD " + json.dumps(out))


def tpcds_leg() -> dict:
    """Sort/window/LIMIT-heavy TPC-DS subset (CPU jax, own small fixture):
    the tpu engine's on-device ORDER BY / window / top-k stages vs the CPU
    engine, per query. Each engine runs in a fresh subprocess so compile
    caches can't bleed. Failures are recorded, never fatal — this leg must
    not sink the device benchmark's result."""
    log("running tpcds sort/window leg ...")
    try:
        from ballista_tpu.testing.tpcdsgen import generate_tpcds

        scale = float(os.environ.get("BENCH_TPCDS_SCALE", "0.1"))
        sf_tag = f"sf{scale:g}".replace(".", "p")
        data_dir = os.environ.get("TPCDS_DATA", f"/tmp/ballista_tpcds_{sf_tag}")
        if not os.path.isdir(os.path.join(data_dir, "store_sales")):
            log(f"generating TPC-DS sf={scale:g} at {data_dir} ...")
            t0 = time.time()
            generate_tpcds(data_dir, scale=scale, seed=17, files_per_table=2)
            log(f"tpcds datagen sf{scale:g}: {time.time() - t0:.1f}s")

        legs = {}
        for engine in ("cpu", "tpu"):
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--tpcds-child", data_dir, engine],
                env=env, capture_output=True, text=True, timeout=900)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"tpcds {engine} child failed:\n"
                    f"{proc.stdout[-800:]}\n{proc.stderr[-800:]}")
            for line in proc.stdout.splitlines():
                if line.startswith("TPCDS_CHILD "):
                    legs[engine] = json.loads(line[len("TPCDS_CHILD "):])
                    break
            else:
                raise RuntimeError(f"tpcds {engine} child printed no stats")

        out = {"metric": f"tpcds_sortwin_{sf_tag}_speedup_vs_cpu",
               "scale": scale, "queries": {}}
        for q in (f"q{n}" for n in TPCDS_QUERIES):
            c, t = legs["cpu"]["queries"][q], legs["tpu"]["queries"][q]
            if c["rows"] != t["rows"]:
                raise RuntimeError(
                    f"tpcds {q}: row-count divergence cpu={c['rows']} "
                    f"tpu={t['rows']}")
            out["queries"][q] = {
                "cpu_s": c["best_s"], "tpu_s": t["best_s"], "rows": t["rows"],
                "speedup": round(c["best_s"] / max(t["best_s"], 1e-9), 2),
            }
        ctr = legs["tpu"].get("counters", {})
        out["device_counters"] = ctr
        if not (ctr.get("sort_invocations") or ctr.get("window_invocations")
                or ctr.get("topk_invocations")):
            raise RuntimeError(
                "tpcds tpu leg ran but the device sort/window family never "
                f"fired (counters: {ctr})")
        gmean = 1.0
        for q in out["queries"].values():
            gmean *= q["speedup"]
        out["value"] = round(gmean ** (1.0 / len(out["queries"])), 2)
        log(f"tpcds leg: geomean speedup {out['value']}x over "
            f"{len(out['queries'])} queries (counters: {ctr})")
        return out
    except (Exception, SystemExit) as e:  # noqa: BLE001 — recorded, not fatal
        log(f"tpcds leg failed: {e}")
        return {"error": str(e)}


TPCDS_SKEW_QUERIES = (3, 68)


def tpcds_skew_child(data_dir: str) -> None:
    """Run the skewed-join TPC-DS subset under chaos `skew` (seeded
    hot-key routing at the shuffle partitioner, docs/aqe.md) through the
    distributed standalone path, with the AQE skew defense ON, then re-run
    the pure-join probe with the defense OFF as the unsplit oracle. Prints
    per-query times, the AQE decision counters, and byte parity."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import (
        AQE_SKEW_ENABLED,
        AQE_SKEW_MIN_BYTES,
        AQE_TARGET_PARTITION_BYTES,
        BROADCAST_JOIN_ROWS_THRESHOLD,
        CHAOS_ENABLED,
        CHAOS_MODE,
        CHAOS_SEED,
        CHAOS_SKEW_FRACTION,
        DEBUG_PLAN_VERIFY,
        DEFAULT_SHUFFLE_PARTITIONS,
        BallistaConfig,
        PLANNER_ADAPTIVE_ENABLED,
    )
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS
    from ballista_tpu.testing.tpcdsgen import register_tpcds

    probe_sql = ("select ss_item_sk, ss_ticket_number, i_brand from store_sales "
                 "join item on ss_item_sk = i_item_sk")

    def cfg(skew_aqe: bool) -> BallistaConfig:
        return BallistaConfig({
            DEFAULT_SHUFFLE_PARTITIONS: 8,
            PLANNER_ADAPTIVE_ENABLED: True,
            BROADCAST_JOIN_ROWS_THRESHOLD: 100,  # force partitioned joins
            CHAOS_ENABLED: True, CHAOS_MODE: "skew", CHAOS_SEED: 5,
            CHAOS_SKEW_FRACTION: 0.7,
            AQE_SKEW_ENABLED: skew_aqe, AQE_SKEW_MIN_BYTES: 4096,
            AQE_TARGET_PARTITION_BYTES: 128 * 1024,
            DEBUG_PLAN_VERIFY: True,
        })

    def counters() -> dict:
        snap = RUN_STATS.snapshot()
        return {k: int(snap.get(k, 0) or 0) for k in
                ("skew_splits", "coalesced_partitions", "broadcast_promotions",
                 "broadcast_demotions", "aqe_mesh_replans")}

    root = os.path.dirname(os.path.abspath(__file__))
    out = {"queries": {}}
    before = counters()

    ctx = SessionContext.standalone(cfg(True), num_executors=1, vcores=4)
    register_tpcds(ctx, data_dir)
    for q in TPCDS_SKEW_QUERIES:
        sql = open(os.path.join(
            root, "benchmarks", "tpcds", "queries", f"q{q}.sql")).read()
        best, rows = float("inf"), 0
        for _ in range(2):
            t0 = time.time()
            res = ctx.sql(sql).collect()
            best = min(best, time.time() - t0)
            rows = res.num_rows
        out["queries"][f"q{q}"] = {"best_s": round(best, 4), "rows": rows}
    t0 = time.time()
    split_res = ctx.sql(probe_sql).collect()
    out["queries"]["join_probe"] = {
        "best_s": round(time.time() - t0, 4), "rows": split_res.num_rows}
    ctx.shutdown()
    out["counters"] = {k: v - before[k] for k, v in counters().items() if v - before[k]}

    # unsplit oracle: same chaos routing, defense off — byte parity proves
    # the slice/merge path reproduced the exact unsplit stream
    ctx = SessionContext.standalone(cfg(False), num_executors=1, vcores=4)
    register_tpcds(ctx, data_dir)
    t0 = time.time()
    oracle = ctx.sql(probe_sql).collect()
    out["oracle_s"] = round(time.time() - t0, 4)
    ctx.shutdown()
    out["parity"] = bool(split_res.to_pandas().equals(oracle.to_pandas()))
    print("TPCDS_SKEW_CHILD " + json.dumps(out))
    if not out["parity"]:
        sys.exit(3)


def tpcds_skew_leg() -> dict:
    """AQE skew-defense leg (CPU jax, shares the tpcds fixture): star
    joins plus a pure-join probe under seeded hot-key chaos. Valid only
    when the probe actually split (skew_splits >= 1) and the split result
    is byte-identical to the unsplit oracle. Failures are recorded, never
    fatal."""
    log("running tpcds skew-defense leg ...")
    try:
        from ballista_tpu.testing.tpcdsgen import generate_tpcds

        scale = float(os.environ.get("BENCH_TPCDS_SCALE", "0.1"))
        sf_tag = f"sf{scale:g}".replace(".", "p")
        data_dir = os.environ.get("TPCDS_DATA", f"/tmp/ballista_tpcds_{sf_tag}")
        if not os.path.isdir(os.path.join(data_dir, "store_sales")):
            log(f"generating TPC-DS sf={scale:g} at {data_dir} ...")
            generate_tpcds(data_dir, scale=scale, seed=17, files_per_table=2)

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--tpcds-skew-child", data_dir],
            env=env, capture_output=True, text=True, timeout=900)
        if proc.returncode != 0:
            raise RuntimeError(
                f"tpcds skew child failed (rc={proc.returncode}):\n"
                f"{proc.stdout[-800:]}\n{proc.stderr[-800:]}")
        for line in proc.stdout.splitlines():
            if line.startswith("TPCDS_SKEW_CHILD "):
                child = json.loads(line[len("TPCDS_SKEW_CHILD "):])
                break
        else:
            raise RuntimeError("tpcds skew child printed no stats")

        ctr = child.get("counters", {})
        if not ctr.get("skew_splits"):
            raise RuntimeError(
                f"tpcds skew leg ran but no partition split fired ({ctr})")
        if not child.get("parity"):
            raise RuntimeError("tpcds skew leg: split result diverged from oracle")
        out = {"metric": f"tpcds_skew_{sf_tag}_parity",
               "scale": scale, "queries": child["queries"],
               "counters": ctr, "oracle_s": child["oracle_s"], "value": 1}
        log(f"tpcds skew leg: parity ok, counters {ctr}")
        return out
    except (Exception, SystemExit) as e:  # noqa: BLE001 — recorded, not fatal
        log(f"tpcds skew leg failed: {e}")
        return {"error": str(e)}


if __name__ == "__main__":
    main()
