"""Benchmark entry point (driver-run on real TPU hardware).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Workload: TPC-H q1 at SF10 (override with TPCH_SCALE) — the
scan→filter→project→group-aggregate pipeline that dominates analytic
engines, at a scale where device residency matters (~60M lineitem rows).
value = lineitem rows aggregated per second per chip on the TPU engine
(hot path: device-resident columns, compiled stage). vs_baseline = speedup
over this framework's CPU engine (pyarrow C++ operators) on the same host —
the "CPU-executor baseline" the north-star gate compares against
(BASELINE.json: ≥3x target at SF100/v5e-8).

Tunnel-hostile design (the axon device link has ~70ms RTT and has been
observed dead for whole rounds):
  * ONE persistent device-leg subprocess, spawned at bench launch, that
    initializes the device exactly once and then runs the whole leg —
    no separate probe process paying init twice.
  * Device init gets the WHOLE BENCH_DEVICE_TIMEOUT budget (default
    1500s) because datagen + the CPU baseline run concurrently in the
    parent while the device initializes.
  * The leg streams progress events (init / fill / per-iteration times)
    to a JSONL file; whatever happened before a timeout or crash is
    folded into the final artifact under "device_progress", so even a
    half-dead tunnel yields evidence.

Failure policy: a dead accelerator tunnel must NOT look like parity. If
the device leg cannot produce a time, the JSON carries value=0,
vs_baseline=0.0, a "device_error" field, and the progress trail.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

DEVICE_LEG_TIMEOUT = int(os.environ.get("BENCH_DEVICE_TIMEOUT", "1500"))
T0 = time.time()


def log(msg: str) -> None:
    print(f"[{time.time() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def best_time(engine: str, data_dir: str, sql: str, warmups: int, iters: int,
              progress=None) -> tuple[float, int]:
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import BallistaConfig, EXECUTOR_ENGINE
    from ballista_tpu.testing.tpchgen import register_tpch

    ctx = SessionContext(BallistaConfig({EXECUTOR_ENGINE: engine}))
    register_tpch(ctx, data_dir)
    rows = ctx.catalog.get("lineitem").statistics().num_rows or 0
    for w in range(warmups):
        t0 = time.time()
        ctx.sql(sql).collect()
        if progress:
            progress("warmup", i=w, s=round(time.time() - t0, 3))
    best = float("inf")
    for i in range(iters):
        t0 = time.time()
        out = ctx.sql(sql).collect()
        dt = time.time() - t0
        best = min(best, dt)
        if progress:
            progress("iter", i=i, s=round(dt, 3))
        assert out.num_rows > 0
    return best, rows


# ---------------------------------------------------------------- device leg

def device_leg_main(data_dir: str, sql_path: str, out_path: str,
                    progress_path: str, ready_path: str) -> None:
    """Runs in the subprocess. Phase 1: device init (the slow, fragile part —
    started before data even exists). Phase 2: wait for the parent's
    data-ready sentinel. Phase 3: warmup (cache fill) + timed iterations.
    Every phase appends a JSONL progress event immediately."""
    pf = open(progress_path, "a", buffering=1)

    def progress(event: str, **kw):
        kw.update(event=event, t=round(time.time() - T0, 1))
        pf.write(json.dumps(kw) + "\n")
        pf.flush()
        os.fsync(pf.fileno())

    progress("leg_start", pid=os.getpid())
    import jax
    p = os.environ.get("JAX_PLATFORMS")
    if p:
        jax.config.update("jax_platforms", p)
    t0 = time.time()
    d = jax.devices()[0]
    progress("devices_ok", platform=d.platform, kind=d.device_kind,
             init_s=round(time.time() - t0, 1))
    import jax.numpy as jnp
    t0 = time.time()
    x = jnp.ones((256, 256), dtype=jnp.bfloat16)
    (x @ x).block_until_ready()
    progress("first_compile_ok", s=round(time.time() - t0, 1))

    ppid = os.getppid()
    while not os.path.exists(ready_path):
        if os.getppid() != ppid:  # parent died before the sentinel: don't
            progress("orphaned")  # hold the accelerator forever
            sys.exit(3)
        time.sleep(1.0)
    progress("data_ready_seen")

    sql = open(sql_path).read()
    best, _rows = best_time("tpu", data_dir, sql, warmups=1, iters=3,
                            progress=progress)
    progress("leg_done", best_s=round(best, 3))
    with open(out_path, "w") as f:
        json.dump({"best_s": best}, f)


def _stderr_tail(path: str, n: int = 600) -> str:
    try:
        with open(path) as f:
            return f.read().strip()[-n:] or "(empty stderr)"
    except OSError:
        return "(no stderr captured)"


def read_progress(progress_path: str) -> list[dict]:
    events = []
    try:
        with open(progress_path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        pass
    except OSError:
        pass
    return events


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--device-leg":
        device_leg_main(*sys.argv[2:7])
        return

    scale = float(os.environ.get("TPCH_SCALE", "10"))
    sf_tag = f"sf{scale:g}".replace(".", "p")
    data_dir = os.environ.get("TPCH_DATA", f"/tmp/ballista_tpch_{sf_tag}")
    sql_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "benchmarks", "tpch", "queries", "q1.sql")

    # spawn the device leg FIRST: device init starts at t=0 and overlaps
    # datagen + the CPU baseline below
    tmp = tempfile.mkdtemp(prefix="bench_leg_")
    out_path = os.path.join(tmp, "leg.json")
    progress_path = os.path.join(tmp, "progress.jsonl")
    ready_path = os.path.join(tmp, "data_ready")
    stderr_path = os.path.join(tmp, "leg.stderr")
    stderr_f = open(stderr_path, "w")
    leg = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--device-leg",
         data_dir, sql_path, out_path, progress_path, ready_path],
        stdout=subprocess.DEVNULL, stderr=stderr_f,
    )
    stderr_f.close()  # child holds its own duplicated fd
    log(f"device leg spawned (pid {leg.pid}); budget {DEVICE_LEG_TIMEOUT}s")

    try:
        if not os.path.isdir(os.path.join(data_dir, "lineitem")):
            log(f"generating TPC-H sf={scale:g} at {data_dir} ...")
            from ballista_tpu.testing.tpchgen import generate_tpch

            t0 = time.time()
            generate_tpch(data_dir, scale=scale, files_per_table=8)
            log(f"datagen {time.time() - t0:.1f}s")

        sql = open(sql_path).read()
        log("running cpu engine baseline ...")
        cpu_t, rows = best_time("cpu", data_dir, sql, warmups=1, iters=3)
        log(f"cpu q1 sf{scale:g}: {cpu_t:.3f}s ({rows / cpu_t:,.0f} rows/s)")

        # release the leg only now: its timed iterations must not contend
        # with the CPU baseline's timed iterations on the same host (init
        # and the baseline DID overlap — the point of the early spawn)
        with open(ready_path, "w") as f:
            f.write("ok")
        t_ready = time.time()

        # budget: the full window from launch, but never less than half of
        # it after data-ready — datagen + baseline time must not starve the
        # leg's query phase (at SF100 parent work alone can eat the window)
        deadline = max(T0 + DEVICE_LEG_TIMEOUT, t_ready + DEVICE_LEG_TIMEOUT / 2)
        seen = 0
        device_error = None
        while True:
            events = read_progress(progress_path)
            for e in events[seen:]:
                log(f"device: {json.dumps(e)}")
            seen = len(events)
            rc = leg.poll()
            if rc is not None:
                if rc != 0:
                    device_error = f"device leg exited {rc}: {_stderr_tail(stderr_path)}"
                break
            if time.time() > deadline:
                # a leg that finished its work but wedged in runtime
                # teardown still produced a valid result: check first
                if os.path.exists(out_path):
                    log("leg hit deadline after writing its result; using it")
                    leg.kill()
                    break
                leg.kill()
                elapsed = round(time.time() - T0)
                stage = events[-1]["event"] if events else "no progress at all"
                device_error = (f"device leg TIMED OUT after {elapsed}s "
                                f"(budget {DEVICE_LEG_TIMEOUT}s); last progress: {stage}")
                log(device_error)
                break
            time.sleep(2.0)
    except BaseException:
        leg.kill()  # never leave an orphan polling for the sentinel
        raise

    tpu_t = 0.0
    if device_error is None:
        try:
            with open(out_path) as f:
                tpu_t = json.load(f)["best_s"]
            log(f"tpu q1 sf{scale:g}: {tpu_t:.3f}s ({cpu_t / tpu_t:.1f}x)")
        except (OSError, ValueError, KeyError) as e:
            device_error = f"device leg produced no output: {e}"

    result = {
        "metric": f"tpch_q1_{sf_tag}_rows_per_sec_per_chip",
        "unit": "rows/s",
        "cpu_rows_per_sec": round(rows / cpu_t),
    }
    if device_error is None and tpu_t > 0:
        result["value"] = round(rows / tpu_t)
        result["vs_baseline"] = round((rows / tpu_t) / (rows / cpu_t), 2)
    else:
        # LOUD failure: never report the CPU number as the TPU number
        result["value"] = 0
        result["vs_baseline"] = 0.0
        result["device_error"] = device_error
    # partial evidence survives either way: the leg's progress trail shows
    # exactly how far the tunnel let us get (init / fill / per-iter times)
    progress_trail = read_progress(progress_path)
    if progress_trail:
        result["device_progress"] = progress_trail
    print(json.dumps(result))


if __name__ == "__main__":
    main()
