"""Benchmark entry point (driver-run on real TPU hardware).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload: TPC-H q1 at SF1 (the first BASELINE.json config) — the
scan→filter→project→group-aggregate pipeline that dominates analytic
engines. value = lineitem rows aggregated per second per chip on the TPU
engine (hot path: device-resident columns, compiled stage).
vs_baseline = speedup over this framework's CPU engine (pyarrow C++
operators) on the same host — the "CPU-executor baseline" the north-star
gate compares against (BASELINE.json: ≥3x target at SF100/v5e-8).
"""

import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    data_dir = os.environ.get("TPCH_DATA", "/tmp/ballista_tpch_sf1")
    scale = float(os.environ.get("TPCH_SCALE", "1.0"))
    if not os.path.isdir(os.path.join(data_dir, "lineitem")):
        log(f"generating TPC-H sf={scale} at {data_dir} ...")
        from ballista_tpu.testing.tpchgen import generate_tpch

        t0 = time.time()
        generate_tpch(data_dir, scale=scale, files_per_table=4)
        log(f"datagen {time.time() - t0:.1f}s")

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import BallistaConfig, EXECUTOR_ENGINE
    from ballista_tpu.testing.tpchgen import register_tpch

    sql = open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "benchmarks", "tpch", "queries", "q1.sql")).read()

    def best_time(engine: str, warmups: int, iters: int) -> tuple[float, int]:
        ctx = SessionContext(BallistaConfig({EXECUTOR_ENGINE: engine}))
        register_tpch(ctx, data_dir)
        rows = ctx.catalog.get("lineitem").statistics().num_rows or 0
        for _ in range(warmups):
            ctx.sql(sql).collect()
        best = float("inf")
        for _ in range(iters):
            t0 = time.time()
            out = ctx.sql(sql).collect()
            best = min(best, time.time() - t0)
            assert out.num_rows > 0
        return best, rows

    log("running cpu engine baseline ...")
    cpu_t, rows = best_time("cpu", warmups=1, iters=3)
    log(f"cpu q1: {cpu_t:.3f}s")

    # a dead accelerator tunnel must not hang the bench: probe device init
    # in a subprocess with a hard timeout before committing to the device leg
    import subprocess

    try:
        probe_src = (
            "import os, jax\n"
            "p = os.environ.get('JAX_PLATFORMS')\n"
            "if p: jax.config.update('jax_platforms', p)\n"
            "print(jax.devices()[0].platform)\n"
        )
        probe = subprocess.run(
            [sys.executable, "-c", probe_src],
            capture_output=True, timeout=180, text=True,
        )
        device_ok = probe.returncode == 0
        log(f"device probe: {probe.stdout.strip() or probe.stderr.strip()[:200]}")
    except subprocess.TimeoutExpired:
        device_ok = False
        log("device probe TIMED OUT (dead tunnel?) — reporting cpu-only")

    if device_ok:
        log("running tpu engine ...")
        tpu_t, _ = best_time("tpu", warmups=1, iters=3)
        log(f"tpu q1: {tpu_t:.3f}s ({cpu_t / tpu_t:.1f}x)")
    else:
        tpu_t = cpu_t  # device unreachable: report parity, not a hang

    tpu_rps = rows / tpu_t
    cpu_rps = rows / cpu_t
    print(json.dumps({
        "metric": "tpch_q1_sf1_rows_per_sec_per_chip",
        "value": round(tpu_rps),
        "unit": "rows/s",
        "vs_baseline": round(tpu_rps / cpu_rps, 2),
    }))


if __name__ == "__main__":
    main()
