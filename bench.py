"""Benchmark entry point (driver-run on real TPU hardware).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Workload: TPC-H q1 at SF10 (override with TPCH_SCALE) — the
scan→filter→project→group-aggregate pipeline that dominates analytic
engines, at a scale where device residency matters (~60M lineitem rows).
value = lineitem rows aggregated per second per chip on the TPU engine
(hot path: device-resident columns, compiled stage). vs_baseline = speedup
over this framework's CPU engine (pyarrow C++ operators) on the same host —
the "CPU-executor baseline" the north-star gate compares against
(BASELINE.json: ≥3x target at SF100/v5e-8).

Tunnel-hostile design, round 4 (the axon device link has ~70ms RTT and has
been observed dead for three consecutive driver runs; rounds 2-3 produced
ZERO device evidence because the leg hung somewhere inside init):
  * The device leg emits a progress event around EVERY fragile statement:
    import_jax_start/ok, devices_start/ok, first_compile_ok, fills, iters.
    A hang is therefore pinned to a single statement in the autopsy.
  * Parent-side staged watchdog: if a leg attempt does not reach
    `devices_ok` within BENCH_INIT_STAGE_TIMEOUT (default 420s), it is
    killed and respawned (BENCH_INIT_ATTEMPTS, default 3) — later attempts
    run with verbose relay/PJRT logging so the stderr tail shows WHY the
    claim loop is stuck. Device init overlaps datagen + the CPU baseline
    in the parent, so attempts are nearly free until data is ready.
  * Reduced-scale fallback: the parent generates BOTH SF<scale> and SF1
    data and times the CPU baseline on both. The ready-file hands the leg
    a `fallback_at` wall-clock: if data becomes ready too late for the
    full-scale timed phase, the leg runs SF1 instead, so *some* hot-path
    device datum lands. A device OOM at full scale also retries at SF1.
  * Roofline evidence: each device iteration event carries the engine's
    RUN_STATS (device-table fill seconds, resident bytes, dispatch+fetch
    seconds) so achieved HBM GB/s is computable from the artifact alone.

Failure policy: a dead accelerator tunnel must NOT look like parity. If
the device leg cannot produce a time, the JSON carries value=0,
vs_baseline=0.0, a "device_error" field, the per-attempt progress trail,
and each attempt's stderr tail.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

DEVICE_LEG_TIMEOUT = int(os.environ.get("BENCH_DEVICE_TIMEOUT", "1500"))
INIT_STAGE_TIMEOUT = int(os.environ.get("BENCH_INIT_STAGE_TIMEOUT", "420"))
INIT_ATTEMPTS = int(os.environ.get("BENCH_INIT_ATTEMPTS", "3"))
# estimated seconds the full-scale device phase needs after data-ready
# (cache fill over the tunnel + 1 warmup + 3 iters); beyond this the leg
# drops to SF1 which needs ~1/10th of it
FULL_SCALE_PHASE_EST = int(os.environ.get("BENCH_FULL_PHASE_EST", "420"))
T0 = time.time()


def log(msg: str) -> None:
    print(f"[{time.time() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def best_time(engine: str, data_dir: str, sql: str, warmups: int, iters: int,
              progress=None) -> tuple[float, int]:
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import BallistaConfig, EXECUTOR_ENGINE
    from ballista_tpu.testing.tpchgen import register_tpch

    ctx = SessionContext(BallistaConfig({EXECUTOR_ENGINE: engine}))
    register_tpch(ctx, data_dir)
    rows = ctx.catalog.get("lineitem").statistics().num_rows or 0

    def run_stats():
        if engine != "tpu":
            return {}
        try:
            from ballista_tpu.ops.tpu import stage_compiler

            return dict(stage_compiler.RUN_STATS)
        except Exception:  # noqa: BLE001 — diagnostics only
            return {}

    for w in range(warmups):
        t0 = time.time()
        ctx.sql(sql).collect()
        if progress:
            progress("warmup", i=w, s=round(time.time() - t0, 3), **run_stats())
    best = float("inf")
    for i in range(iters):
        t0 = time.time()
        out = ctx.sql(sql).collect()
        dt = time.time() - t0
        best = min(best, dt)
        if progress:
            progress("iter", i=i, s=round(dt, 3), **run_stats())
        assert out.num_rows > 0
    return best, rows


# ---------------------------------------------------------------- device leg

def device_leg_main(out_path: str, progress_path: str, ready_path: str,
                    parent_pid: str, attempt: str) -> None:
    """Runs in the subprocess. Phase 1: device init (the slow, fragile part —
    started before data even exists), with an event around every fragile
    statement. Phase 2: wait for the parent's data-ready JSON. Phase 3:
    warmup (cache fill) + timed iterations, full scale or SF1 fallback."""
    attempt = int(attempt)
    parent_pid = int(parent_pid)  # captured BEFORE spawn: survives re-parenting
    pf = open(progress_path, "a", buffering=1)

    def progress(event: str, **kw):
        kw.update(event=event, attempt=attempt, t=round(time.time() - T0, 1))
        pf.write(json.dumps(kw) + "\n")
        pf.flush()
        os.fsync(pf.fileno())

    progress("leg_start", pid=os.getpid())
    progress("import_jax_start")
    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        jax.config.update("jax_platforms", p)
    progress("import_jax_ok", platforms=p or "(default)")
    t0 = time.time()
    progress("devices_start")  # ← the statement that hung rounds 1-3
    d = jax.devices()[0]
    progress("devices_ok", platform=d.platform, kind=d.device_kind,
             init_s=round(time.time() - t0, 1))
    import jax.numpy as jnp

    t0 = time.time()
    x = jnp.ones((256, 256), dtype=jnp.bfloat16)
    (x @ x).block_until_ready()
    progress("first_compile_ok", s=round(time.time() - t0, 1))

    def parent_alive() -> bool:
        try:
            os.kill(parent_pid, 0)
            return True
        except OSError:
            return False

    while not os.path.exists(ready_path):
        if not parent_alive():  # parent died before the sentinel: don't
            progress("orphaned")  # hold the accelerator forever
            sys.exit(3)
        time.sleep(1.0)
    ready = json.load(open(ready_path))
    now = time.time()
    use_fallback = now > ready["fallback_at"] and ready.get("fallback")
    leg_cfg = ready["fallback"] if use_fallback else ready["primary"]
    progress("data_ready_seen", scale=leg_cfg["scale"],
             fallback=bool(use_fallback))

    def run(cfg) -> float:
        sql = open(cfg["sql_path"]).read()
        best, _rows = best_time("tpu", cfg["data_dir"], sql, warmups=1,
                                iters=3, progress=progress)
        return best

    try:
        best = run(leg_cfg)
    except Exception as e:  # noqa: BLE001 — one retry at reduced scale
        if leg_cfg is ready.get("fallback") or not ready.get("fallback"):
            raise
        progress("full_scale_failed", error=f"{type(e).__name__}: {e}"[:300])
        leg_cfg = ready["fallback"]
        progress("retry_at_fallback", scale=leg_cfg["scale"])
        best = run(leg_cfg)
    progress("leg_done", best_s=round(best, 3), scale=leg_cfg["scale"])
    with open(out_path, "w") as f:
        json.dump({"best_s": best, "scale": leg_cfg["scale"]}, f)


def _stderr_tail(path: str, n: int = 600) -> str:
    try:
        with open(path) as f:
            return f.read().strip()[-n:] or "(empty stderr)"
    except OSError:
        return "(no stderr captured)"


def read_progress(progress_path: str) -> list[dict]:
    events = []
    try:
        with open(progress_path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        pass
    except OSError:
        pass
    return events


def spawn_leg(tmp: str, attempt: int, paths: dict) -> subprocess.Popen:
    stderr_path = os.path.join(tmp, f"leg{attempt}.stderr")
    env = dict(os.environ)
    if attempt > 1:
        # verbose relay/PJRT logging: if the claim loop is stuck, the
        # stderr tail becomes the autopsy (rust plugin + libtpu + XLA)
        env.setdefault("RUST_LOG", "info")
        env.setdefault("TPU_STDERR_LOG_LEVEL", "0")
        env.setdefault("TF_CPP_MIN_LOG_LEVEL", "0")
    with open(stderr_path, "w") as stderr_f:
        leg = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--device-leg",
             paths["out"], paths["progress"], paths["ready"],
             str(os.getpid()), str(attempt)],
            stdout=subprocess.DEVNULL, stderr=stderr_f, env=env,
        )
    log(f"device leg attempt {attempt} spawned (pid {leg.pid})")
    return leg


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--device-leg":
        device_leg_main(*sys.argv[2:7])
        return

    scale = float(os.environ.get("TPCH_SCALE", "10"))
    sf_tag = f"sf{scale:g}".replace(".", "p")
    data_dir = os.environ.get("TPCH_DATA", f"/tmp/ballista_tpch_{sf_tag}")
    fb_dir = os.environ.get("TPCH_DATA_SF1", "/tmp/ballista_tpch_sf1")
    sql_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "benchmarks", "tpch", "queries", "q1.sql")

    # spawn the device leg FIRST: device init starts at t=0 and overlaps
    # datagen + the CPU baselines below
    tmp = tempfile.mkdtemp(prefix="bench_leg_")
    paths = {
        "out": os.path.join(tmp, "leg.json"),
        "progress": os.path.join(tmp, "progress.jsonl"),
        "ready": os.path.join(tmp, "data_ready"),
    }
    attempt = 1
    leg = spawn_leg(tmp, attempt, paths)
    attempt_t0 = time.time()
    log(f"budget {DEVICE_LEG_TIMEOUT}s; init stage timeout {INIT_STAGE_TIMEOUT}s"
        f" x {INIT_ATTEMPTS} attempts")

    def kill_leg(p):
        try:
            p.send_signal(signal.SIGKILL)
            p.wait(timeout=10)
        except Exception:  # noqa: BLE001
            pass

    try:
        from ballista_tpu.testing.tpchgen import generate_tpch

        for d, s in ((data_dir, scale), (fb_dir, 1.0)):
            if s == scale and d != data_dir:
                continue
            if not os.path.isdir(os.path.join(d, "lineitem")):
                log(f"generating TPC-H sf={s:g} at {d} ...")
                t0 = time.time()
                generate_tpch(d, scale=s, files_per_table=8)
                log(f"datagen sf{s:g}: {time.time() - t0:.1f}s")

        sql = open(sql_path).read()
        log("running cpu engine baseline ...")
        cpu_t, rows = best_time("cpu", data_dir, sql, warmups=1, iters=3)
        log(f"cpu q1 sf{scale:g}: {cpu_t:.3f}s ({rows / cpu_t:,.0f} rows/s)")
        if scale != 1.0:
            cpu_t_fb, rows_fb = best_time("cpu", fb_dir, sql, warmups=1, iters=2)
            log(f"cpu q1 sf1: {cpu_t_fb:.3f}s ({rows_fb / cpu_t_fb:,.0f} rows/s)")
        else:
            cpu_t_fb, rows_fb = cpu_t, rows

        # release the leg only now: its timed iterations must not contend
        # with the CPU baseline's timed iterations on the same host (init
        # and the baseline DID overlap — the point of the early spawn).
        # fallback_at: the wall-clock beyond which the full-scale phase
        # no longer fits the window — the leg then drops to SF1.
        deadline = max(T0 + DEVICE_LEG_TIMEOUT, time.time() + DEVICE_LEG_TIMEOUT / 3)
        ready = {
            "primary": {"data_dir": data_dir, "scale": scale, "sql_path": sql_path},
            "fallback": ({"data_dir": fb_dir, "scale": 1.0, "sql_path": sql_path}
                         if scale != 1.0 else None),
            "fallback_at": deadline - FULL_SCALE_PHASE_EST,
        }
        with open(paths["ready"] + ".tmp", "w") as f:
            json.dump(ready, f)
        os.rename(paths["ready"] + ".tmp", paths["ready"])

        seen = 0
        device_error = None
        attempt_errors: list[str] = []
        devices_ok = False
        while True:
            events = read_progress(paths["progress"])
            for e in events[seen:]:
                log(f"device: {json.dumps(e)}")
                if e.get("event") == "devices_ok" and e.get("attempt") == attempt:
                    devices_ok = True
            seen = len(events)
            rc = leg.poll()
            now = time.time()
            if rc is not None:
                if rc == 0 or os.path.exists(paths["out"]):
                    # a leg that wrote its result but died in runtime
                    # teardown still produced a valid datum (ADVICE r3)
                    break
                err = (f"attempt {attempt} exited {rc}: "
                       f"{_stderr_tail(os.path.join(tmp, f'leg{attempt}.stderr'))}")
            elif not devices_ok and now - attempt_t0 > INIT_STAGE_TIMEOUT:
                kill_leg(leg)
                err = (f"attempt {attempt}: no devices_ok within "
                       f"{INIT_STAGE_TIMEOUT}s (hung statement: see trail); "
                       f"stderr: {_stderr_tail(os.path.join(tmp, f'leg{attempt}.stderr'), 300)}")
            elif now > deadline:
                if os.path.exists(paths["out"]):
                    log("leg hit deadline after writing its result; using it")
                    kill_leg(leg)
                    break
                kill_leg(leg)
                stage = events[-1]["event"] if events else "no progress at all"
                device_error = (f"device leg TIMED OUT after {round(now - T0)}s "
                                f"(budget {DEVICE_LEG_TIMEOUT}s); last progress: "
                                f"{stage}; attempts: {attempt_errors}")
                log(device_error)
                break
            else:
                time.sleep(2.0)
                continue
            # an attempt just failed (bad exit or init stall)
            log(err)
            attempt_errors.append(err)
            remaining = deadline - time.time()
            if attempt < INIT_ATTEMPTS and remaining > 120:
                attempt += 1
                devices_ok = False
                leg = spawn_leg(tmp, attempt, paths)
                attempt_t0 = time.time()
            else:
                device_error = "; ".join(attempt_errors) or "device leg failed"
                break
    except BaseException:
        kill_leg(leg)  # never leave an orphan polling for the sentinel
        raise

    tpu_t, leg_scale = 0.0, scale
    if device_error is None or os.path.exists(paths["out"]):
        try:
            with open(paths["out"]) as f:
                leg_out = json.load(f)
            tpu_t = leg_out["best_s"]
            leg_scale = leg_out.get("scale", scale)
            device_error = None
        except (OSError, ValueError, KeyError) as e:
            if device_error is None:
                device_error = f"device leg produced no output: {e}"

    # pick the CPU baseline matching the scale the device leg actually ran
    if leg_scale == scale:
        base_t, base_rows, base_tag = cpu_t, rows, sf_tag
    else:
        base_t, base_rows, base_tag = cpu_t_fb, rows_fb, "sf1"

    result = {
        "metric": f"tpch_q1_{base_tag}_rows_per_sec_per_chip",
        "unit": "rows/s",
        "cpu_rows_per_sec": round(base_rows / base_t),
    }
    if device_error is None and tpu_t > 0:
        log(f"tpu q1 {base_tag}: {tpu_t:.3f}s ({base_t / tpu_t:.1f}x)")
        result["value"] = round(base_rows / tpu_t)
        result["vs_baseline"] = round((base_rows / tpu_t) / (base_rows / base_t), 2)
        if leg_scale != scale:
            result["note"] = f"reduced-scale fallback: device ran sf{leg_scale:g}"
    else:
        # LOUD failure: never report the CPU number as the TPU number
        result["value"] = 0
        result["vs_baseline"] = 0.0
        result["device_error"] = device_error
    # partial evidence survives either way: the leg's progress trail shows
    # exactly how far the tunnel let us get (init / fill / per-iter times)
    progress_trail = read_progress(paths["progress"])
    if progress_trail:
        result["device_progress"] = progress_trail[-40:]
    print(json.dumps(result))


if __name__ == "__main__":
    main()
