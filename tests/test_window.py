"""Window functions: parser → planner → WindowExec (+ distributed path).

Oracle = pandas. Covers ranking, running/whole-partition aggregates, peers
sharing values under RANGE frames, lag/lead, empty OVER(), and execution
through the distributed standalone cluster (hash exchange on PARTITION BY).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest


@pytest.fixture()
def ctx():
    from ballista_tpu.client.context import SessionContext

    rng = np.random.default_rng(5)
    n = 5_000
    tbl = pa.table({
        "g": rng.choice(["a", "b", "c", "d"], n),
        "v": rng.integers(0, 100, n),
        "w": np.round(rng.uniform(0, 10, n), 3),
    })
    c = SessionContext()
    c.register_arrow_table("t", tbl, partitions=4)
    c._tbl = tbl
    return c


def test_row_number_rank_dense_rank(ctx):
    out = ctx.sql(
        "select g, v, row_number() over (partition by g order by v, w) rn, "
        "rank() over (partition by g order by v) rk, "
        "dense_rank() over (partition by g order by v) dr from t "
        "order by g, rn"
    ).collect().to_pandas()
    df = ctx._tbl.to_pandas()
    df = df.sort_values(["g", "v", "w"], kind="stable")
    df["rn"] = df.groupby("g").cumcount() + 1
    df["rk"] = df.groupby("g")["v"].rank(method="min").astype(int)
    df["dr"] = df.groupby("g")["v"].rank(method="dense").astype(int)
    df = df.sort_values(["g", "rn"]).reset_index(drop=True)
    assert (out.rn.values == df.rn.values).all()
    assert (out.rk.values == df.rk.values).all()
    assert (out.dr.values == df.dr.values).all()


def test_window_aggregates_running_and_whole(ctx):
    out = ctx.sql(
        "select g, v, sum(v) over (partition by g) tot, "
        "count(*) over (partition by g) c, "
        "sum(v) over (partition by g order by v) run, "
        "avg(w) over (partition by g) aw, "
        "min(v) over (partition by g order by v) mn, "
        "max(v) over (partition by g order by v) mx "
        "from t order by g, v"
    ).collect().to_pandas()
    df = ctx._tbl.to_pandas()
    df["tot"] = df.groupby("g")["v"].transform("sum")
    df["c"] = df.groupby("g")["v"].transform("size")
    df["aw"] = df.groupby("g")["w"].transform("mean")
    df = df.sort_values(["g", "v"], kind="stable").reset_index(drop=True)
    # RANGE frame: peers (equal v) share the running value
    df["run"] = df.groupby("g")["v"].cumsum()
    df["run"] = df.groupby(["g", "v"])["run"].transform("max")
    df["mn"] = df.groupby("g")["v"].cummin()
    df["mx"] = df.groupby("g")["v"].cummax()
    out = out.sort_values(["g", "v"], kind="stable").reset_index(drop=True)
    assert (out.tot.values == df.tot.values).all()
    assert (out.c.values == df.c.values).all()
    assert np.allclose(out.aw.values, df.aw.values)
    assert (out.run.values == df.run.values).all()
    assert (out.mn.values == df.mn.values).all()
    assert (out.mx.values == df.mx.values).all()


def test_lag_lead(ctx):
    out = ctx.sql(
        "select g, v, w, lag(w) over (partition by g order by v, w) p, "
        "lead(w, 2, -1.0) over (partition by g order by v, w) nx "
        "from t order by g, v, w"
    ).collect().to_pandas()
    df = ctx._tbl.to_pandas().sort_values(["g", "v", "w"], kind="stable")
    df["p"] = df.groupby("g")["w"].shift(1)
    df["nx"] = df.groupby("g")["w"].shift(-2).fillna(-1.0)
    df = df.reset_index(drop=True)
    assert np.allclose(out.p.values, df.p.values, equal_nan=True)
    assert np.allclose(out.nx.values, df.nx.values)


def test_global_window_no_partition(ctx):
    out = ctx.sql(
        "select v, row_number() over (order by v desc, w desc) rn, "
        "sum(v) over () tot from t order by rn limit 5"
    ).collect().to_pandas()
    df = ctx._tbl.to_pandas()
    assert out.tot.unique().tolist() == [df.v.sum()]
    top = df.sort_values(["v", "w"], ascending=False, kind="stable").head(5)
    assert (out.v.values == top.v.values).all()
    assert out.rn.tolist() == [1, 2, 3, 4, 5]


def test_window_distributed_standalone(tmp_path):
    """Window over the full distributed path: the PARTITION BY hash
    exchange becomes a real shuffle stage."""
    import pyarrow.parquet as pq

    from ballista_tpu.client.context import SessionContext

    rng = np.random.default_rng(9)
    n = 2_000
    tbl = pa.table({"g": rng.integers(0, 50, n), "v": rng.integers(0, 1000, n)})
    pq.write_table(tbl, str(tmp_path / "t.parquet"))
    ctx = SessionContext.standalone()
    ctx.register_parquet("t", str(tmp_path / "t.parquet"))
    out = ctx.sql(
        "select g, v, row_number() over (partition by g order by v) rn, "
        "sum(v) over (partition by g) tot from t order by g, rn"
    ).collect().to_pandas()
    df = tbl.to_pandas().sort_values(["g", "v"], kind="stable")
    df["rn"] = df.groupby("g").cumcount() + 1
    df["tot"] = df.groupby("g")["v"].transform("sum")
    df = df.sort_values(["g", "rn"]).reset_index(drop=True)
    assert (out.g.values == df.g.values).all()
    assert (out.rn.values == df.rn.values).all()
    assert (out.tot.values == df.tot.values).all()


def test_window_plan_proto_roundtrip(ctx):
    from ballista_tpu.serde import decode_plan, encode_plan

    phys = ctx.create_physical_plan(
        ctx.sql("select g, rank() over (partition by g order by v desc) r from t").plan
    )
    rt = decode_plan(encode_plan(phys))
    assert rt.display() == phys.display()


def test_window_nulls_first_ordering():
    """Per-key NULLS FIRST/LAST must be honored in window ordering."""
    from ballista_tpu.client.context import SessionContext

    tbl = pa.table({"g": ["a", "a", "a"], "v": pa.array([None, 1, 2], pa.int64())})
    ctx = SessionContext()
    ctx.register_arrow_table("t", tbl)
    out = ctx.sql(
        "select v, row_number() over (partition by g order by v nulls first) rn from t"
    ).collect().to_pandas()
    null_row = out[out.v.isna()]
    assert null_row.rn.tolist() == [1]
    out2 = ctx.sql(
        "select v, row_number() over (partition by g order by v desc) rn from t"
    ).collect().to_pandas()
    # DESC default: nulls first (SortExec convention)
    assert out2[out2.v.isna()].rn.tolist() == [1]
    assert out2[out2.v == 2].rn.tolist() == [2]


def test_lag_negative_offset_stays_in_partition():
    """A negative lag offset is a lead — and must NOT cross partitions."""
    from ballista_tpu.client.context import SessionContext

    tbl = pa.table({"g": ["a", "a", "b", "b"], "v": [1, 2, 3, 4]})
    ctx = SessionContext()
    ctx.register_arrow_table("t", tbl)
    out = ctx.sql(
        "select g, v, lag(v, -1) over (partition by g order by v) x from t order by g, v"
    ).collect().to_pandas()
    assert out.x.tolist()[0] == 2.0 or out.x.tolist()[0] == 2  # (a,1) sees (a,2)
    assert pd.isna(out.x.tolist()[1])  # (a,2): nothing after within a
    assert pd.isna(out.x.tolist()[3])  # (b,4): nothing after within b


def test_window_pruning_reads_only_needed_columns():
    from ballista_tpu.client.context import SessionContext

    ctx = SessionContext()
    ctx.register_arrow_table("t", pa.table({"a": [1], "b": [2], "c": [3], "d": [4]}))
    opt = ctx.optimize(ctx.sql("select a, row_number() over (order by a) rn from t").plan)
    assert "projection=[a]" in opt.display()


def test_rows_frames(ctx):
    """Explicit ROWS BETWEEN frames: moving aggregates match pandas rolling."""
    out = ctx.sql(
        "select g, v, w, "
        "sum(v) over (partition by g order by v, w rows between 2 preceding and current row) mv, "
        "avg(w) over (partition by g order by v, w rows between 1 preceding and 1 following) ctr, "
        "min(v) over (partition by g order by v, w rows between unbounded preceding and current row) mn, "
        "count(*) over (partition by g order by v, w rows between current row and unbounded following) rem "
        "from t order by g, v, w"
    ).collect().to_pandas()
    df = ctx._tbl.to_pandas().sort_values(["g", "v", "w"], kind="stable").reset_index(drop=True)
    gb = df.groupby("g")
    mv = gb["v"].rolling(3, min_periods=1).sum().reset_index(drop=True)
    ctr = gb["w"].rolling(3, min_periods=1, center=True).mean().reset_index(drop=True)
    mn = gb["v"].cummin().reset_index(drop=True)
    rem = gb.cumcount(ascending=False) + 1
    assert (out.mv.values == mv.values).all()
    assert np.allclose(out.ctr.values, ctr.values)
    assert (out.mn.values == mn.values).all()
    assert (out.rem.values == rem.values).all()


def test_rows_frame_proto_roundtrip(ctx):
    from ballista_tpu.serde import decode_plan, encode_plan

    phys = ctx.create_physical_plan(ctx.sql(
        "select g, sum(v) over (partition by g order by v "
        "rows between 3 preceding and 1 following) s from t"
    ).plan)
    rt = decode_plan(encode_plan(phys))
    assert rt.display() == phys.display()
    assert "ROWS BETWEEN 3 PRECEDING AND 1 FOLLOWING" in phys.display()


def test_frame_words_stay_identifiers():
    from ballista_tpu.client.context import SessionContext

    ctx2 = SessionContext()
    ctx2.register_arrow_table("t3", pa.table({"rows": [1, 2], "current": [3, 4]}))
    out = ctx2.sql("select rows, current from t3 order by rows").collect().to_pandas()
    assert out["rows"].tolist() == [1, 2]


def test_empty_frames_and_invalid_bounds():
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.errors import SqlParseError

    ctx = SessionContext()
    ctx.register_arrow_table("t5", pa.table({"v": [1, 2, 3, 4, 5]}))
    out = ctx.sql(
        "select v, count(*) over (order by v rows between 5 preceding and 3 preceding) c, "
        "sum(v) over (order by v rows between 2 following and 4 following) s "
        "from t5 order by v"
    ).collect().to_pandas()
    assert out.c.tolist() == [0, 0, 0, 1, 2]
    assert out.s.tolist()[0] == 12 and pd.isna(out.s.tolist()[4])
    for bad in (
        "rows between current row and unbounded preceding",
        "rows between unbounded following and current row",
        "rows between 1.5 preceding and current row",
    ):
        with pytest.raises(SqlParseError):
            ctx.sql(f"select sum(v) over (order by v {bad}) s from t5").collect()
