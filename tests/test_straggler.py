"""Straggler-defense machinery: chaos straggler mode, speculative
execution (first attempt wins, loser cancelled), idempotent shuffle
commits across duplicate attempts, per-task deadlines, and the
flaky-executor quarantine → probe → re-admit lifecycle.
"""

import os
import threading
import time
from types import SimpleNamespace

import pyarrow as pa
import pytest

from ballista_tpu.config import (
    CHAOS_ENABLED,
    CHAOS_MODE,
    CHAOS_PROBABILITY,
    CHAOS_SEED,
    CHAOS_STRAGGLER_DELAY_S,
    CHAOS_STRAGGLER_PARTITION,
    CHAOS_STRAGGLER_STAGE,
    DEFAULT_SHUFFLE_PARTITIONS,
    MAX_PARTITIONS_PER_TASK,
    SPECULATION_MIN_RUNTIME_S,
    SPECULATION_MULTIPLIER,
    SPECULATION_QUANTILE,
    TASK_DEADLINE_MULTIPLIER,
    TASK_DEADLINE_S,
    BallistaConfig,
)
from ballista_tpu.errors import Cancelled, ExecutionError
from ballista_tpu.executor.chaos import ChaosExec
from ballista_tpu.executor.executor import Executor, ExecutorMetadata
from ballista_tpu.executor.standalone import InProcessTaskLauncher, StandaloneCluster
from ballista_tpu.ids import new_executor_id
from ballista_tpu.plan.physical import ExecutionPlan, TaskContext
from ballista_tpu.plan.schema import DFField, DFSchema
from ballista_tpu.scheduler.metrics import InMemoryMetricsCollector
from ballista_tpu.scheduler.server import SchedulerServer
from ballista_tpu.scheduler.state.execution_graph import (
    ExecutionGraph,
    JobState,
    TaskDescription,
)
from ballista_tpu.scheduler.state.executor_manager import ExecutorManager
from ballista_tpu.shuffle.types import PartitionLocation, PartitionStats

from .conftest import tpch_query

SCHEMA = DFSchema([DFField("x", pa.int64(), False)])


class OneBatchSource(ExecutionPlan):
    """N-partition source: each partition yields one small batch."""

    def __init__(self, partitions: int = 2):
        super().__init__(SCHEMA)
        self.partitions = partitions

    def output_partition_count(self):
        return self.partitions

    def execute(self, partition, ctx):
        yield pa.RecordBatch.from_pydict({"x": [partition * 10 + i for i in range(5)]},
                                         schema=SCHEMA.to_arrow())


class SlowSource(OneBatchSource):
    def __init__(self, partitions: int = 2, delay_s: float = 0.2):
        super().__init__(partitions)
        self.delay_s = delay_s

    def execute(self, partition, ctx):
        time.sleep(self.delay_s)
        yield from super().execute(partition, ctx)


# ---------------------------------------------------------------------------
# chaos straggler mode


@pytest.fixture(autouse=True, scope="module")
def _warm_arrow():
    # pyarrow's first from_pydict costs ~0.5s of lazy init — pay it here so
    # the wall-clock assertions below measure the chaos delay, not warmup
    list(OneBatchSource(1).execute(0, TaskContext()))


class TestChaosStraggler:
    def _exec(self, chaos: ChaosExec, partition: int, ctx=None) -> float:
        ctx = ctx or TaskContext()
        t0 = time.time()
        list(chaos.execute(partition, ctx))
        return time.time() - t0

    def test_explicit_partition_delays_only_that_partition(self):
        chaos = ChaosExec(OneBatchSource(4), seed=1, probability=1.0, mode="straggler",
                          straggler_delay_s=0.3, straggler_partition=2)
        assert self._exec(chaos, 0) < 0.2
        assert self._exec(chaos, 2) >= 0.3

    def test_speculative_attempt_escapes_the_delay(self):
        chaos = ChaosExec(OneBatchSource(4), seed=1, probability=1.0, mode="straggler",
                          straggler_delay_s=0.3, straggler_partition=1)
        ctx = TaskContext()
        ctx.task_attempt = 1
        assert self._exec(chaos, 1, ctx) < 0.2

    def test_seeded_roll_is_deterministic_per_partition(self):
        def hit_set(seed: int) -> set:
            chaos = ChaosExec(OneBatchSource(8), seed=seed, probability=0.5,
                              mode="straggler", straggler_delay_s=0.15)
            return {p for p in range(8) if self._exec(chaos, p) >= 0.14}

        first = hit_set(7)
        assert first == hit_set(7)  # same seed → same stragglers
        assert 0 < len(first) < 8, "p=0.5 over 8 partitions should hit some, not all"

    def test_cancel_check_preempts_the_nap(self):
        chaos = ChaosExec(OneBatchSource(1), seed=1, probability=1.0, mode="straggler",
                          straggler_delay_s=30.0, straggler_partition=0)
        ctx = TaskContext()
        ctx.cancel_check = lambda: True
        t0 = time.time()
        with pytest.raises(Cancelled):
            list(chaos.execute(0, ctx))
        assert time.time() - t0 < 2.0

    def test_deadline_preempts_the_nap_with_timed_out_error(self):
        chaos = ChaosExec(OneBatchSource(1), seed=1, probability=1.0, mode="straggler",
                          straggler_delay_s=30.0, straggler_partition=0)
        ctx = TaskContext()
        ctx.deadline_at = time.time() + 0.1
        t0 = time.time()
        with pytest.raises(ExecutionError) as ei:
            list(chaos.execute(0, ctx))
        assert time.time() - t0 < 2.0
        assert getattr(ei.value, "timed_out", False)
        assert getattr(ei.value, "retryable", False)


# ---------------------------------------------------------------------------
# ExecutionGraph speculation bookkeeping


def _graph(cfg: dict | None = None, partitions: int = 4) -> ExecutionGraph:
    stage = SimpleNamespace(stage_id=1, plan=SimpleNamespace(input=None),
                            partitions=partitions, input_stage_ids=[],
                            mesh=False)
    config = BallistaConfig({MAX_PARTITIONS_PER_TASK: 1, **(cfg or {})})
    return ExecutionGraph("job-1", "", "session-1", [stage], config)


SPEC_CFG = {SPECULATION_QUANTILE: 0.5, SPECULATION_MIN_RUNTIME_S: 0.05,
            SPECULATION_MULTIPLIER: 1.5}


def _locs(partition: int) -> list[PartitionLocation]:
    return [PartitionLocation(map_partition=partition, job_id="job-1", stage_id=1,
                              output_partition=0, executor_id="X",
                              path=f"/tmp/data-{partition}.arrow",
                              stats=PartitionStats(num_rows=1, num_bytes=10))]


class TestSpeculation:
    def _run_to_last_task(self, g: ExecutionGraph):
        """Pop 4 single-partition tasks; complete all but the last."""
        tasks = [g.pop_next_task("A") for _ in range(4)]
        for t in tasks[:3]:
            g.update_task_status(t.task_id, 1, 0, "success", t.partitions,
                                 _locs(t.partitions[0]))
        # unit test completes instantly; give the trigger a real median
        g.stages[1].task_durations = [0.2, 0.2, 0.2]
        return tasks[3]

    def test_candidates_and_register(self):
        g = _graph(SPEC_CFG)
        last = self._run_to_last_task(g)
        cands = g.speculation_candidates(now=time.time() + 10)
        assert cands == [(1, last.task_id, "A")]
        dup = g.register_speculative(1, last.task_id, "B")
        assert dup is not None
        assert dup.task_attempt == 1
        assert dup.partitions == last.partitions
        # no double-speculation of the same slice
        assert g.speculation_candidates(now=time.time() + 10) == []
        assert g.register_speculative(1, last.task_id, "C") is None

    def test_speculative_attempt_wins_and_loser_is_cancelled(self):
        g = _graph(SPEC_CFG)
        last = self._run_to_last_task(g)
        dup = g.register_speculative(1, last.task_id, "B")
        events = g.update_task_status(dup.task_id, 1, 0, "success", dup.partitions,
                                      _locs(dup.partitions[0]))
        assert "job_finished" in events
        assert g.status is JobState.SUCCESSFUL
        assert g.drain_cancelled_tasks() == [("A", last.task_id, 1)]
        # the loser's late failure report must not disturb the finished job
        events = g.update_task_status(last.task_id, 1, 0, "failed", last.partitions,
                                      [], error="cancelled late")
        assert events == []
        assert g.status is JobState.SUCCESSFUL

    def test_original_wins_and_speculative_loser_is_cancelled(self):
        g = _graph(SPEC_CFG)
        last = self._run_to_last_task(g)
        dup = g.register_speculative(1, last.task_id, "B")
        events = g.update_task_status(last.task_id, 1, 0, "success", last.partitions,
                                      _locs(last.partitions[0]))
        assert "job_finished" in events
        assert g.drain_cancelled_tasks() == [("B", dup.task_id, 1)]
        # first-wins: the loser's locations must not replace the winner's
        committed = g.stages[1].completed[last.partitions[0]]
        late = g.update_task_status(dup.task_id, 1, 0, "success", dup.partitions,
                                    _locs(dup.partitions[0]))
        assert late == []
        assert g.stages[1].completed[last.partitions[0]] is committed

    def test_failed_original_leaves_speculative_rival_sole_owner(self):
        g = _graph(SPEC_CFG)
        last = self._run_to_last_task(g)
        dup = g.register_speculative(1, last.task_id, "B")
        g.update_task_status(last.task_id, 1, 0, "failed", last.partitions, [],
                             error="boom", retryable=True)
        stage = g.stages[1]
        # the slice is still covered by the rival: nothing re-pended
        assert stage.pending == []
        assert stage.running[dup.task_id].rival_task_id is None
        events = g.update_task_status(dup.task_id, 1, 0, "success", dup.partitions,
                                      _locs(dup.partitions[0]))
        assert "job_finished" in events


class TestDeadlines:
    def test_adaptive_deadline_from_observed_durations(self):
        g = _graph({TASK_DEADLINE_S: 0.0, TASK_DEADLINE_MULTIPLIER: 3.0})
        t1 = g.pop_next_task("A")
        assert t1.deadline_seconds == 0.0  # < 3 samples: no deadline yet
        g.stages[1].task_durations = [1.0, 2.0, 3.0]
        t2 = g.pop_next_task("A")
        assert t2.deadline_seconds == pytest.approx(6.0)  # 3.0 × median 2.0

    def test_deadline_floor_applies_without_samples(self):
        g = _graph({TASK_DEADLINE_S: 7.5})
        assert g.pop_next_task("A").deadline_seconds == pytest.approx(7.5)

    def test_expire_overdue_tasks_repends_and_queues_cancel(self):
        g = _graph({TASK_DEADLINE_S: 0.1})
        t = g.pop_next_task("A")
        stage = g.stages[1]
        stage.running[t.task_id].launched_at -= 60  # far past deadline+grace
        expired, job_failed = g.expire_overdue_tasks(time.time())
        assert expired == [("A", t.task_id, 1)]
        assert not job_failed
        assert t.partitions[0] in stage.pending
        assert ("A", t.task_id, 1) in g.drain_cancelled_tasks()

    def test_executor_enforces_deadline_between_partitions(self, tmp_path):
        from ballista_tpu.shuffle.writer import ShuffleWriterExec

        plan = ShuffleWriterExec(SlowSource(partitions=3, delay_s=0.2),
                                 "job-d", 1, 0, None)
        ex = Executor(str(tmp_path), ExecutorMetadata(id="ex-dl"))
        task = TaskDescription(job_id="job-d", stage_id=1, stage_attempt=0, task_id=9,
                               partitions=[0, 1, 2], plan=plan, session_id="s",
                               deadline_seconds=0.1)
        result = ex.execute_task(task, BallistaConfig())
        assert result.state == "failed"
        assert result.retryable
        assert result.timed_out
        assert "deadline" in result.error


# ---------------------------------------------------------------------------
# idempotent shuffle commit


class TestShuffleCommitIdempotence:
    def _write(self, tmp_path, task_id: str, sort: bool):
        from ballista_tpu.plan.expressions import Column
        from ballista_tpu.shuffle.writer import ShuffleWriterExec

        plan = ShuffleWriterExec(OneBatchSource(1), "job-s", 2, 4, [Column("x")],
                                 sort_shuffle=sort)
        ctx = TaskContext(task_id=task_id, work_dir=str(tmp_path))
        return list(plan.execute(0, ctx))

    @pytest.mark.parametrize("sort", [True, False], ids=["sort", "hash"])
    def test_duplicate_attempts_commit_disjoint_complete_sets(self, tmp_path, sort):
        meta_a = self._write(tmp_path, "11", sort)[0]
        meta_b = self._write(tmp_path, "12", sort)[0]
        paths_a = set(meta_a.column(1).to_pylist())
        paths_b = set(meta_b.column(1).to_pylist())
        assert paths_a and paths_b
        assert paths_a.isdisjoint(paths_b), "attempts must never share files"
        for p in paths_a | paths_b:
            assert os.path.exists(p)
        # the commit is atomic: no temp files survive
        leftovers = [os.path.join(r, f) for r, _, fs in os.walk(tmp_path)
                     for f in fs if f.endswith(".tmp")]
        assert leftovers == []
        # both attempts produced identical row counts (idempotence)
        assert meta_a.column(2).to_pylist() == meta_b.column(2).to_pylist()

    def test_sort_layout_index_committed_per_attempt(self, tmp_path):
        from ballista_tpu.shuffle import paths as shuffle_paths

        meta = self._write(tmp_path, "21", sort=True)[0]
        data_path = meta.column(1).to_pylist()[0]
        assert "-21.arrow" in data_path, "sort data file must be attempt-unique"
        assert os.path.exists(shuffle_paths.index_path(data_path))


# ---------------------------------------------------------------------------
# executor health scoring + quarantine


def _manager(**kw) -> ExecutorManager:
    defaults = dict(quarantine_threshold=0.5, quarantine_min_events=2.0,
                    health_half_life_s=60.0, probe_backoff_s=0.05)
    defaults.update(kw)
    em = ExecutorManager(**defaults)
    for eid in ("A", "B"):
        em.register(ExecutorMetadata(id=eid, vcores=2))
    return em


class TestQuarantine:
    def test_failures_quarantine_and_offers_stop(self):
        em = _manager()
        assert em.record_task_result("A", ok=False) is None  # below min_events
        assert em.record_task_result("A", ok=False) == "quarantined"
        assert em.get("A").health_state == "quarantined"
        assert em.quarantined_count() == 1
        # regular binding paths all exclude A
        assert all(eid == "B" for eid, _ in em.reserve_slots(8))
        assert em.reserve_one_avoiding({"B"}) is None
        assert em.health_snapshot()["A"]["state"] == "quarantined"

    def test_probe_then_readmit(self):
        em = _manager()
        em.record_task_result("A", ok=False)
        em.record_task_result("A", ok=False)
        assert em.probe_reservations(now=time.time()) == []  # backoff not elapsed
        time.sleep(0.06)
        probes = em.probe_reservations()
        assert probes == [("A", 1)]
        assert em.get("A").health_state == "probation"
        assert em.probe_reservations() == []  # one probe in flight, not two
        assert em.record_task_result("A", ok=True) == "readmitted"
        assert em.get("A").health_state == "healthy"
        assert any(eid == "A" for eid, _ in em.reserve_slots(8))

    def test_failed_probe_requarantines(self):
        em = _manager()
        em.record_task_result("A", ok=False)
        em.record_task_result("A", ok=False)
        time.sleep(0.06)
        assert em.probe_reservations() == [("A", 1)]
        assert em.record_task_result("A", ok=False, timed_out=True) == "requarantined"
        assert em.get("A").health_state == "quarantined"

    def test_pull_mode_probe_gate(self):
        em = _manager()
        em.record_task_result("A", ok=False)
        em.record_task_result("A", ok=False)
        assert em.take_slots("A", 4) == 0  # quarantined, backoff pending
        time.sleep(0.06)
        assert em.take_slots("A", 4) == 1  # exactly one probe task
        assert em.get("A").health_state == "probation"
        assert em.take_slots("A", 4) == 0

    def test_cancel_probe_returns_slot_and_state(self):
        em = _manager()
        em.record_task_result("A", ok=False)
        em.record_task_result("A", ok=False)
        time.sleep(0.06)
        em.probe_reservations()
        free_before = em.get("A").free_slots
        em.cancel_probe("A")
        assert em.get("A").health_state == "quarantined"
        assert em.get("A").free_slots == free_before + 1

    def test_threshold_zero_disables_quarantine(self):
        em = _manager(quarantine_threshold=0.0)
        for _ in range(10):
            assert em.record_task_result("A", ok=False) is None
        assert em.get("A").health_state == "healthy"

    def test_successes_decay_the_failure_rate(self):
        em = _manager(quarantine_min_events=4.0)
        for _ in range(6):
            em.record_task_result("A", ok=True)
        assert em.record_task_result("A", ok=False) is None  # 1/7 failure rate
        assert em.get("A").health_state == "healthy"


# ---------------------------------------------------------------------------
# end-to-end: chaos straggler beaten by a speculative attempt


class RecordingLauncher(InProcessTaskLauncher):
    def __init__(self, executors):
        super().__init__(executors)
        self.launches = []  # (executor_id, task_id, stage_id, task_attempt, partitions)
        self._rec_lock = threading.Lock()

    def launch(self, executor_id, tasks, server):
        with self._rec_lock:
            for t in tasks:
                self.launches.append(
                    (executor_id, t.task_id, t.stage_id, t.task_attempt, list(t.partitions)))
        super().launch(executor_id, tasks, server)


def test_speculation_beats_chaos_straggler_e2e(tpch_dir):
    """One partition of the first stage sleeps 8s under chaos straggler
    mode; a speculative duplicate on the OTHER executor must win long
    before that, and exactly one attempt's shuffle files are committed."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    # partition 1 exists only in multi-partition stages (the scan has 2
    # files); the 1-partition final stage can never reach the completion
    # quantile, so a straggler there would be unrescuable by design
    straggler_partition = 1
    cfg = BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 4,
        MAX_PARTITIONS_PER_TASK: 1,  # one task per partition, else nothing to duplicate
        CHAOS_ENABLED: True,
        CHAOS_MODE: "straggler",
        CHAOS_SEED: 42,
        CHAOS_PROBABILITY: 1.0,
        CHAOS_STRAGGLER_DELAY_S: 8.0,
        CHAOS_STRAGGLER_PARTITION: straggler_partition,
        CHAOS_STRAGGLER_STAGE: 1,  # the final stage's reader re-drives the same
        # partition indices in a single unspeculatable task — pin to the scan stage
        SPECULATION_QUANTILE: 0.5,
        SPECULATION_MIN_RUNTIME_S: 0.2,
        SPECULATION_MULTIPLIER: 1.5,
    })
    ctx = SessionContext(cfg)
    register_tpch(ctx, tpch_dir)
    cluster = StandaloneCluster(num_executors=2, vcores=2, config=cfg)
    old_launcher = cluster.launcher
    launcher = RecordingLauncher(cluster.executors)
    cluster.scheduler.launcher = launcher
    cluster.launcher = launcher
    old_launcher.pool.shutdown(wait=False)
    try:
        scheduler = cluster.scheduler
        session_id = scheduler.sessions.create_or_update(cfg.to_key_value_pairs(), "s-spec")
        t0 = time.time()
        job_id = scheduler.submit_sql(tpch_query(6), session_id)
        status = scheduler.wait_for_job(job_id, timeout=60)
        elapsed = time.time() - t0
        assert status["state"] == "successful", status.get("error")
        assert elapsed < 6.5, f"took {elapsed:.1f}s — speculation did not beat the 8s straggler"

        with scheduler._jobs_lock:
            g = scheduler.jobs[job_id]
        # the straggling slice was duplicated: find the stage that actually
        # got a speculative attempt and check the winner differs
        spec = [l for l in launcher.launches if l[3] > 0]
        assert spec, "no speculative attempt was ever launched"
        ex_spec, spec_task, spec_stage, _, spec_parts = spec[0]
        orig = [l for l in launcher.launches
                if l[2] == spec_stage and l[3] == 0 and straggler_partition in l[4]]
        assert orig, "no original attempt recorded for the straggler slice"
        ex_orig, orig_task = orig[0][0], orig[0][1]
        assert ex_spec != ex_orig, "speculative attempt must land on a DIFFERENT executor"

        committed = g.stages[spec_stage].completed[straggler_partition]
        assert committed, "straggler partition has no committed locations"
        winner_ids = {t for t in (spec_task, orig_task)
                      if any(f"-{t}." in os.path.basename(l.path)
                             or f"data-{t}." in os.path.basename(l.path)
                             for l in committed)}
        assert winner_ids == {spec_task}, (
            f"committed files {[l.path for l in committed]} should belong to the "
            f"speculative winner {spec_task}, not the straggler {orig_task}")
        # exactly ONE attempt's files committed for the slice
        for p in spec_parts:
            locs = g.stages[spec_stage].completed.get(p, [])
            tids = {os.path.basename(l.path) for l in locs}
            assert len({t.rsplit("-", 1)[-1] for t in tids}) <= 1
        # the loser aborts asynchronously (its cancel lands mid-straggle and
        # the writer then unlinks its own .tmp) — give it a moment to sweep up
        deadline = time.time() + 5.0
        while True:
            leftovers = [os.path.join(r, f) for r, _, fs in os.walk(cluster.work_dir)
                         for f in fs if f.endswith(".tmp")]
            if not leftovers or time.time() > deadline:
                break
            time.sleep(0.1)
        assert leftovers == []
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# end-to-end: flaky executor quarantined, probed, re-admitted


class FlakyLauncher(InProcessTaskLauncher):
    """Synthesizes retryable failures for the victim until the scheduler
    quarantines it; from then on (probe included) its tasks run for real —
    modelling a flaky executor that recovered while benched."""

    def __init__(self, executors, victim_id):
        super().__init__(executors)
        self.victim_id = victim_id
        self.synthetic_failures = 0
        self.injecting = True

    def launch(self, executor_id, tasks, server):
        from ballista_tpu.executor.executor import TaskResult

        if executor_id == self.victim_id and self.injecting:
            slot = server.executors.get(executor_id)
            if slot is not None and slot.health_state != "healthy":
                self.injecting = False  # benched: recover for the probe
            else:
                for t in tasks:
                    self.synthetic_failures += 1
                    server.update_task_status(executor_id, [TaskResult(
                        task_id=t.task_id, job_id=t.job_id, stage_id=t.stage_id,
                        stage_attempt=t.stage_attempt, partitions=list(t.partitions),
                        state="failed", error="flaky: injected fault", retryable=True,
                    )])
                return
        super().launch(executor_id, tasks, server)


def test_quarantine_probe_readmit_e2e(tpch_dir):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 4, MAX_PARTITIONS_PER_TASK: 1})
    ctx = SessionContext(cfg)
    register_tpch(ctx, tpch_dir)
    import tempfile

    wd = tempfile.mkdtemp(prefix="bt-quarantine-")
    # bias distribution fills the executor with the most free slots first:
    # the extra vcores steer the first tasks onto the victim deterministically
    victim = Executor(wd, ExecutorMetadata(id=str(new_executor_id()), vcores=4), config=cfg)
    healthy = Executor(wd, ExecutorMetadata(id=str(new_executor_id()), vcores=2), config=cfg)
    launcher = FlakyLauncher({victim.metadata.id: victim, healthy.metadata.id: healthy},
                             victim.metadata.id)
    metrics = InMemoryMetricsCollector()
    scheduler = SchedulerServer(launcher, metrics,
                                quarantine_threshold=0.5, quarantine_min_events=1.0,
                                probe_backoff_s=0.5, sweep_interval_s=0.2)
    scheduler.start()
    scheduler.register_executor(victim.metadata)
    scheduler.register_executor(healthy.metadata)
    try:
        session_id = scheduler.sessions.create_or_update(cfg.to_key_value_pairs(), "s-flaky")
        job_id = scheduler.submit_sql(tpch_query(6), session_id)
        status = scheduler.wait_for_job(job_id, timeout=60)
        assert status["state"] == "successful", status.get("error")
        assert launcher.synthetic_failures >= 1, "victim never exercised — test vacuous"
        assert scheduler.executors.get(victim.metadata.id).health_state == "quarantined"
        assert scheduler.executors.quarantined_count() == 1

        # wait out the probe backoff, then give the scheduler work again:
        # the probe task runs for real (probation) and re-admits the victim
        time.sleep(0.6)
        job2 = scheduler.submit_sql(tpch_query(6), session_id)
        status2 = scheduler.wait_for_job(job2, timeout=60)
        assert status2["state"] == "successful", status2.get("error")
        deadline = time.time() + 10
        while time.time() < deadline:
            if scheduler.executors.get(victim.metadata.id).health_state == "healthy":
                break
            time.sleep(0.1)
        assert scheduler.executors.get(victim.metadata.id).health_state == "healthy", (
            scheduler.executors.health_snapshot())
        assert scheduler.executors.quarantined_count() == 0
        # the gauge saw the quarantine while it lasted
        assert metrics.quarantined_executors == 0
    finally:
        scheduler.stop()
        launcher.pool.shutdown(wait=False)
