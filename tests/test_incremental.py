"""Incremental materialized views: append ingestion, delta-maintained
result refresh, continuous queries.

The contract under test is byte equivalence: a maintained refresh (delta
query merged into cached aggregation state) must return exactly the bytes
a from-scratch execution of the same statement returns — across nulls,
strings, duplicate group keys, global (no-GROUP-BY) aggregates, and the
one-side delta-join — while the serving counters prove the cheap path
actually ran. Ineligible shapes must fall back with a recorded reason,
retention bounds must fold (never drop) delta data, and continuous
queries must push a fresh result per version bump.
"""

import hashlib
import threading
import time

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.client.context import SessionContext
from ballista_tpu.config import (
    INGEST_DELTA_RETAIN_VERSIONS,
    SERVING_INCREMENTAL,
    SERVING_RESULT_CACHE,
    BallistaConfig,
)
from ballista_tpu.errors import PlanningError
from ballista_tpu.serving.incremental import DeltaRegistry, analyze_plan
from ballista_tpu.sql.optimizer import optimize
from ballista_tpu.sql.parser import parse_sql
from ballista_tpu.sql.planner import SqlPlanner


def _fingerprint(tbl: pa.Table) -> str:
    """Order-independent byte fingerprint (same bar as dev/qps_exercise)."""
    rows = sorted(str(r) for r in tbl.to_pylist())
    return hashlib.sha256("\n".join(rows).encode()).hexdigest()


def _write(tmp_path, name: str, tbl: pa.Table) -> str:
    d = tmp_path / name
    d.mkdir()
    pq.write_table(tbl, str(d / f"{name}.parquet"))
    return str(d)


BASE_T = pa.table({
    "k": ["a", "b", "a", None, "c", "b"],
    "v": [1, 2, 3, 4, None, 6],
    "s": ["x", "y", "z", "x", None, "y"],
})
DELTA_T = pa.table({
    "k": ["a", None, "d", "b"],
    "v": [10, 20, None, 40],
    "s": [None, "x", "q", "y"],
})
DIM_U = pa.table({"k": ["a", "b", "c", "d"], "w": [100, 200, 300, 400]})


def _incremental_cfg() -> BallistaConfig:
    cfg = BallistaConfig()
    # the result cache (and with it the maintenance ladder) is opt-in
    cfg.set(SERVING_RESULT_CACHE, "true")
    return cfg


@pytest.fixture()
def cluster_ctx(tmp_path):
    ctx = SessionContext.standalone(config=_incremental_cfg(), num_executors=1, vcores=2)
    ctx.register_parquet("t", _write(tmp_path, "t", BASE_T))
    ctx.register_parquet("u", _write(tmp_path, "u", DIM_U))
    yield ctx
    ctx.shutdown()


def _sched(ctx):
    return ctx._cluster.scheduler


def _inc_counters(ctx) -> dict:
    return _sched(ctx).serving.snapshot()["incremental"]


# ---------------------------------------------------------------------------
# eligibility analysis (no cluster)


class TestEligibility:
    def _physical(self, ctx, sql):
        return ctx.create_physical_plan(
            optimize(SqlPlanner(ctx.catalog).plan_query(parse_sql(sql))))

    @pytest.fixture()
    def local_ctx(self, tmp_path):
        ctx = SessionContext()
        ctx.register_parquet("t", _write(tmp_path, "t", BASE_T))
        ctx.register_parquet("u", _write(tmp_path, "u", DIM_U))
        ctx.register_parquet("f", _write(tmp_path, "f", pa.table(
            {"k": ["a", "b"], "x": [1.5, 2.5]})))
        return ctx

    def test_distributive_aggregate_is_maintainable(self, local_ctx):
        for sql in [
            "SELECT k, SUM(v) AS s FROM t GROUP BY k",
            "SELECT k, COUNT(*) AS c, MIN(v) AS lo, MAX(v) AS hi FROM t GROUP BY k",
            "SELECT k, AVG(v) AS a FROM t GROUP BY k",  # pre-decomposed sum/count
            "SELECT SUM(v) AS s FROM t",  # global aggregate, n_group == 0
            "SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k LIMIT 3",  # finisher
        ]:
            d = analyze_plan(self._physical(local_ctx, sql))
            assert d.mode == "aggregate", f"{sql}: {d.mode}/{d.reason}"
            assert d.tables == ("t",)

    def test_filter_project_is_append_maintainable(self, local_ctx):
        d = analyze_plan(self._physical(local_ctx, "SELECT k, v FROM t WHERE v > 1"))
        assert d.mode == "append" and d.tables == ("t",)

    def test_one_side_equi_join_aggregate_is_maintainable(self, local_ctx):
        d = analyze_plan(self._physical(
            local_ctx,
            "SELECT t.k, SUM(t.v) AS s FROM t JOIN u ON t.k = u.k GROUP BY t.k"))
        assert d.mode == "aggregate"
        assert set(d.tables) == {"t", "u"}

    def test_ineligible_shapes_carry_reasons(self, local_ctx):
        cases = {
            # float SUM accumulators are not bit-stable under re-association
            "SELECT k, SUM(x) AS s FROM f GROUP BY k": "float-sum",
            # welford accumulators merge nonlinearly
            "SELECT k, STDDEV(v) AS d FROM t GROUP BY k": "",
            # self-join: both sides change on one append
            "SELECT a.k, SUM(a.v) AS s FROM t a JOIN t b ON a.k = b.k "
            "GROUP BY a.k": "self-join",
            # ORDER BY changes row order under appends (append mode)
            "SELECT k, v FROM t ORDER BY v": "shape-",
        }
        for sql, want in cases.items():
            d = analyze_plan(self._physical(local_ctx, sql))
            assert d.mode == "none", f"{sql} unexpectedly {d.mode}"
            assert want in d.reason, f"{sql}: reason={d.reason!r}"


# ---------------------------------------------------------------------------
# the delta registry: retention, folding, reset


class TestDeltaRegistry:
    def _batches(self, n_rows: int):
        return pa.table({"k": ["x"] * n_rows, "v": list(range(n_rows))}).to_batches()

    def test_range_returns_exactly_the_appended_versions(self):
        reg = DeltaRegistry()
        reg.append("t", 2, self._batches(3))
        reg.append("t", 3, self._batches(5))
        got, why = reg.range("t", 1, 3)
        assert why == "" and sum(b.num_rows for b in got) == 8
        got, why = reg.range("t", 2, 3)
        assert sum(b.num_rows for b in got) == 5

    def test_missing_version_is_unavailable_not_wrong(self):
        reg = DeltaRegistry()
        reg.append("t", 5, self._batches(1))
        got, why = reg.range("t", 3, 5)  # version 4 bumped without a delta
        assert got is None and why == "delta-unavailable"

    def test_version_cap_folds_oldest_to_parquet(self, tmp_path):
        cfg = BallistaConfig()
        cfg.set(INGEST_DELTA_RETAIN_VERSIONS, "2")
        cfg.set("ballista.ingest.compaction.dir", str(tmp_path / "spool"))
        reg = DeltaRegistry(cfg)
        for v in range(1, 6):
            reg.append("t", v, self._batches(4))
        snap = reg.snapshot()
        assert snap["folded_versions"] == 3
        assert snap["retained_versions"] == 2
        # folded data is table content: the view still carries every row
        view = reg.view()["t"]
        folded_rows = sum(pq.read_table(f).num_rows for f in view.folded_files)
        live_rows = sum(b.num_rows for b in view.batches)
        assert folded_rows + live_rows == 20
        # a maintained refresh reaching past the fold horizon must decline
        got, why = reg.range("t", 1, 5)
        assert got is None and why == "delta-compacted"
        # ... but the still-retained tail serves
        got, why = reg.range("t", 3, 5)
        assert got is not None and sum(b.num_rows for b in got) == 8

    def test_byte_budget_folds_but_never_drops(self, tmp_path):
        cfg = BallistaConfig()
        cfg.set("ballista.ingest.delta.retained.max.bytes", "1")  # everything folds
        cfg.set("ballista.ingest.compaction.dir", str(tmp_path / "spool"))
        reg = DeltaRegistry(cfg)
        reg.append("t", 1, self._batches(100))
        reg.append("t", 2, self._batches(100))
        view = reg.view()["t"]
        total = sum(pq.read_table(f).num_rows for f in view.folded_files) + sum(
            b.num_rows for b in view.batches)
        assert total == 200, "budget pressure must compact, never drop rows"
        assert reg.retained.nbytes() <= reg.retain_bytes or reg.retained.nbytes() == 0

    def test_reset_clears_lineage(self):
        reg = DeltaRegistry()
        reg.append("t", 1, self._batches(2))
        reg.reset("t")
        assert reg.empty()
        assert reg.range("t", 0, 1)[0] is None


# ---------------------------------------------------------------------------
# maintained refresh == full recompute, byte for byte


class TestMaintainedParity:
    AGG = ("SELECT k, SUM(v) AS sv, COUNT(*) AS c, COUNT(s) AS cs, "
           "MIN(v) AS lo, MAX(s) AS hi, AVG(v) AS av FROM t GROUP BY k ORDER BY k")

    def test_aggregate_maintained_and_byte_identical(self, cluster_ctx):
        stmt = cluster_ctx.prepare(self.AGG)
        stmt.execute()  # bootstrap: caches accumulator state
        assert _inc_counters(cluster_ctx)["bootstraps"] == 1
        cluster_ctx.append("t", DELTA_T)
        maintained = stmt.execute()
        counters = _inc_counters(cluster_ctx)
        assert counters["maintained"] == 1
        assert counters["recomputes"] == 0
        full = cluster_ctx.sql(self.AGG).collect()
        assert _fingerprint(maintained) == _fingerprint(full)
        assert maintained.to_pydict() == full.to_pydict()

    def test_repeated_appends_keep_maintaining(self, cluster_ctx):
        stmt = cluster_ctx.prepare("SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k")
        stmt.execute()
        for i in range(3):
            cluster_ctx.append("t", pa.table(
                {"k": ["a", "e"], "v": [i, 2 * i], "s": [None, "n"]}))
            got = stmt.execute()
            full = cluster_ctx.sql(
                "SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k").collect()
            assert _fingerprint(got) == _fingerprint(full), f"append {i} diverged"
        assert _inc_counters(cluster_ctx)["maintained"] == 3

    def test_global_aggregate_no_group_by(self, cluster_ctx):
        sql = "SELECT SUM(v) AS s, COUNT(*) AS c FROM t"
        stmt = cluster_ctx.prepare(sql)
        stmt.execute()
        cluster_ctx.append("t", DELTA_T)
        got = stmt.execute()
        assert _inc_counters(cluster_ctx)["maintained"] == 1
        assert got.to_pydict() == cluster_ctx.sql(sql).collect().to_pydict()

    def test_delta_join_one_appended_side(self, cluster_ctx):
        sql = ("SELECT t.k, SUM(t.v * u.w) AS s FROM t JOIN u ON t.k = u.k "
               "GROUP BY t.k ORDER BY t.k")
        stmt = cluster_ctx.prepare(sql)
        stmt.execute()
        cluster_ctx.append("t", DELTA_T)
        got = stmt.execute()
        assert _inc_counters(cluster_ctx)["maintained"] == 1
        full = cluster_ctx.sql(sql).collect()
        assert _fingerprint(got) == _fingerprint(full)

    def test_filter_project_appends_in_place(self, cluster_ctx):
        sql = "SELECT k, v FROM t WHERE v > 1"
        stmt = cluster_ctx.prepare(sql)
        stmt.execute()
        cluster_ctx.append("t", DELTA_T)
        got = stmt.execute()
        counters = _inc_counters(cluster_ctx)
        assert counters["maintained"] == 1
        full = cluster_ctx.sql(sql).collect()
        assert _fingerprint(got) == _fingerprint(full)

    def test_state_survives_result_cache_loss(self, cluster_ctx):
        """Result cache evicted but accumulator state current: the refresh
        renders the finisher locally, with no dispatched job."""
        sql = "SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k"
        stmt = cluster_ctx.prepare(sql)
        stmt.execute()
        cluster_ctx.append("t", DELTA_T)
        want = stmt.execute().to_pydict()
        _sched(cluster_ctx).serving.result_cache.clear()
        got = stmt.execute()
        assert got.to_pydict() == want
        assert _inc_counters(cluster_ctx)["state_renders"] == 1


# ---------------------------------------------------------------------------
# fallback behavior


class TestFallback:
    def test_ineligible_recomputes_with_reason(self, cluster_ctx):
        sql = ("SELECT a.k, SUM(a.v) AS s FROM t a JOIN t b ON a.k = b.k "
               "GROUP BY a.k ORDER BY a.k")
        stmt = cluster_ctx.prepare(sql)
        stmt.execute()
        cluster_ctx.append("t", DELTA_T)
        got = stmt.execute()
        counters = _inc_counters(cluster_ctx)
        assert counters["maintained"] == 0
        assert "self-join" in counters["recompute_reasons"]
        full = cluster_ctx.sql(sql).collect()
        assert _fingerprint(got) == _fingerprint(full)
        mode = next(iter(counters["modes"].values()))
        assert mode == {"mode": "none", "reason": "self-join"}

    def test_compacted_delta_falls_back_but_stays_correct(self, tmp_path):
        cfg = _incremental_cfg()
        cfg.set(INGEST_DELTA_RETAIN_VERSIONS, "1")
        ctx = SessionContext.standalone(config=cfg, num_executors=1, vcores=2)
        try:
            ctx.register_parquet("t", _write(tmp_path, "t", BASE_T))
            sql = "SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k"
            stmt = ctx.prepare(sql)
            stmt.execute()
            # two appends before the refresh: the older one folds to parquet,
            # so the needed range is no longer fully in memory
            ctx.append("t", DELTA_T)
            ctx.append("t", DELTA_T)
            got = stmt.execute()
            counters = _inc_counters(ctx)
            assert counters["recompute_reasons"].get("delta-compacted", 0) >= 1
            full = ctx.sql(sql).collect()
            assert _fingerprint(got) == _fingerprint(full)
        finally:
            ctx.shutdown()

    def test_incremental_knob_off_still_serves_appends(self, tmp_path):
        cfg = _incremental_cfg()
        cfg.set(SERVING_INCREMENTAL, "false")
        ctx = SessionContext.standalone(config=cfg, num_executors=1, vcores=2)
        try:
            ctx.register_parquet("t", _write(tmp_path, "t", BASE_T))
            sql = "SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k"
            stmt = ctx.prepare(sql)
            stmt.execute()
            ctx.append("t", DELTA_T)
            got = stmt.execute()
            counters = _inc_counters(ctx)
            assert counters["maintained"] == 0 and counters["bootstraps"] == 0
            assert got.to_pydict() == ctx.sql(sql).collect().to_pydict()
        finally:
            ctx.shutdown()

    def test_ddl_resets_delta_lineage(self, cluster_ctx, tmp_path):
        stmt = cluster_ctx.prepare("SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k")
        stmt.execute()
        cluster_ctx.append("t", DELTA_T)
        sched = _sched(cluster_ctx)
        assert not sched.ingest.empty()
        sched._on_catalog_change("t")
        assert sched.ingest.empty(), "DDL must orphan retained deltas"


# ---------------------------------------------------------------------------
# linearizability under concurrent appends


class TestConcurrency:
    def test_refreshes_are_monotonic_under_concurrent_appends(self, cluster_ctx):
        """Appends race the refresh loop. Every served COUNT must be a value
        the table actually passed through (4-row snapshots monotonically
        growing by 2), and the final quiesced refresh must equal a full
        recompute byte-for-byte — no refresh may mix state across
        versions."""
        sql = "SELECT COUNT(*) AS n, SUM(v) AS s FROM t"
        stmt = cluster_ctx.prepare(sql)
        stmt.execute()
        n_appends = 12
        done = threading.Event()

        def feeder():
            for i in range(n_appends):
                cluster_ctx.append("t", pa.table(
                    {"k": ["p", "q"], "v": [i, i], "s": ["w", None]}))
                time.sleep(0.005)
            done.set()

        t = threading.Thread(target=feeder)
        t.start()
        counts = []
        while not done.is_set():
            counts.append(stmt.execute().to_pydict()["n"][0])
        t.join()
        base_rows = BASE_T.num_rows
        valid = {base_rows + 2 * i for i in range(n_appends + 1)}
        assert set(counts) <= valid, f"served a count outside any real version: {counts}"
        assert counts == sorted(counts), "refresh results went backwards"
        final = stmt.execute()
        full = cluster_ctx.sql(sql).collect()
        assert _fingerprint(final) == _fingerprint(full)


# ---------------------------------------------------------------------------
# continuous queries


class TestContinuousQueries:
    def test_push_on_every_bump(self, cluster_ctx):
        stmt = cluster_ctx.prepare("SELECT COUNT(*) AS n FROM t")
        sub = stmt.subscribe()
        try:
            first = sub.next(timeout=30)
            assert first.to_pydict()["n"] == [BASE_T.num_rows]
            cluster_ctx.append("t", DELTA_T)
            nxt = sub.next(timeout=30)
            assert nxt.to_pydict()["n"] == [BASE_T.num_rows + DELTA_T.num_rows]
        finally:
            sub.close()
        snap = _sched(cluster_ctx).subscriptions.snapshot()
        assert snap["active"] == 0 and snap["pushed"] >= 2

    def test_unrelated_table_does_not_wake_subscription(self, cluster_ctx):
        stmt = cluster_ctx.prepare("SELECT COUNT(*) AS n FROM u")
        sub = stmt.subscribe()
        try:
            sub.next(timeout=30)  # warm snapshot
            cluster_ctx.append("t", DELTA_T)  # different table
            import queue as _q

            with pytest.raises(Exception):
                # bounded wait; nothing should arrive for a t-only bump
                raw = sub._sub.queue.get(timeout=1.0)
                raise AssertionError(f"unexpected push: {raw}")
        finally:
            sub.close()

    def test_unknown_statement_rejected(self, cluster_ctx):
        cluster_ctx.prepare("SELECT COUNT(*) AS n FROM t")  # warm the cluster
        with pytest.raises(Exception):
            _sched(cluster_ctx).subscribe_statement("no-such-stmt", None, "")


# ---------------------------------------------------------------------------
# local mode append


class TestLocalAppend:
    def test_local_append_overlays_provider(self, tmp_path):
        ctx = SessionContext()
        ctx.register_parquet("t", _write(tmp_path, "t", BASE_T))
        before = ctx.sql("SELECT COUNT(*) AS n FROM t").collect().to_pydict()["n"][0]
        out = ctx.append("t", DELTA_T)
        assert out == {"table": "t", "version": 1, "rows": DELTA_T.num_rows}
        after = ctx.sql("SELECT COUNT(*) AS n FROM t").collect().to_pydict()["n"][0]
        assert after == before + DELTA_T.num_rows
        # aggregates see the merged view
        got = ctx.sql("SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k").collect()
        merged = pa.concat_tables([BASE_T, DELTA_T.cast(BASE_T.schema)])
        want = merged.group_by("k").aggregate([("v", "sum")])
        assert dict(zip(got.to_pydict()["k"], got.to_pydict()["s"])) == dict(
            zip(want.to_pydict()["k"], want.to_pydict()["v_sum"]))

    def test_append_conforms_by_name_and_casts(self, tmp_path):
        ctx = SessionContext()
        ctx.register_parquet("t", _write(tmp_path, "t", BASE_T))
        # reordered columns + int32 values: conformance aligns and casts
        ctx.append("t", pa.table({
            "s": pa.array(["m"]), "v": pa.array([7], pa.int32()), "k": pa.array(["e"])}))
        got = ctx.sql("SELECT v FROM t WHERE k = 'e'").collect()
        assert got.to_pydict()["v"] == [7]

    def test_append_missing_column_is_an_error(self, tmp_path):
        ctx = SessionContext()
        ctx.register_parquet("t", _write(tmp_path, "t", BASE_T))
        with pytest.raises(PlanningError, match="missing column"):
            ctx.append("t", pa.table({"k": ["e"]}))

    def test_append_unknown_table_is_an_error(self):
        ctx = SessionContext()
        with pytest.raises(PlanningError, match="not found"):
            ctx.append("nope", pa.table({"k": ["e"], "v": [1], "s": ["x"]}))
