"""TUI cluster monitor: render layer + REST client against a live scheduler."""

import time


def test_render_layers():
    from ballista_tpu.cli.tui import render_executors, render_header, render_jobs, render_stages

    hdr = render_header({"version": "0.1.0", "scheduler_id": "s0", "executors": 2, "jobs": 1})
    assert "s0" in hdr[0] and "executors 2" in hdr[0]
    jobs = [{"job_id": "abc123", "job_name": "q1", "state": "running",
             "completed_stages": 1, "total_stages": 3, "queued_at": time.time() - 5}]
    out = render_jobs(jobs, 0)
    assert "abc123" in out[1] and out[1].startswith(">")
    execs = [{"id": "e1", "host": "h", "grpc_port": 1, "flight_port": 2,
              "free_slots": 3, "total_slots": 4, "last_seen": time.time(),
              "device_ordinal": 5}]
    out = render_executors(execs, 0)
    assert "3/4" in out[1] and " 5 " in out[1]
    stages = [{"stage_id": 1, "state": "successful", "completed": 4, "running": 0,
               "pending": 0, "metric_percentiles": [
                   {"name": "SortExec: x", "elapsed_ms_p50": 3.2}]}]
    out = render_stages(stages)
    assert "SortExec" in out[1]
    out = render_stages(stages, selected=0)
    assert out[1].startswith(">")


def test_sparkline_and_history():
    from ballista_tpu.cli.tui import SPARK_CHARS, History, render_header, sparkline

    assert sparkline([]) == ""
    assert sparkline([0, 0, 0]) == SPARK_CHARS[1] * 3
    s = sparkline([0, 5, 10], width=3)
    assert len(s) == 3 and s[2] == SPARK_CHARS[-1]
    assert sparkline(list(range(100)), width=10) == sparkline(list(range(90, 100)), width=10)

    h = History(window=4)
    execs = [{"total_slots": 4, "free_slots": 1}]
    for n_done in (0, 0, 1, 3, 3, 3):
        jobs = ([{"state": "RUNNING"}] * 2
                + [{"state": "SUCCESSFUL"}] * n_done)
        h.sample(jobs, execs)
    assert len(h.running_jobs) == 4  # window trims
    assert h.busy_slots[-1] == 3.0
    assert h.completed_rate[-3:] == [2.0, 0.0, 0.0]  # deltas, not totals
    hdr = render_header({"version": "v"}, h, width=80)
    assert len(hdr) == 2 and "slots" in hdr[1]


def test_filter_and_sort_jobs():
    from ballista_tpu.cli.tui import filter_jobs, sort_jobs

    jobs = [
        {"job_id": "a1", "job_name": "etl", "state": "RUNNING", "queued_at": 100.0,
         "ended_at": 190.0},
        {"job_id": "b2", "job_name": "adhoc", "state": "SUCCESSFUL", "queued_at": 120.0,
         "ended_at": 125.0},
    ]
    assert [j["job_id"] for j in filter_jobs(jobs, "ETL")] == ["a1"]
    assert [j["job_id"] for j in filter_jobs(jobs, "success")] == ["b2"]
    assert filter_jobs(jobs, "") == jobs
    assert [j["job_id"] for j in sort_jobs(jobs, "queued")] == ["b2", "a1"]
    assert [j["job_id"] for j in sort_jobs(jobs, "elapsed")] == ["a1", "b2"]
    assert [j["job_id"] for j in sort_jobs(jobs, "name")] == ["b2", "a1"]
    assert [j["job_id"] for j in sort_jobs(jobs, "state")] == ["a1", "b2"]


def test_render_operators_and_config_and_help():
    from ballista_tpu.cli.tui import render_config, render_help, render_operators

    stage = {"stage_id": 3, "completed": 8, "metric_percentiles": [
        {"depth": 0, "name": "ShuffleWriterExec: h", "tasks": 8,
         "elapsed_ms_p50": 1.5, "elapsed_ms_p90": 2.0, "elapsed_ms_p99": 9.0,
         "output_rows_total": 1234},
        {"depth": 1, "name": "FilterExec: x > 1", "tasks": 8,
         "elapsed_ms_p50": 0.5, "elapsed_ms_p90": 0.7, "elapsed_ms_p99": 0.9,
         "output_rows_total": 99},
    ]}
    out = render_operators(stage)
    assert "ShuffleWriterExec" in out[2] and "1234" in out[2]
    assert out[3].startswith("   ")  # depth indents
    assert "(no task metrics yet)" in render_operators(
        {"stage_id": 1, "metric_percentiles": []})[-1]

    cfg = {"scheduler_id": "s0", "version": "0.1.0", "task_distribution": "bias",
           "executor_timeout_s": 180.0, "job_state_backend": "InMemoryJobState",
           "session_config_entries": [
               {"name": "ballista.job.name", "type": "str", "default": "",
                "description": "Job name"},
               {"name": "ballista.shuffle.partitions", "type": "int", "default": 16,
                "description": "Default shuffle fan-out"}]}
    out = render_config(cfg)
    assert "bias" in out[0]
    assert any("ballista.shuffle.partitions" in line for line in out)
    # scroll offset drops the first entry but keeps the header rows
    assert not any("ballista.job.name" in line for line in render_config(cfg, offset=1))

    assert any("cancel" in line for line in render_help())


def test_tui_under_pty_against_live_scheduler():
    """Drive the real curses app under a pty: walk every pane (Tab), open
    help, drill into a finished job's stages and operators, and quit. The
    assertion is a clean exit — curses addstr errors or key-model bugs
    crash the child and surface as a nonzero status."""
    import os
    import pty
    import select
    import subprocess
    import sys

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.executor.executor_process import ExecutorProcess
    from ballista_tpu.scheduler.process import SchedulerProcess

    sched = SchedulerProcess(bind_host="127.0.0.1", port=0, rest_port=0)
    sched.start()
    ex = ExecutorProcess(f"127.0.0.1:{sched.port}", bind_host="127.0.0.1",
                         external_host="127.0.0.1", vcores=2)
    ex.start()
    try:
        ctx = SessionContext.remote(f"127.0.0.1:{sched.port}", BallistaConfig())
        import pyarrow as pa

        ctx.register_arrow_table("t", pa.table({"x": [1, 2, 3]}))
        ctx.sql("select sum(x) from t").collect()  # one finished job to drill

        master, slave = pty.openpty()
        env = dict(os.environ, TERM="xterm", LINES="30", COLUMNS="100")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ballista_tpu.cli.tui",
             "--rest-port", str(sched.rest_port), "--refresh", "0.2"],
            stdin=slave, stdout=slave, stderr=subprocess.PIPE, env=env)
        os.close(slave)
        try:
            for key in ["?", "?", "\t", "\t", "j", "j", "k", "\t",
                        "/", "su", "\r", "\x1b",  # filter to 'su'ccessful, clear
                        "s", "\r", "j", "\r", "\x1b", "\x1b",  # drill stage → ops → back
                        "q"]:
                time.sleep(0.35)
                os.write(master, key.encode())
                # drain the screen so the child never blocks on a full pty
                while select.select([master], [], [], 0)[0]:
                    if not os.read(master, 65536):
                        break
            rc = proc.wait(timeout=15)
        finally:
            if proc.poll() is None:
                proc.kill()
            os.close(master)
        assert rc == 0, proc.stderr.read().decode()[-2000:]
    finally:
        ex.shutdown()
        sched.shutdown()


def test_rest_config_endpoint_against_live_scheduler():
    from ballista_tpu.cli.tui import RestClient, render_config
    from ballista_tpu.scheduler.process import SchedulerProcess

    sched = SchedulerProcess(bind_host="127.0.0.1", port=0, rest_port=0)
    sched.start()
    try:
        c = RestClient(f"http://127.0.0.1:{sched.rest_port}")
        cfg = c.config()
        assert cfg["task_distribution"] in ("bias", "round-robin", "consistent-hash")
        names = [e["name"] for e in cfg["session_config_entries"]]
        assert "ballista.job.name" in names
        # restricted keys are scrubbed exactly like the session KV transport
        from ballista_tpu.config import RESTRICTED_KEYS

        assert not set(names) & set(RESTRICTED_KEYS)
        assert len(render_config(cfg)) >= len(names)
    finally:
        sched.shutdown()
