"""TUI cluster monitor: render layer + REST client against a live scheduler."""

import time


def test_render_layers():
    from ballista_tpu.cli.tui import render_executors, render_header, render_jobs, render_stages

    hdr = render_header({"version": "0.1.0", "scheduler_id": "s0", "executors": 2, "jobs": 1})
    assert "s0" in hdr and "executors 2" in hdr
    jobs = [{"job_id": "abc123", "job_name": "q1", "state": "running",
             "completed_stages": 1, "total_stages": 3, "queued_at": time.time() - 5}]
    out = render_jobs(jobs, 0)
    assert "abc123" in out[1] and out[1].startswith(">")
    execs = [{"id": "e1", "host": "h", "grpc_port": 1, "flight_port": 2,
              "free_slots": 3, "total_slots": 4, "last_seen": time.time()}]
    out = render_executors(execs, 0)
    assert "3/4" in out[1]
    stages = [{"stage_id": 1, "state": "successful", "completed": 4, "running": 0,
               "pending": 0, "metric_percentiles": [
                   {"name": "SortExec: x", "elapsed_ms_p50": 3.2}]}]
    out = render_stages(stages)
    assert "SortExec" in out[1]
