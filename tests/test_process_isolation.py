"""Process-isolated task execution (DedicatedExecutor parity,
executor/process_worker.py): correctness through the wire contract, native
crash containment, and preemptive cancellation."""

import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.config import (
    EXECUTOR_TASK_ISOLATION,
    BallistaConfig,
)


def _write_table(tmp_path, name, tbl):
    d = tmp_path / name
    d.mkdir()
    pq.write_table(tbl, str(d / "part-0.parquet"))
    return str(d)


@pytest.fixture()
def two_tables(tmp_path):
    rng = np.random.default_rng(3)
    n = 20000
    t = pa.table({
        "k": rng.integers(0, 50, n).astype("int64"),
        "v": np.round(rng.random(n) * 100, 3),
    })
    d = pa.table({
        "k": np.arange(50, dtype="int64"),
        "label": [f"g{i % 7}" for i in range(50)],
    })
    return _write_table(tmp_path, "t", t), _write_table(tmp_path, "d", d)


def _run(sql, paths, isolation):
    from ballista_tpu.client.context import SessionContext

    cfg = BallistaConfig({EXECUTOR_TASK_ISOLATION: isolation})
    ctx = SessionContext.standalone(cfg, num_executors=2, vcores=2)
    try:
        ctx.register_parquet("t", paths[0])
        ctx.register_parquet("d", paths[1])
        return ctx.sql(sql).collect().to_pandas()
    finally:
        ctx.shutdown()


def test_process_isolation_matches_thread_mode(two_tables):
    """A multi-stage join+agg query over a standalone cluster returns the
    same result under process isolation as in-thread — every task
    round-trips TaskDefinitionProto/TaskStatusProto by construction."""
    sql = ("SELECT label, sum(v) AS s, count(*) AS c FROM t "
           "JOIN d ON t.k = d.k GROUP BY label ORDER BY label")
    want = _run(sql, two_tables, "thread")
    got = _run(sql, two_tables, "process")
    assert got.label.tolist() == want.label.tolist()
    assert got.c.tolist() == want.c.tolist()
    assert np.allclose(got.s.values, want.s.values, rtol=1e-12)


def test_worker_crash_contained(two_tables):
    """A task that kills its interpreter outright (stand-in for a
    segfaulting native kernel) fails as a retryable task error; the
    executor daemon, scheduler, and cluster survive and serve the next
    query. In-thread, os._exit would take the whole cluster down."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.errors import ExecutionError
    from ballista_tpu.testing.udf_fixtures import hard_crash

    cfg = BallistaConfig({EXECUTOR_TASK_ISOLATION: "process"})
    ctx = SessionContext.standalone(cfg, num_executors=1, vcores=2)
    try:
        ctx.register_parquet("t", two_tables[0])
        ctx.register_udf("hard_crash", hard_crash, pa.int64())
        with pytest.raises(ExecutionError) as ei:
            ctx.sql("SELECT sum(hard_crash(k)) FROM t").collect()
        assert "worker died" in str(ei.value)
        # the cluster is still alive and healthy
        out = ctx.sql("SELECT count(*) AS c FROM t").collect()
        assert out.column("c").to_pylist() == [20000]
    finally:
        ctx.shutdown()


def test_preemptive_cancel_terminates_worker(two_tables):
    """Cancelling a job SIGTERMs the running worker mid-computation — the
    30s sleepy task dies in seconds, which cooperative (between-partition)
    checkpoints cannot do."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.errors import ExecutionError
    from ballista_tpu.testing.udf_fixtures import slow_identity

    cfg = BallistaConfig({EXECUTOR_TASK_ISOLATION: "process"})
    ctx = SessionContext.standalone(cfg, num_executors=1, vcores=2)
    try:
        ctx.register_parquet("t", two_tables[0])
        ctx.register_udf("slow_identity", slow_identity, pa.int64())
        errors = []

        def submit():
            try:
                ctx.sql("SELECT sum(slow_identity(k)) FROM t").collect()
                errors.append(None)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        sched = ctx._ensure_cluster().scheduler
        th = threading.Thread(target=submit)
        t0 = time.time()
        th.start()
        job_id = None
        while time.time() - t0 < 30 and job_id is None:
            with sched._jobs_lock:
                running = [j for j, g in sched.jobs.items()
                           if g.status.value == "running"]
            job_id = running[0] if running else None
            time.sleep(0.2)
        assert job_id is not None, "job never started running"
        time.sleep(2.0)  # let the worker get into the 30s sleep
        sched.cancel_job(job_id)
        th.join(timeout=25)
        elapsed = time.time() - t0
        assert not th.is_alive(), "collect did not return after cancel"
        assert elapsed < 29, f"cancel was not preemptive ({elapsed:.1f}s)"
        assert errors and isinstance(errors[0], ExecutionError)
    finally:
        ctx.shutdown()


def test_tpu_engine_stays_in_thread(two_tables):
    """engine=tpu must NOT spawn per-task workers (each would re-claim the
    exclusively-owned chip and rebuild the device caches): the dispatch
    quietly stays in-thread and the query still answers correctly."""
    from unittest import mock

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import EXECUTOR_ENGINE

    cfg = BallistaConfig({EXECUTOR_TASK_ISOLATION: "process",
                          EXECUTOR_ENGINE: "tpu"})
    ctx = SessionContext.standalone(cfg, num_executors=1, vcores=2)
    try:
        ctx.register_parquet("t", two_tables[0])
        with mock.patch(
                "ballista_tpu.executor.process_worker.run_task_in_subprocess",
                side_effect=AssertionError("device task must not spawn")) as m:
            out = ctx.sql("SELECT count(*) AS c FROM t").collect()
        assert out.column("c").to_pylist() == [20000]
        assert m.call_count == 0
    finally:
        ctx.shutdown()


def test_daemon_flag_process_isolation_over_grpc(tmp_path):
    """The --task-isolation process daemon flag, end-to-end over a real
    gRPC cluster: a crashing UDF fails retryably while the daemon keeps
    serving, and a healthy query follows — the standalone tests above
    can't see the argparse wiring or the gRPC status path."""
    import os
    import subprocess
    import sys

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.errors import ExecutionError
    from ballista_tpu.scheduler.process import SchedulerProcess
    from ballista_tpu.testing.udf_fixtures import hard_crash

    sched = SchedulerProcess(bind_host="127.0.0.1", port=0, rest_port=-1)
    sched.start()
    proc = None
    stderr_f = None
    try:
        addr = f"127.0.0.1:{sched.port}"
        work = str(tmp_path / "exproc")
        os.makedirs(work, exist_ok=True)
        stderr_path = os.path.join(work, "daemon.stderr")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        stderr_f = open(stderr_path, "wb")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ballista_tpu.executor",
             "--scheduler", addr, "--bind-host", "127.0.0.1",
             "--external-host", "127.0.0.1", "--concurrent-tasks", "2",
             "--task-isolation", "process", "--work-dir", work,
             "--flight-server", "python", "--log-level", "WARNING"],
            env=env, stdout=subprocess.DEVNULL, stderr=stderr_f)

        def stderr_tail() -> str:
            with open(stderr_path, "rb") as f:
                return f.read()[-2000:].decode(errors="replace")

        deadline = time.time() + 60
        while time.time() < deadline and not sched.scheduler.executors.alive_executors():
            assert proc.poll() is None, stderr_tail()
            time.sleep(0.3)
        assert sched.scheduler.executors.alive_executors()

        path = _write_table(tmp_path, "t", pa.table({"x": list(range(5000))}))
        ctx = SessionContext.remote(addr, BallistaConfig())
        ctx.register_parquet("t", path)
        ctx.register_udf("hard_crash", hard_crash, pa.int64())
        with pytest.raises(ExecutionError) as ei:
            ctx.sql("SELECT sum(hard_crash(x)) FROM t").collect()
        assert "worker died" in str(ei.value)
        assert proc.poll() is None, "daemon died with the worker"
        out = ctx.sql("SELECT count(*) AS c FROM t").collect()
        assert out.column("c").to_pylist() == [5000]
    finally:
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        if stderr_f is not None:
            stderr_f.close()
        sched.shutdown()
