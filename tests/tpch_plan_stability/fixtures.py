"""Golden staged-plan fixtures: dataless TPC-H tables with injected SF100
statistics (reference: scheduler/tests/tpch_plan_stability/stats_table.rs).

The planner sees real row counts — join build-side choices, broadcast
promotions, semi-key relaxations, and stage boundaries are all decided from
these stats — but no file ever exists: scan partitions are synthetic
descriptors, so the frozen plans are byte-stable across machines.
"""

from __future__ import annotations

import os

import pyarrow as pa

from ballista_tpu.plan.provider import TableProvider, TableStats

# exact TPC-H SF100 cardinalities
SF100_ROWS = {
    "lineitem": 600_037_902,
    "orders": 150_000_000,
    "partsupp": 80_000_000,
    "part": 20_000_000,
    "customer": 15_000_000,
    "supplier": 1_000_000,
    "nation": 25,
    "region": 5,
}

_D = pa.date32()
_S = pa.string()
_I = pa.int64()
_F = pa.float64()

SCHEMAS = {
    "lineitem": [("l_orderkey", _I), ("l_partkey", _I), ("l_suppkey", _I),
                 ("l_linenumber", _I), ("l_quantity", _F), ("l_extendedprice", _F),
                 ("l_discount", _F), ("l_tax", _F), ("l_returnflag", _S),
                 ("l_linestatus", _S), ("l_shipdate", _D), ("l_commitdate", _D),
                 ("l_receiptdate", _D), ("l_shipinstruct", _S), ("l_shipmode", _S),
                 ("l_comment", _S)],
    "orders": [("o_orderkey", _I), ("o_custkey", _I), ("o_orderstatus", _S),
               ("o_totalprice", _F), ("o_orderdate", _D), ("o_orderpriority", _S),
               ("o_clerk", _S), ("o_shippriority", _I), ("o_comment", _S)],
    "customer": [("c_custkey", _I), ("c_name", _S), ("c_address", _S),
                 ("c_nationkey", _I), ("c_phone", _S), ("c_acctbal", _F),
                 ("c_mktsegment", _S), ("c_comment", _S)],
    "part": [("p_partkey", _I), ("p_name", _S), ("p_mfgr", _S), ("p_brand", _S),
             ("p_type", _S), ("p_size", _I), ("p_container", _S),
             ("p_retailprice", _F), ("p_comment", _S)],
    "partsupp": [("ps_partkey", _I), ("ps_suppkey", _I), ("ps_availqty", _I),
                 ("ps_supplycost", _F), ("ps_comment", _S)],
    "supplier": [("s_suppkey", _I), ("s_name", _S), ("s_address", _S),
                 ("s_nationkey", _I), ("s_phone", _S), ("s_acctbal", _F),
                 ("s_comment", _S)],
    "nation": [("n_nationkey", _I), ("n_name", _S), ("n_regionkey", _I),
               ("n_comment", _S)],
    "region": [("r_regionkey", _I), ("r_name", _S), ("r_comment", _S)],
}


class TpchStatsTable(TableProvider):
    """Schema + injected stats, zero data (plans only — never executed)."""

    def __init__(self, name: str):
        self.name = name
        self._schema = pa.schema(SCHEMAS[name])
        self._rows = SF100_ROWS[name]

    def arrow_schema(self) -> pa.Schema:
        return self._schema

    def statistics(self) -> TableStats:
        return TableStats(num_rows=self._rows, total_bytes=self._rows * 100)

    def scan_partitions(self, target_partitions: int) -> list[dict]:
        n = min(target_partitions, max(1, self._rows // 1_000_000)) or 1
        return [
            {"files": [{"file": f"tpch-sf100/{self.name}/part-{i:03d}.parquet"}]}
            for i in range(int(n))
        ]


def stats_context(engine: str = "cpu"):
    """SessionContext over the dataless SF100 tables, target_partitions=16
    (the reference suite's configuration)."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import (
        EXECUTOR_ENGINE,
        TARGET_PARTITIONS,
        BallistaConfig,
    )

    cfg = BallistaConfig({TARGET_PARTITIONS: 16, EXECUTOR_ENGINE: engine})
    ctx = SessionContext(cfg)
    for name in SF100_ROWS:
        ctx.register_table(name, TpchStatsTable(name))
    return ctx


def staged_plan_text(ctx, sql: str) -> str:
    """SQL → optimized logical → physical → distributed stages → stable
    text. Any change to stage boundaries, join modes/orders, broadcast
    decisions, or partition counts changes this text and fails the pin."""
    from ballista_tpu.analysis.plan_check import check_stages
    from ballista_tpu.scheduler.planner import DistributedPlanner

    physical = ctx.create_physical_plan(ctx.sql(sql).plan)
    stages = DistributedPlanner("golden").plan_query_stages(physical)
    # every golden plan must also satisfy the static DAG invariants —
    # unconditional, unlike the ballista.debug.plan.verify runtime gate
    check_stages(stages)
    out = []
    for s in stages:
        flags = []
        if s.broadcast:
            flags.append("broadcast")
        flag = f" [{','.join(flags)}]" if flags else ""
        out.append(
            f"=== Stage {s.stage_id} partitions={s.partitions} -> "
            f"{s.output_partitions} inputs={s.input_stage_ids}{flag}\n"
            + s.plan.display(0)
        )
    return "\n".join(out).rstrip() + "\n"


def query_path(n: int) -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "benchmarks", "tpch", "queries", f"q{n}.sql")
