"""mTLS cluster: every gRPC hop (client→scheduler, scheduler→executor,
executor→scheduler) authenticated with certs from one CA (reference: the
mTLS cluster example + GrpcClientConfig/GrpcServerConfig TLS knobs)."""

import os
import subprocess
import time

import pytest


def _gen_certs(d: str) -> dict:
    def run(*args):
        subprocess.run(args, check=True, capture_output=True)

    ca_key, ca_crt = f"{d}/ca.key", f"{d}/ca.crt"
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes", "-days", "2",
        "-keyout", ca_key, "-out", ca_crt, "-subj", "/CN=ballista-test-ca")
    out = {"ca": ca_crt}
    for who in ("server", "client"):
        key, csr, crt = f"{d}/{who}.key", f"{d}/{who}.csr", f"{d}/{who}.crt"
        run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
            "-keyout", key, "-out", csr, "-subj", f"/CN={who}")
        ext = f"{d}/{who}.ext"
        with open(ext, "w") as f:
            f.write("subjectAltName=IP:127.0.0.1,DNS:localhost\n")
        run("openssl", "x509", "-req", "-in", csr, "-CA", ca_crt, "-CAkey", ca_key,
            "-CAcreateserial", "-days", "2", "-out", crt, "-extfile", ext)
        out[f"{who}_key"], out[f"{who}_crt"] = key, crt
    return out


def test_mtls_cluster_end_to_end(tmp_path, tpch_dir):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import (
        GRPC_TLS_CA,
        GRPC_TLS_CERT,
        GRPC_TLS_KEY,
        BallistaConfig,
    )
    from ballista_tpu.executor.executor_process import ExecutorProcess
    from ballista_tpu.scheduler.process import SchedulerProcess
    from ballista_tpu.testing.tpchgen import register_tpch

    certs = _gen_certs(str(tmp_path))
    sched = SchedulerProcess(
        bind_host="127.0.0.1", port=0, rest_port=-1, flight_proxy_port=-1,
        tls_cert=certs["server_crt"], tls_key=certs["server_key"],
        tls_client_ca=certs["ca"],
    )
    sched.start()
    addr = f"127.0.0.1:{sched.port}"
    ex = ExecutorProcess(
        addr, bind_host="127.0.0.1", external_host="127.0.0.1", vcores=2,
        tls_cert=certs["server_crt"], tls_key=certs["server_key"], tls_ca=certs["ca"],
    )
    ex.start()
    time.sleep(0.3)
    try:
        cfg = BallistaConfig({
            GRPC_TLS_CA: certs["ca"],
            GRPC_TLS_CERT: certs["client_crt"],
            GRPC_TLS_KEY: certs["client_key"],
        })
        ctx = SessionContext.remote(addr, cfg)
        register_tpch(ctx, tpch_dir)
        out = ctx.sql("select count(*) n from nation").collect()
        assert out.column("n").to_pylist() == [25]

        # a client WITHOUT certs must be rejected (mTLS requires client auth)
        import grpc

        from ballista_tpu.proto import pb
        from ballista_tpu.scheduler.grpc_service import scheduler_stub

        bare = scheduler_stub(grpc.insecure_channel(addr))
        with pytest.raises(grpc.RpcError):
            bare.GetJobStatus(pb.GetJobStatusParams(job_id="x"), timeout=5)
    finally:
        ex.shutdown()
        sched.shutdown()


def test_mtls_cluster_proxied_results(tmp_path, tpch_dir):
    """NAT/k8s mode under mTLS: the scheduler's Flight proxy serves TLS and
    relays from the executor's TLS data plane with its own client certs."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import (
        FLIGHT_PROXY,
        GRPC_TLS_CA,
        GRPC_TLS_CERT,
        GRPC_TLS_KEY,
        BallistaConfig,
    )
    from ballista_tpu.executor.executor_process import ExecutorProcess
    from ballista_tpu.scheduler.process import SchedulerProcess
    from ballista_tpu.testing.tpchgen import register_tpch

    certs = _gen_certs(str(tmp_path))
    sched = SchedulerProcess(
        bind_host="127.0.0.1", port=0, rest_port=-1, flight_proxy_port=0,
        tls_cert=certs["server_crt"], tls_key=certs["server_key"],
        tls_client_ca=certs["ca"],
    )
    sched.start()
    addr = f"127.0.0.1:{sched.port}"
    ex = ExecutorProcess(
        addr, bind_host="127.0.0.1", external_host="127.0.0.1", vcores=2,
        tls_cert=certs["server_crt"], tls_key=certs["server_key"], tls_ca=certs["ca"],
    )
    ex.start()
    time.sleep(0.3)
    try:
        cfg = BallistaConfig({
            GRPC_TLS_CA: certs["ca"],
            GRPC_TLS_CERT: certs["client_crt"],
            GRPC_TLS_KEY: certs["client_key"],
            FLIGHT_PROXY: f"127.0.0.1:{sched.flight_proxy_port}",
        })
        ctx = SessionContext.remote(addr, cfg)
        register_tpch(ctx, tpch_dir)
        out = ctx.sql(
            "select n_regionkey, count(*) n from nation group by n_regionkey order by n_regionkey"
        ).collect()
        assert out.column("n").to_pylist() == [5, 5, 5, 5, 5]
    finally:
        ex.shutdown()
        sched.shutdown()
