"""Scheduler fail-over: persisted job graphs + ownership + restart resume
(reference: JobState trait + JobAcquired/JobReleased, cluster/mod.rs:221,283)."""

import time


def test_file_job_state_roundtrip(tmp_path, tpch_ctx):
    from ballista_tpu.scheduler.planner import DistributedPlanner
    from ballista_tpu.scheduler.state.execution_graph import ExecutionGraph
    from ballista_tpu.scheduler.state.job_state import FileJobState

    from .conftest import tpch_query

    physical = tpch_ctx.create_physical_plan(tpch_ctx.sql(tpch_query(1)).plan)
    stages = DistributedPlanner("jobf").plan_query_stages(physical)
    g = ExecutionGraph("jobf", "", "s1", stages)
    store = FileJobState(str(tmp_path))
    store.save_graph(g)
    assert store.list_jobs() == ["jobf"]
    g2 = store.load_graph("jobf")
    assert g2 is not None and set(g2.stages) == set(g.stages)
    store.remove_job("jobf")
    assert store.list_jobs() == []


def test_ownership_arbitration(tmp_path):
    from ballista_tpu.scheduler.state.job_state import FileJobState

    store = FileJobState(str(tmp_path))
    assert store.acquire("j1", "sched-a")
    assert store.acquire("j1", "sched-a")       # idempotent for the owner
    assert not store.acquire("j1", "sched-b")   # held by a
    assert store.acquire("j1", "sched-b", force=True)  # takeover
    store.release("j1", "sched-b")
    assert store.acquire("j1", "sched-c")


def test_scheduler_restart_resumes_job(tmp_path, tpch_dir, tpch_ref_tables):
    """Kill the scheduler after a job completes stages, start a NEW
    scheduler on the same state dir: the job recovers from the persisted
    graph with its materialized shuffle outputs intact."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.executor.executor_process import ExecutorProcess
    from ballista_tpu.scheduler.process import SchedulerProcess
    from ballista_tpu.testing.reference import compare_results, run_reference
    from ballista_tpu.testing.tpchgen import register_tpch

    from .conftest import tpch_query

    state_dir = str(tmp_path / "state")
    sched1 = SchedulerProcess(bind_host="127.0.0.1", port=0, rest_port=-1,
                              flight_proxy_port=-1, job_state_dir=state_dir,
                              scheduler_id="sched-1")
    sched1.start()
    addr1 = f"127.0.0.1:{sched1.port}"
    ex = ExecutorProcess(addr1, bind_host="127.0.0.1", external_host="127.0.0.1", vcores=2)
    ex.start()
    time.sleep(0.2)
    try:
        ctx = SessionContext.remote(addr1)
        register_tpch(ctx, tpch_dir)
        # run a job to completion so the graph (with completed stages) persists
        out = ctx.sql(tpch_query(1)).collect()
        problems = compare_results(out, run_reference(1, tpch_ref_tables), 1)
        assert not problems

        # scheduler dies; a replacement takes over the same state dir
        sched1.shutdown()
        sched2 = SchedulerProcess(bind_host="127.0.0.1", port=0, rest_port=-1,
                                  flight_proxy_port=-1, job_state_dir=state_dir,
                                  scheduler_id="sched-1")  # same identity → owns its jobs
        sched2.start()
        try:
            with sched2.scheduler._jobs_lock:
                recovered = dict(sched2.scheduler.jobs)
            assert recovered, "no jobs recovered after restart"
            g = list(recovered.values())[-1]
            assert g.status.value == "successful"
            # the recovered graph still serves results: its final-stage
            # locations point at the executor's materialized outputs
            st = g.job_status()
            assert st["partitions"], "recovered graph lost its output locations"
        finally:
            sched2.shutdown()
    finally:
        ex.shutdown()


def test_standby_does_not_steal_live_jobs(tmp_path):
    from ballista_tpu.scheduler.server import SchedulerServer
    from ballista_tpu.scheduler.state.job_state import FileJobState

    store = FileJobState(str(tmp_path))
    assert store.acquire("job-x", "live-sched")
    standby = SchedulerServer(scheduler_id="standby", job_state=FileJobState(str(tmp_path)))
    # nothing to load (no graph persisted), but ownership must block anyway
    assert not standby.job_state.acquire("job-x", "standby")


def test_forced_takeover_by_different_scheduler_id(tmp_path, tpch_ctx):
    """A standby with a DIFFERENT id adopts a dead owner's jobs only with
    force (the --force-recover path)."""
    from ballista_tpu.scheduler.planner import DistributedPlanner
    from ballista_tpu.scheduler.server import SchedulerServer
    from ballista_tpu.scheduler.state.execution_graph import ExecutionGraph
    from ballista_tpu.scheduler.state.job_state import FileJobState

    from .conftest import tpch_query

    physical = tpch_ctx.create_physical_plan(tpch_ctx.sql(tpch_query(1)).plan)
    stages = DistributedPlanner("jobt").plan_query_stages(physical)
    g = ExecutionGraph("jobt", "", "s1", stages)
    store = FileJobState(str(tmp_path))
    assert store.acquire("jobt", "dead-sched")
    store.save_graph(g)

    standby = SchedulerServer(scheduler_id="standby", job_state=FileJobState(str(tmp_path)))
    assert standby.recover_jobs(force=False) == []      # ownership blocks
    assert standby.recover_jobs(force=True) == ["jobt"]  # takeover adopts


def test_corrupt_graph_quarantined_not_fatal(tmp_path):
    import os

    from ballista_tpu.scheduler.state.job_state import FileJobState

    store = FileJobState(str(tmp_path))
    with open(os.path.join(str(tmp_path), "badjob.graph"), "wb") as f:
        f.write(b"\xff\xfenot a proto")
    assert store.load_graph("badjob") is None
    assert os.path.exists(os.path.join(str(tmp_path), "badjob.graph.bad"))
    assert "badjob" not in store.list_jobs()


def test_owner_lease_expiry(tmp_path):
    """A dead owner's lease expires: standby adopts without force; live
    owners keep refreshing the lease on every checkpoint."""
    import time

    from ballista_tpu.scheduler.state.job_state import FileJobState

    s = FileJobState(str(tmp_path), lease_s=0.3)
    assert s.acquire("j", "dead-sched")
    assert not s.acquire("j", "standby")
    time.sleep(0.4)
    assert s.acquire("j", "standby")


def test_concurrent_takeover_single_winner(tmp_path):
    """Two standbys adopting the same expired lease: exactly one wins
    (CAS takeover under an flock — regression for the non-atomic rewrite)."""
    import threading

    from ballista_tpu.scheduler.state.job_state import FileJobState

    import os
    import time

    st = FileJobState(str(tmp_path), lease_s=60.0)
    assert st.acquire("jobx", "dead-owner")
    # backdate the dead owner's marker past the lease
    marker = st._owner_path("jobx")
    past = time.time() - 3600
    os.utime(marker, (past, past))

    results = {}
    barrier = threading.Barrier(8)

    def adopt(sid):
        barrier.wait()
        results[sid] = st.acquire("jobx", sid)

    threads = [threading.Thread(target=adopt, args=(f"s{i}",)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(results.values()) == 1, results
    winner = next(s for s, ok in results.items() if ok)
    # idempotent re-acquire by the winner; losers still refused
    assert st.acquire("jobx", winner)
    loser = next(s for s in results if s != winner)
    assert not st.acquire("jobx", loser)
