"""Native C++ row router: bit parity with the numpy hasher and routing
correctness. Skipped when no compiler/lib is available."""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.ops import native
from ballista_tpu.ops.hashing import hash_arrays, split_batch_by_partition


needs_native = pytest.mark.skipif(native.get_lib() is None, reason="native lib unavailable")


@needs_native
def test_native_hash_parity_int_float_string():
    cols = [
        pa.array([1, 2, 3, 2**40, -5], pa.int64()),
        pa.array([0.0, -0.0, 1.5, 2.25, -3.125]),
        pa.array(["a", "bb", "", "ccc", "dddd"]),
    ]
    for c in cols:
        np_h = hash_arrays([c])
        nat_h = native.hash_arrays_native([c])
        assert nat_h is not None
        assert (np_h == nat_h).all(), c.type

    np_h = hash_arrays(cols)
    nat_h = native.hash_arrays_native(cols)
    assert (np_h == nat_h).all()


@needs_native
def test_native_hash_parity_nulls_and_dates():
    c = pa.array([1, None, 3], pa.int64())
    assert (hash_arrays([c]) == native.hash_arrays_native([c])).all()
    d = pa.array([0, 1, 20000], pa.int32()).cast(pa.date32())
    assert (hash_arrays([d]) == native.hash_arrays_native([d])).all()


@needs_native
def test_native_route():
    h = hash_arrays([pa.array(np.arange(1000), pa.int64())])
    pids, bounds, order = native.route_native(h, 7)
    assert (pids == (h % np.uint64(7)).astype(np.uint32)).all()
    assert bounds[0] == 0 and bounds[-1] == 1000
    # order groups rows by partition, stable
    for p in range(7):
        seg = order[bounds[p]:bounds[p + 1]]
        assert (pids[seg] == p).all()
        assert (np.diff(seg.astype(np.int64)) > 0).all()  # stable = increasing


def test_split_batch_by_partition_roundtrip():
    batch = pa.record_batch({"k": pa.array(list(range(100)), pa.int64()),
                             "v": pa.array([str(i) for i in range(100)])})
    keys = [batch.column(0)]
    seen = []
    for p, sub in split_batch_by_partition(batch, keys, 5):
        assert 0 <= p < 5
        seen.extend(sub.column(0).to_pylist())
    assert sorted(seen) == list(range(100))
