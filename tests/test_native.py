"""Native C++ row router: bit parity with the numpy hasher and routing
correctness. Skipped when no compiler/lib is available."""

import json
import os

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.ops import native
from ballista_tpu.ops.hashing import hash_arrays, split_batch_by_partition


needs_native = pytest.mark.skipif(native.get_lib() is None, reason="native lib unavailable")


@needs_native
def test_native_hash_parity_int_float_string():
    cols = [
        pa.array([1, 2, 3, 2**40, -5], pa.int64()),
        pa.array([0.0, -0.0, 1.5, 2.25, -3.125]),
        pa.array(["a", "bb", "", "ccc", "dddd"]),
    ]
    for c in cols:
        np_h = hash_arrays([c])
        nat_h = native.hash_arrays_native([c])
        assert nat_h is not None
        assert (np_h == nat_h).all(), c.type

    np_h = hash_arrays(cols)
    nat_h = native.hash_arrays_native(cols)
    assert (np_h == nat_h).all()


@needs_native
def test_native_hash_parity_nulls_and_dates():
    c = pa.array([1, None, 3], pa.int64())
    assert (hash_arrays([c]) == native.hash_arrays_native([c])).all()
    d = pa.array([0, 1, 20000], pa.int32()).cast(pa.date32())
    assert (hash_arrays([d]) == native.hash_arrays_native([d])).all()


@needs_native
def test_native_route():
    h = hash_arrays([pa.array(np.arange(1000), pa.int64())])
    pids, bounds, order = native.route_native(h, 7)
    assert (pids == (h % np.uint64(7)).astype(np.uint32)).all()
    assert bounds[0] == 0 and bounds[-1] == 1000
    # order groups rows by partition, stable
    for p in range(7):
        seg = order[bounds[p]:bounds[p + 1]]
        assert (pids[seg] == p).all()
        assert (np.diff(seg.astype(np.int64)) > 0).all()  # stable = increasing


def test_split_batch_by_partition_roundtrip():
    batch = pa.record_batch({"k": pa.array(list(range(100)), pa.int64()),
                             "v": pa.array([str(i) for i in range(100)])})
    keys = [batch.column(0)]
    seen = []
    for p, sub in split_batch_by_partition(batch, keys, 5):
        assert 0 <= p < 5
        seen.extend(sub.column(0).to_pylist())
    assert sorted(seen) == list(range(100))


# ---------------------------------------------------------------------------
# native C++ Flight shuffle server (native/flight_shuffle.cpp)


@pytest.fixture(scope="module")
def native_flight(tmp_path_factory):
    from ballista_tpu.executor.executor_process import start_native_flight_server

    work = str(tmp_path_factory.mktemp("native-flight"))
    started = start_native_flight_server(work, "127.0.0.1", 0)
    if started is None:
        pytest.skip("native flight server unavailable (no arrow headers?)")
    proc, port = started
    yield work, port
    proc.terminate()
    proc.wait(timeout=5)


def _write_shuffle_files(work):
    import io
    import json
    import os

    import pyarrow.ipc as ipc

    batch = pa.record_batch({
        "a": pa.array(range(100), pa.int64()),
        "s": pa.array([f"x{i % 7}" for i in range(100)]),
    })
    d = os.path.join(work, "jobn", "1", "0")
    os.makedirs(d, exist_ok=True)
    hash_file = os.path.join(d, "data-t1.arrow")
    with open(hash_file, "wb") as f:
        with ipc.new_stream(f, batch.schema) as w:
            w.write_batch(batch)
    from ballista_tpu.shuffle import paths as shuffle_paths

    sort_file = os.path.join(d, "sorted-t1.arrow")
    index = {}
    with open(sort_file, "wb") as f:
        for pid in (0, 3):
            start = f.tell()
            buf = io.BytesIO()
            with ipc.new_stream(buf, batch.schema) as w:
                w.write_batch(batch.slice(pid * 10, 10))
            f.write(buf.getvalue())
            index[str(pid)] = [start, f.tell() - start, 10, f.tell() - start]
    # the PRODUCTION index filename convention (x.arrow -> x.idx) — the C++
    # server must agree with shuffle/paths.py, not with a test-local name
    with open(shuffle_paths.index_path(sort_file), "w") as f:
        json.dump(index, f)
    return batch, hash_file, sort_file


def test_native_flight_wire_contract(native_flight):
    """The C++ data plane must serve the exact contract of the python
    server: do_get (hash + sort layouts, missing → empty), raw-block
    do_action, and job GC."""
    import json
    import os

    import pyarrow.flight as flight
    import pyarrow.ipc as ipc

    work, port = native_flight
    batch, hash_file, sort_file = _write_shuffle_files(work)
    client = flight.FlightClient(f"grpc://127.0.0.1:{port}")

    t = flight.Ticket(json.dumps({"path": hash_file, "layout": "hash", "output_partition": 0}).encode())
    tbl = client.do_get(t).read_all()
    assert tbl.num_rows == 100 and tbl.column("a").to_pylist() == list(range(100))

    t = flight.Ticket(json.dumps({"path": sort_file, "layout": "sort", "output_partition": 3}).encode())
    tbl = client.do_get(t).read_all()
    assert tbl.column("a").to_pylist() == list(range(30, 40))

    t = flight.Ticket(json.dumps({"path": sort_file, "layout": "sort", "output_partition": 9}).encode())
    assert client.do_get(t).read_all().num_rows == 0

    # a MISSING index file must be an error (FetchFailed/ResultLost fuel),
    # never a silent empty result
    t = flight.Ticket(json.dumps(
        {"path": sort_file + ".gone.arrow", "layout": "sort", "output_partition": 0}
    ).encode())
    with pytest.raises(flight.FlightError):
        client.do_get(t).read_all()

    action = flight.Action(
        "io_block_transport",
        json.dumps({"path": sort_file, "layout": "sort", "output_partition": 0}).encode(),
    )
    raw = b"".join(r.body.to_pybytes() for r in client.do_action(action))
    assert ipc.open_stream(pa.BufferReader(raw)).read_all().column("a").to_pylist() == list(range(10))

    list(client.do_action(flight.Action("remove_job_data", json.dumps({"job_id": "jobn"}).encode())))
    assert not os.path.exists(os.path.join(work, "jobn"))


# -- data-plane containment (both server impls share the wire contract) ----


@pytest.fixture(scope="module")
def python_flight(tmp_path_factory):
    from ballista_tpu.flight.server import start_flight_server

    work = str(tmp_path_factory.mktemp("py-flight"))
    server, port = python_flight_handle = start_flight_server(work, "127.0.0.1", 0)
    yield work, port
    server.shutdown()


def _assert_contained(work, port):
    import pyarrow.flight as flight

    client = flight.FlightClient(f"grpc://127.0.0.1:{port}")
    # a secret OUTSIDE the work dir must not be readable through any path
    secret = os.path.join(os.path.dirname(work), "secret-" + os.path.basename(work))
    os.makedirs(secret, exist_ok=True)
    secret_file = os.path.join(secret, "creds.arrow")
    with open(secret_file, "wb") as f:
        f.write(b"hunter2")
    rejected = (flight.FlightError, pa.ArrowInvalid)  # status mapping differs per impl
    for path in (secret_file, os.path.join(work, "..", os.path.basename(secret), "creds.arrow")):
        t = flight.Ticket(json.dumps({"path": path, "layout": "hash", "output_partition": 0}).encode())
        with pytest.raises(rejected):
            list(client.do_get(t))
        a = flight.Action("io_block_transport", json.dumps(
            {"path": path, "layout": "hash", "output_partition": 0}).encode())
        with pytest.raises(rejected):
            list(client.do_action(a))
    # job-id traversal must not delete outside the work dir
    for bad in ("../" + os.path.basename(secret), "..", "a/b", ""):
        a = flight.Action("remove_job_data", json.dumps({"job_id": bad}).encode())
        with pytest.raises(rejected):
            list(client.do_action(a))
    assert os.path.exists(secret_file)
    # contained reads still work
    d = os.path.join(work, "jobc", "1", "0")
    os.makedirs(d, exist_ok=True)
    batch = pa.record_batch({"x": pa.array([1, 2, 3], pa.int64())})
    inside = os.path.join(d, "data-t1.arrow")
    with open(inside, "wb") as f:
        import pyarrow.ipc as ipc

        with ipc.new_stream(f, batch.schema) as w:
            w.write_batch(batch)
    t = flight.Ticket(json.dumps({"path": inside, "layout": "hash", "output_partition": 0}).encode())
    got = list(client.do_get(t))
    assert sum(c.data.num_rows for c in got) == 3


def test_python_flight_path_containment(python_flight):
    _assert_contained(*python_flight)


@needs_native
def test_native_flight_path_containment(native_flight):
    _assert_contained(*native_flight)
