"""Dynamic join selection: the decision-boundary matrix plus mid-stage
(first-batch-time) behavior.

Mirrors the reference's AQE join-selection test harness — the
stats-injecting fake table and broadcast-threshold matrices of
scheduler/src/state/aqe/test/{stats_table.rs,broadcast_thresholds.rs} —
against this engine's pure decision function and executable operator
(ops/cpu/dynamic_join.py)."""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import (
    BROADCAST_JOIN_ROWS_THRESHOLD,
    BROADCAST_JOIN_THRESHOLD,
    DEFAULT_SHUFFLE_PARTITIONS,
    BallistaConfig,
)
from ballista_tpu.ops.cpu.dynamic_join import (
    DynamicJoinSelectionExec,
    select_strategy,
)
from ballista_tpu.plan.expressions import Column
from ballista_tpu.plan.physical import MemoryScanExec, RepartitionExec, TaskContext
from ballista_tpu.plan.schema import DFField, DFSchema

KB = 1024
MB = 1024 * 1024


# ------------------------------------------------------- threshold matrix


@pytest.mark.parametrize(
    "l_bytes,l_rows,r_bytes,r_rows,expect",
    [
        # build (left) under both thresholds → broadcast as-is
        (1 * MB, 1_000, 100 * MB, 9_000_000, ("Broadcast", False, "collect_left")),
        # right smaller → swap, under thresholds → broadcast swapped
        (100 * MB, 9_000_000, 1 * MB, 1_000, ("BroadcastSwapped", True, "collect_left")),
        # byte boundary: exactly AT the threshold broadcasts ...
        (10 * MB, 1_000, 100 * MB, 2_000, ("Broadcast", False, "collect_left")),
        # ... one byte over does not (build still smaller → plain partitioned)
        (10 * MB + 1, 1_000, 100 * MB, 2_000, ("Partitioned", False, "partitioned")),
        # rows are a conjunct: small bytes but too many rows → no broadcast
        (1 * MB, 1_000_001, 100 * MB, 9_000_000, ("Partitioned", False, "partitioned")),
        # rows exactly at the threshold broadcast
        (1 * MB, 1_000_000, 100 * MB, 9_000_000, ("Broadcast", False, "collect_left")),
        # both over byte threshold → partitioned, smaller side builds
        (50 * MB, 10, 40 * MB, 10, ("PartitionedSwapped", True, "partitioned")),
        (40 * MB, 10, 50 * MB, 10, ("Partitioned", False, "partitioned")),
        # equal sizes → keep planned orientation
        (40 * MB, 10, 40 * MB, 10, ("Partitioned", False, "partitioned")),
    ],
)
def test_threshold_matrix_inner(l_bytes, l_rows, r_bytes, r_rows, expect):
    got = select_strategy(l_bytes, l_rows, True, r_bytes, r_rows, True,
                          "inner", False, 10 * MB, 1_000_000)
    assert got == expect


def test_zero_byte_threshold_disables_promotion():
    """A 0 byte threshold disables dynamic promotion entirely — including
    the row-based path (reference dynamic_join.rs:266-270)."""
    got = select_strategy(1 * KB, 10, True, 100 * MB, 500, True,
                          "inner", False, 0, 1_000_000)
    assert got == ("AsPlanned", False, "partitioned")


def test_unknown_sides():
    # both unknown → nothing proven, run as planned
    assert select_strategy(99 * MB, 0, False, 99 * MB, 0, False,
                           "inner", False, 10 * MB, 10**6)[0] == "AsPlanned"
    # only right proven small → build from it
    assert select_strategy(99 * MB, 0, False, 1 * MB, 100, True,
                           "inner", False, 10 * MB, 10**6)[0] == "BroadcastSwapped"
    # only left proven → build from it, no swap
    assert select_strategy(1 * MB, 100, True, 99 * MB, 0, False,
                           "inner", False, 10 * MB, 10**6)[0] == "Broadcast"


@pytest.mark.parametrize("jt,swapped_safe,unswapped_safe", [
    ("inner", True, True),
    ("right", False, True),    # swapped right→left emits build rows
    ("left", True, False),     # left emits build rows; swapped→right is safe
    ("full", False, False),
    ("right_semi", False, True),
    ("left_semi", True, False),
    ("right_anti", False, True),
    ("left_anti", True, False),
])
def test_collect_safety_by_join_type(jt, swapped_safe, unswapped_safe):
    """Broadcast collection is only safe for join types that never emit
    rows on behalf of the (shared) build — evaluated AGAINST the post-swap
    type (reference dynamic_join.rs:278-292 collect_left_broadcast_safe)."""
    # unswapped: left is the small side
    d, _, mode = select_strategy(1 * KB, 10, True, 100 * MB, 10**7, True,
                                 jt, False, 10 * MB, 10**6)
    assert (mode == "collect_left") == unswapped_safe, (jt, d)
    # swapped: right is the small side
    d, _, mode = select_strategy(100 * MB, 10**7, True, 1 * KB, 10, True,
                                 jt, False, 10 * MB, 10**6)
    assert (mode == "collect_left") == swapped_safe, (jt, d)


def test_single_partition_probe_relaxes_safety():
    """With a single-partition probe there is exactly one join instance, so
    even build-emitting types may collect (planner rule at
    physical_planner.py:548-550)."""
    d, _, mode = select_strategy(1 * KB, 10, True, 100 * MB, 10**7, True,
                                 "full", True, 10 * MB, 10**6)
    assert mode == "collect_left" and d == "Broadcast"


# ------------------------------------------------ mid-stage (dam) behavior


def _mk_scan(name, n_rows, partitions, key_mod, seed):
    rng = np.random.default_rng(seed)
    tbl = pa.table({
        f"{name}_k": rng.integers(0, key_mod, n_rows),
        f"{name}_v": rng.integers(0, 1000, n_rows),
    })
    schema = DFSchema([DFField(f"{name}_k", pa.int64(), False, name),
                       DFField(f"{name}_v", pa.int64(), False, name)])
    return MemoryScanExec(schema, tbl.to_batches(), partitions)


def _dyn_join(left, right, jt="inner"):
    from ballista_tpu.engine.physical_planner import _join_exec_schema

    on = [(Column(left.df_schema.field(0).name, left.df_schema.field(0).qualifier),
           Column(right.df_schema.field(0).name, right.df_schema.field(0).qualifier))]
    schema = _join_exec_schema(left.df_schema, right.df_schema, jt)
    return DynamicJoinSelectionExec(left, right, on, jt, None, schema)


def _partitioned(node, n=4):
    keys = [Column(node.df_schema.field(0).name, node.df_schema.field(0).qualifier)]
    return RepartitionExec(node, "hash", n, keys)


def _collect(plan, cfg=None):
    ctx = TaskContext(cfg or BallistaConfig())
    batches = []
    for p in range(plan.output_partition_count()):
        batches.extend(b for b in plan.execute(p, ctx) if b.num_rows)
    return pa.Table.from_batches(batches, schema=plan.schema())


@pytest.mark.parametrize("jt", ["inner", "left", "right", "full",
                                "left_semi", "right_semi", "left_anti", "right_anti"])
def test_mid_stage_matches_static_all_types(jt):
    """The dam-decided join must agree with the statically planned
    partitioned join for every join type, with the small side on the RIGHT
    so a swap is exercised where legal."""
    from ballista_tpu.plan.physical import HashJoinExec

    big = _mk_scan("b", 20_000, 4, 500, 1)
    small = _mk_scan("s", 300, 2, 500, 2)
    dyn = _dyn_join(_partitioned(big), _partitioned(small), jt)
    want_join = HashJoinExec(_partitioned(big), _partitioned(small), dyn.on, jt,
                             None, "partitioned", dyn.df_schema)
    got = _collect(dyn).to_pandas()
    want = _collect(want_join).to_pandas()
    sort_cols = list(want.columns)
    got = got.sort_values(sort_cols).reset_index(drop=True)
    want = want.sort_values(sort_cols).reset_index(drop=True)
    assert got.equals(want), (jt, dyn.decision, len(got), len(want))
    assert dyn.decision, "operator must record its decision"


def test_mid_stage_swaps_to_small_right():
    # byte threshold below the left side's ~800 KB so the dam overflows on
    # it, proving only the right side small → swapped broadcast
    cfg = BallistaConfig({BROADCAST_JOIN_THRESHOLD: 64 * KB})
    big = _mk_scan("b", 50_000, 4, 1000, 3)
    small = _mk_scan("s", 100, 2, 1000, 4)
    dyn = _dyn_join(_partitioned(big), _partitioned(small), "inner")
    out = _collect(dyn, cfg)
    assert dyn.decision == "BroadcastSwapped", dyn.decision
    # column order preserved despite the internal swap
    assert out.schema.names == [f.name for f in dyn.df_schema]


def test_mid_stage_short_circuit_skips_probe_observation():
    """A planned build proven small must not dam the probe side at all."""
    big = _mk_scan("b", 50_000, 4, 1000, 3)
    small = _mk_scan("s", 100, 2, 1000, 4)
    dyn = _dyn_join(_partitioned(small), _partitioned(big), "inner")
    probe_calls = []
    orig = dyn.right.execute

    def counting(p, ctx):
        probe_calls.append(p)
        return orig(p, ctx)

    dyn.right.execute = counting
    ctx = TaskContext(BallistaConfig())
    list(dyn.execute(0, ctx))
    assert dyn.decision == "Broadcast"
    # only the join's own probe of partition 0 ran — no dam sweep over all
    # probe partitions before the decision
    assert probe_calls == [0], probe_calls


def test_mid_stage_both_big_runs_as_planned():
    cfg = BallistaConfig({BROADCAST_JOIN_THRESHOLD: 4 * KB,
                          BROADCAST_JOIN_ROWS_THRESHOLD: 50})
    a = _mk_scan("a", 30_000, 4, 200, 5)
    b = _mk_scan("c", 30_000, 4, 200, 6)
    dyn = _dyn_join(_partitioned(a), _partitioned(b), "inner")
    out = _collect(dyn, cfg)
    assert dyn.decision == "AsPlanned", dyn.decision
    assert out.num_rows > 0


def test_mid_stage_zero_threshold_short_circuits():
    cfg = BallistaConfig({BROADCAST_JOIN_THRESHOLD: 0})
    a = _mk_scan("a", 1_000, 2, 100, 7)
    b = _mk_scan("c", 1_000, 2, 100, 8)
    dyn = _dyn_join(_partitioned(a), _partitioned(b), "inner")
    _collect(dyn, cfg)
    assert dyn.decision == "AsPlanned"


# --------------------------------------------------------- integration


def test_planner_emits_dynamic_node_and_query_is_correct():
    """End-to-end: the planner defers partitioned joins; execution decides
    and the result matches a non-adaptive run."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import PLANNER_ADAPTIVE_ENABLED

    rng = np.random.default_rng(9)
    fact = pa.table({"k": rng.integers(0, 5_000, 80_000),
                     "v": rng.integers(0, 100, 80_000)})
    dim = pa.table({"k": np.arange(5_000), "x": rng.integers(0, 50, 5_000)})
    sql = ("select fact.k, sum(v) s from fact, dim "
           "where fact.k = dim.k and x < 5 group by fact.k order by s desc, fact.k limit 20")

    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 4,
                          BROADCAST_JOIN_ROWS_THRESHOLD: 100})  # force partitioned plan
    ctx = SessionContext(cfg)
    ctx.register_arrow_table("fact", fact, partitions=4)
    ctx.register_arrow_table("dim", dim, partitions=2)
    physical = ctx.create_physical_plan(ctx.sql(sql).plan)
    assert "DynamicJoinSelectionExec" in physical.display()
    got = ctx.sql(sql).collect().to_pandas()

    cfg2 = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 4,
                           BROADCAST_JOIN_ROWS_THRESHOLD: 100,
                           PLANNER_ADAPTIVE_ENABLED: False})
    ctx2 = SessionContext(cfg2)
    ctx2.register_arrow_table("fact", fact, partitions=4)
    ctx2.register_arrow_table("dim", dim, partitions=2)
    assert "DynamicJoinSelectionExec" not in ctx2.create_physical_plan(ctx2.sql(sql).plan).display()
    want = ctx2.sql(sql).collect().to_pandas()
    assert got.equals(want)


def test_serde_roundtrip_dynamic_node():
    from ballista_tpu.serde import decode_plan, encode_plan

    a = _mk_scan("a", 100, 2, 10, 10)
    b = _mk_scan("c", 100, 2, 10, 11)
    dyn = _dyn_join(_partitioned(a), _partitioned(b), "left")
    back = decode_plan(encode_plan(dyn))
    assert isinstance(back, DynamicJoinSelectionExec)
    assert back.join_type == "left" and back.mode == "partitioned"
    assert repr(back.df_schema) == repr(dyn.df_schema)


def test_resolution_with_stats_concretizes():
    """resolve_with_stats (the AQE resolution path) must produce a concrete
    plan containing no deferred node, honoring the matrix."""
    a = _mk_scan("a", 4_000, 2, 100, 12)
    b = _mk_scan("c", 200, 2, 100, 13)
    dyn = _dyn_join(_partitioned(a), _partitioned(b), "inner")
    resolved = dyn.resolve_with_stats(50 * MB, 4_000, 2 * KB, 200, 10 * MB, 10**6)
    assert dyn.decision == "BroadcastSwapped"
    assert "DynamicJoinSelectionExec" not in resolved.display()
    got = _collect(resolved).to_pandas().sort_values(
        ["a_k", "a_v", "c_k", "c_v"]).reset_index(drop=True)
    want = _collect(_dyn_join(_partitioned(a), _partitioned(b), "inner")).to_pandas(
    ).sort_values(["a_k", "a_v", "c_k", "c_v"]).reset_index(drop=True)
    assert got.equals(want)


def test_tpu_engine_raises_collect_budget():
    """engine=tpu plans joins with the HBM-scale collect budget
    (ballista.tpu.broadcast.join.threshold.rows): a build side far past
    the CPU broadcast-rows threshold still plans as a collect build —
    the only shape the device stage compiler takes — while engine=cpu
    defers the same join for runtime selection."""
    import numpy as np
    import pyarrow as pa

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import EXECUTOR_ENGINE, BallistaConfig
    from ballista_tpu.ops.cpu.dynamic_join import DynamicJoinSelectionExec
    from ballista_tpu.plan.physical import HashJoinExec
    from ballista_tpu.plan.provider import MemoryTable, TableStats

    class BigStats(MemoryTable):
        def __init__(self, batches, schema=None, partitions=1, rows=0):
            super().__init__(batches, schema, partitions)
            self._rows = rows

        def statistics(self):
            return TableStats(num_rows=self._rows, total_bytes=self._rows * 64)

    build = pa.table({"k": np.arange(100, dtype="int64"), "v": np.arange(100.0)})
    probe = pa.table({"k": np.arange(100, dtype="int64"), "w": np.arange(100.0)})
    sql = "SELECT sum(w + v) AS s FROM p JOIN b ON p.k = b.k"

    def plan_with(engine):
        ctx = SessionContext(BallistaConfig({EXECUTOR_ENGINE: engine}))
        # build 5M rows: past the CPU 1M-row broadcast cap, well under the
        # 16M tpu collect budget; probe 40M keeps the build side the build
        ctx.register_table("b", BigStats(build.to_batches(), build.schema,
                                         partitions=4, rows=5_000_000))
        ctx.register_table("p", BigStats(probe.to_batches(), probe.schema,
                                         partitions=4, rows=40_000_000))
        from .conftest import iter_plan

        return list(iter_plan(ctx.create_physical_plan(ctx.sql(sql).plan)))

    tpu_nodes = plan_with("tpu")
    joins = [n for n in tpu_nodes if isinstance(n, HashJoinExec)]
    assert joins and all(j.mode == "collect_left" for j in joins), \
        [n.node_str() for n in tpu_nodes]
    assert not any(isinstance(n, DynamicJoinSelectionExec) for n in tpu_nodes)

    cpu_nodes = plan_with("cpu")
    assert any(isinstance(n, DynamicJoinSelectionExec) for n in cpu_nodes), \
        [n.node_str() for n in cpu_nodes]
