"""Parser / planner / optimizer unit tests (reference analog: DataFusion's
sql planner tests + ballista's plan-shape assertions)."""

import datetime as dt

import pytest

from ballista_tpu.errors import SqlParseError
from ballista_tpu.plan.expressions import BinaryExpr, Column, Literal
from ballista_tpu.sql.ast import SelectStmt
from ballista_tpu.sql.parser import parse_sql
from ballista_tpu.sql.tokenizer import tokenize

from .conftest import tpch_query


def test_tokenize_basics():
    toks = tokenize("select a, 'x''y', 1.5e3 from t -- comment\nwhere a >= 2")
    kinds = [t.kind for t in toks]
    assert "eof" in kinds
    assert any(t.kind == "string" and t.value == "x'y" for t in toks)
    assert any(t.kind == "number" and t.value == "1.5e3" for t in toks)


def test_parse_date_interval():
    stmt = parse_sql("select date '1994-01-01' + interval '3' month from t")
    assert isinstance(stmt, SelectStmt)
    e = stmt.projections[0]
    assert isinstance(e, BinaryExpr)
    assert e.left.value == dt.date(1994, 1, 1)


def test_parse_errors():
    with pytest.raises(SqlParseError):
        parse_sql("select from")
    with pytest.raises(SqlParseError):
        parse_sql("select 1 extra_token still_here (")


@pytest.mark.parametrize("q", list(range(1, 23)))
def test_parse_all_tpch(q):
    stmt = parse_sql(tpch_query(q))
    assert isinstance(stmt, SelectStmt)


@pytest.mark.parametrize("q", list(range(1, 23)))
def test_plan_and_optimize_all_tpch(q, tpch_ctx):
    df = tpch_ctx.sql(tpch_query(q))
    opt = tpch_ctx.optimize(df.plan)
    text = opt.display()
    # decorrelation must leave no subquery expressions behind
    # (SubqueryAlias nodes are fine; "<subquery>" placeholders are not)
    assert "<subquery>" not in text and "<scalar subquery>" not in text


def test_q19_or_factoring(tpch_ctx):
    opt = tpch_ctx.optimize(tpch_ctx.sql(tpch_query(19)).plan)
    text = opt.display()
    # the common join key must have been factored out of the OR into a Join
    assert "Join: type=inner" in text


def test_filter_pushdown_to_scan(tpch_ctx):
    opt = tpch_ctx.optimize(
        tpch_ctx.sql("select l_orderkey from lineitem where l_quantity < 5 and l_orderkey > 100").plan
    )
    text = opt.display()
    assert "TableScan" in text and "filters=" in text


def test_projection_pushdown(tpch_ctx):
    opt = tpch_ctx.optimize(tpch_ctx.sql("select l_orderkey from lineitem").plan)
    text = opt.display()
    assert "projection=[l_orderkey]" in text


def test_union_chain_keeps_all_branches_and_defers_order():
    """3-way UNION ALL chains keep every branch, and a trailing ORDER
    BY/LIMIT binds to the WHOLE union, not a branch."""
    import pyarrow as pa

    from ballista_tpu.client.context import SessionContext

    ctx = SessionContext()
    ctx.register_arrow_table("t", pa.table({"v": [5, 1, 9]}))
    ctx.register_arrow_table("u", pa.table({"v": [7, 3]}))
    out = ctx.sql(
        "select v from t union all select v from u union all select v from u "
        "order by v limit 4"
    ).collect().to_pandas()
    assert out.v.tolist() == [1, 3, 3, 5]


def test_mixed_union_chain_left_associative():
    """a UNION ALL b UNION c dedups the whole left side; a UNION b UNION
    ALL c keeps the trailing duplicates (SQL left associativity)."""
    import pyarrow as pa

    from ballista_tpu.client.context import SessionContext

    ctx = SessionContext()
    ctx.register_arrow_table("t", pa.table({"v": [1]}))
    ctx.register_arrow_table("u", pa.table({"v": [1]}))
    ctx.register_arrow_table("w", pa.table({"v": [2, 2]}))
    out = ctx.sql(
        "select v from t union all select v from u union select v from w order by v"
    ).collect().to_pandas()
    assert out.v.tolist() == [1, 2]
    out2 = ctx.sql(
        "select v from t union select v from u union all select v from w order by v"
    ).collect().to_pandas()
    assert out2.v.tolist() == [1, 2, 2]


def test_show_columns_and_describe():
    import pyarrow as pa

    from ballista_tpu.client.context import SessionContext

    ctx = SessionContext()
    ctx.register_arrow_table("t", pa.table({"a": [1], "b": ["x"]}))
    out = ctx.sql("show columns from t").collect().to_pandas()
    assert out.column_name.tolist() == ["a", "b"]
    assert out.data_type.tolist()[0].startswith("int")
    out2 = ctx.sql("describe t").collect().to_pandas()
    assert out2.column_name.tolist() == ["a", "b"]


def test_values_table_refs():
    """(VALUES ...) [AS] t(cols) as a table factor, incl. joins against it."""
    import pyarrow as pa

    from ballista_tpu.client.context import SessionContext

    ctx = SessionContext()
    out = ctx.sql(
        "select a, b from (values (1, 'x'), (2, 'y'), (3, 'z')) AS t(a, b) "
        "where a >= 2 order by a desc"
    ).collect().to_pandas()
    assert out.a.tolist() == [3, 2]
    assert out.b.tolist() == ["z", "y"]
    ctx.register_arrow_table("u", pa.table({"k": [1, 2, 3]}))
    out2 = ctx.sql(
        "select k from u, (values (2), (3)) v(m) where k = m order by k"
    ).collect().to_pandas()
    assert out2.k.tolist() == [2, 3]
    # default column names
    out3 = ctx.sql("select column1 from (values (7)) t").collect().to_pandas()
    assert out3.column1.tolist() == [7]


def test_values_edge_cases_clean_errors():
    import pyarrow as pa

    import pytest

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.errors import PlanningError, SqlParseError

    ctx = SessionContext()
    out = ctx.sql(
        "select * from (values (1, 'x'), (-2, 'y')) t(a, b) order by a"
    ).collect().to_pandas()
    assert out.a.tolist() == [-2, 1]
    with pytest.raises(PlanningError):
        ctx.sql("select * from (values (1), (2.5)) t").collect()
    with pytest.raises(SqlParseError):
        ctx.sql("select * from (values (-'x')) t").collect()
    with pytest.raises(PlanningError):
        ctx.sql("select * from (values (null), (1)) t").collect()


def test_except_and_intersect():
    """Set-semantics EXCEPT / INTERSECT (semi/anti-join lowering over all
    columns, distinct left side), incl. multi-column and through the
    distributed standalone path."""
    import pyarrow as pa

    from ballista_tpu.client.context import SessionContext

    ctx = SessionContext()
    ctx.register_arrow_table("t", pa.table({"v": [1, 2, 2, 3, 4]}))
    ctx.register_arrow_table("u", pa.table({"v": [2, 4, 5]}))
    out = ctx.sql("select v from t intersect select v from u order by v").collect().to_pandas()
    assert out.v.tolist() == [2, 4]
    out2 = ctx.sql("select v from t except select v from u order by v").collect().to_pandas()
    assert out2.v.tolist() == [1, 3]
    ctx.register_arrow_table("a2", pa.table({"x": [1, 1, 2], "y": ["p", "q", "p"]}))
    ctx.register_arrow_table("b2", pa.table({"x": [1, 2], "y": ["q", "p"]}))
    out3 = ctx.sql(
        "select x, y from a2 intersect select x, y from b2 order by x, y"
    ).collect().to_pandas()
    assert out3.x.tolist() == [1, 2] and out3.y.tolist() == ["q", "p"]


def test_select_list_scalar_subquery_edges():
    """SELECT-list scalar subqueries: correlated COUNT yields 0 (not NULL)
    for no-match rows, outer rows survive via LEFT join, a same-named
    correlation key stays unambiguous, and an empty grouped uncorrelated
    subquery yields NULL without wiping the outer rows."""
    import pandas as pd
    import pyarrow as pa

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.errors import PlanningError

    ctx = SessionContext()
    ctx.register_arrow_table("t", pa.table({"k": [1, 2, 3]}))
    ctx.register_arrow_table("s", pa.table({"k": [1, 1], "v": [10.0, 20.0]}))
    r = ctx.sql("select k, (select count(*) from s where s.k = t.k) c "
                "from t order by k").collect().to_pandas()
    assert r.c.tolist() == [2, 0, 0]
    r2 = ctx.sql("select k, (select max(v) from s where s.k = t.k) mv "
                 "from t order by k").collect().to_pandas()
    assert r2.mv[0] == 20.0 and pd.isna(r2.mv[1]) and pd.isna(r2.mv[2])
    r3 = ctx.sql("select k, (select sum(v) from s where s.k = 10 group by s.k) sv "
                 "from t order by k").collect().to_pandas()
    assert len(r3) == 3 and pd.isna(r3.sv).all()
    # the no-match 0 must feed the subquery's post-aggregate arithmetic:
    # count(*)+1 over no rows is 1, not 0 (and not NULL)
    r4 = ctx.sql("select k, (select count(*) + 1 from s where s.k = t.k) c "
                 "from t order by k").collect().to_pandas()
    assert r4.c.tolist() == [3, 1, 1]
    # grouping beyond the correlation keys can return >1 row per outer row;
    # the lowering must refuse rather than silently duplicate outer rows
    with pytest.raises(PlanningError, match="more than one row"):
        ctx.sql("select k, (select count(*) from s where s.k = t.k group by s.v) c "
                "from t").collect()
    # grouping BY the correlation key is provably single-row — still works
    r5 = ctx.sql("select k, (select sum(v) from s where s.k = t.k group by s.k) sv "
                 "from t order by k").collect().to_pandas()
    assert r5.sv[0] == 30.0 and pd.isna(r5.sv[1]) and pd.isna(r5.sv[2])
    # WHERE-context correlated COUNT: the no-match value is 0 (not NULL), so
    # `= 0` must KEEP the no-match rows — an inner-join lowering drops them
    r6 = ctx.sql("select k from t where (select count(*) from s where s.k = t.k) = 0 "
                 "order by k").collect().to_pandas()
    assert r6.k.tolist() == [2, 3]


def test_except_intersect_all_bag_semantics():
    """INTERSECT ALL keeps min(count_l, count_r) copies; EXCEPT ALL keeps
    count_l - count_r copies (row_number bag lowering); NULL rows count as
    equal duplicates like the set forms."""
    import pandas as pd
    import pyarrow as pa

    from ballista_tpu.client.context import SessionContext

    ctx = SessionContext()
    ctx.register_arrow_table("ba", pa.table({"x": [1, 1, 1, 2, 2, 3, None]}))
    ctx.register_arrow_table("bb", pa.table({"x": [1, 1, 2, 4, None, None]}))
    r = ctx.sql("select x from ba intersect all select x from bb order by x"
                ).collect().to_pandas()
    assert r.x.fillna(-1).tolist() == [1.0, 1.0, 2.0, -1.0]
    r2 = ctx.sql("select x from ba except all select x from bb order by x"
                 ).collect().to_pandas()
    assert r2.x.tolist() == [1, 2, 3] and not pd.isna(r2.x).any()
    # mixed chain: ALL and set forms compose with INTERSECT precedence
    r3 = ctx.sql("select x from ba except all select x from bb "
                 "intersect all select x from bb order by x").collect().to_pandas()
    # rhs of except_all = bb ∩all bb = bb itself
    assert r3.x.tolist() == [1, 2, 3]


def test_intersect_distributed(tmp_path):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ballista_tpu.client.context import SessionContext

    rng = np.random.default_rng(6)
    pq.write_table(pa.table({"k": rng.integers(0, 500, 5000)}), str(tmp_path / "a.parquet"))
    pq.write_table(pa.table({"k": rng.integers(250, 750, 5000)}), str(tmp_path / "b.parquet"))
    ctx = SessionContext.standalone()
    ctx.register_parquet("a", str(tmp_path / "a.parquet"))
    ctx.register_parquet("b", str(tmp_path / "b.parquet"))
    try:
        out = ctx.sql("select k from a intersect select k from b order by k").collect().to_pandas()
        import pandas as pd

        ka = set(pq.read_table(str(tmp_path / "a.parquet")).to_pandas().k)
        kb = set(pq.read_table(str(tmp_path / "b.parquet")).to_pandas().k)
        assert out.k.tolist() == sorted(ka & kb)
    finally:
        ctx.shutdown()


def test_set_op_precedence_and_null_semantics():
    """INTERSECT binds tighter than UNION/EXCEPT; NULLs compare equal in
    set operations; duplicate output names raise a clean error."""
    import pyarrow as pa

    import pytest

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.errors import PlanningError

    ctx = SessionContext()
    ctx.register_arrow_table("t", pa.table({"v": [1]}))
    ctx.register_arrow_table("u", pa.table({"v": [2]}))
    ctx.register_arrow_table("w", pa.table({"v": [2]}))
    out = ctx.sql(
        "select v from t union select v from u intersect select v from w order by v"
    ).collect().to_pandas()
    assert out.v.tolist() == [1, 2]  # t UNION (u INTERSECT w)
    ctx.register_arrow_table("n1", pa.table({"v": pa.array([1, None], pa.int64())}))
    ctx.register_arrow_table("n2", pa.table({"v": pa.array([None], pa.int64())}))
    i = ctx.sql("select v from n1 intersect select v from n2").collect().to_pandas()
    assert i.v.isna().tolist() == [True]
    e = ctx.sql("select v from n1 except select v from n2").collect().to_pandas()
    assert e.v.tolist() == [1]
    with pytest.raises(PlanningError):
        ctx.sql("select v, v from t intersect select v, v from u").collect()


def test_exists_with_select_one_and_derived_table(tpch_ctx):
    """EXISTS (SELECT 1 ...) must keep correlation columns visible (the
    select list is void for existence, but projections BELOW the correlated
    filter — derived-table renames — are load-bearing)."""
    out = tpch_ctx.sql(
        "SELECT count(*) AS c FROM nation WHERE EXISTS "
        "(SELECT 1 FROM region WHERE r_regionkey = n_regionkey)"
    ).collect()
    assert out.column("c").to_pylist() == [25]
    out = tpch_ctx.sql(
        "SELECT count(*) AS c FROM nation WHERE EXISTS "
        "(SELECT 1 FROM (SELECT r_regionkey AS rk FROM region) s WHERE s.rk = n_regionkey)"
    ).collect()
    assert out.column("c").to_pylist() == [25]
    out = tpch_ctx.sql(
        "SELECT count(*) AS c FROM nation WHERE NOT EXISTS "
        "(SELECT 1 FROM region WHERE r_regionkey = n_regionkey AND r_regionkey < 2)"
    ).collect()
    assert out.column("c").to_pylist() == [15]
