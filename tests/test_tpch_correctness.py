"""TPC-H correctness: engine output vs the independent pandas oracle.

Reference analog: benchmarks `verify expected results` CI leg
(.github/workflows/rust.yml) and the SF10 distributed matrix (tpch.yml).
"""

import pytest

from ballista_tpu.testing.reference import compare_results, run_reference

from .conftest import tpch_query


@pytest.mark.parametrize("q", list(range(1, 23)))
def test_tpch_local_cpu(q, tpch_ctx, tpch_ref_tables):
    eng = tpch_ctx.sql(tpch_query(q)).collect()
    ref = run_reference(q, tpch_ref_tables)
    problems = compare_results(eng, ref, q)
    assert not problems, "\n".join(problems)
