"""End-to-end shuffle integrity: checksums, corruption detection, blame.

Covers the integrity round of shuffle hardening:
 - per-output-partition CRCs travel with both layouts (sort: 5th index
   field; hash: `.crc` sidecar) and verify end to end over Flight;
 - in-transit corruption (seeded chaos bit-flip at serve time) is caught
   by the reader, refetched ONCE in place, and heals transparently;
 - persistent corruption (bad bytes on disk) escalates as
   FetchFailed(cause="corruption"), reruns the upstream stage tree, and
   files a corruption strike against the SERVING executor;
 - job-state checkpoints are CRC-framed: a torn/corrupt checkpoint is
   skipped with a WARN on recover instead of adopted as truth;
 - a truncated shuffle file fails serve-time with a typed error instead
   of silently streaming short.
"""

import json
import os

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import (
    IO_RETRIES,
    IO_RETRY_WAIT_MS,
    SHUFFLE_CHECKSUM_ENABLED,
    SHUFFLE_COMPRESSION_CODEC,
    SHUFFLE_FETCH_COALESCE,
    SHUFFLE_READER_FORCE_REMOTE,
    BallistaConfig,
)
from ballista_tpu.errors import DataCorrupted, FetchFailed, ShortRead
from ballista_tpu.plan.expressions import Column
from ballista_tpu.plan.physical import MemoryScanExec, TaskContext
from ballista_tpu.plan.schema import DFSchema
from ballista_tpu.shuffle import paths as sp
from ballista_tpu.shuffle.integrity import checksum_bytes, verify_blocks


def _write_stage(tmp_path, rows=40_000, partitions=4, sort=True, extra_cfg=None):
    """One map output through the real writer; returns (work_dir, locations
    by output partition, rows, df schema)."""
    from ballista_tpu.shuffle.writer import ShuffleWriterExec, metadata_to_locations

    rng = np.random.default_rng(3)
    batches = [pa.record_batch({
        "k": pa.array(rng.integers(0, 1 << 20, rows)),
        "v": pa.array(rng.integers(0, 100, rows)),
    })]
    schema = DFSchema.from_arrow(batches[0].schema)
    writer = ShuffleWriterExec(
        MemoryScanExec(schema, batches, partitions=1),
        "ijob", 1, partitions, [Column("k")], sort_shuffle=sort)
    cfg = BallistaConfig(extra_cfg or {})
    ctx = TaskContext(cfg, task_id="t0", work_dir=str(tmp_path))
    locs: dict[int, list] = {p: [] for p in range(partitions)}
    for meta in writer.execute(0, ctx):
        for loc in metadata_to_locations(meta, "ijob", 1, 0, "e1", "127.0.0.1", 0):
            locs[loc.output_partition].append(loc)
    return str(tmp_path), locs, rows, schema


def _reader_ctx(extra=None):
    cfg = BallistaConfig({SHUFFLE_READER_FORCE_REMOTE: True, **(extra or {})})
    return cfg, TaskContext(cfg, task_id="t", work_dir="")


def _read_remote(schema, locs_by_p, port, extra=None):
    from ballista_tpu.shuffle.reader import ShuffleReaderExec
    from ballista_tpu.shuffle.types import PartitionLocation

    _, ctx = _reader_ctx(extra)
    plocs = [[PartitionLocation(**{**l.__dict__, "flight_port": port})
              for l in locs_by_p[p]] for p in sorted(locs_by_p)]
    reader = ShuffleReaderExec(schema, plocs)
    rows = [sum(b.num_rows for b in reader.execute(p, ctx)) for p in range(len(plocs))]
    return rows, reader


# -- checksum round-trip, both layouts, compressed + uncompressed -------------


@pytest.mark.parametrize("codec", ["none", "lz4"])
def test_sort_layout_index_carries_range_checksums(tmp_path, codec):
    """Every non-empty sort-layout index entry gains a 5th checksum field
    matching the exact bytes of its range; remote fetch verifies clean."""
    from ballista_tpu.flight.server import start_flight_server

    work, locs, rows, schema = _write_stage(
        tmp_path, sort=True, extra_cfg={SHUFFLE_COMPRESSION_CODEC: codec})
    path = locs[0][0].path
    with open(sp.index_path(path)) as f:
        index = json.load(f)
    assert index, "expected non-empty sort index"
    with open(path, "rb") as f:
        blob = f.read()
    for entry in index.values():
        assert len(entry) >= 5 and isinstance(entry[4], str), entry
        start, length = entry[0], entry[1]
        assert checksum_bytes(blob[start:start + length]) == entry[4]
    for p, ls in locs.items():
        for l in ls:
            assert sp.checksum_for(l.path, l.layout, p) is not None
    server, port = start_flight_server(work, "127.0.0.1", 0)
    try:
        got, _ = _read_remote(schema, locs, port)
        assert sum(got) == rows
    finally:
        server.shutdown()


@pytest.mark.parametrize("codec", ["none", "zstd"])
def test_hash_layout_writes_crc_sidecar(tmp_path, codec):
    from ballista_tpu.flight.server import start_flight_server

    work, locs, rows, schema = _write_stage(
        tmp_path, sort=False, extra_cfg={SHUFFLE_COMPRESSION_CODEC: codec})
    for p, ls in locs.items():
        for l in ls:
            assert os.path.exists(sp.crc_path(l.path)), l.path
            with open(l.path, "rb") as f:
                blob = f.read()
            expected = sp.checksum_for(l.path, l.layout, p)
            assert expected == checksum_bytes(blob)
            assert verify_blocks([blob], expected)
    server, port = start_flight_server(work, "127.0.0.1", 0)
    try:
        got, _ = _read_remote(schema, locs, port)
        assert sum(got) == rows
    finally:
        server.shutdown()


def test_checksum_disabled_writes_legacy_format(tmp_path):
    """Knob off: no sidecars, 4-field index entries, reads work unchanged."""
    from ballista_tpu.flight.server import start_flight_server

    work, locs, rows, schema = _write_stage(
        tmp_path, sort=True, extra_cfg={SHUFFLE_CHECKSUM_ENABLED: False})
    path = locs[0][0].path
    assert not os.path.exists(sp.crc_path(path))
    with open(sp.index_path(path)) as f:
        for entry in json.load(f).values():
            assert len(entry) == 4, entry
    for p, ls in locs.items():
        for l in ls:
            assert sp.checksum_for(l.path, l.layout, p) is None
    server, port = start_flight_server(work, "127.0.0.1", 0)
    try:
        got, _ = _read_remote(
            schema, locs, port, {SHUFFLE_CHECKSUM_ENABLED: False})
        assert sum(got) == rows
    finally:
        server.shutdown()


# -- in-transit corruption: detect, retry once in place, heal -----------------


def _chaos_server(monkeypatch, work, p="1.0", once="1", seed="7"):
    from ballista_tpu.flight.server import start_flight_server

    monkeypatch.setenv("BALLISTA_CHAOS_CORRUPT_P", p)
    monkeypatch.setenv("BALLISTA_CHAOS_CORRUPT_ONCE", once)
    monkeypatch.setenv("BALLISTA_CHAOS_SEED", seed)
    return start_flight_server(work, "127.0.0.1", 0)


def test_transient_corruption_block_path_retries_once_and_heals(tmp_path, monkeypatch):
    """Chaos corrupt-once flips a bit in the FIRST serve of the partition;
    the client catches the mismatch, refetches once in place (no generic
    retry budget burned), and the second serve decodes byte-correct."""
    work, locs, rows, schema = _write_stage(tmp_path, sort=True, partitions=1)
    server, port = _chaos_server(monkeypatch, work)
    try:
        got, reader = _read_remote(
            schema, locs, port, {SHUFFLE_FETCH_COALESCE: False, IO_RETRIES: 0})
        assert sum(got) == rows
        assert reader.metrics.extra["checksum_failures"] == 1
        assert reader.metrics.extra["corruption_retries"] == 1
        assert server.stats["chaos_corruptions"] == 1
        assert server.stats["checksum_failures"] == 0  # client-side catch
    finally:
        server.shutdown()


def test_transient_corruption_coalesced_path_retries_tail(tmp_path, monkeypatch):
    work, locs, rows, schema = _write_stage(tmp_path, sort=True, partitions=1)
    locs = {0: locs[0] * 3}  # several locations on one executor → coalesced
    server, port = _chaos_server(monkeypatch, work)
    try:
        got, reader = _read_remote(schema, locs, port, {IO_RETRIES: 0})
        assert sum(got) == rows * 3
        assert reader.metrics.extra["checksum_failures"] >= 1
        assert reader.metrics.extra["corruption_retries"] >= 1
        assert server.stats["chaos_corruptions"] >= 1
    finally:
        server.shutdown()


def test_chaos_corrupt_roll_and_flip_are_deterministic():
    from ballista_tpu.executor.chaos import corrupt_roll, flip_bit

    assert corrupt_roll(7, "a|0", 1.0) is True
    assert corrupt_roll(7, "a|0", 0.0) is False
    assert corrupt_roll(7, "a|0", 0.5) == corrupt_roll(7, "a|0", 0.5)
    data = bytes(range(64))
    flipped = flip_bit(data, 7, "a|0")
    assert flipped == flip_bit(data, 7, "a|0")  # same seed+key → same flip
    assert flipped != data
    diff = [i for i in range(64) if flipped[i] != data[i]]
    assert len(diff) == 1
    assert bin(flipped[diff[0]] ^ data[diff[0]]).count("1") == 1
    assert flip_bit(b"", 7, "x") == b""
    assert flip_bit(data, 8, "a|0") != flipped or True  # different seed allowed to differ


def test_header_sniff_never_misfires_on_arrow_bytes(tmp_path):
    """The block-path JSON header is sniffed from the first Result; Arrow
    IPC bytes (which never start with '{') must not parse as a header."""
    import pyarrow.ipc as ipc

    from ballista_tpu.flight.client import _try_parse_header

    batch = pa.record_batch({"x": pa.array([1, 2, 3])})
    sink = pa.BufferOutputStream()
    with ipc.new_stream(sink, batch.schema) as w:
        w.write_batch(batch)
    assert _try_parse_header(sink.getvalue()) is None
    assert _try_parse_header(pa.py_buffer(b"")) is None
    hdr = _try_parse_header(pa.py_buffer(b'{"nbytes": 10, "crc": "c32:aa"}'))
    assert hdr == {"nbytes": 10, "crc": "c32:aa"}


# -- persistent corruption: escalate with blame -------------------------------


def _corrupt_on_disk(path: str, offset: int = -1):
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        pos = size // 2 if offset < 0 else offset
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0x40]))


def test_persistent_corruption_remote_escalates_fetchfailed(tmp_path):
    from ballista_tpu.flight.server import start_flight_server

    work, locs, rows, schema = _write_stage(tmp_path, sort=False, partitions=1)
    _corrupt_on_disk(locs[0][0].path)
    server, port = start_flight_server(work, "127.0.0.1", 0)
    try:
        with pytest.raises(FetchFailed) as ei:
            _read_remote(schema, locs, port,
                         {SHUFFLE_FETCH_COALESCE: False, IO_RETRIES: 0,
                          IO_RETRY_WAIT_MS: 1})
        assert ei.value.cause == "corruption"
        assert ei.value.executor_id == "e1"
        assert "[corruption]" in str(ei.value)
    finally:
        server.shutdown()


def test_persistent_corruption_local_read_escalates(tmp_path):
    from ballista_tpu.shuffle.reader import ShuffleReaderExec

    _, locs, rows, schema = _write_stage(tmp_path, sort=True, partitions=2)
    target = locs[0][0]
    start, length = sp.range_for(target.path, target.layout, 0)
    _corrupt_on_disk(target.path, offset=start + length // 2)
    cfg = BallistaConfig({IO_RETRIES: 0})
    ctx = TaskContext(cfg, task_id="t", work_dir="")
    reader = ShuffleReaderExec(schema, [locs[0], locs[1]])
    with pytest.raises(FetchFailed) as ei:
        list(reader.execute(0, ctx))
    assert ei.value.cause == "corruption"
    # the sibling partition's range is untouched and still reads clean
    assert sum(b.num_rows for b in reader.execute(1, ctx)) > 0


def test_corruption_cause_round_trips_control_plane_wire():
    from ballista_tpu.errors import error_to_proto_kind
    from ballista_tpu.executor.executor import TaskResult
    from ballista_tpu.scheduler.state.executor_manager import ExecutorMetadata
    from ballista_tpu.serde_control import decode_task_status, encode_task_status

    err = FetchFailed("e9", "j", 3, 1, "bad bytes", cause="corruption")
    kind = error_to_proto_kind(err)
    assert kind == "FetchPartitionError:corruption"
    assert error_to_proto_kind(DataCorrupted("x#p0", "c32:aa", "c32:bb")) == "DataCorrupted"

    r = TaskResult(
        task_id=1, job_id="j", stage_id=4, stage_attempt=0, partitions=[0],
        state="failed", error="fetch failed", error_kind=kind, retryable=True,
        fetch_failed_executor_id="e9", fetch_failed_stage_id=3,
        fetch_failed_cause="corruption")
    meta = ExecutorMetadata(id="e1", host="h", grpc_port=1, flight_port=2)
    back = decode_task_status(encode_task_status(r, "e1"), meta)
    assert back.fetch_failed_cause == "corruption"
    assert back.fetch_failed_executor_id == "e9"


def test_graph_repeated_corruption_fails_job_with_blame(tpch_ctx):
    """Corruption-caused reruns are bounded by MAX_STAGE_ATTEMPTS; the final
    job failure names corruption (suspect disks), not a generic retry cap."""
    from .test_distributed import _fake_success, _tiny_graph

    g = _tiny_graph(tpch_ctx)
    final = max(g.stages)
    upstream = g.stages[final].spec.input_stage_ids[0]
    events = []
    guard = 0
    while g.status.value == "running" and guard < 200:
        guard += 1
        t = g.pop_next_task("e1")
        if t is None:
            break
        if t.stage_id == final:
            events = g.update_task_status(
                t.task_id, t.stage_id, t.stage_attempt, "failed", t.partitions,
                [], "checksum mismatch", retryable=True,
                fetch_failed_executor_id="e1", fetch_failed_stage_id=upstream,
                fetch_failed_cause="corruption")
            if "job_failed" in events:
                break
        else:
            _fake_success(g, t)
    assert g.status.value == "failed"
    assert "corruption" in g.error
    assert g.stages[upstream].attempt >= 1  # upstream actually reran


def test_corruption_strike_feeds_executor_health():
    from ballista_tpu.scheduler.state.executor_manager import (
        ExecutorManager,
        ExecutorMetadata,
    )

    em = ExecutorManager()
    em.register(ExecutorMetadata(id="ex1", host="h", grpc_port=1, flight_port=2))
    em.record_corruption_strike("ex1")
    slot = em.get("ex1")
    assert slot.corruption_strikes == 1
    assert slot.failure_rate > 0  # strike counts as a failed task outcome
    assert em.record_corruption_strike("missing") is None  # unknown id: no-op
    # heartbeat-shipped reader gauges surface in the health snapshot
    em.heartbeat("ex1", {"checksum_failures": 3.0, "corruption_retries": 2.0})
    snap = em.health_snapshot()["ex1"]
    assert snap["corruption_strikes"] == 1
    assert snap["checksum_failures"] == 3
    assert snap["corruption_retries"] == 2


# -- serve-time truncation guard ----------------------------------------------


def test_truncated_shuffle_file_raises_typed_short_read(tmp_path):
    from ballista_tpu.flight.server import start_flight_server

    work, locs, rows, schema = _write_stage(tmp_path, sort=True, partitions=2)
    path = locs[0][0].path
    os.truncate(path, os.path.getsize(path) - 16)
    with open(sp.index_path(path)) as f:
        index = json.load(f)
    last_p = int(max(index, key=lambda k: index[k][0]))
    with pytest.raises(ShortRead) as ei:
        sp.open_range_buffer(path, "sort", last_p)
    assert ei.value.size < ei.value.offset + ei.value.length
    server, port = start_flight_server(work, "127.0.0.1", 0)
    try:
        with pytest.raises(FetchFailed):
            _read_remote(schema, {0: locs[last_p]}, port,
                         {SHUFFLE_FETCH_COALESCE: False, IO_RETRIES: 0,
                          IO_RETRY_WAIT_MS: 1})
        assert server.stats["short_reads"] >= 1
    finally:
        server.shutdown()


# -- native C++ server parity -------------------------------------------------


@pytest.fixture(scope="module")
def native_flight_work(tmp_path_factory):
    from ballista_tpu.executor.executor_process import start_native_flight_server

    work = str(tmp_path_factory.mktemp("native-integrity"))
    started = start_native_flight_server(work, "127.0.0.1", 0)
    if started is None:
        pytest.skip("native flight server unavailable")
    proc, port = started
    yield work, port
    proc.terminate()
    proc.wait(timeout=5)


def test_native_server_ships_checksums_and_guards_truncation(native_flight_work):
    """The C++ data plane must ship the same checksum headers as the python
    server (block want_crc opt-in + coalesced "crc" key), reject truncated
    ranges, and pass the python reader's verification end to end."""
    import pyarrow.flight as flight

    work, port = native_flight_work
    _, locs, rows, schema = _write_stage(work, sort=True, partitions=2)
    target = locs[0][0]
    client = flight.FlightClient(f"grpc://127.0.0.1:{port}")
    expected = sp.checksum_for(target.path, target.layout, 0)
    assert expected is not None

    # block path: want_crc prepends a {"nbytes", "crc"} header result
    ticket = {"path": target.path, "layout": target.layout,
              "output_partition": 0, "want_crc": True}
    results = list(client.do_action(flight.Action(
        "io_block_transport", json.dumps(ticket).encode())))
    hdr = json.loads(results[0].body.to_pybytes())
    assert hdr["crc"] == expected
    body = b"".join(r.body.to_pybytes() for r in results[1:])
    assert hdr["nbytes"] == len(body)
    assert checksum_bytes(body) == expected
    # without the opt-in, the stream is bare blocks (legacy clients)
    del ticket["want_crc"]
    results = list(client.do_action(flight.Action(
        "io_block_transport", json.dumps(ticket).encode())))
    assert not results[0].body.to_pybytes().startswith(b"{")

    # coalesced header carries the crc
    results = list(client.do_action(flight.Action(
        "io_coalesced_transport",
        json.dumps({"locations": [{"path": target.path, "layout": target.layout,
                                   "output_partition": 0}]}).encode())))
    hdr = json.loads(results[0].body.to_pybytes())
    assert hdr["i"] == 0 and hdr["crc"] == expected

    # the python reader verifies against the native server's headers
    got, reader = _read_remote(schema, locs, port)
    assert sum(got) == rows
    assert reader.metrics.extra["checksum_failures"] == 0

    # truncation guard: an index range past EOF is a typed serve error
    os.truncate(target.path, os.path.getsize(target.path) - 8)
    with open(sp.index_path(target.path)) as f:
        index = json.load(f)
    last_p = int(max(index, key=lambda k: index[k][0]))
    with pytest.raises(flight.FlightError, match="truncated"):
        list(client.do_action(flight.Action(
            "io_block_transport",
            json.dumps({"path": target.path, "layout": target.layout,
                        "output_partition": last_p}).encode())))


# -- checksummed job-state checkpoints ----------------------------------------


def test_graph_checkpoint_framing_roundtrip_and_tamper():
    from ballista_tpu.scheduler.state.job_state import (
        GRAPH_MAGIC,
        _frame_graph,
        _unframe_graph,
    )

    payload = b"\x08\x01\x12\x04jobx" * 9
    framed = _frame_graph(payload)
    assert framed.startswith(GRAPH_MAGIC)
    assert _unframe_graph(framed) == payload
    assert _unframe_graph(payload) == payload  # legacy: no magic → pass-through
    bad = bytearray(framed)
    bad[-1] ^= 0x01
    with pytest.raises(ValueError, match="CRC mismatch"):
        _unframe_graph(bytes(bad))
    with pytest.raises(ValueError, match="truncated"):
        _unframe_graph(GRAPH_MAGIC + b"\x00")


def test_corrupt_checkpoint_skipped_on_recover(tmp_path, tpch_ctx, caplog):
    import logging

    from ballista_tpu.scheduler.state.job_state import FileJobState

    from .test_distributed import _tiny_graph

    g = _tiny_graph(tpch_ctx)
    store = FileJobState(str(tmp_path))
    store.save_graph(g)
    loaded = store.load_graph(g.job_id)
    assert loaded is not None and loaded.job_id == g.job_id
    # flip a payload bit: the CRC check must reject the whole checkpoint
    path = os.path.join(str(tmp_path), f"{g.job_id}.graph")
    _corrupt_on_disk(path, offset=os.path.getsize(path) - 3)
    with caplog.at_level(logging.WARNING):
        assert store.load_graph(g.job_id) is None
    assert any("torn/corrupt" in r.message for r in caplog.records)
    assert os.path.exists(path + ".bad")  # quarantined, not re-adopted
    assert store.load_graph(g.job_id) is None  # gone from the store
