"""AQE + chaos tests (reference: scheduler/src/state/aqe/test/,
chaos robustness runs)."""

import pyarrow as pa
import pytest

from ballista_tpu.config import (
    AQE_TARGET_PARTITION_BYTES,
    BallistaConfig,
    CHAOS_ENABLED,
    CHAOS_MODE,
    CHAOS_PROBABILITY,
    CHAOS_SEED,
    DEFAULT_SHUFFLE_PARTITIONS,
    PLANNER_ADAPTIVE_ENABLED,
)
from ballista_tpu.scheduler.aqe.rules import coalesce_groups
from ballista_tpu.testing.reference import compare_results, run_reference

from .conftest import tpch_query


def test_coalesce_groups_binpack():
    # 8 buckets of 10 bytes, target 35 → 3 groups
    groups = coalesce_groups([10] * 8, 35, 5, 1.2)
    assert [len(g) for g in groups] == [4, 4]
    # skewed: big bucket alone, small ones packed
    groups = coalesce_groups([100, 1, 1, 1, 100, 1], 50, 2, 1.0)
    flat = [i for g in groups for i in g]
    assert flat == list(range(6))
    # tiny tail merges backwards
    groups = coalesce_groups([40, 40, 1], 45, 5, 1.0)
    assert groups[-1][-1] == 2 and len(groups) == 2


def test_aqe_coalescing_end_to_end(tpch_dir, tpch_ref_tables):
    """Large shuffle partition count + tiny data → AQE shrinks reduce tasks."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 16,
        PLANNER_ADAPTIVE_ENABLED: True,
        AQE_TARGET_PARTITION_BYTES: 1 << 30,  # everything packs into one group
    })
    ctx = SessionContext.standalone(cfg, num_executors=1, vcores=4)
    register_tpch(ctx, tpch_dir)
    try:
        eng = ctx.sql(tpch_query(3)).collect()
        problems = compare_results(eng, run_reference(3, tpch_ref_tables), 3)
        assert not problems, "\n".join(problems)
        # at least one stage must have been coalesced below 16 partitions
        sched = ctx._cluster.scheduler
        with sched._jobs_lock:
            g = list(sched.jobs.values())[-1]
        coalesced = [
            s for s in g.stages.values()
            if s.effective_partitions < s.spec.partitions
        ]
        assert coalesced, g.display()
    finally:
        ctx.shutdown()


def test_aqe_empty_propagation(tpch_dir):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({PLANNER_ADAPTIVE_ENABLED: True, DEFAULT_SHUFFLE_PARTITIONS: 4})
    ctx = SessionContext.standalone(cfg, num_executors=1)
    register_tpch(ctx, tpch_dir)
    try:
        # impossible predicate → empty side → inner join prunes to empty
        out = ctx.sql(
            "select n_name, r_name from nation join region on n_regionkey = r_regionkey "
            "where r_name = 'NOWHERE'"
        ).collect()
        assert out.num_rows == 0
    finally:
        ctx.shutdown()


def test_chaos_transient_retries_converge(tpch_dir, tpch_ref_tables):
    """Transient injected failures must be retried to a correct result."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({
        CHAOS_ENABLED: True, CHAOS_MODE: "transient", CHAOS_PROBABILITY: 0.25,
        CHAOS_SEED: 7, DEFAULT_SHUFFLE_PARTITIONS: 4,
    })
    ctx = SessionContext.standalone(cfg, num_executors=1, vcores=4)
    register_tpch(ctx, tpch_dir)
    try:
        eng = ctx.sql(tpch_query(6)).collect()
        problems = compare_results(eng, run_reference(6, tpch_ref_tables), 6)
        assert not problems, "\n".join(problems)
    finally:
        ctx.shutdown()


def test_chaos_fatal_fails_job(tpch_dir):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.errors import ExecutionError
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({
        CHAOS_ENABLED: True, CHAOS_MODE: "fatal", CHAOS_PROBABILITY: 1.0,
    })
    ctx = SessionContext.standalone(cfg, num_executors=1)
    register_tpch(ctx, tpch_dir)
    try:
        with pytest.raises(ExecutionError, match="chaos"):
            ctx.sql("select count(*) from lineitem").collect()
    finally:
        ctx.shutdown()


def test_incremental_broadcast_elision_virtual():
    """AdaptivePlanner::replan_stages analog: when the partitioned join's
    build input finishes tiny BEFORE the probe shuffle starts, the join is
    replanned to CollectLeft broadcast and the probe stage's hash writer is
    rewritten to passthrough — the probe-side shuffle is elided."""
    import numpy as np

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import BROADCAST_JOIN_ROWS_THRESHOLD
    from ballista_tpu.plan.physical import HashJoinExec
    from ballista_tpu.scheduler.planner import DistributedPlanner
    from ballista_tpu.scheduler.state.execution_graph import ExecutionGraph
    from ballista_tpu.shuffle.reader import UnresolvedShuffleExec

    from .test_distributed import _fake_success

    rng = np.random.default_rng(3)
    cfg = BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 4,
        BROADCAST_JOIN_ROWS_THRESHOLD: 1000,  # planner estimate (10k) exceeds
    })
    ctx = SessionContext(cfg)
    ctx.register_arrow_table("fact", pa.table({
        "k": rng.integers(0, 10_000, 50_000), "v": rng.integers(0, 100, 50_000),
    }), partitions=4)
    ctx.register_arrow_table("dim", pa.table({
        "k": np.arange(10_000), "x": rng.integers(0, 200, 10_000),
    }), partitions=2)
    sql = "select fact.k, sum(v) s from fact, dim where fact.k = dim.k and x = 1 group by fact.k"
    physical = ctx.create_physical_plan(ctx.sql(sql).plan)
    # the planner must have chosen partitioned mode (estimates too big) —
    # deferred behind a DynamicJoinSelectionExec since the planner emits
    # the decision node for partitioned joins
    from ballista_tpu.ops.cpu.dynamic_join import DynamicJoinSelectionExec

    def find_joins(n):
        if isinstance(n, (HashJoinExec, DynamicJoinSelectionExec)):
            yield n
        for c in n.children():
            yield from find_joins(c)
    assert any(j.mode == "partitioned" for j in find_joins(physical)), physical.display()

    stages = DistributedPlanner("jobi").plan_query_stages(physical)
    g = ExecutionGraph("jobi", "", "s1", stages, cfg)
    # identify build (dim-side hash) and probe (fact-side hash) stages: the
    # join stage consumes both; build was planned first (lower id)
    join_stage = next(
        s for s in stages
        if any(isinstance(n, (HashJoinExec, DynamicJoinSelectionExec))
               for n in _walk_plan(s.plan))
    )
    b_id, p_id = sorted(join_stage.input_stage_ids)[:2]
    # run ONLY the build stage to completion (tiny actual output)
    guard = 0
    while g.stages[b_id].state.value != "successful" and guard < 100:
        guard += 1
        t = g.pop_next_task("e1")
        assert t is not None and t.stage_id == b_id, f"expected build task, got {t}"
        _fake_success(g, t)
    # elision must have fired: probe writer is now passthrough
    assert g.stages[p_id].spec.plan.output_partitions == 0, "probe shuffle not elided"
    joins = [
        n for n in _walk_plan(g.stages[join_stage.stage_id].spec.plan)
        if isinstance(n, HashJoinExec)
    ]
    assert joins and joins[0].mode == "collect_left"
    assert isinstance(joins[0].left, UnresolvedShuffleExec) and joins[0].left.broadcast
    assert g.stages[b_id].spec.broadcast
    # and the graph still runs to completion with the rewritten stages
    guard = 0
    while g.status.value == "running" and guard < 1000:
        guard += 1
        t = g.pop_next_task("e1")
        if t is None:
            break
        _fake_success(g, t)
    assert g.status.value == "successful", g.display()


def test_incremental_elision_end_to_end(tmp_path):
    """Same shape through a real standalone cluster: results must match the
    local engine regardless of when the elision window hits."""
    import numpy as np
    import pyarrow.parquet as pq

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import BROADCAST_JOIN_ROWS_THRESHOLD

    rng = np.random.default_rng(4)
    d = str(tmp_path)
    pq.write_table(pa.table({
        "k": rng.integers(0, 10_000, 50_000), "v": rng.integers(0, 100, 50_000),
    }), f"{d}/fact.parquet")
    pq.write_table(pa.table({
        "k": np.arange(10_000), "x": rng.integers(0, 200, 10_000),
    }), f"{d}/dim.parquet")
    cfg = BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 4,
        BROADCAST_JOIN_ROWS_THRESHOLD: 1000,
    })
    sql = "select fact.k, sum(v) s from fact, dim where fact.k = dim.k and x = 1 group by fact.k order by s desc, fact.k limit 20"
    dist = SessionContext.standalone(cfg, num_executors=1, vcores=1)
    dist.register_parquet("fact", f"{d}/fact.parquet")
    dist.register_parquet("dim", f"{d}/dim.parquet")
    local = SessionContext(cfg)
    local.register_parquet("fact", f"{d}/fact.parquet")
    local.register_parquet("dim", f"{d}/dim.parquet")
    try:
        a = dist.sql(sql).collect().to_pandas()
        b = local.sql(sql).collect().to_pandas()
        assert a.k.tolist() == b.k.tolist()
        assert a.s.tolist() == b.s.tolist()
    finally:
        dist.shutdown()


def _walk_plan(node):
    yield node
    for c in node.children():
        yield from _walk_plan(c)


def test_incremental_empty_cascade_skips_and_cancels():
    """alter_stages analog: when the build input finishes EMPTY, the join
    stage is proven empty and completes WITHOUT scheduling, the probe-side
    shuffle stage is cancelled as unconsumed, and the job still finishes."""
    import numpy as np

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.plan.physical import HashJoinExec
    from ballista_tpu.scheduler.planner import DistributedPlanner
    from ballista_tpu.scheduler.state.execution_graph import ExecutionGraph

    from .test_distributed import _fake_success

    from ballista_tpu.config import BROADCAST_JOIN_ROWS_THRESHOLD

    rng = np.random.default_rng(5)
    cfg = BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 4,
        BROADCAST_JOIN_ROWS_THRESHOLD: 1000,  # force partitioned mode
    })
    ctx = SessionContext(cfg)
    ctx.register_arrow_table("fact", pa.table({
        "k": rng.integers(0, 10_000, 50_000), "v": rng.integers(0, 100, 50_000),
    }), partitions=4)
    ctx.register_arrow_table("dim", pa.table({
        "k": np.arange(10_000), "x": rng.integers(0, 200, 10_000),
    }), partitions=2)
    sql = "select fact.k, sum(v) s from fact, dim where fact.k = dim.k and x = 1 group by fact.k"
    physical = ctx.create_physical_plan(ctx.sql(sql).plan)
    from ballista_tpu.ops.cpu.dynamic_join import DynamicJoinSelectionExec

    stages = DistributedPlanner("jobe").plan_query_stages(physical)
    g = ExecutionGraph("jobe", "", "s1", stages, cfg)
    join_stage = next(
        s for s in stages
        if any(isinstance(n, (HashJoinExec, DynamicJoinSelectionExec))
               for n in _walk_plan(s.plan))
    )
    b_id, p_id = sorted(join_stage.input_stage_ids)[:2]

    # pop tasks WITHOUT completing them until a probe task is in flight,
    # so the cancellation path has a genuinely running task to revoke
    popped = []
    t_probe = None
    guard = 0
    while t_probe is None and guard < 50:
        guard += 1
        t = g.pop_next_task("e-probe")
        assert t is not None
        if t.stage_id == p_id:
            t_probe = t
        else:
            popped.append(t)
    # now finish the build stage with EMPTY output (zero locations)
    for t in popped:
        assert t.stage_id == b_id, t
        g.update_task_status(t.task_id, t.stage_id, t.stage_attempt,
                             "success", t.partitions, [])
    assert g.stages[b_id].state.value == "successful"

    # the join stage was proven empty and completed without running
    js = g.stages[join_stage.stage_id]
    assert js.state.value == "successful" and js.skipped
    # the probe stage is no longer consumed: cancelled, its running task queued
    ps = g.stages[p_id]
    assert ps.state.value == "successful" and ps.skipped
    doomed = g.drain_cancelled_tasks()
    assert any(tid == t_probe.task_id for (_e, tid, _s) in doomed), doomed
    # and the rest of the graph still completes
    guard = 0
    while g.status.value == "running" and guard < 1000:
        guard += 1
        t = g.pop_next_task("e1")
        if t is None:
            break
        _fake_success(g, t)
    assert g.status.value == "successful"


def test_alter_fanout_virtual():
    """Stage-alteration replanning (alter_stages.rs analog): a middle
    stage's hash fan-out shrinks at resolution when its observed input
    volume proves the planned bucket count too high, and the downstream
    consumer is repartitioned to the new K before it resolves."""
    import numpy as np

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.scheduler.planner import DistributedPlanner
    from ballista_tpu.scheduler.state.execution_graph import ExecutionGraph

    from .test_distributed import _fake_success

    rng = np.random.default_rng(7)
    cfg = BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 32,
        PLANNER_ADAPTIVE_ENABLED: True,
    })
    ctx = SessionContext(cfg)
    ctx.register_arrow_table("t", pa.table({
        "k": rng.integers(0, 1000, 20_000), "v": rng.integers(0, 100, 20_000),
    }), partitions=4)
    sql = ("select k2, sum(s) t from (select k % 10 k2, sum(v) s from t group by k) q "
           "group by k2")
    physical = ctx.create_physical_plan(ctx.sql(sql).plan)
    stages = DistributedPlanner("jobf").plan_query_stages(physical)
    g = ExecutionGraph("jobf", "", "s1", stages, cfg)
    # find the middle stage: hash writer whose every leaf is a shuffle input
    from ballista_tpu.shuffle.reader import UnresolvedShuffleExec

    def leaves(n):
        kids = n.children()
        if not kids:
            yield n
        for c in kids:
            yield from leaves(c)

    mids = [
        s for s in g.stages.values()
        if s.spec.plan.output_partitions > 1
        and s.spec.input_stage_ids
        and all(isinstance(l, UnresolvedShuffleExec) for l in leaves(s.spec.plan.input))
    ]
    assert mids, g.display()
    mid = mids[0]
    planned_k = mid.spec.plan.output_partitions
    assert planned_k == 32
    consumer = g.stages[g.output_links[mid.stage_id][0]]
    assert consumer.spec.partitions == planned_k
    # run the upstream (leaf) stages; _fake_success reports ~10-byte outputs
    guard = 0
    while mid.state.value == "unresolved" and guard < 200:
        guard += 1
        t = g.pop_next_task("e1")
        assert t is not None
        _fake_success(g, t)
    # resolution must have altered the fan-out and repartitioned the consumer
    new_k = mid.spec.plan.output_partitions
    assert 0 < new_k <= planned_k // 2, f"fan-out not altered: {new_k}"
    assert mid.spec.output_partitions == new_k
    assert consumer.spec.partitions == new_k
    assert len(consumer.pending) == new_k
    # the graph still runs to completion with the altered stages
    guard = 0
    while g.status.value == "running" and guard < 1000:
        guard += 1
        t = g.pop_next_task("e1")
        if t is None:
            break
        _fake_success(g, t)
    assert g.status.value == "successful", g.display()


def test_alter_fanout_end_to_end(tpch_dir, tpch_ref_tables):
    """Same alteration through a real standalone cluster: tiny data with an
    oversized shuffle partition count — results must match the oracle and
    some middle stage must have shrunk its fan-out."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 24,
        PLANNER_ADAPTIVE_ENABLED: True,
    })
    ctx = SessionContext.standalone(cfg, num_executors=1, vcores=2)
    register_tpch(ctx, tpch_dir)
    try:
        eng = ctx.sql(tpch_query(13)).collect()  # nested agg: customer × orders → distribution
        problems = compare_results(eng, run_reference(13, tpch_ref_tables), 13)
        assert not problems, "\n".join(problems)
        sched = ctx._cluster.scheduler
        with sched._jobs_lock:
            g = list(sched.jobs.values())[-1]
        altered = [
            s for s in g.stages.values()
            if 0 < s.spec.plan.output_partitions < 24 and s.spec.input_stage_ids
        ]
        assert altered, g.display()
    finally:
        ctx.shutdown()


# ---------------------------------------------------------------- skew AQE


def _write_skew_tables(d):
    """Parquet join inputs with nulls, strings and duplicate keys: 4 fact
    files (the multi-file scan is what gives each map task its own output
    locations — slicing needs >= 2 map outputs per hot bucket) + 2 dim
    files so the dim side shuffles too."""
    import os

    import numpy as np
    import pyarrow.parquet as pq

    rng = np.random.default_rng(11)
    os.makedirs(f"{d}/fact")
    os.makedirs(f"{d}/dim")
    for i in range(4):
        n = 15_000
        pq.write_table(pa.table({
            "k": rng.integers(0, 2000, n),
            "v": rng.integers(0, 100, n),
            "s": pa.array([f"row{j % 97}" if j % 13 else None for j in range(n)]),
        }), f"{d}/fact/part{i}.parquet")
    for i in range(2):
        pq.write_table(pa.table({
            "k": np.arange(i * 1000, (i + 1) * 1000),
            "x": rng.integers(0, 200, 1000),
        }), f"{d}/dim/part{i}.parquet")


def _aqe_counter(key: str) -> int:
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

    return int(RUN_STATS.snapshot().get(key, 0) or 0)


def _run_skew_join(d, skew_aqe: bool):
    """Skewed fact⋈dim under chaos skew; returns (result, graph)."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import (
        AQE_SKEW_ENABLED,
        AQE_SKEW_MIN_BYTES,
        BROADCAST_JOIN_ROWS_THRESHOLD,
        CHAOS_SKEW_FRACTION,
        DEBUG_PLAN_VERIFY,
    )

    cfg = BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 8,
        PLANNER_ADAPTIVE_ENABLED: True,
        BROADCAST_JOIN_ROWS_THRESHOLD: 100,  # force the partitioned join
        CHAOS_ENABLED: True, CHAOS_MODE: "skew", CHAOS_SEED: 5,
        CHAOS_SKEW_FRACTION: 0.7,
        AQE_SKEW_ENABLED: skew_aqe,
        AQE_SKEW_MIN_BYTES: 1024,
        AQE_TARGET_PARTITION_BYTES: 64 * 1024,
        DEBUG_PLAN_VERIFY: True,  # plan_check gates every resolution
    })
    ctx = SessionContext.standalone(cfg, num_executors=1, vcores=4)
    ctx.register_parquet("fact", f"{d}/fact")
    ctx.register_parquet("dim", f"{d}/dim")
    try:
        out = ctx.sql(
            "select fact.k, v, s, x from fact join dim on fact.k = dim.k"
        ).collect()
        sched = ctx._cluster.scheduler
        with sched._jobs_lock:
            g = list(sched.jobs.values())[-1]
        assert g.status.value == "successful", g.display()
        return out, g
    finally:
        ctx.shutdown()


def test_skew_split_byte_parity_and_coalesce_interaction(tmp_path):
    """Chaos `skew` piles ~70% of fact rows onto one reduce bucket; the
    resolution-time split must slice it into partition-range tasks while
    the cold buckets still coalesce, and the merged result must be
    byte-identical to the unsplit oracle (null/string/duplicate-key rows
    cross the slice boundaries)."""
    _write_skew_tables(tmp_path)
    before = _aqe_counter("skew_splits")
    split_out, g = _run_skew_join(tmp_path, skew_aqe=True)
    oracle_out, og = _run_skew_join(tmp_path, skew_aqe=False)

    reports = {s.stage_id: s.skew_report for s in g.stages.values() if s.skew_report}
    assert reports, g.display()
    (report,) = reports.values()
    assert report.splits and report.extra_partitions >= 1
    assert all(len(s.partitions) >= 2 for s in report.splits)
    # interaction: the same resolution also coalesced the cold segment, so
    # the stage's effective count is NOT planned + extra_partitions
    st = g.stages[next(iter(reports))]
    assert st.effective_partitions != st.spec.partitions
    assert st.effective_partitions < st.spec.partitions + report.extra_partitions
    assert not any(s.skew_report for s in og.stages.values())
    assert _aqe_counter("skew_splits") >= before + 1

    assert split_out.num_rows == oracle_out.num_rows
    assert split_out.to_pandas().equals(oracle_out.to_pandas()), \
        "skew-split result diverged from unsplit oracle"


def test_plan_check_rejects_corrupted_split(tmp_path):
    """plan_check's skew rule proves cover/no-overlap/order of the slice
    readers against the producer's locations — corrupting either property
    after resolution must raise a skew-cover / skew-order violation."""
    import copy

    from ballista_tpu.analysis.plan_check import verify_graph

    _write_skew_tables(tmp_path)
    _, g = _run_skew_join(tmp_path, skew_aqe=True)
    st = next(s for s in g.stages.values() if s.skew_report)
    assert not verify_graph(g), "resolved split graph must verify clean"

    split = st.skew_report.splits[0]
    from ballista_tpu.shuffle.reader import ShuffleReaderExec

    def probe_readers():
        from ballista_tpu.analysis.plan_check import _shuffle_leaves

        return [
            r for r in _shuffle_leaves(st.resolved_plan)
            if isinstance(r, ShuffleReaderExec) and not r.broadcast
            # the sliced (probe) reader's slice lists differ; the
            # duplicated build side's are identical
            and r.partition_locations[split.partitions[0]]
            != r.partition_locations[split.partitions[1]]
        ]

    # order corruption: swap two slices' location lists in place
    r = probe_readers()[0]
    p0, p1 = split.partitions[0], split.partitions[1]
    saved = copy.copy(r.partition_locations)
    r.partition_locations[p0], r.partition_locations[p1] = (
        r.partition_locations[p1], r.partition_locations[p0])
    codes = {v.code for v in verify_graph(g)}
    assert "skew-order" in codes, codes
    r.partition_locations = saved

    # cover corruption: a slice loses one of its map outputs
    r = probe_readers()[0]
    victim = next(p for p in split.partitions if len(r.partition_locations[p]) > 0)
    saved_list = r.partition_locations[victim]
    r.partition_locations[victim] = saved_list[:-1]
    codes = {v.code for v in verify_graph(g)}
    assert "skew-cover" in codes, codes
    r.partition_locations[victim] = saved_list
    assert not verify_graph(g)


# -------------------------------------------------- runtime join switching


def _dyn_join(planned_mode: str):
    import numpy as np

    from ballista_tpu.engine.physical_planner import _join_exec_schema
    from ballista_tpu.ops.cpu.dynamic_join import DynamicJoinSelectionExec
    from ballista_tpu.plan.expressions import Column
    from ballista_tpu.plan.physical import MemoryScanExec
    from ballista_tpu.plan.schema import DFSchema

    def scan(name):
        t = pa.table({name: np.arange(8, dtype="int64")})
        return MemoryScanExec(DFSchema.from_arrow(t.schema), t.to_batches(), 4)

    left, right = scan("bk"), scan("pk")
    schema = _join_exec_schema(left.df_schema, right.df_schema, "inner")
    return DynamicJoinSelectionExec(
        left, right, [(Column("bk"), Column("pk"))], "inner", None, schema,
        planned_mode=planned_mode)


def test_broadcast_demotion_on_oversized_build():
    """A hedged broadcast (planned_mode=collect_left) whose build arrives
    past BOTH thresholds must resolve to a partitioned join and count a
    broadcast demotion; a build that confirms small keeps collect_left and
    counts nothing."""
    from ballista_tpu.plan.physical import HashJoinExec

    before = _aqe_counter("broadcast_demotions")
    j = _dyn_join("collect_left")
    out = j.resolve_with_stats(
        l_bytes=1 << 30, l_rows=1 << 22, r_bytes=1 << 31, r_rows=1 << 23,
        byte_thr=1 << 20, rows_thr=1 << 20)
    assert isinstance(out, HashJoinExec) and out.mode == "partitioned"
    assert _aqe_counter("broadcast_demotions") == before + 1

    # oversized-in-rows-only demotes too (the wire budget is byte-bound,
    # but the collect hash table is row-bound)
    j = _dyn_join("collect_left")
    out = j.resolve_with_stats(
        l_bytes=1 << 10, l_rows=1 << 22, r_bytes=1 << 30, r_rows=1 << 23,
        byte_thr=1 << 20, rows_thr=1 << 20)
    assert getattr(out, "mode", "") != "collect_left"
    assert _aqe_counter("broadcast_demotions") == before + 2

    # confirmation: the hedge was paranoia, the build really is small
    base_p = _aqe_counter("broadcast_promotions")
    j = _dyn_join("collect_left")
    out = j.resolve_with_stats(
        l_bytes=1 << 10, l_rows=100, r_bytes=1 << 30, r_rows=1 << 23,
        byte_thr=1 << 20, rows_thr=1 << 20)
    assert getattr(out, "mode", "") == "collect_left"
    assert _aqe_counter("broadcast_demotions") == before + 2
    assert _aqe_counter("broadcast_promotions") == base_p


def test_broadcast_promotion_counts():
    """The mirror switch: a join planned partitioned whose build proves
    tiny at resolution promotes to collect_left and counts a promotion."""
    before = _aqe_counter("broadcast_promotions")
    j = _dyn_join("partitioned")
    out = j.resolve_with_stats(
        l_bytes=1 << 10, l_rows=100, r_bytes=1 << 30, r_rows=1 << 23,
        byte_thr=1 << 20, rows_thr=1 << 20)
    assert getattr(out, "mode", "") == "collect_left"
    assert _aqe_counter("broadcast_promotions") == before + 1


def test_planner_hedges_near_threshold_broadcasts():
    """A build ESTIMATE within hedge.factor of the broadcast cap plans as a
    co-partitioned DynamicJoinSelectionExec with planned_mode=collect_left
    (demotable at runtime); far below the band it stays a static broadcast,
    and engine=tpu never hedges (only collect-build chains compile into
    device stages)."""
    import numpy as np

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import EXECUTOR_ENGINE
    from ballista_tpu.ops.cpu.dynamic_join import DynamicJoinSelectionExec
    from ballista_tpu.plan.physical import HashJoinExec
    from ballista_tpu.plan.provider import MemoryTable, TableStats

    from .conftest import iter_plan

    class LyingStats(MemoryTable):
        def __init__(self, batches, schema, partitions, rows):
            super().__init__(batches, schema, partitions)
            self._rows = rows

        def statistics(self):
            return TableStats(num_rows=self._rows, total_bytes=self._rows * 64)

    build = pa.table({"k": np.arange(100, dtype="int64"), "v": np.arange(100.0)})
    probe = pa.table({"k": np.arange(100, dtype="int64"), "w": np.arange(100.0)})

    def plan_with(engine, build_rows):
        from ballista_tpu.config import EXECUTOR_ENGINE as ENG

        ctx = SessionContext(BallistaConfig({
            ENG: engine, PLANNER_ADAPTIVE_ENABLED: True,
        }))
        ctx.register_table("b", LyingStats(build.to_batches(), build.schema, 4, build_rows))
        ctx.register_table("p", LyingStats(probe.to_batches(), probe.schema, 4, 40_000_000))
        sql = "SELECT sum(w + v) AS s FROM p JOIN b ON p.k = b.k"
        return list(iter_plan(ctx.create_physical_plan(ctx.sql(sql).plan)))

    # 900k rows: under the 1M cap but within the 4x hedge band → hedged
    hedged = [n for n in plan_with("cpu", 900_000)
              if isinstance(n, DynamicJoinSelectionExec)]
    assert hedged and hedged[0].planned_mode == "collect_left"

    # 100k rows: far below the band → the static broadcast stands
    nodes = plan_with("cpu", 100_000)
    assert not any(isinstance(n, DynamicJoinSelectionExec) for n in nodes)
    assert any(isinstance(n, HashJoinExec) and n.mode == "collect_left"
               for n in nodes)

    # engine=tpu: same 900k estimate must NOT hedge
    nodes = plan_with("tpu", 900_000)
    assert not any(isinstance(n, DynamicJoinSelectionExec) for n in nodes)
    assert any(isinstance(n, HashJoinExec) and n.mode == "collect_left"
               for n in nodes)


# ------------------------------------------------------------- mesh rungs


def _mesh_stage_plan(buckets: int = 8):
    import numpy as np

    from ballista_tpu.ops.tpu.mesh_stage import MeshExchangeExec
    from ballista_tpu.plan.expressions import Column
    from ballista_tpu.plan.physical import MemoryScanExec
    from ballista_tpu.plan.schema import DFSchema
    from ballista_tpu.shuffle.writer import ShuffleWriterExec

    t = pa.table({"k": np.arange(64, dtype="int64")})
    scan = MemoryScanExec(DFSchema.from_arrow(t.schema), t.to_batches(), 4)
    ex = MeshExchangeExec(scan, [Column("k")], buckets)
    return ShuffleWriterExec(ex, "jm", 2, buckets, [Column("k")]), ex


def _mesh_stats(bucket_bytes):
    from ballista_tpu.scheduler.aqe.rules import InputStageStats

    return {1: InputStageStats(
        stage_id=1, total_rows=sum(bucket_bytes) // 8,
        total_bytes=sum(bucket_bytes), bucket_bytes=list(bucket_bytes),
        broadcast=False)}


def test_mesh_aqe_demote_vs_replan():
    """The two mesh-AQE rungs: a hot bucket demotes the fused exchange
    (mesh_mode_reason=demoted:aqe:skew) instead of splitting under it; a
    uniformly small input replans the device bucket count instead of
    coalescing readers; an already-demoted exchange is left alone."""
    from ballista_tpu.config import AQE_SKEW_MIN_BYTES
    from ballista_tpu.ops.tpu.mesh_stage import MeshExchangeExec
    from ballista_tpu.scheduler.aqe.rules import apply_aqe

    from .conftest import iter_plan

    cfg = BallistaConfig({
        PLANNER_ADAPTIVE_ENABLED: True,
        AQE_SKEW_MIN_BYTES: 1024,
        AQE_TARGET_PARTITION_BYTES: 64 * 1024,
    })

    # rung 1: hot bucket → demote, never a split under the exchange
    before = _aqe_counter("aqe_mesh_replans")
    plan, ex = _mesh_stage_plan()
    stats = _mesh_stats([4096] * 7 + [1 << 20])
    out, new_parts, report = apply_aqe(plan, stats, cfg, stage_partitions=8)
    assert new_parts is None and report is None
    # the upstream AQE passes may rebuild the tree, so read the exchange
    # back out of the returned plan rather than trusting the original node
    (demoted,) = [n for n in iter_plan(out) if isinstance(n, MeshExchangeExec)]
    assert demoted.demote_reason == "aqe:skew"
    assert _aqe_counter("aqe_mesh_replans") == before + 1

    # rung 2: uniform small input → bucket-count replan on a fresh exchange
    plan, ex = _mesh_stage_plan()
    stats = _mesh_stats([8192] * 8)  # 64 KiB total → 1 bucket wanted
    out, new_parts, report = apply_aqe(plan, stats, cfg, stage_partitions=8)
    assert report is None
    assert new_parts is not None and 0 < new_parts <= 4
    replanned = [n for n in iter_plan(out) if isinstance(n, MeshExchangeExec)]
    assert replanned and replanned[0].file_partitions == new_parts
    assert not replanned[0].demote_reason
    assert _aqe_counter("aqe_mesh_replans") == before + 2

    # rung 3: an exchange already demoted for capacity is never replanned
    plan, ex = _mesh_stage_plan()
    ex.demote_reason = "capacity"
    out, new_parts, report = apply_aqe(plan, stats, cfg, stage_partitions=8)
    assert new_parts is None and report is None
    assert ex.demote_reason == "capacity"
    assert _aqe_counter("aqe_mesh_replans") == before + 2
