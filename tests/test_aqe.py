"""AQE + chaos tests (reference: scheduler/src/state/aqe/test/,
chaos robustness runs)."""

import pyarrow as pa
import pytest

from ballista_tpu.config import (
    AQE_TARGET_PARTITION_BYTES,
    BallistaConfig,
    CHAOS_ENABLED,
    CHAOS_MODE,
    CHAOS_PROBABILITY,
    CHAOS_SEED,
    DEFAULT_SHUFFLE_PARTITIONS,
    PLANNER_ADAPTIVE_ENABLED,
)
from ballista_tpu.scheduler.aqe.rules import coalesce_groups
from ballista_tpu.testing.reference import compare_results, run_reference

from .conftest import tpch_query


def test_coalesce_groups_binpack():
    # 8 buckets of 10 bytes, target 35 → 3 groups
    groups = coalesce_groups([10] * 8, 35, 5, 1.2)
    assert [len(g) for g in groups] == [4, 4]
    # skewed: big bucket alone, small ones packed
    groups = coalesce_groups([100, 1, 1, 1, 100, 1], 50, 2, 1.0)
    flat = [i for g in groups for i in g]
    assert flat == list(range(6))
    # tiny tail merges backwards
    groups = coalesce_groups([40, 40, 1], 45, 5, 1.0)
    assert groups[-1][-1] == 2 and len(groups) == 2


def test_aqe_coalescing_end_to_end(tpch_dir, tpch_ref_tables):
    """Large shuffle partition count + tiny data → AQE shrinks reduce tasks."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 16,
        PLANNER_ADAPTIVE_ENABLED: True,
        AQE_TARGET_PARTITION_BYTES: 1 << 30,  # everything packs into one group
    })
    ctx = SessionContext.standalone(cfg, num_executors=1, vcores=4)
    register_tpch(ctx, tpch_dir)
    try:
        eng = ctx.sql(tpch_query(3)).collect()
        problems = compare_results(eng, run_reference(3, tpch_ref_tables), 3)
        assert not problems, "\n".join(problems)
        # at least one stage must have been coalesced below 16 partitions
        sched = ctx._cluster.scheduler
        with sched._jobs_lock:
            g = list(sched.jobs.values())[-1]
        coalesced = [
            s for s in g.stages.values()
            if s.effective_partitions < s.spec.partitions
        ]
        assert coalesced, g.display()
    finally:
        ctx.shutdown()


def test_aqe_empty_propagation(tpch_dir):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({PLANNER_ADAPTIVE_ENABLED: True, DEFAULT_SHUFFLE_PARTITIONS: 4})
    ctx = SessionContext.standalone(cfg, num_executors=1)
    register_tpch(ctx, tpch_dir)
    try:
        # impossible predicate → empty side → inner join prunes to empty
        out = ctx.sql(
            "select n_name, r_name from nation join region on n_regionkey = r_regionkey "
            "where r_name = 'NOWHERE'"
        ).collect()
        assert out.num_rows == 0
    finally:
        ctx.shutdown()


def test_chaos_transient_retries_converge(tpch_dir, tpch_ref_tables):
    """Transient injected failures must be retried to a correct result."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({
        CHAOS_ENABLED: True, CHAOS_MODE: "transient", CHAOS_PROBABILITY: 0.25,
        CHAOS_SEED: 7, DEFAULT_SHUFFLE_PARTITIONS: 4,
    })
    ctx = SessionContext.standalone(cfg, num_executors=1, vcores=4)
    register_tpch(ctx, tpch_dir)
    try:
        eng = ctx.sql(tpch_query(6)).collect()
        problems = compare_results(eng, run_reference(6, tpch_ref_tables), 6)
        assert not problems, "\n".join(problems)
    finally:
        ctx.shutdown()


def test_chaos_fatal_fails_job(tpch_dir):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.errors import ExecutionError
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({
        CHAOS_ENABLED: True, CHAOS_MODE: "fatal", CHAOS_PROBABILITY: 1.0,
    })
    ctx = SessionContext.standalone(cfg, num_executors=1)
    register_tpch(ctx, tpch_dir)
    try:
        with pytest.raises(ExecutionError, match="chaos"):
            ctx.sql("select count(*) from lineitem").collect()
    finally:
        ctx.shutdown()
