"""Per-chip executor device pinning (SURVEY §7 step 7: one executor per
chip, scheduler slot = chip; reference analog: the vcore slot model of
executor/src/executor_process.rs:261 + state/executor_manager.rs:62).

Runs on the virtual 8-device CPU mesh from conftest. Three layers pinned:
 * runtime.device_scope commits jax ops to the bound device;
 * an in-process cluster of differently pinned tpu-engine executors keeps
   device placement disjoint (cache keys include the ordinal);
 * real daemon subprocesses accept --device-ordinal and register chip=slot
   metadata with the scheduler.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from .conftest import tpch_query


def test_bound_device_and_scope():
    import jax

    from ballista_tpu.ops.tpu.runtime import bound_device, device_scope

    devs = jax.devices()
    assert len(devs) == 8, "conftest must force an 8-device CPU mesh"
    assert bound_device(3) is devs[3]
    assert bound_device(-1) is None
    with device_scope(3):
        x = jax.numpy.arange(8) * 2
        assert x.devices() == {devs[3]}
    with device_scope(-1):  # unpinned: no-op scope
        y = jax.numpy.arange(4)
        assert y.devices() == {devs[0]}


def test_metadata_serde_roundtrip_ordinal():
    from ballista_tpu.executor.executor import ExecutorMetadata
    from ballista_tpu.serde_control import decode_executor_metadata, encode_executor_metadata

    # ordinal 0 is a valid chip and must survive the wire (explicit presence)
    m0 = ExecutorMetadata(id="e0", device_ordinal=0)
    assert decode_executor_metadata(encode_executor_metadata(m0)).device_ordinal == 0
    # unpinned stays unpinned
    mu = ExecutorMetadata(id="e1")
    assert decode_executor_metadata(encode_executor_metadata(mu)).device_ordinal == -1


@pytest.fixture(scope="module")
def pinned_cluster():
    from ballista_tpu.executor.executor_process import ExecutorProcess
    from ballista_tpu.scheduler.process import SchedulerProcess

    sched = SchedulerProcess(bind_host="127.0.0.1", port=0, rest_port=0)
    sched.start()
    addr = f"127.0.0.1:{sched.port}"
    ex1 = ExecutorProcess(addr, bind_host="127.0.0.1", external_host="127.0.0.1",
                          engine="tpu", device_ordinal=1)
    ex2 = ExecutorProcess(addr, bind_host="127.0.0.1", external_host="127.0.0.1",
                          engine="tpu", device_ordinal=2)
    ex1.start()
    ex2.start()
    time.sleep(0.3)
    yield sched, addr, ex1, ex2
    ex1.shutdown()
    ex2.shutdown()
    sched.shutdown()


def test_pinned_slot_model(pinned_cluster):
    """engine=tpu + pinned chip ⇒ vcores defaults to 1: slots = chips."""
    _, _, ex1, ex2 = pinned_cluster
    assert ex1.metadata.vcores == 1
    assert ex2.metadata.vcores == 1
    assert {ex1.metadata.device_ordinal, ex2.metadata.device_ordinal} == {1, 2}


def test_pinned_cluster_query_and_placement(pinned_cluster, tpch_dir, tpch_ref_tables):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import EXECUTOR_ENGINE, BallistaConfig
    from ballista_tpu.ops.tpu import stage_compiler
    from ballista_tpu.testing.reference import compare_results, run_reference
    from ballista_tpu.testing.tpchgen import register_tpch

    _, addr, ex1, ex2 = pinned_cluster
    from ballista_tpu.config import TPU_MIN_ROWS

    stage_compiler.DEVICE_CACHE._cache.clear()
    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
    ctx = SessionContext.remote(addr, cfg)
    register_tpch(ctx, tpch_dir)
    for q in (1, 6):
        got = ctx.sql(tpch_query(q)).collect()
        problems = compare_results(got, run_reference(q, tpch_ref_tables), q)
        assert not problems, "\n".join(problems)

    # every device-resident table must sit on one of the two pinned chips,
    # never the process default (device 0)
    import jax

    devs = jax.devices()
    tables = list(stage_compiler.DEVICE_CACHE._cache.values())
    assert tables, "tpu engine should have cached at least one device table"
    for dt in tables:
        places = set()
        for c in dt.cols:
            places |= c.devices()
        assert places and places <= {devs[1], devs[2]}, places


def test_health_and_rest_report_ordinal(pinned_cluster):
    sched, _, ex1, _ = pinned_cluster
    with urllib.request.urlopen(
            f"http://127.0.0.1:{ex1.health_port}/health", timeout=5) as r:
        assert json.load(r)["device_ordinal"] == 1
    with urllib.request.urlopen(
            f"http://127.0.0.1:{sched.rest_port}/api/executors", timeout=5) as r:
        info = json.load(r)
    assert {e["device_ordinal"] for e in info} == {1, 2}
    assert all(e["total_slots"] == 1 for e in info)


def _spawn_executor_daemon(addr: str, ordinal: int, work_dir: str):
    """Daemon stderr goes to a FILE under its work dir — a PIPE nobody
    drains would wedge a chatty daemon on a full 64 KiB buffer mid-run."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_PLATFORM_NAME"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    os.makedirs(work_dir, exist_ok=True)
    stderr_path = os.path.join(work_dir, "daemon.stderr")
    p = subprocess.Popen(
        [sys.executable, "-m", "ballista_tpu.executor",
         "--scheduler", addr, "--bind-host", "127.0.0.1",
         "--external-host", "127.0.0.1", "--engine", "tpu",
         "--device-ordinal", str(ordinal), "--work-dir", work_dir,
         "--flight-server", "python", "--log-level", "WARNING"],
        env=env, stdout=subprocess.DEVNULL, stderr=open(stderr_path, "wb"),
    )
    p.stderr_path = stderr_path
    return p


def _daemon_stderr_tail(p) -> str:
    try:
        with open(p.stderr_path, "rb") as f:
            return f.read()[-2000:].decode(errors="replace")
    except OSError:
        return "<no stderr captured>"


def test_pinned_daemon_subprocesses(tmp_path, tpch_dir, tpch_ref_tables):
    """Real daemon processes, each pinned via --device-ordinal, serving a
    remote tpu-engine query (the deployment shape: one daemon per chip)."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import EXECUTOR_ENGINE, TPU_MIN_ROWS, BallistaConfig
    from ballista_tpu.scheduler.process import SchedulerProcess
    from ballista_tpu.testing.reference import compare_results, run_reference
    from ballista_tpu.testing.tpchgen import register_tpch

    sched = SchedulerProcess(bind_host="127.0.0.1", port=0, rest_port=0)
    sched.start()
    addr = f"127.0.0.1:{sched.port}"
    procs = [
        _spawn_executor_daemon(addr, i, str(tmp_path / f"ex{i}")) for i in (0, 1)
    ]
    try:
        deadline = time.time() + 90
        while time.time() < deadline:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{sched.rest_port}/api/executors", timeout=5) as r:
                info = json.load(r)
            if len(info) == 2:
                break
            for p in procs:
                assert p.poll() is None, _daemon_stderr_tail(p)
            time.sleep(0.5)
        assert len(info) == 2, "daemons did not register in time"
        assert {e["device_ordinal"] for e in info} == {0, 1}
        assert all(e["total_slots"] == 1 for e in info)

        cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
        ctx = SessionContext.remote(addr, cfg)
        register_tpch(ctx, tpch_dir)
        got = ctx.sql(tpch_query(6)).collect()
        problems = compare_results(got, run_reference(6, tpch_ref_tables), 6)
        assert not problems, "\n".join(problems)
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        sched.shutdown()
