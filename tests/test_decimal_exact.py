"""Exact decimal semantics on the CPU engine (SURVEY §7 hard-part #2;
reference behavior: DataFusion decimal128 exactness).

The engine keeps decimal128 end-to-end: tight-precision literals, Arrow
arithmetic rules with decimal256 widening, max-precision sums, wire serde
of decimal schemas/literals, and the device money lane fed by unscaled
ints. These tests pin exactness TO THE DIGIT against Python's Decimal — a
float64 engine cannot pass them at these row counts."""

import decimal

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from .conftest import tpch_query

D = decimal.Decimal


@pytest.fixture(scope="module")
def dec_tpch_dir(tmp_path_factory, tpch_dir):
    """TPC-H SF0.01 with the money columns cast to decimal(15,2) — the type
    the reference's generators emit."""
    out = tmp_path_factory.mktemp("dec_tpch")
    money = {
        "lineitem": ["l_extendedprice", "l_discount", "l_tax", "l_quantity"],
        "orders": ["o_totalprice"],
        "customer": ["c_acctbal"],
        "supplier": ["s_acctbal"],
        "part": ["p_retailprice"],
        "partsupp": ["ps_supplycost"],
        "nation": [], "region": [],
    }
    import glob
    import os

    for table, cols in money.items():
        os.makedirs(out / table, exist_ok=True)
        for i, f in enumerate(sorted(glob.glob(f"{tpch_dir}/{table}/*.parquet"))):
            t = pq.read_table(f)
            for c in cols:
                if c in t.column_names:
                    idx = t.column_names.index(c)
                    t = t.set_column(
                        idx, c, t.column(c).cast(pa.decimal128(15, 2)))
            pq.write_table(t, out / table / f"part{i}.parquet")
    return str(out)


@pytest.fixture()
def dec_ctx(dec_tpch_dir):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    ctx = SessionContext()
    register_tpch(ctx, dec_tpch_dir)
    return ctx


def _exact_q6(dec_tpch_dir) -> D:
    """Ground truth for TPC-H q6 revenue computed in Python Decimal."""
    import glob

    total = D(0)
    for f in sorted(glob.glob(f"{dec_tpch_dir}/lineitem/*.parquet")):
        t = pq.read_table(f, columns=["l_shipdate", "l_discount", "l_quantity",
                                      "l_extendedprice"])
        df = t.to_pandas()
        import datetime

        m = (
            (df.l_shipdate >= datetime.date(1994, 1, 1))
            & (df.l_shipdate < datetime.date(1995, 1, 1))
            & (df.l_discount >= D("0.05")) & (df.l_discount <= D("0.07"))
            & (df.l_quantity < 24)
        )
        for p, disc in zip(df.l_extendedprice[m], df.l_discount[m]):
            total += p * disc
    return total


def test_q6_exact_to_the_digit(dec_ctx, dec_tpch_dir):
    out = dec_ctx.sql(tpch_query(6)).collect()
    assert pa.types.is_decimal(out.schema.field(0).type), out.schema
    got = out.to_pandas().iloc[0, 0]
    assert got == _exact_q6(dec_tpch_dir), (got, _exact_q6(dec_tpch_dir))


def test_q1_exact_money_sums(dec_ctx, dec_tpch_dir):
    """q1's sum(l_extendedprice*(1-l_discount)*(1+l_tax)) — the three-way
    decimal product that needs tight literal typing + decimal256 partials —
    must match Python Decimal exactly per group."""
    out = dec_ctx.sql(tpch_query(1)).collect()
    df = out.to_pandas().set_index(["l_returnflag", "l_linestatus"])
    # charge column is exact decimal
    charge_col = next(c for c in out.schema.names if "charge" in c or "1 + l_tax" in c)
    assert pa.types.is_decimal(out.schema.field(charge_col).type), out.schema

    import glob

    want: dict[tuple, D] = {}
    import datetime

    for f in sorted(glob.glob(f"{dec_tpch_dir}/lineitem/*.parquet")):
        t = pq.read_table(f, columns=["l_returnflag", "l_linestatus", "l_shipdate",
                                      "l_extendedprice", "l_discount", "l_tax"])
        df2 = t.to_pandas()
        m = df2.l_shipdate <= datetime.date(1998, 9, 2)
        for rf, ls, p, d, x in zip(df2.l_returnflag[m], df2.l_linestatus[m],
                                   df2.l_extendedprice[m], df2.l_discount[m],
                                   df2.l_tax[m]):
            want[(rf, ls)] = want.get((rf, ls), D(0)) + p * (1 - d) * (1 + x)
    for key, exact in want.items():
        assert df.loc[key, charge_col] == exact, (key, df.loc[key, charge_col], exact)


def test_adversarial_float_error_accumulation():
    """300k × 0.10 sums to exactly 30000.00 — float64 accumulation drifts,
    the decimal engine must not."""
    from ballista_tpu.client.context import SessionContext

    n = 300_000
    t = pa.table({
        "g": pa.array(np.arange(n) % 7, pa.int64()),
        "v": pa.array([D("0.10")] * n, pa.decimal128(15, 2)),
    })
    ctx = SessionContext()
    ctx.register_arrow_table("m", t, partitions=4)
    out = ctx.sql("select sum(v) from m").collect().to_pandas().iloc[0, 0]
    assert out == D("30000.00")
    grouped = ctx.sql("select g, sum(v) s from m group by g order by g").collect()
    per = grouped.to_pandas()
    total = sum(per.s)
    assert total == D("30000.00") and all(
        s in (D("4285.70"), D("4285.80")) for s in per.s)


def test_distributed_decimal_over_the_wire(dec_tpch_dir):
    """q6 through a standalone cluster: decimal schemas and literals must
    round-trip the task/shuffle wire with the same exact answer."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.testing.tpchgen import register_tpch

    ctx = SessionContext.standalone(BallistaConfig(), num_executors=2, vcores=2)
    try:
        register_tpch(ctx, dec_tpch_dir)
        got = ctx.sql(tpch_query(6)).collect().to_pandas().iloc[0, 0]
        assert got == _exact_q6(dec_tpch_dir)
    finally:
        ctx.shutdown()


def test_tpu_engine_decimal_money_lane(dec_tpch_dir):
    """The device path ingests decimal columns as unscaled int64 (exact, no
    float sniffing) and q6/q1 agree with the CPU engine."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import EXECUTOR_ENGINE, TPU_MIN_ROWS, BallistaConfig
    from ballista_tpu.ops.tpu.columnar import encode_column
    from ballista_tpu.testing.tpchgen import register_tpch

    arr = pa.array([D("10.25"), None, D("7.75")], pa.decimal128(15, 2))
    col = encode_column(arr)
    assert col is not None and col.kind == "money" and col.scale == 2
    assert list(col.data) == [1025, 0, 775] and list(col.valid) == [True, False, True]

    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
    tpu_ctx = SessionContext(cfg)
    register_tpch(tpu_ctx, dec_tpch_dir)
    cpu_ctx = SessionContext()
    register_tpch(cpu_ctx, dec_tpch_dir)
    for q in (6, 1):
        got = tpu_ctx.sql(tpch_query(q)).collect().to_pandas()
        want = cpu_ctx.sql(tpch_query(q)).collect().to_pandas()
        assert len(got) == len(want)
        for c in want.columns:
            gv, wv = got[c].values, want[c].values
            if want[c].dtype.kind == "f":
                assert np.allclose(gv.astype(float), wv.astype(float), rtol=1e-9), c
            elif want[c].dtype == object and len(wv) and isinstance(wv[0], D):
                # device partials ride int64 cents; tolerate ≤1 ulp at the
                # declared scale from the float64 fetch path
                for g, w in zip(gv, wv):
                    assert abs(D(str(g)) - w) <= D("0.01") * 2, (c, g, w)
            else:
                assert (gv == wv).all(), c


def test_decimal_literal_and_schema_serde():
    from ballista_tpu.plan.expressions import Literal, literal_type
    from ballista_tpu.serde import (
        decode_literal,
        encode_literal,
        str_to_type,
        type_to_str,
    )

    v = D("-123.4567")
    assert decode_literal(encode_literal(v)) == v
    assert literal_type(v) == pa.decimal128(7, 4)
    for t in (pa.decimal128(15, 2), pa.decimal128(38, 6), pa.decimal256(49, 6)):
        assert str_to_type(type_to_str(t)) == t


def test_decimal_group_key_and_shuffle_routing():
    """GROUP BY on a decimal column hash-partitions (the shuffle router
    needed a decimal branch) and groups exactly."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import DEFAULT_SHUFFLE_PARTITIONS, BallistaConfig

    n = 50_000
    rng = np.random.default_rng(6)
    vals = [D(f"{x}.{y:02d}") for x, y in zip(rng.integers(0, 20, n), rng.integers(0, 100, n))]
    t = pa.table({"d": pa.array(vals, pa.decimal128(15, 2)),
                  "v": pa.array(np.ones(n, np.int64))})
    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 8})
    ctx = SessionContext.standalone(cfg, num_executors=2, vcores=2)
    try:
        ctx.register_arrow_table("m", t, partitions=4)
        out = ctx.sql("select d, count(*) c from m group by d order by d").collect()
        got = {row["d"]: row["c"] for row in out.to_pylist()}
    finally:
        ctx.shutdown()
    import collections

    want = collections.Counter(vals)
    assert got == dict(want)


def test_window_sum_over_decimal_exact():
    from ballista_tpu.client.context import SessionContext

    t = pa.table({
        "id": pa.array([1, 2, 3, 4], pa.int64()),
        "p": pa.array([D("0.10"), D("0.20"), None, D("0.40")], pa.decimal128(15, 2)),
    })
    ctx = SessionContext()
    ctx.register_arrow_table("d", t)
    out = ctx.sql("select id, sum(p) over (order by id) s, min(p) over (order by id) mn "
                  "from d order by id").collect()
    assert pa.types.is_decimal(out.schema.field("s").type)
    assert out.column("s").to_pylist() == [D("0.10"), D("0.30"), D("0.30"), D("0.70")]
    assert out.column("mn").to_pylist() == [D("0.10")] * 4
    out2 = ctx.sql("select id, sum(p) over (order by id rows between 1 preceding "
                   "and current row) s from d order by id").collect()
    assert out2.column("s").to_pylist() == [D("0.10"), D("0.30"), D("0.20"), D("0.40")]


def test_case_branches_mixing_decimal():
    from ballista_tpu.client.context import SessionContext

    t = pa.table({
        "g": pa.array([1, 2], pa.int64()),
        "p": pa.array([D("1.25"), D("2.50")], pa.decimal128(15, 2)),
    })
    ctx = SessionContext()
    ctx.register_arrow_table("d", t)
    # int-literal branch widens with the decimal branch (not int64)
    r = ctx.sql("select g, case when g = 1 then 0 else p end x from d order by g").collect()
    assert pa.types.is_decimal(r.schema.field("x").type), r.schema
    assert r.column("x").to_pylist() == [D("0.00"), D("2.50")]
    # sci-notation literal stays float and must still land in the decimal slot
    r2 = ctx.sql("select g, case when g = 1 then p else 15e-1 end x from d order by g").collect()
    assert r2.column("x").to_pylist()[0] == D("1.25")


def test_arith_type_rules_match_arrow():
    """The planner's decimal_arith_type must predict Arrow's kernel result
    types for the shapes TPC-H hits (the planner/runtime contract)."""
    import pyarrow.compute as pc

    from ballista_tpu.plan.expressions import Column, Literal, decimal_arith_type

    p152 = pa.decimal128(15, 2)
    a = pa.array([D("1.23")], p152)
    b = pa.array([D("2.50")], p152)
    cases = [("+", pc.add), ("-", pc.subtract), ("*", pc.multiply)]
    for op, fn in cases:
        planned = decimal_arith_type(Column("x"), Column("y"), p152, p152, op)
        assert planned == fn(a, b).type, op
    # int literal minimal typing: 1 - dec(15,2) plans (17,2) like the
    # evaluator's tightened scalar produces
    planned = decimal_arith_type(Literal(1), Column("y"), pa.int64(), p152, "-")
    got = pc.subtract(pa.scalar(D(1)), a)
    assert planned == got.type, (planned, got.type)
    # division always plans float64
    assert decimal_arith_type(None, None, p152, p152, "/") == pa.float64()
