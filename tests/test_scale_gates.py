"""Reproducible scale gates (reference: .github/workflows/tpch.yml — the
SF10 distributed correctness matrix, scaled to what one host runs on
demand). Excluded from the default run by pytest.ini; invoke explicitly:

    python -m pytest -m sf1    # all 22 queries, 2 daemons, remote reads
    python -m pytest -m sf10   # SF10-shaped single-query leg

Data generates once into /tmp and is reused across invocations.

Every gate also carries the `slow` marker: an explicit command-line
`-m` (like the bounded tier-1 run's `-m 'not slow'`) REPLACES the
pytest.ini addopts exclusion, and these gates need far more wall time
than that run's budget — without the marker they'd eat the whole
budget mid-suite and silently starve every test file sorting after
this one."""

import os
import time

import pytest

from .conftest import tpch_query

pytestmark = pytest.mark.slow


def _dataset(scale: float, tag: str) -> str:
    from ballista_tpu.testing.tpchgen import generate_tpch

    d = os.environ.get("TPCH_DATA", f"/tmp/ballista_tpch_gate_{tag}")
    if not os.path.isdir(os.path.join(d, "lineitem")):
        generate_tpch(d, scale=scale, seed=1, files_per_table=8)
    return d


@pytest.fixture(scope="module")
def sf1_cluster():
    from ballista_tpu.executor.executor_process import ExecutorProcess
    from ballista_tpu.scheduler.process import SchedulerProcess

    sched = SchedulerProcess(bind_host="127.0.0.1", port=0, rest_port=-1)
    sched.start()
    addr = f"127.0.0.1:{sched.port}"
    ex1 = ExecutorProcess(addr, bind_host="127.0.0.1", external_host="127.0.0.1", vcores=4)
    ex2 = ExecutorProcess(addr, bind_host="127.0.0.1", external_host="127.0.0.1",
                          vcores=4, policy="pull")
    ex1.start()
    ex2.start()
    time.sleep(0.3)
    yield addr
    ex1.shutdown()
    ex2.shutdown()
    sched.shutdown()


@pytest.mark.sf1
@pytest.mark.parametrize("q", range(1, 23))
def test_sf1_all22_distributed(q, sf1_cluster):
    """22/22 over a REAL 2-daemon cluster with forced remote Flight reads,
    each query oracle-checked against pandas at SF1."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import SHUFFLE_READER_FORCE_REMOTE, BallistaConfig
    from ballista_tpu.testing.reference import compare_results, load_tables, run_reference
    from ballista_tpu.testing.tpchgen import register_tpch

    data = _dataset(1.0, "sf1")
    global _SF1_REF
    if "_SF1_REF" not in globals() or _SF1_REF is None:
        _SF1_REF = load_tables(data)
    from ballista_tpu.config import CLIENT_JOB_TIMEOUT_S

    cfg = BallistaConfig({SHUFFLE_READER_FORCE_REMOTE: True,
                          CLIENT_JOB_TIMEOUT_S: 2400})
    ctx = SessionContext.remote(sf1_cluster, cfg)
    register_tpch(ctx, data)
    eng = ctx.sql(tpch_query(q)).collect()
    problems = compare_results(eng, run_reference(q, _SF1_REF), q)
    assert not problems, "\n".join(problems)


_SF1_REF = None


@pytest.fixture(scope="module")
def pinned8_cluster(tmp_path_factory):
    """8 real executor daemon subprocesses on one 8-device host, each pinned
    to a distinct device ordinal with slots=chips (SURVEY §7 step 7)."""
    from ballista_tpu.scheduler.process import SchedulerProcess

    from .test_device_binding import _daemon_stderr_tail, _spawn_executor_daemon

    sched = SchedulerProcess(bind_host="127.0.0.1", port=0, rest_port=0)
    sched.start()
    addr = f"127.0.0.1:{sched.port}"
    root = tmp_path_factory.mktemp("pinned8")
    procs = [_spawn_executor_daemon(addr, i, str(root / f"ex{i}")) for i in range(8)]
    import json
    import urllib.request

    deadline = time.time() + 180
    n = 0
    while time.time() < deadline:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{sched.rest_port}/api/executors", timeout=5) as r:
            n = len(json.load(r))
        if n == 8:
            break
        dead = [(p.args[-7], _daemon_stderr_tail(p)) for p in procs if p.poll() is not None]
        assert not dead, f"daemon(s) died during startup: {dead}"
        time.sleep(1.0)
    assert n == 8, (f"only {n}/8 pinned daemons registered; stderr tails: "
                    f"{[_daemon_stderr_tail(p) for p in procs]}")
    yield addr
    import subprocess

    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
    sched.shutdown()


@pytest.mark.pinned8
@pytest.mark.parametrize("q", range(1, 23))
def test_pinned8_all22_sf1(q, pinned8_cluster):
    """All 22 TPC-H queries at SF1 over 8 per-chip-pinned daemon
    subprocesses with the tpu engine, oracle-checked against pandas."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import (
        CLIENT_JOB_TIMEOUT_S,
        EXECUTOR_ENGINE,
        BallistaConfig,
    )
    from ballista_tpu.testing.reference import compare_results, load_tables, run_reference
    from ballista_tpu.testing.tpchgen import register_tpch

    data = _dataset(1.0, "sf1")
    global _SF1_REF
    if "_SF1_REF" not in globals() or _SF1_REF is None:
        _SF1_REF = load_tables(data)
    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", CLIENT_JOB_TIMEOUT_S: 2400})
    ctx = SessionContext.remote(pinned8_cluster, cfg)
    register_tpch(ctx, data)
    eng = ctx.sql(tpch_query(q)).collect()
    problems = compare_results(eng, run_reference(q, _SF1_REF), q)
    assert not problems, "\n".join(problems)


SF10_QUERIES = [1, 3, 6, 9]
_SF10_WANTS: dict = {}


@pytest.fixture(scope="module")
def sf10_wants():
    """Compute ALL oracle results up front, then FREE the ~30 GB of pandas
    tables before any engine run: the engine phase (jax-CPU XLA working
    sets at SF10) and the oracle must never be resident together —
    their sum OOM-killed the combined run on a 125 GB host."""
    import gc

    from ballista_tpu.testing.reference import load_tables, run_reference

    # union of the columns q1/q3/q6/q9 reference: full SF10 tables cost
    # ~40 GB (comment strings dominate) before any merge intermediate
    cols = {
        "lineitem": ["l_shipdate", "l_returnflag", "l_linestatus", "l_quantity",
                     "l_extendedprice", "l_discount", "l_tax", "l_orderkey",
                     "l_partkey", "l_suppkey"],
        "orders": ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"],
        "customer": ["c_custkey", "c_mktsegment"],
        "part": ["p_partkey", "p_name"],
        "partsupp": ["ps_partkey", "ps_suppkey", "ps_supplycost"],
        "supplier": ["s_suppkey", "s_nationkey"],
        "nation": ["n_nationkey", "n_name"],
    }
    if not _SF10_WANTS:
        tables = load_tables(_dataset(10.0, "sf10"), columns=cols)
        for q in SF10_QUERIES:
            _SF10_WANTS[q] = run_reference(q, tables)
        del tables
        gc.collect()
    return _SF10_WANTS


@pytest.mark.sf10
@pytest.mark.parametrize("q", SF10_QUERIES)
def test_sf10_single_query(q, sf10_wants):
    """SF10 leg with the TPU engine (CPU-jax under the conftest pin) and an
    INDEPENDENT pandas oracle — q1/q6 scan-agg plus q3/q9 join+agg, so
    device lowering, shuffle, and spill are all exercised at a scale where
    memory pressure is real (~60M lineitem rows)."""
    import gc

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import CLIENT_JOB_TIMEOUT_S, EXECUTOR_ENGINE, BallistaConfig
    from ballista_tpu.ops.tpu.stage_compiler import clear_device_caches
    from ballista_tpu.testing.reference import compare_results
    from ballista_tpu.testing.tpchgen import register_tpch

    data = _dataset(10.0, "sf10")
    ctx = SessionContext.standalone(
        BallistaConfig({EXECUTOR_ENGINE: "tpu", CLIENT_JOB_TIMEOUT_S: 3600}),
        num_executors=2, vcores=2)
    register_tpch(ctx, data)
    try:
        got = ctx.sql(tpch_query(q)).collect()
    finally:
        ctx.shutdown()
        # unbounded per-query state (join build tables, compiled entries)
        # must not accumulate across the 4 queries on one host
        clear_device_caches()
        gc.collect()
    problems = compare_results(got, sf10_wants[q], q)
    assert not problems, "\n".join(problems)
