"""Variance/stddev aggregate family (reference analog: DataFusion's
VarianceAccumulator feeding Ballista's two-phase distributed aggregation).

The planner decomposes var/stddev into Welford (count, mean, M2) partials
merged with the mean-centered formula — NOT naive sum-of-squares, which
catastrophically cancels (see test_variance_large_magnitude_stability).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from ballista_tpu.client.context import SessionContext
from ballista_tpu.config import BallistaConfig, EXECUTOR_ENGINE, TPU_MIN_ROWS
from ballista_tpu.plan.provider import MemoryTable


def _ctx_with_table(engine: str = "cpu", nulls: bool = False):
    ctx = SessionContext(BallistaConfig({EXECUTOR_ENGINE: engine, TPU_MIN_ROWS: 0}))
    rng = np.random.default_rng(7)
    k = rng.integers(0, 5, 2000)
    v = rng.normal(100.0, 25.0, 2000)
    if nulls:
        vals = [None if i % 89 == 0 else float(v[i]) for i in range(2000)]
    else:
        vals = [float(x) for x in v]
    t = pa.table({"k": pa.array(k, pa.int64()), "v": pa.array(vals, pa.float64())})
    ctx.register_table("t", MemoryTable(t.to_batches()))
    return ctx, t.to_pandas()


@pytest.mark.parametrize("nulls", [False, True])
def test_variance_family_oracle(nulls):
    ctx, df = _ctx_with_table(nulls=nulls)
    out = ctx.sql(
        "select k, stddev(v) as sd, stddev_samp(v) as sds, stddev_pop(v) as sdp, "
        "variance(v) as vr, var_samp(v) as vs, var_pop(v) as vp "
        "from t group by k order by k"
    ).collect().to_pandas()
    g = df.groupby("k")["v"]
    exp = pd.DataFrame({
        "sd": g.std(), "sdp": g.std(ddof=0), "vs": g.var(), "vp": g.var(ddof=0),
    })
    assert len(out) == 5
    for i in range(5):
        assert abs(out.sd[i] - exp.sd.iloc[i]) < 1e-9
        assert abs(out.sds[i] - exp.sd.iloc[i]) < 1e-9
        assert abs(out.sdp[i] - exp.sdp.iloc[i]) < 1e-9
        assert abs(out.vr[i] - exp.vs.iloc[i]) < 1e-9
        assert abs(out.vs[i] - exp.vs.iloc[i]) < 1e-9
        assert abs(out.vp[i] - exp.vp.iloc[i]) < 1e-9


def test_variance_int_column_and_global():
    ctx = SessionContext()
    t = pa.table({"x": pa.array([2, 4, 4, 4, 5, 5, 7, 9], pa.int64())})
    ctx.register_table("ints", MemoryTable(t.to_batches()))
    out = ctx.sql(
        "select stddev_pop(x) as sdp, var_pop(x) as vp, stddev(x) as sd from ints"
    ).collect().to_pandas()
    assert abs(out.sdp[0] - 2.0) < 1e-12  # classic textbook example
    assert abs(out.vp[0] - 4.0) < 1e-12
    assert abs(out.sd[0] - np.std([2, 4, 4, 4, 5, 5, 7, 9], ddof=1)) < 1e-12


def test_variance_degenerate_groups():
    """SQL semantics: sample forms need n>=2 (else NULL); population forms
    give 0 for a single row; all-NULL input gives NULL for both."""
    ctx = SessionContext()
    t = pa.table({
        "k": pa.array([1, 2, 2, 3], pa.int64()),
        "v": pa.array([1.5, 2.0, 4.0, None], pa.float64()),
    })
    ctx.register_table("d", MemoryTable(t.to_batches()))
    out = ctx.sql(
        "select k, stddev(v) as sd, stddev_pop(v) as sdp from d group by k order by k"
    ).collect().to_pandas()
    assert pd.isna(out.sd[0]) and out.sdp[0] == 0.0          # single row
    assert abs(out.sd[1] - np.sqrt(2.0)) < 1e-12             # two rows
    assert pd.isna(out.sd[2]) and pd.isna(out.sdp[2])        # all NULL


def test_variance_tpu_engine_correct():
    """Welford partials aren't device-liftable yet: the engine=tpu path must
    still give exact results (per-subtree CPU fallback, never silent
    wrongness). Device lift of the (cnt, mean, m2) triple is a follow-up."""
    ctx, df = _ctx_with_table(engine="tpu")
    out = ctx.sql(
        "select k, stddev(v) as sd, var_pop(v) as vp from t group by k order by k"
    ).collect().to_pandas()
    g = df.groupby("k")["v"]
    for i in range(5):
        assert abs(out.sd[i] - g.std().iloc[i]) < 1e-9
        assert abs(out.vp[i] - g.var(ddof=0).iloc[i]) < 1e-9


def test_variance_large_magnitude_stability():
    """Regression: the naive q − s²/n decomposition catastrophically cancels
    at epoch-microsecond magnitudes (returned 0.0 for true stddev 25). The
    Welford merge must stay accurate."""
    ctx = SessionContext()
    rng = np.random.default_rng(3)
    v = 1.7e15 + rng.normal(0.0, 25.0, 4000)
    k = rng.integers(0, 3, 4000)
    t = pa.table({"k": pa.array(k, pa.int64()), "v": pa.array(v, pa.float64())})
    ctx.register_table("big", MemoryTable(t.to_batches()))
    out = ctx.sql(
        "select k, stddev(v) as sd, stddev(v - 1700000000000000.0) as sd0 "
        "from big group by k order by k"
    ).collect().to_pandas()
    df = t.to_pandas()
    exp = df.groupby("k")["v"].std()
    for i in range(3):
        # relative error driven by ulp(1.7e15)≈0.25 in the raw data itself;
        # anything under 2% proves the merge didn't cancel (the naive form
        # returns 0.0 or garbage here)
        assert abs(out.sd[i] - exp.iloc[i]) / exp.iloc[i] < 0.02, (out.sd[i], exp.iloc[i])
        assert abs(out.sd0[i] - exp.iloc[i]) / exp.iloc[i] < 0.02


def test_variance_nan_propagates_through_merge():
    """A genuine data NaN (not a null) must surface as NaN from the merged
    result, exactly as a single-partition run would — the merge must not
    zero it into a finite wrong answer."""
    ctx = SessionContext()
    vals = [1.0, 2.0, float("nan"), 3.0, 4.0, 5.0, 6.0, 7.0]
    t = pa.table({"v": pa.array(vals, pa.float64())})
    # two batches → two partial rows merged at the final phase
    batches = pa.table({"v": pa.array(vals[:3], pa.float64())}).to_batches() + \
        pa.table({"v": pa.array(vals[3:], pa.float64())}).to_batches()
    ctx.register_table("nt", MemoryTable(batches))
    out = ctx.sql("select stddev(v) as sd, var_pop(v) as vp from nt").collect().to_pandas()
    assert np.isnan(out.sd[0]) and np.isnan(out.vp[0]), out


def test_variance_distinct_rejected():
    from ballista_tpu.errors import PlanningError

    ctx, _ = _ctx_with_table()
    with pytest.raises(PlanningError):
        ctx.sql("select stddev(distinct v) from t").collect()


def test_stddev_rejected_as_window():
    from ballista_tpu.errors import SqlParseError
    from ballista_tpu.sql.parser import parse_sql

    with pytest.raises(SqlParseError):
        parse_sql("select stddev(x) over (partition by k) from t")
