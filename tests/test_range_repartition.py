"""Dynamic range repartition pipeline (distributed sort support)."""

import numpy as np
import pyarrow as pa

from ballista_tpu.config import BallistaConfig
from ballista_tpu.ops.cpu.range_repartition import (
    BufferExec,
    RuntimeStatsExec,
    UnorderedRangeRepartitionExec,
)
from ballista_tpu.plan.expressions import SortKey, col
from ballista_tpu.plan.physical import (
    CoalescePartitionsExec,
    MemoryScanExec,
    SortExec,
    TaskContext,
)
from ballista_tpu.plan.schema import DFSchema
from ballista_tpu.utils.tdigest import TDigest


def test_tdigest_quantiles():
    d = TDigest()
    rng = np.random.default_rng(0)
    vals = rng.normal(100, 15, 100_000)
    d.add_array(vals)
    for q in (0.1, 0.5, 0.9):
        est = d.quantile(q)
        true = np.quantile(vals, q)
        assert abs(est - true) < 1.0, (q, est, true)
    # merge two digests ≈ one over all data
    d1, d2 = TDigest(), TDigest()
    d1.add_array(vals[:50_000])
    d2.add_array(vals[50_000:])
    d1.merge(d2)
    assert abs(d1.quantile(0.5) - np.quantile(vals, 0.5)) < 1.5
    # round-trip serde
    d3 = TDigest.from_list(d1.to_list())
    assert abs(d3.quantile(0.5) - d1.quantile(0.5)) < 1e-9


def test_range_repartition_total_order():
    rng = np.random.default_rng(1)
    vals = rng.integers(0, 1_000_000, 50_000)
    tbl = pa.table({"x": pa.array(vals, pa.int64())})
    scan = MemoryScanExec(DFSchema.from_arrow(tbl.schema), tbl.to_batches(max_chunksize=4096), partitions=4)
    key = SortKey(col("x"), ascending=True)
    tapped = RuntimeStatsExec(scan, col("x"))
    pipeline = CoalescePartitionsExec(
        SortExec(UnorderedRangeRepartitionExec(BufferExec(tapped), key, 4), [key], None)
    )
    ctx = TaskContext(BallistaConfig())
    out = []
    for b in pipeline.execute(0, ctx):
        out.extend(b.column(0).to_pylist())
    assert out == sorted(vals.tolist())
    # balance: quantile cuts should spread rows across buckets
    # (re-run router alone to inspect)
    router = UnorderedRangeRepartitionExec(RuntimeStatsExec(scan, col("x")), key, 4)
    sizes = []
    for p in range(4):
        n = sum(b.num_rows for b in router.execute(p, TaskContext(BallistaConfig())))
        sizes.append(n)
    assert sum(sizes) == 50_000
    assert max(sizes) < 50_000 * 0.5, sizes  # no bucket hogs everything


def test_range_repartition_descending():
    vals = list(range(1000))
    tbl = pa.table({"x": pa.array(vals, pa.int64())})
    scan = MemoryScanExec(DFSchema.from_arrow(tbl.schema), tbl.to_batches(max_chunksize=100), partitions=2)
    key = SortKey(col("x"), ascending=False)
    pipeline = CoalescePartitionsExec(
        SortExec(UnorderedRangeRepartitionExec(RuntimeStatsExec(scan, col("x")), key, 3), [key], None)
    )
    out = []
    for b in pipeline.execute(0, TaskContext(BallistaConfig())):
        out.extend(b.column(0).to_pylist())
    assert out == sorted(vals, reverse=True)


def test_range_repartition_string_key():
    """String sort keys route through exact positional quantile cuts
    (a T-Digest cannot hold strings) — the SF10 q9 ORDER BY n_name shape
    that used to die with 'Failed to parse string as double'. NULLs route
    as empty strings; the per-range sorts still produce the total order."""
    rng = np.random.default_rng(5)
    words = np.array(["ALGERIA", "BRAZIL", "CANADA", "EGYPT", "FRANCE",
                      "GERMANY", "INDIA", "JAPAN", "KENYA", "PERU"])
    vals = words[rng.integers(0, len(words), 20_000)].tolist()
    vals[::997] = [None] * len(vals[::997])
    tbl = pa.table({"s": pa.array(vals, pa.string())})
    scan = MemoryScanExec(DFSchema.from_arrow(tbl.schema),
                          tbl.to_batches(max_chunksize=2048), partitions=4)
    key = SortKey(col("s"), ascending=True)
    tapped = RuntimeStatsExec(scan, col("s"))  # must not crash on strings
    pipeline = CoalescePartitionsExec(
        SortExec(UnorderedRangeRepartitionExec(BufferExec(tapped), key, 4), [key], None)
    )
    out = []
    for b in pipeline.execute(0, TaskContext(BallistaConfig())):
        out.extend(b.column(0).to_pylist())
    nn = sorted(v for v in vals if v is not None)
    n_null = vals.count(None)
    assert [v for v in out if v is not None] == nn
    # nulls_first=False ⇒ every NULL lands at the END of the total order
    assert out[-n_null:] == [None] * n_null
    # spread: no single range bucket holds everything
    router = UnorderedRangeRepartitionExec(
        RuntimeStatsExec(scan, col("s")), key, 4)
    sizes = [sum(b.num_rows for b in router.execute(p, TaskContext(BallistaConfig())))
             for p in range(4)]
    assert sum(sizes) == 20_000
    assert max(sizes) < 20_000 * 0.7, sizes


def test_range_repartition_descending_string():
    vals = [f"k{i:04d}" for i in range(3000)]
    tbl = pa.table({"s": pa.array(vals, pa.string())})
    scan = MemoryScanExec(DFSchema.from_arrow(tbl.schema),
                          tbl.to_batches(max_chunksize=128), partitions=2)
    key = SortKey(col("s"), ascending=False)
    pipeline = CoalescePartitionsExec(
        SortExec(UnorderedRangeRepartitionExec(RuntimeStatsExec(scan, col("s")), key, 3), [key], None)
    )
    out = []
    for b in pipeline.execute(0, TaskContext(BallistaConfig())):
        out.extend(b.column(0).to_pylist())
    assert out == sorted(vals, reverse=True)


def test_aqe_fanout_shrink_rewrites_range_router():
    """Regression (SF10 q9 returned 7/175 rows): when AQE shrinks a hash
    fan-out, a downstream range-sort stage's reader follows the new count —
    but the router's bucket count must follow TOO, or the passthrough
    stage's (now fewer) tasks drain only the first buckets and every other
    range's rows are routed-but-never-read. Inflated table stats force the
    planner's range pipeline onto small real data; the tiny observed bytes
    then trigger the shrink at stage resolution."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import EXECUTOR_ENGINE
    from ballista_tpu.plan.provider import MemoryTable

    class InflatedStatsTable(MemoryTable):
        def statistics(self):
            s = super().statistics()
            type(s)  # keep dataclass import-free
            from ballista_tpu.plan.provider import TableStats

            return TableStats(num_rows=50_000_000, total_bytes=4 << 30)

    rng = np.random.default_rng(11)
    words = [f"NATION{i:02d}" for i in range(25)]
    n = 60_000
    tbl = pa.table({
        "g": pa.array([words[i] for i in rng.integers(0, 25, n)], pa.string()),
        "v": pa.array(rng.integers(0, 1000, n).astype("int64"), pa.int64()),
    })
    provider = InflatedStatsTable.from_table(tbl, partitions=8)

    sql = "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY g"
    ctx = SessionContext.standalone(BallistaConfig({EXECUTOR_ENGINE: "cpu"}),
                                    num_executors=2, vcores=2)
    try:
        ctx.register_table("t", provider)
        # precondition: the inflated stats actually put the range pipeline
        # into the plan (estimate 50M × 0.1 agg > 2M threshold)
        phys = ctx.create_physical_plan(ctx.sql(sql).plan)
        from ballista_tpu.ops.cpu.range_repartition import UnorderedRangeRepartitionExec

        from .conftest import iter_plan

        assert any(isinstance(nd, UnorderedRangeRepartitionExec)
                   for nd in iter_plan(phys)), phys.display()
        got = ctx.sql(sql).collect().to_pandas()
    finally:
        ctx.shutdown()
    want = (tbl.to_pandas().groupby("g", as_index=False)
            .agg(s=("v", "sum")).sort_values("g"))
    assert got.g.tolist() == want.g.tolist(), \
        f"{len(got)}/{len(want)} rows survived the shrink"
    assert got.s.tolist() == want.s.tolist()
