"""Dynamic range repartition pipeline (distributed sort support)."""

import numpy as np
import pyarrow as pa

from ballista_tpu.config import BallistaConfig
from ballista_tpu.ops.cpu.range_repartition import (
    BufferExec,
    RuntimeStatsExec,
    UnorderedRangeRepartitionExec,
)
from ballista_tpu.plan.expressions import SortKey, col
from ballista_tpu.plan.physical import (
    CoalescePartitionsExec,
    MemoryScanExec,
    SortExec,
    TaskContext,
)
from ballista_tpu.plan.schema import DFSchema
from ballista_tpu.utils.tdigest import TDigest


def test_tdigest_quantiles():
    d = TDigest()
    rng = np.random.default_rng(0)
    vals = rng.normal(100, 15, 100_000)
    d.add_array(vals)
    for q in (0.1, 0.5, 0.9):
        est = d.quantile(q)
        true = np.quantile(vals, q)
        assert abs(est - true) < 1.0, (q, est, true)
    # merge two digests ≈ one over all data
    d1, d2 = TDigest(), TDigest()
    d1.add_array(vals[:50_000])
    d2.add_array(vals[50_000:])
    d1.merge(d2)
    assert abs(d1.quantile(0.5) - np.quantile(vals, 0.5)) < 1.5
    # round-trip serde
    d3 = TDigest.from_list(d1.to_list())
    assert abs(d3.quantile(0.5) - d1.quantile(0.5)) < 1e-9


def test_range_repartition_total_order():
    rng = np.random.default_rng(1)
    vals = rng.integers(0, 1_000_000, 50_000)
    tbl = pa.table({"x": pa.array(vals, pa.int64())})
    scan = MemoryScanExec(DFSchema.from_arrow(tbl.schema), tbl.to_batches(max_chunksize=4096), partitions=4)
    key = SortKey(col("x"), ascending=True)
    tapped = RuntimeStatsExec(scan, col("x"))
    pipeline = CoalescePartitionsExec(
        SortExec(UnorderedRangeRepartitionExec(BufferExec(tapped), key, 4), [key], None)
    )
    ctx = TaskContext(BallistaConfig())
    out = []
    for b in pipeline.execute(0, ctx):
        out.extend(b.column(0).to_pylist())
    assert out == sorted(vals.tolist())
    # balance: quantile cuts should spread rows across buckets
    # (re-run router alone to inspect)
    router = UnorderedRangeRepartitionExec(RuntimeStatsExec(scan, col("x")), key, 4)
    sizes = []
    for p in range(4):
        n = sum(b.num_rows for b in router.execute(p, TaskContext(BallistaConfig())))
        sizes.append(n)
    assert sum(sizes) == 50_000
    assert max(sizes) < 50_000 * 0.5, sizes  # no bucket hogs everything


def test_range_repartition_descending():
    vals = list(range(1000))
    tbl = pa.table({"x": pa.array(vals, pa.int64())})
    scan = MemoryScanExec(DFSchema.from_arrow(tbl.schema), tbl.to_batches(max_chunksize=100), partitions=2)
    key = SortKey(col("x"), ascending=False)
    pipeline = CoalescePartitionsExec(
        SortExec(UnorderedRangeRepartitionExec(RuntimeStatsExec(scan, col("x")), key, 3), [key], None)
    )
    out = []
    for b in pipeline.execute(0, TaskContext(BallistaConfig())):
        out.extend(b.column(0).to_pylist())
    assert out == sorted(vals, reverse=True)
