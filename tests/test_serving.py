"""High-QPS serving tier: parameterized plan cache (literal lifting +
shape fingerprints), prepared statements, the result cache with
table-version invalidation, the short-query fast lane (byte parity with
the full DAG path), and per-lane admission shedding.
"""

import time

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from ballista_tpu.client.context import SessionContext
from ballista_tpu.config import (
    DEFAULT_SHUFFLE_PARTITIONS,
    SERVING_FAST_LANE,
    SERVING_PLAN_CACHE,
    SERVING_RESULT_CACHE,
    BallistaConfig,
)
from ballista_tpu.errors import ClusterOverloaded, PlanningError
from ballista_tpu.scheduler.admission import (
    DRAINING,
    LANE_BATCH,
    LANE_INTERACTIVE,
    SHEDDING,
    AdmissionController,
)
from ballista_tpu.scheduler.metrics import InMemoryMetricsCollector
from ballista_tpu.serving.fast_lane import FAST_TASK_ID_BASE
from ballista_tpu.serving.normalize import (
    bind_logical,
    bind_physical,
    collect_physical_params,
    config_fingerprint,
    decode_params,
    encode_params,
    lift_parameters,
)
from ballista_tpu.serving.tier import PlanTemplate, ServingTier
from ballista_tpu.sql.optimizer import optimize
from ballista_tpu.sql.parser import parse_sql
from ballista_tpu.sql.planner import SqlPlanner

from .conftest import tpch_query


def _optimized(ctx: SessionContext, sql: str):
    return optimize(SqlPlanner(ctx.catalog).plan_query(parse_sql(sql)))


def _local_ctx(rows: int = 50) -> SessionContext:
    ctx = SessionContext()
    ctx.register_arrow_table(
        "t", pa.table({"a": list(range(rows)), "b": [float(i) for i in range(rows)]}))
    return ctx


def _sorted(tbl: pa.Table) -> pa.Table:
    return tbl.sort_by([(n, "ascending") for n in tbl.column_names])


# ---------------------------------------------------------------------------
# plan normalization: literal lifting, shape keys, binding


class TestPlanNormalization:
    def test_same_shape_different_literals_share_one_cache_entry(self):
        ctx = _local_ctx()
        l1 = lift_parameters(_optimized(ctx, "SELECT a, b FROM t WHERE a < 10"))
        l2 = lift_parameters(_optimized(ctx, "SELECT a, b FROM t WHERE a < 20"))
        assert l1.cacheable and l2.cacheable
        assert l1.key == l2.key, "shape key must be literal-independent"
        assert l1.values == (10,) and l2.values == (20,)

        tier = ServingTier()
        assert tier.lookup_template(l1.key, l1.values) is None  # cold miss
        phys = ctx.create_physical_plan(l1.tagged)
        tier.store_template(PlanTemplate(
            key=l1.key, physical=phys, type_tags=l1.type_tags, values=l1.values,
            tables=l1.tables, bindable=True))
        hit = tier.lookup_template(l2.key, l2.values)
        assert hit is not None, "different literals must hit the same entry"
        snap = tier.snapshot()["plan_cache"]
        assert snap == {**snap, "entries": 1, "hits": 1, "misses": 1}

    def test_different_shape_gets_a_different_key(self):
        ctx = _local_ctx()
        l1 = lift_parameters(_optimized(ctx, "SELECT a, b FROM t WHERE a < 10"))
        l2 = lift_parameters(_optimized(ctx, "SELECT a, b FROM t WHERE a > 10"))
        l3 = lift_parameters(_optimized(ctx, "SELECT a FROM t WHERE a < 10"))
        assert len({l1.key, l2.key, l3.key}) == 3

    def test_binding_substitutes_without_mutating_the_template(self):
        ctx = _local_ctx()
        lift = lift_parameters(_optimized(ctx, "SELECT a, b FROM t WHERE a < 10"))
        phys = ctx.create_physical_plan(lift.tagged)
        assert collect_physical_params(phys) == {0}

        assert ctx.execute_collect(bind_physical(phys, (30,))).num_rows == 30
        # the template still binds its ORIGINAL value afterwards — binding
        # must never write through into the cached tree
        assert ctx.execute_collect(bind_physical(phys, (10,))).num_rows == 10
        assert collect_physical_params(phys) == {0}

        bound = bind_logical(lift.tagged, (25,))
        assert ctx.execute_collect(ctx.create_physical_plan(bound)).num_rows == 25

    def test_values_rows_are_uncacheable(self):
        ctx = _local_ctx()
        lift = lift_parameters(_optimized(ctx, "SELECT * FROM (VALUES (1), (2)) v(a)"))
        assert not lift.cacheable
        assert "VALUES" in lift.reason

    def test_text_cache_hit_requires_resident_template(self):
        ctx = _local_ctx()
        lift = lift_parameters(_optimized(ctx, "SELECT a FROM t WHERE a < 7"))
        tier = ServingTier()
        tier.remember_text("q", "fp", lift.key, lift.values)
        assert tier.lookup_text("q", "fp") is None, "text entry without template is dead"
        tier.store_template(PlanTemplate(
            key=lift.key, physical=ctx.create_physical_plan(lift.tagged),
            type_tags=lift.type_tags, values=lift.values, tables=lift.tables,
            bindable=True))
        assert tier.lookup_text("q", "fp") is not None
        assert tier.lookup_text("q", "other-fp") is None, "config fp is part of the key"

    def test_config_fingerprint_tracks_catalog_registrations(self):
        c1, c2 = BallistaConfig(), BallistaConfig()
        c2.set("ballista.catalog.table.t", "/data/v2/t.parquet")
        assert config_fingerprint(c1) != config_fingerprint(c2)

    def test_non_bindable_template_serves_exact_values_only(self):
        t = PlanTemplate(key="k", physical=None, type_tags=("int64",),
                         values=(5,), tables=("t",), bindable=False)
        assert t.accepts((5,))
        assert not t.accepts((6,))
        assert not t.accepts((5, 5))

    def test_param_wire_codec_round_trips_tagged_types(self):
        from datetime import date, datetime
        from decimal import Decimal

        vals = (1, "x", 2.5, date(1998, 12, 1), datetime(2026, 8, 5, 12, 30),
                Decimal("10.25"), None)
        assert decode_params(encode_params(vals)) == vals


# ---------------------------------------------------------------------------
# result cache: version-vector invalidation


class TestResultCache:
    def test_table_version_bump_orphans_cached_results(self):
        tier = ServingTier()
        tbl = pa.table({"x": [1, 2, 3]})
        rkey = tier.result_key("k", (5,), ("t",))
        assert tier.lookup_result(rkey) is None
        tier.store_result(rkey, tbl)
        assert tier.lookup_result(tier.result_key("k", (5,), ("t",))) is tbl
        tier.table_versions.bump("t")
        assert tier.lookup_result(tier.result_key("k", (5,), ("t",))) is None
        # the old entry is orphaned, not scanned: still resident until LRU
        assert tier.snapshot()["result_cache"]["entries"] == 1

    def test_oversized_results_are_not_cached(self):
        tier = ServingTier()
        tier.result_max_bytes = 8
        tier.store_result(("k", (), ()), pa.table({"x": list(range(1000))}))
        assert tier.snapshot()["result_cache"]["entries"] == 0

    def test_e2e_invalidation_on_table_reregistration(self, tmp_path):
        p1, p2 = str(tmp_path / "v1.parquet"), str(tmp_path / "v2.parquet")
        pq.write_table(pa.table({"a": list(range(10))}), p1)
        pq.write_table(pa.table({"a": list(range(100, 120))}), p2)

        cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 2,
                              SERVING_RESULT_CACHE: True})
        ctx = SessionContext.standalone(cfg, num_executors=1)
        try:
            ctx.register_parquet("t", p1)
            q = "SELECT a FROM t WHERE a < 1000"
            r1 = ctx.sql(q).collect()
            r2 = ctx.sql(q).collect()
            serving = ctx._cluster.scheduler.serving
            assert serving.snapshot()["result_cache"]["hits"] >= 1
            assert _sorted(r1).equals(_sorted(r2))

            # re-registering the table bumps its version: the next lookup
            # must MISS and read the new file, never the stale result
            ctx.register_parquet("t", p2)
            r3 = ctx.sql(q).collect()
            assert _sorted(r3).column("a").to_pylist() == list(range(100, 120))
            assert serving.table_versions.bumps >= 1
        finally:
            ctx.shutdown()


# ---------------------------------------------------------------------------
# fast lane vs full DAG: byte parity on TPC-H


def _serving_ctx(tpch_dir, **overrides) -> SessionContext:
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 2, **overrides})
    ctx = SessionContext.standalone(cfg, num_executors=2)
    register_tpch(ctx, tpch_dir)
    return ctx


@pytest.mark.parametrize("q", [1, 6])
def test_serving_path_byte_parity_with_legacy_path(q, tpch_dir):
    """The serving submit path (plan cache + template binding) must return
    byte-identical results to the legacy queued path for the same query."""
    on = _serving_ctx(tpch_dir)
    off = _serving_ctx(tpch_dir, **{SERVING_PLAN_CACHE: False})
    try:
        sql = tpch_query(q)
        r_on_cold = on.sql(sql).collect()
        r_on_warm = on.sql(sql).collect()  # second run rides the caches
        r_off = off.sql(sql).collect()
        assert _sorted(r_on_cold).equals(_sorted(r_off))
        assert _sorted(r_on_warm).equals(_sorted(r_off))
        assert on._cluster.scheduler.serving.snapshot()["plan_cache"]["hits"] >= 1
        assert off._cluster.scheduler.serving.snapshot()["plan_cache"]["misses"] == 0, \
            "disabled serving tier must not touch the caches"
    finally:
        on.shutdown()
        off.shutdown()


def test_fast_lane_byte_parity_on_single_stage_query(tpch_dir):
    """A single-stage query executes through the fast lane (no execution
    graph); its bytes must match the full-DAG path with the lane disabled."""
    sql = ("SELECT l_orderkey, l_partkey, l_quantity FROM lineitem "
           "WHERE l_quantity < 3")
    fast = _serving_ctx(tpch_dir)
    slow = _serving_ctx(tpch_dir, **{SERVING_FAST_LANE: False})
    try:
        r_fast = [fast.sql(sql).collect() for _ in range(2)]
        r_slow = slow.sql(sql).collect()
        for r in r_fast:
            assert _sorted(r).equals(_sorted(r_slow))
        snap = fast._cluster.scheduler.serving.snapshot()
        assert snap["fast_lane"]["executed"] >= 1, "fast lane never engaged — vacuous"
        assert slow._cluster.scheduler.serving.snapshot()["fast_lane"]["executed"] == 0
    finally:
        fast.shutdown()
        slow.shutdown()


def test_prepared_statement_binds_fresh_values_e2e(tpch_dir):
    ctx = _serving_ctx(tpch_dir)
    try:
        ps = ctx.prepare("SELECT l_orderkey FROM lineitem WHERE l_quantity < 3")
        assert ps.num_params == 1
        r3 = ps.execute()
        r7 = ps.execute([7])
        assert r7.num_rows > r3.num_rows > 0
        # bound executions are plan-cache hits, not re-plans
        assert ctx._cluster.scheduler.serving.snapshot()["plan_cache"]["hits"] >= 2
        with pytest.raises(PlanningError):
            ps.execute([1, 2])
        ps.close()
        assert ctx._cluster.scheduler.serving.snapshot()["prepared_statements"] == 0
    finally:
        ctx.shutdown()


def test_prepare_rejects_non_select():
    ctx = SessionContext()
    with pytest.raises(PlanningError):
        ctx.prepare("CREATE EXTERNAL TABLE x STORED AS PARQUET LOCATION '/tmp/x'")


# ---------------------------------------------------------------------------
# per-lane admission: interactive traffic survives batch overload


def _ctl(**kw) -> AdmissionController:
    defaults = dict(enabled=True, max_pending=64, per_session_quota=4,
                    shed_depth=32, drain_depth=48, shed_loop_lag_s=10.0,
                    shed_memory_pressure=0.9, min_retry_after_ms=1,
                    interactive_max_pending=4)
    defaults.update(kw)
    return AdmissionController(**defaults)


class TestPerLaneShedding:
    def test_chaos_overload_pressure_sheds_batch_but_not_interactive(self):
        """Memory pressure from a chaos-overloaded pool trips SHEDDING;
        the batch lane's quota halves while the interactive lane keeps its
        full session quota — short queries keep flowing."""
        from ballista_tpu.executor.chaos import ChaosExec
        from ballista_tpu.executor.memory_pool import MemoryPool
        from ballista_tpu.plan.physical import ExecutionPlan, TaskContext
        from ballista_tpu.plan.schema import DFField, DFSchema

        schema = DFSchema([DFField("x", pa.int64(), False)])

        class OneBatch(ExecutionPlan):
            def __init__(self):
                super().__init__(schema)

            def output_partition_count(self):
                return 1

            def execute(self, partition, task_ctx):
                yield pa.RecordBatch.from_pydict({"x": [1]}, schema=schema.to_arrow())

        chaos = ChaosExec(OneBatch(), seed=1, probability=1.0, mode="overload",
                          straggler_delay_s=0.01)
        pool = MemoryPool(100)
        task_ctx = TaskContext()
        task_ctx.memory_pool = pool
        gen = chaos.execute(0, task_ctx)
        next(gen)  # chaos reservation live: the pool reads saturated
        assert pool.pressure() >= 1.0

        ctl = _ctl(per_session_quota=2)
        assert ctl.update(0.0, pool.pressure()) == SHEDDING
        ctl.admit("s1", "b1", lane=LANE_BATCH)
        with pytest.raises(ClusterOverloaded) as ei:
            ctl.admit("s1", "b2", lane=LANE_BATCH)  # halved quota of 1
        assert ei.value.reason == "shedding"
        # the same session's interactive work still gets its FULL quota
        ctl.admit("s1", "i1", lane=LANE_INTERACTIVE)
        lanes = ctl.snapshot()["lanes"]
        assert lanes[LANE_BATCH]["shed_total"] == 1
        assert lanes[LANE_INTERACTIVE]["shed_total"] == 0
        assert lanes[LANE_INTERACTIVE]["inflight"] == 1
        list(gen)  # drain the chaos generator → reservation released

    def test_interactive_lane_has_its_own_depth_cap(self):
        ctl = _ctl(interactive_max_pending=2, per_session_quota=10)
        ctl.admit("s1", "i1", lane=LANE_INTERACTIVE)
        ctl.admit("s2", "i2", lane=LANE_INTERACTIVE)
        with pytest.raises(ClusterOverloaded) as ei:
            ctl.admit("s3", "i3", lane=LANE_INTERACTIVE)
        assert ei.value.reason == "depth"
        ctl.finish("i1")
        ctl.admit("s3", "i3", lane=LANE_INTERACTIVE)

    def test_draining_halves_the_interactive_cap_but_admits(self):
        ctl = _ctl(interactive_max_pending=4, max_pending=100,
                   per_session_quota=100, shed_depth=2, drain_depth=4)
        for i in range(4):
            ctl.admit(f"s{i}", f"b{i}", lane=LANE_BATCH)
        assert ctl.update(0.0, 0.0) == DRAINING
        with pytest.raises(ClusterOverloaded) as ei:
            ctl.admit("s9", "late-batch", lane=LANE_BATCH)
        assert ei.value.reason == "draining"
        # interactive cap halves to 2 while draining — degraded, not dead
        ctl.admit("sa", "i1", lane=LANE_INTERACTIVE)
        ctl.admit("sb", "i2", lane=LANE_INTERACTIVE)
        with pytest.raises(ClusterOverloaded) as ei:
            ctl.admit("sc", "i3", lane=LANE_INTERACTIVE)
        assert ei.value.reason == "draining"

    def test_finish_releases_the_lane_slot(self):
        ctl = _ctl(interactive_max_pending=1)
        ctl.admit("s1", "i1", lane=LANE_INTERACTIVE)
        assert ctl.lane_of("i1") == LANE_INTERACTIVE
        ctl.finish("i1")
        assert ctl.lane_of("i1") is None
        assert ctl.snapshot()["lanes"][LANE_INTERACTIVE]["inflight"] == 0
        ctl.admit("s1", "i2", lane=LANE_INTERACTIVE)


# ---------------------------------------------------------------------------
# observability: /api surfaces, prometheus counters, heartbeat gauge, serde


class TestServingObservability:
    def test_api_state_includes_serving_and_lane_snapshots(self):
        import json
        import urllib.request

        from ballista_tpu.scheduler.api.rest import start_rest_api
        from ballista_tpu.scheduler.server import SchedulerServer

        metrics = InMemoryMetricsCollector()
        scheduler = SchedulerServer(None, metrics)
        scheduler.serving.note_fast_lane("executed")
        server, port = start_rest_api(scheduler, metrics, host="127.0.0.1", port=0)
        try:
            state = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/state"))
            assert state["serving"]["fast_lane"]["executed"] == 1
            assert set(state["serving"]) >= {"plan_cache", "result_cache", "fast_lane"}
            assert set(state["overload"]["lanes"]) == {LANE_BATCH, LANE_INTERACTIVE}
        finally:
            server.shutdown()

    def test_prometheus_renders_serving_counters(self):
        m = InMemoryMetricsCollector()
        m.record_plan_cache(True)
        m.record_plan_cache(False)
        m.record_result_cache(True)
        m.record_fast_lane("executed")
        m.record_fast_lane("fallback")
        m.record_lane_admitted(LANE_INTERACTIVE)
        m.record_job_rejected("depth", lane=LANE_INTERACTIVE)
        out = m.render_prometheus()
        assert 'plan_cache_total{outcome="hit"} 1' in out
        assert 'plan_cache_total{outcome="miss"} 1' in out
        assert "result_cache" in out
        assert "fast_lane" in out
        assert 'lane="interactive"' in out

    def test_executor_counts_fast_lane_tasks(self, tmp_path):
        from ballista_tpu.executor.executor import Executor, ExecutorMetadata
        from ballista_tpu.scheduler.state.execution_graph import TaskDescription

        ex = Executor(str(tmp_path), ExecutorMetadata(id="ex-fl"))
        # plan=None fails fast in run_task — the gauge must still count the
        # ATTEMPT, mirroring tasks_run accounting
        task = TaskDescription(job_id="j", stage_id=1, stage_attempt=0,
                               task_id=FAST_TASK_ID_BASE + 3, partitions=[0],
                               plan=None, session_id="s", fast_lane=True)
        ex.run_task(task)
        assert ex.fast_lane_tasks == 1

    def test_task_id_band_is_the_wire_encoding_of_fast_lane(self, tmp_path):
        """No proto field exists for fast_lane; the reserved task-id band
        must survive an encode/decode round trip."""
        from ballista_tpu.scheduler.planner import DistributedPlanner
        from ballista_tpu.scheduler.state.execution_graph import TaskDescription
        from ballista_tpu.serde_control import (
            decode_task_definition,
            encode_task_definition,
        )

        path = str(tmp_path / "t.parquet")
        pq.write_table(pa.table({"a": [1, 2, 3]}), path)
        ctx = SessionContext()
        ctx.register_parquet("t", path)
        physical = ctx.create_physical_plan(_optimized(ctx, "SELECT a FROM t"))
        stages = DistributedPlanner("job-band").plan_query_stages(physical)
        assert len(stages) == 1
        for task_id, expect in ((FAST_TASK_ID_BASE, True), (7, False)):
            t = TaskDescription(job_id="job-band", stage_id=stages[0].stage_id,
                                stage_attempt=0, task_id=task_id, partitions=[0],
                                plan=stages[0].plan, session_id="s",
                                fast_lane=expect)
            decoded = decode_task_definition(encode_task_definition(t))
            assert decoded.fast_lane is expect
            assert decoded.task_id == task_id


# ---------------------------------------------------------------------------
# wait_for_job tail latency: the poll floor must not eat fast-lane wins


def test_client_poll_floor_is_sub_hundred_ms():
    from ballista_tpu.client import remote

    assert remote.POLL_INTERVAL_S <= 0.02, \
        "a 100ms first poll wipes out single-digit-ms fast-lane latency"
    assert remote.POLL_INTERVAL_MAX_S <= 2.0


def test_scheduler_wait_for_job_returns_promptly(tpch_dir):
    """End-to-end latency guard: a warm repeated single-stage query through
    the serving tier completes well under the old polling floor regime."""
    ctx = _serving_ctx(tpch_dir)
    try:
        sql = "SELECT l_orderkey FROM lineitem WHERE l_quantity < 2"
        ctx.sql(sql).collect()  # warm: compile + plan template
        t0 = time.monotonic()
        ctx.sql(sql).collect()
        warm_s = time.monotonic() - t0
        assert warm_s < 5.0, f"warm single-stage query took {warm_s:.2f}s"
    finally:
        ctx.shutdown()
