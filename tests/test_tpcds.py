"""TPC-DS subset: generator + queries + oracle verification, local and
distributed standalone (reference analog: benchmarks tpcds bin + tpcds.yml)."""

import pytest


@pytest.fixture(scope="module")
def tpcds_dir(tmp_path_factory):
    from ballista_tpu.testing.tpcdsgen import generate_tpcds

    d = str(tmp_path_factory.mktemp("tpcds") / "sf01")
    generate_tpcds(d, scale=0.1, seed=17, files_per_table=2)
    return d


@pytest.fixture(scope="module")
def tpcds_ref(tpcds_dir):
    from ballista_tpu.testing.tpcds_reference import load_tables

    return load_tables(tpcds_dir)


def _query(n: int) -> str:
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return open(os.path.join(root, "benchmarks", "tpcds", "queries", f"q{n}.sql")).read()


@pytest.mark.parametrize("q", [1, 3, 6, 7, 8, 10, 12, 13, 15, 17, 19, 20, 21, 22, 23, 25, 26, 29, 30, 32, 33, 34, 35, 36, 37, 38, 39, 40, 42, 43, 45, 46, 47, 48, 50, 52, 53, 55, 57, 59, 61, 62, 63, 65, 67, 68, 69, 70, 71, 73, 76, 79, 81, 82, 86, 87, 88, 89, 90, 91, 92, 93, 96, 97, 98, 99])
def test_tpcds_local(q, tpcds_dir, tpcds_ref):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpcds_reference import compare_results, run_reference
    from ballista_tpu.testing.tpcdsgen import register_tpcds

    ctx = SessionContext()
    register_tpcds(ctx, tpcds_dir)
    out = ctx.sql(_query(q)).collect()
    problems = compare_results(out, run_reference(q, tpcds_ref), q)
    assert not problems, "\n".join(problems)


@pytest.mark.parametrize("q", [3, 25, 68, 93, 98, 99])
def test_tpcds_distributed_standalone(q, tpcds_dir, tpcds_ref):
    """Representative queries through the full distributed path (q98
    exercises a window over aggregate output across a shuffle)."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpcds_reference import compare_results, run_reference
    from ballista_tpu.testing.tpcdsgen import register_tpcds

    ctx = SessionContext.standalone()
    register_tpcds(ctx, tpcds_dir)
    out = ctx.sql(_query(q)).collect()
    problems = compare_results(out, run_reference(q, tpcds_ref), q)
    assert not problems, "\n".join(problems)
