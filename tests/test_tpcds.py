"""TPC-DS subset: generator + queries + oracle verification, local and
distributed standalone (reference analog: benchmarks tpcds bin + tpcds.yml)."""

import pytest


@pytest.fixture(scope="module")
def tpcds_dir(tmp_path_factory):
    from ballista_tpu.testing.tpcdsgen import generate_tpcds

    d = str(tmp_path_factory.mktemp("tpcds") / "sf01")
    generate_tpcds(d, scale=0.1, seed=17, files_per_table=2)
    return d


@pytest.fixture(scope="module")
def tpcds_ref(tpcds_dir):
    from ballista_tpu.testing.tpcds_reference import load_tables

    return load_tables(tpcds_dir)


def _query(n: int) -> str:
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return open(os.path.join(root, "benchmarks", "tpcds", "queries", f"q{n}.sql")).read()


@pytest.mark.parametrize("q", [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18, 19, 20, 21, 22, 23, 24, 25, 26, 27, 28, 29, 30, 31, 32, 33, 34, 35, 36, 37, 38, 39, 40, 41, 42, 43, 44, 45, 46, 47, 48, 49, 50, 51, 52, 53, 54, 55, 56, 57, 58, 59, 60, 61, 62, 63, 64, 65, 66, 67, 68, 69, 70, 71, 72, 73, 74, 75, 76, 77, 78, 79, 80, 81, 82, 83, 84, 85, 86, 87, 88, 89, 90, 91, 92, 93, 94, 95, 96, 97, 98, 99])
def test_tpcds_local(q, tpcds_dir, tpcds_ref):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpcds_reference import compare_results, run_reference
    from ballista_tpu.testing.tpcdsgen import register_tpcds

    ctx = SessionContext()
    register_tpcds(ctx, tpcds_dir)
    out = ctx.sql(_query(q)).collect()
    problems = compare_results(out, run_reference(q, tpcds_ref), q)
    assert not problems, "\n".join(problems)


@pytest.mark.parametrize("q", [3, 25, 68, 93, 98, 99])
def test_tpcds_distributed_standalone(q, tpcds_dir, tpcds_ref):
    """Representative queries through the full distributed path (q98
    exercises a window over aggregate output across a shuffle)."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpcds_reference import compare_results, run_reference
    from ballista_tpu.testing.tpcdsgen import register_tpcds

    ctx = SessionContext.standalone()
    register_tpcds(ctx, tpcds_dir)
    out = ctx.sql(_query(q)).collect()
    problems = compare_results(out, run_reference(q, tpcds_ref), q)
    assert not problems, "\n".join(problems)


@pytest.mark.parametrize("q", [3, 7, 19, 25, 42, 43, 52, 55, 68, 93, 98, 99])
def test_tpcds_tpu_engine(q, tpcds_dir, tpcds_ref):
    """Representative TPC-DS shapes (star joins, date-dim filters, windows
    over aggregates, returns-chain joins) through the TPU engine with the
    per-subtree fallback seam — oracle-checked, and the engine must
    actually place device stages across the subset."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import EXECUTOR_ENGINE, TPU_MIN_ROWS, BallistaConfig
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.ops.tpu.final_stage import TpuFinalStageExec
    from ballista_tpu.ops.tpu.stage_compiler import TpuStageExec
    from ballista_tpu.testing.tpcds_reference import compare_results, run_reference
    from ballista_tpu.testing.tpcdsgen import register_tpcds

    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
    ctx = SessionContext(cfg)
    register_tpcds(ctx, tpcds_dir)
    out = ctx.sql(_query(q)).collect()
    problems = compare_results(out, run_reference(q, tpcds_ref), q)
    assert not problems, "\n".join(problems)
    # the engine must engage: the compiled plan carries device stages
    phys = maybe_compile_tpu(ctx.create_physical_plan(ctx.sql(_query(q)).plan), cfg)
    from .conftest import iter_plan

    stages = [n for n in iter_plan(phys)
              if isinstance(n, (TpuStageExec, TpuFinalStageExec))]
    assert stages, f"q{q}: no device stages compiled\n{phys.display()}"


@pytest.mark.parametrize("q", [36, 47, 67, 86, 98])
def test_tpcds_sort_window_device_stages(q, tpcds_dir, tpcds_ref):
    """Window- and ORDER BY-heavy TPC-DS shapes (rollup ranks, moving
    windows over monthly sales, top-N category reports): the engine must
    place TpuSortStageExec/TpuWindowStageExec nodes, those nodes must
    actually run on the device path, and results stay oracle-exact."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import EXECUTOR_ENGINE, TPU_MIN_ROWS, BallistaConfig
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.ops.tpu.sort_window import TpuSortStageExec, TpuWindowStageExec
    from ballista_tpu.plan.physical import TaskContext
    from ballista_tpu.testing.tpcds_reference import compare_results, run_reference
    from ballista_tpu.testing.tpcdsgen import register_tpcds

    from .conftest import iter_plan

    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
    ctx = SessionContext(cfg)
    register_tpcds(ctx, tpcds_dir)
    out = ctx.sql(_query(q)).collect()
    problems = compare_results(out, run_reference(q, tpcds_ref), q)
    assert not problems, "\n".join(problems)

    phys = maybe_compile_tpu(ctx.create_physical_plan(ctx.sql(_query(q)).plan), cfg)
    nodes = [n for n in iter_plan(phys)
             if isinstance(n, (TpuSortStageExec, TpuWindowStageExec))]
    assert nodes, f"q{q}: no sort/window device stages\n{phys.display()}"
    tc = TaskContext(cfg)
    for p in range(phys.output_partition_count()):
        list(phys.execute(p, tc))
    ran = [n for n in nodes if n.tpu_count >= 1 and n.fallback_count == 0]
    assert ran, f"q{q}: sort/window stages compiled but none ran on device"


def _skew_cfg(skew_aqe: bool = True):
    from ballista_tpu.config import (
        AQE_SKEW_ENABLED,
        AQE_SKEW_MIN_BYTES,
        AQE_TARGET_PARTITION_BYTES,
        BROADCAST_JOIN_ROWS_THRESHOLD,
        CHAOS_ENABLED,
        CHAOS_MODE,
        CHAOS_SEED,
        CHAOS_SKEW_FRACTION,
        DEBUG_PLAN_VERIFY,
        DEFAULT_SHUFFLE_PARTITIONS,
        BallistaConfig,
        PLANNER_ADAPTIVE_ENABLED,
    )

    return BallistaConfig({
        DEFAULT_SHUFFLE_PARTITIONS: 8,
        PLANNER_ADAPTIVE_ENABLED: True,
        BROADCAST_JOIN_ROWS_THRESHOLD: 100,  # force partitioned joins
        CHAOS_ENABLED: True, CHAOS_MODE: "skew", CHAOS_SEED: 5,
        CHAOS_SKEW_FRACTION: 0.7,
        AQE_SKEW_ENABLED: skew_aqe, AQE_SKEW_MIN_BYTES: 4096,
        AQE_TARGET_PARTITION_BYTES: 128 * 1024,
        DEBUG_PLAN_VERIFY: True,
    })


@pytest.mark.parametrize("q", [3, 19, 42, 55, 68])
def test_tpcds_skewed_distributed(q, tpcds_dir, tpcds_ref):
    """Star-join subset under chaos `skew` (seeded hot-key routing at the
    shuffle partitioner) with the full AQE skew defense armed and
    plan_check gating every resolution — results must stay oracle-exact
    even when one reduce bucket takes ~70% of the shuffle."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpcds_reference import compare_results, run_reference
    from ballista_tpu.testing.tpcdsgen import register_tpcds

    ctx = SessionContext.standalone(_skew_cfg(), num_executors=1, vcores=4)
    register_tpcds(ctx, tpcds_dir)
    try:
        out = ctx.sql(_query(q)).collect()
        problems = compare_results(out, run_reference(q, tpcds_ref), q)
        assert not problems, "\n".join(problems)
    finally:
        ctx.shutdown()


def test_tpcds_skewed_join_splits_byte_identical(tpcds_dir):
    """A pure-join TPC-DS shape (store_sales ⋈ item on the hot-routed item
    key) must actually take the partition-split path — skew_splits >= 1 —
    and the merged result must be byte-identical to the unsplit run."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS
    from ballista_tpu.testing.tpcdsgen import register_tpcds

    sql = ("select ss_item_sk, ss_ticket_number, i_brand from store_sales "
           "join item on ss_item_sk = i_item_sk")

    def run(skew_aqe):
        ctx = SessionContext.standalone(_skew_cfg(skew_aqe), num_executors=1, vcores=4)
        register_tpcds(ctx, tpcds_dir)
        before = int(RUN_STATS.snapshot().get("skew_splits", 0) or 0)
        try:
            out = ctx.sql(sql).collect()
        finally:
            ctx.shutdown()
        return out, int(RUN_STATS.snapshot().get("skew_splits", 0) or 0) - before

    split_out, splits = run(True)
    oracle_out, oracle_splits = run(False)
    assert splits >= 1 and oracle_splits == 0
    assert split_out.to_pandas().equals(oracle_out.to_pandas()), \
        "TPC-DS skew-split result diverged from unsplit oracle"
