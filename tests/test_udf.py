"""Scalar UDF registry (BallistaFunctionRegistry analog, core/src/registry.rs)."""

import pyarrow as pa
import pyarrow.compute as pc


def test_udf_local_sql():
    from ballista_tpu.client.context import SessionContext

    ctx = SessionContext()
    ctx.register_arrow_table("t", pa.table({"x": [1, 2, 3], "s": ["a", "b", "c"]}))

    def triple(a):
        return pc.multiply(pc.cast(a, pa.int64()), 3)

    ctx.register_udf("triple", triple, pa.int64())
    out = ctx.sql("select triple(x) t3 from t where triple(x) > 3 order by t3").collect()
    assert out.column("t3").to_pylist() == [6, 9]


def test_udf_ships_module_to_remote_cluster(tmp_path):
    """UDFs from an importable module run on real remote executors: the
    session config carries the module name, executors import it."""
    import time

    from ballista_tpu import udf as udf_mod
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.executor.executor_process import ExecutorProcess
    from ballista_tpu.scheduler.process import SchedulerProcess
    from ballista_tpu.testing.udf_fixtures import double_it, shout

    sched = SchedulerProcess(bind_host="127.0.0.1", port=0, rest_port=-1, flight_proxy_port=-1)
    sched.start()
    addr = f"127.0.0.1:{sched.port}"
    ex = ExecutorProcess(addr, bind_host="127.0.0.1", external_host="127.0.0.1", vcores=2)
    ex.start()
    time.sleep(0.2)
    try:
        import pyarrow.parquet as pq

        ctx = SessionContext.remote(addr)
        pq.write_table(pa.table({"x": [5, 6], "s": ["hey", "yo"]}), str(tmp_path / "t.parquet"))
        ctx.register_parquet("t", str(tmp_path / "t.parquet"))
        ctx.register_udf("double_it", double_it, pa.int64())
        ctx.register_udf("shout", shout, pa.string())
        assert "udf_fixtures" in (ctx.config.get(udf_mod.UDF_MODULES) or "")
        out = ctx.sql("select double_it(x) d, shout(s) u from t order by d").collect()
        assert out.column("d").to_pylist() == [10, 12]
        assert out.column("u").to_pylist() == ["HEY!", "YO!"]
    finally:
        ex.shutdown()
        sched.shutdown()
