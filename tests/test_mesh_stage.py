"""Mesh-wide stage execution: planner merge, serde, and the on-device
exchange's byte parity + demotion ladder.

The acceptance bar (ISSUE 7): a stage executed in mesh mode on ≥2 devices
produces BYTE-IDENTICAL results to the per-partition path, performs its
intra-mesh hash repartition with zero shuffle files / zero Flight fetches
for the fused edge, and automatically demotes on capacity overflow or
unsupported column types."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pytest

from ballista_tpu.config import (
    EXECUTOR_ENGINE,
    MAX_PARTITIONS_PER_TASK,
    TPU_MESH_ENABLED,
    TPU_MESH_EXCHANGE_CAPACITY,
    TPU_MESH_MIN_ROWS,
    TPU_MIN_ROWS,
    BallistaConfig,
)
from ballista_tpu.ops.tpu.mesh_stage import MeshExchangeExec, contains_mesh_exchange
from ballista_tpu.scheduler.planner import DistributedPlanner, merge_mesh_stages

from .conftest import iter_plan, tpch_query


def _mesh_cfg(**over) -> BallistaConfig:
    base = {EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0, TPU_MESH_ENABLED: True}
    base.update(over)
    return BallistaConfig(base)


def _need_devices(n: int = 2) -> None:
    import jax

    if len(jax.devices()) < n:
        pytest.skip(f"need {n} devices")


def _q1_stages(tpch_ctx, job="jm"):
    physical = tpch_ctx.create_physical_plan(tpch_ctx.sql(tpch_query(1)).plan)
    return DistributedPlanner(job).plan_query_stages(physical)


# -- planner merge ------------------------------------------------------------


def test_merge_requires_tpu_engine_and_flag(tpch_ctx):
    stages = _q1_stages(tpch_ctx)
    # flag off → untouched
    same = merge_mesh_stages(list(stages), BallistaConfig({EXECUTOR_ENGINE: "tpu"}))
    assert len(same) == len(stages)
    # flag on but CPU engine → untouched (per-partition tasks gain nothing)
    same = merge_mesh_stages(list(stages), BallistaConfig({TPU_MESH_ENABLED: True}))
    assert len(same) == len(stages)
    assert not any(s.mesh for s in same)


def test_merge_fuses_single_consumer_hash_edge(tpch_ctx):
    stages = _q1_stages(tpch_ctx)
    producer_ids = {s.stage_id for s in stages if s.plan.sort_shuffle and s.plan.keys}
    assert producer_ids, "q1 should have a hash-exchange stage"
    merged = merge_mesh_stages(list(stages), _mesh_cfg())
    assert len(merged) < len(stages)
    merged_ids = {s.stage_id for s in merged}
    assert producer_ids - merged_ids, "a hash-exchange producer stage must be gone"
    mesh_stages = [s for s in merged if s.mesh]
    assert len(mesh_stages) == 1
    ms = mesh_stages[0]
    nodes = [n for n in iter_plan(ms.plan) if isinstance(n, MeshExchangeExec)]
    assert len(nodes) == 1
    # the exchange node carries the producer's reduce-bucket shape and keys
    assert nodes[0].file_partitions == ms.partitions
    assert nodes[0].keys
    # input edges recomputed over the fused plan
    from ballista_tpu.scheduler.planner import _find_input_stages

    assert ms.input_stage_ids == _find_input_stages(ms.plan)


def test_merge_leaves_broadcast_edges(tpch_ctx):
    physical = tpch_ctx.create_physical_plan(tpch_ctx.sql(tpch_query(3)).plan)
    stages = DistributedPlanner("jb").plan_query_stages(physical)
    n_broadcast = sum(1 for s in stages if s.broadcast)
    assert n_broadcast >= 1
    merged = merge_mesh_stages(list(stages), _mesh_cfg())
    # broadcast build stages must survive — their edge is read-in-full by
    # every probe task, never a hash exchange
    assert sum(1 for s in merged if s.broadcast) == n_broadcast


def test_choose_mesh_mode_reasons(tpch_ctx):
    from ballista_tpu.scheduler.planner import choose_mesh_mode
    from ballista_tpu.shuffle.reader import UnresolvedShuffleExec

    stages = _q1_stages(tpch_ctx)
    producer = next(s for s in stages if s.plan.sort_shuffle and s.plan.keys)
    consumer = next(s for s in stages if producer.stage_id in s.input_stage_ids)
    leaves = [
        n for n in iter_plan(consumer.plan)
        if isinstance(n, UnresolvedShuffleExec) and n.stage_id == producer.stage_id
    ]
    cfg = _mesh_cfg()
    ok, reason = choose_mesh_mode(producer, [(consumer, leaves)], cfg)
    assert ok and reason == "mesh"
    # two consumers of one producer: keep the file path (the exchange result
    # would have to be served to two different stages)
    ok, reason = choose_mesh_mode(
        producer, [(consumer, leaves), (consumer, leaves)], cfg)
    assert not ok and reason.startswith("consumers")
    # a non-hash (passthrough) writer never merges
    final = stages[-1]
    assert not final.plan.sort_shuffle
    ok, reason = choose_mesh_mode(final, [(consumer, leaves)], cfg)
    assert not ok and reason == "not-hash-exchange"


# -- serde + graph plumbing ---------------------------------------------------


def test_mesh_exchange_serde_round_trip(tpch_ctx):
    from ballista_tpu.serde import plan_from_bytes, plan_to_bytes

    merged = merge_mesh_stages(_q1_stages(tpch_ctx), _mesh_cfg())
    ms = next(s for s in merged if s.mesh)
    back = plan_from_bytes(plan_to_bytes(ms.plan))
    nodes = [n for n in iter_plan(back) if isinstance(n, MeshExchangeExec)]
    assert len(nodes) == 1
    orig = next(n for n in iter_plan(ms.plan) if isinstance(n, MeshExchangeExec))
    assert nodes[0].file_partitions == orig.file_partitions
    assert len(nodes[0].keys) == len(orig.keys)
    assert type(nodes[0].producer).__name__ == type(orig.producer).__name__
    assert contains_mesh_exchange(back)


def test_from_proto_recovers_mesh_flag(tpch_ctx):
    from ballista_tpu.scheduler.state.execution_graph import ExecutionGraph

    merged = merge_mesh_stages(_q1_stages(tpch_ctx), _mesh_cfg())
    g = ExecutionGraph("jp", "", "s1", merged, _mesh_cfg())
    g2 = ExecutionGraph.from_proto(g.to_proto())
    flags = {sid: st.spec.mesh for sid, st in g2.stages.items()}
    want = {s.stage_id: s.mesh for s in merged}
    assert any(flags.values())
    assert flags == want


def test_mesh_stage_pops_as_one_task(tpch_ctx):
    from ballista_tpu.scheduler.state.execution_graph import ExecutionGraph

    merged = merge_mesh_stages(_q1_stages(tpch_ctx), _mesh_cfg())
    cfg = _mesh_cfg(**{MAX_PARTITIONS_PER_TASK: 1})
    g = ExecutionGraph("jt", "", "s1", merged, cfg)
    ms = next(st for st in g.stages.values() if st.spec.mesh)
    assert ms.is_runnable, "the merged stage should resolve immediately (leaf scans)"
    task = g.pop_next_task("e1")
    assert task is not None
    assert task.stage_id == ms.stage_id
    # ONE task spanning every reduce bucket — MAX_PARTITIONS_PER_TASK=1
    # must NOT slice a mesh stage
    assert task.partitions == list(range(ms.spec.partitions))
    assert not ms.pending


# -- the exchange node directly (byte parity + demotion ladder) ---------------


def _producer_table(n=4000, with_nulls=True):
    rng = np.random.default_rng(17)
    k = rng.choice([f"key{i:03d}" for i in range(60)], n)
    v = rng.uniform(-50, 50, n)
    cols = {
        "k": pa.array(k),
        "v": pa.array(v),
        "q": pa.array(rng.integers(0, 1000, n), type=pa.int64()),
    }
    if with_nulls:
        vmask = rng.random(n) < 0.05
        cols["v"] = pc.if_else(pa.array(vmask), pa.nulls(n, pa.float64()),
                               pa.array(v))
        kmask = rng.random(n) < 0.03
        cols["k"] = pc.if_else(pa.array(kmask), pa.nulls(n, pa.string()),
                               pa.array(k))
    return pa.table(cols)


def _mesh_exchange_over(tbl: pa.Table, partitions=4, file_partitions=8):
    from ballista_tpu.plan.expressions import Column
    from ballista_tpu.plan.physical import MemoryScanExec
    from ballista_tpu.plan.schema import DFSchema

    schema = DFSchema.from_arrow(tbl.schema)
    batches = tbl.combine_chunks().to_batches(
        max_chunksize=max(1, tbl.num_rows // partitions))
    scan = MemoryScanExec(schema, batches, partitions)
    return MeshExchangeExec(scan, [Column("k")], file_partitions)


def _collect_buckets(node: MeshExchangeExec, cfg: BallistaConfig):
    from ballista_tpu.plan.physical import TaskContext

    ctx = TaskContext(cfg)
    return [list(node.execute(p, ctx)) for p in range(node.output_partition_count())]


def _bucket_tables(buckets, schema):
    return [
        pa.Table.from_batches(bs, schema=schema) if bs
        else pa.table({f.name: pa.array([], f.type) for f in schema}, schema=schema)
        for bs in buckets
    ]


def test_device_and_host_buckets_byte_identical():
    """The acceptance-bar core: the on-device all_to_all produces buckets
    byte-identical to the host split (the writer's routing minus the files)
    — same rows, same order, nulls/strings/floats/ints all round-tripped."""
    _need_devices(2)
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

    tbl = _producer_table()
    schema = tbl.schema
    RUN_STATS.clear()
    mesh_buckets = _collect_buckets(_mesh_exchange_over(tbl), _mesh_cfg())
    stats = RUN_STATS.snapshot()
    assert stats.get("mesh_mode_reason") == "mesh"
    assert stats.get("mesh_devices", 0) >= 2
    assert stats.get("exchange_bytes_on_device", 0) > 0
    # force the host split via the min-rows demotion rung
    RUN_STATS.clear()
    host_buckets = _collect_buckets(
        _mesh_exchange_over(tbl), _mesh_cfg(**{TPU_MESH_MIN_ROWS: 10**9}))
    assert RUN_STATS.snapshot().get("mesh_mode_reason") == "demoted:small-input"

    assert [len(bs) for bs in mesh_buckets] == [len(bs) for bs in host_buckets]
    for p, (mt, ht) in enumerate(zip(_bucket_tables(mesh_buckets, schema),
                                     _bucket_tables(host_buckets, schema))):
        assert mt.equals(ht), f"device bucket {p} diverges from host split"
    # every input row landed in exactly one bucket
    total = sum(b.num_rows for bs in mesh_buckets for b in bs)
    assert total == tbl.num_rows


def test_capacity_overflow_demotes_with_reason():
    _need_devices(2)
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

    tbl = _producer_table()
    RUN_STATS.clear()
    buckets = _collect_buckets(
        _mesh_exchange_over(tbl), _mesh_cfg(**{TPU_MESH_EXCHANGE_CAPACITY: 1}))
    assert RUN_STATS.snapshot().get("mesh_mode_reason") == "demoted:capacity"
    # the demoted path still serves every row — no silent truncation
    assert sum(b.num_rows for bs in buckets for b in bs) == tbl.num_rows


def test_unsupported_dtype_demotes_with_reason():
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

    tbl = _producer_table(n=500, with_nulls=False)
    tbl = tbl.append_column("blob", pa.array([b"x"] * 500, type=pa.binary()))
    RUN_STATS.clear()
    buckets = _collect_buckets(_mesh_exchange_over(tbl), _mesh_cfg())
    reason = RUN_STATS.snapshot().get("mesh_mode_reason", "")
    assert reason.startswith("demoted:dtype")
    assert sum(b.num_rows for bs in buckets for b in bs) == tbl.num_rows


def test_aqe_demote_reason_forces_host_path():
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

    node = _mesh_exchange_over(_producer_table(n=400, with_nulls=False))
    node.demote_reason = "aqe:input-bytes(9>1)"
    RUN_STATS.clear()
    _collect_buckets(node, _mesh_cfg())
    assert RUN_STATS.snapshot().get("mesh_mode_reason") == "demoted:aqe:input-bytes(9>1)"


# -- end to end through the real scheduler ------------------------------------


_E2E_SQL = ("select k, sum(v) s, count(*) c, min(q) mn "
            "from t where q < 700 group by k order by k")


def _shuffle_stage_dirs(work_dir: str) -> dict[str, set[int]]:
    """job_id → set of stage ids that wrote shuffle files."""
    out: dict[str, set[int]] = {}
    for job in os.listdir(work_dir):
        jp = os.path.join(work_dir, job)
        if not os.path.isdir(jp):
            continue
        out[job] = {int(d) for d in os.listdir(jp) if d.isdigit()}
    return out


def _run_standalone(tbl, mesh: bool, **over):
    from ballista_tpu.client.context import SessionContext

    cfg = _mesh_cfg(**{TPU_MESH_ENABLED: mesh, **over})
    ctx = SessionContext.standalone(cfg, num_executors=1, vcores=2)
    try:
        ctx.register_arrow_table("t", tbl, partitions=4)
        out = ctx.sql(_E2E_SQL).collect()
        sched = ctx._cluster.scheduler
        with sched._jobs_lock:
            graph = list(sched.jobs.values())[-1]
        stage_dirs = _shuffle_stage_dirs(ctx._cluster.work_dir).get(graph.job_id, set())
        return out, graph, stage_dirs
    finally:
        ctx.shutdown()


@pytest.mark.multichip
def test_e2e_mesh_parity_and_zero_shuffle_files():
    _need_devices(2)
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS
    from ballista_tpu.shuffle.reader import ShuffleReaderExec

    tbl = _producer_table(n=12_000)

    ref, ref_graph, ref_dirs = _run_standalone(tbl, mesh=False)

    RUN_STATS.clear()
    got, graph, dirs = _run_standalone(tbl, mesh=True)
    stats = RUN_STATS.snapshot()

    # byte parity against the per-partition path
    assert got.equals(ref), "mesh-mode result diverges from per-partition path"

    # the fused stage ran with the on-device exchange, spanning the mesh
    assert stats.get("mesh_mode_reason") == "mesh"
    assert stats.get("mesh_devices", 0) >= 2
    assert stats.get("exchange_bytes_on_device", 0) > 0
    assert stats.get("exchange_s", 0) > 0

    # the exchange edge vanished from the stage DAG: fewer stages, and the
    # merged stage's plan reads no shuffle files at all
    assert len(graph.stages) < len(ref_graph.stages)
    mesh_stage = next(s for s in graph.stages.values() if s.spec.mesh)
    plan = mesh_stage.resolved_plan or mesh_stage.spec.plan
    readers = [n for n in iter_plan(plan) if isinstance(n, ShuffleReaderExec)]
    assert not readers, "fused edge must not read shuffle files"
    # zero shuffle-file writes for the fused edge: the eliminated producer
    # stage wrote files in the reference run and has NO directory now
    gone = {s.stage_id for s in ref_graph.stages.values()} - set(graph.stages)
    assert gone and gone <= ref_dirs
    assert not (gone & dirs), "mesh run must not write files for the fused edge"
    # what remains on disk is exactly the surviving stages' outputs
    assert dirs <= set(graph.stages)


@pytest.mark.multichip
def test_e2e_capacity_demotion_stays_correct():
    _need_devices(2)
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

    tbl = _producer_table(n=6_000)
    ref, _, _ = _run_standalone(tbl, mesh=False)
    RUN_STATS.clear()
    got, graph, _ = _run_standalone(tbl, mesh=True,
                                    **{TPU_MESH_EXCHANGE_CAPACITY: 1})
    assert RUN_STATS.snapshot().get("mesh_mode_reason") == "demoted:capacity"
    assert got.equals(ref), "capacity-demoted mesh stage diverges"
