"""ROLLUP / CUBE / GROUPING SETS lowering (one Aggregate branch per set,
typed-NULL fill, UNION ALL) — local, distributed, and TPC-DS shaped."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest


@pytest.fixture()
def ctx():
    from ballista_tpu.client.context import SessionContext

    rng = np.random.default_rng(2)
    n = 8_000
    tbl = pa.table({
        "a": rng.choice(["x", "y", "z"], n),
        "b": rng.choice(["p", "q"], n),
        "v": rng.integers(1, 100, n),
    })
    c = SessionContext()
    c.register_arrow_table("t", tbl, partitions=3)
    c._tbl = tbl
    return c


def test_rollup(ctx):
    out = ctx.sql(
        "select a, b, sum(v) s, count(*) c from t group by rollup(a, b)"
    ).collect().to_pandas()
    df = ctx._tbl.to_pandas()
    n_full = len(df.groupby(["a", "b"]))
    assert len(out) == n_full + df.a.nunique() + 1
    tot = out[out.a.isna() & out.b.isna()]
    assert tot.s.tolist() == [df.v.sum()] and tot.c.tolist() == [len(df)]
    bya = out[out.a.notna() & out.b.isna()].sort_values("a")
    exp = df.groupby("a")["v"].sum()
    assert bya.s.tolist() == exp.tolist()


def test_cube_and_grouping_sets(ctx):
    df = ctx._tbl.to_pandas()
    cube = ctx.sql("select a, b, sum(v) s from t group by cube(a, b)").collect()
    assert cube.num_rows == len(df.groupby(["a", "b"])) + df.a.nunique() + df.b.nunique() + 1
    gs = ctx.sql(
        "select a, b, sum(v) s from t group by grouping sets ((a), (b))"
    ).collect().to_pandas()
    assert len(gs) == df.a.nunique() + df.b.nunique()
    byb = gs[gs.a.isna()].sort_values("b")
    assert byb.s.tolist() == df.groupby("b")["v"].sum().tolist()


def test_rollup_having_and_order(ctx):
    df = ctx._tbl.to_pandas()
    out = ctx.sql(
        "select a, b, sum(v) s from t group by rollup(a, b) "
        "having sum(v) > 100 order by s desc limit 3"
    ).collect().to_pandas()
    assert out.s.tolist()[0] == df.v.sum()  # grand total ranks first
    assert (out.s.values[:-1] >= out.s.values[1:]).all()


def test_rollup_distributed_standalone(tmp_path):
    import pyarrow.parquet as pq

    from ballista_tpu.client.context import SessionContext

    rng = np.random.default_rng(3)
    n = 5_000
    tbl = pa.table({
        "a": rng.choice(["x", "y"], n),
        "b": rng.choice(["p", "q", "r"], n),
        "v": rng.integers(1, 50, n),
    })
    pq.write_table(tbl, str(tmp_path / "t.parquet"))
    ctx = SessionContext.standalone()
    ctx.register_parquet("t", str(tmp_path / "t.parquet"))
    out = ctx.sql("select a, b, sum(v) s from t group by rollup(a, b)").collect().to_pandas()
    df = tbl.to_pandas()
    assert len(out) == len(df.groupby(["a", "b"])) + df.a.nunique() + 1
    assert out[out.a.isna()].s.tolist() == [df.v.sum()]


def test_tpcds_q36_shaped_rollup(tmp_path_factory):
    """TPC-DS q36 shape (minus its rank window): gross-margin rollup over
    category/class with date+item joins."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpcdsgen import generate_tpcds, register_tpcds

    d = str(tmp_path_factory.mktemp("tpcds36"))
    generate_tpcds(d, scale=0.05, seed=17)
    ctx = SessionContext()
    register_tpcds(ctx, d)
    out = ctx.sql(
        "SELECT sum(ss_net_profit) / sum(ss_ext_sales_price) AS gross_margin, "
        "       i_category, i_class "
        "FROM store_sales, date_dim, item "
        "WHERE d_date_sk = ss_sold_date_sk AND i_item_sk = ss_item_sk AND d_year = 2001 "
        "GROUP BY ROLLUP(i_category, i_class) "
        "ORDER BY gross_margin LIMIT 100"
    ).collect().to_pandas()
    import pyarrow.parquet as pq

    ss = pq.read_table(f"{d}/store_sales").to_pandas()
    dd = pq.read_table(f"{d}/date_dim").to_pandas()
    it = pq.read_table(f"{d}/item").to_pandas()
    m = ss.merge(dd[dd.d_year == 2001], left_on="ss_sold_date_sk", right_on="d_date_sk")
    m = m.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    full = m.groupby(["i_category", "i_class"])
    expected_rows = len(full) + m.i_category.nunique() + 1
    assert len(out) == min(100, expected_rows)
    total = out[out.i_category.isna()]
    assert np.allclose(
        total.gross_margin.values, [m.ss_net_profit.sum() / m.ss_ext_sales_price.sum()]
    )


def test_aggregate_over_grouping_key(ctx):
    """Aggregate args must keep real values even when their column is a
    grouped-out key (only the OUTPUT key becomes NULL)."""
    df = ctx._tbl.to_pandas()
    out = ctx.sql(
        "select a, sum(v) s, count(*) c from t group by rollup(a)"
    ).collect().to_pandas()
    tot = out[out.a.isna()]
    assert tot.s.tolist() == [df.v.sum()]


def test_soft_keywords_stay_identifiers():
    from ballista_tpu.client.context import SessionContext

    ctx = SessionContext()
    ctx.register_arrow_table("t2", pa.table({"sets": [1, 2], "cube": [3, 4], "rollup": [5, 6]}))
    out = ctx.sql("select sets, cube, rollup from t2 order by sets").collect().to_pandas()
    assert out.sets.tolist() == [1, 2]
    assert out["cube"].tolist() == [3, 4]
    assert out["rollup"].tolist() == [5, 6]


def test_grouping_fn_and_rank_over_rollup(ctx):
    """grouping() markers + window functions computed over the whole
    grouping-sets union (TPC-DS q36 shape)."""
    out = ctx.sql(
        "select sum(v) s, a, b, grouping(a) + grouping(b) lvl, "
        "rank() over (partition by grouping(a) + grouping(b) order by sum(v)) r "
        "from t group by rollup(a, b) order by lvl desc, a, b"
    ).collect().to_pandas()
    df = ctx._tbl.to_pandas()
    assert set(out.lvl) == {0, 1, 2}
    top = out[out.lvl == 2]
    assert top.s.tolist() == [df.v.sum()] and top.r.tolist() == [1]
    lvl1 = out[out.lvl == 1].sort_values("r")
    exp = df.groupby("a")["v"].sum().sort_values()
    assert lvl1.s.tolist() == exp.tolist()
    n_full = len(df.groupby(["a", "b"]))
    assert sorted(out[out.lvl == 0].r.tolist()) == list(range(1, n_full + 1))
