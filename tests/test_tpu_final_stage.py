"""TpuFinalStageExec: device execution of final-agg / sort / top-K stages.

Reference parity target: the engine owns EVERY stage shape
(ballista/executor/src/execution_engine.rs:51) — round 3 extends device
execution beyond partial-agg chains to the merge/sort stage class.
Each test cross-checks the tpu engine against the cpu engine and asserts
the device path actually ran (no silent fallback)."""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import (
    BallistaConfig,
    EXECUTOR_ENGINE,
    TPU_MIN_ROWS,
)


def _walk(n):
    yield n
    for c in n.children():
        yield from _walk(c)


def _run_checked(sql, tables, expect_final=1):
    """Run on both engines; assert `expect_final` device final stages
    compiled AND ran with zero fallbacks; return (tpu, cpu) tables."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.ops.tpu.final_stage import TpuFinalStageExec
    from ballista_tpu.plan.physical import TaskContext

    results = {}
    for engine in ("tpu", "cpu"):
        cfg = BallistaConfig({EXECUTOR_ENGINE: engine, TPU_MIN_ROWS: 0})
        ctx = SessionContext(cfg)
        for name, tbl in tables.items():
            ctx.register_arrow_table(name, tbl, partitions=2)
        results[engine] = ctx.sql(sql).collect()
        if engine == "tpu":
            phys = maybe_compile_tpu(ctx.create_physical_plan(ctx.sql(sql).plan), cfg)
            stages = [nd for nd in _walk(phys) if isinstance(nd, TpuFinalStageExec)]
            assert len(stages) == expect_final, phys.display()
            tc = TaskContext(cfg)
            for p in range(phys.output_partition_count()):
                list(phys.execute(p, tc))
            assert all(s.tpu_count == 1 for s in stages), "final stage did not run on device"
            assert all(s.fallback_count == 0 for s in stages), "final stage fell back"
    return results["tpu"], results["cpu"]


def test_final_merge_sort_limit_all_agg_kinds():
    """sum/count/min/max/avg merge + two-key ORDER BY (DESC then ASC) +
    LIMIT — the q3/q10 stage class — matches the CPU engine exactly."""
    rng = np.random.default_rng(7)
    n = 20000
    t = pa.table({
        "g": rng.integers(0, 500, n).astype("int64"),
        "s": pa.array([f"name{i % 37}" for i in range(n)]),
        "v": np.round(rng.random(n) * 100, 2),
        "w": rng.integers(0, 1000, n).astype("int64"),
    })
    sql = ("SELECT g, s, sum(v) AS sv, count(*) AS c, min(w) AS mw, "
           "max(w) AS xw, avg(v) AS av "
           "FROM t GROUP BY g, s ORDER BY sv DESC, g ASC LIMIT 25")
    tpu, cpu = _run_checked(sql, {"t": t})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert tp.g.tolist() == cp.g.tolist()
    assert tp.s.tolist() == cp.s.tolist()
    assert np.allclose(tp.sv.values, cp.sv.values)
    assert tp.c.tolist() == cp.c.tolist()
    assert tp.mw.tolist() == cp.mw.tolist()
    assert tp.xw.tolist() == cp.xw.tolist()
    assert np.allclose(tp.av.values, cp.av.values)


def test_final_stage_nullable_keys_and_accumulators():
    """NULL group keys form their own group; a group whose agg inputs are
    all NULL decodes to NULL after the device merge (not 0 / ±inf)."""
    rng = np.random.default_rng(11)
    n = 8000
    g = rng.integers(0, 50, n).astype("int64")
    null_g = rng.random(n) < 0.1
    v = np.round(rng.random(n) * 10, 2)
    null_v = rng.random(n) < 0.3
    null_v[g == 49] = True  # group 49: all agg inputs NULL
    t = pa.table({
        "g": pa.array(g, pa.int64(), mask=null_g),
        "v": pa.array(v, pa.float64(), mask=null_v),
    })
    sql = ("SELECT g, sum(v) AS s, min(v) AS mn, max(v) AS mx, count(v) AS c "
           "FROM t GROUP BY g ORDER BY g ASC LIMIT 100")
    tpu, cpu = _run_checked(sql, {"t": t})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert tp.g.fillna(-1).tolist() == cp.g.fillna(-1).tolist()
    assert tp.s.isna().tolist() == cp.s.isna().tolist()
    assert np.allclose(tp.s.fillna(0).values, cp.s.fillna(0).values)
    assert tp.mn.isna().tolist() == cp.mn.isna().tolist()
    assert np.allclose(tp.mn.fillna(0).values, cp.mn.fillna(0).values)
    assert tp.c.tolist() == cp.c.tolist()


def test_final_stage_having_filter():
    """HAVING lowers as a device-side filter over merged groups."""
    rng = np.random.default_rng(13)
    n = 10000
    t = pa.table({
        "g": rng.integers(0, 200, n).astype("int64"),
        "v": rng.integers(1, 10, n).astype("int64"),
    })
    sql = ("SELECT g, sum(v) AS s, count(*) AS c FROM t GROUP BY g "
           "HAVING sum(v) > 250 ORDER BY s DESC, g ASC")
    tpu, cpu = _run_checked(sql, {"t": t})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert len(tp) == len(cp) and len(tp) > 0
    assert tp.g.tolist() == cp.g.tolist()
    assert tp.s.tolist() == cp.s.tolist()


def test_final_stage_string_sort_key_collation():
    """String ORDER BY keys sort by host-built lexicographic rank LUTs —
    dictionary code order (appearance order) must never leak through."""
    rng = np.random.default_rng(17)
    n = 6000
    # appearance order deliberately differs from lexicographic order
    names = [f"{'zyxwv'[i % 5]}_cat{i % 23:02d}" for i in range(n)]
    t = pa.table({
        "s": pa.array(names),
        "v": rng.integers(0, 100, n).astype("int64"),
    })
    for direction in ("ASC", "DESC"):
        sql = (f"SELECT s, sum(v) AS sv FROM t GROUP BY s "
               f"ORDER BY s {direction} LIMIT 30")
        tpu, cpu = _run_checked(sql, {"t": t})
        tp, cp = tpu.to_pandas(), cpu.to_pandas()
        assert tp.s.tolist() == cp.s.tolist(), direction
        assert tp.sv.tolist() == cp.sv.tolist(), direction


def test_final_stage_money_group_key():
    """Float group keys that refine to fixed-point money (the q10/q18
    c_acctbal / o_totalprice shape) group and sort exactly on device."""
    rng = np.random.default_rng(19)
    n = 9000
    prices = np.round(rng.integers(100, 400, n) + rng.integers(0, 100, n) / 100.0, 2)
    t = pa.table({
        "price": pa.array(prices, pa.float64()),
        "q": rng.integers(1, 50, n).astype("int64"),
    })
    sql = ("SELECT price, sum(q) AS tq, count(*) AS c FROM t GROUP BY price "
           "ORDER BY price DESC LIMIT 50")
    tpu, cpu = _run_checked(sql, {"t": t})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert np.allclose(tp.price.values, cp.price.values)
    assert tp.tq.tolist() == cp.tq.tolist()
    assert tp.c.tolist() == cp.c.tolist()


def test_final_stage_no_sort_projection_only():
    """Final merge + post-projection without ORDER BY still lowers (the
    writer-rooted merge stage shape); row order is engine-defined so
    compare as sets keyed by the group column."""
    rng = np.random.default_rng(23)
    n = 12000
    t = pa.table({
        "g": rng.integers(0, 300, n).astype("int64"),
        "a": np.round(rng.random(n) * 5, 2),
        "b": rng.integers(0, 7, n).astype("int64"),
    })
    sql = "SELECT g, sum(a) AS sa, avg(a) AS aa, sum(b) AS sb FROM t GROUP BY g"
    tpu, cpu = _run_checked(sql, {"t": t})
    tp = tpu.to_pandas().sort_values("g").reset_index(drop=True)
    cp = cpu.to_pandas().sort_values("g").reset_index(drop=True)
    assert tp.g.tolist() == cp.g.tolist()
    assert np.allclose(tp.sa.values, cp.sa.values)
    assert np.allclose(tp.aa.values, cp.aa.values)
    assert tp.sb.tolist() == cp.sb.tolist()


def test_final_stage_fetch_exceeds_groups():
    """LIMIT larger than the group count returns every group."""
    rng = np.random.default_rng(29)
    n = 5000
    t = pa.table({
        "g": rng.integers(0, 8, n).astype("int64"),
        "v": rng.integers(0, 100, n).astype("int64"),
    })
    sql = "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY s DESC LIMIT 1000"
    tpu, cpu = _run_checked(sql, {"t": t})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert len(tp) == 8
    assert tp.g.tolist() == cp.g.tolist()
    assert tp.s.tolist() == cp.s.tolist()


def test_final_stage_welford_not_matched():
    """Variance queries keep their final merge on CPU (welford triples are
    merged host-side) — the matcher must not wrap them, so the query still
    answers correctly with zero device-final stages."""
    rng = np.random.default_rng(31)
    n = 6000
    t = pa.table({
        "g": rng.integers(0, 20, n).astype("int64"),
        "v": rng.normal(100.0, 10.0, n),
    })
    sql = "SELECT g, stddev(v) AS sd FROM t GROUP BY g ORDER BY g"
    tpu, cpu = _run_checked(sql, {"t": t}, expect_final=0)
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert tp.g.tolist() == cp.g.tolist()
    assert np.allclose(tp.sd.values, cp.sd.values, rtol=1e-9)


def test_final_stage_date_group_and_sort():
    """Date group keys and date sort keys ride the int32 day lanes."""
    import datetime as dt

    rng = np.random.default_rng(37)
    n = 7000
    base = dt.date(1995, 1, 1)
    days = rng.integers(0, 365, n)
    t = pa.table({
        "d": pa.array([base + dt.timedelta(days=int(x)) for x in days], pa.date32()),
        "v": rng.integers(0, 100, n).astype("int64"),
    })
    sql = ("SELECT d, sum(v) AS s, count(*) AS c FROM t GROUP BY d "
           "ORDER BY d DESC LIMIT 40")
    tpu, cpu = _run_checked(sql, {"t": t})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert tp.d.tolist() == cp.d.tolist()
    assert tp.s.tolist() == cp.s.tolist()
    assert tp.c.tolist() == cp.c.tolist()


def test_final_stage_hbm_cap_enforced():
    """A final stage whose [P, N] stacking exceeds TPU_MAX_DEVICE_BYTES is
    rejected with Unsupported BEFORE dispatch (no device OOM reliance) and
    the query falls back to the CPU subtree with the right answer."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import TPU_MAX_DEVICE_BYTES
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.ops.tpu.final_stage import TpuFinalStageExec
    from ballista_tpu.ops.tpu.kernels import Unsupported
    from ballista_tpu.plan.physical import TaskContext

    rng = np.random.default_rng(43)
    n = 4000
    t = pa.table({
        "g": rng.integers(0, 50, n).astype("int64"),
        "v": rng.integers(0, 100, n).astype("int64"),
    })
    sql = "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY s DESC LIMIT 5"
    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0,
                          TPU_MAX_DEVICE_BYTES: 1024})
    ctx = SessionContext(cfg)
    ctx.register_arrow_table("t", t, partitions=2)
    phys = maybe_compile_tpu(ctx.create_physical_plan(ctx.sql(sql).plan), cfg)
    stages = [nd for nd in _walk(phys) if isinstance(nd, TpuFinalStageExec)]
    assert len(stages) == 1, phys.display()
    # the budget check raises Unsupported cleanly (not a device error)
    with pytest.raises(Unsupported, match="device bytes"):
        stages[0]._tpu_run_all(TaskContext(cfg))
    # ... and the full query answers correctly through the CPU fallback
    out = ctx.sql(sql).collect().to_pandas()
    cfg_cpu = BallistaConfig({EXECUTOR_ENGINE: "cpu"})
    ctx_cpu = SessionContext(cfg_cpu)
    ctx_cpu.register_arrow_table("t", t, partitions=2)
    exp = ctx_cpu.sql(sql).collect().to_pandas()
    assert out.g.tolist() == exp.g.tolist()
    assert out.s.tolist() == exp.s.tolist()
    assert all(s.tpu_count == 0 for s in stages)


def test_bypass_partitioning_contract():
    """Pin the hash-repartition bypass contract (final_stage.py): the exec
    still advertises the repartition's K output partitions, but ALL rows
    come out on partition 0 and partitions 1..K-1 are empty — consumers
    must never trust declared hash placement of this node's output."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.ops.tpu.final_stage import TpuFinalStageExec
    from ballista_tpu.plan.physical import RepartitionExec, TaskContext

    rng = np.random.default_rng(47)
    n = 6000
    t = pa.table({
        "g": rng.integers(0, 100, n).astype("int64"),
        "v": rng.integers(0, 10, n).astype("int64"),
    })
    sql = "SELECT g, sum(v) AS s FROM t GROUP BY g"
    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
    ctx = SessionContext(cfg)
    ctx.register_arrow_table("t", t, partitions=2)
    phys = maybe_compile_tpu(ctx.create_physical_plan(ctx.sql(sql).plan), cfg)
    stages = [nd for nd in _walk(phys) if isinstance(nd, TpuFinalStageExec)]
    assert len(stages) == 1, phys.display()
    fs = stages[0]
    if not isinstance(fs.child, RepartitionExec):
        pytest.skip("local plan no longer places a hash repartition here")
    k = fs.output_partition_count()
    assert k > 1  # the advertised partition count is the repartition's K
    tc = TaskContext(cfg)
    rows_by_part = [
        sum(b.num_rows for b in fs.execute(p, tc)) for p in range(k)
    ]
    assert fs.tpu_count == 1 and fs.fallback_count == 0
    assert rows_by_part[0] == 100  # every group lands on partition 0
    assert all(r == 0 for r in rows_by_part[1:])


def test_final_stage_concurrent_contexts_share_cache_entry():
    """Two sessions with identical stage shapes share one compile-cache
    entry; concurrent execution is serialized by the per-entry run lock and
    both answers are exact (pins the retrace/`cell`-race guard)."""
    import threading

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.ops.tpu.final_stage import TpuFinalStageExec
    from ballista_tpu.plan.physical import TaskContext

    rng = np.random.default_rng(53)
    n = 8000
    sql = "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY s DESC LIMIT 7"

    def make_table(seed):
        r = np.random.default_rng(seed)
        return pa.table({
            "g": r.integers(0, 60, n).astype("int64"),
            "v": r.integers(0, 100, n).astype("int64"),
        })

    tables = [make_table(s) for s in (1, 2)]
    outs: dict = {}
    errs: list = []

    def run(i):
        try:
            cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
            ctx = SessionContext(cfg)
            ctx.register_arrow_table("t", tables[i], partitions=2)
            phys = maybe_compile_tpu(
                ctx.create_physical_plan(ctx.sql(sql).plan), cfg)
            stages = [nd for nd in _walk(phys)
                      if isinstance(nd, TpuFinalStageExec)]
            assert len(stages) == 1
            tc = TaskContext(cfg)
            batches = []
            for p in range(phys.output_partition_count()):
                batches.extend(phys.execute(p, tc))
            assert stages[0].tpu_count == 1 and stages[0].fallback_count == 0
            outs[i] = pa.Table.from_batches(
                [b for b in batches if b.num_rows], phys.schema())
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errs, errs
    for i in range(2):
        got = outs[i].to_pandas()
        df = tables[i].to_pandas().groupby("g", as_index=False).v.sum()
        exp = df.sort_values(["v", "g"], ascending=[False, True]).head(7)
        assert got.g.tolist() == exp.g.tolist()
        assert got.s.tolist() == exp.v.tolist()


def test_final_stage_distributed_standalone():
    """The staged (distributed) path: a standalone cluster on the tpu
    engine produces the same q3-class answer as the cpu engine."""
    from ballista_tpu.client.context import SessionContext

    rng = np.random.default_rng(41)
    n = 15000
    t = pa.table({
        "g": rng.integers(0, 400, n).astype("int64"),
        "v": np.round(rng.random(n) * 100, 2),
    })
    sql = "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY s DESC LIMIT 10"
    results = {}
    for engine in ("tpu", "cpu"):
        cfg = BallistaConfig({EXECUTOR_ENGINE: engine, TPU_MIN_ROWS: 0})
        ctx = SessionContext.standalone(cfg)
        try:
            ctx.register_arrow_table("t", t, partitions=2)
            results[engine] = ctx.sql(sql).collect()
        finally:
            ctx.shutdown()
    tp, cp = results["tpu"].to_pandas(), results["cpu"].to_pandas()
    assert tp.g.tolist() == cp.g.tolist()
    assert np.allclose(tp.s.values, cp.s.values)


def test_declined_final_stage_reuses_materialized_child():
    """When the final stage declines the device (e.g. merged input below
    TPU_MIN_ROWS), its CPU fallback must aggregate the child output the
    device attempt ALREADY materialized — never re-execute the child
    subtree (which would silently re-scan the whole input on the host:
    the 100x-overhead bug the round-5 profile pinned). The child device
    stage must therefore report zero CPU fallbacks."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.ops.tpu.final_stage import TpuFinalStageExec
    from ballista_tpu.ops.tpu.stage_compiler import TpuStageExec
    from ballista_tpu.plan.physical import TaskContext

    rng = np.random.default_rng(7)
    n = 40000
    t = pa.table({
        "g": rng.integers(0, 3, n).astype("int64"),  # 3 groups << min_rows
        "v": rng.integers(0, 1000, n).astype("int64"),
    })
    sql = "SELECT g, sum(v) AS s, count(*) AS c FROM t GROUP BY g ORDER BY g"
    # min_rows low enough for the 40k-row scan stage to take the device,
    # high enough that the handful of merged partial rows decline it
    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 100})
    ctx = SessionContext(cfg)
    ctx.register_arrow_table("t", t, partitions=4)
    phys = maybe_compile_tpu(ctx.create_physical_plan(ctx.sql(sql).plan), cfg)
    finals = [nd for nd in _walk(phys) if isinstance(nd, TpuFinalStageExec)]
    stages = [nd for nd in _walk(phys) if isinstance(nd, TpuStageExec)]
    assert finals and stages, phys.display()
    tc = TaskContext(cfg)
    rows = []
    for p in range(phys.output_partition_count()):
        for b in phys.execute(p, tc):
            rows.extend(b.to_pylist())
    # the final stage declined (device roundtrip not worth 3 rows) ...
    assert all(f.tpu_count == 0 and f.fallback_count > 0 for f in finals)
    # ... reused the materialized child output instead of re-scanning (the
    # child executed exactly once, on device, with no host fallback) ...
    assert all(s.fallback_count == 0 for s in stages), \
        "child stage re-executed on the host after its results were consumed"
    assert all(s.tpu_count == 1 for s in stages), \
        "child stage re-dispatched: fallback did not reuse the materialized tables"
    # ... and RELEASED the pinned host copy once the last expected fallback
    # partition was served (it must not stay resident for the plan's lifetime)
    assert all(f._mat_node is None and f._mat_input is None for f in finals), \
        "materialized child copy still pinned after serving"
    # correctness against pandas
    import pandas as pd

    want = (t.to_pandas().groupby("g", as_index=False)
            .agg(s=("v", "sum"), c=("v", "size")).sort_values("g"))
    got = pd.DataFrame(rows).sort_values("g")
    assert got.g.tolist() == want.g.tolist()
    assert got.s.tolist() == want.s.tolist()
    assert got.c.tolist() == want.c.tolist()


def test_consumed_device_results_rerun_not_host_fallback():
    """Re-executing a partition whose device result was already consumed
    re-dispatches the (hot) device path once and serves every partition
    from it — it must not degrade to a host re-scan of the subtree."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.ops.tpu.stage_compiler import TpuStageExec
    from ballista_tpu.plan.physical import TaskContext

    rng = np.random.default_rng(9)
    n = 30000
    t = pa.table({
        "g": rng.integers(0, 8, n).astype("int64"),
        "v": rng.integers(0, 1000, n).astype("int64"),
    })
    sql = "SELECT g, sum(v) AS s FROM t GROUP BY g"
    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
    ctx = SessionContext(cfg)
    ctx.register_arrow_table("t", t, partitions=3)
    phys = maybe_compile_tpu(ctx.create_physical_plan(ctx.sql(sql).plan), cfg)
    stages = [nd for nd in _walk(phys) if isinstance(nd, TpuStageExec)]
    assert stages
    st = stages[0]
    tc = TaskContext(cfg)
    first = [[b.to_pydict() for b in st.execute(p, tc)]
             for p in range(st.output_partition_count())]
    runs_after_first = st.tpu_count
    assert runs_after_first >= 1 and st.fallback_count == 0
    # consume AGAIN: one extra device dispatch serves all partitions
    second = [[b.to_pydict() for b in st.execute(p, tc)]
              for p in range(st.output_partition_count())]
    assert st.fallback_count == 0, "consumed re-read degraded to host fallback"
    assert st.tpu_count == runs_after_first + 1, \
        "re-read should cost exactly one re-dispatch"
    assert first == second
