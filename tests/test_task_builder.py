"""Per-task plan restriction (scheduler/task_builder.py — the reference's
state/task_builder.rs semantics): task protos must stay ~flat as partition
counts grow, and leaves under a collapse must keep full input."""

import pyarrow as pa

from ballista_tpu.config import BallistaConfig, EXECUTOR_ENGINE
from ballista_tpu.plan.physical import (
    CoalescePartitionsExec,
    FilterExec,
    HashJoinExec,
    ParquetScanExec,
)
from ballista_tpu.plan.schema import DFSchema
from ballista_tpu.scheduler.state.execution_graph import TaskDescription
from ballista_tpu.scheduler.task_builder import restrict_plan_to_partitions
from ballista_tpu.serde_control import encode_task_definition
from ballista_tpu.shuffle.reader import ShuffleReaderExec
from ballista_tpu.shuffle.types import PartitionLocation, PartitionStats
from ballista_tpu.shuffle.writer import ShuffleWriterExec
from ballista_tpu.plan.expressions import Column


def _schema():
    return DFSchema.from_arrow(pa.schema([("k", pa.int64()), ("v", pa.float64())]), "t")


def _locs(n_parts: int, n_locs: int):
    return [
        [
            PartitionLocation(
                map_partition=m, job_id="j", stage_id=1, output_partition=p,
                executor_id=f"e{m}", host=f"host-{m}.example.com", flight_port=50051,
                path=f"/work/j/1/{p}/data-{m}.arrow", layout="hash",
                stats=PartitionStats(100, 1000),
            )
            for m in range(n_locs)
        ]
        for p in range(n_parts)
    ]


def _task(plan, partitions):
    return TaskDescription(job_id="j", stage_id=2, stage_attempt=0, task_id=1,
                           partitions=partitions, plan=plan, session_id="s")


def test_task_plan_size_flat_vs_partition_count():
    """A 1-partition task's proto must not scale with the stage's total
    partition×location table (the SF1000 16 MiB plan ceiling failure)."""
    sizes = {}
    for n_parts in (16, 64, 256):
        reader = ShuffleReaderExec(_schema(), _locs(n_parts, 32))
        plan = ShuffleWriterExec(FilterExec(reader, Column("k", "t")), "j", 2,
                                 n_parts, [Column("k", "t")])
        full = encode_task_definition(_task(plan, list(range(n_parts)))).ByteSize()
        one = encode_task_definition(_task(plan, [3])).ByteSize()
        sizes[n_parts] = (one, full)
    # full plans grow linearly; single-partition tasks stay flat
    assert sizes[256][1] > 10 * sizes[16][0]
    assert sizes[256][0] < sizes[16][0] * 1.5, sizes
    assert sizes[256][0] < sizes[256][1] / 50, sizes


def test_restriction_keeps_global_partition_indexing():
    reader = ShuffleReaderExec(_schema(), _locs(8, 4))
    out = restrict_plan_to_partitions(FilterExec(reader, Column("k", "t")), [5])
    new_reader = out.children()[0]
    assert len(new_reader.partition_locations) == 8
    assert [len(l) for l in new_reader.partition_locations] == [0, 0, 0, 0, 0, 4, 0, 0]


def test_collapse_scoping_keeps_full_build_side():
    """Leaves under a collect_left build (and under CoalescePartitions)
    keep FULL input — the task_builder.rs under-collapse trap."""
    build_reader = ShuffleReaderExec(_schema(), _locs(4, 2))
    probe_reader = ShuffleReaderExec(_schema(), _locs(4, 2))
    join = HashJoinExec(
        CoalescePartitionsExec(build_reader), probe_reader,
        [(Column("k", "t"), Column("k", "t"))], "inner", None, "collect_left",
        _schema().merge(_schema()),
    )
    out = restrict_plan_to_partitions(join, [1])
    new_build = out.children()[0].children()[0]
    new_probe = out.children()[1]
    assert [len(l) for l in new_build.partition_locations] == [2, 2, 2, 2]
    assert [len(l) for l in new_probe.partition_locations] == [0, 2, 0, 0]


def test_tpu_engine_keeps_full_scans():
    """engine=tpu: scans stay whole (device-table cache is keyed on the
    scan's file set) while reader lists still shrink."""
    scan = ParquetScanExec(_schema(), [{"files": [{"file": f"/d/{i}.parquet"}]}
                                       for i in range(8)], ["k", "v"], [], "t")
    reader = ShuffleReaderExec(_schema(), _locs(8, 2))
    join = HashJoinExec(scan, reader, [(Column("k", "t"), Column("k", "t"))],
                        "inner", None, "partitioned", _schema().merge(_schema()))
    tpu_cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu"})
    out = restrict_plan_to_partitions(join, [2], tpu_cfg)
    assert [len(p["files"]) for p in out.children()[0].partitions] == [1] * 8
    assert [len(l) for l in out.children()[1].partition_locations] == [0, 0, 2, 0, 0, 0, 0, 0]
    cpu_out = restrict_plan_to_partitions(join, [2], BallistaConfig())
    assert [len(p["files"]) for p in cpu_out.children()[0].partitions] == [0, 0, 1, 0, 0, 0, 0, 0]
