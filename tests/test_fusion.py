"""Unit tests for the whole-stage fusion planner, cost model, and the
Pallas kernel family (interpreter mode — pure CPU, no TPC-H data, fast).

Heavier end-to-end parity tests (fused vs staged byte-identical on TPC-H
stages) live in tests/test_tpu_fusion.py.
"""

import numpy as np
import pytest

from ballista_tpu.ops.tpu.fusion import (
    AGGREGATE,
    CostModel,
    PREDICATE,
    PROBE,
    PROJECT,
    StageEstimate,
    plan_spans,
)


def _est(**kw):
    base = dict(
        rows=1_000_000, partitions=8, group_domain=8, n_group_keys=1,
        lanes=1, has_mult=False, n_filters=1, n_projections=1, n_joins=0,
        max_probe_table=0, agg_funcs=("sum", "count"),
    )
    base.update(kw)
    return StageEstimate(**base)


# ---------------------------------------------------------------- cost model


def test_forced_modes_win():
    for mode in ("staged", "fused_xla", "fused_pallas"):
        cm = CostModel(mode=mode)
        assert cm.choose(_est()).mode == mode


def test_disabled_falls_to_staged():
    cm = CostModel(enabled=False)
    dec = cm.choose(_est())
    assert dec.mode == "staged"
    assert "disabled" in dec.reason


def test_small_input_prefers_staged():
    # below min.rows AND staged-eligible: dispatch overhead dominates, the
    # per-span mode gives roofline taps for free
    cm = CostModel(min_fused_rows=4096)
    assert cm.choose(_est(rows=1000)).mode == "staged"
    # exactly at the threshold: fused
    assert cm.choose(_est(rows=4096)).mode == "fused_xla"


def test_small_but_staged_ineligible_fuses():
    cm = CostModel(min_fused_rows=4096)
    # expansion lanes disqualify the staged form
    assert cm.choose(_est(rows=1000, lanes=4)).mode == "fused_xla"
    # so does an unbounded group domain (sorted path)
    assert cm.choose(_est(rows=1000, group_domain=None)).mode == "fused_xla"


def test_tpu_platform_picks_pallas_when_eligible():
    cm = CostModel(platform="tpu")
    dec = cm.choose(_est(group_domain=256))
    assert dec.mode == "fused_pallas"


def test_cpu_platform_never_auto_picks_pallas():
    cm = CostModel(platform="cpu")
    assert cm.choose(_est(group_domain=256)).mode == "fused_xla"


def test_pallas_ineligibility_boundaries():
    cm = CostModel(platform="tpu")
    # G beyond the kernel ceiling
    assert cm.choose(_est(group_domain=1 << 20)).mode == "fused_xla"
    # unbounded group domain (int64 keys → sorted path)
    assert cm.choose(_est(group_domain=None)).mode == "fused_xla"
    # expansion lanes
    assert cm.choose(_est(lanes=4)).mode == "fused_xla"
    # aggregate-through-join weights
    assert cm.choose(_est(has_mult=True)).mode == "fused_xla"
    # min/max not in the kernel family
    assert cm.choose(_est(agg_funcs=("sum", "min"))).mode == "fused_xla"
    # scalar aggregation (G == 1) isn't worth a kernel launch
    assert cm.choose(_est(group_domain=1, n_group_keys=0)).mode == "fused_xla"


def test_legacy_pallas_knob_forces_kernel_path():
    # ballista.tpu.pallas.enabled predates the fusion knobs and must keep
    # working — even on CPU (interpreter mode), which tier-1 relies on
    cm = CostModel(force_pallas=True, platform="cpu")
    dec = cm.choose(_est())
    assert dec.mode == "fused_pallas"
    assert "legacy" in dec.reason


def test_fused_xla_reason_is_explanatory():
    cm = CostModel(platform="cpu")
    dec = cm.choose(_est(lanes=2, group_domain=None))
    assert "unbounded group domain" in dec.reason
    assert "2 expansion lanes" in dec.reason


# ------------------------------------------------------------- span planner


class _Fake:
    pass


def _mk(cls_name):
    from ballista_tpu.plan import physical

    cls = getattr(physical, cls_name)
    return object.__new__(cls)  # structure-only: planner isinstance checks


def test_plan_spans_merges_consecutive_kinds():
    ops = [_mk("FilterExec"), _mk("FilterExec"), _mk("CoalesceBatchesExec"),
           _mk("ProjectionExec"), _mk("HashJoinExec"), _mk("ProjectionExec")]
    spans = plan_spans(1, ops, agg=object())
    assert [(s.kind, s.ops) for s in spans] == [
        (PREDICATE, 3),  # scan filter + 2 FilterExec merge; Coalesce skipped
        (PROJECT, 1),
        (PROBE, 1),
        (PROJECT, 1),
        (AGGREGATE, 1),
    ]


def test_plan_spans_no_agg_no_filters():
    assert plan_spans(0, [], agg=None) == []
    spans = plan_spans(0, [_mk("ProjectionExec")], agg=None)
    assert [(s.kind, s.ops) for s in spans] == [(PROJECT, 1)]


# ------------------------------------------------- pallas kernels (interpret)


def test_masked_group_reduce_matches_numpy():
    from ballista_tpu.ops.tpu.pallas_kernels import masked_group_reduce

    rng = np.random.default_rng(7)
    P, N, G = 3, 512, 11
    vals = rng.uniform(-5, 5, (P, N)).astype(np.float32)
    gid = rng.integers(0, G, (P, N)).astype(np.int32)
    mask = rng.random((P, N)) < 0.7
    sums, cnts = masked_group_reduce(vals, gid, mask, G, block_n=128)
    sums, cnts = np.asarray(sums), np.asarray(cnts)
    assert sums.shape == (P, G) and cnts.shape == (P, G)
    for p in range(P):
        for g in range(G):
            sel = mask[p] & (gid[p] == g)
            assert cnts[p, g] == sel.sum()
            np.testing.assert_allclose(
                sums[p, g], vals[p][sel].astype(np.float64).sum(),
                rtol=1e-4, atol=1e-4)


def test_masked_group_reduce_multi_tile():
    # G = 300 needs 3 lane tiles of 128 — the multi-tile grid axis that
    # replaced the single-tile GROUP_LANES ceiling
    from ballista_tpu.ops.tpu.pallas_kernels import GROUP_LANES, masked_group_reduce

    G = 2 * GROUP_LANES + 44
    rng = np.random.default_rng(11)
    P, N = 2, 256
    vals = rng.uniform(0, 1, (P, N)).astype(np.float32)
    gid = rng.integers(0, G, (P, N)).astype(np.int32)
    mask = np.ones((P, N), dtype=bool)
    sums, cnts = masked_group_reduce(vals, gid, mask, G, block_n=256)
    sums, cnts = np.asarray(sums), np.asarray(cnts)
    assert sums.shape == (P, G)
    assert cnts.sum() == P * N
    ref = np.zeros((P, G))
    for p in range(P):
        np.add.at(ref[p], gid[p], vals[p].astype(np.float64))
    np.testing.assert_allclose(sums, ref, rtol=1e-4, atol=1e-4)


def test_masked_group_reduce_ceiling():
    from ballista_tpu.ops.tpu.pallas_kernels import MAX_GROUPS, masked_group_reduce

    with pytest.raises(ValueError):
        masked_group_reduce(
            np.zeros((1, 8), np.float32), np.zeros((1, 8), np.int32),
            np.ones((1, 8), bool), MAX_GROUPS + 1)


def test_hash_probe_matches_numpy():
    from ballista_tpu.ops.tpu.pallas_kernels import hash_probe

    rng = np.random.default_rng(3)
    T = 64
    table = np.full(T, -1, np.int32)
    present = rng.choice(T, size=40, replace=False)
    table[present] = np.arange(40, dtype=np.int32)
    P, N = 2, 256
    keys = rng.integers(0, T, (P, N)).astype(np.int32)
    mask = rng.random((P, N)) < 0.8
    rows, matched = hash_probe(keys, table, mask, block_n=128)
    rows, matched = np.asarray(rows), np.asarray(matched)
    exp_matched = mask & (table[keys] >= 0)
    np.testing.assert_array_equal(matched, exp_matched)
    np.testing.assert_array_equal(rows, np.where(exp_matched, table[keys], 0))
