"""On-device sort / window / top-k stages: byte parity with the CPU engine
on adversarial inputs, across all three fusion rungs.

Parity is asserted per column over Arrow IPC stream bytes — bitwise
(NaN payloads, ±0.0 signs) without the chunk-slicing layout artifacts a
whole-table stream picks up from `Table.slice`."""

import io

import numpy as np
import pyarrow as pa
import pyarrow.ipc as ipc
import pytest

from ballista_tpu.config import (
    BallistaConfig,
    TPU_FUSION_MODE,
    TPU_MIN_ROWS,
    TPU_SORT_ENABLED,
    TPU_SORT_PALLAS_MAX_ROWS,
    TPU_TOPK_ENABLED,
)
from ballista_tpu.plan.expressions import Column, SortKey, WindowFunction
from ballista_tpu.plan.physical import (
    ExecutionPlan,
    SortExec,
    TaskContext,
    WindowExec,
)
from ballista_tpu.plan.schema import DFSchema

MODES = ("staged", "fused_xla", "fused_pallas")


class _Src(ExecutionPlan):
    def __init__(self, tbl, df_schema, chunk=97):
        super().__init__(df_schema)
        self.tbl = tbl
        self.chunk = chunk

    def children(self):
        return []

    def output_partition_count(self):
        return 1

    def execute(self, partition, ctx):
        yield from self.tbl.to_batches(max_chunksize=self.chunk)


def _cfg(mode, **extra):
    settings = {TPU_MIN_ROWS: 0, TPU_FUSION_MODE: mode}
    settings.update(extra)
    return BallistaConfig(settings)


def _collect(plan, cfg):
    ctx = TaskContext(cfg)
    batches = list(plan.execute(0, ctx))
    return pa.Table.from_batches(batches, schema=plan.schema())


def _column_bytes(tbl):
    out = []
    for c in tbl.column_names:
        one = pa.table({c: tbl.column(c).combine_chunks()})
        buf = io.BytesIO()
        with ipc.new_stream(buf, one.schema) as w:
            w.write_table(one)
        out.append(buf.getvalue())
    return out


def _assert_parity(cpu_plan, dev_plan, cfg):
    cpu = _collect(cpu_plan, cfg)
    dev = _collect(dev_plan, cfg)
    assert dev_plan.tpu_count >= 1, "device path did not run"
    assert dev_plan.fallback_count == 0, "device path fell back"
    assert _column_bytes(cpu) == _column_bytes(dev)
    return cpu, dev


def _adversarial_table(n=384):
    rng = np.random.default_rng(11)
    f = rng.integers(-40, 40, n).astype(np.float64)
    f[::7] = np.nan
    f[::11] = 0.0
    f[1::11] = -0.0
    return pa.table({
        "f": pa.array(f),
        "i": pa.array(rng.integers(0, 12, n), pa.int64()),
        "inull": pa.array(
            [None if j % 5 == 0 else int(v)
             for j, v in enumerate(rng.integers(0, 7, n))], pa.int32()),
        "s": pa.array([["aa", "b", "aa", "zz", "m"][j % 5] if j % 13 else None
                       for j in range(n)]),
    })


@pytest.mark.parametrize("mode", MODES)
def test_sort_parity_adversarial(mode):
    """NULLS FIRST/LAST per key, NaN and ±0.0 ordering, string keys, multi
    key DESC — byte-identical to the CPU sort, with and without LIMIT."""
    from ballista_tpu.ops.tpu.sort_window import TpuSortStageExec

    tbl = _adversarial_table()
    schema = DFSchema.from_arrow(tbl.schema)
    cfg = _cfg(mode)
    keysets = [
        [SortKey(Column("i")), SortKey(Column("f"), ascending=False,
                                       nulls_first=True)],
        [SortKey(Column("inull"), nulls_first=True)],
        [SortKey(Column("inull"), ascending=False, nulls_first=False)],
        [SortKey(Column("s")), SortKey(Column("i"), ascending=False)],
        [SortKey(Column("f"))],
    ]
    for keys in keysets:
        for fetch in (None, 10):
            _assert_parity(
                SortExec(_Src(tbl, schema), keys, fetch),
                TpuSortStageExec(_Src(tbl, schema), keys, fetch, cfg),
                cfg)


@pytest.mark.parametrize("mode", MODES)
def test_topk_ties_at_cut_boundary(mode):
    """Duplicate key values straddling the LIMIT cut: the fused top-k must
    keep exactly the rows the stable full sort keeps."""
    from ballista_tpu.ops.tpu.sort_window import TpuSortStageExec

    n = 300
    # every key value appears 20×, so any small LIMIT cuts inside a tie run
    tbl = pa.table({
        "k": pa.array([j % 15 for j in range(n)], pa.int64()),
        "payload": pa.array(range(n), pa.int64()),
    })
    schema = DFSchema.from_arrow(tbl.schema)
    cfg = _cfg(mode)
    for fetch in (7, 20, 33):
        keys = [SortKey(Column("k"))]
        _assert_parity(SortExec(_Src(tbl, schema), keys, fetch),
                       TpuSortStageExec(_Src(tbl, schema), keys, fetch, cfg),
                       cfg)


@pytest.mark.parametrize("mode", ("staged", "fused_pallas"))
def test_sort_dictionary_duplicate_values(mode):
    """A dictionary whose entries contain duplicate strings: equal strings
    must share a rank (ties fall to stability), matching the CPU sort of
    the decoded column. The CPU oracle itself cannot sort dictionary
    columns, so this shape is pure device upside."""
    from ballista_tpu.ops.tpu.sort_window import TpuSortStageExec

    codes = pa.array([0, 1, 2, 3, 4, 0, 2, 1, 3, 0] * 30, pa.int32())
    dup = pa.DictionaryArray.from_arrays(
        codes, pa.array(["b", "aa", "b", "c", "aa"]))
    payload = pa.array(range(300), pa.int64())
    tbl = pa.table({"s": dup, "p": payload})
    schema = DFSchema.from_arrow(tbl.schema)
    dec = pa.table({"s": dup.cast(pa.string()), "p": payload})
    dec_schema = DFSchema.from_arrow(dec.schema)
    keys = [SortKey(Column("s"), ascending=False), SortKey(Column("p"))]
    cfg = _cfg(mode)
    devp = TpuSortStageExec(_Src(tbl, schema), keys, None, cfg)
    dev = _collect(devp, cfg)
    assert devp.tpu_count == 1 and devp.fallback_count == 0
    cpu = _collect(SortExec(_Src(dec, dec_schema), keys, None), cfg)
    assert (dev.column("s").cast(pa.string()).combine_chunks().to_pylist()
            == cpu.column("s").combine_chunks().to_pylist())
    assert dev.column("p").combine_chunks().equals(
        cpu.column("p").combine_chunks())


def _window_schema(tbl, wexprs, schema):
    return DFSchema.from_arrow(pa.schema(
        list(tbl.schema)
        + [pa.field(f"w{j}", w.data_type(schema))
           for j, w in enumerate(wexprs)]))


@pytest.mark.parametrize("mode", MODES)
def test_window_parity_adversarial(mode):
    """row_number/rank/count/sum/min/max over partition+order with NaN
    order keys, nullable agg args, and peer frames whose order values
    repeat ACROSS partition boundaries (scan resets must isolate
    partitions)."""
    from ballista_tpu.ops.tpu.sort_window import TpuWindowStageExec

    rng = np.random.default_rng(23)
    n = 384
    f = rng.integers(-10, 10, n).astype(np.float64)
    f[::9] = np.nan
    # order values drawn from a tiny domain: every partition contains the
    # same order values, so peer groups abut identically-valued rows in
    # the neighbor partition — any boundary leak shows up in rank/sum
    tbl = pa.table({
        "g": pa.array(rng.integers(0, 8, n), pa.int64()),
        "o": pa.array(rng.integers(0, 3, n), pa.int64()),
        "f": pa.array(f),
        "vnull": pa.array(
            [None if j % 4 == 0 else int(v)
             for j, v in enumerate(rng.integers(-50, 50, n))], pa.int64()),
    })
    schema = DFSchema.from_arrow(tbl.schema)
    over = ([Column("g")], [SortKey(Column("o"))])
    wexprs = [
        WindowFunction("row_number", [], *over, None),
        WindowFunction("rank", [], *over, None),
        WindowFunction("count", [Column("vnull")], *over, None),
        WindowFunction("sum", [Column("vnull")], *over, None),
        WindowFunction("min", [Column("f")], [Column("g")],
                       [SortKey(Column("f"), nulls_first=True)], None),
        WindowFunction("max", [Column("vnull")], [],
                       [SortKey(Column("o"), ascending=False)], None),
    ]
    wschema = _window_schema(tbl, wexprs, schema)
    cfg = _cfg(mode)
    _assert_parity(WindowExec(_Src(tbl, schema), wexprs, wschema),
                   TpuWindowStageExec(_Src(tbl, schema), wexprs, wschema, cfg),
                   cfg)


@pytest.mark.parametrize("mode", ("fused_xla", "fused_pallas"))
def test_window_empty_and_all_null_partitions(mode):
    """Partitions of size one and partitions whose aggregate argument is
    entirely NULL (SQL: aggregate over zero valid rows is NULL)."""
    from ballista_tpu.ops.tpu.sort_window import TpuWindowStageExec

    g = pa.array([0] * 50 + [1] + [2] * 49 + [3], pa.int64())
    v = pa.array([None] * 50                       # partition 0: all null
                 + [7]                             # singleton partition
                 + [int(x) for x in range(49)]     # dense partition
                 + [None],                         # singleton, null arg
                 pa.int64())
    tbl = pa.table({"g": g, "v": v})
    schema = DFSchema.from_arrow(tbl.schema)
    over = ([Column("g")], [SortKey(Column("v"), nulls_first=True)])
    wexprs = [
        WindowFunction("sum", [Column("v")], *over, None),
        WindowFunction("min", [Column("v")], *over, None),
        WindowFunction("count", [Column("v")], *over, None),
        WindowFunction("rank", [], *over, None),
    ]
    wschema = _window_schema(tbl, wexprs, schema)
    cfg = _cfg(mode)
    _assert_parity(WindowExec(_Src(tbl, schema), wexprs, wschema),
                   TpuWindowStageExec(_Src(tbl, schema), wexprs, wschema, cfg),
                   cfg)


def test_zero_row_input():
    from ballista_tpu.ops.tpu.sort_window import (
        TpuSortStageExec,
        TpuWindowStageExec,
    )

    tbl = pa.table({"a": pa.array([], pa.int64())})
    schema = DFSchema.from_arrow(tbl.schema)
    cfg = _cfg("fused_pallas")
    keys = [SortKey(Column("a"))]
    out = _collect(TpuSortStageExec(_Src(tbl, schema), keys, 5, cfg), cfg)
    assert out.num_rows == 0
    wexprs = [WindowFunction("row_number", [], [], [SortKey(Column("a"))], None)]
    wschema = _window_schema(tbl, wexprs, schema)
    out = _collect(TpuWindowStageExec(_Src(tbl, schema), wexprs, wschema, cfg),
                   cfg)
    assert out.num_rows == 0 and out.num_columns == 2


@pytest.mark.parametrize("mode", ("fused_xla", "fused_pallas"))
def test_estimate_covers_device_bytes(mode):
    """Fill test: estimate_sort_stage must price at least the bytes the
    stage actually shipped (RUN_STATS device_bytes) — for a plain sort, a
    top-k, and a window stage."""
    from ballista_tpu.ops.tpu import fusion
    from ballista_tpu.ops.tpu.sort_window import (
        TpuSortStageExec,
        TpuWindowStageExec,
        _encode_key_arrays,
    )
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

    tbl = _adversarial_table()
    n = tbl.num_rows
    schema = DFSchema.from_arrow(tbl.schema)
    cfg = _cfg(mode)
    keys = [SortKey(Column("inull"), nulls_first=True),
            SortKey(Column("f"), ascending=False)]
    batch = tbl.combine_chunks().to_batches()[0]
    arrays = [batch.column("inull"), batch.column("f")]
    _, key_meta = _encode_key_arrays(
        arrays, [(k.ascending, k.nulls_first) for k in keys])

    for fetch in (None, 8):
        devp = TpuSortStageExec(_Src(tbl, schema), keys, fetch, cfg)
        _collect(devp, cfg)
        assert devp.tpu_count == 1
        actual = int(RUN_STATS.snapshot()["device_bytes"])
        est = fusion.estimate_sort_stage(
            n, key_meta, fetch=fetch if len(keys) == 1 else None)
        assert est.table_bytes >= actual > 0, (est.table_bytes, actual)

    wexprs = [
        WindowFunction("sum", [Column("i")], [Column("i")],
                       [SortKey(Column("f"))], None),
        WindowFunction("rank", [], [Column("i")], [SortKey(Column("f"))],
                       None),
    ]
    wschema = _window_schema(tbl, wexprs, schema)
    devp = TpuWindowStageExec(_Src(tbl, schema), wexprs, wschema, cfg)
    _collect(devp, cfg)
    assert devp.tpu_count == 1
    actual = int(RUN_STATS.snapshot()["device_bytes"])
    warrays = [batch.column("i"), batch.column("f")]
    _, wmeta = _encode_key_arrays(warrays, [(True, False), (True, False)])
    west = fusion.estimate_sort_stage(n, wmeta, window_funcs=len(wexprs))
    assert west.table_bytes >= actual > 0, (west.table_bytes, actual)


def test_demotion_reason_recorded():
    """A forced fused_pallas sort over the lane ceiling demotes to
    fused_xla with the cost model's rationale in RUN_STATS."""
    from ballista_tpu.ops.tpu.sort_window import TpuSortStageExec
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

    tbl = pa.table({"a": pa.array(range(600), pa.int64())})
    schema = DFSchema.from_arrow(tbl.schema)
    cfg = _cfg("fused_pallas", **{TPU_SORT_PALLAS_MAX_ROWS: 128})
    devp = TpuSortStageExec(_Src(tbl, schema), [SortKey(Column("a"))], None,
                            cfg)
    _collect(devp, cfg)
    assert devp.tpu_count == 1 and devp.fallback_count == 0
    stats = RUN_STATS.snapshot()
    assert stats["fusion_mode"] == "fused_xla"
    assert "forced fused_pallas but" in stats["fusion_reason"]


def test_counters_flow_to_heartbeat_gauges():
    """RunStats → ExecutorProcess._tpu_metrics: the sort-family gauges are
    exported once the family has run (stats-sync invariant, live)."""
    from ballista_tpu.executor.executor_process import ExecutorProcess
    from ballista_tpu.ops.tpu.sort_window import TpuSortStageExec

    tbl = _adversarial_table()
    schema = DFSchema.from_arrow(tbl.schema)
    cfg = _cfg("fused_pallas")
    devp = TpuSortStageExec(_Src(tbl, schema),
                            [SortKey(Column("i"))], 5, cfg)
    _collect(devp, cfg)
    gauges = dict(ExecutorProcess._tpu_metrics())
    for key in ("tpu_sort_kernel_s", "tpu_topk_invocations",
                "tpu_topk_rows_kept"):
        assert key in gauges, key
    assert gauges["tpu_topk_invocations"] >= 1
    assert gauges["tpu_topk_rows_kept"] >= 5


def test_engine_wiring_and_knob_gate():
    """maybe_compile_tpu wraps SortExec/WindowExec when the family knob is
    on, and leaves the plan untouched when it is off."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import EXECUTOR_ENGINE
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.ops.tpu.sort_window import (
        TpuSortStageExec,
        TpuWindowStageExec,
    )

    from .conftest import iter_plan

    rng = np.random.default_rng(3)
    t = pa.table({
        "g": pa.array(rng.integers(0, 5, 500), pa.int64()),
        "v": pa.array(rng.integers(0, 99, 500), pa.int64()),
    })
    sql = ("SELECT g, v, rank() OVER (PARTITION BY g ORDER BY v) rk "
           "FROM t ORDER BY v DESC, g LIMIT 20")
    for enabled in (True, False):
        cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0,
                              TPU_SORT_ENABLED: enabled})
        ctx = SessionContext(cfg)
        ctx.register_arrow_table("t", t, partitions=2)
        phys = maybe_compile_tpu(
            ctx.create_physical_plan(ctx.sql(sql).plan), cfg)
        nodes = [nd for nd in iter_plan(phys)
                 if isinstance(nd, (TpuSortStageExec, TpuWindowStageExec))]
        if enabled:
            assert nodes, phys.display()
        else:
            assert not nodes, phys.display()


def test_topk_knob_disables_fused_cut():
    """ballista.tpu.topk.enabled=false: LIMIT sorts still run on device but
    through the full sort (sort_full_materializations counts it)."""
    from ballista_tpu.ops.tpu.sort_window import (
        TpuSortStageExec,
        counters_snapshot,
    )

    tbl = _adversarial_table()
    schema = DFSchema.from_arrow(tbl.schema)
    cfg = _cfg("fused_pallas", **{TPU_TOPK_ENABLED: False})
    before = counters_snapshot()["sort_full_materializations"]
    devp = TpuSortStageExec(_Src(tbl, schema), [SortKey(Column("i"))], 5, cfg)
    cpu = SortExec(_Src(tbl, schema), [SortKey(Column("i"))], 5)
    _assert_parity(cpu, devp, cfg)
    after = counters_snapshot()["sort_full_materializations"]
    assert after == before + 1
