"""ICI collective exchange: all_to_all hash routing on the virtual 8-device
CPU mesh (the driver's multi-chip dryrun validates the same path).

Reference parity: exchange semantics of ShuffleWriter(hash K) →
ShuffleReader (ballista/core/src/execution_plans/shuffle_reader.rs:762),
expressed as XLA collectives; the file shuffle remains the escape hatch
when the host-side capacity check says the data does not fit."""

import numpy as np
import pytest

from ballista_tpu.parallel.exchange import (
    ExchangeCapacityExceeded,
    exchange_capacity_fits,
    hash_exchange_all_to_all,
    hash_exchange_table,
    make_mesh,
    partial_then_psum,
    require_exchange_capacity,
    required_exchange_capacity,
)


def _mesh8():
    mesh = make_mesh(8)
    if mesh.devices.size < 8:
        pytest.skip("need 8 virtual devices")
    return mesh


def _expected_routing(keys_np, n):
    from ballista_tpu.ops.hashing import splitmix64

    return splitmix64(keys_np.astype(np.uint64)) % np.uint64(n)


@pytest.mark.multichip
def test_hash_exchange_routes_every_row_once():
    import jax.numpy as jnp

    mesh = _mesh8()
    n = mesh.devices.size
    rows = 64 * n
    rng = np.random.default_rng(3)
    keys_np = rng.integers(0, 10_000, rows).astype(np.int64)
    vals_np = np.arange(rows, dtype=np.int64)

    rk, rv, ro = hash_exchange_all_to_all(
        jnp.asarray(keys_np), jnp.asarray(vals_np), mesh, capacity=rows)
    rk, rv, ro = np.asarray(rk), np.asarray(rv), np.asarray(ro)
    # every input row arrives exactly once, on the device its key hashes to
    got = sorted(rv[ro].tolist())
    assert got == vals_np.tolist()
    dest = _expected_routing(keys_np, n)
    per_dev = rk.reshape(n, -1)
    per_ok = ro.reshape(n, -1)
    for d in range(n):
        want = sorted(keys_np[dest == d].tolist())
        assert sorted(per_dev[d][per_ok[d]].tolist()) == want


@pytest.mark.multichip
def test_hash_exchange_overflow_never_clobbers_valid_rows():
    """Force overflow: surviving rows must be an intact SUBSET of the
    input — an overflow write must never zero a valid slot (the round-2
    data-loss bug: overflow used to share slot cap-1 with real rows)."""
    import jax.numpy as jnp

    mesh = _mesh8()
    n = mesh.devices.size
    rows = 64 * n
    # all keys hash-route somewhere; capacity 8 per (sender, dest) pair is
    # far below the ~64/8 rows per pair on average → guaranteed overflow
    # for at least some pairs with 10k distinct keys
    rng = np.random.default_rng(5)
    keys_np = rng.integers(0, 37, rows).astype(np.int64)  # few keys → skew
    vals_np = np.arange(1, rows + 1, dtype=np.int64)  # all nonzero
    cap = 4

    assert not exchange_capacity_fits(
        [keys_np[i * 64:(i + 1) * 64] for i in range(n)], n, cap)

    rk, rv, ro = hash_exchange_all_to_all(
        jnp.asarray(keys_np), jnp.asarray(vals_np), mesh, capacity=cap)
    rk, rv, ro = np.asarray(rk), np.asarray(rv), np.asarray(ro)
    surv_vals = rv[ro]
    # every surviving value is a real input row (no zeroed/clobbered slots)
    assert len(surv_vals) > 0
    assert set(surv_vals.tolist()) <= set(vals_np.tolist())
    # and its key traveled with it to the right destination
    dest = {v: d for v, d in zip(vals_np, _expected_routing(keys_np, n))}
    key_of = dict(zip(vals_np.tolist(), keys_np.tolist()))
    per = ro.reshape(n, -1)
    vals_per = rv.reshape(n, -1)
    keys_per = rk.reshape(n, -1)
    for d in range(n):
        for v, k in zip(vals_per[d][per[d]].tolist(), keys_per[d][per[d]].tolist()):
            assert key_of[v] == k
            assert dest[v] == d


def test_exchange_capacity_fits_gate():
    n = 8
    rng = np.random.default_rng(7)
    keys = [rng.integers(0, 1 << 40, 256).astype(np.int64) for _ in range(n)]
    # 256 rows over 8 destinations ≈ 32/dest; 96 slots is comfortably enough
    assert exchange_capacity_fits(keys, n, 96)
    assert not exchange_capacity_fits(keys, n, 8)


@pytest.mark.multichip
def test_make_mesh_clamps_device_count():
    mesh = _mesh8()
    n = mesh.devices.size
    # asking for fewer devices than exist clamps the mesh to that many
    assert make_mesh(4).devices.size == 4
    assert make_mesh(1).devices.size == 1
    # asking for more than any backend has is a hard error, not truncation
    with pytest.raises(RuntimeError, match="devices"):
        make_mesh(n * 1000)


def test_require_exchange_capacity_raises_typed():
    # every row routes to ONE destination → required == row count
    keys = [np.zeros(100, dtype=np.int64)]
    assert require_exchange_capacity(keys, 8, 100) == 100
    with pytest.raises(ExchangeCapacityExceeded) as ei:
        require_exchange_capacity(keys, 8, 10)
    assert ei.value.required == 100
    assert ei.value.capacity == 10
    assert ei.value.n_devices == 8
    assert "demote" in str(ei.value)


def test_required_capacity_prehashed_routes_on_raw_hash():
    # prehashed: the values ARE the combined row hashes — no splitmix64 pass
    h = np.full(64, 5, dtype=np.uint64)  # all route to 5 % n
    assert required_exchange_capacity([h], 8, prehashed=True) == 64
    spread = np.arange(64, dtype=np.uint64)  # 8 rows per destination
    assert required_exchange_capacity([spread], 8, prehashed=True) == 8
    assert exchange_capacity_fits([spread], 8, 8, prehashed=True)
    assert not exchange_capacity_fits([spread], 8, 7, prehashed=True)


@pytest.mark.multichip
def test_hash_exchange_table_skewed_round_trip():
    """Multi-lane table exchange under heavy key skew: every live row
    arrives exactly once on the device its PRE-combined hash routes to,
    all lanes travel together, and dead (padding) rows never arrive."""
    mesh = _mesh8()
    n = mesh.devices.size
    rows = 64 * n
    rng = np.random.default_rng(9)
    hot = rng.random(rows) < 0.8  # 80% of rows on one hot key
    hashes = np.where(
        hot, np.uint64(0xDEADBEEF),
        rng.integers(1, 1 << 62, rows).astype(np.uint64),
    )
    lane_a = np.arange(rows, dtype=np.int64)  # row id
    lane_b = rng.integers(-1000, 1000, rows).astype(np.int64)
    live = np.ones(rows, dtype=bool)
    live[-7:] = False  # a padding tail that must never arrive

    shards = [
        hashes[d * 64:(d + 1) * 64][live[d * 64:(d + 1) * 64]] for d in range(n)
    ]
    cap = required_exchange_capacity(shards, n, prehashed=True)
    h_out, (a_out, b_out), ok = hash_exchange_table(
        hashes.view(np.int64), [lane_a, lane_b], live, mesh, capacity=cap)
    h_out = np.asarray(h_out)
    a_out, b_out = np.asarray(a_out), np.asarray(b_out)
    ok = np.asarray(ok)

    # exactly the live rows arrive, each once
    assert sorted(a_out[ok].tolist()) == lane_a[live].tolist()
    # lanes travel together with their hash
    b_of = dict(zip(lane_a.tolist(), lane_b.tolist()))
    h_of = dict(zip(lane_a.tolist(), hashes.tolist()))
    for rid, b, h in zip(a_out[ok].tolist(), b_out[ok].tolist(),
                         h_out[ok].view(np.uint64).tolist()):
        assert b_of[rid] == b
        assert h_of[rid] == h
    # and each lands on the device its hash routes to
    per_rid = a_out.reshape(n, -1)
    per_ok = ok.reshape(n, -1)
    dest = (hashes % np.uint64(n)).astype(np.int64)
    for d in range(n):
        got = sorted(per_rid[d][per_ok[d]].tolist())
        want = sorted(lane_a[live & (dest == d)].tolist())
        assert got == want


@pytest.mark.multichip
def test_partial_then_psum_merges_globally():
    import jax.numpy as jnp

    mesh = _mesh8()
    rows = 128 * mesh.devices.size
    rng = np.random.default_rng(11)
    g = rng.integers(0, 4, rows)
    v = rng.integers(0, 100, rows).astype(np.float32)
    G = 4

    def gmask_fn(vals):
        # group id rides in the value's fractional tag for the test: instead
        # derive masks from value ranges — here simply recompute from a
        # broadcasted device-side copy is impossible, so encode group in
        # the integer part: v = group * 1000 + x
        return jnp.stack([(vals // 1000) == grp for grp in range(G)])

    enc = (g * 1000 + (v % 1000).astype(np.int64)).astype(np.float32)
    sums, cnts = partial_then_psum(jnp.asarray(enc), gmask_fn, G, mesh)
    sums, cnts = np.asarray(sums), np.asarray(cnts)
    for grp in range(G):
        sel = g == grp
        assert cnts[grp] == sel.sum()
        assert np.isclose(sums[grp], enc[sel].sum(), rtol=1e-6)
