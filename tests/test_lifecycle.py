"""Executor lifecycle & storage failure domain (docs/lifecycle.md).

Graceful drain with shuffle handoff (zero upstream-stage reruns,
byte-identical results), hard-kill mid-drain recompute fallback,
disk-pressure watermarks (typed ENOSPC, shed/reject ladder, placement
gating), orphaned-data GC (scheduler TTL sweep + executor startup sweep),
and a rolling restart of a multi-executor fleet under live query load.
"""

import os
import threading
import time
from types import SimpleNamespace

import pytest

from ballista_tpu.config import (
    BallistaConfig,
    CHAOS_ENABLED,
    CHAOS_MODE,
    CHAOS_PROBABILITY,
    CHAOS_SEED,
    DEFAULT_SHUFFLE_PARTITIONS,
    EXECUTOR_DATA_TTL_S,
)
from ballista_tpu.executor.executor import ExecutionEngine, Executor, ExecutorMetadata
from ballista_tpu.executor.standalone import StandaloneCluster
from ballista_tpu.testing.reference import compare_results, run_reference

from .conftest import tpch_query


class SlowEngine(ExecutionEngine):
    """Stretches every task by a few ms so a drain reliably lands while
    the job is mid-flight (upstream outputs committed, consumers pending)."""

    def create_query_stage_exec(self, plan, config, stage_attempt=0):
        time.sleep(0.05)
        return super().create_query_stage_exec(plan, config, stage_attempt)


def _drain_cluster(tpch_dir, cfg, num_executors=2):
    """SessionContext over a per-executor-work-dir standalone fleet: each
    executor owns its work-dir subtree and Flight server, so drain
    migration moves real bytes between data planes."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    ctx = SessionContext.standalone(cfg, num_executors=num_executors)
    ctx._cluster = StandaloneCluster(
        num_executors, 4, config=cfg, per_executor_work_dirs=True,
        engine_factory=SlowEngine)
    register_tpch(ctx, tpch_dir)
    return ctx


def _drain_midflight(ctx, cfg, q, drain_timeout=60.0):
    """Submit query q, wait until some executor holds committed map
    outputs while the job is still running, then drain that executor.
    Returns (job_id, drain_result, final_status)."""
    cluster = ctx._cluster
    sched = cluster.scheduler
    sid = sched.sessions.create_or_update(cfg.to_key_value_pairs(), "s-lifecycle")
    job_id = sched.submit_sql(tpch_query(q), sid)
    victim = None
    deadline = time.time() + 60
    while time.time() < deadline and victim is None:
        for eid in list(cluster.executors):
            if sched._locations_on(eid):
                victim = eid
                break
        else:
            time.sleep(0.01)
    assert victim is not None, "no committed map outputs ever appeared"
    res = sched.drain_executor(victim, timeout_s=drain_timeout)
    status = sched.wait_for_job(job_id, timeout=120)
    return job_id, res, status


def test_drain_migration_zero_reruns(tpch_dir, tpch_ref_tables):
    """Tentpole: draining an executor mid-query hands its shuffle outputs
    off to the survivor — the job completes byte-identical with ZERO
    upstream-stage reruns and nonzero migration counters."""
    from ballista_tpu.client.context import fetch_job_results

    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 4})
    ctx = _drain_cluster(tpch_dir, cfg)
    sched = ctx._cluster.scheduler
    try:
        job_id, res, status = _drain_midflight(ctx, cfg, q=3)
        assert status["state"] == "successful", status.get("error")
        assert res["status"] == "drained", res
        assert res["migrated_partitions"] > 0 and res["migrated_bytes"] > 0, res
        # zero reruns: no stage ever re-attempted (FetchFailed would bump these)
        g = sched.jobs.get(job_id)
        attempts = {sid: s.attempt for sid, s in g.stages.items()}
        assert all(a == 0 for a in attempts.values()), attempts
        # byte parity vs the reference oracle, fetched through the
        # REWRITTEN locations on the surviving data plane
        out = fetch_job_results(status, cfg)
        problems = compare_results(out, run_reference(3, tpch_ref_tables), 3)
        assert not problems, "\n".join(problems)
        # terminal ledger + stats surfaced for /api/state
        drained = sched.executors.drained_snapshot()
        assert res["executor_id"] in drained
        assert drained[res["executor_id"]]["reason"] == "drained"
        assert sched.lifecycle_stats["drains"] == 1
        assert sched.lifecycle_stats["migrated_partitions"] == res["migrated_partitions"]
        # the drained executor left the fleet
        alive = [e.metadata.id for e in sched.executors.alive_executors()]
        assert res["executor_id"] not in alive
    finally:
        ctx.shutdown()


def test_drain_kill_recompute_parity(tpch_dir, tpch_ref_tables, monkeypatch):
    """Hard-kill mid-migration (chaos mode=drain_kill): the unmigrated
    remainder falls back to today's recompute path and the job still
    produces byte-identical results."""
    from ballista_tpu.client.context import fetch_job_results

    monkeypatch.setenv("BALLISTA_CHAOS_DRAIN_KILL_AFTER", "1")
    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 4})
    ctx = _drain_cluster(tpch_dir, cfg)
    sched = ctx._cluster.scheduler
    try:
        job_id, res, status = _drain_midflight(ctx, cfg, q=3)
        assert res["status"] == "drain-killed", res
        assert sched.lifecycle_stats["drain_kills"] == 1
        assert status["state"] == "successful", status.get("error")
        out = fetch_job_results(status, cfg)
        problems = compare_results(out, run_reference(3, tpch_ref_tables), 3)
        assert not problems, "\n".join(problems)
        drained = sched.executors.drained_snapshot()
        assert drained[res["executor_id"]]["reason"] == "drain-killed"
    finally:
        ctx.shutdown()


def test_disk_full_chaos_retry_heals(tpch_dir):
    """Injected ENOSPC at shuffle-write points (chaos mode=disk_full,
    once-mode) fails tasks typed + retryable; the retry of the same slice
    heals and the job converges to the correct result — no job failure.
    p=1.0 + once-mode is DETERMINISTIC: every task's first shuffle write
    ENOSPCs and every retry heals, with the per-stage task count (2)
    safely under the stage retry budget."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.executor import chaos
    from ballista_tpu.testing.tpchgen import register_tpch

    chaos._DISK_FULL_FIRED.clear()
    cfg = BallistaConfig({
        CHAOS_ENABLED: True, CHAOS_MODE: "disk_full",
        CHAOS_PROBABILITY: 1.0, CHAOS_SEED: 11,
        DEFAULT_SHUFFLE_PARTITIONS: 2,
    })
    ctx = SessionContext.standalone(cfg, num_executors=1, vcores=4)
    register_tpch(ctx, tpch_dir)
    # every task fails exactly once by design; don't let the health ledger
    # quarantine the only executor over the injected faults
    ctx._ensure_cluster().scheduler.executors.quarantine_threshold = 2.0
    try:
        out = ctx.sql(
            "select n_name, count(*) as c from nation group by n_name order by n_name"
        ).collect()
        assert len(chaos._DISK_FULL_FIRED) > 0, "no ENOSPC ever injected — test vacuous"
        assert out.num_rows == 25
        assert all(c == 1 for c in out.column("c").to_pylist())
    finally:
        ctx.shutdown()
        chaos._DISK_FULL_FIRED.clear()


def test_watermark_ladder(tmp_path):
    """Shed order: the low watermark stops OPTIONAL spill writes first;
    the high watermark rejects new task admission with a typed retryable
    DiskExhausted; below both, everything is allowed."""
    from ballista_tpu.executor import disk

    cfg = BallistaConfig()
    wd = str(tmp_path)
    try:
        # between the watermarks: spills shed, tasks still admitted
        disk.force_used_fraction(0.92)
        assert not disk.spill_allowed(cfg, wd)
        assert not disk.admission_blocked(cfg, wd)

        # past the high watermark: task admission rejects typed + retryable
        disk.force_used_fraction(0.97)
        assert disk.admission_blocked(cfg, wd)
        ex = Executor(wd, ExecutorMetadata(id="ex-disk", vcores=1), config=cfg)
        task = SimpleNamespace(task_id=1, job_id="job-x", stage_id=1,
                               stage_attempt=0, partitions=[0],
                               session_id="s", fast_lane=False)
        r = ex.run_task(task, cfg)
        assert r.state == "failed"
        assert r.error_kind == "DiskExhausted"
        assert r.retryable
        assert ex.disk_rejections == 1

        # with headroom the whole ladder opens back up
        disk.force_used_fraction(0.5)
        assert disk.spill_allowed(cfg, wd)
        assert not disk.admission_blocked(cfg, wd)
    finally:
        disk.force_used_fraction(None)


def test_disk_rejecting_gates_placement():
    """A heartbeat reporting disk_rejecting=1 takes the executor out of
    the schedulable set (placement steers away from full nodes); the
    pressure clearing restores it."""
    from ballista_tpu.scheduler.state.executor_manager import ExecutorManager

    m = ExecutorManager()
    meta = ExecutorMetadata(id="ex-full", vcores=2)
    m.register(meta)
    assert m.executors["ex-full"].schedulable
    m.heartbeat("ex-full", {"disk_rejecting": 1.0, "disk_used_bytes": 99.0,
                            "disk_free_bytes": 1.0})
    slot = m.executors["ex-full"]
    assert not slot.schedulable
    assert slot.disk_used_bytes == 99.0
    snap = m.health_snapshot()["ex-full"]
    assert snap["disk_rejecting"] is True
    m.heartbeat("ex-full", {"disk_rejecting": 0.0})
    assert m.executors["ex-full"].schedulable


def test_ttl_gc_sweeps_terminal_not_live(tpch_dir):
    """The scheduler TTL sweep removes a terminal job's data once it ages
    past ballista.executor.data.ttl.seconds — and never touches a job
    that is still inside its TTL."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 2, EXECUTOR_DATA_TTL_S: 1})
    ctx = SessionContext.standalone(cfg, num_executors=1)
    register_tpch(ctx, tpch_dir)
    try:
        sql = "select l_returnflag, count(*) from lineitem group by l_returnflag"
        ctx.sql(sql).collect()
        ctx.sql(sql).collect()
        cluster = ctx._cluster
        sched = cluster.scheduler
        with sched._jobs_lock:
            job_old, job_live = sorted(sched.jobs)[:2]
        dir_old = os.path.join(cluster.work_dir, job_old)
        dir_live = os.path.join(cluster.work_dir, job_live)
        assert os.path.isdir(dir_old) and os.path.isdir(dir_live)
        # age one job past its TTL; leave the other fresh
        sched.jobs[job_old].ended_at = time.time() - 30
        sched.jobs[job_live].ended_at = time.time()
        sched._sweep_job_data_ttl(time.time())
        assert sched.lifecycle_stats["gc_swept_jobs"] == 1
        deadline = time.time() + 10
        while time.time() < deadline and os.path.isdir(dir_old):
            time.sleep(0.05)
        assert not os.path.isdir(dir_old), "expired job data not reclaimed"
        assert os.path.isdir(dir_live), "GC touched a job inside its TTL"
        with sched._jobs_lock:
            assert job_old not in sched.jobs
            assert job_live in sched.jobs
    finally:
        ctx.shutdown()


def test_startup_orphan_sweep(tmp_path):
    """sweep_stale_dirs reclaims dirs older than the TTL, keeps fresh
    ones, and is a no-op when the TTL is 0 (disabled)."""
    from ballista_tpu.executor import lifecycle

    old = tmp_path / "job-old"
    old.mkdir()
    (old / "data.arrow").write_bytes(b"x" * 128)
    os.utime(old, (time.time() - 7200, time.time() - 7200))
    fresh = tmp_path / "job-fresh"
    fresh.mkdir()
    (fresh / "data.arrow").write_bytes(b"y" * 64)

    orphans, nbytes = lifecycle.sweep_stale_dirs(str(tmp_path), 3600)
    assert orphans == 1 and nbytes == 128
    assert not old.exists()
    assert fresh.exists()
    # disabled TTL sweeps nothing
    os.utime(fresh, (time.time() - 7200, time.time() - 7200))
    assert lifecycle.sweep_stale_dirs(str(tmp_path), 0) == (0, 0)
    assert fresh.exists()


def test_rolling_restart_under_load(tpch_dir, tpch_ref_tables):
    """Rolling restart: drain each of a 3-executor fleet's original nodes
    one at a time (adding a replacement after each) while queries run —
    every query must keep succeeding with byte-identical results."""
    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 4})
    ctx = _drain_cluster(tpch_dir, cfg, num_executors=3)
    cluster = ctx._cluster
    sched = cluster.scheduler
    originals = list(cluster.executors)
    results, errors = [], []
    stop = threading.Event()

    def load():
        while not stop.is_set():
            try:
                results.append(ctx.sql(tpch_query(6)).collect())
            except Exception as e:  # noqa: BLE001 — surfaced as a test failure
                errors.append(e)
                return

    t = threading.Thread(target=load, daemon=True, name="query-load")
    t.start()
    try:
        for eid in originals:
            # drain only once this node actually holds shuffle outputs, so
            # every handoff in the rolling restart moves real data
            deadline = time.time() + 30
            while time.time() < deadline and not sched._locations_on(eid):
                time.sleep(0.01)
            res = sched.drain_executor(eid, timeout_s=60)
            assert res["status"] == "drained", res
            cluster.add_executor(vcores=4, config=cfg, engine_factory=SlowEngine)
        assert sched.lifecycle_stats["migrated_partitions"] > 0
        stop.set()
        t.join(timeout=120)
        assert not errors, errors
        assert results, "load thread never completed a query"
        ref = run_reference(6, tpch_ref_tables)
        for out in results:
            problems = compare_results(out, ref, 6)
            assert not problems, "\n".join(problems)
        assert len(sched.executors.alive_executors()) == 3
        assert len(sched.executors.drained_snapshot()) == 3
        assert sched.lifecycle_stats["drains"] == 3
    finally:
        stop.set()
        ctx.shutdown()
