"""Scheduler scale-out: sharded job ownership, multi-scheduler failover,
and lease-based direct dispatch.

Covers the scale-out acceptance bars end to end: job→shard routing is
deterministic and survives resharding (jobs checkpointed under N=2
complete under N=4), a chaos-killed scheduler instance loses no jobs and
its successor re-executes no completed stage, direct dispatch is
byte-identical to the full graph path and demotes cleanly on revocation
or expiry, KEDA GetMetrics reports exactly the scheduler's own admission
/ shard / lease counters, and the job-status proxy coalesces a polling
herd into single-flight computations.
"""

import hashlib
import tempfile
import threading
import time

import pytest

from ballista_tpu.config import DEFAULT_SHUFFLE_PARTITIONS, BallistaConfig
from ballista_tpu.scheduler.shard import shard_of

FILTER_SQL = ("SELECT l_orderkey, l_partkey, l_quantity FROM lineitem "
              "WHERE l_quantity < 10")
GROUP_SQL = ("SELECT l_returnflag, COUNT(*) AS c, SUM(l_quantity) AS q "
             "FROM lineitem GROUP BY l_returnflag")


def _fingerprint(tbl) -> bytes:
    cols = sorted(tbl.column_names)
    rows = sorted(zip(*(tbl.column(c).to_pylist() for c in cols)))
    return hashlib.sha256(repr((cols, rows)).encode()).digest()


def _session_cfg(tpch_dir):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 2})
    ctx = SessionContext(cfg)
    register_tpch(ctx, tpch_dir)
    return cfg


# ---------------------------------------------------------------------------
# shard routing
# ---------------------------------------------------------------------------


def test_shard_of_deterministic_and_spread():
    ids = [f"job-{i:04d}" for i in range(256)]
    for n in (1, 2, 4, 8):
        owners = [shard_of(j, n) for j in ids]
        # stable across calls (CRC32, not salted hash)
        assert owners == [shard_of(j, n) for j in ids]
        assert all(0 <= o < n for o in owners)
        if n > 1:
            # 256 ids over <=8 shards: every shard must see work
            assert len(set(owners)) == n
    assert shard_of("anything", 1) == 0


def test_resharding_ownership_stability(tpch_dir):
    """Jobs planned + checkpointed under a 2-shard scheduler complete
    under a fresh 4-shard scheduler on the same state dir: routing is a
    pure function of (job_id, N), so changing N only remaps owners —
    it never strands a job."""
    from ballista_tpu.executor.standalone import InProcessTaskLauncher, StandaloneCluster
    from ballista_tpu.scheduler.server import SchedulerServer
    from ballista_tpu.scheduler.state.job_state import FileJobState

    cfg = _session_cfg(tpch_dir)
    state_dir = tempfile.mkdtemp(prefix="bt-reshard-")

    # phase 1: N=2 shards, ZERO executors — jobs plan and checkpoint but
    # cannot run, modeling a scheduler that died before dispatch
    s1 = SchedulerServer(InProcessTaskLauncher({}), scheduler_id="resh-a",
                         job_state=FileJobState(state_dir), shards=2)
    s1.start()
    try:
        sid = s1.sessions.create_or_update(cfg.to_key_value_pairs(), "s-reshard")
        jobs = [s1.submit_sql(GROUP_SQL, sid) for _ in range(8)]
        store = FileJobState(state_dir)
        deadline = time.time() + 30
        while time.time() < deadline and set(store.list_jobs()) < set(jobs):
            time.sleep(0.05)
        assert set(store.list_jobs()) >= set(jobs)
    finally:
        s1.stop()

    # phase 2: N=4 shards over a real fleet adopts and finishes them
    cluster = StandaloneCluster(num_executors=2, vcores=4, config=cfg,
                                with_flight=False, shards=4,
                                job_state=FileJobState(state_dir))
    try:
        recovered = cluster.scheduler.recover_jobs(force=True)
        assert set(recovered) >= set(jobs)
        fps = set()
        owners = set()
        for jid in jobs:
            st = cluster.scheduler.wait_for_job(jid, timeout=120)
            assert st["state"] == "successful", st
            from ballista_tpu.client.context import fetch_job_results

            fps.add(_fingerprint(fetch_job_results(st, cfg)))
            sh = cluster.scheduler._shard_for(jid)
            assert sh.shard_id == shard_of(jid, 4)
            owners.add(sh.shard_id)
        # identical query → identical bytes from every shard's jobs
        assert len(fps) == 1
        # 8 random job ids over 4 shards: ownership actually spread
        assert len(owners) >= 2
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# multi-scheduler failover
# ---------------------------------------------------------------------------


def test_scheduler_killed_mid_job_no_double_execution(tpch_dir):
    """Chaos-kill the owning scheduler after stage 1 checkpoints but
    before stage 2 runs: a live peer's orphan sweep adopts the job from
    the shared store and finishes it WITHOUT re-executing the completed
    stage (resume from materialized shuffle outputs)."""
    from ballista_tpu.client.context import fetch_job_results
    from ballista_tpu.executor.standalone import MultiSchedulerCluster
    from ballista_tpu.scheduler.state.execution_graph import StageState
    from ballista_tpu.scheduler.state.job_state import FileJobState

    cfg = _session_cfg(tpch_dir)
    cluster = MultiSchedulerCluster(num_schedulers=2, num_executors=2,
                                    vcores=4, config=cfg, lease_s=2.0,
                                    sweep_interval_s=0.5)
    gate = threading.Event()
    launches: dict[tuple, int] = {}
    lock = threading.Lock()

    # instrument the SHARED executors: count every task execution and hold
    # stage>=2 tasks at the gate so the kill lands between the stage-1
    # checkpoint and the final stage
    for ex in cluster.executors.values():
        orig = ex.run_task

        def run_task(task, cfg=None, _orig=orig):
            with lock:
                key = (task.job_id, task.stage_id, task.task_id)
                launches[key] = launches.get(key, 0) + 1
            if task.stage_id >= 2:
                gate.wait(timeout=30)
            return _orig(task, cfg)

        ex.run_task = run_task

    try:
        owner = cluster.schedulers[0]
        survivor = cluster.schedulers[1]
        sid = owner.sessions.create_or_update(cfg.to_key_value_pairs(), "s-chaos")
        jid = owner.submit_sql(GROUP_SQL, sid)

        # wait until the PERSISTED graph shows a finished stage — the
        # durable resume point a successor recovers from
        store = FileJobState(cluster.state_dir)
        deadline = time.time() + 30
        checkpointed = False
        while time.time() < deadline:
            g = store.load_graph(jid)
            if g is not None and any(
                    st.state is StageState.SUCCESSFUL for st in g.stages.values()):
                checkpointed = True
                break
            time.sleep(0.05)
        assert checkpointed, "stage-1 checkpoint never landed"

        cluster.kill(0)
        gate.set()

        # the survivor's sweep adopts once the dead owner's lease goes stale
        deadline = time.time() + 30
        st = None
        while time.time() < deadline:
            st = survivor.job_status(jid)
            if st is not None and st["state"] in ("successful", "failed", "cancelled"):
                break
            time.sleep(0.1)
        assert st is not None and st["state"] == "successful", st

        # no double execution of the checkpointed stage: every stage-1 task
        # ran exactly once across BOTH schedulers
        with lock:
            stage1 = {k: n for k, n in launches.items()
                      if k[0] == jid and k[1] == 1}
        assert stage1 and all(n == 1 for n in stage1.values()), stage1

        # the adopted job's bytes match a fresh run of the same query
        adopted_fp = _fingerprint(fetch_job_results(st, cfg))
        jid2 = survivor.submit_sql(GROUP_SQL, sid)
        st2 = survivor.wait_for_job(jid2, timeout=120)
        assert st2["state"] == "successful", st2
        assert adopted_fp == _fingerprint(fetch_job_results(st2, cfg))
    finally:
        gate.set()
        cluster.shutdown()


# ---------------------------------------------------------------------------
# direct dispatch
# ---------------------------------------------------------------------------


@pytest.fixture
def direct_cluster(tpch_dir):
    from ballista_tpu.executor.standalone import StandaloneCluster

    cfg = _session_cfg(tpch_dir)
    cluster = StandaloneCluster(num_executors=2, vcores=4, config=cfg,
                                with_flight=False)
    sid = cluster.scheduler.sessions.create_or_update(
        cfg.to_key_value_pairs(), "s-direct")
    try:
        yield cluster, cfg, sid
    finally:
        cluster.shutdown()


def _dispatcher(cluster, sid, **kw):
    from ballista_tpu.client.direct import DirectDispatcher, LocalLeaseTransport

    d = DirectDispatcher(cluster.scheduler,
                         LocalLeaseTransport(cluster.executors), sid, **kw)
    # prepare takes concrete SQL; literal lifting parameterizes it
    d.prepare(FILTER_SQL)
    return d


def test_direct_dispatch_byte_parity(direct_cluster):
    from ballista_tpu.client.context import fetch_job_results

    cluster, cfg, sid = direct_cluster
    scheduler = cluster.scheduler
    d = _dispatcher(cluster, sid)
    for k in (3, 10, 24):
        st_direct = d.execute((k,))
        assert st_direct.get("direct_dispatch") is True
        jid = scheduler.execute_prepared(d.statement_id, (k,), session_id=sid)
        st_sched = scheduler.wait_for_job(jid, timeout=120)
        assert st_sched["state"] == "successful", st_sched
        assert (_fingerprint(fetch_job_results(st_direct, cfg))
                == _fingerprint(fetch_job_results(st_sched, cfg)))
    assert d.stats["demoted"] == 0 and d.stats["direct"] == 3
    snap = scheduler.leases.snapshot()
    assert snap["direct_jobs_reconciled"] == 3
    assert snap["direct_tasks_reconciled"] == d.stats["tasks"]


def test_lease_revocation_demotes_cleanly(direct_cluster):
    from ballista_tpu.client.context import fetch_job_results

    cluster, cfg, sid = direct_cluster
    scheduler = cluster.scheduler
    d = _dispatcher(cluster, sid)
    st = d.execute((10,))
    assert st.get("direct_dispatch") is True
    baseline = _fingerprint(fetch_job_results(st, cfg))

    lease = d._lease
    assert scheduler.revoke_executor_lease(lease.lease_id)
    # executor-side tables reject a revoked lease even if the client's
    # copy looks fresh (the push is off-thread; poll for it)
    deadline = time.time() + 5
    while time.time() < deadline:
        ex = cluster.executors[lease.executor_id]
        if ex.lease_table.admit(lease.lease_id, lease.band_start + 9000) is not None:
            break
        ex.lease_table.release(lease.lease_id)
        time.sleep(0.05)

    # client still holds the stale token: its next dispatch demotes to the
    # graph path, then a FRESH lease restores direct service
    d._lease = lease.clone()
    d._lease.revoked = False  # registry revoke mutated the shared original
    d._lease.expires_at = time.time() + 60  # client copy looks valid
    st2 = d.execute((10,))
    assert "direct_dispatch" not in st2 or not st2.get("direct_dispatch")
    assert _fingerprint(fetch_job_results(st2, cfg)) == baseline
    assert d.stats["demoted"] == 1

    st3 = d.execute((10,))
    assert st3.get("direct_dispatch") is True
    assert _fingerprint(fetch_job_results(st3, cfg)) == baseline
    assert scheduler.leases.snapshot()["direct_jobs_demoted"] >= 1


def test_lease_expiry_demotes_cleanly(direct_cluster):
    from ballista_tpu.client.context import fetch_job_results

    cluster, cfg, sid = direct_cluster
    d = _dispatcher(cluster, sid, ttl_s=0.2)
    st = d.execute((10,))
    assert st.get("direct_dispatch") is True
    baseline = _fingerprint(fetch_job_results(st, cfg))
    time.sleep(0.4)
    # pin a DETACHED client copy past expiry so only the EXECUTOR's check
    # fires (the registry sweep may have marked the shared original): the
    # token is expired at the lease table, the dispatch is rejected, and
    # the dispatcher demotes with identical bytes
    d._lease = d._lease.clone()
    d._lease.revoked = False
    d._lease.expires_at = time.time() + 60
    st2 = d.execute((10,))
    assert not st2.get("direct_dispatch")
    assert _fingerprint(fetch_job_results(st2, cfg)) == baseline
    assert d.stats["demoted"] == 1


def test_mint_denied_without_headroom(tpch_dir):
    from ballista_tpu.executor.standalone import StandaloneCluster

    cfg = _session_cfg(tpch_dir)
    cluster = StandaloneCluster(num_executors=1, vcores=2, config=cfg,
                                with_flight=False)
    try:
        sid = cluster.scheduler.sessions.create_or_update(
            cfg.to_key_value_pairs(), "s-deny")
        a = cluster.scheduler.mint_executor_lease(sid, slots=2)
        assert a is not None
        # every slot leased out: the next mint is denied, not oversubscribed
        b = cluster.scheduler.mint_executor_lease(sid, slots=1)
        assert b is None
        assert cluster.scheduler.leases.snapshot()["denied"] == 1
        # revocation returns the slots; minting works again
        assert cluster.scheduler.revoke_executor_lease(a.lease_id)
        c = cluster.scheduler.mint_executor_lease(sid, slots=2)
        assert c is not None
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# lease-band invariants (analysis rule)
# ---------------------------------------------------------------------------


def test_verify_lease_bands_rule():
    from ballista_tpu.analysis.plan_check import (
        PlanVerificationError,
        check_lease_bands,
        verify_lease_bands,
    )
    from ballista_tpu.serving.lease import (
        DIRECT_TASK_ID_BASE,
        ExecutorLease,
        LeaseRegistry,
    )

    def mk(lease_id, start, size, cursor=0):
        return ExecutorLease(
            lease_id=lease_id, executor_id="e1", host="", flight_port=0,
            session_id="s", slots=1, expires_at=time.time() + 60,
            band_start=start, band_size=size, next_offset=cursor)

    base = DIRECT_TASK_ID_BASE
    good = [mk("a", base, 100), mk("b", base + 100, 100, cursor=50)]
    assert verify_lease_bands(good) == []

    overlap = verify_lease_bands([mk("a", base, 100), mk("b", base + 50, 100)])
    assert any(v.code == "lease-band" for v in overlap)
    below = verify_lease_bands([mk("a", base - 10, 100)])
    assert any(v.code == "lease-band" for v in below)
    runaway = verify_lease_bands([mk("a", base, 100, cursor=101)])
    assert any(v.code == "lease-band" for v in runaway)
    with pytest.raises(PlanVerificationError):
        check_lease_bands([mk("a", base, 0)])

    # the registry mints disjoint bands by construction
    reg = LeaseRegistry()
    minted = [reg.mint(executor_id="e1", host="", flight_port=0,
                       session_id="s", slots=1, ttl_s=60) for _ in range(5)]
    assert verify_lease_bands(minted) == []


# ---------------------------------------------------------------------------
# KEDA external scaler
# ---------------------------------------------------------------------------


def test_keda_metrics_match_scheduler_counters(tpch_dir):
    from ballista_tpu.executor.standalone import StandaloneCluster
    from ballista_tpu.proto import keda_pb2 as kpb
    from ballista_tpu.scheduler import external_scaler as xs

    cfg = _session_cfg(tpch_dir)
    cluster = StandaloneCluster(num_executors=1, vcores=8, config=cfg,
                                with_flight=False, shards=2)
    try:
        scheduler = cluster.scheduler
        sid = scheduler.sessions.create_or_update(
            cfg.to_key_value_pairs(), "s-keda")
        # settle into a known state: one finished job, two live leases
        jid = scheduler.submit_sql(FILTER_SQL, sid)
        assert scheduler.wait_for_job(jid, timeout=120)["state"] == "successful"
        leases = [scheduler.mint_executor_lease(sid) for _ in range(2)]
        assert all(leases)

        svc = xs.ExternalScalerService(scheduler)
        got = {m.metricName: m.metricValue
               for m in svc.GetMetrics(kpb.GetMetricsRequest(), None).metricValues}

        lanes = scheduler.admission.snapshot().get("lanes", {})
        assert got[xs.ACTIVE_LEASES] == scheduler.leases.active_count() == 2
        assert got[xs.INTERACTIVE_INFLIGHT] == int(
            lanes.get("interactive", {}).get("inflight", 0))
        assert got[xs.BATCH_INFLIGHT] == int(
            lanes.get("batch", {}).get("inflight", 0))
        assert got[xs.LANE_SHED_TOTAL] == sum(
            int(l.get("shed_total", 0)) for l in lanes.values())
        assert got[xs.SHARD_QUEUE_DEPTH] == max(
            s["queue_depth"] for s in scheduler.shards_snapshot())
        assert got[xs.PENDING_JOBS] == 0 and got[xs.RUNNING_JOBS] == 0

        spec = {m.metricName for m in
                svc.GetMetricSpec(kpb.ScaledObjectRef(), None).metricSpecs}
        assert xs.SHARD_QUEUE_DEPTH in spec and xs.PENDING_JOBS in spec
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# poll coalescing (thundering-herd fix)
# ---------------------------------------------------------------------------


def test_poll_coalescer_single_flight():
    from ballista_tpu.scheduler.grpc_service import _PollCoalescer

    c = _PollCoalescer()
    computed = []
    start = threading.Barrier(9)
    results = []
    rlock = threading.Lock()

    def compute():
        computed.append(1)
        time.sleep(0.2)  # hold the herd in flight
        return {"state": "running"}

    def poll():
        start.wait()
        r = c.get("job-x", compute)
        with rlock:
            results.append(r)

    threads = [threading.Thread(target=poll) for _ in range(9)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(results) == 9
    assert all(r == {"state": "running"} for r in results)
    # one leader computed; everyone else piggybacked
    assert len(computed) == 1
    assert c.computed == 1 and c.coalesced == 8

    # distinct jobs never share a flight
    assert c.get("job-y", lambda: "y") == "y"
    assert c.computed == 2


def test_poll_coalescer_leader_failure_degrades():
    from ballista_tpu.scheduler.grpc_service import _PollCoalescer

    c = _PollCoalescer()
    with pytest.raises(RuntimeError):
        c.get("j", lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    # the flight is cleaned up; the next poll computes fresh
    assert c.get("j", lambda: "ok") == "ok"
