"""Overload protection: bounded admission with retry_after hints, the
normal → shedding → draining state machine, executor-side pressure
rejection (and the scheduler retrying onto a healthy executor), the
Flight data plane's stream gate + circuit breaker, and the client's
jittered backoff honoring the scheduler's hint.
"""

import json
import threading
import time
from types import SimpleNamespace

import grpc
import pyarrow as pa
import pyarrow.flight as flight
import pytest

from ballista_tpu.config import (
    CLIENT_BACKOFF_BASE_MS,
    CLIENT_BACKOFF_MAX_MS,
    CLIENT_SUBMIT_RETRIES,
    DEFAULT_SHUFFLE_PARTITIONS,
    MAX_PARTITIONS_PER_TASK,
    BallistaConfig,
)
from ballista_tpu.errors import CircuitOpen, ClusterOverloaded, IoError
from ballista_tpu.executor.chaos import ChaosExec
from ballista_tpu.executor.executor import Executor, ExecutorMetadata
from ballista_tpu.executor.memory_pool import MemoryPool, SessionPoolRegistry
from ballista_tpu.executor.standalone import InProcessTaskLauncher, StandaloneCluster
from ballista_tpu.flight.client import CircuitBreaker
from ballista_tpu.flight.server import BallistaFlightServer, _StreamGate
from ballista_tpu.ids import new_executor_id
from ballista_tpu.plan.physical import ExecutionPlan, TaskContext
from ballista_tpu.plan.schema import DFField, DFSchema
from ballista_tpu.scheduler.admission import DRAINING, NORMAL, SHEDDING, AdmissionController
from ballista_tpu.scheduler.metrics import InMemoryMetricsCollector
from ballista_tpu.scheduler.server import SchedulerServer
from ballista_tpu.scheduler.state.execution_graph import TaskDescription

from .conftest import tpch_query

SCHEMA = DFSchema([DFField("x", pa.int64(), False)])


class OneBatchSource(ExecutionPlan):
    def __init__(self, partitions: int = 2):
        super().__init__(SCHEMA)
        self.partitions = partitions

    def output_partition_count(self):
        return self.partitions

    def execute(self, partition, ctx):
        yield pa.RecordBatch.from_pydict({"x": [partition * 10 + i for i in range(5)]},
                                         schema=SCHEMA.to_arrow())


# ---------------------------------------------------------------------------
# admission controller


def _ctl(**kw) -> AdmissionController:
    defaults = dict(enabled=True, max_pending=8, per_session_quota=4,
                    shed_depth=4, drain_depth=6, shed_loop_lag_s=2.0,
                    shed_memory_pressure=0.9, min_retry_after_ms=10)
    defaults.update(kw)
    return AdmissionController(**defaults)


class TestAdmission:
    def test_per_session_quota_rejects_with_retry_after(self):
        ctl = _ctl(per_session_quota=2)
        ctl.admit("s1", "j1")
        ctl.admit("s1", "j2")
        with pytest.raises(ClusterOverloaded) as ei:
            ctl.admit("s1", "j3")
        assert ei.value.reason == "quota"
        assert ei.value.retryable
        assert ei.value.retry_after_ms >= 10
        # the quota is per session, not cluster-wide
        ctl.admit("s2", "j3")

    def test_cluster_depth_cap(self):
        ctl = _ctl(max_pending=3, per_session_quota=10, shed_depth=10, drain_depth=10)
        for i in range(3):
            ctl.admit(f"s{i}", f"j{i}")
        with pytest.raises(ClusterOverloaded) as ei:
            ctl.admit("s9", "j9")
        assert ei.value.reason == "depth"
        assert ctl.depth() == 3

    def test_finish_releases_slot_and_is_idempotent(self):
        ctl = _ctl(per_session_quota=1)
        ctl.admit("s1", "j1")
        with pytest.raises(ClusterOverloaded):
            ctl.admit("s1", "j2")
        ctl.finish("j1")
        ctl.finish("j1")  # duplicate terminal event — must not underflow
        ctl.admit("s1", "j2")
        assert ctl.depth() == 1

    def test_rejection_records_no_state(self):
        ctl = _ctl(max_pending=1)
        ctl.admit("s1", "j1")
        with pytest.raises(ClusterOverloaded):
            ctl.admit("s2", "j2")
        assert ctl.depth() == 1
        assert ctl.snapshot()["rejected_total"] == 1

    def test_disabled_gate_admits_everything_but_still_tracks(self):
        ctl = _ctl(enabled=False, max_pending=1, per_session_quota=1)
        for i in range(5):
            ctl.admit("s1", f"j{i}")
        assert ctl.depth() == 5

    def test_retry_after_tracks_drain_rate(self):
        ctl = _ctl(min_retry_after_ms=1)
        # synthesize a drain history: ~20 finishes over the last 2 seconds
        now = time.monotonic()
        for i in range(20):
            ctl._finishes.append(now - 2.0 + i * 0.1)
        # ~10 jobs/s → 1 job over budget clears in ~100ms
        hint = ctl.retry_after_ms(excess=1)
        assert 30 <= hint <= 300, hint
        # 10x the excess → 10x the hint (linear in the backlog joined)
        assert ctl.retry_after_ms(excess=10) >= 5 * hint

    def test_retry_after_fallback_without_history(self):
        assert _ctl(min_retry_after_ms=100).retry_after_ms() == 1000


class TestOverloadStateMachine:
    def test_depth_drives_shed_then_drain_then_recovery(self):
        ctl = _ctl(max_pending=100, per_session_quota=100, shed_depth=4, drain_depth=6)
        for i in range(4):
            ctl.admit("s1", f"j{i}")
        assert ctl.update(0.0, 0.0) == SHEDDING
        for i in range(4, 6):
            ctl.admit("s2", f"j{i}")
        assert ctl.update(0.0, 0.0) == DRAINING
        with pytest.raises(ClusterOverloaded) as ei:
            ctl.admit("s3", "late")
        assert ei.value.reason == "draining"
        # draining steps DOWN through shedding, never jumps to normal
        ctl.finish("j5")
        assert ctl.update(0.0, 0.0) == SHEDDING
        # hysteresis: still shedding until depth <= shed_depth // 2
        for j in ("j2", "j3", "j4"):
            ctl.finish(j)
        assert ctl.state == SHEDDING
        assert ctl.update(0.0, 0.0) == NORMAL  # depth 2 == 4 // 2

    def test_shedding_halves_the_session_quota(self):
        ctl = _ctl(per_session_quota=4, shed_depth=2, drain_depth=50, max_pending=50)
        ctl.admit("s1", "j1")
        ctl.admit("s1", "j2")
        assert ctl.update(0.0, 0.0) == SHEDDING
        with pytest.raises(ClusterOverloaded) as ei:
            ctl.admit("s1", "j3")  # 2 in flight >= halved quota of 2
        assert ei.value.reason == "shedding"
        # a fresh tenant still gets its (halved) share — degradation, not an outage
        ctl.admit("s2", "j3")

    def test_loop_lag_and_memory_pressure_also_shed(self):
        ctl = _ctl(shed_loop_lag_s=1.0, shed_memory_pressure=0.8)
        assert ctl.update(1.5, 0.0) == SHEDDING
        assert ctl.update(0.0, 0.0) == NORMAL  # depth 0, signals recovered
        assert ctl.update(0.0, 0.9) == SHEDDING
        assert ctl.update(0.0, 0.5) == NORMAL

    def test_no_transition_returns_none(self):
        ctl = _ctl()
        assert ctl.update(0.0, 0.0) is None
        assert ctl.state == NORMAL


# ---------------------------------------------------------------------------
# scheduler integration: the gate in front of submit paths


class TestSchedulerAdmission:
    def _scheduler(self, **admission_kw):
        metrics = InMemoryMetricsCollector()
        s = SchedulerServer(None, metrics, admission=_ctl(**admission_kw))
        sid = s.sessions.create_or_update(BallistaConfig().to_key_value_pairs(), "s-adm")
        return s, metrics, sid

    def test_shed_submission_creates_no_job_state(self):
        # unstarted scheduler: admitted jobs stay in flight forever, so the
        # quota math is deterministic
        s, metrics, sid = self._scheduler(per_session_quota=2, max_pending=10)
        j1 = s.submit_sql("SELECT 1", sid)
        j2 = s.submit_sql("SELECT 1", sid)
        with pytest.raises(ClusterOverloaded) as ei:
            s.submit_sql("SELECT 1", sid)
        assert ei.value.reason == "quota"
        assert set(s.jobs) == {j1, j2}, "shed submission must not create a job"
        assert metrics.jobs_rejected == {"quota": 1}
        assert s.admission.snapshot()["inflight_jobs"] == 2

    def test_terminal_notify_releases_the_slot(self):
        s, _, sid = self._scheduler(per_session_quota=1, max_pending=10)
        j1 = s.submit_sql("SELECT 1", sid)
        with pytest.raises(ClusterOverloaded):
            s.submit_sql("SELECT 1", sid)
        s._notify(j1)  # fires on every terminal transition
        s.submit_sql("SELECT 1", sid)

    def test_heartbeat_pressure_feeds_the_state_machine(self):
        s, metrics, sid = self._scheduler(shed_memory_pressure=0.8)
        for eid in ("A", "B"):
            s.executors.register(ExecutorMetadata(id=eid))
        s.executor_heartbeat("A", {"memory_pressure": 1.0})
        s.executor_heartbeat("B", {"memory_pressure": 0.9})
        assert s.executors.aggregate_pressure() == pytest.approx(0.95)
        assert s.admission.update(0.0, s.executors.aggregate_pressure()) == SHEDDING
        # pressure_rejections arrives as a GAUGE; the scheduler counts growth
        s.executor_heartbeat("A", {"pressure_rejections": 3.0})
        s.executor_heartbeat("A", {"pressure_rejections": 5.0})
        s.executor_heartbeat("A", {"pressure_rejections": 5.0})
        assert metrics.pressure_rejections == 5
        snap = s.executors.health_snapshot()["A"]
        assert snap["pressure_rejections"] == 5


def test_admitted_jobs_complete_under_small_quota_e2e(tpch_dir):
    """Real cluster, tiny admission budget: everything the gate admits
    completes, the slots release on completion, and a post-drain
    submission is admitted again (no leaked slots)."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 2})
    ctx = SessionContext(cfg)
    register_tpch(ctx, tpch_dir)
    cluster = StandaloneCluster(num_executors=2, vcores=2, config=cfg)
    cluster.scheduler.admission = _ctl(per_session_quota=2, max_pending=2)
    try:
        scheduler = cluster.scheduler
        sid = scheduler.sessions.create_or_update(cfg.to_key_value_pairs(), "s-e2e")
        jobs = [scheduler.submit_sql(tpch_query(6), sid) for _ in range(2)]
        for j in jobs:
            status = scheduler.wait_for_job(j, timeout=60)
            assert status["state"] == "successful", status.get("error")
        deadline = time.time() + 5
        while scheduler.admission.depth() > 0 and time.time() < deadline:
            time.sleep(0.05)
        assert scheduler.admission.depth() == 0, "slots must release on completion"
        # drained: a new submission is admitted without any manual reset
        j3 = scheduler.submit_sql(tpch_query(6), sid)
        assert scheduler.wait_for_job(j3, timeout=60)["state"] == "successful"
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# executor-side pressure gate


class TestExecutorPressureGate:
    def _task(self, session_id="sess") -> TaskDescription:
        return TaskDescription(job_id="job-p", stage_id=1, stage_attempt=0,
                               task_id=7, partitions=[0], plan=None,
                               session_id=session_id)

    def test_saturated_pool_rejects_retryably(self, tmp_path):
        ex = Executor(str(tmp_path), ExecutorMetadata(id="ex-p"))
        ex.session_pools = SessionPoolRegistry(capacity_per_session=100)
        ex.session_pools.get("sess").grow_wait(100, timeout_s=0.0)
        result = ex.run_task(self._task())
        assert result.state == "failed"
        assert result.retryable
        assert result.error_kind == "ResourceExhausted"
        assert "saturated" in result.error
        assert ex.pressure_rejections == 1

    def test_headroom_admits(self, tmp_path):
        ex = Executor(str(tmp_path), ExecutorMetadata(id="ex-h"))
        ex.session_pools = SessionPoolRegistry(capacity_per_session=100)
        ex.session_pools.get("sess").grow_wait(50, timeout_s=5.0)
        assert ex._reject_if_saturated(self._task()) is None
        assert ex.pressure_rejections == 0

    def test_no_pools_means_no_gate(self, tmp_path):
        ex = Executor(str(tmp_path), ExecutorMetadata(id="ex-n"))
        assert ex._reject_if_saturated(self._task()) is None

    def test_pool_pressure_and_overcommit_observability(self):
        reg = SessionPoolRegistry(capacity_per_session=100)
        reg.get("a").grow_wait(150, timeout_s=0.0)  # forced through: overcommit
        reg.get("b").grow_wait(20, timeout_s=1.0)
        assert reg.aggregate_pressure() == pytest.approx(1.5)  # max, not mean
        assert reg.total_overcommitted() == 150
        assert reg.get("a").saturated
        assert not reg.get("b").saturated


def test_chaos_overload_mode_saturates_the_pool():
    chaos = ChaosExec(OneBatchSource(1), seed=1, probability=1.0, mode="overload",
                      straggler_delay_s=0.05)
    pool = MemoryPool(100)
    ctx = TaskContext()
    ctx.memory_pool = pool
    gen = chaos.execute(0, ctx)
    next(gen)  # first batch out: the chaos reservation is live
    assert pool.saturated
    assert pool.pressure() >= 1.0
    list(gen)  # drain → finally releases
    assert pool.reserved == 0
    assert pool.overcommitted >= 100, "forced reservation must be counted"


def test_pressure_rejection_retries_to_healthy_executor_e2e(tpch_dir):
    """One executor's session pool is saturated before the job starts; its
    tasks bounce off the admission gate retryably and the scheduler lands
    the retries on the healthy executor — the job still succeeds."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 2, MAX_PARTITIONS_PER_TASK: 1})
    ctx = SessionContext(cfg)
    register_tpch(ctx, tpch_dir)
    import tempfile

    wd = tempfile.mkdtemp(prefix="bt-pressure-")
    # extra vcores bias the first offers onto the saturated executor
    choked = Executor(wd, ExecutorMetadata(id=str(new_executor_id()), vcores=4), config=cfg)
    healthy = Executor(wd, ExecutorMetadata(id=str(new_executor_id()), vcores=2), config=cfg)
    launcher = InProcessTaskLauncher({choked.metadata.id: choked,
                                      healthy.metadata.id: healthy})
    metrics = InMemoryMetricsCollector()
    scheduler = SchedulerServer(launcher, metrics,
                                quarantine_threshold=0.5, quarantine_min_events=1.0,
                                sweep_interval_s=0.2)
    scheduler.start()
    scheduler.register_executor(choked.metadata)
    scheduler.register_executor(healthy.metadata)
    try:
        sid = scheduler.sessions.create_or_update(cfg.to_key_value_pairs(), "s-pressure")
        choked.session_pools = SessionPoolRegistry(capacity_per_session=64)
        choked.session_pools.get(sid).grow_wait(64, timeout_s=0.0)
        job_id = scheduler.submit_sql(tpch_query(6), sid)
        status = scheduler.wait_for_job(job_id, timeout=60)
        assert status["state"] == "successful", status.get("error")
        assert choked.pressure_rejections >= 1, "choked executor never exercised — vacuous"
        assert healthy.tasks_run >= 1
        assert choked.tasks_run == 0, "saturated pool must admit nothing"
    finally:
        scheduler.stop()
        launcher.pool.shutdown(wait=False)


# ---------------------------------------------------------------------------
# Flight data plane: stream gate + circuit breaker


class TestStreamGate:
    def test_cap_with_empty_queue_rejects_immediately(self):
        gate = _StreamGate(max_streams=1, accept_queue=0)
        gate.acquire()
        t0 = time.time()
        with pytest.raises(flight.FlightUnavailableError):
            gate.acquire()
        assert time.time() - t0 < 1.0, "no queue slot → fail fast, not after timeout"
        gate.release()
        gate.acquire()  # slot freed → admitted again
        gate.release()

    def test_bounded_waiters_time_out_then_overflow_rejected(self):
        gate = _StreamGate(max_streams=1, accept_queue=1, acquire_timeout_s=0.15)
        gate.acquire()
        results = []

        def waiter():
            try:
                gate.acquire()
                results.append("ok")
            except flight.FlightUnavailableError:
                results.append("timeout")

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        assert gate.waiters == 1
        # the queue is full: an extra caller is turned away immediately
        with pytest.raises(flight.FlightUnavailableError):
            gate.acquire()
        t.join(timeout=2)
        assert results == ["timeout"], "queued waiter must give up after the timeout"
        assert gate.waiters == 0

    def test_waiter_admitted_when_slot_frees(self):
        gate = _StreamGate(max_streams=1, accept_queue=4, acquire_timeout_s=5.0)
        gate.acquire()
        got = threading.Event()

        def waiter():
            gate.acquire()
            got.set()

        threading.Thread(target=waiter, daemon=True).start()
        time.sleep(0.05)
        assert not got.is_set()
        gate.release()
        assert got.wait(timeout=2)

    def test_zero_max_streams_disables_the_gate(self):
        gate = _StreamGate(max_streams=0, accept_queue=0)
        for _ in range(10):
            gate.acquire()

    def test_do_get_rejection_counts_in_server_stats(self, tmp_path):
        server = BallistaFlightServer(host="127.0.0.1", port=0, work_dir=str(tmp_path))
        try:
            server.gate = _StreamGate(max_streams=1, accept_queue=0)
            server.gate.acquire()  # exhaust the only slot
            ticket = flight.Ticket(json.dumps(
                {"path": str(tmp_path / "x.arrow"), "layout": "hash"}).encode())
            with pytest.raises(flight.FlightUnavailableError):
                server.do_get(None, ticket)
            assert server.stats["streams_rejected"] == 1
        finally:
            server.shutdown()


class TestCircuitBreaker:
    def test_trip_after_consecutive_failures(self):
        br = CircuitBreaker(threshold=2, cooldown_s=60.0)
        br.failure("a:1")
        br.check("a:1")  # one failure: still closed
        br.failure("a:1")
        assert br.trips == 1
        with pytest.raises(CircuitOpen) as ei:
            br.check("a:1")
        assert isinstance(ei.value, IoError)  # reader retry ladder handles it
        assert ei.value.retry_after_s > 0
        br.check("b:1")  # per-address: other peers unaffected

    def test_success_resets_the_consecutive_count(self):
        br = CircuitBreaker(threshold=2, cooldown_s=60.0)
        br.failure("a:1")
        br.success("a:1")
        br.failure("a:1")
        assert br.trips == 0
        br.check("a:1")

    def test_half_open_single_probe_then_close(self):
        br = CircuitBreaker(threshold=1, cooldown_s=0.1)
        br.failure("a:1")
        with pytest.raises(CircuitOpen):
            br.check("a:1")
        time.sleep(0.12)
        br.check("a:1")  # cooldown elapsed: THIS caller is the probe
        with pytest.raises(CircuitOpen):
            br.check("a:1")  # second caller while the probe is in flight
        br.success("a:1")
        br.check("a:1")  # probe succeeded: circuit closed

    def test_failed_probe_reopens_for_another_cooldown(self):
        br = CircuitBreaker(threshold=1, cooldown_s=0.1)
        br.failure("a:1")
        time.sleep(0.12)
        br.check("a:1")  # probe allowed
        br.failure("a:1")  # probe failed
        assert br.trips == 2
        with pytest.raises(CircuitOpen):
            br.check("a:1")  # re-opened: cooling down again
        time.sleep(0.12)
        br.check("a:1")  # next probe window

    def test_threshold_zero_disables(self):
        br = CircuitBreaker(threshold=0, cooldown_s=0.1)
        for _ in range(10):
            br.failure("a:1")
        br.check("a:1")
        assert br.trips == 0


# ---------------------------------------------------------------------------
# client backoff honoring the scheduler's hint


class FakeRpcError(grpc.RpcError):
    def __init__(self, code, details="", trailing=()):
        self._code = code
        self._details = details
        self._trailing = trailing

    def code(self):
        return self._code

    def details(self):
        return self._details

    def trailing_metadata(self):
        return self._trailing


def _client(cfg: BallistaConfig):
    from ballista_tpu.client.remote import RemoteSchedulerClient

    # the channel dials lazily — nothing listens on this port and no rpc
    # in these tests ever reaches the wire (the stub is replaced)
    return RemoteSchedulerClient("df://127.0.0.1:1", cfg)


class TestClientBackoff:
    def test_hint_extraction_prefers_trailing_metadata(self):
        from ballista_tpu.client.remote import _retry_after_ms

        e = FakeRpcError(grpc.StatusCode.RESOURCE_EXHAUSTED,
                         details="overloaded [retry_after_ms=9999]",
                         trailing=(("retry-after-ms", "250"),))
        assert _retry_after_ms(e) == 250
        e2 = FakeRpcError(grpc.StatusCode.RESOURCE_EXHAUSTED,
                          details="overloaded [retry_after_ms=400]")
        assert _retry_after_ms(e2) == 400
        assert _retry_after_ms(FakeRpcError(grpc.StatusCode.UNAVAILABLE, "nope")) is None

    def test_backoff_is_floored_at_the_server_hint(self):
        c = _client(BallistaConfig({CLIENT_BACKOFF_BASE_MS: 100,
                                    CLIENT_BACKOFF_MAX_MS: 10_000}))
        # attempt 0 alone would be 100ms; the 4s hint must dominate
        for _ in range(20):
            s = c._backoff_s(0, hint_ms=4000)
            assert 2.0 <= s <= 4.0  # jitter is 0.5x..1.0x
        # and the cap still bounds a hostile hint
        assert c._backoff_s(0, hint_ms=10**9) <= 10.0

    def test_backoff_grows_exponentially_under_the_cap(self):
        c = _client(BallistaConfig({CLIENT_BACKOFF_BASE_MS: 100,
                                    CLIENT_BACKOFF_MAX_MS: 1000}))
        assert c._backoff_s(0) <= 0.1
        assert c._backoff_s(10) <= 1.0  # capped

    def test_submit_retries_resource_exhausted_then_succeeds(self):
        c = _client(BallistaConfig({CLIENT_SUBMIT_RETRIES: 5,
                                    CLIENT_BACKOFF_BASE_MS: 1,
                                    CLIENT_BACKOFF_MAX_MS: 50}))
        calls = []

        def fake_execute(req, timeout):
            calls.append(time.monotonic())
            if len(calls) <= 2:
                raise FakeRpcError(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                   trailing=(("retry-after-ms", "40"),))
            return SimpleNamespace(job_id="job-ok")

        c.stub = SimpleNamespace(ExecuteQuery=fake_execute)
        t0 = time.monotonic()
        assert c._submit(SimpleNamespace()) == "job-ok"
        assert len(calls) == 3
        assert c.submit_retries == 2
        # two backoffs honoring the 40ms hint, each jittered to >= 20ms
        assert time.monotonic() - t0 >= 0.04

    def test_overload_surfaces_typed_after_retries_exhausted(self):
        c = _client(BallistaConfig({CLIENT_SUBMIT_RETRIES: 1,
                                    CLIENT_BACKOFF_BASE_MS: 1,
                                    CLIENT_BACKOFF_MAX_MS: 5}))

        def always_shed(req, timeout):
            raise FakeRpcError(grpc.StatusCode.RESOURCE_EXHAUSTED,
                               details="draining [retry_after_ms=123]")

        c.stub = SimpleNamespace(ExecuteQuery=always_shed)
        with pytest.raises(ClusterOverloaded) as ei:
            c._submit(SimpleNamespace())
        assert ei.value.retry_after_ms == 123
        assert ei.value.retryable

    def test_idempotent_rpcs_retry_transient_codes(self):
        c = _client(BallistaConfig({CLIENT_SUBMIT_RETRIES: 3,
                                    CLIENT_BACKOFF_BASE_MS: 1,
                                    CLIENT_BACKOFF_MAX_MS: 5}))
        attempts = []

        def flaky(req, timeout):
            attempts.append(1)
            if len(attempts) < 3:
                raise FakeRpcError(grpc.StatusCode.UNAVAILABLE, "scheduler blip")
            return "status"

        assert c._call_idempotent(flaky, None, "GetJobStatus") == "status"
        assert len(attempts) == 3

    def test_idempotent_rpcs_do_not_retry_fatal_codes(self):
        c = _client(BallistaConfig({CLIENT_SUBMIT_RETRIES: 3,
                                    CLIENT_BACKOFF_BASE_MS: 1,
                                    CLIENT_BACKOFF_MAX_MS: 5}))
        attempts = []

        def broken(req, timeout):
            attempts.append(1)
            raise FakeRpcError(grpc.StatusCode.INVALID_ARGUMENT, "bad request")

        with pytest.raises(grpc.RpcError):
            c._call_idempotent(broken, None, "GetJobStatus")
        assert len(attempts) == 1, "non-transient codes must not burn retries"
