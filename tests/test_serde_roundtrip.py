"""Auto-derived serde round-trip property test (ISSUE 11 satellite).

Rather than hand-writing one assertion per plan-node field (the approach
that let `QueryStage.broadcast` silently drop out of
`ExecutionGraph.from_proto` for a whole PR), this derives the field list
from each class's `__init__` signature at runtime: encode real planner
output, decode it, walk the two trees in lockstep, and require every
scalar constructor parameter to survive. A field added to a node but
forgotten in serde.py fails here automatically — the dynamic twin of the
`serde-sync` static pass.
"""

import inspect

import pytest

from ballista_tpu.serde import decode_plan, encode_plan, plan_from_bytes, plan_to_bytes

from .tpch_plan_stability.fixtures import query_path, stats_context

pytestmark = pytest.mark.analysis

# wire-form aliases: the constructor param is stored under another name
# (kept in sync with analysis/passes/serde_sync.py ENCODE_ALIASES)
_PARAM_ALIASES = {("MemoryScanExec", "schema"): "df_schema"}

# params that are legitimately NOT preserved bit-for-bit by the wire format
_SKIP_PARAMS = {
    ("ShuffleReaderExec", "partition_locations"),  # flattened + regrouped
}


def _scalarish(v) -> bool:
    if isinstance(v, (bool, int, float, str, type(None))):
        return True
    if isinstance(v, (list, tuple)):
        return all(_scalarish(x) for x in v)
    return False


def _stable_repr(v):
    """An address-free textual form, or None if there isn't one."""
    if isinstance(v, (list, tuple)):
        parts = [_stable_repr(x) for x in v]
        return None if any(p is None for p in parts) else "[" + ", ".join(parts) + "]"
    if isinstance(v, dict):
        # decode_plan canonicalizes optional keys to explicit Nones
        # (e.g. scan partitions gain `row_groups: None`); drop them so
        # semantically-equal forms compare equal
        parts = [(k, _stable_repr(x)) for k, x in sorted(v.items()) if x is not None]
        if any(p is None for _, p in parts):
            return None
        return "{" + ", ".join(f"{k!r}: {p}" for k, p in parts) + "}"
    if _scalarish(v):
        return repr(v)
    if hasattr(v, "fields"):  # DFSchema — DFField has a stable repr
        return repr(v.fields)
    r = repr(v)
    return None if " at 0x" in r else r


def _params(node):
    sig = inspect.signature(type(node).__init__)
    for name, p in sig.parameters.items():
        if name == "self" or p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD):
            continue
        yield name


def _pairs(a, b, path="root"):
    yield a, b, path
    ca, cb = a.children(), b.children()
    assert len(ca) == len(cb), f"{path}: child count {len(ca)} != {len(cb)}"
    for i, (x, y) in enumerate(zip(ca, cb)):
        yield from _pairs(x, y, f"{path}.{type(a).__name__}[{i}]")


def _assert_roundtrip(plan):
    back = decode_plan(encode_plan(plan))
    for orig, dec, path in _pairs(plan, back):
        assert type(orig) is type(dec), f"{path}: {type(orig).__name__} decoded as {type(dec).__name__}"
        cls = type(orig).__name__
        for name in _params(orig):
            if (cls, name) in _SKIP_PARAMS:
                continue
            attr = _PARAM_ALIASES.get((cls, name), name)
            if not hasattr(orig, attr):
                continue  # param not stored verbatim; the static pass vets these
            v0, v1 = getattr(orig, attr), getattr(dec, attr, "<missing>")
            if not _scalarish(v0):
                r0, r1 = _stable_repr(v0), _stable_repr(v1)
                if r0 is None:
                    continue  # no stable form (e.g. a child plan: the
                    # lockstep walk compares those node by node)
                assert r1 == r0, f"{path}: {cls}.{attr} changed: {r0} -> {r1}"
                continue
            assert v1 == v0, (
                f"{path}: {cls}.{attr} was {v0!r} before serde, {v1!r} after "
                f"— a constructor param is missing from encode_plan/decode_plan"
            )


@pytest.fixture(scope="module")
def ctx():
    return stats_context()


@pytest.mark.parametrize("n", [1, 3, 5, 7, 9, 18, 21])
def test_stage_plans_roundtrip(ctx, n):
    from ballista_tpu.scheduler.planner import DistributedPlanner

    with open(query_path(n), encoding="utf-8") as f:
        sql = f.read()
    physical = ctx.create_physical_plan(ctx.sql(sql).plan)
    for s in DistributedPlanner(f"rt{n}").plan_query_stages(physical):
        _assert_roundtrip(s.plan)


def test_mesh_stage_plan_roundtrips():
    from ballista_tpu.config import (
        EXECUTOR_ENGINE,
        TPU_MESH_ENABLED,
        TPU_MIN_ROWS,
        BallistaConfig,
    )
    from ballista_tpu.scheduler.planner import DistributedPlanner, merge_mesh_stages

    tctx = stats_context(engine="tpu")
    with open(query_path(1), encoding="utf-8") as f:
        sql = f.read()
    physical = tctx.create_physical_plan(tctx.sql(sql).plan)
    stages = DistributedPlanner("rtmesh").plan_query_stages(physical)
    merged = merge_mesh_stages(
        list(stages),
        BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0,
                        TPU_MESH_ENABLED: True}),
    )
    assert any(s.mesh for s in merged)
    for s in merged:
        _assert_roundtrip(s.plan)


def test_bytes_helpers_roundtrip(ctx):
    from ballista_tpu.scheduler.planner import DistributedPlanner

    with open(query_path(6), encoding="utf-8") as f:
        sql = f.read()
    physical = ctx.create_physical_plan(ctx.sql(sql).plan)
    stage = DistributedPlanner("rtb").plan_query_stages(physical)[0]
    assert plan_from_bytes(plan_to_bytes(stage.plan)).display(0) == stage.plan.display(0)


def test_execution_graph_proto_preserves_every_stage_field(ctx):
    """dataclasses.fields(QueryStage) drives the assertion, so a NEW stage
    flag that from_proto forgets (the PR-8 `broadcast` bug, re-fixed this
    PR along with `mesh`) fails here without editing this test."""
    import dataclasses

    from ballista_tpu.scheduler.planner import DistributedPlanner, QueryStage
    from ballista_tpu.scheduler.state.execution_graph import ExecutionGraph
    from ballista_tpu.shuffle.reader import UnresolvedShuffleExec

    with open(query_path(3), encoding="utf-8") as f:
        sql = f.read()
    physical = ctx.create_physical_plan(ctx.sql(sql).plan)
    stages = DistributedPlanner("rtg").plan_query_stages(physical)

    # force the sentinel-valued flags onto a producer/consumer edge so the
    # round trip can't pass by every field being its default
    prod = stages[0]
    prod.broadcast = True
    for s in stages:
        stack = [s.plan]
        while stack:
            node = stack.pop()
            if isinstance(node, UnresolvedShuffleExec) and node.stage_id == prod.stage_id:
                node.broadcast = True
            stack.extend(node.children())

    g = ExecutionGraph("rtg", "rtg", "sess", stages)
    g2 = ExecutionGraph.from_proto(g.to_proto(), g.config)
    assert set(g.stages) == set(g2.stages)
    for sid, st in g.stages.items():
        spec0, spec1 = st.spec, g2.stages[sid].spec
        for f in dataclasses.fields(QueryStage):
            v0, v1 = getattr(spec0, f.name), getattr(spec1, f.name)
            if f.name == "plan":
                assert v1.display(0) == v0.display(0), f"stage {sid}: plan changed"
                continue
            assert v1 == v0, (
                f"stage {sid}: QueryStage.{f.name} was {v0!r}, came back {v1!r} "
                f"— ExecutionGraph.to_proto/from_proto dropped a field"
            )
    assert any(st.spec.broadcast for st in g2.stages.values())
