"""Out-of-core TPU execution tests (jax CPU backend via conftest env).

- admission boundary decisions: plan_stage's ladder (run_whole /
  spill_colds / grace_split / cpu_demote) at exact budget boundaries
- host spill pool: put → pop byte parity across the host and disk tiers,
  tmp+rename discipline, counters
- device-table spill → touch → re-upload byte parity through the cache
- grace-join vs CPU-engine oracle on skewed keys with nulls + strings,
  byte-identical to the unconstrained device run
- grace recursion-depth cap → CPU-engine demotion (still correct)
- chaos hbm_oom e2e: TPC-H q3 under a forced sub-working-set budget
  completes byte-identical via grace with nonzero counters; an injected
  RESOURCE_EXHAUSTED is absorbed by the spill+retry rung
"""

import os
import re

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import (
    BallistaConfig,
    CHAOS_ENABLED,
    CHAOS_MODE,
    EXECUTOR_ENGINE,
    TPU_HBM_BUDGET_BYTES,
    TPU_HBM_GRACE_DEPTH,
    TPU_MIN_ROWS,
)
from ballista_tpu.ops.tpu import hbm
from ballista_tpu.ops.tpu.fusion import StageEstimate

from .conftest import tpch_query
from .test_tpu_fill import _assert_tables_identical, _mixed_table, _scan


def _est(table=1000, dicts=0, build=4000, jidx=0, has_mult=False):
    return StageEstimate(
        rows=100, partitions=2, group_domain=64, n_group_keys=1, lanes=1,
        has_mult=has_mult, n_filters=0, n_projections=0, n_joins=1,
        max_probe_table=0, table_bytes=table, dict_bytes=dicts,
        build_bytes=build, max_build_bytes=build, max_build_jidx=jidx)


def _plan(est, budget, **kw):
    kw.setdefault("grace_eligible", True)
    kw.setdefault("grace_fanout", 4)
    kw.setdefault("grace_max_depth", 2)
    return hbm.plan_stage(est, budget, **kw)


class TestAdmission:
    def test_exact_fit_runs_whole(self):
        p = _plan(_est(), 5000)  # working set == budget
        assert p.decision == hbm.RUN_WHOLE
        assert p.working_set == 5000

    def test_one_byte_over_grace_splits(self):
        p = _plan(_est(), 4999)
        assert p.decision == hbm.GRACE_SPLIT
        assert p.grace_depth == 1
        assert p.grace_buckets == 4
        assert p.split_jidx == 0

    def test_unbudgeted_runs_whole(self):
        assert _plan(_est(), 0).decision == hbm.RUN_WHOLE

    def test_cold_residents_force_spill(self):
        p = _plan(_est(), 6000, resident_other=2000)
        assert p.decision == hbm.SPILL_COLDS
        p = _plan(_est(), 8000, resident_other=2000)  # both fit: no spill
        assert p.decision == hbm.RUN_WHOLE

    def test_depth_escalation(self):
        # depth 1 (1000 + 4000/4 = 2000) misses, depth 2 (1000 + 250) fits
        p = _plan(_est(), 1500)
        assert p.decision == hbm.GRACE_SPLIT
        assert p.grace_depth == 2
        assert p.grace_buckets == 16

    def test_depth_cap_demotes_to_cpu(self):
        p = _plan(_est(), 1010)  # even 16 buckets: 1000 + 250 > 1010
        assert p.decision == hbm.CPU_DEMOTE
        assert "depth cap" in p.reason

    def test_fixed_bytes_over_budget_demote(self):
        p = _plan(_est(), 900)  # non-splittable 1000 B alone exceed budget
        assert p.decision == hbm.CPU_DEMOTE
        assert "non-splittable" in p.reason

    def test_ineligible_join_demotes(self):
        p = _plan(_est(), 4999, grace_eligible=False)
        assert p.decision == hbm.CPU_DEMOTE
        p = _plan(_est(build=0, jidx=-1), 999)  # no build at all
        assert p.decision == hbm.CPU_DEMOTE

    def test_grace_disabled_demotes(self):
        p = _plan(_est(), 4999, grace_max_depth=0)
        assert p.decision == hbm.CPU_DEMOTE
        assert "disabled" in p.reason

    def test_post_oom_hint_prefers_grace(self):
        p = _plan(_est(), 10_000, force_grace=True)
        assert p.decision == hbm.GRACE_SPLIT
        assert "post-OOM" in p.reason

    def test_post_oom_hint_without_grace_reruns_whole(self):
        # the evict+spill freed the device: a joinless stage's one retry
        # re-attempts the device run instead of demoting straight to CPU
        p = _plan(_est(build=0, jidx=-1), 10_000, force_grace=True)
        assert p.decision == hbm.RUN_WHOLE
        assert "re-running whole after spill" in p.reason
        p = _plan(_est(), 10_000, force_grace=True, grace_max_depth=0)
        assert p.decision == hbm.RUN_WHOLE

    def test_observed_bytes_floor_the_estimate(self):
        # AQE-observed input volume overrides an optimistic build estimate
        p = _plan(_est(build=10), 2000, observed_bytes=5000)
        assert p.working_set == 6000
        assert p.decision == hbm.GRACE_SPLIT


def test_grace_bucket_of_covers_and_is_deterministic():
    keys = np.array([0, 1, 5, -3, 1 << 40, 7, 7, 123456789], dtype=np.int64)
    b1 = hbm.grace_bucket_of(keys, 4)
    b2 = hbm.grace_bucket_of(keys, 4)
    assert (b1 == b2).all()
    assert ((b1 >= 0) & (b1 < 4)).all()
    # equal keys always share a bucket (the correctness invariant)
    assert b1[5] == b1[6]
    # a spread of keys lands in more than one bucket
    many = hbm.grace_bucket_of(np.arange(1000, dtype=np.int64), 4)
    assert len(np.unique(many)) == 4


class TestGracePostconditions:
    def _report(self, **over):
        kw = dict(stage_tag="s", n_buckets=4, fanout=4, depth=1, max_depth=2,
                  buckets_run=[0, 1, 3], buckets_empty=[2])
        kw.update(over)
        return hbm.GraceReport(**kw)

    def test_good_report_passes(self):
        from ballista_tpu.analysis.plan_check import check_grace

        assert check_grace(self._report()) == []

    def test_missing_bucket_flags_cover(self):
        from ballista_tpu.analysis.plan_check import check_grace

        v = check_grace(self._report(buckets_run=[0, 1], buckets_empty=[2]))
        assert any("grace-cover" == x.code for x in v)

    def test_overlap_flags_cover(self):
        from ballista_tpu.analysis.plan_check import check_grace

        v = check_grace(self._report(buckets_run=[0, 1, 2, 3],
                                     buckets_empty=[2]))
        assert any("grace-cover" == x.code for x in v)

    def test_non_producer_order_merge_flags(self):
        from ballista_tpu.analysis.plan_check import check_grace

        v = check_grace(self._report(merge="bucket-major-shuffled"))
        assert any("grace-order" == x.code for x in v)

    def test_depth_over_cap_flags(self):
        from ballista_tpu.analysis.plan_check import check_grace

        v = check_grace(self._report(depth=3, max_depth=2, n_buckets=64,
                                     buckets_run=list(range(64)),
                                     buckets_empty=[]))
        assert any("grace-depth" == x.code for x in v)

    def test_bucket_fanout_mismatch_flags(self):
        from ballista_tpu.analysis.plan_check import check_grace

        v = check_grace(self._report(n_buckets=5,
                                     buckets_run=[0, 1, 2, 3, 4],
                                     buckets_empty=[]))
        assert any("grace-depth" == x.code for x in v)


class TestHostSpillPool:
    def test_host_tier_roundtrip_preserves_none_slots(self):
        pool = hbm.HostSpillPool(max_host_bytes=1 << 20)
        arrays = [np.arange(10, dtype=np.int64), None,
                  np.ones((3, 3), dtype=bool)]
        nb = sum(a.nbytes for a in arrays if a is not None)
        pool.put(("k",), ("meta", 1), arrays, nb)
        st = pool.stats()
        assert st["spill_events"] == 1 and st["spill_bytes"] == nb
        assert st["host_bytes"] == nb
        meta, back = pool.pop(("k",))
        assert meta == ("meta", 1)
        assert back[1] is None
        assert np.array_equal(back[0], arrays[0])
        assert np.array_equal(back[2], arrays[2])
        assert pool.stats()["reupload_events"] == 1
        assert pool.pop(("k",)) is None

    def test_disk_tier_tmp_rename_discipline(self, tmp_path):
        pool = hbm.HostSpillPool(max_host_bytes=0, spill_dir=str(tmp_path))
        arrays = [np.arange(100, dtype=np.float64), None]
        pool.put(("d",), "m", arrays, arrays[0].nbytes)
        files = os.listdir(tmp_path)
        assert len(files) == 1 and files[0].endswith(".npz")
        assert not any(f.endswith(".tmp") for f in files)
        meta, back = pool.pop(("d",))
        assert meta == "m"
        assert back[1] is None
        assert np.array_equal(back[0], arrays[0])
        assert os.listdir(tmp_path) == []  # consumed

    def test_host_overflow_demotes_coldest_to_disk(self, tmp_path):
        pool = hbm.HostSpillPool(max_host_bytes=100, spill_dir=str(tmp_path))
        a1 = [np.zeros(10, dtype=np.int64)]  # 80 B
        a2 = [np.ones(10, dtype=np.int64)]
        pool.put(("one",), "m1", a1, 80)
        pool.put(("two",), "m2", a2, 80)
        assert len(pool) == 2
        assert pool.stats()["host_bytes"] <= 100
        assert len(os.listdir(tmp_path)) == 1  # the cold entry hit disk
        _, b1 = pool.pop(("one",))
        _, b2 = pool.pop(("two",))
        assert np.array_equal(b1[0], a1[0]) and np.array_equal(b2[0], a2[0])

    def test_clear_removes_disk_files(self, tmp_path):
        pool = hbm.HostSpillPool(max_host_bytes=0, spill_dir=str(tmp_path))
        pool.put(("x",), "m", [np.arange(5)], 40)
        assert os.listdir(tmp_path)
        pool.clear()
        assert os.listdir(tmp_path) == []
        assert len(pool) == 0


def test_device_table_spill_touch_reupload_parity(tmp_path):
    """A cached device table demoted to the pool and re-fetched on the next
    touch must be byte-identical — through the host tier AND the disk tier."""
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.plan.physical import TaskContext

    for host_bytes in (1 << 30, 0):  # host tier, then forced disk tier
        sc.clear_device_caches()
        pool = hbm.HostSpillPool(max_host_bytes=host_bytes,
                                 spill_dir=str(tmp_path))
        ctx = TaskContext(BallistaConfig({}))
        scan = _scan(_mixed_table())
        buckets = [1 << 12, 1 << 14, 1 << 16]
        dt = sc.DEVICE_CACHE.get(scan, buckets, ctx, 1 << 30, None,
                                 spill_pool=pool)
        freed = sc.DEVICE_CACHE.ensure_headroom(0, None, pool)
        assert freed == dt.nbytes
        assert pool.stats()["spill_events"] == 1
        assert sc.DEVICE_CACHE.resident_bytes() == 0
        dt2 = sc.DEVICE_CACHE.get(scan, buckets, ctx, 1 << 30, None,
                                  spill_pool=pool)
        assert pool.stats()["reupload_events"] == 1
        _assert_tables_identical(dt, dt2)
    sc.clear_device_caches()


# ---------------------------------------------------------------------------
# e2e: grace-partitioned join vs CPU-engine oracle


def _skewed_tables():
    """Skewed join keys (70% in 10 hot keys), NULL probe keys, dictionary
    strings on both sides, money-lane amounts, and probe keys with no dim
    match (unmatched masking)."""
    rng = np.random.default_rng(7)
    n = 30_000
    keys = np.where(rng.random(n) < 0.7,
                    rng.integers(0, 10, n),
                    rng.integers(0, 1200, n)).astype(np.int64)
    key_arr = pa.array(
        [None if i % 23 == 0 else int(k) for i, k in enumerate(keys)],
        pa.int64())
    fact = pa.table({
        "k": key_arr,
        "flag": pa.array(rng.choice(["x", "y", "z", "w"], n)),
        "amount": np.round(rng.uniform(0, 100, n), 2),
    })
    dk = np.arange(1000, dtype=np.int64)  # keys 1000..1199 unmatched
    dim = pa.table({
        "dk": dk,
        "name": pa.array([f"seg{int(v) % 5}" for v in dk]),
    })
    return fact, dim


_ORACLE_SQL = (
    "select f.flag, d.name, count(*) c, sum(f.amount) s "
    "from fact f join dim d on f.k = d.dk "
    "group by f.flag, d.name order by f.flag, d.name")


def _join_stage_rec(stages: dict) -> dict:
    """The per-stage record of the budget-relevant join stage: the one whose
    admission reason states a working set (the final stage states its own
    `final stage fits` reason and would shadow it in the merged snapshot)."""
    recs = [r for r in stages.values()
            if re.search(r"working set (\d+) B", r.get("hbm_plan_reason", ""))]
    assert recs, f"no admission-planned stage in {list(stages)}"
    return max(recs, key=lambda r: int(
        re.search(r"working set (\d+) B", r["hbm_plan_reason"]).group(1)))


def _working_set(rec: dict) -> int:
    return int(re.search(r"working set (\d+) B",
                         rec["hbm_plan_reason"]).group(1))


def _run_oracle(cfg_over: dict) -> tuple[pa.Table, dict]:
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.client.context import SessionContext

    sc.clear_device_caches()
    sc.RUN_STATS.clear()
    fact, dim = _skewed_tables()
    ctx = SessionContext(BallistaConfig(cfg_over))
    ctx.register_arrow_table("fact", fact, partitions=3)
    ctx.register_arrow_table("dim", dim, partitions=2)
    out = ctx.sql(_ORACLE_SQL).collect()
    return out, sc.RUN_STATS.stages()


def _assert_same_values(got: pa.Table, ref: pa.Table):
    assert got.num_rows == ref.num_rows
    for col in ("flag", "name", "c"):
        assert got.column(col).to_pylist() == ref.column(col).to_pylist()
    g = np.asarray(got.column("s").to_pylist(), dtype=np.float64)
    r = np.asarray(ref.column("s").to_pylist(), dtype=np.float64)
    assert np.allclose(g, r, rtol=0, atol=1e-6), (g, r)


def test_grace_join_matches_cpu_oracle():
    ref, _ = _run_oracle({})  # CPU engine oracle

    whole, stages = _run_oracle({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
    rec = _join_stage_rec(stages)
    assert rec["hbm_plan"] == hbm.RUN_WHOLE
    _assert_same_values(whole, ref)
    working = _working_set(rec)

    graced, stages = _run_oracle({
        EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0,
        TPU_HBM_BUDGET_BYTES: working - 1,
    })
    rec = _join_stage_rec(stages)
    assert rec["hbm_plan"] == hbm.GRACE_SPLIT, rec["hbm_plan_reason"]
    assert rec["grace_splits"] >= 2
    _assert_same_values(graced, ref)
    # byte-identity against the unconstrained device run: producer-order
    # reunification makes the grace output literally the same table
    assert graced.equals(whole)


def test_grace_depth_cap_demotes_to_cpu_engine():
    ref, _ = _run_oracle({})
    _, stages = _run_oracle({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
    working = _working_set(_join_stage_rec(stages))

    # budget below the working set with grace disabled: the only rung left
    # is the CPU engine — the stage must decline, not crash, and the CPU
    # fallback must serve the exact oracle result
    demoted, stages = _run_oracle({
        EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0,
        TPU_HBM_BUDGET_BYTES: working - 1, TPU_HBM_GRACE_DEPTH: 0,
    })
    rec = _join_stage_rec(stages)
    assert rec["hbm_plan"] == hbm.CPU_DEMOTE
    _assert_same_values(demoted, ref)


# ---------------------------------------------------------------------------
# e2e: chaos hbm_oom on TPC-H q3


@pytest.fixture()
def _chaos_cleanup():
    yield
    hbm.disarm_chaos()


def _run_q3_standalone(tpch_dir, cfg_over: dict):
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    sc.clear_device_caches()
    sc.RUN_STATS.clear()
    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0, **cfg_over})
    ctx = SessionContext.standalone(cfg, num_executors=1, vcores=2)
    register_tpch(ctx, tpch_dir)
    try:
        out = ctx.sql(tpch_query(3)).collect()
    finally:
        ctx.shutdown()
    return out, sc.RUN_STATS.stages()


def test_chaos_hbm_oom_q3_grace_byte_identical(tpch_dir, tpch_ref_tables,
                                               monkeypatch, _chaos_cleanup):
    """TPC-H q3 whose join stage exceeds a chaos-forced HBM budget must
    complete byte-identical via the grace rung (nonzero grace_splits), not
    raise RESOURCE_EXHAUSTED or silently leave the device engine."""
    from ballista_tpu.testing.reference import compare_results, run_reference

    baseline, stages = _run_q3_standalone(tpch_dir, {})
    working = _working_set(_join_stage_rec(stages))

    monkeypatch.setenv("BALLISTA_CHAOS_HBM_BUDGET", str(working - 1))
    chaotic, stages = _run_q3_standalone(
        tpch_dir, {CHAOS_ENABLED: True, CHAOS_MODE: "hbm_oom"})

    rec = _join_stage_rec(stages)
    assert rec["hbm_budget_bytes"] == working - 1
    assert rec["hbm_plan"] == hbm.GRACE_SPLIT, rec["hbm_plan_reason"]
    assert rec.get("grace_splits", 0) > 0
    assert chaotic.equals(baseline), "grace q3 diverges from device baseline"
    problems = compare_results(chaotic, run_reference(3, tpch_ref_tables), 3)
    assert not problems, "\n".join(problems)


def test_chaos_injected_oom_spill_retry_converges(tpch_dir, tpch_ref_tables,
                                                  monkeypatch, _chaos_cleanup):
    """An injected RESOURCE_EXHAUSTED on a device upload is absorbed by the
    evict+spill+retry rung: the stage re-runs on device and the query is
    still correct (hbm_oom_retries recorded)."""
    from ballista_tpu.testing.reference import compare_results, run_reference

    monkeypatch.setenv("BALLISTA_CHAOS_HBM_BUDGET", str(1 << 30))
    monkeypatch.setenv("BALLISTA_CHAOS_HBM_OOM_N", "1")
    out, stages = _run_q3_standalone(
        tpch_dir, {CHAOS_ENABLED: True, CHAOS_MODE: "hbm_oom"})

    assert any(r.get("hbm_oom_retries", 0) >= 1 for r in stages.values()), \
        {t: r.get("hbm_oom_retries") for t, r in stages.items()}
    problems = compare_results(out, run_reference(3, tpch_ref_tables), 3)
    assert not problems, "\n".join(problems)
