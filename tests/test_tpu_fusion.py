"""Whole-stage fusion end-to-end tests: fused-vs-staged parity on TPC-H
shaped stages, the Pallas kernel paths through the full engine, the
fallback ladder, and RunStats/heartbeat visibility.

These run the stage compiler end-to-end (jax CPU backend, Pallas in
interpreter mode) and are heavier than tests/test_fusion.py's pure unit
tests.
"""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import (
    BallistaConfig,
    EXECUTOR_ENGINE,
    TPU_FUSION_ENABLED,
    TPU_FUSION_MIN_ROWS,
    TPU_FUSION_MODE,
    TPU_MIN_ROWS,
)

from .conftest import tpch_query


def _ctx(tbl_parts=None, tpch_dir=None, **cfg_extra):
    from ballista_tpu.client.context import SessionContext

    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0, **cfg_extra})
    ctx = SessionContext(cfg)
    if tbl_parts:
        for name, (tbl, parts) in tbl_parts.items():
            ctx.register_arrow_table(name, tbl, partitions=parts)
    if tpch_dir is not None:
        from ballista_tpu.testing.tpchgen import register_tpch

        register_tpch(ctx, tpch_dir)
    return ctx


def _run_mode(sql, mode, tbl_parts=None, tpch_dir=None, **cfg_extra):
    """Collect `sql` under a forced fusion mode; return (table, stats)."""
    import ballista_tpu.ops.tpu.stage_compiler as sc

    ctx = _ctx(tbl_parts, tpch_dir, **{TPU_FUSION_MODE: mode, **cfg_extra})
    sc.RUN_STATS.clear()
    out = ctx.sql(sql).collect()
    return out, sc.RUN_STATS.snapshot()


def _synth(n=50_000, seed=5, cats=5):
    rng = np.random.default_rng(seed)
    names = [f"c{i:04d}" for i in range(cats)]
    return pa.table({
        "cat": rng.choice(names, n),
        "price": np.round(rng.uniform(1, 100, n), 2),  # money (int64 cents)
        "w": rng.uniform(0.0, 10.0, n),                # true f64
        "qty": rng.integers(1, 50, n),
    })


# ----------------------------------------------------- staged/fused parity


@pytest.mark.parametrize("q", [1, 6, 12, 19])
def test_tpch_parity_staged_vs_fused(q, tpch_dir):
    """Staged and fused_xla trace the SAME jnp expressions over the same
    inputs — results must be byte-identical, not just allclose. (A stage
    that is staged-ineligible clamps to fused_xla; q1/q6 must genuinely
    run staged.)"""
    sql = tpch_query(q)
    fused, s_f = _run_mode(sql, "fused_xla", tpch_dir=tpch_dir)
    staged, s_s = _run_mode(sql, "staged", tpch_dir=tpch_dir)
    assert s_f.get("fusion_mode") == "fused_xla"
    assert s_s.get("fusion_mode") in ("staged", "fused_xla")
    assert staged.combine_chunks().equals(fused.combine_chunks())
    if q in (1, 6):
        assert s_s.get("fusion_mode") == "staged"
        # staged mode carries the per-span roofline split
        assert set(s_s.get("span_s", {})) == {"predicate", "project", "aggregate"}
        assert s_s.get("fused_spans") == 0
        assert s_f.get("fused_spans", 0) >= 2


def test_parity_with_join_filter_project(tpch_dir):
    """filter→project→join-probe→partial-agg combo (q14 shape): fused and
    staged byte-identical through the probe gathers too."""
    sql = tpch_query(14)
    fused, s_f = _run_mode(sql, "fused_xla", tpch_dir=tpch_dir)
    staged, s_s = _run_mode(sql, "staged", tpch_dir=tpch_dir)
    assert staged.combine_chunks().equals(fused.combine_chunks())
    # q14's stage joins through part (unique direct build): staged-eligible
    assert s_s.get("fusion_mode") == "staged"


def test_parity_synthetic_all_agg_funcs():
    sql = ("select cat, sum(price) s, sum(w) ws, count(*) c, min(qty) mn, "
           "max(qty) mx from t where qty > 7 group by cat order by cat")
    tbl = _synth()
    fused, s_f = _run_mode(sql, "fused_xla", {"t": (tbl, 4)})
    staged, s_s = _run_mode(sql, "staged", {"t": (tbl, 4)})
    assert s_s.get("fusion_mode") == "staged"
    assert staged.combine_chunks().equals(fused.combine_chunks())


# ----------------------------------------------------------- pallas paths


def test_fused_pallas_forced_via_fusion_mode():
    """ballista.tpu.fusion.mode=fused_pallas routes eligible stages through
    the kernels (interpret mode on CPU); f32 sums carry a tolerance, counts
    are exact, and the mode is visible in RunStats."""
    sql = ("select cat, sum(w) s, count(*) c from t where qty > 10 "
           "group by cat order by cat")
    tbl = _synth(n=30_000, seed=21)
    pallas, s_p = _run_mode(sql, "fused_pallas", {"t": (tbl, 4)})
    staged, _ = _run_mode(sql, "staged", {"t": (tbl, 4)})
    assert s_p.get("fusion_mode") == "fused_pallas"
    assert s_p.get("fusion_reason", "").startswith("forced")
    p, s = pallas.to_pandas(), staged.to_pandas()
    assert p.cat.tolist() == s.cat.tolist()
    assert (p.c.values == s.c.values).all()
    np.testing.assert_allclose(p.s.values, s.s.values, rtol=2e-5)


def test_pallas_multi_tile_group_domain():
    """G past the old 128-lane/64-budget ceilings: a ~300-category domain
    (pow2 → 512) runs the multi-tile kernel grid, compared against the
    sorted path which is oracle-exact."""
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.plan.physical import TaskContext

    sql = ("select cat, sum(w) s, count(*) c from t group by cat "
           "order by cat")
    tbl = _synth(n=40_000, seed=13, cats=300)
    pallas, s_p = _run_mode(sql, "fused_pallas", {"t": (tbl, 4)})
    ref, s_r = _run_mode(sql, "fused_xla", {"t": (tbl, 4)})
    assert s_p.get("fusion_mode") == "fused_pallas"
    # fused_xla at G=512 exceeds the unroll budget → sorted path (still
    # one fused kernel, exact math)
    assert s_r.get("fusion_mode") == "fused_xla"
    p, r = pallas.to_pandas(), ref.to_pandas()
    assert p.cat.tolist() == r.cat.tolist()
    assert (p.c.values == r.c.values).all()
    np.testing.assert_allclose(p.s.values, r.s.values, rtol=2e-5)

    # and the stage really ran on device, zero fallbacks
    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0,
                          TPU_FUSION_MODE: "fused_pallas"})
    from ballista_tpu.client.context import SessionContext

    ctx = SessionContext(cfg)
    ctx.register_arrow_table("t", tbl, partitions=4)
    phys = maybe_compile_tpu(ctx.create_physical_plan(ctx.sql(sql).plan), cfg)
    stages = [n for n in _walk(phys) if isinstance(n, sc.TpuStageExec)]
    assert stages
    tc = TaskContext(cfg)
    for p_ in range(phys.output_partition_count()):
        list(phys.execute(p_, tc))
    assert sum(s.tpu_count for s in stages) >= 1
    assert sum(s.fallback_count for s in stages) == 0


def test_pallas_fallback_ladder_to_fused_xla():
    """fused_pallas requested for a money-sum stage at large G: the kernel
    family can't carry exact int64 cents, the trace raises Unsupported, and
    the ladder lands on fused_xla (sorted) — NOT the CPU engine."""
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.plan.physical import TaskContext

    sql = ("select cat, sum(price) s, count(*) c from t group by cat "
           "order by cat")
    tbl = _synth(n=30_000, seed=3, cats=300)
    out, stats = _run_mode(sql, "fused_pallas", {"t": (tbl, 4)})
    assert stats.get("fusion_mode") == "fused_xla"  # clamped by the ladder
    df = tbl.to_pandas()
    g = (df.groupby("cat", as_index=False)
         .agg(s=("price", "sum"), c=("price", "size")).sort_values("cat"))
    o = out.to_pandas()
    assert o.cat.tolist() == g.cat.tolist()
    # engine money math is exact int64 cents; pandas' float accumulation
    # is the noisy side of this comparison
    np.testing.assert_allclose(o.s.values.astype(float), g.s.values, rtol=1e-12)
    assert (o.c.values == g.c.values).all()

    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0,
                          TPU_FUSION_MODE: "fused_pallas"})
    from ballista_tpu.client.context import SessionContext

    ctx = SessionContext(cfg)
    ctx.register_arrow_table("t", tbl, partitions=4)
    phys = maybe_compile_tpu(ctx.create_physical_plan(ctx.sql(sql).plan), cfg)
    stages = [n for n in _walk(phys) if isinstance(n, sc.TpuStageExec)]
    assert stages
    tc = TaskContext(cfg)
    for p_ in range(phys.output_partition_count()):
        list(phys.execute(p_, tc))
    assert sum(s.fallback_count for s in stages) == 0


# ------------------------------------------------------- cost model in situ


def test_auto_small_input_staged():
    """The cost model's staged fallback, end to end: tiny staged-eligible
    input in auto mode → staged execution, with the reason recorded."""
    sql = "select cat, sum(w) s, count(*) c from t group by cat order by cat"
    tbl = _synth(n=2_000, seed=9)
    out, stats = _run_mode(sql, "auto", {"t": (tbl, 2)})
    assert stats.get("fusion_mode") == "staged"
    assert "fusion.min.rows" in stats.get("fusion_reason", "")
    # and above the threshold the same shape fuses
    big = _synth(n=20_000, seed=9)
    out2, stats2 = _run_mode(sql, "auto", {"t": (big, 2)})
    assert stats2.get("fusion_mode") == "fused_xla"


def test_fusion_disabled_lands_staged():
    sql = "select cat, sum(w) s from t group by cat order by cat"
    tbl = _synth(n=20_000, seed=2)
    out, stats = _run_mode(sql, "auto", {"t": (tbl, 2)},
                           **{TPU_FUSION_ENABLED: False})
    assert stats.get("fusion_mode") == "staged"
    assert "disabled" in stats.get("fusion_reason", "")


# ------------------------------------------------- stats/heartbeat surface


def test_runstats_and_heartbeat_gauges(tpch_dir):
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.executor.executor_process import ExecutorProcess

    out, stats = _run_mode(tpch_query(1), "fused_xla", tpch_dir=tpch_dir)
    assert stats.get("fusion_mode") == "fused_xla"
    assert stats.get("fused_spans", 0) >= 2  # filter→project→agg stage
    assert stats.get("fused_kernel_s", 0.0) > 0.0
    assert "fusion_reason" in stats

    gauges = dict(ExecutorProcess._tpu_metrics())
    assert gauges.get("tpu_fusion_mode") == 1.0  # fused_xla
    assert gauges.get("tpu_fused_spans", 0.0) >= 2.0
    assert gauges.get("tpu_fused_kernel_s", 0.0) > 0.0


def _walk(node):
    yield node
    for c in node.children():
        yield from _walk(c)
