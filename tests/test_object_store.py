"""S3 object-store integration (reference: examples/tests/object_store.rs
with testcontainers + MinIO — replaced by an in-process S3 protocol shim,
since this environment has no containers or network egress). Exercises the
REAL pyarrow S3FileSystem client end-to-end: registration discovery
(ListObjectsV2), schema reads, and ranged GETs during scans."""

import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest


@pytest.fixture()
def s3_env(tmp_path, monkeypatch):
    from ballista_tpu.testing.mini_s3 import start_mini_s3

    bucket = tmp_path / "test-bucket" / "sales"
    bucket.mkdir(parents=True)
    tbl = pa.table({
        "id": pa.array(range(1000), pa.int64()),
        "region": pa.array([f"r{i % 4}" for i in range(1000)]),
        "amount": pa.array([round(0.25 * (i % 97), 2) for i in range(1000)]),
    })
    pq.write_table(tbl.slice(0, 500), bucket / "part-0.parquet")
    pq.write_table(tbl.slice(500), bucket / "part-1.parquet")
    srv, endpoint = start_mini_s3(str(tmp_path))
    monkeypatch.setenv("AWS_ENDPOINT_URL", endpoint)
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "test")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "test")
    monkeypatch.setenv("AWS_ALLOW_HTTP", "true")
    monkeypatch.setenv("AWS_REGION", "us-east-1")
    yield "s3://test-bucket/sales", tbl
    srv.shutdown()


def test_s3_scan_end_to_end(s3_env):
    from ballista_tpu.client.context import SessionContext

    uri, tbl = s3_env
    ctx = SessionContext()
    ctx.register_parquet("sales", uri)
    out = ctx.sql(
        "SELECT region, count(*) AS c, sum(amount) AS s FROM sales "
        "GROUP BY region ORDER BY region"
    ).collect().to_pandas()
    assert out.region.tolist() == ["r0", "r1", "r2", "r3"]
    assert int(out.c.sum()) == 1000
    df = tbl.to_pandas().groupby("region")["amount"].sum()
    import numpy as np

    assert np.allclose(out.s.values, df.sort_index().values, atol=1e-9)


def test_s3_scan_distributed_standalone(s3_env):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import BallistaConfig

    uri, _ = s3_env
    ctx = SessionContext.standalone(BallistaConfig(), num_executors=1, vcores=2)
    try:
        ctx.register_parquet("sales", uri)
        out = ctx.sql("SELECT count(*) AS c FROM sales WHERE id < 250").collect()
        assert out.column("c").to_pylist() == [250]
    finally:
        ctx.shutdown()
