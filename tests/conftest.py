import os

# Sharding/parallelism tests run on a virtual 8-device CPU mesh (the driver
# separately dry-runs the multi-chip path). Tests must be hermetic: a TPU
# plugin whose tunnel died must never hang CPU-only test runs. Env vars
# alone are too late here — a sitecustomize on PYTHONPATH may have imported
# jax at interpreter startup — so pin the platform through the supported
# post-import config override as well.
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_PLATFORM_NAME"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture(scope="session")
def tpch_dir(tmp_path_factory):
    """Session-scoped TPC-H SF0.01 parquet directory."""
    from ballista_tpu.testing.tpchgen import generate_tpch

    d = tmp_path_factory.mktemp("tpch") / "sf001"
    generate_tpch(str(d), scale=0.01, seed=42, files_per_table=2)
    return str(d)


@pytest.fixture()
def tpch_ctx(tpch_dir):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    ctx = SessionContext()
    register_tpch(ctx, tpch_dir)
    return ctx


@pytest.fixture(scope="session")
def tpch_ref_tables(tpch_dir):
    from ballista_tpu.testing.reference import load_tables

    return load_tables(tpch_dir)


def tpch_query(n: int) -> str:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "benchmarks", "tpch", "queries", f"q{n}.sql")) as f:
        return f.read()


def iter_plan(node):
    """Depth-first walk of a physical plan (shared by plan-shape tests)."""
    yield node
    for c in node.children():
        yield from iter_plan(c)
