"""Operator / kernel unit tests: hashing contract, join matching, config."""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import BallistaConfig, EXECUTOR_ENGINE, DEFAULT_SHUFFLE_PARTITIONS
from ballista_tpu.errors import ConfigurationError
from ballista_tpu.ops.cpu.join_kernel import match_pairs
from ballista_tpu.ops.hashing import hash_arrays, partition_indices


def test_hash_deterministic_across_types():
    a = pa.array([1, 2, 3, 2**40], pa.int64())
    h1 = hash_arrays([a])
    h2 = hash_arrays([a.cast(pa.int32(), safe=False)])  # 2**40 wraps; ignore last
    assert (h1[:3] == h2[:3]).all()
    d = pa.array([0, 1, 2], pa.int32()).cast(pa.date32())
    hd = hash_arrays([d])
    assert len(set(hd.tolist())) == 3


def test_hash_strings_and_nulls():
    s = pa.array(["abc", "abd", None, "abc"])
    h = hash_arrays([s])
    assert h[0] == h[3] and h[0] != h[1]
    # null has its own stable hash
    h2 = hash_arrays([pa.array([None], pa.string())])
    assert h[2] == h2[0]


def test_partition_indices_range():
    a = pa.array(np.arange(1000), pa.int64())
    p = partition_indices([a], 7)
    assert p.min() >= 0 and p.max() < 7
    # roughly uniform
    counts = np.bincount(p, minlength=7)
    assert counts.min() > 80


def test_match_pairs_duplicates_and_nulls():
    build = [pa.array([1, 2, 2, None, 5], pa.int64())]
    probe = [pa.array([2, 5, 7, None], pa.int64())]
    bi, pi = match_pairs(build, probe)
    pairs = sorted(zip(pi.tolist(), bi.tolist()))
    # probe row 0 (val 2) matches build rows 1 and 2; probe row 1 (val 5) matches build 4
    assert pairs == [(0, 1), (0, 2), (1, 4)]


def test_match_pairs_multi_key():
    build = [pa.array([1, 1, 2]), pa.array(["a", "b", "a"])]
    probe = [pa.array([1, 2]), pa.array(["b", "a"])]
    bi, pi = match_pairs(build, probe)
    assert sorted(zip(pi.tolist(), bi.tolist())) == [(0, 1), (1, 2)]


def test_config_validation():
    c = BallistaConfig()
    assert c.get(DEFAULT_SHUFFLE_PARTITIONS) == 16
    c.set(DEFAULT_SHUFFLE_PARTITIONS, "8")
    assert c.get(DEFAULT_SHUFFLE_PARTITIONS) == 8
    with pytest.raises(ConfigurationError):
        c.set("ballista.unknown.key", 1)
    with pytest.raises(ConfigurationError):
        c.set(EXECUTOR_ENGINE, "gpu")
    pairs = c.to_key_value_pairs()
    c2 = BallistaConfig.from_key_value_pairs(pairs)
    assert c2.get(DEFAULT_SHUFFLE_PARTITIONS) == 8


def test_config_docs_generation():
    from ballista_tpu.config import generate_config_docs

    docs = generate_config_docs()
    assert "ballista.executor.engine" in docs
    assert "ballista.tpu.shape.buckets" in docs


def test_config_docs_file_is_fresh():
    """docs-as-code means the COMMITTED file tracks the registry — the
    generator only returns a string, so nothing else catches drift."""
    import os

    from ballista_tpu.config import generate_config_docs

    path = os.path.join(os.path.dirname(__file__), "..", "docs", "configs.md")
    with open(path) as f:
        on_disk = f.read()
    assert on_disk == generate_config_docs(), (
        "docs/configs.md is stale; regenerate with "
        "python -c \"from ballista_tpu.config import generate_config_docs; "
        "open('docs/configs.md','w').write(generate_config_docs())\"")


def test_hash_nullable_columns_match_clean_columns():
    """Wire contract under nulls: a nullable column's VALID slots must hash
    identically to the same values in a null-free column (and to the native
    C++ hasher). Regression for the float64 to_numpy round-trip that
    mis-hashed every row of nullable date32/bool columns and lost precision
    on nullable int64 > 2^53."""
    from ballista_tpu.ops import native

    big = 2**60 + 12345  # would corrupt through float64
    cases = [
        (pa.array([1, None, big, -7], pa.int64()),
         pa.array([1, 0, big, -7], pa.int64())),
        (pa.array([3, None, 20000], pa.int32()).cast(pa.date32()),
         pa.array([3, 0, 20000], pa.int32()).cast(pa.date32())),
        (pa.array([True, None, False], pa.bool_()),
         pa.array([True, False, False], pa.bool_())),
        (pa.array([1.5, None, -2.25], pa.float64()),
         pa.array([1.5, 0.0, -2.25], pa.float64())),
    ]
    for nullable, clean in cases:
        hn = hash_arrays([nullable])
        hc = hash_arrays([clean])
        valid = np.asarray(nullable.is_valid())
        assert (hn[valid] == hc[valid]).all(), nullable.type
        # null slots get the stable null tag, distinct from the filled value
        assert (hn[~valid] != hc[~valid]).all(), nullable.type
        nat = native.hash_arrays_native([nullable])
        if nat is not None:
            assert (hn == nat).all(), nullable.type


def test_hash_date64_columns():
    """date64 repartition keys must hash (ms-int64 direct cast) and agree
    with the equivalent date32 values where representable."""
    from ballista_tpu.ops import native

    ms = pa.array([86_400_000, None, 172_800_000], pa.int64()).cast(pa.date64())
    h = hash_arrays([ms])
    assert len(set(h.tolist())) == 3
    nat = native.hash_arrays_native([ms])
    if nat is not None:
        assert (h == nat).all()


def test_decimal_parquet_exact_policy(tmp_path):
    """decimal128 parquet (what the reference's TPC-H generators emit) and
    decimal arrow tables keep EXACT decimal semantics end-to-end: sums widen
    to decimal128(38,s) like DataFusion's, min/max preserve the input type,
    nulls flow, and no float rounding touches the money lane."""
    import decimal

    import pyarrow.parquet as pq

    from ballista_tpu.client.context import SessionContext

    D = decimal.Decimal
    tbl = pa.table({
        "g": pa.array(["a", "b", "a"]),
        "price": pa.array([D("10.25"), None, D("7.75")], pa.decimal128(15, 2)),
    })
    pq.write_table(tbl, tmp_path / "d.parquet")
    ctx = SessionContext()
    ctx.register_parquet("d", str(tmp_path / "d.parquet"))
    assert ctx.catalog.get("d").arrow_schema().field("price").type == pa.decimal128(15, 2)
    out = ctx.sql("SELECT sum(price) s, min(price) mn, count(price) c FROM d").collect()
    assert out.schema.field("s").type == pa.decimal128(38, 2)
    assert out.schema.field("mn").type == pa.decimal128(15, 2)
    r = out.to_pandas()
    assert r.s[0] == D("18.00") and r.mn[0] == D("7.75") and int(r.c[0]) == 2
    ctx.register_arrow_table("m", tbl)
    r2 = ctx.sql("SELECT g, sum(price) s FROM m GROUP BY g ORDER BY g").collect()
    assert r2.column("s").to_pylist() == [D("18.00"), None]
