"""TPU engine tests (run on jax CPU backend via conftest env).

- hash twin parity: jax hash64 must be bit-identical to the numpy hasher
  (the shuffle wire contract)
- TPC-H correctness with engine=tpu (device stages + per-subtree fallback)
- stage compilation actually happens for q1-shaped pipelines
"""

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import (
    BallistaConfig,
    EXECUTOR_ENGINE,
    TPU_MIN_ROWS,
)
from ballista_tpu.testing.reference import compare_results, run_reference

from .conftest import tpch_query


@pytest.fixture()
def tpu_ctx(tpch_dir):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
    ctx = SessionContext(cfg)
    register_tpch(ctx, tpch_dir)
    return ctx


def test_hash64_parity_with_numpy():
    from ballista_tpu.ops.hashing import splitmix64, hash_combine
    from ballista_tpu.ops.tpu.kernels import hash64, hash_combine_jax
    from ballista_tpu.ops.tpu.runtime import ensure_jax

    jax = ensure_jax()
    jnp = jax.numpy
    x = np.array([0, 1, 2, 12345678901234, 2**63 - 1], dtype=np.uint64)
    np_h = splitmix64(x)
    jax_h = np.asarray(hash64(jnp.asarray(x)))
    assert (np_h == jax_h).all()
    np_c = hash_combine(np_h, np_h[::-1].copy())
    jax_c = np.asarray(hash_combine_jax(jnp.asarray(np_h), jnp.asarray(np_h[::-1].copy())))
    assert (np_c == jax_c).all()


def test_q1_compiles_to_tpu_stage(tpu_ctx):
    df = tpu_ctx.sql(tpch_query(1))
    phys = tpu_ctx.create_physical_plan(df.plan)
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu

    compiled = maybe_compile_tpu(phys, tpu_ctx.config)
    assert "TpuStageExec" in compiled.display()


@pytest.mark.parametrize("q", [1, 3, 5, 6, 10, 12, 14, 18, 19])
def test_tpch_tpu_engine(q, tpu_ctx, tpch_ref_tables):
    eng = tpu_ctx.sql(tpch_query(q)).collect()
    ref = run_reference(q, tpch_ref_tables)
    problems = compare_results(eng, ref, q)
    assert not problems, "\n".join(problems)


def test_large_domain_groupby_on_device(tpu_ctx):
    """q3's group-by (l_orderkey × build-side keys — thousands of groups)
    must take the sort-based segmented-reduction path, not fall back."""
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.plan.physical import TaskContext

    phys = maybe_compile_tpu(
        tpu_ctx.create_physical_plan(tpu_ctx.sql(tpch_query(3)).plan), tpu_ctx.config
    )
    stages = [n for n in _walk(phys) if isinstance(n, sc.TpuStageExec)]
    assert stages
    ctx = TaskContext(tpu_ctx.config)
    for p in range(phys.output_partition_count()):
        list(phys.execute(p, ctx))
    assert sum(s.tpu_count for s in stages) >= 1
    assert sum(s.fallback_count for s in stages) == 0


def test_sorted_path_min_max_sum_count_oracle():
    """Synthetic large-domain aggregation: every agg func through the
    sorted path must match pandas (int money math exact, f64 sums via the
    segmented scan) — and must actually run on the device path."""
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.plan.physical import TaskContext

    rng = np.random.default_rng(7)
    n = 20_000
    tbl = pa.table({
        "k": rng.integers(0, 3000, n),
        "price": np.round(rng.uniform(1, 100, n), 2),   # money (int64 cents)
        "weight": rng.uniform(0.0, 1.0, n),              # true f64
        "qty": rng.integers(1, 50, n),
    })
    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
    ctx = SessionContext(cfg)
    ctx.register_arrow_table("t", tbl, partitions=4)
    sql = (
        "SELECT k, sum(price) AS s, sum(weight) AS w, count(*) AS c, "
        "min(qty) AS mn, max(qty) AS mx FROM t WHERE qty > 5 GROUP BY k ORDER BY k"
    )
    out = ctx.sql(sql).collect().to_pandas()
    df = tbl.to_pandas()
    df = df[df.qty > 5]
    g = (
        df.groupby("k")
        .agg(s=("price", "sum"), w=("weight", "sum"), c=("price", "size"),
             mn=("qty", "min"), mx=("qty", "max"))
        .reset_index()
        .sort_values("k")
        .reset_index(drop=True)
    )
    assert len(out) == len(g)
    assert (out.k.values == g.k.values).all()
    assert np.allclose(out.s.values, g.s.values, atol=1e-9)
    assert np.allclose(out.w.values, g.w.values, rtol=1e-12)
    assert (out.c.values == g.c.values).all()
    assert (out.mn.values == g.mn.values).all()
    assert (out.mx.values == g.mx.values).all()

    # the oracle match must come from the DEVICE path, not a silent fallback
    phys = maybe_compile_tpu(ctx.create_physical_plan(ctx.sql(sql).plan), cfg)
    stages = [nd for nd in _walk(phys) if isinstance(nd, sc.TpuStageExec)]
    assert stages
    tc = TaskContext(cfg)
    for p in range(phys.output_partition_count()):
        list(phys.execute(p, tc))
    assert sum(s.tpu_count for s in stages) >= 1
    assert sum(s.fallback_count for s in stages) == 0


def test_tpu_stage_actually_ran(tpu_ctx):
    """The q1 pipeline must execute on the device path, not fall back."""
    import ballista_tpu.ops.tpu.stage_compiler as sc

    df = tpu_ctx.sql(tpch_query(1))
    phys = tpu_ctx.create_physical_plan(df.plan)
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.plan.physical import TaskContext

    compiled = maybe_compile_tpu(phys, tpu_ctx.config)
    stages = [n for n in _walk(compiled) if isinstance(n, sc.TpuStageExec)]
    assert stages
    ctx = TaskContext(tpu_ctx.config)
    for p in range(compiled.output_partition_count()):
        list(compiled.execute(p, ctx))
    assert stages[0].tpu_count >= 1
    assert stages[0].fallback_count == 0


def test_q5_join_pipeline_on_device(tpu_ctx, tpch_ref_tables):
    """q5's 4-join probe chain must compile and run on the device path."""
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.plan.physical import TaskContext

    phys = maybe_compile_tpu(
        tpu_ctx.create_physical_plan(tpu_ctx.sql(tpch_query(5)).plan), tpu_ctx.config
    )
    stages = [n for n in _walk(phys) if isinstance(n, sc.TpuStageExec)]
    assert stages
    joins = [op for s in stages for op in s.ops if type(op).__name__ == "HashJoinExec"]
    assert len(joins) >= 3
    ctx = TaskContext(tpu_ctx.config)
    for p in range(phys.output_partition_count()):
        list(phys.execute(p, ctx))
    assert sum(s.tpu_count for s in stages) >= 1
    assert sum(s.fallback_count for s in stages) == 0


def test_expansion_join_on_device(tpu_ctx, tpch_ref_tables):
    """q12's build side (filtered lineitem) has duplicate join keys: the
    expansion-join lanes must keep it on the device path, correctly."""
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.plan.physical import TaskContext

    eng = tpu_ctx.sql(tpch_query(12)).collect()
    problems = compare_results(eng, run_reference(12, tpch_ref_tables), 12)
    assert not problems, "\n".join(problems)

    phys = maybe_compile_tpu(
        tpu_ctx.create_physical_plan(tpu_ctx.sql(tpch_query(12)).plan), tpu_ctx.config
    )
    stages = [n for n in _walk(phys) if isinstance(n, sc.TpuStageExec)]
    assert stages
    ctx = TaskContext(tpu_ctx.config)
    for p in range(phys.output_partition_count()):
        list(phys.execute(p, ctx))
    assert sum(s.tpu_count for s in stages) >= 1
    assert sum(s.fallback_count for s in stages) == 0


def test_expansion_join_with_large_domain_groupby():
    """Duplicate build keys AND a large int group domain: expansion lanes
    concatenate into the sorted segmented reduction. Oracle = pandas."""
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.plan.physical import TaskContext

    rng = np.random.default_rng(11)
    n_fact, n_dim = 30_000, 2_000
    fact = pa.table({
        "fk": rng.integers(0, 500, n_fact),     # join key (dense)
        "gk": rng.integers(0, 4000, n_fact),    # large group domain
        "v": rng.integers(1, 100, n_fact),
    })
    dim = pa.table({
        "dk": rng.integers(0, 500, n_dim),      # ~4 dups per key
        "w": rng.integers(1, 10, n_dim),
    })
    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
    ctx = SessionContext(cfg)
    ctx.register_arrow_table("fact", fact, partitions=4)
    ctx.register_arrow_table("dim", dim, partitions=1)
    sql = (
        "SELECT gk, sum(v * w) AS s, count(*) AS c FROM fact, dim "
        "WHERE fk = dk GROUP BY gk ORDER BY gk"
    )
    out = ctx.sql(sql).collect().to_pandas()
    df = fact.to_pandas().merge(dim.to_pandas(), left_on="fk", right_on="dk")
    df["p"] = df.v * df.w
    g = (
        df.groupby("gk").agg(s=("p", "sum"), c=("p", "size"))
        .reset_index().sort_values("gk").reset_index(drop=True)
    )
    assert len(out) == len(g)
    assert (out.gk.values == g.gk.values).all()
    assert (out.s.values == g.s.values).all()
    assert (out.c.values == g.c.values).all()

    phys = maybe_compile_tpu(ctx.create_physical_plan(ctx.sql(sql).plan), cfg)
    stages = [nd for nd in _walk(phys) if isinstance(nd, sc.TpuStageExec)]
    if stages:  # planner may pick partitioned mode; if collect_left, no fallback
        tc = TaskContext(cfg)
        for p in range(phys.output_partition_count()):
            list(phys.execute(p, tc))
        assert sum(s.fallback_count for s in stages) == 0


def test_collective_exchange_mesh_execution(tpch_dir, tpch_ref_tables):
    """ballista.tpu.collective.exchange: the stage's device table shards by
    partition across the (virtual 8-device) mesh and GSPMD inserts the
    collectives — results identical to single-device and the CPU oracle."""
    import jax

    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import TPU_COLLECTIVE_EXCHANGE
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.plan.physical import TaskContext
    from ballista_tpu.testing.tpchgen import register_tpch

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device backend")
    cfg = BallistaConfig({
        EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0, TPU_COLLECTIVE_EXCHANGE: True,
    })
    ctx = SessionContext(cfg)
    register_tpch(ctx, tpch_dir)
    # q1: unrolled path; q3: sorted path with a join — both through the mesh
    for q in (1, 3):
        eng = ctx.sql(tpch_query(q)).collect()
        problems = compare_results(eng, run_reference(q, tpch_ref_tables), q)
        assert not problems, "\n".join(problems)

    phys = maybe_compile_tpu(ctx.create_physical_plan(ctx.sql(tpch_query(1)).plan), cfg)
    stages = [n for n in _walk(phys) if isinstance(n, sc.TpuStageExec)]
    assert stages
    tc = TaskContext(cfg)
    for p in range(phys.output_partition_count()):
        list(phys.execute(p, tc))
    assert stages[0].tpu_count >= 1 and stages[0].fallback_count == 0
    # the cached device table must actually be sharded across the mesh
    sharded = [
        dt for key, dt in sc.DEVICE_CACHE._cache.items()
        if any(len(c.sharding.device_set) == len(jax.devices()) for c in dt.cols)
    ]
    assert sharded, "no mesh-sharded device table in cache"


def test_money_encoding_exact():
    from ballista_tpu.ops.tpu.columnar import encode_column

    vals = pa.array([1.01, 2.50, 999999.99, 0.0])
    dc = encode_column(vals)
    assert dc.kind == "money"
    assert dc.scale == 2
    assert list(np.asarray(dc.data, dtype=np.int64)) == [101, 250, 99999999, 0]
    # non-fixed-point floats stay f64
    dc2 = encode_column(pa.array([1.001, 2.5]))
    assert dc2.kind == "f64"


def _walk(node):
    yield node
    for c in node.children():
        yield from _walk(c)


def test_pallas_fused_aggregation_path():
    """ballista.tpu.pallas.enabled: float sums + counts route through the
    fused Pallas masked-group-reduction kernel (interpret mode on CPU) and
    match pandas; exact int64 money stays on XLA and stays correct."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import TPU_PALLAS

    rng = np.random.default_rng(21)
    n = 30_000
    tbl = pa.table({
        "cat": rng.choice(["a", "b", "c", "d", "e"], n),
        "w": rng.uniform(0.0, 10.0, n),        # true f64 → pallas path
        "qty": rng.integers(1, 50, n),
    })
    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0, TPU_PALLAS: True})
    ctx = SessionContext(cfg)
    ctx.register_arrow_table("t", tbl, partitions=4)
    sql = "select cat, sum(w) s, count(*) c from t where qty > 10 group by cat order by cat"
    out = ctx.sql(sql).collect().to_pandas()
    df = tbl.to_pandas()
    df = df[df.qty > 10]
    g = df.groupby("cat", as_index=False).agg(s=("w", "sum"), c=("w", "size")).sort_values("cat")
    assert out.cat.tolist() == g.cat.tolist()
    assert (out.c.values == g.c.values).all()
    # f32 kernel accumulation: tolerance scaled to the sums
    assert np.allclose(out.s.values, g.s.values, rtol=2e-5)

    # device path ran, no fallback
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.plan.physical import TaskContext

    phys = maybe_compile_tpu(ctx.create_physical_plan(ctx.sql(sql).plan), cfg)
    stages = [nd for nd in _walk(phys) if isinstance(nd, sc.TpuStageExec)]
    assert stages
    tc = TaskContext(cfg)
    for p in range(phys.output_partition_count()):
        list(phys.execute(p, tc))
    assert sum(s.tpu_count for s in stages) >= 1
    assert sum(s.fallback_count for s in stages) == 0


def test_device_side_shuffle_routing(tmp_path):
    """ROADMAP device-side shuffle write: the sorted path emits a __pid
    column (bit-exact hash twin), the shuffle writer consumes it instead of
    host hashing, and written buckets match host routing exactly."""
    import glob
    import json

    import pyarrow.ipc as ipc
    import pyarrow.parquet as pq

    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.ops.hashing import partition_indices
    from ballista_tpu.plan.physical import TaskContext
    from ballista_tpu.scheduler.planner import DistributedPlanner
    from ballista_tpu.shuffle import paths as sp

    rng = np.random.default_rng(5)
    n = 30_000
    pq.write_table(pa.table({
        "k": rng.integers(0, 5000, n),
        "v": rng.integers(1, 100, n),
    }), str(tmp_path / "t.parquet"))
    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
    ctx = SessionContext(cfg)
    ctx.register_parquet("t", str(tmp_path / "t.parquet"))
    sql = "select k, sum(v) s from t where v > 10 group by k"
    phys = ctx.create_physical_plan(ctx.sql(sql).plan)
    stages = DistributedPlanner("jpid").plan_query_stages(phys)
    stage1 = stages[0]
    compiled = maybe_compile_tpu(stage1.plan, cfg)
    tpu = [nd for nd in _walk(compiled) if isinstance(nd, sc.TpuStageExec)]
    assert tpu and tpu[0].emit_pid is not None

    work = str(tmp_path / "work")
    tc = TaskContext(cfg, task_id="t0", work_dir=work)
    for p in range(stage1.partitions):
        list(compiled.execute(p, tc))
    assert tpu[0].pid_emitted >= 1
    assert tpu[0].fallback_count == 0

    checked = 0
    for f in glob.glob(f"{work}/jpid/1/*.arrow"):
        idx = json.load(open(sp.index_path(f)))
        for pid_s, entry in idx.items():
            off, length = entry[0], entry[1]
            with open(f, "rb") as fh:
                fh.seek(off)
                buf = fh.read(length)
            tblx = ipc.open_stream(pa.BufferReader(buf)).read_all()
            assert "__pid" not in tblx.column_names
            if tblx.num_rows:
                host = partition_indices(
                    [tblx.column("k").combine_chunks()], stage1.output_partitions
                )
                assert (host == int(pid_s)).all()
                checked += 1
    assert checked > 0


def test_q22_string_fn_filter_on_device(tpu_ctx, tpch_ref_tables):
    """substring(c_phone,..) IN (...) composes into the dictionary LUT:
    q22's scalar-subquery stage runs on device with a correct result."""
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.plan.physical import TaskContext

    eng = tpu_ctx.sql(tpch_query(22)).collect()
    problems = compare_results(eng, run_reference(22, tpch_ref_tables), 22)
    assert not problems, "\n".join(problems)

    phys = maybe_compile_tpu(
        tpu_ctx.create_physical_plan(tpu_ctx.sql(tpch_query(22)).plan), tpu_ctx.config
    )
    stages = [n for n in _walk(phys) if isinstance(n, sc.TpuStageExec)]
    assert stages
    ctx = TaskContext(tpu_ctx.config)
    for p in range(phys.output_partition_count()):
        list(phys.execute(p, ctx))
    assert sum(s.tpu_count for s in stages) >= 1
    assert sum(s.fallback_count for s in stages) == 0


def test_semi_and_anti_joins_on_device(tmp_path):
    """IN / NOT IN subqueries (decorrelated to right_semi / right_anti
    collect_left joins) run on device: the probe's match mask is the
    filter — no build gathers, no expansion lanes, duplicate membership
    keys fine."""
    import pyarrow.parquet as pq

    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.plan.physical import TaskContext

    rng = np.random.default_rng(8)
    n = 30_000
    pq.write_table(pa.table({
        "k": rng.integers(0, 5000, n), "g": rng.choice(["a", "b", "c"], n),
        "v": rng.integers(1, 100, n),
    }), str(tmp_path / "fact.parquet"))
    # duplicate count 20 > MAX_JOIN_DUP: membership joins must not trip the
    # expansion-lane cap (semi/anti never unroll lanes)
    pq.write_table(
        pa.table({"mk": np.repeat(rng.choice(5000, 800, replace=False), 20)}),
        str(tmp_path / "member.parquet"),
    )
    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
    ctx = SessionContext(cfg)
    ctx.register_parquet("fact", str(tmp_path / "fact.parquet"))
    ctx.register_parquet("member", str(tmp_path / "member.parquet"))
    f = pq.read_table(str(tmp_path / "fact.parquet")).to_pandas()
    m = set(pq.read_table(str(tmp_path / "member.parquet")).to_pandas().mk)
    for sql, sel in [
        ("select g, sum(v) s, count(*) c from fact where k in (select mk from member) "
         "group by g order by g", f[f.k.isin(m)]),
        ("select g, sum(v) s, count(*) c from fact where k not in (select mk from member) "
         "group by g order by g", f[~f.k.isin(m)]),
    ]:
        out = ctx.sql(sql).collect().to_pandas()
        g = sel.groupby("g").agg(s=("v", "sum"), c=("v", "size")).reset_index().sort_values("g")
        assert out.s.tolist() == g.s.tolist()
        assert out.c.tolist() == g.c.tolist()
        phys = maybe_compile_tpu(ctx.create_physical_plan(ctx.sql(sql).plan), cfg)
        stages = [nd for nd in _walk(phys) if isinstance(nd, sc.TpuStageExec)]
        assert stages
        tc = TaskContext(cfg)
        for p in range(phys.output_partition_count()):
            list(phys.execute(p, tc))
        assert sum(s.tpu_count for s in stages) >= 1
        assert sum(s.fallback_count for s in stages) == 0


def test_explain_analyze_shows_device_counters(tpu_ctx):
    """EXPLAIN ANALYZE with engine=tpu analyzes the COMPILED tree: the
    TpuStageExec appears with its device/fallback counters."""
    out = tpu_ctx.sql("explain analyze " + tpch_query(6)).collect().to_pandas()
    body = out[out.plan_type.str.startswith("analyzed")].plan.iloc[0]
    assert "TpuStageExec" in body
    assert "device_runs=1" in body and "cpu_fallbacks=0" in body


# -- NULL-bearing data on the device path (validity planes) -----------------


def _device_oracle(sql: str, tables: dict, cfg_extra=None, expect_device=True):
    """Run `sql` on the tpu engine over `tables`, assert the device path
    actually executed (no silent fallback), and return the result alongside
    the cpu engine's answer for the same query."""
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.plan.physical import TaskContext

    results = {}
    for engine in ("tpu", "cpu"):
        cfg = BallistaConfig({EXECUTOR_ENGINE: engine, TPU_MIN_ROWS: 0,
                              **(cfg_extra or {})})
        ctx = SessionContext(cfg)
        for name, tbl in tables.items():
            ctx.register_arrow_table(name, tbl, partitions=2)
        results[engine] = ctx.sql(sql).collect()
        if engine == "tpu" and expect_device:
            phys = maybe_compile_tpu(ctx.create_physical_plan(ctx.sql(sql).plan), cfg)
            stages = [nd for nd in _walk(phys) if isinstance(nd, sc.TpuStageExec)]
            assert stages, "no device stage compiled"
            tc = TaskContext(cfg)
            for p in range(phys.output_partition_count()):
                list(phys.execute(p, tc))
            assert sum(s.tpu_count for s in stages) >= 1
            assert sum(s.fallback_count for s in stages) == 0, "silent cpu fallback"
    return results["tpu"], results["cpu"]


def _null_table(n=8000, seed=11):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 50, n).astype("int64")
    price = np.round(rng.uniform(1, 100, n), 2)
    qty = rng.integers(1, 50, n).astype("int64")
    flag = rng.integers(0, 2, n).astype(bool)
    null_price = rng.random(n) < 0.3
    null_qty = rng.random(n) < 0.2
    null_k = rng.random(n) < 0.1
    return pa.table({
        "k": pa.array(k, pa.int64()).to_pandas().where(~null_k).astype("Int64").to_numpy(
            dtype=object, na_value=None),
        "price": pa.array(np.where(null_price, np.nan, price)).to_pandas().where(
            ~null_price).to_numpy(dtype=object, na_value=None),
        "qty": pa.array(qty).to_pandas().where(~null_qty).astype("Int64").to_numpy(
            dtype=object, na_value=None),
        "flag": flag,
    })


def test_nullable_filter_and_aggs_on_device():
    """Filters + sum/min/max/count over NULL-bearing columns stay on device
    and agree with the CPU engine (null-strict comparisons, count(x) skips
    nulls, WHERE treats unknown as false)."""
    tbl = _null_table()
    sql = ("SELECT count(*) AS c_all, count(qty) AS c_qty, sum(price) AS s, "
           "min(qty) AS mn, max(qty) AS mx FROM t WHERE price > 10")
    tpu, cpu = _device_oracle(sql, {"t": tbl})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert tp.c_all[0] == cp.c_all[0]
    assert tp.c_qty[0] == cp.c_qty[0]
    assert abs(tp.s[0] - cp.s[0]) < 1e-6
    assert tp.mn[0] == cp.mn[0] and tp.mx[0] == cp.mx[0]


def test_nullable_group_key_on_device():
    """A nullable GROUP BY key: NULL forms its own group (sorted path's
    null-marker sort operand), matching the CPU engine."""
    tbl = _null_table()
    sql = ("SELECT k, count(*) AS c, sum(price) AS s FROM t "
           "WHERE qty >= 1 GROUP BY k ORDER BY k NULLS LAST")
    tpu, cpu = _device_oracle(sql, {"t": tbl})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert len(tp) == len(cp)
    # align on key (None sorts last in both by the ORDER BY)
    assert tp.k.isna().tolist() == cp.k.isna().tolist()
    assert tp.k.fillna(-1).tolist() == cp.k.fillna(-1).tolist()
    assert (tp.c.values == cp.c.values).all()
    assert np.allclose(tp.s.fillna(-1).values, cp.s.fillna(-1).values, atol=1e-6)


def test_is_null_predicates_on_device():
    tbl = _null_table()
    sql = ("SELECT count(*) AS c FROM t WHERE qty IS NULL AND price IS NOT NULL")
    tpu, cpu = _device_oracle(sql, {"t": tbl})
    assert tpu.to_pandas().c[0] == cpu.to_pandas().c[0]


def test_all_null_group_aggregates_to_null_on_device():
    """A group whose agg inputs are all NULL yields NULL (not 0 / ±inf) —
    the valid-count companion outputs."""
    tbl = pa.table({
        "g": pa.array([1, 1, 2, 2, 3], pa.int64()),
        "v": pa.array([None, None, 5.25, 7.75, None], pa.float64()),
        "q": pa.array([None, None, 4, 2, 9], pa.int64()),
    })
    sql = ("SELECT g, sum(v) AS s, min(q) AS mn, max(q) AS mx, count(q) AS c "
           "FROM t GROUP BY g ORDER BY g")
    tpu, cpu = _device_oracle(sql, {"t": tbl})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert tp.s.isna().tolist() == cp.s.isna().tolist() == [True, False, True]
    assert tp.mn.isna().tolist() == cp.mn.isna().tolist() == [True, False, False]
    assert float(tp.s[1]) == 13.0
    assert int(tp.mn[1]) == 2 and int(tp.mx[1]) == 4
    assert int(tp.mn[2]) == 9
    assert tp.c.tolist() == cp.c.tolist() == [0, 2, 1]


def test_nullable_probe_key_join_on_device():
    """Inner join whose probe key has NULLs: null keys match nothing."""
    rng = np.random.default_rng(5)
    n = 4000
    key = rng.integers(0, 100, n).astype("int64")
    null_key = rng.random(n) < 0.25
    probe = pa.table({
        "fk": pa.array([None if m else int(v) for v, m in zip(key, null_key)], pa.int64()),
        "amt": np.round(rng.uniform(1, 10, n), 2),
    })
    build = pa.table({
        "id": pa.array(np.arange(100), pa.int64()),
        "cat": pa.array([f"c{i % 5}" for i in range(100)]),
    })
    sql = ("SELECT cat, count(*) AS c, sum(amt) AS s FROM probe "
           "JOIN build ON fk = id GROUP BY cat ORDER BY cat")
    tpu, cpu = _device_oracle(sql, {"probe": probe, "build": build})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert tp.cat.tolist() == cp.cat.tolist()
    assert tp.c.tolist() == cp.c.tolist()
    assert np.allclose(tp.s.values, cp.s.values, atol=1e-6)


def test_right_outer_join_on_device():
    """Right outer join (emit every probe row; NULL build columns on miss)
    through the device chain: unmatched rows ride lane 0 with invalid
    gathers, count(build_col) skips them."""
    rng = np.random.default_rng(3)
    n = 6000
    probe = pa.table({
        "ck": rng.integers(0, 200, n).astype("int64"),   # some keys miss
        "amt": np.round(rng.uniform(1, 10, n), 2),
    })
    build = pa.table({
        "id": pa.array(np.arange(0, 120), pa.int64()),   # ids 120..199 unmatched
        "grp": pa.array([f"g{i % 4}" for i in range(120)]),
        "w": pa.array(np.arange(0, 120).astype("float64") / 2),
    })
    sql = ("SELECT ck, count(w) AS cw, count(*) AS c, sum(amt) AS s "
           "FROM build RIGHT JOIN probe ON id = ck GROUP BY ck ORDER BY ck")
    tpu, cpu = _device_oracle(sql, {"probe": probe, "build": build})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert tp.ck.tolist() == cp.ck.tolist()
    assert tp.cw.tolist() == cp.cw.tolist()
    assert tp.c.tolist() == cp.c.tolist()
    assert np.allclose(tp.s.values, cp.s.values, atol=1e-6)
    # sanity: the miss range exists, so count(w) < count(*) somewhere
    assert (tp.cw.values < tp.c.values).any()


def test_filtered_semi_anti_join_on_device():
    """EXISTS / NOT EXISTS with a correlated residual predicate (the q21
    shape: l2.l_suppkey <> l1.l_suppkey) lowers to an OR across build match
    lanes on device."""
    rng = np.random.default_rng(9)
    n = 5000
    t1 = pa.table({
        "ok": rng.integers(0, 400, n).astype("int64"),
        "sk": rng.integers(0, 10, n).astype("int64"),
        "v": np.round(rng.uniform(1, 5, n), 2),
    })
    m = 2000
    t2 = pa.table({
        "ok2": rng.integers(0, 400, m).astype("int64"),
        "sk2": rng.integers(0, 10, m).astype("int64"),
    })
    for kw in ("EXISTS", "NOT EXISTS"):
        sql = (f"SELECT sk, count(*) AS c, sum(v) AS s FROM t1 WHERE {kw} "
               f"(SELECT 1 FROM t2 WHERE ok2 = ok AND sk2 <> sk) "
               f"GROUP BY sk ORDER BY sk")
        tpu, cpu = _device_oracle(sql, {"t1": t1, "t2": t2})
        tp, cp = tpu.to_pandas(), cpu.to_pandas()
        assert tp.sk.tolist() == cp.sk.tolist(), kw
        assert tp.c.tolist() == cp.c.tolist(), kw
        assert np.allclose(tp.s.values, cp.s.values, atol=1e-6), kw


def test_aggregate_through_join_multiplicity():
    """count(build_col) through a dup≫16 expansion join uses match-count
    gathers (no lane unrolling, no MAX_JOIN_DUP ceiling) — the q13 shape."""
    rng = np.random.default_rng(21)
    n = 3000
    build = pa.table({
        "fk": rng.integers(0, 60, n).astype("int64"),  # up to ~70 dups per key
        "bid": pa.array(np.arange(n), pa.int64()),
    })
    probe = pa.table({
        "id": pa.array(np.arange(80), pa.int64()),     # ids 60..79 unmatched
        "grp": pa.array([i % 7 for i in range(80)], pa.int64()),
    })
    for jt, sqljoin in (("inner", "JOIN"), ("outer", "RIGHT JOIN")):
        sql = (f"SELECT grp, count(bid) AS cb, count(*) AS c FROM build "
               f"{sqljoin} probe ON fk = id GROUP BY grp ORDER BY grp")
        tpu, cpu = _device_oracle(sql, {"probe": probe, "build": build})
        tp, cp = tpu.to_pandas(), cpu.to_pandas()
        assert tp.grp.tolist() == cp.grp.tolist(), jt
        assert tp.cb.tolist() == cp.cb.tolist(), jt
        assert tp.c.tolist() == cp.c.tolist(), jt


@pytest.fixture(scope="module")
def tpch_mid_dir(tmp_path_factory):
    """SF0.05: large enough that no filtered build side is empty (at SF0.01
    the q16/q18 subquery builds vanish and adaptively fall back — correct,
    but it would mask real device-coverage regressions)."""
    from ballista_tpu.testing.tpchgen import generate_tpch

    d = tmp_path_factory.mktemp("tpch-mid") / "sf005"
    # seed 1: every correlated-subquery build side (q16 complaint suppliers,
    # q18 big-quantity orders) is non-empty at this scale
    generate_tpch(str(d), scale=0.05, seed=1, files_per_table=2)
    return str(d)


# (n partial device stages, n final/sort device stages) per query — exact
# pins so a silent coverage regression in EITHER stage class fails loudly.
# q6/q14/q17/q19 are global (no-GROUP-BY) aggregations: their final merge
# is a handful of rows, left on CPU by design.
TPCH_DEVICE_STAGE_PINS = {
    1: (1, 1), 2: (1, 1), 3: (1, 1), 4: (1, 1), 5: (1, 1), 6: (1, 0),
    7: (1, 1), 8: (1, 1), 9: (1, 1), 10: (1, 1), 11: (2, 1), 12: (1, 1),
    13: (1, 2), 14: (1, 0), 15: (2, 2), 16: (1, 2), 17: (1, 0), 18: (1, 1),
    19: (1, 0), 20: (1, 1), 21: (1, 1), 22: (1, 1),
}


def test_all_22_tpch_queries_run_device_stages(tpch_mid_dir):
    """Coverage pin: every TPC-H query compiles its pinned number of device
    stages (partial-agg chains AND final-agg/sort stages) and runs them all
    with ZERO cpu fallbacks (VERDICT round-2 item #2's done criterion:
    counts must not regress, not just ≥1)."""
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.engine.tpu_engine import maybe_compile_tpu
    from ballista_tpu.ops.tpu.final_stage import TpuFinalStageExec
    from ballista_tpu.plan.physical import TaskContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
    tpu_ctx = SessionContext(cfg)
    register_tpch(tpu_ctx, tpch_mid_dir)
    bad = []
    for q in range(1, 23):
        sql = tpch_query(q)
        phys = maybe_compile_tpu(
            tpu_ctx.create_physical_plan(tpu_ctx.sql(sql).plan), cfg)
        partial = [nd for nd in _walk(phys) if isinstance(nd, sc.TpuStageExec)]
        final = [nd for nd in _walk(phys) if isinstance(nd, TpuFinalStageExec)]
        want = TPCH_DEVICE_STAGE_PINS[q]
        if (len(partial), len(final)) != want:
            bad.append((q, f"stages=({len(partial)},{len(final)}) want {want}"))
            continue
        tc = TaskContext(cfg)
        for p in range(phys.output_partition_count()):
            list(phys.execute(p, tc))
        runs = sum(s.tpu_count for s in partial) + sum(s.tpu_count for s in final)
        fb = sum(s.fallback_count for s in partial) + sum(s.fallback_count for s in final)
        if runs != len(partial) + len(final) or fb:
            bad.append((q, f"runs={runs}/{len(partial) + len(final)} fallbacks={fb}"))
    assert not bad, bad


def test_variance_on_device_sorted_path():
    """var/stddev partials (Welford (cnt, mean, M2) triple) computed on
    device via the sorted segmented two-pass, including an all-NULL group
    and the n<2 sample-variance guard — vs the CPU engine."""
    rng = np.random.default_rng(17)
    n = 6000
    g = rng.integers(0, 40, n).astype("int64")
    v = np.round(rng.normal(1000.0, 25.0, n), 4)
    null_v = rng.random(n) < 0.25
    # group 39: all inputs NULL; group 38: exactly one non-null row
    null_v[g == 39] = True
    one = np.nonzero(g == 38)[0]
    null_v[one] = True
    null_v[one[0]] = False
    tbl = pa.table({
        "g": pa.array(g, pa.int64()),
        "v": pa.array(v, pa.float64(), mask=null_v),
    })
    sql = ("SELECT g, var_samp(v) AS vs, var_pop(v) AS vp, "
           "stddev(v) AS sd, count(v) AS c FROM t GROUP BY g ORDER BY g")
    tpu, cpu = _device_oracle(sql, {"t": tbl})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert tp.g.tolist() == cp.g.tolist()
    assert tp.c.tolist() == cp.c.tolist()
    # group 39 (no inputs): NULL everywhere; group 38 (n=1): samp NULL, pop 0
    assert tp.vs.isna().tolist() == cp.vs.isna().tolist()
    assert tp.vp.isna().tolist() == cp.vp.isna().tolist()
    assert np.allclose(tp.vs.fillna(0).values, cp.vs.fillna(0).values,
                       rtol=1e-9, atol=1e-9)
    assert np.allclose(tp.vp.fillna(0).values, cp.vp.fillna(0).values,
                       rtol=1e-9, atol=1e-9)
    assert np.allclose(tp.sd.fillna(0).values, cp.sd.fillna(0).values,
                       rtol=1e-9, atol=1e-9)


def test_variance_on_device_unrolled_path():
    """Variance over a low-cardinality dictionary group key rides the
    unrolled masked-reduction path (two fused passes, no sort)."""
    rng = np.random.default_rng(23)
    n = 8000
    cat = rng.integers(0, 4, n)
    # large offset stresses the centered form: naive sum-of-squares loses
    # all significant digits at 1e8 magnitude with unit variance
    v = 1.0e8 + rng.normal(0.0, 1.0, n)
    tbl = pa.table({
        "cat": pa.array([f"c{i}" for i in cat]),
        "v": pa.array(v, pa.float64()),
    })
    sql = ("SELECT cat, stddev_samp(v) AS sd, var_pop(v) AS vp "
           "FROM t GROUP BY cat ORDER BY cat")
    tpu, cpu = _device_oracle(sql, {"t": tbl})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert tp.cat.tolist() == cp.cat.tolist()
    assert np.allclose(tp.sd.values, cp.sd.values, rtol=1e-6)
    assert np.allclose(tp.vp.values, cp.vp.values, rtol=1e-6)
    # the data really does have ~unit stddev — catastrophic cancellation
    # would produce 0 or wild values here
    assert (np.abs(tp.sd.values - 1.0) < 0.1).all()


def test_variance_global_no_groups_on_device():
    rng = np.random.default_rng(29)
    v = rng.normal(50.0, 7.0, 5000)
    tbl = pa.table({"v": pa.array(v, pa.float64())})
    sql = "SELECT var_samp(v) AS vs, stddev_pop(v) AS sp, avg(v) AS m FROM t"
    tpu, cpu = _device_oracle(sql, {"t": tbl})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert np.allclose(tp.vs[0], cp.vs[0], rtol=1e-9)
    assert np.allclose(tp.sp[0], cp.sp[0], rtol=1e-9)
    assert np.allclose(tp.m[0], cp.m[0], rtol=1e-12)


def test_same_shape_stages_with_different_builds_do_not_collide():
    """Two stages identical except for the FILTER on a join's build side
    (TPC-DS q39's d_moy=1 vs d_moy=2 date_dim sides) must not share build
    tables: the stage fingerprint carries the full build subtree."""
    rng = np.random.default_rng(41)
    n = 5000
    fact = pa.table({
        "fk": rng.integers(0, 200, n).astype("int64"),
        "v": rng.integers(0, 100, n).astype("int64"),
    })
    dim = pa.table({
        "id": pa.array(np.arange(200), pa.int64()),
        "moy": pa.array((np.arange(200) % 12) + 1, pa.int64()),
    })
    t1 = "SELECT count(*) c, sum(v) s FROM fact JOIN dim ON fk = id WHERE moy = 1"
    t2 = "SELECT count(*) c, sum(v) s FROM fact JOIN dim ON fk = id WHERE moy = 2"
    tpu1, cpu1 = _device_oracle(t1, {"fact": fact, "dim": dim})
    tpu2, cpu2 = _device_oracle(t2, {"fact": fact, "dim": dim})
    p1, p2 = tpu1.to_pandas(), tpu2.to_pandas()
    assert p1.c[0] == cpu1.to_pandas().c[0]
    assert p2.c[0] == cpu2.to_pandas().c[0]
    assert (p1.c[0], p1.s[0]) != (p2.c[0], p2.s[0])


def test_union_pushdown_device_stages():
    """Partial aggregation over a UNION (TPC-DS cross-channel shapes)
    pushes through the union so each branch runs a device stage; results
    match the CPU engine."""
    rng = np.random.default_rng(43)
    a = pa.table({
        "g": pa.array([f"g{i%5}" for i in rng.integers(0, 5, 4000)]),
        "v": rng.integers(0, 50, 4000).astype("int64"),
    })
    b = pa.table({
        "g": pa.array([f"g{i%5}" for i in rng.integers(0, 5, 3000)]),
        "v": rng.integers(50, 99, 3000).astype("int64"),
    })
    sql = ("SELECT g, count(*) c, sum(v) s FROM "
           "(SELECT g, v FROM a UNION ALL SELECT g, v FROM b) u "
           "GROUP BY g ORDER BY g")
    tpu, cpu = _device_oracle(sql, {"a": a, "b": b})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert tp.g.tolist() == cp.g.tolist()
    assert tp.c.tolist() == cp.c.tolist()
    assert tp.s.tolist() == cp.s.tolist()


def test_expression_group_key_hoisted_to_device():
    """Group keys that are single-column expressions (q62/q99's substr)
    hoist: the device groups by the raw column, a CPU projection applies
    the expression over the few partial rows, the final agg merges."""
    rng = np.random.default_rng(47)
    n = 6000
    names = [f"warehouse-{i:02d}-site" for i in range(30)]
    tbl = pa.table({
        "w": pa.array([names[i] for i in rng.integers(0, 30, n)]),
        "v": rng.integers(0, 100, n).astype("int64"),
    })
    sql = ("SELECT substr(w, 1, 11) wk, count(*) c, sum(v) s "
           "FROM t GROUP BY substr(w, 1, 11) ORDER BY wk")
    tpu, cpu = _device_oracle(sql, {"t": tbl})
    tp, cp = tpu.to_pandas(), cpu.to_pandas()
    assert tp.wk.tolist() == cp.wk.tolist()
    assert tp.c.tolist() == cp.c.tolist()
    assert tp.s.tolist() == cp.s.tolist()
    # the 11-char prefix folds 30 warehouses into 3 groups — the hoist must
    # actually merge finer device groups downstream
    assert len(tp) == 3
