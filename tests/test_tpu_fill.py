"""TPU cold-path tests (jax CPU backend via conftest env).

- pipelined / chunked device fill is byte-identical to the strict serial
  fill (the defaults-off safety property)
- compile/fill overlap actually overlaps: a q1 run with artificially slow
  encode+upload reports compile_overlap_s > 0 and still returns correct rows
- the persistent XLA compile cache round-trips: after clearing every
  in-process cache, the recompile is served from disk (cache_hits grows)
- LruDict bounds the module caches (entry cap, byte budget, clear)
- RUN_STATS keeps concurrent stage runs isolated
"""

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import (
    BallistaConfig,
    EXECUTOR_ENGINE,
    TPU_COMPILE_CACHE_DIR,
    TPU_COMPILE_OVERLAP,
    TPU_FILL_CHUNK_ROWS,
    TPU_FILL_THREADS,
    TPU_MIN_ROWS,
)
from ballista_tpu.plan.physical import MemoryScanExec, TaskContext
from ballista_tpu.plan.schema import DFSchema

from .conftest import tpch_query


def _mixed_table(n: int = 5_000) -> pa.Table:
    rng = np.random.default_rng(11)
    price = np.round(rng.uniform(1, 1000, n), 2)
    qty = rng.integers(1, 50, n).astype(np.int64)
    flag = rng.choice(["A", "N", "R"], n)
    day = rng.integers(8000, 11000, n).astype(np.int32)
    weight = rng.uniform(0.0, 1.0, n)
    ok = rng.random(n) > 0.5
    nullable = pa.array(
        [None if i % 7 == 0 else int(v) for i, v in enumerate(qty)], pa.int64()
    )
    return pa.table({
        "qty": qty,
        "price": price,                       # money lane (2-decimal f64)
        "flag": flag,                         # dictionary codes + LUT
        "day": pa.array(day, pa.date32()),
        "weight": weight,                     # true f64
        "ok": ok,
        "maybe": nullable,                    # validity plane
    })


def _scan(tbl: pa.Table, partitions: int = 3) -> MemoryScanExec:
    batches = tbl.to_batches(max_chunksize=max(1, tbl.num_rows // (partitions * 2)))
    return MemoryScanExec(DFSchema.from_arrow(tbl.schema), batches, partitions)


def _load(scan, **kw):
    import ballista_tpu.ops.tpu.stage_compiler as sc

    ctx = TaskContext(BallistaConfig({}))
    return sc.DEVICE_CACHE._load(scan, [1 << 12, 1 << 14, 1 << 16], ctx, None, **kw)


def _assert_tables_identical(a, b):
    assert a.kinds == b.kinds
    assert a.scales == b.scales
    assert a.dicts == b.dicts
    assert a.part_rows == b.part_rows
    assert a.nbytes == b.nbytes
    assert np.array_equal(np.asarray(a.mask), np.asarray(b.mask))
    for ca, cb in zip(a.cols, b.cols):
        assert ca.dtype == cb.dtype
        assert np.array_equal(np.asarray(ca), np.asarray(cb))
    for va, vb in zip(a.valids, b.valids):
        assert (va is None) == (vb is None)
        if va is not None:
            assert np.array_equal(np.asarray(va), np.asarray(vb))


def test_pipelined_fill_byte_identical_to_serial():
    tbl = _mixed_table()
    serial = _load(_scan(tbl), fill_threads=1)
    piped = _load(_scan(tbl), fill_threads=4)
    _assert_tables_identical(serial, piped)


def test_chunked_upload_byte_identical():
    tbl = _mixed_table()
    whole = _load(_scan(tbl), fill_threads=1)
    chunked = _load(_scan(tbl), fill_threads=4, chunk_rows=7)
    _assert_tables_identical(whole, chunked)


def test_fill_records_encode_upload_split():
    rec: dict = {}
    _load(_scan(_mixed_table()), fill_threads=2, stats=rec)
    assert rec["encode_s"] >= 0
    assert rec["upload_s"] >= 0


def test_on_spec_fires_with_full_compile_metadata():
    """The spec table must carry everything the compile key reads (kinds,
    scales, dict sizes, dtypes, valid slots, P, N) before uploads drain."""
    fired: list = []
    tbl = _mixed_table()
    dt = _load(_scan(tbl), fill_threads=4, on_spec=fired.append)
    assert len(fired) == 1
    spec = fired[0]
    assert spec.kinds == dt.kinds
    assert spec.scales == dt.scales
    assert spec.dicts == dt.dicts
    assert spec.part_rows == dt.part_rows
    assert spec.shape == dt.shape
    for sc_, dc in zip(spec.cols, dt.cols):
        assert sc_.shape == tuple(dc.shape)
        assert np.dtype(sc_.dtype) == np.dtype(dc.dtype)
    for sv, dv in zip(spec.valids, dt.valids):
        assert (sv is None) == (dv is None)


def test_unencodable_column_raises_unsupported_in_pipeline():
    from ballista_tpu.ops.tpu.kernels import Unsupported

    tbl = pa.table({
        "a": np.arange(100, dtype=np.int64),
        "bad": pa.array([[1, 2]] * 100, pa.list_(pa.int64())),
    })
    with pytest.raises(Unsupported):
        _load(_scan(tbl), fill_threads=4)


@pytest.fixture()
def tpu_ctx(tpch_dir):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0})
    ctx = SessionContext(cfg)
    register_tpch(ctx, tpch_dir)
    return ctx


def test_compile_overlaps_slow_fill(tpu_ctx, monkeypatch):
    """With encode and upload artificially slowed, the compile worker must
    start (and make progress) under the fill: compile_overlap_s > 0."""
    import ballista_tpu.ops.tpu.columnar as columnar
    import ballista_tpu.ops.tpu.stage_compiler as sc

    sc.clear_device_caches()
    sc.RUN_STATS.clear()

    real_encode = columnar.encode_column
    real_put = sc._put_chunked

    def slow_encode(arr):
        time.sleep(0.02)
        return real_encode(arr)

    def slow_put(mesh, arr, spec=None, chunk_rows=0):
        time.sleep(0.05)
        return real_put(mesh, arr, spec, chunk_rows)

    monkeypatch.setattr(columnar, "encode_column", slow_encode)
    monkeypatch.setattr(sc, "_put_chunked", slow_put)

    out = tpu_ctx.sql(tpch_query(1)).collect()
    assert out.to_pandas().shape[0] > 0
    stats = sc.RUN_STATS.snapshot()
    assert stats.get("compile_overlap_s", 0.0) > 0.0
    # the legacy total is still reported alongside the split
    assert stats["compile_s"] >= stats.get("trace_s", 0.0)
    assert stats["fill_s"] >= stats["upload_s"] > 0.0


def test_overlap_off_is_serial_and_correct(tpch_dir, tpch_ref_tables):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.reference import compare_results, run_reference
    from ballista_tpu.testing.tpchgen import register_tpch
    import ballista_tpu.ops.tpu.stage_compiler as sc

    sc.clear_device_caches()
    cfg = BallistaConfig({
        EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0,
        TPU_COMPILE_OVERLAP: False, TPU_FILL_THREADS: 1,
    })
    ctx = SessionContext(cfg)
    register_tpch(ctx, tpch_dir)
    eng = ctx.sql(tpch_query(6)).collect()
    ref = run_reference(6, tpch_ref_tables)
    problems = compare_results(eng, ref, 6)
    assert not problems, "\n".join(problems)


def test_persistent_cache_roundtrip(tpch_dir, tmp_path):
    """Simulated restart: clear every in-process cache, rerun the same
    stage — the XLA recompile must be served from the on-disk cache."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.ops.tpu import runtime
    from ballista_tpu.testing.tpchgen import register_tpch
    import ballista_tpu.ops.tpu.stage_compiler as sc

    cache_dir = str(tmp_path / "xla-cache")
    cfg = BallistaConfig({
        EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0,
        TPU_COMPILE_CACHE_DIR: cache_dir,
    })
    ctx = SessionContext(cfg)
    register_tpch(ctx, tpch_dir)

    sc.clear_device_caches()
    ctx.sql(tpch_query(6)).collect()
    cold = runtime.compile_cache_stats()
    assert cold["dir"] == cache_dir
    assert cold["requests"] > 0
    import os

    assert os.listdir(cache_dir), "persistent cache wrote nothing"

    # "restart": drop the in-process compile/LUT/build/device caches so the
    # stage re-traces and re-invokes backend compile from scratch
    sc.clear_device_caches()
    ctx2 = SessionContext(cfg)
    register_tpch(ctx2, tpch_dir)
    ctx2.sql(tpch_query(6)).collect()
    warm = runtime.compile_cache_stats()
    assert warm["hits"] > cold["hits"], (
        f"warm run missed the persistent cache: {cold} -> {warm}")


def test_lru_dict_entry_cap_and_bytes():
    from ballista_tpu.ops.tpu.stage_compiler import LruDict

    d = LruDict(3)
    for i in range(5):
        d[i] = i * 10
    assert len(d) == 3
    assert d.evictions == 2
    assert 0 not in d and 1 not in d
    assert d.get(4) == 40
    # LRU order: touching 2 protects it from the next eviction
    assert d[2] == 20
    d[5] = 50
    assert 2 in d and 3 not in d

    b = LruDict(100, max_bytes=100, sizer=lambda v: v)
    b["a"] = 60
    b["b"] = 60  # over budget: "a" evicted
    assert "a" not in b and "b" in b
    assert b.nbytes() == 60
    b.clear()
    assert len(b) == 0 and b.nbytes() == 0


def test_module_caches_are_bounded():
    import ballista_tpu.ops.tpu.final_stage as fs
    import ballista_tpu.ops.tpu.stage_compiler as sc

    for cache in (sc._COMPILE_CACHE, sc._LUT_CACHE, sc._BUILD_CACHE,
                  fs._FINAL_COMPILE_CACHE):
        assert isinstance(cache, sc.LruDict)
        assert cache.max_entries >= 1


def test_run_stats_isolation_across_concurrent_stages():
    from ballista_tpu.ops.tpu.stage_compiler import RunStats

    rs = RunStats()
    barrier = threading.Barrier(2)

    def stage(tag, key, value):
        with rs.run(tag) as rec:
            barrier.wait()
            rs.set(key, value, rec=rec)
            time.sleep(0.01)
            # thread-local routing: a bare set() lands in THIS run
            rs.set(f"{key}_tls", value + 1)

    t1 = threading.Thread(target=stage, args=("stage_a", "fill_s", 1.0))
    t2 = threading.Thread(target=stage, args=("stage_b", "exec_s", 2.0))
    t1.start(); t2.start(); t1.join(); t2.join()

    stages = rs.stages()
    assert stages["stage_a"] == {"fill_s": 1.0, "fill_s_tls": 2.0}
    assert stages["stage_b"] == {"exec_s": 2.0, "exec_s_tls": 3.0}
    merged = rs.snapshot()
    assert merged["fill_s"] == 1.0 and merged["exec_s"] == 2.0
    # legacy surfaces: Mapping view and item assignment outside a run scope
    assert dict(rs)["fill_s"] == 1.0
    rs["device_bytes"] = 7
    assert rs["device_bytes"] == 7
    rs.clear()
    assert not rs.snapshot() and not rs.stages()


def test_fill_and_cache_knobs_registered():
    cfg = BallistaConfig({})
    assert int(cfg.get(TPU_FILL_THREADS)) == 0
    assert int(cfg.get(TPU_FILL_CHUNK_ROWS)) == 0
    assert bool(cfg.get(TPU_COMPILE_OVERLAP)) is True
    assert str(cfg.get(TPU_COMPILE_CACHE_DIR) or "") == ""


def test_estimate_stage_matches_actual_device_bytes():
    """The admission planner trusts estimate_stage byte-for-byte: its
    table_bytes must equal the filled DeviceTable's nbytes (data stacks +
    validity planes + row mask), and dictionary-coded string columns must
    price their device LUTs in dict_bytes rather than undercounting to the
    4-byte code plane alone."""
    from ballista_tpu.ops.tpu import fusion

    tbl = _mixed_table()
    scan = _scan(tbl)
    dt = _load(scan, fill_threads=1)
    est = fusion.estimate_stage(scan, [], None, dt, [])
    assert est.table_bytes == dt.nbytes
    # "flag" is dictionary-encoded: the LUT rows must be priced
    assert any(d for d in dt.dicts)
    assert est.dict_bytes > 0
    # the full working set the planner admits against is estimate-exact
    assert est.table_bytes + est.dict_bytes >= dt.nbytes
