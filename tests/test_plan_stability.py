"""Golden staged-plan stability suite (reference:
scheduler/tests/tpch_plan_stability/): all 22 TPC-H distributed plans are
frozen with injected SF100 stats at target_partitions=16, for both engine
planning modes. Any stage-boundary / join-mode / broadcast / partition-
count change fails here; regenerate deliberately with
`python dev/update_plan_stability.py` and review the diff."""

import os

import pytest

from .tpch_plan_stability.fixtures import query_path, staged_plan_text, stats_context

APPROVED = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tpch_plan_stability", "approved")


@pytest.fixture(scope="module", params=["cpu", "tpu"])
def golden_ctx(request):
    return request.param, stats_context(request.param)


@pytest.mark.parametrize("q", range(1, 23))
def test_staged_plan_stable(golden_ctx, q):
    engine, ctx = golden_ctx
    with open(query_path(q)) as f:
        sql = f.read()
    got = staged_plan_text(ctx, sql)
    path = os.path.join(APPROVED, engine, f"q{q}.txt")
    with open(path) as f:
        want = f.read()
    assert got == want, (
        f"staged plan for q{q} ({engine} planning) changed; if intended, run "
        f"`python dev/update_plan_stability.py` and review the diff\n--- approved\n"
        f"{want}\n--- got\n{got}"
    )
