"""The engine invariant analyzer as a tier-1 gate (ISSUE 11).

Two fronts:

1. the AST lint suite (`ballista_tpu.analysis`) must report zero
   actionable findings over the repo — new violations fail CI here, not
   in review;
2. the static plan verifier (`analysis.plan_check`) must accept every
   real planner output and REJECT deliberately corrupted DAGs (schema
   mismatch on a shuffle edge, partition-count mismatch, a mesh flag on
   a stage with no exchange, ...).
"""

import json
import os

import pytest

from ballista_tpu.analysis import Analyzer, SourceFile, load_baseline
from ballista_tpu.analysis.core import repo_root
from ballista_tpu.analysis.plan_check import (
    PlanVerificationError,
    check_stages,
    verify_graph,
    verify_stages,
)

from .tpch_plan_stability.fixtures import query_path, stats_context

pytestmark = pytest.mark.analysis


# -- the repo-wide gate -------------------------------------------------------


def test_repo_is_clean():
    """`python -m ballista_tpu.analysis` must exit 0: every pass over every
    file, after suppressions and the checked-in baseline."""
    report = Analyzer().run()
    assert report.files_scanned > 100, "scan set collapsed — collect() is broken"
    assert report.ok, "\n" + report.render()


def test_baseline_entries_are_justified():
    """Every grandfathered finding needs a hand-written reason, and no entry
    may linger after its violation is fixed (run() flags those as stale)."""
    path = os.path.join(repo_root(), "dev", "analysis_baseline.json")
    baseline = load_baseline(path)
    for key, reason in baseline.items():
        assert reason.strip(), f"baseline entry {key!r} has no reason"
        assert reason != "grandfathered; fix or justify", (
            f"baseline entry {key!r} still carries the --update-baseline "
            f"placeholder reason; write a real one"
        )


def test_cli_json_smoke():
    from ballista_tpu.analysis.__main__ import main

    assert main(["--json"]) == 0


# -- suppression mechanics ----------------------------------------------------


def _one_file_analyzer(rel: str, text: str) -> Analyzer:
    from ballista_tpu.analysis.passes.bounded_cache import BoundedCachePass

    return Analyzer(passes=[BoundedCachePass()], baseline_path="/dev/null",
                    files=[SourceFile(rel, text)])


def test_unsuppressed_cache_is_flagged():
    report = _one_file_analyzer("ballista_tpu/x.py", "_CACHE = {}\n").run()
    assert len(report.findings) == 1
    assert report.findings[0].pass_id == "bounded-cache"
    assert "_CACHE" in report.findings[0].message


def test_line_suppression_with_reason():
    report = _one_file_analyzer(
        "ballista_tpu/x.py",
        "# analysis: ignore[bounded-cache] bounded by protocol\n_CACHE = {}\n",
    ).run()
    assert not report.findings
    assert len(report.suppressed) == 1
    assert report.suppressed[0][1].reason == "bounded by protocol"


def test_reasonless_suppression_does_not_count():
    report = _one_file_analyzer(
        "ballista_tpu/x.py",
        "# analysis: ignore[bounded-cache]\n_CACHE = {}\n",
    ).run()
    assert len(report.findings) == 1
    assert "lacks a reason" in report.findings[0].message


def test_skip_file_suppression():
    report = _one_file_analyzer(
        "ballista_tpu/x.py",
        "# analysis: skip-file[bounded-cache] generated registry module\n"
        "_A = {}\n_B = []\n",
    ).run()
    assert not report.findings
    assert len(report.suppressed) == 2


def test_star_suppression_covers_every_pass():
    report = _one_file_analyzer(
        "ballista_tpu/x.py",
        "_CACHE = {}  # analysis: ignore[*] scratch module\n",
    ).run()
    assert not report.findings and len(report.suppressed) == 1


def test_baseline_grandfathers_and_goes_stale(tmp_path):
    src = SourceFile("ballista_tpu/x.py", "_CACHE = {}\n")
    from ballista_tpu.analysis.passes.bounded_cache import BoundedCachePass

    finding = BoundedCachePass().run(
        Analyzer(passes=[], baseline_path="/dev/null", files=[src])
    )[0]
    baseline = tmp_path / "b.json"
    baseline.write_text(json.dumps({"findings": [
        {"key": finding.key(), "reason": "pre-existing; tracked in #123"},
        {"key": "bounded-cache:ballista_tpu/gone.py:_OLD", "reason": "x"},
    ]}))
    report = Analyzer(passes=[BoundedCachePass()], baseline_path=str(baseline),
                      files=[src]).run()
    assert not report.findings
    assert [f.key() for f, _ in report.baselined] == [finding.key()]
    # the entry for the deleted file no longer matches anything → stale → fail
    assert report.stale_baseline == ["bounded-cache:ballista_tpu/gone.py:_OLD"]
    assert not report.ok


# -- the plan verifier over real planner output -------------------------------


@pytest.fixture(scope="module")
def q3_stages():
    from ballista_tpu.scheduler.planner import DistributedPlanner

    ctx = stats_context()
    with open(query_path(3), encoding="utf-8") as f:
        sql = f.read()
    physical = ctx.create_physical_plan(ctx.sql(sql).plan)
    return ctx, DistributedPlanner("q3gate").plan_query_stages(physical)


def _fresh(ctx, n=3, job="fresh"):
    from ballista_tpu.scheduler.planner import DistributedPlanner

    with open(query_path(n), encoding="utf-8") as f:
        sql = f.read()
    physical = ctx.create_physical_plan(ctx.sql(sql).plan)
    return DistributedPlanner(job).plan_query_stages(physical)


def _leaves(plan):
    from ballista_tpu.shuffle.reader import UnresolvedShuffleExec

    out = []

    def walk(n):
        if isinstance(n, UnresolvedShuffleExec):
            out.append(n)
        for c in n.children():
            walk(c)

    walk(plan)
    return out


def test_planner_output_verifies_clean(q3_stages):
    _, stages = q3_stages
    assert verify_stages(stages) == []
    check_stages(stages)  # does not raise


def test_mesh_merged_output_verifies_clean():
    from ballista_tpu.config import (
        EXECUTOR_ENGINE,
        TPU_MESH_ENABLED,
        TPU_MIN_ROWS,
        BallistaConfig,
    )
    from ballista_tpu.scheduler.planner import merge_mesh_stages

    ctx = stats_context(engine="tpu")
    stages = _fresh(ctx, n=3, job="q3mesh")
    merged = merge_mesh_stages(
        list(stages),
        BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0,
                        TPU_MESH_ENABLED: True}),
    )
    assert any(s.mesh for s in merged), "q3 should mesh-fuse a hash edge"
    assert verify_stages(merged) == []


def test_rejects_schema_mismatch_on_shuffle_edge(q3_stages):
    ctx, _ = q3_stages
    stages = _fresh(ctx, job="corrupt-schema")
    import pyarrow as pa

    from ballista_tpu.plan.schema import DFField, DFSchema

    corrupted = False
    for s in stages:
        for leaf in _leaves(s.plan):
            leaf.df_schema = DFSchema([DFField("phantom_col", pa.int64())])
            corrupted = True
            break
        if corrupted:
            break
    assert corrupted
    with pytest.raises(PlanVerificationError) as ei:
        check_stages(stages)
    assert any(v.code == "edge-schema" for v in ei.value.violations)


def test_rejects_partition_count_mismatch(q3_stages):
    ctx, _ = q3_stages
    stages = _fresh(ctx, job="corrupt-parts")
    leaf = next(l for s in stages for l in _leaves(s.plan))
    leaf.output_partitions += 7
    with pytest.raises(PlanVerificationError) as ei:
        check_stages(stages)
    assert any(v.code == "edge-partitions" for v in ei.value.violations)


def test_rejects_mesh_flag_without_exchange(q3_stages):
    ctx, _ = q3_stages
    stages = _fresh(ctx, job="corrupt-mesh")
    stages[0].mesh = True  # no MeshExchangeExec anywhere in that plan
    with pytest.raises(PlanVerificationError) as ei:
        check_stages(stages)
    assert any(v.code == "mesh-flag" for v in ei.value.violations)


def test_rejects_dangling_and_duplicate_stage_ids(q3_stages):
    ctx, _ = q3_stages
    stages = _fresh(ctx, job="corrupt-ids")
    # drop a PRODUCER some consumer still reads → dangling-input
    victim = stages[0].stage_id
    remaining = [s for s in stages if s.stage_id != victim]
    violations = verify_stages(remaining)
    assert any(v.code == "dangling-input" for v in violations)
    dup = list(stages) + [stages[0]]
    assert any(v.code == "dup-stage-id" for v in verify_stages(dup))


# -- graph-level invariants ---------------------------------------------------


def _graph(stages, config=None):
    from ballista_tpu.scheduler.state.execution_graph import ExecutionGraph

    return ExecutionGraph("jg", "gate", "sess", stages, config)


def test_graph_of_planner_output_verifies_clean(q3_stages):
    ctx, _ = q3_stages
    g = _graph(_fresh(ctx, job="gclean"))
    assert verify_graph(g) == []


def test_graph_rejects_task_id_in_fast_lane_band(q3_stages):
    from ballista_tpu.serving.fast_lane import FAST_TASK_ID_BASE

    ctx, _ = q3_stages
    g = _graph(_fresh(ctx, job="gband"))
    g.next_task_id = FAST_TASK_ID_BASE + 5
    assert any(v.code == "task-id-band" for v in verify_graph(g))


def test_graph_rejects_aqe_growth(q3_stages):
    ctx, _ = q3_stages
    g = _graph(_fresh(ctx, job="ggrow"))
    st = next(iter(g.stages.values()))
    st.effective_partitions = st.spec.partitions + 1
    assert any(v.code == "aqe-grew" for v in verify_graph(g))


def test_debug_knob_fails_job_on_corrupt_graph(q3_stages):
    """The ballista.debug.plan.verify wiring: _maybe_verify must fail the
    job (not raise past the event loop) when the graph is corrupt."""
    from ballista_tpu.config import DEBUG_PLAN_VERIFY, BallistaConfig
    from ballista_tpu.scheduler.state.execution_graph import JobState

    ctx, _ = q3_stages
    g = _graph(_fresh(ctx, job="gknob"),
               BallistaConfig({DEBUG_PLAN_VERIFY: True}))
    next(iter(g.stages.values())).spec.mesh = True  # corrupt: no exchange
    g._maybe_verify("unit test")
    assert g.status is JobState.FAILED
    assert "mesh-flag" in g.error

    # knob off → same corruption goes unchecked (the gate is opt-in)
    g2 = _graph(_fresh(ctx, job="gknob2"))
    next(iter(g2.stages.values())).spec.mesh = True
    g2._maybe_verify("unit test")
    assert g2.status is JobState.RUNNING
