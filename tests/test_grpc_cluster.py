"""Real gRPC cluster integration: scheduler daemon + push/pull executor
daemons + remote client, in one process but over real sockets.

Reference analog: the client crate's remote-context tests + tpch.yml's
distributed matrix (scaled down to a handful of representative queries).
"""

import time

import pytest

from ballista_tpu.testing.reference import compare_results, run_reference

from .conftest import tpch_query


@pytest.fixture(scope="module")
def grpc_cluster(tmp_path_factory):
    from ballista_tpu.executor.executor_process import ExecutorProcess
    from ballista_tpu.scheduler.process import SchedulerProcess

    sched = SchedulerProcess(bind_host="127.0.0.1", port=0, rest_port=0)
    sched.start()
    addr = f"127.0.0.1:{sched.port}"
    ex1 = ExecutorProcess(addr, bind_host="127.0.0.1", external_host="127.0.0.1", vcores=4)
    ex2 = ExecutorProcess(addr, bind_host="127.0.0.1", external_host="127.0.0.1",
                          vcores=4, policy="pull")
    ex1.start()
    ex2.start()
    time.sleep(0.3)
    sched.test_executors = [ex1, ex2]  # so tests can reach real work dirs
    yield sched, addr
    ex1.shutdown()
    ex2.shutdown()
    sched.shutdown()


@pytest.fixture()
def remote_ctx(grpc_cluster, tpch_dir):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    _, addr = grpc_cluster
    ctx = SessionContext.remote(addr)
    register_tpch(ctx, tpch_dir)
    return ctx


@pytest.mark.parametrize("q", [1, 3, 13, 22])
def test_tpch_remote_grpc(q, remote_ctx, tpch_ref_tables):
    eng = remote_ctx.sql(tpch_query(q)).collect()
    problems = compare_results(eng, run_reference(q, tpch_ref_tables), q)
    assert not problems, "\n".join(problems)


def test_rest_api(grpc_cluster, remote_ctx):
    import json
    import urllib.request

    sched, _ = grpc_cluster
    port = sched.rest_port
    state = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/api/state"))
    assert state["executors"] == 2
    execs = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/api/executors"))
    assert len(execs) == 2
    # run a query, then check job endpoints + prometheus + dot
    remote_ctx.sql("select count(*) from nation").collect()
    jobs = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/api/jobs"))
    assert jobs
    job_id = jobs[-1]["job_id"]
    stages = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/api/job/{job_id}/stages"))
    assert stages and "plan" in stages[0]
    pcts = [p for s in stages for p in s.get("metric_percentiles", [])]
    assert pcts and all("elapsed_ms_p50" in p and "tasks" in p for p in pcts)
    dot = urllib.request.urlopen(f"http://127.0.0.1:{port}/api/job/{job_id}/dot").read().decode()
    assert dot.startswith("digraph")
    metrics = urllib.request.urlopen(f"http://127.0.0.1:{port}/api/metrics").read().decode()
    assert "ballista_scheduler_jobs_completed_total" in metrics
    # web monitor page + its JSON stage-graph endpoint; the page embeds the
    # sparkline/config features backed by /api/config
    page = urllib.request.urlopen(f"http://127.0.0.1:{port}/").read().decode()
    assert "cluster monitor" in page and "/api/jobs" in page
    assert "spark-act" in page and "toggleConfig" in page
    cfg = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/api/config"))
    assert cfg["session_config_entries"] and cfg["scheduler_id"]
    graph = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/api/job/{job_id}/graph"))
    assert graph["job_id"] == job_id and graph["stages"]
    assert all(len(e) == 2 for e in graph["edges"])
    sids = {s["stage_id"] for s in graph["stages"]}
    assert all(a in sids and b in sids for a, b in graph["edges"])


def test_native_data_plane_forced_remote(grpc_cluster, tpch_dir, tpch_ref_tables):
    """Force every shuffle fetch over Flight (no local fast path): sort-
    layout partition reads go through the executors' native C++ servers."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import SHUFFLE_READER_FORCE_REMOTE
    from ballista_tpu.testing.tpchgen import register_tpch

    _, addr = grpc_cluster
    ctx = SessionContext.remote(addr)
    ctx.config.set(SHUFFLE_READER_FORCE_REMOTE, True)
    register_tpch(ctx, tpch_dir)
    eng = ctx.sql(tpch_query(3)).collect()
    problems = compare_results(eng, run_reference(3, tpch_ref_tables), 3)
    assert not problems, "\n".join(problems)


def test_flight_result_proxy(grpc_cluster, tpch_dir):
    """Clients that cannot reach executors fetch results through the
    scheduler's Flight proxy (flight_proxy_service.rs analog)."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import FLIGHT_PROXY
    from ballista_tpu.testing.tpchgen import register_tpch

    sched, addr = grpc_cluster
    assert sched.flight_proxy_port > 0
    ctx = SessionContext.remote(addr)
    ctx.config.set(FLIGHT_PROXY, f"127.0.0.1:{sched.flight_proxy_port}")
    register_tpch(ctx, tpch_dir)
    out = ctx.sql(
        "select r_name, count(*) c from nation, region "
        "where n_regionkey = r_regionkey group by r_name order by r_name"
    ).collect()
    assert out.num_rows == 5
    assert out.column("c").to_pylist() == [5, 5, 5, 5, 5]


def test_execute_query_push(grpc_cluster, tpch_dir):
    """Server-streaming status: submit + watch in one rpc, no polling."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import PUSH_STATUS
    from ballista_tpu.testing.tpchgen import register_tpch

    _, addr = grpc_cluster
    ctx = SessionContext.remote(addr)
    ctx.config.set(PUSH_STATUS, True)
    register_tpch(ctx, tpch_dir)
    out = ctx.sql("select count(*) n from nation").collect()
    assert out.column("n").to_pylist() == [25]
    # direct stream: terminal event carries the full status
    client = ctx._ensure_remote()
    status = client.execute_sql_push("select count(*) n from region")
    assert status["state"] == "successful"


def test_executor_memory_sizing(grpc_cluster):
    """cgroup/host-aware memory pool drives the per-task spill budget."""
    from ballista_tpu.config import BallistaConfig, SORT_SHUFFLE_MEMORY_LIMIT
    from ballista_tpu.executor.executor_process import detect_memory_limit

    assert detect_memory_limit() > 0
    cfg = BallistaConfig()
    cfg.set_default_if_unset(SORT_SHUFFLE_MEMORY_LIMIT, 123)
    assert cfg.get(SORT_SHUFFLE_MEMORY_LIMIT) == 123
    explicit = BallistaConfig({SORT_SHUFFLE_MEMORY_LIMIT: 999})
    explicit.set_default_if_unset(SORT_SHUFFLE_MEMORY_LIMIT, 123)
    assert explicit.get(SORT_SHUFFLE_MEMORY_LIMIT) == 999


def test_wire_version_gate(grpc_cluster):
    from ballista_tpu.executor.executor import ExecutorMetadata
    from ballista_tpu.proto import pb
    from ballista_tpu.scheduler.grpc_service import scheduler_stub
    from ballista_tpu.serde_control import encode_executor_metadata

    import grpc

    _, addr = grpc_cluster
    stub = scheduler_stub(grpc.insecure_channel(addr))
    bad = ExecutorMetadata(id="bad", wire_version="btpu-OLD")
    resp = stub.RegisterExecutor(
        pb.RegisterExecutorParams(metadata=encode_executor_metadata(bad)), timeout=5
    )
    assert not resp.success
    assert "wire protocol" in resp.error


def test_cancel_job(remote_ctx, grpc_cluster):
    client = remote_ctx._ensure_remote()
    job_id = client.execute_sql(tpch_query(9))
    client.cancel_job(job_id)
    status = client.wait_for_job(job_id, timeout=30)
    assert status["state"] in ("cancelled", "successful")  # may finish first


def test_tui_rest_client_against_live_scheduler(grpc_cluster, remote_ctx):
    from ballista_tpu.cli.tui import RestClient, render_jobs, render_stages

    sched, _ = grpc_cluster
    remote_ctx.sql("select count(*) from region").collect()
    c = RestClient(f"http://127.0.0.1:{sched.rest_port}")
    assert c.state()["executors"] == 2
    jobs = c.jobs()
    assert jobs and jobs[-1]["state"] == "successful"
    assert c.executors()
    st = c.stages(jobs[-1]["job_id"])
    assert st and "metric_percentiles" in st[0]
    # the render layer digests live payloads
    assert len(render_jobs(jobs, 0)) == len(jobs) + 1
    assert len(render_stages(st)) == len(st) + 1


def test_memory_tables_over_remote_cluster(grpc_cluster):
    """In-memory tables work against a REAL cluster: the client plans and
    ships the physical plan with MemoryScanNode IPC bytes (the reference's
    BallistaQueryPlanner flow)."""
    import pyarrow as pa

    from ballista_tpu.client.context import SessionContext

    _, addr = grpc_cluster
    ctx = SessionContext.remote(addr)
    ctx.register_arrow_table("mem", pa.table({"x": [1, 2, 3, 4], "g": ["a", "b", "a", "b"]}),
                             partitions=2)
    out = ctx.sql("select g, sum(x) s, count(*) c from mem group by g order by g").collect()
    assert out.column("s").to_pylist() == [4, 6]
    assert out.column("c").to_pylist() == [2, 2]


def test_remote_explain_analyze(grpc_cluster, remote_ctx):
    """EXPLAIN ANALYZE in remote mode renders per-stage operator metrics
    fetched over GetJobMetrics (DistributedExplainAnalyzeExec analog)."""
    out = remote_ctx.sql(
        "explain analyze select n_regionkey, count(*) from nation group by n_regionkey"
    ).collect()
    plans = dict(zip(out.column("plan_type").to_pylist(), out.column("plan").to_pylist()))
    body = plans.get("analyzed_plan (distributed)", "")
    assert "stage" in body and "elapsed_ms" in body, plans


def test_concurrent_sessions_and_jobs(grpc_cluster, tpch_dir, tpch_ref_tables):
    """8 clients submit simultaneously: scheduler state (event loop, graph
    registry, session manager, slot accounting) stays consistent and every
    result is correct."""
    import concurrent.futures as fut

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    _, addr = grpc_cluster
    queries = [1, 3, 6, 12, 14, 19, 6, 1]

    def run_one(q):
        ctx = SessionContext.remote(addr)
        register_tpch(ctx, tpch_dir)
        out = ctx.sql(tpch_query(q)).collect()
        return q, compare_results(out, run_reference(q, tpch_ref_tables), q)

    with fut.ThreadPoolExecutor(max_workers=8) as pool:
        results = list(pool.map(run_one, queries))
    bad = [(q, p) for q, p in results if p]
    assert not bad, bad


def test_clean_job_data_gc_fans_out(grpc_cluster, remote_ctx):
    """CleanJobData removes the job's shuffle files on EVERY executor
    (reference: ExecutorManager::clean_up_job_data rpc fan-out), not just
    the scheduler's own state."""
    import glob
    import os
    import time as _t

    sched, addr = grpc_cluster
    out = remote_ctx.sql("select count(*) c from lineitem").collect()
    assert out.num_rows == 1
    with sched.scheduler._jobs_lock:
        job_id = list(sched.scheduler.jobs)[-1]
    # the job's shuffle dirs must exist under the real executor work dirs
    # BEFORE cleanup — otherwise this test can pass without testing anything
    work_dirs = [ex.work_dir for ex in sched.test_executors]
    before = [d for wd in work_dirs for d in glob.glob(os.path.join(wd, job_id))]
    assert before, f"no shuffle dirs for {job_id} under {work_dirs}"
    sched.scheduler.clean_job_data(job_id)
    deadline = _t.time() + 10
    remaining = list(before)
    while _t.time() < deadline and remaining:
        remaining = [d for wd in work_dirs for d in glob.glob(os.path.join(wd, job_id))]
        _t.sleep(0.2)
    assert not remaining, remaining


def test_keda_external_scaler(grpc_cluster, remote_ctx):
    """KEDA ExternalScaler rpcs on the scheduler port (external_scaler.rs):
    IsActive true, spec advertises pending_jobs, metrics report queue
    pressure as job counts."""
    import grpc as grpclib

    from ballista_tpu.proto import keda_pb2 as kpb
    from ballista_tpu.scheduler.external_scaler import external_scaler_stub

    from types import SimpleNamespace

    from ballista_tpu.scheduler.state.execution_graph import JobState

    sched, addr = grpc_cluster
    with grpclib.insecure_channel(addr) as ch:
        stub = external_scaler_stub(ch)
        assert stub.IsActive(kpb.ScaledObjectRef(name="x")).result is True
        spec = stub.GetMetricSpec(kpb.ScaledObjectRef(name="x"))
        # executor scaling on pending_jobs, scheduler scaling on the
        # deepest shard event queue
        assert [(m.metricName, m.targetSize) for m in spec.metricSpecs] == [
            ("pending_jobs", 1), ("shard_queue_depth", 1)]
        spec5 = stub.GetMetricSpec(
            kpb.ScaledObjectRef(name="x", scalerMetadata={"targetSize": "5"}))
        assert spec5.metricSpecs[0].targetSize == 5
        remote_ctx.sql("select count(*) from region").collect()
        # observe NONZERO pressure: park fake queued/running jobs in the
        # registry so the count mapping is actually exercised
        s = sched.scheduler
        fakes = {
            "zz_q1": SimpleNamespace(status=JobState.QUEUED),
            "zz_q2": SimpleNamespace(status=JobState.QUEUED),
            "zz_r1": SimpleNamespace(status=JobState.RUNNING),
        }
        with s._jobs_lock:
            s.jobs.update(fakes)
        try:
            vals = {m.metricName: m.metricValue
                    for m in stub.GetMetrics(kpb.GetMetricsRequest()).metricValues}
        finally:
            with s._jobs_lock:
                for k in fakes:
                    s.jobs.pop(k, None)
        assert vals["pending_jobs"] == 2
        assert vals["running_jobs"] == 1
