"""Real gRPC cluster integration: scheduler daemon + push/pull executor
daemons + remote client, in one process but over real sockets.

Reference analog: the client crate's remote-context tests + tpch.yml's
distributed matrix (scaled down to a handful of representative queries).
"""

import time

import pytest

from ballista_tpu.testing.reference import compare_results, run_reference

from .conftest import tpch_query


@pytest.fixture(scope="module")
def grpc_cluster(tmp_path_factory):
    from ballista_tpu.executor.executor_process import ExecutorProcess
    from ballista_tpu.scheduler.process import SchedulerProcess

    sched = SchedulerProcess(bind_host="127.0.0.1", port=0, rest_port=0)
    sched.start()
    addr = f"127.0.0.1:{sched.port}"
    ex1 = ExecutorProcess(addr, bind_host="127.0.0.1", external_host="127.0.0.1", vcores=4)
    ex2 = ExecutorProcess(addr, bind_host="127.0.0.1", external_host="127.0.0.1",
                          vcores=4, policy="pull")
    ex1.start()
    ex2.start()
    time.sleep(0.3)
    yield sched, addr
    ex1.shutdown()
    ex2.shutdown()
    sched.shutdown()


@pytest.fixture()
def remote_ctx(grpc_cluster, tpch_dir):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    _, addr = grpc_cluster
    ctx = SessionContext.remote(addr)
    register_tpch(ctx, tpch_dir)
    return ctx


@pytest.mark.parametrize("q", [1, 3, 13, 22])
def test_tpch_remote_grpc(q, remote_ctx, tpch_ref_tables):
    eng = remote_ctx.sql(tpch_query(q)).collect()
    problems = compare_results(eng, run_reference(q, tpch_ref_tables), q)
    assert not problems, "\n".join(problems)


def test_rest_api(grpc_cluster, remote_ctx):
    import json
    import urllib.request

    sched, _ = grpc_cluster
    port = sched.rest_port
    state = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/api/state"))
    assert state["executors"] == 2
    execs = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/api/executors"))
    assert len(execs) == 2
    # run a query, then check job endpoints + prometheus + dot
    remote_ctx.sql("select count(*) from nation").collect()
    jobs = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/api/jobs"))
    assert jobs
    job_id = jobs[-1]["job_id"]
    stages = json.load(urllib.request.urlopen(f"http://127.0.0.1:{port}/api/job/{job_id}/stages"))
    assert stages and "plan" in stages[0]
    dot = urllib.request.urlopen(f"http://127.0.0.1:{port}/api/job/{job_id}/dot").read().decode()
    assert dot.startswith("digraph")
    metrics = urllib.request.urlopen(f"http://127.0.0.1:{port}/api/metrics").read().decode()
    assert "ballista_scheduler_jobs_completed_total" in metrics


def test_wire_version_gate(grpc_cluster):
    from ballista_tpu.executor.executor import ExecutorMetadata
    from ballista_tpu.proto import pb
    from ballista_tpu.scheduler.grpc_service import scheduler_stub
    from ballista_tpu.serde_control import encode_executor_metadata

    import grpc

    _, addr = grpc_cluster
    stub = scheduler_stub(grpc.insecure_channel(addr))
    bad = ExecutorMetadata(id="bad", wire_version="btpu-OLD")
    resp = stub.RegisterExecutor(
        pb.RegisterExecutorParams(metadata=encode_executor_metadata(bad)), timeout=5
    )
    assert not resp.success
    assert "wire protocol" in resp.error


def test_cancel_job(remote_ctx, grpc_cluster):
    client = remote_ctx._ensure_remote()
    job_id = client.execute_sql(tpch_query(9))
    client.cancel_job(job_id)
    status = client.wait_for_job(job_id, timeout=30)
    assert status["state"] in ("cancelled", "successful")  # may finish first
