"""End-to-end executor-loss recovery with REAL shuffle files.

Two executors with SEPARATE work dirs; executor A dies after finishing its
stage-1 tasks and its shuffle outputs are deleted (ResultLost). The
scheduler must roll back, recompute A's stages on B, and the job must
still produce a correct result (reference: reset_stages_on_lost_executor +
rerun_successful_stage, execution_graph.rs:180,216).
"""

import shutil
import tempfile
import threading

import pytest

from ballista_tpu.config import BallistaConfig, DEFAULT_SHUFFLE_PARTITIONS
from ballista_tpu.executor.executor import Executor, ExecutorMetadata
from ballista_tpu.executor.standalone import InProcessTaskLauncher
from ballista_tpu.ids import new_executor_id
from ballista_tpu.scheduler.server import Event, SchedulerServer
from ballista_tpu.testing.reference import compare_results, run_reference

from .conftest import tpch_query


class KillingLauncher(InProcessTaskLauncher):
    """Kills executor `victim` (and deletes its shuffle files) right after
    it reports its second successful task."""

    def __init__(self, executors, victim_id: str, victim_work_dir: str):
        super().__init__(executors)
        self.victim_id = victim_id
        self.victim_work_dir = victim_work_dir
        self.victim_successes = 0
        self.killed = False
        self._lock = threading.Lock()

    def launch(self, executor_id, tasks, server):
        with self._lock:
            if self.killed and executor_id == self.victim_id:
                raise RuntimeError("executor is dead")
        ex = self.executors[executor_id]

        def run(task):
            result = ex.execute_task(task, server.sessions.get(task.session_id))
            kill_now = False
            with self._lock:
                if (
                    executor_id == self.victim_id
                    and not self.killed
                    and result.state == "success"
                ):
                    self.victim_successes += 1
                    if self.victim_successes >= 2:
                        self.killed = True
                        kill_now = True
            if kill_now:
                # the executor dies: its shuffle outputs are gone
                shutil.rmtree(self.victim_work_dir, ignore_errors=True)
                server.post(Event("executor_lost", executor_id))
                return  # status never reaches the scheduler
            server.update_task_status(executor_id, [result])

        for t in tasks:
            self.pool.submit(run, t)


@pytest.mark.parametrize("q", [3])
def test_executor_lost_recovery_e2e(q, tpch_dir, tpch_ref_tables):
    from ballista_tpu.client.context import SessionContext, fetch_job_results
    from ballista_tpu.errors import ExecutionError
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 4})
    wd_a = tempfile.mkdtemp(prefix="bt-victim-")
    wd_b = tempfile.mkdtemp(prefix="bt-survivor-")
    ex_a = Executor(wd_a, ExecutorMetadata(id=str(new_executor_id()), vcores=2), config=cfg)
    ex_b = Executor(wd_b, ExecutorMetadata(id=str(new_executor_id()), vcores=2), config=cfg)
    launcher = KillingLauncher({ex_a.metadata.id: ex_a, ex_b.metadata.id: ex_b},
                               ex_a.metadata.id, wd_a)
    scheduler = SchedulerServer(launcher)
    scheduler.start()
    scheduler.register_executor(ex_a.metadata)
    scheduler.register_executor(ex_b.metadata)

    ctx = SessionContext(cfg)
    register_tpch(ctx, tpch_dir)
    try:
        session_id = scheduler.sessions.create_or_update(cfg.to_key_value_pairs(), "s-recovery")
        job_id = scheduler.submit_sql(tpch_query(q), session_id)
        status = scheduler.wait_for_job(job_id, timeout=120)
        assert status["state"] == "successful", status.get("error")
        assert launcher.killed, "victim executor was never killed — test vacuous"
        out = fetch_job_results(status, cfg)
        problems = compare_results(out, run_reference(q, tpch_ref_tables), q)
        assert not problems, "\n".join(problems)
    finally:
        scheduler.stop()
        launcher.pool.shutdown(wait=False)
        shutil.rmtree(wd_b, ignore_errors=True)
