"""Distributed machinery tests.

Layers (mirroring SURVEY.md §4's pyramid):
- distributed planner stage shapes
- execution graph state machine (virtual cluster: fake launcher, no real
  execution — reference SchedulerTest/VirtualTaskLauncher)
- standalone end-to-end: real scheduler + executors + shuffle files
- Flight remote-read path via force_remote_read (reference sort_shuffle.rs)
"""

import pyarrow as pa
import pytest

from ballista_tpu.config import (
    BallistaConfig,
    DEFAULT_SHUFFLE_PARTITIONS,
    SHUFFLE_READER_FORCE_REMOTE,
)
from ballista_tpu.testing.reference import compare_results, run_reference

from .conftest import tpch_query


@pytest.fixture()
def standalone_ctx(tpch_dir):
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 4})
    ctx = SessionContext.standalone(cfg, num_executors=2, vcores=4)
    register_tpch(ctx, tpch_dir)
    yield ctx
    ctx.shutdown()


# -- distributed planner -----------------------------------------------------


def test_stage_split_shapes(tpch_ctx):
    from ballista_tpu.scheduler.planner import DistributedPlanner

    df = tpch_ctx.sql(tpch_query(1))
    physical = tpch_ctx.create_physical_plan(df.plan)
    stages = DistributedPlanner("job1").plan_query_stages(physical)
    # q1: partial agg stage (hash shuffle) + final stage
    assert len(stages) >= 2
    assert stages[-1].stage_id == max(s.stage_id for s in stages)
    # every non-final stage is an input of something
    consumed = {i for s in stages for i in s.input_stage_ids}
    for s in stages[:-1]:
        assert s.stage_id in consumed or s.broadcast


def test_broadcast_stage_for_join(tpch_ctx):
    from ballista_tpu.scheduler.planner import DistributedPlanner

    df = tpch_ctx.sql(tpch_query(3))
    physical = tpch_ctx.create_physical_plan(df.plan)
    stages = DistributedPlanner("job3").plan_query_stages(physical)
    assert any(s.broadcast for s in stages), "q3 should produce a broadcast build stage"


# -- execution graph (virtual cluster, no real execution) ---------------------


def _tiny_graph(tpch_ctx, q=1):
    from ballista_tpu.scheduler.planner import DistributedPlanner
    from ballista_tpu.scheduler.state.execution_graph import ExecutionGraph

    physical = tpch_ctx.create_physical_plan(tpch_ctx.sql(tpch_query(q)).plan)
    stages = DistributedPlanner("jobv").plan_query_stages(physical)
    return ExecutionGraph("jobv", "", "s1", stages)


def _fake_success(graph, task, executor_id="e1"):
    from ballista_tpu.shuffle.types import PartitionLocation, PartitionStats

    locs = []
    stage = graph.stages[task.stage_id]
    k = stage.spec.output_partitions
    for p in task.partitions:
        outs = range(k) if stage.spec.plan.output_partitions > 0 else [p]
        for o in outs:
            locs.append(PartitionLocation(
                map_partition=p, job_id=task.job_id, stage_id=task.stage_id,
                output_partition=o, executor_id=executor_id, path=f"/fake/{task.stage_id}/{p}/{o}",
                stats=PartitionStats(num_rows=1, num_batches=1, num_bytes=10),
            ))
    return graph.update_task_status(
        task.task_id, task.stage_id, task.stage_attempt, "success", task.partitions, locs
    )


def test_graph_lifecycle_virtual(tpch_ctx):
    g = _tiny_graph(tpch_ctx)
    seen_stages = set()
    guard = 0
    while g.status.value == "running" and guard < 1000:
        guard += 1
        t = g.pop_next_task("e1")
        if t is None:
            break
        seen_stages.add(t.stage_id)
        _fake_success(g, t)
    assert g.status.value == "successful", g.display()
    assert len(seen_stages) == len(g.stages)


def test_graph_executor_lost_recompute(tpch_ctx):
    g = _tiny_graph(tpch_ctx)
    # finish stage 1 on e1
    tasks = []
    while True:
        t = g.pop_next_task("e1")
        if t is None or t.stage_id != 1:
            break
        tasks.append(t)
    for t in tasks:
        _fake_success(g, t, "e1")
    assert g.stages[1].state.value == "successful"
    # losing e1 must rerun stage 1 (its shuffle outputs lived there)
    n = g.reset_stages_on_lost_executor("e1")
    assert n >= 1
    assert g.stages[1].state.value in ("resolved", "unresolved")
    assert g.stages[1].attempt == 1


def test_graph_task_failure_retry(tpch_ctx):
    g = _tiny_graph(tpch_ctx)
    t = g.pop_next_task("e1")
    ev = g.update_task_status(t.task_id, t.stage_id, t.stage_attempt, "failed",
                              t.partitions, [], "transient io", retryable=True)
    assert "job_failed" not in ev
    # failed partitions go back in the queue
    assert set(t.partitions) <= set(g.stages[t.stage_id].pending)
    t2 = g.pop_next_task("e1")
    assert t2 is not None
    ev = g.update_task_status(t2.task_id, t2.stage_id, t2.stage_attempt, "failed",
                              t2.partitions, [], "fatal", retryable=False)
    assert "job_failed" in ev
    assert g.status.value == "failed"


# -- standalone end-to-end -----------------------------------------------------


@pytest.mark.parametrize("q", [1, 3, 5, 7, 13, 17, 18, 21, 22])
def test_tpch_standalone(q, standalone_ctx, tpch_ref_tables):
    eng = standalone_ctx.sql(tpch_query(q)).collect()
    ref = run_reference(q, tpch_ref_tables)
    problems = compare_results(eng, ref, q)
    assert not problems, "\n".join(problems)


def test_tpch_standalone_remote_reads(tpch_dir, tpch_ref_tables):
    """Force every shuffle read over Arrow Flight (no local fast path)."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({DEFAULT_SHUFFLE_PARTITIONS: 4, SHUFFLE_READER_FORCE_REMOTE: True})
    ctx = SessionContext.standalone(cfg, num_executors=2, vcores=4)
    register_tpch(ctx, tpch_dir)
    try:
        eng = ctx.sql(tpch_query(3)).collect()
        problems = compare_results(eng, run_reference(3, tpch_ref_tables), 3)
        assert not problems, "\n".join(problems)
    finally:
        ctx.shutdown()


def test_plan_proto_roundtrip(tpch_ctx):
    from ballista_tpu.serde import plan_from_bytes, plan_to_bytes

    for q in (1, 3, 17):
        physical = tpch_ctx.create_physical_plan(tpch_ctx.sql(tpch_query(q)).plan)
        b = plan_to_bytes(physical)
        restored = plan_from_bytes(b)
        assert restored.display() == physical.display()


def test_shuffle_writer_reader_roundtrip(tmp_path):
    """Unit: hash + sort layouts round-trip through writer → reader."""
    from ballista_tpu.plan.expressions import col
    from ballista_tpu.plan.physical import MemoryScanExec, TaskContext
    from ballista_tpu.plan.schema import DFSchema
    from ballista_tpu.shuffle.reader import ShuffleReaderExec
    from ballista_tpu.shuffle.types import PartitionLocation
    from ballista_tpu.shuffle.writer import ShuffleWriterExec, metadata_to_locations

    tbl = pa.table({"k": pa.array(list(range(100)), pa.int64()),
                    "v": pa.array([f"s{i}" for i in range(100)])})
    scan = MemoryScanExec(DFSchema.from_arrow(tbl.schema), tbl.to_batches(), partitions=2)
    for sort_shuffle in (False, True):
        writer = ShuffleWriterExec(scan, "jobx", 1, 4, [col("k")], sort_shuffle=sort_shuffle)
        ctx = TaskContext(BallistaConfig(), task_id="t0", work_dir=str(tmp_path))
        locations = []
        for p in range(2):
            for meta in writer.execute(p, ctx):
                locations.extend(metadata_to_locations(meta, "jobx", 1, p, "e1", "localhost", 0))
        by_out = [[] for _ in range(4)]
        for l in locations:
            by_out[l.output_partition].append(l)
        reader = ShuffleReaderExec(scan.df_schema, by_out)
        seen = []
        for p in range(4):
            for b in reader.execute(p, TaskContext(BallistaConfig())):
                seen.extend(b.column(0).to_pylist())
        assert sorted(seen) == list(range(100)), f"sort_shuffle={sort_shuffle}"


def test_sort_shuffle_spill_path(tmp_path, tpch_dir, tpch_ref_tables):
    """A tiny sort-shuffle memory limit forces per-bucket spills + the
    consolidation merge; results stay correct through a standalone cluster
    (reference: sort_shuffle spill.rs / SpillManager)."""
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import SORT_SHUFFLE_MEMORY_LIMIT
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({SORT_SHUFFLE_MEMORY_LIMIT: 16 * 1024})  # ~everything spills
    ctx = SessionContext.standalone(cfg, num_executors=1, vcores=2)
    register_tpch(ctx, tpch_dir)
    try:
        eng = ctx.sql(tpch_query(3)).collect()
        problems = compare_results(eng, run_reference(3, tpch_ref_tables), 3)
        assert not problems, "\n".join(problems)
    finally:
        ctx.shutdown()


def test_midstream_fetch_failure_no_duplicates(monkeypatch):
    """A transient failure after the flight client already streamed some
    batches must not duplicate rows on retry (fetches buffer before
    yielding — the reference's fetch_partition_buffered)."""
    import pyarrow as pa

    from ballista_tpu import config as cfgmod
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.plan.physical import TaskContext
    from ballista_tpu.shuffle import reader as reader_mod
    from ballista_tpu.shuffle.types import PartitionLocation, PartitionStats

    batches = [pa.record_batch({"x": pa.array([i, i + 1], pa.int64())}) for i in (0, 2, 4)]
    calls = {"n": 0}

    def flaky(loc, ctx):
        calls["n"] += 1
        if calls["n"] == 1:
            yield batches[0]
            yield batches[1]
            raise ConnectionError("mid-stream drop")
        yield from batches

    monkeypatch.setattr("ballista_tpu.flight.client.fetch_partition_flight", flaky)
    loc = PartitionLocation(
        map_partition=0, job_id="j", stage_id=1, output_partition=0,
        executor_id="e1", host="nowhere", flight_port=1, path="/does/not/exist",
        layout="hash", stats=PartitionStats(6, 100),
    )
    ctx = TaskContext(BallistaConfig({cfgmod.IO_RETRY_WAIT_MS: 1}))
    got = list(reader_mod.fetch_partition(loc, ctx, force_remote=True))
    assert calls["n"] == 2
    rows = [v for b in got for v in b.column("x").to_pylist()]
    assert rows == [0, 1, 2, 3, 4, 5], rows  # once each, no duplicates


def test_concurrent_location_fetch_order_deterministic(monkeypatch):
    """Multi-location reads fetch concurrently but yield in location order
    (order-sensitive float merges depend on it)."""
    import threading
    import time as _t

    import pyarrow as pa

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.plan.physical import TaskContext
    from ballista_tpu.plan.schema import DFSchema
    from ballista_tpu.shuffle import reader as reader_mod
    from ballista_tpu.shuffle.types import PartitionLocation, PartitionStats

    n_locs = 6
    inflight = {"now": 0, "peak": 0}
    lock = threading.Lock()

    def slow(loc, ctx):
        with lock:
            inflight["now"] += 1
            inflight["peak"] = max(inflight["peak"], inflight["now"])
        _t.sleep(0.05)
        with lock:
            inflight["now"] -= 1
        yield pa.record_batch({"x": pa.array([loc.map_partition], pa.int64())})

    monkeypatch.setattr("ballista_tpu.flight.client.fetch_partition_flight", slow)
    locs = [
        PartitionLocation(
            map_partition=m, job_id="j", stage_id=1, output_partition=0,
            executor_id=f"e{m}", host=f"h{m}", flight_port=1, path="/nope",
            layout="hash", stats=PartitionStats(1, 10),
        )
        for m in range(n_locs)
    ]
    schema = DFSchema.from_arrow(pa.schema([("x", pa.int64())]), "t")
    rd = reader_mod.ShuffleReaderExec(schema, [locs])
    ctx = TaskContext(BallistaConfig())
    t0 = _t.time()
    out = [b.column("x").to_pylist()[0] for b in rd.execute(0, ctx) if b.num_rows]
    elapsed = _t.time() - t0
    assert out == list(range(n_locs))          # deterministic location order
    assert inflight["peak"] >= 3               # genuinely concurrent
    assert elapsed < 0.05 * n_locs * 0.8       # faster than serial


def test_spill_consolidation_streams_bounded_memory(tmp_path):
    """Consolidating spilled buckets must stream spill files batch-by-batch,
    never rebuffering a whole bucket (peak Arrow allocation during the
    consolidation stays near one batch, far under the spilled volume)."""
    import numpy as np
    import pyarrow as pa

    from ballista_tpu.config import BallistaConfig, SORT_SHUFFLE_MEMORY_LIMIT
    from ballista_tpu.plan.expressions import Column
    from ballista_tpu.plan.physical import MemoryScanExec, TaskContext
    from ballista_tpu.plan.schema import DFSchema
    from ballista_tpu.shuffle import writer as writer_mod

    rng = np.random.default_rng(4)
    batch_rows = 20_000
    n_batches = 24
    batches = [
        pa.record_batch({
            "k": pa.array(rng.integers(0, 1 << 20, batch_rows)),
            "v": pa.array(rng.random(batch_rows)),
        })
        for _ in range(n_batches)
    ]
    batch_bytes = batches[0].nbytes
    schema = DFSchema.from_arrow(batches[0].schema, "t")
    scan = MemoryScanExec(schema, batches)
    w = writer_mod.ShuffleWriterExec(scan, "spilljob", 1, 4, [Column("k", "t")],
                                     sort_shuffle=True)

    peaks = []
    orig = writer_mod.ShuffleWriterExec._iter_bucket_batches

    def spy(in_memory, spill_files):
        base = pa.total_allocated_bytes()
        for b in orig(in_memory, spill_files):
            peaks.append(pa.total_allocated_bytes() - base)
            yield b

    writer_mod.ShuffleWriterExec._iter_bucket_batches = staticmethod(spy)
    try:
        ctx = TaskContext(BallistaConfig({SORT_SHUFFLE_MEMORY_LIMIT: 2 * batch_bytes}),
                          work_dir=str(tmp_path))
        meta = list(w.execute(0, ctx))[0]
        total_rows = sum(meta.column(2).to_pylist())
        assert total_rows == batch_rows * n_batches
    finally:
        writer_mod.ShuffleWriterExec._iter_bucket_batches = staticmethod(orig)
    assert peaks, "consolidation never streamed"
    spilled_volume = batch_bytes * n_batches
    # old behavior rebuffered ~a whole bucket (¼ of the data); streaming
    # holds at most a few decoded batches at once
    assert max(peaks) < spilled_volume / 8, (max(peaks), spilled_volume)


def test_consistent_hash_distribution_sticky():
    """task-distribution=consistent-hash: the same (job, stage, partition)
    identity lands on the same executor across offers, spilling to ring
    neighbors only when the preferred node is full."""
    from ballista_tpu.scheduler.state.executor_manager import ExecutorManager
    from ballista_tpu.executor.executor import ExecutorMetadata
    from ballista_tpu.version import WIRE_PROTOCOL_VERSION

    m = ExecutorManager("consistent-hash")
    for i in range(4):
        m.register(ExecutorMetadata(id=f"e{i}", host=f"h{i}", vcores=4,
                                    wire_version=WIRE_PROTOCOL_VERSION))
    keys = [f"job-a/2/{p}" for p in range(16)]
    first = {k: m.pick_consistent(k) for k in keys}
    assert len(set(first.values())) > 1, "ring degenerated to one executor"
    # free everything and re-pick: placement must be identical (sticky)
    for k, e in first.items():
        m.free_slot(e, 1)
    second = {k: m.pick_consistent(k) for k in keys}
    assert first == second
    # saturate one executor's slots: its keys spill to a neighbor
    for k, e in second.items():
        m.free_slot(e, 1)
    target = first[keys[0]]
    taken = m.take_slots(target, 4)
    assert taken == 4
    spilled = m.pick_consistent(keys[0])
    assert spilled is not None and spilled != target


def test_session_memory_pool_try_grow_drives_spill(tmp_path):
    """The session-shared pool's try_grow refusal makes a writer spill even
    with NO static per-task limit — and budget not taken by one task is
    available to another (cross-task lending, runtime_cache.rs:59)."""
    import numpy as np
    import pyarrow as pa

    from ballista_tpu.config import BallistaConfig, SORT_SHUFFLE_MEMORY_LIMIT
    from ballista_tpu.executor.memory_pool import MemoryPool
    from ballista_tpu.plan.expressions import Column
    from ballista_tpu.plan.physical import MemoryScanExec, TaskContext
    from ballista_tpu.plan.schema import DFSchema
    from ballista_tpu.shuffle.writer import ShuffleWriterExec

    rng = np.random.default_rng(8)
    batches = [
        pa.record_batch({"k": pa.array(rng.integers(0, 1 << 20, 10_000)),
                         "v": pa.array(rng.random(10_000))})
        for _ in range(12)
    ]
    schema = DFSchema.from_arrow(batches[0].schema, "t")
    pool = MemoryPool(capacity=3 * batches[0].nbytes)

    scan = MemoryScanExec(schema, batches)
    w = ShuffleWriterExec(scan, "pooljob", 1, 4, [Column("k", "t")], sort_shuffle=True)
    ctx = TaskContext(BallistaConfig({SORT_SHUFFLE_MEMORY_LIMIT: 0}),  # no static limit
                      work_dir=str(tmp_path))
    ctx.memory_pool = pool
    meta = list(w.execute(0, ctx))[0]
    assert sum(meta.column(2).to_pylist()) == 120_000  # all rows written
    assert pool.reserved == 0, "pool reservation leaked"
    # another consumer can now take the WHOLE capacity (cross-task lending)
    assert pool.try_grow(pool.capacity)
    pool.shrink(pool.capacity)


def test_session_pool_registry_ttl_eviction():
    """Idle session pools are evicted on lookup after the TTL (the executor
    never hears about session removal — runtime_cache.rs:86 semantics), and
    eviction resets leaked reservations for the session's next task."""
    from ballista_tpu.executor.memory_pool import SessionPoolRegistry

    reg = SessionPoolRegistry(capacity_per_session=100, ttl_s=0.05)
    p1 = reg.get("s1")
    assert p1.try_grow(90)  # a task dies holding a reservation
    reg.get("s2")
    assert len(reg) == 2
    import time as _t

    _t.sleep(0.08)
    p1b = reg.get("s1")  # sweep evicts both idle entries, s1 re-created fresh
    assert p1b is not p1 and p1b.reserved == 0
    assert len(reg) == 1  # s2 swept
    assert reg.get("s2").reserved == 0
