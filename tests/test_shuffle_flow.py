"""Reduce-side streaming merge + write-side memory-pool discipline.

Covers the round-5 rework of shuffle flow control:
 - the multi-location reader streams under a consumed-bytes window
   (reference: sort_shuffle/multi_stream_reader.rs) instead of buffering
   whole partitions per location;
 - sort-shuffle spills are byte-accounted in operator metrics
   (reference: sort_shuffle/spill.rs:46,110);
 - a try_grow refusal with nothing left to spill BLOCKS with a deadline
   for peer tasks to shrink instead of unconditionally overcommitting.
"""

import os
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import (
    SHUFFLE_READER_FORCE_REMOTE,
    SHUFFLE_READER_MAX_BYTES,
    SORT_SHUFFLE_MEMORY_LIMIT,
    SORT_SHUFFLE_POOL_WAIT_S,
    BallistaConfig,
)
from ballista_tpu.executor.memory_pool import MemoryPool
from ballista_tpu.plan.expressions import Column
from ballista_tpu.plan.physical import MemoryScanExec, TaskContext
from ballista_tpu.plan.schema import DFSchema


def _write_stage(tmp_path, rows=200_000, partitions=8):
    """Produce a sort-layout stage and return (work_dir, locations by output
    partition, total rows)."""
    from ballista_tpu.shuffle.writer import ShuffleWriterExec, metadata_to_locations

    rng = np.random.default_rng(11)
    batches = []
    for off in range(0, rows, 32 * 1024):
        n = min(32 * 1024, rows - off)
        batches.append(pa.record_batch({
            "k": pa.array(rng.integers(0, 1 << 20, n)),
            "v": pa.array(rng.integers(0, 100, n)),
        }))
    schema = DFSchema.from_arrow(batches[0].schema)
    scan = MemoryScanExec(schema, batches, partitions=1)
    writer = ShuffleWriterExec(scan, "sjob", 1, partitions, [Column("k")])
    ctx = TaskContext(BallistaConfig(), task_id="t0", work_dir=str(tmp_path))
    locs: dict[int, list] = {p: [] for p in range(partitions)}
    for meta in writer.execute(0, ctx):
        for loc in metadata_to_locations(meta, "sjob", 1, 0, "e1", "127.0.0.1", 0):
            locs[loc.output_partition].append(loc)
    return str(tmp_path), locs, rows, schema


def test_streaming_merge_correct_and_window_bounded(tmp_path):
    """All rows arrive in location order; with a window smaller than one
    partition the prefetcher serializes, with a large window it overlaps."""
    import ballista_tpu.shuffle.reader as rd
    from ballista_tpu.flight.server import start_flight_server

    work, locs_by_p, rows, schema = _write_stage(tmp_path, rows=120_000, partitions=4)
    server, port = start_flight_server(work, "127.0.0.1", 0)
    active = [0]
    peak = [0]
    lock = threading.Lock()
    orig = rd.fetch_partition

    def tracking(loc, ctx, force_remote=False, governor=None, counters=None):
        with lock:
            active[0] += 1
            peak[0] = max(peak[0], active[0])
        try:
            yield from orig(loc, ctx, force_remote=force_remote, governor=governor)
        finally:
            with lock:
                active[0] -= 1

    def read_all(max_bytes):
        from ballista_tpu.shuffle.reader import ShuffleReaderExec
        from ballista_tpu.shuffle.types import PartitionLocation

        from ballista_tpu.config import SHUFFLE_FETCH_COALESCE

        # coalescing off: this test exercises the PER-LOCATION prefetch
        # window (all 6 duplicates share one address, so coalescing would
        # collapse them into a single RPC and bypass the window entirely)
        cfg = BallistaConfig({SHUFFLE_READER_FORCE_REMOTE: True,
                              SHUFFLE_READER_MAX_BYTES: max_bytes,
                              SHUFFLE_FETCH_COALESCE: False})
        ctx = TaskContext(cfg)
        got = 0
        peak[0] = 0
        # duplicate each output partition's single location 6× so one
        # execute(p) has a REAL multi-location merge to do
        reader = ShuffleReaderExec(schema, [
            [PartitionLocation(**{**l.__dict__, "flight_port": port})
             for l in locs_by_p[p] * 6]
            for p in range(4)
        ])
        for p in range(4):
            for b in reader.execute(p, ctx):
                got += b.num_rows
        return got

    rd.fetch_partition = tracking
    try:
        # tiny window: one fetch admitted at a time
        got = read_all(max_bytes=1)
        assert got == rows * 6
        assert peak[0] == 1, f"tiny window should serialize fetches, peak={peak[0]}"
        # large window: prefetch overlaps
        got = read_all(max_bytes=1 << 30)
        assert got == rows * 6
        assert peak[0] > 1, "large window should prefetch concurrently"
    finally:
        rd.fetch_partition = orig
        server.shutdown()


def test_streaming_merge_preserves_location_order(tmp_path):
    """Yield order is location order even when later fetches finish first."""
    from ballista_tpu.flight.server import start_flight_server
    from ballista_tpu.shuffle.reader import ShuffleReaderExec
    from ballista_tpu.shuffle.types import PartitionLocation

    work, locs_by_p, rows, schema = _write_stage(tmp_path, rows=50_000, partitions=2)
    server, port = start_flight_server(work, "127.0.0.1", 0)
    try:
        cfg = BallistaConfig({SHUFFLE_READER_FORCE_REMOTE: True})
        ctx = TaskContext(cfg)
        base = [PartitionLocation(**{**l.__dict__, "flight_port": port})
                for l in locs_by_p[0]]
        reader = ShuffleReaderExec(schema, [base * 4])
        first_ks = []
        per_loc_rows = sum(l.stats.num_rows for l in base)
        seen = 0
        for b in reader.execute(0, ctx):
            if seen % per_loc_rows == 0 and b.num_rows:
                first_ks.append(b.column(0)[0].as_py())
            seen += b.num_rows
        assert seen == per_loc_rows * 4
        # each copy of the location replays the identical stream
        assert len(set(first_ks)) == 1, first_ks
    finally:
        server.shutdown()


def test_spill_metrics_accounted(tmp_path):
    """Sort-shuffle spills surface as spilled_bytes/spill_count metrics."""
    from ballista_tpu.shuffle.writer import ShuffleWriterExec

    rng = np.random.default_rng(5)
    batches = [pa.record_batch({"k": pa.array(rng.integers(0, 1000, 64 * 1024)),
                                "v": pa.array(rng.integers(0, 10, 64 * 1024))})
               for _ in range(8)]
    schema = DFSchema.from_arrow(batches[0].schema)
    writer = ShuffleWriterExec(
        MemoryScanExec(schema, batches, partitions=1), "mjob", 1, 4, [Column("k")])
    cfg = BallistaConfig({SORT_SHUFFLE_MEMORY_LIMIT: 256 * 1024})
    ctx = TaskContext(cfg, task_id="t0", work_dir=str(tmp_path))
    list(writer.execute(0, ctx))
    m = writer.metrics.as_dict()
    assert m.get("spill_count", 0) >= 1, m
    assert m.get("spilled_bytes", 0) > 0, m


def test_pool_grow_wait_blocks_until_peer_shrinks():
    pool = MemoryPool(100)
    assert pool.try_grow(80)
    t0 = time.monotonic()

    def release_later():
        time.sleep(0.3)
        pool.shrink(80)

    threading.Thread(target=release_later, daemon=True).start()
    assert pool.grow_wait(50, timeout_s=5.0) is True
    assert time.monotonic() - t0 >= 0.25
    assert pool.reserved == 50 and pool.overcommitted == 0


def test_pool_grow_wait_deadline_overcommits():
    pool = MemoryPool(100)
    assert pool.try_grow(80)
    t0 = time.monotonic()
    assert pool.grow_wait(50, timeout_s=0.2) is False
    assert time.monotonic() - t0 >= 0.15
    assert pool.reserved == 130 and pool.overcommitted == 50


def test_pool_oversized_reservation_skips_the_deadline():
    """A reservation larger than the whole pool can never be satisfied by
    peers shrinking — it must overcommit immediately, not sleep."""
    pool = MemoryPool(100)
    t0 = time.monotonic()
    assert pool.grow_wait(500, timeout_s=10.0) is False
    assert time.monotonic() - t0 < 1.0
    assert pool.reserved == 500 and pool.overcommitted == 500


def test_concurrent_writers_share_pool_without_unbounded_overcommit(tmp_path):
    """Two sort-shuffle writers race on one tiny session pool: both finish,
    spills happen, reservations drain to zero, and any overcommit is the
    bounded deadline path (not the old unconditional grow)."""
    from ballista_tpu.shuffle.writer import ShuffleWriterExec

    rng = np.random.default_rng(8)
    pool = MemoryPool(512 * 1024)
    results = []

    def run(tag: str):
        batches = [pa.record_batch({
            "k": pa.array(rng.integers(0, 1000, 32 * 1024)),
            "v": pa.array(rng.integers(0, 10, 32 * 1024)),
        }) for _ in range(6)]
        schema = DFSchema.from_arrow(batches[0].schema)
        writer = ShuffleWriterExec(
            MemoryScanExec(schema, batches, partitions=1), f"cjob-{tag}", 1, 4, [Column("k")])
        cfg = BallistaConfig({SORT_SHUFFLE_MEMORY_LIMIT: 10 * 1024 * 1024,
                              SORT_SHUFFLE_POOL_WAIT_S: 0.5})
        ctx = TaskContext(cfg, task_id=tag, work_dir=str(tmp_path / tag))
        os.makedirs(ctx.work_dir, exist_ok=True)
        ctx.memory_pool = pool
        try:
            metas = list(writer.execute(0, ctx))
            results.append((tag, metas, writer.metrics.as_dict()))
        except Exception as e:  # noqa: BLE001
            results.append((tag, e, None))

    ts = [threading.Thread(target=run, args=(t,)) for t in ("a", "b")]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert len(results) == 2
    for tag, metas, m in results:
        assert not isinstance(metas, Exception), (tag, metas)
    assert pool.reserved == 0  # every hold (including overcommit) drained
    # at least one writer had to spill under the shared budget
    assert any((m or {}).get("spill_count", 0) >= 1 for _, _, m in results), results


def test_extra_metrics_survive_control_plane_wire():
    """Operator extras (spilled_bytes, spill_count, tpu counters, ...) must
    round-trip TaskStatusProto — the distributed path feeding EXPLAIN
    ANALYZE and the REST percentiles, not just in-process standalone."""
    from ballista_tpu.executor.executor import TaskResult
    from ballista_tpu.scheduler.state.executor_manager import ExecutorMetadata
    from ballista_tpu.serde_control import decode_task_status, encode_task_status

    r = TaskResult(
        task_id=1, job_id="j", stage_id=2, stage_attempt=0, partitions=[0],
        state="success",
        metrics=[{"name": "ShuffleWriterExec: h", "output_rows": 10,
                  "elapsed_ns": 123, "depth": 0,
                  "spilled_bytes": 4096, "spill_count": 2}],
    )
    meta = ExecutorMetadata(id="e1", host="h", grpc_port=1, flight_port=2)
    back = decode_task_status(encode_task_status(r, "e1"), meta)
    (m,) = back.metrics
    assert m["spilled_bytes"] == 4096 and m["spill_count"] == 2
    assert m["name"] == "ShuffleWriterExec: h" and m["elapsed_ns"] == 123


# -- coalesced, zero-copy data plane ------------------------------------------


def _write_multi_map(tmp_path, maps=4, partitions=3):
    """M hash-layout map outputs for one stage; returns (work_dir, locations
    by output partition, row counts by output partition, df schema)."""
    import pyarrow.ipc as ipc

    from ballista_tpu.shuffle import paths as sp
    from ballista_tpu.shuffle.types import PartitionLocation, PartitionStats

    schema = pa.schema([("k", pa.int64()), ("m", pa.int64())])
    locs: dict[int, list] = {r: [] for r in range(partitions)}
    rows = {r: 0 for r in range(partitions)}
    for m in range(maps):
        for r in range(partitions):
            os.makedirs(sp.hash_partition_dir(str(tmp_path), "cjob", 1, r), exist_ok=True)
            p = sp.hash_data_path(str(tmp_path), "cjob", 1, r, f"t{m}")
            n = 7 * (m + 1) + r
            batch = pa.record_batch(
                {"k": pa.array(np.arange(n, dtype="int64")),
                 "m": pa.array(np.full(n, m, dtype="int64"))})
            with ipc.new_stream(p, batch.schema) as w:
                w.write_batch(batch)
            rows[r] += n
            locs[r].append(PartitionLocation(
                map_partition=m, job_id="cjob", stage_id=1, output_partition=r,
                executor_id="e1", host="127.0.0.1", flight_port=0, path=p,
                layout="hash", stats=PartitionStats(n, 1, os.path.getsize(p))))
    return str(tmp_path), locs, rows, DFSchema.from_arrow(schema)


def _reader_ctx(extra=None):
    from ballista_tpu.config import SHUFFLE_READER_FORCE_REMOTE as FR

    cfg = BallistaConfig({FR: True, **(extra or {})})
    return cfg, TaskContext(cfg, task_id="t", work_dir="")


def test_coalesced_fetch_one_rpc_per_executor(tmp_path):
    """A reduce partition pulling M map outputs from ONE executor must issue
    exactly one coalesced RPC (M·R block RPCs with coalescing off)."""
    from ballista_tpu.config import SHUFFLE_FETCH_COALESCE
    from ballista_tpu.flight.server import start_flight_server
    from ballista_tpu.shuffle.reader import ShuffleReaderExec

    work, locs, rows, schema = _write_multi_map(tmp_path, maps=4, partitions=3)
    server, port = start_flight_server(work, "127.0.0.1", 0)
    try:
        for r in locs:
            for l in locs[r]:
                l.flight_port = port
        _, ctx = _reader_ctx()
        reader = ShuffleReaderExec(schema, [locs[r] for r in sorted(locs)])
        for r in sorted(locs):
            got = sum(b.num_rows for b in reader.execute(r, ctx))
            assert got == rows[r]
        assert server.stats["coalesced_rpc"] == len(locs)
        assert server.stats["block_rpc"] == 0
        assert reader.metrics.extra["fetch_rpcs"] == 1  # last partition: 1 RPC
        assert reader.metrics.extra["bytes_fetched_remote"] > 0
        assert "time_to_first_batch_ns" in reader.metrics.extra

        before = server.stats["block_rpc"]
        _, ctx_off = _reader_ctx({SHUFFLE_FETCH_COALESCE: False})
        reader2 = ShuffleReaderExec(schema, [locs[r] for r in sorted(locs)])
        for r in sorted(locs):
            assert sum(b.num_rows for b in reader2.execute(r, ctx_off)) == rows[r]
        assert server.stats["block_rpc"] - before == 4 * 3  # M·R uncoalesced
    finally:
        server.shutdown()


def test_coalesced_midstream_failure_maps_to_right_identity(tmp_path):
    """Losing map j's file mid-stream must surface as FetchFailed carrying
    map j's identity (locations before j were already served) so the
    scheduler recomputes the RIGHT upstream partition."""
    from ballista_tpu.config import IO_RETRIES, IO_RETRY_WAIT_MS
    from ballista_tpu.errors import FetchFailed
    from ballista_tpu.flight.server import start_flight_server
    from ballista_tpu.shuffle.reader import ShuffleReaderExec

    work, locs, rows, schema = _write_multi_map(tmp_path, maps=4, partitions=1)
    server, port = start_flight_server(work, "127.0.0.1", 0)
    try:
        for l in locs[0]:
            l.flight_port = port
        os.remove(locs[0][2].path)  # lose map 2, maps 0-1 still stream fine
        _, ctx = _reader_ctx({IO_RETRIES: 1, IO_RETRY_WAIT_MS: 1})
        reader = ShuffleReaderExec(schema, [locs[0]])
        with pytest.raises(FetchFailed) as ei:
            list(reader.execute(0, ctx))
        assert ei.value.map_partition == 2
        assert ei.value.job_id == "cjob" and ei.value.stage_id == 1
    finally:
        server.shutdown()


def test_do_get_streams_without_read_all(tmp_path, monkeypatch):
    """The decoded do_get path must be a true stream: neither the server nor
    the relay may materialize the partition with read_all()."""
    import pyarrow.ipc as ipc

    from ballista_tpu.config import SHUFFLE_BLOCK_TRANSPORT
    from ballista_tpu.flight.server import start_flight_server
    from ballista_tpu.shuffle.reader import ShuffleReaderExec

    def boom(self, *a, **k):
        raise AssertionError("read_all() materializes the whole partition")

    monkeypatch.setattr(ipc.RecordBatchStreamReader, "read_all", boom)
    work, locs, rows, schema = _write_multi_map(tmp_path, maps=3, partitions=1)
    server, port = start_flight_server(work, "127.0.0.1", 0)
    try:
        for l in locs[0]:
            l.flight_port = port
        _, ctx = _reader_ctx({SHUFFLE_BLOCK_TRANSPORT: False})
        reader = ShuffleReaderExec(schema, [locs[0]])
        assert sum(b.num_rows for b in reader.execute(0, ctx)) == rows[0]
        assert server.stats["do_get"] >= 1
    finally:
        server.shutdown()


def test_sort_layout_range_serves_identically_with_and_without_mmap(tmp_path, monkeypatch):
    """Sort-layout byte ranges must decode identically as zero-copy mmap
    slices and as plain reads (the env escape hatch)."""
    from ballista_tpu.flight.server import start_flight_server
    from ballista_tpu.shuffle.reader import ShuffleReaderExec

    work, locs_by_p, total_rows, schema = _write_stage(tmp_path, rows=50_000, partitions=4)
    server, port = start_flight_server(work, "127.0.0.1", 0)
    try:
        from ballista_tpu.shuffle.types import PartitionLocation

        plocs = [[PartitionLocation(**{**l.__dict__, "flight_port": port})
                  for l in locs_by_p[p]] for p in range(4)]

        def read_all_rows():
            _, ctx = _reader_ctx()
            reader = ShuffleReaderExec(schema, plocs)
            return [sum(b.num_rows for b in reader.execute(p, ctx)) for p in range(4)]

        with_mmap = read_all_rows()
        monkeypatch.setenv("BALLISTA_SHUFFLE_MMAP", "0")
        without_mmap = read_all_rows()
        assert with_mmap == without_mmap
        assert sum(with_mmap) == total_rows
    finally:
        server.shutdown()


def test_proxy_relays_coalesced_tickets_verbatim(tmp_path):
    """External mode: the scheduler proxy must pass a coalesced stream
    through unchanged — framing intact, ONE upstream RPC."""
    from ballista_tpu.config import FLIGHT_PROXY
    from ballista_tpu.flight.proxy import start_flight_proxy
    from ballista_tpu.flight.server import start_flight_server
    from ballista_tpu.shuffle.reader import ShuffleReaderExec

    work, locs, rows, schema = _write_multi_map(tmp_path, maps=4, partitions=1)
    server, port = start_flight_server(work, "127.0.0.1", 0)
    proxy, proxy_port = start_flight_proxy("127.0.0.1", 0)
    try:
        for l in locs[0]:
            l.flight_port = port
        _, ctx = _reader_ctx({FLIGHT_PROXY: f"127.0.0.1:{proxy_port}"})
        reader = ShuffleReaderExec(schema, [locs[0]])
        assert sum(b.num_rows for b in reader.execute(0, ctx)) == rows[0]
        assert server.stats["coalesced_rpc"] == 1  # one RPC reached the executor
        assert proxy.stats["relayed_actions"] == 1
    finally:
        proxy.shutdown()
        server.shutdown()


def test_coalesce_falls_back_when_server_lacks_action(tmp_path):
    """Against a data plane without io_coalesced_transport (e.g. an older
    native server) the client must cache the capability miss and fall back
    to per-location fetches — same rows, no error."""
    import json

    import pyarrow.flight as flight

    from ballista_tpu.flight import client as fc
    from ballista_tpu.flight.server import BallistaFlightServer
    from ballista_tpu.shuffle.reader import ShuffleReaderExec

    class LegacyServer(BallistaFlightServer):
        def do_action(self, context, action):
            if action.type == "io_coalesced_transport":
                raise flight.FlightServerError(f"unknown action {action.type}")
            yield from super().do_action(context, action)

    work, locs, rows, schema = _write_multi_map(tmp_path, maps=3, partitions=1)
    server = LegacyServer("127.0.0.1", 0, work)
    port = server.port
    t = threading.Thread(target=server.serve, daemon=True)
    t.start()
    try:
        for l in locs[0]:
            l.flight_port = port
        _, ctx = _reader_ctx()
        reader = ShuffleReaderExec(schema, [locs[0]])
        assert sum(b.num_rows for b in reader.execute(0, ctx)) == rows[0]
        assert f"127.0.0.1:{port}" in fc._NO_COALESCE
        assert server.stats["block_rpc"] == 3  # per-location fallback
    finally:
        with fc._NO_COALESCE_LOCK:
            fc._NO_COALESCE.discard(f"127.0.0.1:{port}")
        server.shutdown()


def test_chained_buffer_reader_exact_reads():
    """ipc decode over the chained reader: read(n) must return exactly n
    bytes across block boundaries, and odd server block sizes must not
    corrupt the stream (no b''.join reassembly anywhere)."""
    import pyarrow.ipc as ipc

    from ballista_tpu.flight.client import ChainedBufferReader

    batch = pa.record_batch({"x": pa.array(np.arange(10_000, dtype="int64"))})
    sink = pa.BufferOutputStream()
    with ipc.new_stream(sink, batch.schema) as w:
        for _ in range(5):
            w.write_batch(batch)
    blob = sink.getvalue().to_pybytes()
    for block in (7, 1024, 100_000, len(blob) + 1):
        blocks = [blob[i:i + block] for i in range(0, len(blob), block)]
        r = ChainedBufferReader([pa.py_buffer(b) for b in blocks])
        got = list(ipc.open_stream(r))
        assert sum(b.num_rows for b in got) == 50_000
    # raw semantics: exact-n reads spanning blocks, zero-copy within one
    r = ChainedBufferReader([pa.py_buffer(b"abc"), pa.py_buffer(b"defgh")])
    assert bytes(r.read(2)) == b"ab"
    assert bytes(r.read(3)) == b"cde"  # spans the boundary
    assert bytes(r.read(-1)) == b"fgh"
    assert r.read(10) == b""
