"""Warm device-runtime daemon: lifecycle, parity, quotas, fallback.

Everything runs under jax CPU (JAX_PLATFORMS=cpu — the tier-1 harness
env, forced onto spawned daemons by the fixtures): the daemon protocol,
attach ladder, session quotas, and byte parity are platform-independent,
which is the point — the attached path must be indistinguishable from
the in-process engine in everything but where the work happened.
"""

import io
import json
import os
import socket as socketlib
import time

import numpy as np
import pyarrow as pa
import pytest

from ballista_tpu.config import (
    CHAOS_DAEMON_ARM,
    CHAOS_DAEMON_ONCE,
    CHAOS_ENABLED,
    CHAOS_MODE,
    EXECUTOR_ENGINE,
    TPU_DAEMON_ATTACH_TIMEOUT_MS,
    TPU_DAEMON_ENABLED,
    TPU_DAEMON_EXECUTE_TIMEOUT_S,
    TPU_DAEMON_SESSION_QUOTA_BYTES,
    TPU_DAEMON_SOCKET,
    TPU_DAEMON_SPAWN,
    TPU_MIN_ROWS,
    BallistaConfig,
)
from ballista_tpu.device_daemon import client as dclient
from ballista_tpu.device_daemon import protocol as dproto

SQL = ("SELECT cat, sum(price) AS s, count(*) AS c, avg(qty) AS q "
       "FROM t GROUP BY cat ORDER BY cat")


def _table(n=20_000, seed=11):
    rng = np.random.default_rng(seed)
    return pa.table({
        "cat": rng.choice([f"c{i}" for i in range(7)], n),
        "price": np.round(rng.uniform(1, 100, n), 2),
        "qty": rng.integers(1, 50, n),
    })


def _run_query(tbl, **cfg_extra):
    import ballista_tpu.ops.tpu.stage_compiler as sc
    from ballista_tpu.client.context import SessionContext

    cfg = BallistaConfig({EXECUTOR_ENGINE: "tpu", TPU_MIN_ROWS: 0, **cfg_extra})
    ctx = SessionContext(cfg)
    ctx.register_arrow_table("t", tbl, partitions=3)
    sc.RUN_STATS.clear()
    out = ctx.sql(SQL).collect()
    return out, sc.RUN_STATS.snapshot()


def _spawn_and_wait(sock_path, timeout_s=60.0):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = dclient.spawn_daemon(sock_path, parent_pid=os.getpid(), env=env)
    client = dclient.DaemonClient(sock_path)
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"daemon died rc={proc.returncode}: "
                + open(dproto.daemon_log_path(sock_path)).read()[-2000:])
        try:
            client.wait_ready(timeout_s=5.0, poll_s=0.2)
            return proc, client
        except dclient.DaemonUnavailable:
            time.sleep(0.2)
    raise RuntimeError(f"daemon not ready in {timeout_s}s")


@pytest.fixture(scope="module")
def daemon(tmp_path_factory):
    sock = str(tmp_path_factory.mktemp("daemon") / "d.sock")
    proc, client = _spawn_and_wait(sock)
    yield sock, client
    client.shutdown()
    try:
        proc.wait(timeout=10)
    except Exception:  # noqa: BLE001
        proc.kill()
    dclient.reset_attach_cache()


@pytest.fixture(autouse=True)
def _clean_attach_cache():
    yield
    dclient.reset_attach_cache()


def _daemon_cfg(sock, **extra):
    return {TPU_DAEMON_ENABLED: True, TPU_DAEMON_SOCKET: sock,
            TPU_DAEMON_ATTACH_TIMEOUT_MS: 10_000, **extra}


# ------------------------------------------------------------- lifecycle

def test_spawn_attach_status(daemon):
    sock, client = daemon
    st = client.status()
    assert st["ready"] is True
    phases = {p["name"]: p for p in st["init"]["phases"]}
    assert set(phases) == {"platform_probe", "jax_devices", "first_compile"}
    assert all(p["status"] == "ok" for p in phases.values())
    # probe report persisted next to the socket, matching status
    report = json.load(open(dproto.probe_report_path(sock)))
    assert report["ok"] is True
    assert report["pid"] == st["pid"]


def test_attach_is_cached_and_reattaches(daemon):
    sock, _ = daemon
    cfg = BallistaConfig(_daemon_cfg(sock))
    c1, mode1, _ = dclient.attach(cfg)
    assert mode1 == "attached" and c1 is not None
    c2, mode2, _ = dclient.attach(cfg)
    assert c2 is c1  # cached per (socket, pid)
    # a "crashed" client (lost state) re-runs the ladder and lands on the
    # same live daemon without spawning a second one
    dclient.reset_attach_cache()
    c3, mode3, _ = dclient.attach(cfg)
    assert mode3 == "attached"
    assert c3.ping()["pid"] == c1.ping()["pid"]


def test_daemon_survives_client_crash_mid_frame(daemon):
    sock, client = daemon
    # a client that dies mid-message must not take the daemon down
    raw = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
    raw.connect(sock)
    raw.sendall(b"\x00\x00\x10\x00garbage-partial-frame")
    raw.close()
    time.sleep(0.2)
    assert client.ping()["pid"] > 0
    out, stats = _run_query(_table(), **_daemon_cfg(sock))
    assert stats.get("daemon_mode") == "attached"
    assert out.num_rows == 7


# ---------------------------------------------------------------- parity

def test_attached_byte_identical_to_in_process(daemon):
    sock, client = daemon
    tbl = _table()
    base, base_stats = _run_query(tbl)
    att, att_stats = _run_query(tbl, **_daemon_cfg(sock))
    assert att_stats.get("daemon_mode") == "attached"
    assert att_stats.get("daemon_attached") == 1.0
    assert "daemon_mode" not in base_stats

    def ipc_bytes(t):
        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, t.schema) as w:
            w.write_table(t)
        return sink.getvalue()

    assert att.equals(base)
    assert ipc_bytes(att) == ipc_bytes(base)
    # the daemon mirrored its engine stats into the client's RUN_STATS
    assert att_stats.get("exec_s") is not None
    # daemon-side init phase timings rode back for the heartbeat gauges
    assert att_stats.get("init_jax_devices_s") is not None


def test_executor_heartbeat_exports_daemon_gauges(daemon):
    sock, _ = daemon
    _run_query(_table(), **_daemon_cfg(sock))
    from ballista_tpu.executor.executor_process import ExecutorProcess

    metrics = dict(ExecutorProcess._tpu_metrics())
    assert metrics.get("tpu_daemon_attached") == 1.0
    assert "daemon_sessions" in metrics
    assert "daemon_queue_depth" in metrics
    assert "tpu_init_jax_devices_s" in metrics


# ------------------------------------------------------- session quotas

def test_session_quota_clamps_budget():
    from ballista_tpu.config import TPU_HBM_BUDGET_BYTES
    from ballista_tpu.ops.tpu import hbm

    cfg = BallistaConfig({TPU_HBM_BUDGET_BYTES: 1 << 30})
    assert hbm.resolve_hbm_budget(cfg) == 1 << 30
    with hbm.session_quota(1 << 20):
        assert hbm.resolve_hbm_budget(cfg) == 1 << 20
        with hbm.session_quota(0):  # inner scope: no ceiling
            assert hbm.resolve_hbm_budget(cfg) == 1 << 30
    assert hbm.resolve_hbm_budget(cfg) == 1 << 30


def test_session_quota_forces_spill_plan():
    from ballista_tpu.config import TPU_HBM_BUDGET_BYTES
    from ballista_tpu.ops.tpu import hbm
    from ballista_tpu.ops.tpu.fusion import StageEstimate

    est = StageEstimate(
        rows=1 << 20, partitions=2, group_domain=8, n_group_keys=1, lanes=1,
        has_mult=False, n_filters=0, n_projections=0, n_joins=0,
        max_probe_table=0, table_bytes=4 << 20, dict_bytes=1 << 20)
    cfg = BallistaConfig({TPU_HBM_BUDGET_BYTES: 1 << 30})
    roomy = hbm.plan_stage(est, hbm.resolve_hbm_budget(cfg),
                           grace_eligible=True, grace_fanout=8,
                           grace_max_depth=2, resident_other=2 << 20)
    assert roomy.decision == hbm.RUN_WHOLE
    # same stage, same knobs, but admitted under a 6 MiB session quota:
    # the cold residents no longer fit beside it — spill becomes the plan
    with hbm.session_quota(6 << 20):
        tight = hbm.plan_stage(est, hbm.resolve_hbm_budget(cfg),
                               grace_eligible=True, grace_fanout=8,
                               grace_max_depth=2, resident_other=2 << 20)
    assert tight.decision == hbm.SPILL_COLDS


def test_session_quota_enforced_through_daemon(daemon):
    sock, client = daemon
    import ballista_tpu.ops.tpu.stage_compiler as sc

    quota = 2 << 20
    _, stats = _run_query(
        _table(), **_daemon_cfg(sock, **{TPU_DAEMON_SESSION_QUOTA_BYTES: quota}))
    assert stats.get("daemon_mode") == "attached"
    # the daemon-side admission ran against the clamped budget and
    # mirrored it back into the attached stage's record. (The flat
    # snapshot also carries the CLIENT-side final stage's budget, which
    # is unclamped by design — the quota governs daemon-resident work.)
    attached = [r for r in sc.RUN_STATS.stages().values()
                if r.get("daemon_mode") == "attached"]
    assert attached and attached[-1].get("hbm_budget_bytes") == quota
    st = client.status()
    sess = [s for s in st["session_detail"].values()
            if s["quota_bytes"] == quota]
    assert sess and sess[0]["executes"] >= 1


# ------------------------------------------------- stale socket + fallback

def test_stale_socket_cleanup(tmp_path):
    stale = str(tmp_path / "stale.sock")
    lst = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
    lst.bind(stale)
    lst.close()  # the path stays behind: classic dead-daemon litter
    assert os.path.exists(stale)
    cfg = BallistaConfig(_daemon_cfg(stale, **{TPU_DAEMON_SPAWN: False,
                                               TPU_DAEMON_ATTACH_TIMEOUT_MS: 500}))
    c, mode, reason = dclient.attach(cfg)
    assert c is None and mode == "in_process"
    assert "stale socket removed" in reason
    assert not os.path.exists(stale)


def test_graceful_fallback_when_no_daemon(tmp_path):
    sock = str(tmp_path / "nobody-home.sock")
    out, stats = _run_query(
        _table(), **_daemon_cfg(sock, **{TPU_DAEMON_ATTACH_TIMEOUT_MS: 300}))
    assert out.num_rows == 7  # the query still ran, in-process
    assert stats.get("daemon_mode") == "in_process"
    assert str(stats.get("daemon_mode_reason", "")).startswith("attach_failed")
    assert stats.get("daemon_attached") == 0.0


# -------------------------------------------------------- cache clearing

def test_clear_device_caches_routes_to_daemon(daemon):
    sock, client = daemon
    import ballista_tpu.ops.tpu.stage_compiler as sc

    _run_query(_table(), **_daemon_cfg(sock))
    before = client.status()
    assert before["compiled_entries"] >= 1
    clears = before["clear_count"]
    sc.clear_device_caches()  # attached process: must forward to the daemon
    after = client.status()
    assert after["clear_count"] == clears + 1
    assert after["compiled_entries"] == 0


# ------------------------------------------------------- failure domain

def _ipc_bytes(t):
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, t.schema) as w:
        w.write_table(t)
    return sink.getvalue()


def _shutdown_daemon(sock):
    """Best-effort cleanup of a per-test daemon (alive or already dead)."""
    try:
        dclient.DaemonClient(sock, timeout_s=5.0).shutdown()
    except Exception:  # noqa: BLE001 — a corpse is fine, that's the point
        pass


def _chaos_cfg(sock, mode, arm="mid_execute", once=True, **extra):
    # spawn=True so the respawn-and-retry leg of the ladder can bring a
    # fresh daemon back after the injected crash; generous attach timeout
    # because each respawn pays a cold jax-CPU init
    return _daemon_cfg(sock, **{
        TPU_DAEMON_SPAWN: True, TPU_DAEMON_ATTACH_TIMEOUT_MS: 60_000,
        CHAOS_ENABLED: True, CHAOS_MODE: mode,
        CHAOS_DAEMON_ARM: arm, CHAOS_DAEMON_ONCE: once, **extra})


def test_derived_execute_deadline():
    assert dproto.derive_execute_timeout_s(120, 0) == 120.0
    # +1s per 16 MiB of stage input
    assert dproto.derive_execute_timeout_s(120, 1 << 30) == 184.0
    assert dproto.derive_execute_timeout_s(10, 1 << 40) == 80.0  # cap: 8x floor
    assert dproto.derive_execute_timeout_s(0, 0) == 1.0  # floor clamp


def test_generation_token_minted_and_echoed(daemon):
    sock, client = daemon
    gen = client.ping().get("gen")
    assert gen  # minted at bind time
    assert client.status().get("gen") == gen
    cfg = BallistaConfig(_daemon_cfg(sock))
    c, mode, _ = dclient.attach(cfg)
    assert mode == "attached"
    assert dclient.attached_generation(sock) == gen


def test_watchdog_kills_wedged_execute(tmp_path):
    """daemon_hang wedges the execute thread before serde decode; the
    watchdog overruns the shipped deadline, writes the post-mortem, and
    exits 4 — the client sees a typed DaemonCrashed."""
    sock = str(tmp_path / "hang.sock")
    # a leftover post-mortem from a previous corpse must not survive a
    # fresh bind (it would misclassify the NEXT crash as a watchdog kill)
    with open(dproto.crash_report_path(sock), "w") as f:
        f.write("{}")
    proc, client = _spawn_and_wait(sock)
    try:
        assert not os.path.exists(dproto.crash_report_path(sock))
        gen = client.ping()["gen"]
        cfg = BallistaConfig(_chaos_cfg(sock, "daemon_hang", arm="pre_execute"))
        with pytest.raises(dclient.DaemonCrashed):
            client.execute(b"never-decoded", cfg.to_key_value_pairs(), [0],
                           tag="stage_deadbeef", deadline_s=2.0)
        assert proc.wait(timeout=30) == 4  # diagnosed death, not a raw abort
        report = dclient.read_crash_report(sock)
        assert report is not None
        assert report["kind"] == "watchdog"
        assert report["generation"] == gen
        # the offending request header rode into the post-mortem — minus
        # the bulky config pairs
        assert report["request"]["tag"] == "stage_deadbeef"
        assert "pairs" not in report["request"]
        assert report["deadline_s"] == 2.0
        assert report["stacks"]  # every thread's stack, via faulthandler
    finally:
        _shutdown_daemon(sock)


@pytest.mark.parametrize("mode", ["daemon_crash", "daemon_hang"])
def test_crash_recovery_respawn_byte_parity(tmp_path, mode):
    """One injected daemon death mid-query (SIGKILL-style exit or a hang
    the watchdog converts to one): the stage ladder respawns, retries
    once, and the answer is byte-identical to the in-process run."""
    sock = str(tmp_path / f"{mode}.sock")
    tbl = _table()
    base, _ = _run_query(tbl)
    dclient.reset_failure_counters()
    extra = {}
    if mode == "daemon_hang":
        # short deadline so the watchdog converts the hang into a death
        # quickly; roomy enough that the retry's recompile+execute fits
        extra[TPU_DAEMON_EXECUTE_TIMEOUT_S] = 12
    try:
        out, stats = _run_query(tbl, **_chaos_cfg(sock, mode, **extra))
        assert out.equals(base)
        assert _ipc_bytes(out) == _ipc_bytes(base)
        c = dclient.failure_counters()
        assert c["daemon_crashes_detected"] >= 1
        assert c["daemon_restarts"] >= 1  # the respawn leg recovered it
        assert c["poisoned_stages"] == 0  # once-armed: no quarantine
        if mode == "daemon_hang":
            # classified from the <socket>.crash.json post-mortem
            assert c["watchdog_kills"] >= 1
        # the recovery is visible in the run's stats (→ heartbeat gauges)
        assert stats.get("daemon_restarts", 0) >= 1
        import ballista_tpu.ops.tpu.stage_compiler as sc
        recs = sc.RUN_STATS.stages().values()
        assert any(r.get("daemon_failover") == "daemon_restarted"
                   for r in recs)
    finally:
        _shutdown_daemon(sock)


def test_poison_quarantine_demotes_after_second_crash(tmp_path):
    """Without once-arming every daemon incarnation dies on the stage:
    the second crash per fingerprint quarantines it and the stage demotes
    to the in-process ladder — byte-identically, with no crash loop."""
    sock = str(tmp_path / "poison.sock")
    tbl = _table()
    base, _ = _run_query(tbl)
    dclient.reset_failure_counters()
    try:
        out, stats = _run_query(
            tbl, **_chaos_cfg(sock, "daemon_crash", once=False))
        assert out.equals(base)
        assert _ipc_bytes(out) == _ipc_bytes(base)
        c = dclient.failure_counters()
        assert c["daemon_crashes_detected"] >= 2
        assert c["poisoned_stages"] >= 1
        assert stats.get("daemon_failover") == "poisoned"
        # the quarantine is on disk, keyed by stage tag, TTL'd
        entries = json.load(
            open(dproto.poison_path(sock))).get("entries", {})
        assert any(t.startswith("stage_") for t in entries)
        assert all(e["crashes"] >= dclient.POISON_CRASH_THRESHOLD
                   for e in entries.values())
        # second run: quarantined stages demote WITHOUT touching a daemon
        # (no new crashes, no respawn storm — the loop is broken)
        before = dclient.failure_counters()["daemon_crashes_detected"]
        out2, stats2 = _run_query(
            tbl, **_chaos_cfg(sock, "daemon_crash", once=False))
        assert _ipc_bytes(out2) == _ipc_bytes(base)
        assert stats2.get("daemon_mode") == "in_process"
        assert stats2.get("daemon_failover") == "poisoned"
        assert dclient.failure_counters()["daemon_crashes_detected"] == before
    finally:
        _shutdown_daemon(sock)
        dclient.clear_poison(sock)


def test_poison_entries_expire_after_ttl(tmp_path):
    sock = str(tmp_path / "ttl.sock")
    assert dclient.record_stage_crash(sock, "stage_oldwound", "fp", 600) == 1
    assert not dclient.is_poisoned(sock, "stage_oldwound", 600)  # 1 < threshold
    assert dclient.record_stage_crash(sock, "stage_oldwound", "fp", 600) == 2
    assert dclient.is_poisoned(sock, "stage_oldwound", 600)
    # age the entry past the TTL window: the quarantine lifts
    p = dproto.poison_path(sock)
    data = json.load(open(p))
    data["entries"]["stage_oldwound"]["updated"] = time.time() - 10_000
    with open(p, "w") as f:
        json.dump(data, f)
    assert not dclient.is_poisoned(sock, "stage_oldwound", 600)
    # and the count restarts from scratch — old crashes don't haunt
    assert dclient.record_stage_crash(sock, "stage_oldwound", "fp", 600) == 1


def test_lease_stale_generation_fences_direct_dispatch():
    from ballista_tpu.serving.lease import LeaseRegistry, LeaseTable

    live = {"gen": "boot-1"}
    table = LeaseTable(generation_probe=lambda: live["gen"])
    reg = LeaseRegistry()
    lease = reg.mint("exec-1", "h", 50050, "s", slots=2, ttl_s=30.0)
    assert lease.daemon_generation == ""  # scheduler can't see the daemon
    # the generation survives the wire round trip (Flight action body)
    from ballista_tpu.serving.lease import ExecutorLease
    assert ExecutorLease.from_wire(lease.to_wire()).daemon_generation == ""
    table.grant(lease)  # executor stamps its live generation at grant
    tid = lease.take_task_id()
    assert table.admit(lease.lease_id, tid) is None
    table.release(lease.lease_id)
    live["gen"] = "boot-2"  # the daemon silently restarted
    tid2 = lease.take_task_id()
    assert table.admit(lease.lease_id, tid2) == "stale-daemon-generation"
    assert table.rejections >= 1
    # an unfenced lease (executor not attached at grant time) never fences
    live["gen"] = ""
    table2 = LeaseTable(generation_probe=lambda: live["gen"])
    lease2 = reg.mint("exec-2", "h", 50051, "s", slots=2, ttl_s=30.0)
    table2.grant(lease2)
    live["gen"] = "boot-9"
    assert table2.admit(lease2.lease_id, lease2.take_task_id()) is None
