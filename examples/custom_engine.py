"""Extension-point demo: a custom ExecutionEngine behind the engine seam.

The reference exposes `ExecutionEngine` as THE executor extension trait
(executor/src/execution_engine.rs:51) and ships custom scheduler/executor
example binaries; this is the equivalent here — wrap stage preparation to
observe or rewrite every stage plan an executor runs.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from ballista_tpu.executor.executor import ExecutionEngine
from ballista_tpu.executor.standalone import StandaloneCluster
from ballista_tpu.client.context import SessionContext


class AuditingEngine(ExecutionEngine):
    """Logs every stage plan before execution (a monitoring/rewrite hook)."""

    def __init__(self):
        super().__init__()
        self.stages_seen = 0

    def create_query_stage_exec(self, plan, config, stage_attempt=0):
        self.stages_seen += 1
        print(f"[audit] stage #{self.stages_seen} attempt={stage_attempt}:")
        print("  " + plan.display().replace("\n", "\n  ")[:300])
        return super().create_query_stage_exec(plan, config, stage_attempt)


def main():
    d = tempfile.mkdtemp()
    rng = np.random.default_rng(0)
    pq.write_table(pa.table({
        "k": rng.integers(0, 100, 10_000), "v": rng.integers(0, 50, 10_000),
    }), f"{d}/t.parquet")

    engine = AuditingEngine()
    cluster = StandaloneCluster(num_executors=1, vcores=2, engine_factory=lambda: engine)
    try:
        ctx = SessionContext.standalone()
        ctx._cluster = cluster
        ctx.register_parquet("t", f"{d}/t.parquet")
        out = ctx.sql("select k, sum(v) s from t group by k order by s desc limit 3").collect()
        print(out.to_pandas())
        print(f"custom engine observed {engine.stages_seen} stages")
        assert engine.stages_seen >= 2
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
