"""Window functions + ROLLUP through the distributed standalone cluster."""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from ballista_tpu.client.context import SessionContext

rng = np.random.default_rng(0)
n = 100_000
sales = pa.table({
    "region": rng.choice(["emea", "amer", "apac"], n),
    "rep": rng.choice([f"rep{i}" for i in range(20)], n),
    "amount": np.round(rng.uniform(10, 5000, n), 2),
})
path = os.path.join(tempfile.mkdtemp(), "sales.parquet")
pq.write_table(sales, path)

ctx = SessionContext.standalone()
ctx.register_parquet("sales", path)

print("-- top 3 reps per region (window ranking over a hash exchange) --")
print(ctx.sql("""
    SELECT region, rep, total FROM (
        SELECT region, rep, sum(amount) AS total,
               rank() OVER (PARTITION BY region ORDER BY sum(amount) DESC) AS r
        FROM sales GROUP BY region, rep
    ) t WHERE r <= 3 ORDER BY region, total DESC
""").collect().to_pandas())

print("-- rollup subtotals --")
print(ctx.sql("""
    SELECT region, rep, sum(amount) AS total
    FROM sales GROUP BY ROLLUP(region, rep)
    ORDER BY region, rep LIMIT 10
""").collect().to_pandas())

print("-- 7-row moving average --")
print(ctx.sql("""
    SELECT region, amount,
           avg(amount) OVER (PARTITION BY region ORDER BY amount
                             ROWS BETWEEN 6 PRECEDING AND CURRENT ROW) AS ma
    FROM sales ORDER BY region, amount LIMIT 5
""").collect().to_pandas())
ctx.shutdown()
print("OK")
