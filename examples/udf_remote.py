"""Scalar UDFs shipped by reference to a real cluster.

UDFs defined in an importable module re-register on executors via
`ballista.udf.modules` (see ballista_tpu/udf.py). Run a scheduler +
executor first:

    python -m ballista_tpu.scheduler --port 50050 &
    python -m ballista_tpu.executor --scheduler localhost:50050 &
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pyarrow as pa
import pyarrow.parquet as pq

from ballista_tpu.client.context import SessionContext
from ballista_tpu.testing.udf_fixtures import double_it, shout

addr = sys.argv[1] if len(sys.argv) > 1 else "localhost:50050"
pq.write_table(pa.table({"x": [5, 6], "s": ["hey", "yo"]}), "/tmp/udf_demo.parquet")

ctx = SessionContext.remote(addr)
ctx.register_parquet("t", "/tmp/udf_demo.parquet")
ctx.register_udf("double_it", double_it, pa.int64())
ctx.register_udf("shout", shout, pa.string())
print(ctx.sql("select double_it(x) d, shout(s) u from t order by d").collect().to_pandas())
