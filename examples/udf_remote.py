"""Scalar UDFs shipped by reference to a real cluster.

UDFs defined in an importable module re-register on executors via
`ballista.udf.modules` (see ballista_tpu/udf.py). Run a scheduler +
executor first:

    python -m ballista_tpu.scheduler --port 50050 &
    python -m ballista_tpu.executor --scheduler localhost:50050 &
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pyarrow as pa
import pyarrow.parquet as pq

from ballista_tpu.client.context import SessionContext
from ballista_tpu.testing.udf_fixtures import double_it, shout

addr = sys.argv[1] if len(sys.argv) > 1 else "localhost:50050"
pq.write_table(pa.table({"x": [5, 6], "s": ["hey", "yo"]}), "/tmp/udf_demo.parquet")

# no cluster at `addr`? start a demo scheduler + executor in-process so the
# example runs out of the box (the documented daemons take precedence)
import socket

host, _, port = addr.partition(":")
try:
    socket.create_connection((host, int(port or "50050")), timeout=1).close()
except (OSError, ValueError):
    from ballista_tpu.executor.executor_process import ExecutorProcess
    from ballista_tpu.scheduler.process import SchedulerProcess

    print(f"no scheduler at {addr}; starting a demo cluster in-process")
    _sched = SchedulerProcess(bind_host="127.0.0.1", port=0, rest_port=-1)
    _sched.start()
    addr = f"127.0.0.1:{_sched.port}"
    _ex = ExecutorProcess(addr, bind_host="127.0.0.1", external_host="127.0.0.1", vcores=2)
    _ex.start()
    def _cleanup():
        import contextlib

        with contextlib.suppress(Exception):
            _ex.shutdown()
        with contextlib.suppress(Exception):
            _sched.shutdown()

    # run while the interpreter is still healthy: daemon teardown during
    # interpreter exit races thread-pool shutdown and prints noise
    demo_cleanup = _cleanup

ctx = SessionContext.remote(addr)
ctx.register_parquet("t", "/tmp/udf_demo.parquet")
ctx.register_udf("double_it", double_it, pa.int64())
ctx.register_udf("shout", shout, pa.string())
print(ctx.sql("select double_it(x) d, shout(s) u from t order by d").collect().to_pandas())

if "demo_cleanup" in dir():
    demo_cleanup()
