"""TPU engine example: same SQL, engine selected per session
(reference seam: ballista.executor.engine)."""

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ballista_tpu.client.context import SessionContext
from ballista_tpu.config import BallistaConfig, EXECUTOR_ENGINE
from ballista_tpu.testing.tpchgen import generate_tpch, register_tpch

data = os.path.join(tempfile.gettempdir(), "ballista_example_tpch_sf1")
if not os.path.isdir(os.path.join(data, "lineitem")):
    print("generating SF1 ...")
    generate_tpch(data, scale=1.0, files_per_table=4)

sql = open(os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "benchmarks", "tpch", "queries", "q1.sql")).read()

for engine in ("cpu", "tpu"):
    ctx = SessionContext(BallistaConfig({EXECUTOR_ENGINE: engine}))
    register_tpch(ctx, data)
    ctx.sql(sql).collect()  # warm (device cache + XLA compile on tpu)
    t0 = time.time()
    out = ctx.sql(sql).collect()
    print(f"{engine}: {time.time() - t0:.3f}s ({out.num_rows} rows)")
