"""Standalone-cluster SQL example (reference: examples/standalone-sql).

Spins an in-process scheduler + 2 executors, registers TPC-H data, runs a
query over the real stage/shuffle machinery.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ballista_tpu.client.context import SessionContext
from ballista_tpu.testing.tpchgen import generate_tpch, register_tpch

data = os.path.join(tempfile.gettempdir(), "ballista_example_tpch")
if not os.path.isdir(os.path.join(data, "lineitem")):
    generate_tpch(data, scale=0.01)

ctx = SessionContext.standalone(num_executors=2, vcores=4)
register_tpch(ctx, data)

df = ctx.sql(
    """
    select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty, count(*) as n
    from lineitem
    where l_shipdate <= date '1998-09-02'
    group by l_returnflag, l_linestatus
    order by l_returnflag, l_linestatus
    """
)
df.show()
ctx.shutdown()
