"""DataFrame-API example (reference: examples/standalone-dataframe)."""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ballista_tpu.client.context import SessionContext
from ballista_tpu.plan.expressions import AggregateFunction, col, lit
from ballista_tpu.testing.tpchgen import generate_tpch, register_tpch

data = os.path.join(tempfile.gettempdir(), "ballista_example_tpch")
if not os.path.isdir(os.path.join(data, "lineitem")):
    generate_tpch(data, scale=0.01)

ctx = SessionContext()  # local mode
register_tpch(ctx, data)

df = (
    ctx.table("lineitem")
    .filter(col("l_quantity") > lit(45))
    .aggregate([col("l_returnflag")], [AggregateFunction("count", None)])
    .sort(col("l_returnflag").sort())
)
df.show()
