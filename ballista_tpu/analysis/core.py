"""Pass framework for the engine invariant analyzer.

The moving parts:

- `SourceFile` — one parsed python file (text, lines, lazily-built AST,
  suppression comments). Passes share these parses; nothing re-reads disk.
- `Finding` — one violation. Its `key()` deliberately excludes the line
  number so baseline entries survive unrelated edits to the same file.
- suppression comments — `# analysis: ignore[pass-id] reason` on (or one
  line above) the offending line; `# analysis: skip-file[pass-id]` in the
  file header. A reason string is REQUIRED: a suppression is a reviewed
  decision, not an escape hatch.
- baseline — a checked-in JSON file (`dev/analysis_baseline.json`) of
  grandfathered findings, each with a reason. New findings fail; baselined
  ones are reported separately; baseline entries that no longer match any
  finding are flagged as stale so the file can only shrink.
- `Analyzer` — collects the scan set (the `ballista_tpu` package + `dev/`
  + `bench.py`, minus generated protos), runs the passes, applies
  suppressions and the baseline, and returns an `AnalysisReport`.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field

# -- findings ---------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    """One invariant violation.

    `symbol` is the stable discriminator inside a file (a knob name, a
    cache variable, a class.param) — `key()` is built from it instead of
    the line number so baselines don't churn on unrelated edits."""

    pass_id: str
    path: str  # repo-relative, '/'-separated
    line: int
    message: str
    symbol: str = ""

    def key(self) -> str:
        return f"{self.pass_id}:{self.path}:{self.symbol or self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"


# -- suppression comments ---------------------------------------------------

_IGNORE_RE = re.compile(r"#\s*analysis:\s*ignore\[([a-z0-9_,\- *]+)\]\s*(.*)")
_SKIP_FILE_RE = re.compile(r"#\s*analysis:\s*skip-file\[([a-z0-9_,\- *]+)\]\s*(.*)")


@dataclass
class Suppression:
    pass_ids: set[str]  # {"*"} = every pass
    reason: str
    line: int

    def covers(self, pass_id: str) -> bool:
        return "*" in self.pass_ids or pass_id in self.pass_ids


def _parse_suppressions(lines: list[str]) -> tuple[list[Suppression], list[Suppression]]:
    """Returns (line-level, file-level) suppressions. A line-level ignore
    covers its own line and the line below (so it can sit above a long
    statement)."""
    per_line: list[Suppression] = []
    per_file: list[Suppression] = []
    for i, text in enumerate(lines, start=1):
        m = _IGNORE_RE.search(text)
        if m:
            ids = {p.strip() for p in m.group(1).split(",") if p.strip()}
            per_line.append(Suppression(ids, m.group(2).strip(), i))
        m = _SKIP_FILE_RE.search(text)
        if m and i <= 15:
            ids = {p.strip() for p in m.group(1).split(",") if p.strip()}
            per_file.append(Suppression(ids, m.group(2).strip(), i))
    return per_line, per_file


# -- source files -----------------------------------------------------------


class SourceFile:
    """One python file of the scan set: text + lazy AST + suppressions."""

    def __init__(self, rel: str, text: str, abspath: str = ""):
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.abspath = abspath or rel
        self.lines = text.splitlines()
        self._tree: ast.Module | None = None
        self._parse_error: str | None = None
        self.line_suppressions, self.file_suppressions = _parse_suppressions(self.lines)

    @classmethod
    def from_path(cls, abspath: str, rel: str) -> "SourceFile":
        with open(abspath, encoding="utf-8") as f:
            return cls(rel, f.read(), abspath)

    @property
    def tree(self) -> ast.Module | None:
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.text, filename=self.rel)
            except SyntaxError as e:  # surfaced as a finding by the analyzer
                self._parse_error = str(e)
        return self._tree

    @property
    def parse_error(self) -> str | None:
        _ = self.tree
        return self._parse_error

    @property
    def module_name(self) -> str | None:
        """Dotted module name for files under the package root, else None."""
        if not self.rel.endswith(".py"):
            return None
        parts = self.rel[: -len(".py")].split("/")
        if parts[-1] == "__init__":
            parts = parts[:-1]
        if parts and parts[0] == "ballista_tpu":
            return ".".join(parts)
        return None

    def suppressed(self, finding: Finding) -> Suppression | None:
        for s in self.file_suppressions:
            if s.covers(finding.pass_id):
                return s
        for s in self.line_suppressions:
            if s.covers(finding.pass_id) and s.line in (finding.line, finding.line - 1):
                return s
        return None

    # -- shared AST helpers (used by several passes) -----------------------

    def walk_with_parents(self):
        """Yields (node, parent) over the whole tree."""
        tree = self.tree
        if tree is None:
            return
        stack = [(tree, None)]
        while stack:
            node, parent = stack.pop()
            yield node, parent
            for child in ast.iter_child_nodes(node):
                stack.append((child, node))

    def string_literals(self):
        """Yields (value, lineno) for every string constant that is NOT a
        statement-level string (docstrings and bare-string comments carry
        prose, not live keys)."""
        for node, parent in self.walk_with_parents():
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and not isinstance(parent, ast.Expr)
            ):
                yield node.value, node.lineno


# -- baseline ---------------------------------------------------------------


def load_baseline(path: str) -> dict[str, str]:
    """key -> reason. Missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        text = f.read()
    if not text.strip():  # e.g. --baseline '' routes here via /dev/null
        return {}
    data = json.loads(text)
    out: dict[str, str] = {}
    for entry in data.get("findings", []):
        out[entry["key"]] = entry.get("reason", "")
    return out


def save_baseline(path: str, findings: list[Finding], reasons: dict[str, str] | None = None) -> None:
    reasons = reasons or {}
    entries = [
        {"key": f.key(), "reason": reasons.get(f.key(), "grandfathered; fix or justify"),
         "message": f.message}
        for f in sorted(findings, key=lambda f: f.key())
    ]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"comment": "Grandfathered analyzer findings. Entries may only be "
                              "removed (by fixing the violation); additions need a "
                              "written reason. See docs/static_analysis.md.",
                   "findings": entries}, f, indent=2)
        f.write("\n")


# -- analyzer ---------------------------------------------------------------

DEFAULT_BASELINE_REL = os.path.join("dev", "analysis_baseline.json")

_EXCLUDE_PARTS = ("_pb2",)  # generated protobuf modules


def repo_root() -> str:
    """The directory holding the ballista_tpu package (and dev/, docs/)."""
    here = os.path.dirname(os.path.abspath(__file__))  # .../ballista_tpu/analysis
    return os.path.dirname(os.path.dirname(here))


@dataclass
class AnalysisReport:
    findings: list[Finding] = field(default_factory=list)  # actionable (new)
    suppressed: list[tuple[Finding, Suppression]] = field(default_factory=list)
    baselined: list[tuple[Finding, str]] = field(default_factory=list)
    stale_baseline: list[str] = field(default_factory=list)  # keys with no match
    files_scanned: int = 0
    passes_run: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings and not self.stale_baseline

    def render(self) -> str:
        out = []
        for f in sorted(self.findings, key=lambda f: (f.path, f.line)):
            out.append(f.render())
        for key in self.stale_baseline:
            out.append(f"(baseline) stale entry no longer matches any finding: {key}")
        out.append(
            f"{len(self.findings)} finding(s), {len(self.baselined)} baselined, "
            f"{len(self.suppressed)} suppressed, {len(self.stale_baseline)} stale "
            f"baseline entr(ies) over {self.files_scanned} files "
            f"[{', '.join(self.passes_run)}]"
        )
        return "\n".join(out)

    def to_json(self) -> str:
        return json.dumps(
            {
                "ok": self.ok,
                "files_scanned": self.files_scanned,
                "passes": self.passes_run,
                "findings": [
                    {"pass": f.pass_id, "path": f.path, "line": f.line,
                     "message": f.message, "key": f.key()}
                    for f in self.findings
                ],
                "baselined": [
                    {"key": f.key(), "reason": r} for f, r in self.baselined
                ],
                "suppressed": [
                    {"key": f.key(), "reason": s.reason} for f, s in self.suppressed
                ],
                "stale_baseline": self.stale_baseline,
            },
            indent=2,
        )


class Analyzer:
    """Collect the scan set, run passes, apply suppressions + baseline."""

    def __init__(self, root: str | None = None, passes=None,
                 baseline_path: str | None = None,
                 files: list[SourceFile] | None = None):
        self.root = os.path.abspath(root or repo_root())
        if passes is None:
            from ballista_tpu.analysis.passes import ALL_PASSES

            passes = ALL_PASSES
        self.passes = list(passes)
        self.baseline_path = baseline_path if baseline_path is not None else os.path.join(
            self.root, DEFAULT_BASELINE_REL
        )
        self._files = files

    # -- scan set ----------------------------------------------------------

    def collect(self) -> list[SourceFile]:
        if self._files is not None:
            return self._files
        out: list[SourceFile] = []
        roots = [("ballista_tpu", True), ("dev", False)]
        for top, recurse in roots:
            base = os.path.join(self.root, top)
            if not os.path.isdir(base):
                continue
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if not fn.endswith(".py"):
                        continue
                    if any(p in fn for p in _EXCLUDE_PARTS):
                        continue
                    ap = os.path.join(dirpath, fn)
                    out.append(SourceFile.from_path(ap, os.path.relpath(ap, self.root)))
                if not recurse:
                    break
        for single in ("bench.py",):
            ap = os.path.join(self.root, single)
            if os.path.exists(ap):
                out.append(SourceFile.from_path(ap, single))
        self._files = out
        return out

    def file(self, rel: str) -> SourceFile | None:
        rel = rel.replace(os.sep, "/")
        for f in self.collect():
            if f.rel == rel:
                return f
        return None

    # -- run ---------------------------------------------------------------

    def run(self, pass_ids: list[str] | None = None) -> AnalysisReport:
        files = self.collect()
        by_rel = {f.rel: f for f in files}
        report = AnalysisReport(files_scanned=len(files))
        raw: list[Finding] = []
        for f in files:
            if f.parse_error:
                raw.append(Finding("parse", f.rel, 1, f"syntax error: {f.parse_error}"))
        for p in self.passes:
            if pass_ids is not None and p.pass_id not in pass_ids:
                continue
            report.passes_run.append(p.pass_id)
            raw.extend(p.run(self))
        baseline = load_baseline(self.baseline_path)
        matched_keys: set[str] = set()
        for f in raw:
            src = by_rel.get(f.path)
            sup = src.suppressed(f) if src is not None else None
            if sup is not None:
                if sup.reason:
                    report.suppressed.append((f, sup))
                    continue
                # a reasonless suppression is not a reviewed decision: the
                # finding stays actionable, annotated so the author sees why
                f = Finding(f.pass_id, f.path, f.line,
                            f.message + " [matching suppression lacks a reason]",
                            f.symbol)
            if f.key() in baseline:
                matched_keys.add(f.key())
                report.baselined.append((f, baseline[f.key()]))
                continue
            report.findings.append(f)
        report.stale_baseline = sorted(set(baseline) - matched_keys)
        return report


class AnalysisPass:
    """Base class: subclasses set `pass_id`/`doc` and implement run()."""

    pass_id = "base"
    doc = ""

    def run(self, analyzer: Analyzer) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError
