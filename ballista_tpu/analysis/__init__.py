"""Engine invariant analyzer: custom static-analysis passes + plan verifier.

Eight PRs of growth accreted repo-wide invariants that were enforced only
by reviewer memory: every `ballista.*` knob registered AND documented,
module caches bounded, CPU-side modules never importing jax at top level,
every plan node serde-complete, RunStats gauges emitted where consumed,
no blocking calls on the scheduler event loop. This package makes them
machine-checked:

- `core`       — the pass framework: shared AST walking, typed `Finding`s,
                 per-line / per-file suppression comments, a checked-in
                 baseline for grandfathered violations
- `passes/`    — the engine-specific passes (see `passes.ALL_PASSES`)
- `plan_check` — the second front: a static verifier over physical plans /
                 `ExecutionGraph`s (stage-boundary schema agreement,
                 partition-count consistency, mesh gating, fast-lane
                 task-id band disjointness)

CLI: `python -m ballista_tpu.analysis` (see `__main__.py`); the tier-1
gate is `tests/test_static_analysis.py`. Docs: docs/static_analysis.md.
"""

from ballista_tpu.analysis.core import (  # noqa: F401
    Analyzer,
    AnalysisReport,
    Finding,
    SourceFile,
    load_baseline,
    repo_root,
)
