"""event-loop hygiene: no blocking calls in scheduler event-loop handlers.

Every graph mutation funnels through `SchedulerServer._event_loop` →
`_handle`; one blocking call there stalls task placement, heartbeat
application, and AQE resolution cluster-wide (the admission controller
even sheds on loop lag — a blocked loop triggers exactly the overload
it's meant to prevent). Planning already runs on a spawned thread for
this reason.

The pass builds the intra-class call graph from `_handle` over
`self.method()` edges (nested function defs are excluded — they are
thread targets, not loop code) and flags the blocking primitives:
`time.sleep`, subprocess spawns, raw socket dials, `urlopen`,
`Event.wait`, `Thread.join` without a timeout, and `Future.result()`
without a timeout.
"""

from __future__ import annotations

import ast

from ballista_tpu.analysis.core import AnalysisPass, Analyzer, Finding

SERVER_REL = "ballista_tpu/scheduler/server.py"
ROOT_METHODS = ("_handle",)

_BLOCKING_MODULE_CALLS = {
    ("time", "sleep"),
    ("subprocess", "run"),
    ("subprocess", "check_call"),
    ("subprocess", "check_output"),
    ("subprocess", "Popen"),
    ("socket", "create_connection"),
}
_TIMEOUT_REQUIRED_METHODS = {"result", "join", "wait"}


def _has_timeout(call: ast.Call) -> bool:
    if any(k.arg == "timeout" for k in call.keywords):
        return True
    return bool(call.args)  # positional timeout (Event.wait(5), join(5))


def _method_defs(cls: ast.ClassDef) -> dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)}


def _own_statements(fn: ast.FunctionDef):
    """Walk fn's body, NOT descending into nested function defs (those run
    on other threads)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        for child in ast.iter_child_nodes(node):
            stack.append(child)


def _self_calls(fn: ast.FunctionDef) -> set[str]:
    out: set[str] = set()
    for node in _own_statements(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            out.add(node.func.attr)
    return out


class EventLoopHygienePass(AnalysisPass):
    pass_id = "event-loop"
    doc = "no blocking sleeps/IO in SchedulerServer event-loop handlers"

    def run(self, analyzer: Analyzer) -> list[Finding]:
        findings: list[Finding] = []
        src = analyzer.file(SERVER_REL)
        if src is None or src.tree is None:
            return findings
        cls = next((n for n in src.tree.body
                    if isinstance(n, ast.ClassDef) and n.name == "SchedulerServer"), None)
        if cls is None:
            return findings
        methods = _method_defs(cls)

        reachable: set[str] = set()
        stack = [m for m in ROOT_METHODS if m in methods]
        while stack:
            name = stack.pop()
            if name in reachable:
                continue
            reachable.add(name)
            for callee in _self_calls(methods[name]):
                if callee in methods and callee not in reachable:
                    stack.append(callee)

        for name in sorted(reachable):
            for node in _own_statements(methods[name]):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                    pair = (f.value.id, f.attr)
                    if pair in _BLOCKING_MODULE_CALLS:
                        findings.append(Finding(
                            self.pass_id, src.rel, node.lineno,
                            f"blocking call {pair[0]}.{pair[1]}() inside event-loop "
                            f"handler SchedulerServer.{name}; post work to a thread "
                            f"or use the sweep timer",
                            symbol=f"{name}:{pair[0]}.{pair[1]}",
                        ))
                        continue
                if isinstance(f, ast.Name) and f.id == "urlopen":
                    findings.append(Finding(
                        self.pass_id, src.rel, node.lineno,
                        f"blocking urlopen() inside event-loop handler "
                        f"SchedulerServer.{name}",
                        symbol=f"{name}:urlopen",
                    ))
                    continue
                if isinstance(f, ast.Attribute) and \
                        f.attr in _TIMEOUT_REQUIRED_METHODS and not _has_timeout(node):
                    findings.append(Finding(
                        self.pass_id, src.rel, node.lineno,
                        f".{f.attr}() without a timeout inside event-loop handler "
                        f"SchedulerServer.{name}; an unbounded wait wedges the "
                        f"whole scheduler",
                        symbol=f"{name}:{f.attr}",
                    ))
        return findings
