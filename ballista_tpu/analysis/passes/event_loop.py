"""event-loop hygiene: no blocking calls in scheduler event-loop handlers.

Every graph mutation funnels through a scheduler shard's event loop →
`SchedulerServer._handle`; one blocking call there stalls task placement,
heartbeat application, and AQE resolution for every job the shard owns
(the admission controller even sheds on loop lag — a blocked loop
triggers exactly the overload it's meant to prevent). Planning already
runs on a spawned thread for this reason.

The pass roots its search at BOTH handler entry points — the per-shard
`SchedulerShard._handle` (ballista_tpu/scheduler/shard.py) and
`SchedulerServer._handle` — building the call graph over `self.method()`
edges plus the shard's `self.server.method()` cross-class edges (nested
function defs are excluded — they are thread targets, not loop code),
and flags the blocking primitives: `time.sleep`, subprocess spawns, raw
socket dials, `urlopen`, `Event.wait`, `Thread.join` without a timeout,
and `Future.result()` without a timeout.
"""

from __future__ import annotations

import ast

from ballista_tpu.analysis.core import AnalysisPass, Analyzer, Finding

SERVER_REL = "ballista_tpu/scheduler/server.py"
SHARD_REL = "ballista_tpu/scheduler/shard.py"
ROOT_METHODS = ("_handle",)

_BLOCKING_MODULE_CALLS = {
    ("time", "sleep"),
    ("subprocess", "run"),
    ("subprocess", "check_call"),
    ("subprocess", "check_output"),
    ("subprocess", "Popen"),
    ("socket", "create_connection"),
}
_TIMEOUT_REQUIRED_METHODS = {"result", "join", "wait"}


def _has_timeout(call: ast.Call) -> bool:
    if any(k.arg == "timeout" for k in call.keywords):
        return True
    return bool(call.args)  # positional timeout (Event.wait(5), join(5))


def _method_defs(cls: ast.ClassDef) -> dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)}


def _own_statements(fn: ast.FunctionDef):
    """Walk fn's body, NOT descending into nested function defs (those run
    on other threads)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        for child in ast.iter_child_nodes(node):
            stack.append(child)


def _self_calls(fn: ast.FunctionDef) -> set[str]:
    out: set[str] = set()
    for node in _own_statements(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            out.add(node.func.attr)
    return out


def _server_calls(fn: ast.FunctionDef) -> set[str]:
    """Cross-class edges: `self.server.method()` calls from a shard method
    into SchedulerServer (the shard loop forwards its events there)."""
    out: set[str] = set()
    for node in _own_statements(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Attribute) \
                and isinstance(node.func.value.value, ast.Name) \
                and node.func.value.value.id == "self" \
                and node.func.value.attr == "server":
            out.add(node.func.attr)
    return out


def _class_def(src, name: str) -> ast.ClassDef | None:
    if src is None or src.tree is None:
        return None
    return next((n for n in src.tree.body
                 if isinstance(n, ast.ClassDef) and n.name == name), None)


def _reachable(methods: dict[str, ast.FunctionDef], roots) -> set[str]:
    seen: set[str] = set()
    stack = [m for m in roots if m in methods]
    while stack:
        name = stack.pop()
        if name in seen:
            continue
        seen.add(name)
        for callee in _self_calls(methods[name]):
            if callee in methods and callee not in seen:
                stack.append(callee)
    return seen


class EventLoopHygienePass(AnalysisPass):
    pass_id = "event-loop"
    doc = "no blocking sleeps/IO reachable from any scheduler shard's event-loop handlers"

    def run(self, analyzer: Analyzer) -> list[Finding]:
        findings: list[Finding] = []
        server_src = analyzer.file(SERVER_REL)
        server_cls = _class_def(server_src, "SchedulerServer")
        if server_cls is None:
            return findings
        server_methods = _method_defs(server_cls)

        # roots: SchedulerServer._handle, plus every SchedulerServer method
        # a shard's event loop reaches through self.server.X() edges
        server_roots = set(ROOT_METHODS)
        shard_src = analyzer.file(SHARD_REL)
        shard_cls = _class_def(shard_src, "SchedulerShard")
        if shard_cls is not None:
            shard_methods = _method_defs(shard_cls)
            shard_reachable = _reachable(shard_methods, ROOT_METHODS)
            for name in sorted(shard_reachable):
                server_roots |= _server_calls(shard_methods[name])
            self._flag(findings, shard_src, shard_methods, shard_reachable,
                       "SchedulerShard")

        server_reachable = _reachable(server_methods, server_roots)
        self._flag(findings, server_src, server_methods, server_reachable,
                   "SchedulerServer")
        return findings

    def _flag(self, findings: list[Finding], src, methods, reachable,
              cls_name: str) -> None:
        for name in sorted(reachable):
            for node in _own_statements(methods[name]):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                    pair = (f.value.id, f.attr)
                    if pair in _BLOCKING_MODULE_CALLS:
                        findings.append(Finding(
                            self.pass_id, src.rel, node.lineno,
                            f"blocking call {pair[0]}.{pair[1]}() inside event-loop "
                            f"handler {cls_name}.{name}; post work to a thread "
                            f"or use the sweep timer",
                            symbol=f"{name}:{pair[0]}.{pair[1]}",
                        ))
                        continue
                if isinstance(f, ast.Name) and f.id == "urlopen":
                    findings.append(Finding(
                        self.pass_id, src.rel, node.lineno,
                        f"blocking urlopen() inside event-loop handler "
                        f"{cls_name}.{name}",
                        symbol=f"{name}:urlopen",
                    ))
                    continue
                if isinstance(f, ast.Attribute) and \
                        f.attr in _TIMEOUT_REQUIRED_METHODS and not _has_timeout(node):
                    findings.append(Finding(
                        self.pass_id, src.rel, node.lineno,
                        f".{f.attr}() without a timeout inside event-loop handler "
                        f"{cls_name}.{name}; an unbounded wait wedges the "
                        f"whole shard",
                        symbol=f"{name}:{f.attr}",
                    ))
