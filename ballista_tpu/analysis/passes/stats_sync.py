"""stats-registry sync: RunStats keys emitted vs consumed vs documented.

The executor heartbeat (`_tpu_metrics`) forwards a fixed tuple of RunStats
keys as `tpu_*` gauges. Two drift modes have bitten:

- a consumer key nobody emits (gauge silently always absent — the
  `exchange_bytes_on_device` emission was nearly lost to a refactor and
  is invisible to grep because the `.set(` call spans lines), and
- an emitted key that is neither exported as a gauge nor documented in
  the RunStats docstring (diagnostics nobody can discover).

So: every key `_tpu_metrics` consumes must be emitted somewhere under
`ops/tpu/`, and every emitted key must be consumed by `_tpu_metrics` OR
named in the RunStats class docstring. Emission sites are found by AST —
`<anything>.set("key", ...)`-style calls where the receiver smells like a
stats sink (RUN_STATS / rec / stats / run-scope handles) and string
subscript stores on the same receivers.
"""

from __future__ import annotations

import ast

from ballista_tpu.analysis.core import AnalysisPass, Analyzer, Finding

EXEC_REL = "ballista_tpu/executor/executor_process.py"
STATS_REL = "ballista_tpu/ops/tpu/stage_compiler.py"

_SINK_NAMES = {"RUN_STATS", "rec", "stats", "run_stats", "_rec", "srec"}


def _receiver_name(node: ast.expr) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def emitted_keys(analyzer: Analyzer) -> dict[str, tuple[str, int]]:
    """key -> (rel, lineno) across ops/tpu/ modules."""
    out: dict[str, tuple[str, int]] = {}
    for src in analyzer.collect():
        if not src.rel.startswith("ballista_tpu/ops/tpu/"):
            continue
        tree = src.tree
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "set" and node.args:
                if _receiver_name(node.func.value) not in _SINK_NAMES:
                    continue
                k = node.args[0]
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out.setdefault(k.value, (src.rel, node.lineno))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) and \
                            _receiver_name(t.value) in _SINK_NAMES and \
                            isinstance(t.slice, ast.Constant) and \
                            isinstance(t.slice.value, str):
                        out.setdefault(t.slice.value, (src.rel, node.lineno))
    return out


def consumed_keys(analyzer: Analyzer) -> dict[str, int]:
    """key -> lineno consumed by _tpu_metrics: the gauge tuple iterated by
    its for-loop plus `"key" in stats` membership checks."""
    src = analyzer.file(EXEC_REL)
    out: dict[str, int] = {}
    if src is None or src.tree is None:
        return out
    fn = None
    for node in ast.walk(src.tree):
        if isinstance(node, ast.FunctionDef) and node.name == "_tpu_metrics":
            fn = node
            break
    if fn is None:
        return out
    for node in ast.walk(fn):
        if isinstance(node, ast.For) and isinstance(node.iter, (ast.Tuple, ast.List)):
            for elt in node.iter.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    out.setdefault(elt.value, elt.lineno)
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], ast.In) \
                and isinstance(node.left, ast.Constant) \
                and isinstance(node.left.value, str) \
                and _receiver_name(node.comparators[0]) in ("stats",):
            out.setdefault(node.left.value, node.lineno)
    return out


def _runstats_docstring(analyzer: Analyzer) -> str:
    src = analyzer.file(STATS_REL)
    if src is None or src.tree is None:
        return ""
    for node in src.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "RunStats":
            return ast.get_docstring(node) or ""
    return ""


class StatsRegistrySyncPass(AnalysisPass):
    pass_id = "stats-sync"
    doc = "RunStats keys: heartbeat consumers must be emitted; emissions documented"

    def run(self, analyzer: Analyzer) -> list[Finding]:
        findings: list[Finding] = []
        emitted = emitted_keys(analyzer)
        consumed = consumed_keys(analyzer)
        doc = _runstats_docstring(analyzer)

        for key, lineno in sorted(consumed.items()):
            if key not in emitted:
                findings.append(Finding(
                    self.pass_id, EXEC_REL, lineno,
                    f"heartbeat gauge tpu_{key} consumes RunStats key '{key}' "
                    f"but nothing under ops/tpu/ emits it",
                    symbol=f"consumed:{key}",
                ))
        for key, (rel, lineno) in sorted(emitted.items()):
            if key in consumed or key in doc:
                continue
            findings.append(Finding(
                self.pass_id, rel, lineno,
                f"RunStats key '{key}' is emitted but neither exported by the "
                f"heartbeat nor documented in the RunStats docstring",
                symbol=f"emitted:{key}",
            ))
        return findings
