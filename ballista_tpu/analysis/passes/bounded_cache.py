"""bounded-cache: module-level mutable caches must be LruDict-bounded.

PR 5 retrofitted four unbounded module dicts by hand after they grew
without limit under sustained load. The invariant: a module-level
assignment of an EMPTY mutable container (`{}`, `[]`, `dict()`, `list()`,
`set()`, `OrderedDict()`, `defaultdict(...)`) is a cache until proven
otherwise — it must be an `LruDict` (ballista_tpu.utils.lru) or carry an
`# analysis: ignore[bounded-cache] <reason>` suppression stating why it
cannot grow unbounded (e.g. keyed by fleet membership, an explicit
registration surface).

Non-empty literals are lookup tables, not caches, and are not flagged.
Names that are obviously not containers of unbounded growth (locks,
sentinel lists like __all__) are skipped by name.
"""

from __future__ import annotations

import ast

from ballista_tpu.analysis.core import AnalysisPass, Analyzer, Finding

_EMPTY_CALLS = {"dict", "list", "set", "OrderedDict", "defaultdict", "deque"}
_SKIP_NAMES = {"__all__"}


def _is_empty_mutable(value: ast.expr) -> bool:
    if isinstance(value, ast.Dict) and not value.keys:
        return True
    if isinstance(value, ast.List) and not value.elts:
        return True
    if isinstance(value, ast.Call):
        fn = value.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else "")
        if name in _EMPTY_CALLS and not value.args and not value.keywords:
            return True
        if name == "defaultdict":  # defaultdict(list) etc. is still empty
            return True
    return False


class BoundedCachePass(AnalysisPass):
    pass_id = "bounded-cache"
    doc = "module-level mutable dict/list caches must be LruDict or carry a suppression"

    def run(self, analyzer: Analyzer) -> list[Finding]:
        findings: list[Finding] = []
        for src in analyzer.collect():
            tree = src.tree
            if tree is None:
                continue
            for stmt in tree.body:
                targets: list[ast.expr] = []
                value = None
                if isinstance(stmt, ast.Assign):
                    targets, value = stmt.targets, stmt.value
                elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                    targets, value = [stmt.target], stmt.value
                if value is None or not _is_empty_mutable(value):
                    continue
                for t in targets:
                    if not isinstance(t, ast.Name) or t.id in _SKIP_NAMES:
                        continue
                    findings.append(Finding(
                        self.pass_id, src.rel, stmt.lineno,
                        f"module-level mutable container '{t.id}' is unbounded; "
                        f"use ballista_tpu.utils.lru.LruDict or suppress with a "
                        f"reason why it cannot grow without limit",
                        symbol=t.id,
                    ))
        return findings
