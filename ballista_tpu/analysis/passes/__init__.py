"""Engine-specific analysis passes.

Each module defines one `AnalysisPass` subclass; `ALL_PASSES` is the
registry the CLI and the tier-1 gate run. Order is reporting order only —
passes are independent.
"""

from ballista_tpu.analysis.passes.bounded_cache import BoundedCachePass
from ballista_tpu.analysis.passes.event_loop import EventLoopHygienePass
from ballista_tpu.analysis.passes.jax_guard import JaxGuardPass
from ballista_tpu.analysis.passes.knob_sync import KnobSyncPass
from ballista_tpu.analysis.passes.serde_sync import SerdeCompletenessPass
from ballista_tpu.analysis.passes.stats_sync import StatsRegistrySyncPass

ALL_PASSES = [
    KnobSyncPass(),
    BoundedCachePass(),
    JaxGuardPass(),
    SerdeCompletenessPass(),
    StatsRegistrySyncPass(),
    EventLoopHygienePass(),
]

__all__ = [
    "ALL_PASSES",
    "BoundedCachePass",
    "EventLoopHygienePass",
    "JaxGuardPass",
    "KnobSyncPass",
    "SerdeCompletenessPass",
    "StatsRegistrySyncPass",
]
