"""jax-guard: CPU-side modules must not import jax at module top level.

A scheduler, CPU executor, Flight daemon, or client that transitively
imports `jax` at import time pays multi-second platform init (and on TPU
hosts can grab the accelerator) just to move bytes around. Worse, the
executor heartbeat keys its TPU gauges on
`sys.modules.get("ballista_tpu.ops.tpu.stage_compiler")` — an accidental
eager import makes a CPU executor report TPU metrics. The convention is
function-level (lazy) jax imports everywhere; this pass enforces it on
every module reachable from the CPU entry points via the MODULE-LEVEL
import graph (a lazy import inside a function is reachable only when the
TPU engine actually runs, which is the point).

`if TYPE_CHECKING:` imports are ignored; imports inside try/except at
module level still count (they execute at import time).
"""

from __future__ import annotations

import ast

from ballista_tpu.analysis.core import AnalysisPass, Analyzer, Finding, SourceFile

ENTRY_POINTS = (
    "ballista_tpu.scheduler.process",
    "ballista_tpu.scheduler.server",
    "ballista_tpu.scheduler.__main__",
    "ballista_tpu.executor.executor_process",
    "ballista_tpu.executor.standalone",
    "ballista_tpu.executor.__main__",
    "ballista_tpu.flight.server",
    "ballista_tpu.flight.proxy",
    "ballista_tpu.client.context",
    "ballista_tpu.cli.main",
)

_BANNED = ("jax", "jaxlib")


def _is_type_checking_if(stmt: ast.stmt) -> bool:
    if not isinstance(stmt, ast.If):
        return False
    t = stmt.test
    return (isinstance(t, ast.Name) and t.id == "TYPE_CHECKING") or (
        isinstance(t, ast.Attribute) and t.attr == "TYPE_CHECKING"
    )


def _module_level_imports(tree: ast.Module):
    """Yields (module_string, lineno) for imports that execute at module
    import time: top-level statements plus bodies of top-level if/try
    blocks (minus TYPE_CHECKING guards)."""
    stack: list[ast.stmt] = list(tree.body)
    while stack:
        stmt = stack.pop()
        if isinstance(stmt, ast.Import):
            for a in stmt.names:
                yield a.name, stmt.lineno
        elif isinstance(stmt, ast.ImportFrom):
            yield stmt.module or "", stmt.lineno, stmt.level, [a.name for a in stmt.names]
        elif isinstance(stmt, ast.If):
            if not _is_type_checking_if(stmt):
                stack.extend(stmt.body)
            stack.extend(stmt.orelse)
        elif isinstance(stmt, (ast.Try, ast.With)):
            stack.extend(stmt.body)
            if isinstance(stmt, ast.Try):
                for h in stmt.handlers:
                    stack.extend(h.body)
                stack.extend(stmt.orelse)
                stack.extend(stmt.finalbody)


def module_imports(src: SourceFile, known: set[str]) -> list[tuple[str, int]]:
    """Resolve this file's module-level imports to dotted names within the
    package (edges of the import graph) plus external roots like 'jax'."""
    tree = src.tree
    if tree is None or src.module_name is None:
        return []
    out: list[tuple[str, int]] = []
    pkg_parts = src.module_name.split(".")
    if not src.rel.endswith("/__init__.py"):
        pkg_parts = pkg_parts[:-1]  # containing package for relative imports
    for item in _module_level_imports(tree):
        if len(item) == 2:  # plain `import x.y`
            out.append((item[0], item[1]))
            continue
        mod, lineno, level, names = item
        if level:  # relative: resolve against the containing package
            base_parts = pkg_parts[: len(pkg_parts) - (level - 1)]
            base = ".".join(base_parts + ([mod] if mod else []))
        else:
            base = mod
        out.append((base, lineno))
        for n in names:  # `from pkg import submodule` edges
            cand = f"{base}.{n}" if base else n
            if cand in known:
                out.append((cand, lineno))
    return out


class JaxGuardPass(AnalysisPass):
    pass_id = "jax-guard"
    doc = "modules reachable from CPU entry points must not import jax at module level"

    def run(self, analyzer: Analyzer) -> list[Finding]:
        files = analyzer.collect()
        by_mod: dict[str, SourceFile] = {}
        for f in files:
            if f.module_name:
                by_mod[f.module_name] = f
        known = set(by_mod)

        edges: dict[str, list[tuple[str, int]]] = {}
        for mod, src in by_mod.items():
            resolved: list[tuple[str, int]] = []
            for target, lineno in module_imports(src, known):
                # importing a module also imports its ancestor packages
                parts = target.split(".")
                for i in range(1, len(parts) + 1):
                    prefix = ".".join(parts[:i])
                    if prefix in known or prefix.split(".")[0] in _BANNED:
                        resolved.append((prefix, lineno))
            edges[mod] = resolved

        reachable: dict[str, str] = {}  # module -> entry point that reaches it
        stack = [(e, e) for e in ENTRY_POINTS if e in known]
        while stack:
            mod, entry = stack.pop()
            if mod in reachable:
                continue
            reachable[mod] = entry
            for target, _ in edges.get(mod, []):
                if target in known and target not in reachable:
                    stack.append((target, entry))

        findings: list[Finding] = []
        for mod, entry in sorted(reachable.items()):
            src = by_mod[mod]
            for target, lineno in edges.get(mod, []):
                if target.split(".")[0] in _BANNED:
                    findings.append(Finding(
                        self.pass_id, src.rel, lineno,
                        f"module-level import of '{target}' in a module reachable "
                        f"from CPU entry point {entry}; make the import lazy "
                        f"(function-level)",
                        symbol=target,
                    ))
        return findings
