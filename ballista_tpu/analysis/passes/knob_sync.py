"""knob-sync: config keys and env knobs stay registered AND documented.

Three invariants, each of which has drifted at least once during review:

1. Every `"ballista.*"` string literal used anywhere in the engine is a
   registered `ConfigEntry` (or lives in an open namespace —
   `ballista.catalog.*` / `ballista.udf.*` carry session-shipped
   registrations, not knobs).
2. `docs/configs.md` is exactly what `generate_config_docs()` renders —
   the file is generated (dev/gen_configs.py), so any hand edit or any
   registry change without a regen is a finding. This subsumes "every
   registered entry is documented".
3. Every `BALLISTA_*` environment variable the code reads maps to a knob:
   either it is named in a registered entry's description (the env
   escape-hatch convention) or it is a registered `EnvKnob`
   (config.ENV_KNOBS — daemon-only knobs with no session-config
   equivalent, e.g. cache sizing read at import time).
"""

from __future__ import annotations

import ast
import os
import re

from ballista_tpu.analysis.core import AnalysisPass, Analyzer, Finding

_KEY_RE = re.compile(r"^ballista(\.[a-z0-9_]+)+$")
_OPEN_PREFIXES = ("ballista.catalog.", "ballista.udf.")
_ENV_READERS = {"get", "getenv", "_env_bool", "_env_int", "_env_float", "_env_str"}


def _env_reads(tree: ast.Module):
    """Yields (var_name, lineno) for os.environ / _env_* reads of a
    BALLISTA_* variable. String-literal grep would false-positive on
    constants like BALLISTA_VERSION (a python name, not an env var), so
    only actual read sites count."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = ""
            if isinstance(node.func, ast.Attribute):
                name = node.func.attr
            elif isinstance(node.func, ast.Name):
                name = node.func.id
            if name in _ENV_READERS and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                        and arg.value.startswith("BALLISTA_"):
                    yield arg.value, node.lineno
        elif isinstance(node, ast.Subscript):
            v = node.value
            if isinstance(v, ast.Attribute) and v.attr == "environ":
                s = node.slice
                if isinstance(s, ast.Constant) and isinstance(s.value, str) \
                        and s.value.startswith("BALLISTA_"):
                    yield s.value, node.lineno


class KnobSyncPass(AnalysisPass):
    pass_id = "knob-sync"
    doc = "ballista.* keys registered + documented; BALLISTA_* env reads mapped to knobs"

    def run(self, analyzer: Analyzer) -> list[Finding]:
        from ballista_tpu import config as cfg

        findings: list[Finding] = []
        valid = set(cfg.VALID_ENTRIES)

        # 1. every ballista.* literal is a registered key
        for src in analyzer.collect():
            if src.rel == "ballista_tpu/config.py":  # the registry itself
                continue
            for value, lineno in src.string_literals():
                if not _KEY_RE.match(value):
                    continue
                if value in valid or value.startswith(_OPEN_PREFIXES):
                    continue
                findings.append(Finding(
                    self.pass_id, src.rel, lineno,
                    f'config key "{value}" is not a registered ConfigEntry '
                    f"(register it in config.py or move it under an open namespace)",
                    symbol=value,
                ))

        # 2. docs/configs.md is exactly the rendered registry
        docs_path = os.path.join(analyzer.root, "docs", "configs.md")
        expected = cfg.generate_config_docs()
        try:
            with open(docs_path, encoding="utf-8") as f:
                actual = f.read()
        except OSError:
            actual = None
        if actual is None:
            findings.append(Finding(
                self.pass_id, "docs/configs.md", 1,
                "docs/configs.md is missing; run `python dev/gen_configs.py`",
                symbol="<missing>",
            ))
        elif actual != expected:
            findings.append(Finding(
                self.pass_id, "docs/configs.md", 1,
                "docs/configs.md is stale vs the config.py registry; "
                "run `python dev/gen_configs.py`",
                symbol="<stale>",
            ))

        # 3. every BALLISTA_* env read maps to a knob
        documented_env: set[str] = set()
        for e in cfg.VALID_ENTRIES.values():
            documented_env.update(re.findall(r"BALLISTA_[A-Z0-9_]+", e.description))
        registered_env = set(getattr(cfg, "ENV_KNOBS", {}))
        known = documented_env | registered_env
        seen_reads: set[str] = set()
        for src in analyzer.collect():
            tree = src.tree
            if tree is None:
                continue
            for var, lineno in _env_reads(tree):
                seen_reads.add(var)
                if var in known:
                    continue
                findings.append(Finding(
                    self.pass_id, src.rel, lineno,
                    f"env var {var} is read here but maps to no knob: name it in "
                    f"a ConfigEntry description or register an EnvKnob in config.py",
                    symbol=var,
                ))
        # registered EnvKnobs must correspond to a real read somewhere
        cfg_src = analyzer.file("ballista_tpu/config.py")
        for var in sorted(registered_env - seen_reads):
            findings.append(Finding(
                self.pass_id,
                cfg_src.rel if cfg_src else "ballista_tpu/config.py", 1,
                f"EnvKnob {var} is registered but nothing reads it",
                symbol=f"unused:{var}",
            ))
        return findings
