"""serde-completeness: plan nodes round-trip every constructor parameter.

PR 8 shipped exactly this bug class: a new `QueryStage.mesh` flag that the
graph proto round-trip silently dropped. The invariant has three legs:

1. ENCODE covers the constructor: for each `isinstance(plan, Cls)` branch
   of `serde.encode_plan`, every parameter of `Cls.__init__` must be read
   off `plan` somewhere in that branch (a parameter nobody reads cannot be
   on the wire).
2. DECODE reconstructs explicitly: every constructor call of a plan class
   inside `serde.decode_plan` must pass a value for EVERY `__init__`
   parameter. Defaulted parameters are precisely the dangerous ones — a
   new flag with a default decodes "successfully" while dropping state.
3. The stage-spec round-trip in `ExecutionGraph.from_proto` must supply
   every `QueryStage` dataclass field to the reconstructed `QueryStage`
   (this leg is what catches the mesh/broadcast class of bug).

Signatures come from runtime introspection (the classes are imported
anyway); branch structure comes from the AST of serde.py. Parameters whose
wire form is intentionally derived rather than stored verbatim are listed
in `ENCODE_ALIASES` with the attribute that carries them.
"""

from __future__ import annotations

import ast
import inspect

from ballista_tpu.analysis.core import AnalysisPass, Analyzer, Finding

# encode branches read these attributes FOR the named parameter
# (param is on the wire, just under a transformed read)
ENCODE_ALIASES: dict[tuple[str, str], str] = {
    # MemoryScanExec(schema=..) stores the scan schema as .df_schema
    ("MemoryScanExec", "schema"): "df_schema",
}

SERDE_REL = "ballista_tpu/serde.py"
GRAPH_REL = "ballista_tpu/scheduler/state/execution_graph.py"


def _class_params(cls) -> list[str]:
    sig = inspect.signature(cls.__init__)
    return [p for p in list(sig.parameters)[1:]
            if sig.parameters[p].kind not in (inspect.Parameter.VAR_POSITIONAL,
                                              inspect.Parameter.VAR_KEYWORD)]


def _serde_classes() -> dict[str, type]:
    """Every plan-node class serde.py dispatches on, by name."""
    import ballista_tpu.serde as serde
    from ballista_tpu.ops.cpu.dynamic_join import DynamicJoinSelectionExec

    out: dict[str, type] = {}
    for name, obj in vars(serde).items():
        if inspect.isclass(obj) and name.endswith("Exec"):
            out[name] = obj
    out["DynamicJoinSelectionExec"] = DynamicJoinSelectionExec
    return out


def encode_branches(tree: ast.Module) -> list[tuple[str, ast.stmt, int]]:
    """(class_name, branch_body_container, lineno) for each isinstance
    branch of encode_plan. A branch testing `isinstance(p, A) or f(p)`
    yields only A — helper-dispatched classes (DynamicJoinSelectionExec)
    are checked through the explicit call-count leg instead."""
    fn = next((n for n in tree.body
               if isinstance(n, ast.FunctionDef) and n.name == "encode_plan"), None)
    if fn is None:
        return []
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.If):
            continue
        tests = [node.test]
        if isinstance(node.test, ast.BoolOp):
            tests = list(node.test.values)
        for t in tests:
            if (isinstance(t, ast.Call) and isinstance(t.func, ast.Name)
                    and t.func.id == "isinstance" and len(t.args) == 2
                    and isinstance(t.args[1], ast.Name)):
                out.append((t.args[1].id, node, node.lineno))
    return out


def _attr_reads(branch: ast.If, receiver: str = "plan") -> set[str]:
    reads: set[str] = set()
    for stmt in branch.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and node.value.id == receiver:
                reads.add(node.attr)
    return reads


def decode_calls(tree: ast.Module, class_names: set[str]):
    """(class_name, n_explicit_args, has_star, lineno) for constructor
    calls inside decode_plan."""
    fn = next((n for n in tree.body
               if isinstance(n, ast.FunctionDef) and n.name == "decode_plan"), None)
    if fn is None:
        return
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in class_names:
            star = any(isinstance(a, ast.Starred) for a in node.args) or \
                any(k.arg is None for k in node.keywords)
            yield node.func.id, len(node.args) + len(node.keywords), star, node.lineno


class SerdeCompletenessPass(AnalysisPass):
    pass_id = "serde-sync"
    doc = "plan/stage node __init__ params must agree with encode/decode coverage"

    def run(self, analyzer: Analyzer) -> list[Finding]:
        findings: list[Finding] = []
        classes = _serde_classes()

        serde_src = analyzer.file(SERDE_REL)
        if serde_src is not None and serde_src.tree is not None:
            tree = serde_src.tree
            covered: set[str] = set()
            for cls_name, branch, lineno in encode_branches(tree):
                cls = classes.get(cls_name)
                if cls is None:
                    continue
                covered.add(cls_name)
                reads = _attr_reads(branch)
                for param in _class_params(cls):
                    attr = ENCODE_ALIASES.get((cls_name, param), param)
                    if attr not in reads:
                        findings.append(Finding(
                            self.pass_id, serde_src.rel, lineno,
                            f"encode_plan({cls_name}) never reads plan.{attr}: "
                            f"__init__ parameter '{param}' cannot reach the wire",
                            symbol=f"{cls_name}.{param}",
                        ))
            decoded: set[str] = set()
            for cls_name, n_args, star, lineno in decode_calls(tree, set(classes)):
                decoded.add(cls_name)
                if star:
                    continue
                params = _class_params(classes[cls_name])
                if n_args != len(params):
                    findings.append(Finding(
                        self.pass_id, serde_src.rel, lineno,
                        f"decode_plan builds {cls_name} with {n_args} of "
                        f"{len(params)} __init__ parameters; a defaulted "
                        f"parameter silently loses state on the wire",
                        symbol=f"{cls_name}.__call__",
                    ))
            # every encodable class must also be constructed somewhere in decode
            for cls_name in sorted(covered - decoded):
                findings.append(Finding(
                    self.pass_id, serde_src.rel, 1,
                    f"{cls_name} has an encode branch but decode_plan never "
                    f"constructs it",
                    symbol=f"{cls_name}.decode",
                ))

        # leg 3: QueryStage fields survive the ExecutionGraph proto round-trip
        findings.extend(self._check_query_stage(analyzer))
        return findings

    def _check_query_stage(self, analyzer: Analyzer) -> list[Finding]:
        import dataclasses

        from ballista_tpu.scheduler.planner import QueryStage

        findings: list[Finding] = []
        fields = [f.name for f in dataclasses.fields(QueryStage)]
        src = analyzer.file(GRAPH_REL)
        if src is None or src.tree is None:
            return findings
        fn = None
        for node in ast.walk(src.tree):
            if isinstance(node, ast.FunctionDef) and node.name == "from_proto":
                fn = node
                break
        if fn is None:
            return findings
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                    and node.func.id == "QueryStage":
                supplied = {k.arg for k in node.keywords if k.arg}
                # positional args cover leading fields in order
                supplied.update(fields[: len(node.args)])
                for f in fields:
                    if f not in supplied:
                        findings.append(Finding(
                            self.pass_id, src.rel, node.lineno,
                            f"ExecutionGraph.from_proto rebuilds QueryStage "
                            f"without '{f}': the flag is dropped on scheduler "
                            f"restart / graph hand-off",
                            symbol=f"QueryStage.{f}",
                        ))
        return findings
