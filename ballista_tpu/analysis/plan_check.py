"""Static verifier over staged physical plans and ExecutionGraphs.

The distributed planner, the mesh merge pass, AQE replans, and graph
recovery all REWRITE stage DAGs; each rewrite preserves a set of
invariants nothing re-checks afterward. This module checks them:

stage-list invariants (`verify_stages`):
- stage ids unique; every root is a ShuffleWriterExec tagged with its own
  stage id; `input_stage_ids` equals the UnresolvedShuffleExec leaves
  actually present in the plan; references resolve; the DAG is acyclic
- every shuffle edge agrees with its producer: the leaf's
  `output_partitions` matches the producer stage's, the `broadcast` flag
  matches, and the leaf's schema (field names + dtypes) matches what the
  producer's writer actually emits
- mesh gating (`merge_mesh_stages` postconditions): `stage.mesh` iff the
  plan contains a MeshExchangeExec; a mesh stage is never a broadcast
  producer; the exchange's device bucket count equals the stage's task
  span

graph invariants (`verify_graph`): all of the above on the stage specs,
plus `effective_partitions <= spec.partitions + skew growth` (AQE may
shrink by coalescing, and may grow ONLY by the slice count its
SkewSplitReport accounts for), task ids below the fast-lane band
(`FAST_TASK_ID_BASE` — graph tasks and fast jobs share the executor's
task-id namespace), and resolved readers tagged with a live
`source_stage_id`.

skew-split postconditions (`verify_graph`, when a stage carries a
SkewSplitReport): for every split hot bucket and every non-broadcast
resolved reader, the slice tasks' location lists must either each equal
the producer's full bucket list (a duplicated join build side) or
concatenate to EXACTLY that list in (map_partition, path) order —
cover, no overlap, and order, the same three legs the grace verifier
checks. A violated split would silently drop, duplicate, or permute
probe rows.

lease-band invariants (`verify_lease_bands`): direct-dispatch leases
reserve task-id bands at/above `DIRECT_TASK_ID_BASE`, pairwise disjoint,
with allocation cursors inside their band — and `verify_graph` flags any
scheduler-run task whose id strays into that band. Together these prove
a direct-dispatched task id can never collide with a scheduler-assigned
one in the executor's shared namespace.

Wiring: `ballista.debug.plan.verify` runs `check_stages` at submit time
(after `merge_mesh_stages`) and `check_graph` after AQE replans, failing
the job instead of executing a corrupt DAG. The TPC-H plan-stability
tests call `check_stages` unconditionally on every golden plan.
"""

from __future__ import annotations

from dataclasses import dataclass

from ballista_tpu.errors import GeneralError
from ballista_tpu.ops.tpu.mesh_stage import MeshExchangeExec, contains_mesh_exchange
from ballista_tpu.shuffle.reader import ShuffleReaderExec, UnresolvedShuffleExec
from ballista_tpu.shuffle.writer import ShuffleWriterExec


@dataclass(frozen=True)
class PlanViolation:
    code: str  # stable machine tag, e.g. "edge-schema"
    stage_id: int
    message: str

    def render(self) -> str:
        return f"stage {self.stage_id}: [{self.code}] {self.message}"


class PlanVerificationError(GeneralError):
    """Raised by check_stages/check_graph; carries the full violation list."""

    def __init__(self, violations: list[PlanViolation]):
        self.violations = violations
        super().__init__(
            "plan verification failed:\n  " +
            "\n  ".join(v.render() for v in violations)
        )


def _schema_fields(schema) -> list[tuple[str, str]]:
    # compare names + dtypes; qualifiers legitimately differ across a
    # shuffle edge (the reader drops table qualifiers the writer kept)
    return [(f.name, str(f.dtype)) for f in schema]


def _shuffle_leaves(plan) -> list:
    out = []

    def walk(n):
        if isinstance(n, (UnresolvedShuffleExec, ShuffleReaderExec)):
            out.append(n)
        for c in n.children():
            walk(c)

    walk(plan)
    return out


def _mesh_exchanges(plan) -> list[MeshExchangeExec]:
    out = []

    def walk(n):
        if isinstance(n, MeshExchangeExec):
            out.append(n)
        for c in n.children():
            walk(c)

    walk(plan)
    return out


def verify_stages(stages) -> list[PlanViolation]:
    """Invariants over a list of QueryStage (pre-graph, post-merge)."""
    v: list[PlanViolation] = []
    by_id = {}
    for s in stages:
        if s.stage_id in by_id:
            v.append(PlanViolation("dup-stage-id", s.stage_id,
                                   "duplicate stage id in stage list"))
        by_id[s.stage_id] = s

    for s in stages:
        plan = s.plan
        if not isinstance(plan, ShuffleWriterExec):
            v.append(PlanViolation("root-not-writer", s.stage_id,
                                   f"stage root is {type(plan).__name__}, "
                                   f"expected ShuffleWriterExec"))
            continue
        if plan.stage_id != s.stage_id:
            v.append(PlanViolation("writer-stage-id", s.stage_id,
                                   f"writer is tagged stage {plan.stage_id}"))
        if plan.output_partitions > 0 and plan.output_partitions != s.output_partitions:
            v.append(PlanViolation(
                "writer-partitions", s.stage_id,
                f"writer produces {plan.output_partitions} output partitions "
                f"but the stage advertises {s.output_partitions}"))

        leaves = [l for l in _shuffle_leaves(plan) if isinstance(l, UnresolvedShuffleExec)]
        leaf_ids = sorted({l.stage_id for l in leaves})
        if leaf_ids != sorted(set(s.input_stage_ids)):
            v.append(PlanViolation(
                "input-ids", s.stage_id,
                f"input_stage_ids={sorted(set(s.input_stage_ids))} but the plan "
                f"references stages {leaf_ids}"))

        for leaf in leaves:
            prod = by_id.get(leaf.stage_id)
            if prod is None:
                v.append(PlanViolation(
                    "dangling-input", s.stage_id,
                    f"reads stage {leaf.stage_id} which is not in the stage list"))
                continue
            if leaf.output_partitions != prod.output_partitions:
                v.append(PlanViolation(
                    "edge-partitions", s.stage_id,
                    f"reads stage {prod.stage_id} expecting "
                    f"{leaf.output_partitions} partitions; the producer makes "
                    f"{prod.output_partitions}"))
            if bool(leaf.broadcast) != bool(prod.broadcast):
                v.append(PlanViolation(
                    "edge-broadcast", s.stage_id,
                    f"reads stage {prod.stage_id} with broadcast={leaf.broadcast} "
                    f"but the producer stage has broadcast={prod.broadcast}"))
            if isinstance(prod.plan, ShuffleWriterExec):
                produced = _schema_fields(prod.plan.input.df_schema)
                expected = _schema_fields(leaf.df_schema)
                if produced != expected:
                    v.append(PlanViolation(
                        "edge-schema", s.stage_id,
                        f"reads stage {prod.stage_id} expecting fields "
                        f"{expected} but the producer emits {produced}"))

        # mesh gating postconditions
        exchanges = _mesh_exchanges(plan)
        if bool(s.mesh) != bool(exchanges):
            v.append(PlanViolation(
                "mesh-flag", s.stage_id,
                f"mesh={s.mesh} but the plan contains {len(exchanges)} "
                f"MeshExchangeExec node(s); the flag and the plan must agree "
                f"(pop_next_task ships mesh stages as ONE unsliced task)"))
        if s.mesh and s.broadcast:
            v.append(PlanViolation(
                "mesh-broadcast", s.stage_id,
                "a mesh stage cannot be a broadcast producer (the merge gate "
                "rejects broadcast edges)"))
        for ex in exchanges:
            if ex.file_partitions != s.partitions:
                v.append(PlanViolation(
                    "mesh-buckets", s.stage_id,
                    f"mesh exchange routes {ex.file_partitions} device buckets "
                    f"but the stage spans {s.partitions} task partitions; the "
                    f"single mesh task must cover exactly the reduce buckets"))

    # acyclicity over the input-stage edges
    state: dict[int, int] = {}  # 0=visiting, 1=done

    def dfs(sid: int) -> bool:
        if state.get(sid) == 1:
            return True
        if state.get(sid) == 0:
            return False
        state[sid] = 0
        s = by_id.get(sid)
        ok = all(dfs(i) for i in (s.input_stage_ids if s else []) if i in by_id)
        state[sid] = 1
        return ok

    for sid in by_id:
        if not dfs(sid):
            v.append(PlanViolation("cycle", sid, "stage dependency cycle"))
            break
    return v


def verify_graph(graph) -> list[PlanViolation]:
    """verify_stages over the specs, plus runtime-state invariants."""
    from ballista_tpu.serving.fast_lane import FAST_TASK_ID_BASE

    stages = [st.spec for st in graph.stages.values()]
    v = verify_stages(stages)
    if graph.next_task_id >= FAST_TASK_ID_BASE:
        v.append(PlanViolation(
            "task-id-band", 0,
            f"next_task_id={graph.next_task_id} has crossed the fast-lane "
            f"band (FAST_TASK_ID_BASE={FAST_TASK_ID_BASE}); graph and fast "
            f"tasks would collide in the executor task-id namespace"))
    from ballista_tpu.serving.lease import DIRECT_TASK_ID_BASE

    for st in graph.stages.values():
        report = getattr(st, "skew_report", None)
        allowed_growth = getattr(report, "extra_partitions", 0) if report else 0
        if st.effective_partitions > st.spec.partitions + allowed_growth:
            v.append(PlanViolation(
                "aqe-grew", st.stage_id,
                f"effective_partitions={st.effective_partitions} exceeds the "
                f"planned {st.spec.partitions} plus the {allowed_growth} "
                f"slice partitions the skew report accounts for; AQE growth "
                f"must be backed by a SkewSplitReport"))
        v.extend(_verify_skew_splits(graph, st))
        for task_id in st.running:
            if task_id >= DIRECT_TASK_ID_BASE:
                v.append(PlanViolation(
                    "lease-band", st.stage_id,
                    f"running task {task_id} is inside the direct-dispatch "
                    f"lease band (>= {DIRECT_TASK_ID_BASE}); only a client "
                    f"holding an executor lease may mint ids there, never "
                    f"the scheduler's graph loop"))
            elif task_id >= FAST_TASK_ID_BASE:
                v.append(PlanViolation(
                    "task-id-band", st.stage_id,
                    f"running task {task_id} is inside the fast-lane id band"))
        if st.resolved_plan is not None and st.resolved_plan is not st.spec.plan:
            for leaf in _shuffle_leaves(st.resolved_plan):
                if isinstance(leaf, UnresolvedShuffleExec):
                    continue  # partially resolved plans are legal mid-flight
                src = getattr(leaf, "source_stage_id", None)
                if src is not None and src not in graph.stages:
                    v.append(PlanViolation(
                        "reader-source", st.stage_id,
                        f"resolved reader tagged source_stage_id={src}, which "
                        f"is not a stage of this graph"))
    return v


def _verify_skew_splits(graph, st) -> list[PlanViolation]:
    """Postconditions of an AQE skew split, checked against the stage's
    SkewSplitReport before any slice task runs. For each hot bucket, each
    non-broadcast reader's lists at the slice partitions must either each
    equal the producer's full bucket location list (duplicated build side)
    or concatenate exactly to it — cover / no-overlap / order over
    (map_partition, path) identity."""
    v: list[PlanViolation] = []
    report = getattr(st, "skew_report", None)
    if report is None or st.resolved_plan is None:
        return v
    readers = [l for l in _shuffle_leaves(st.resolved_plan)
               if isinstance(l, ShuffleReaderExec) and not l.broadcast]
    for split in report.splits:
        for r in readers:
            src = getattr(r, "source_stage_id", None)
            prod = graph.stages.get(src) if src is not None else None
            if prod is None:
                continue
            want = sorted(
                (l.map_partition, l.path) for l in prod.output_locations()
                if l.output_partition == split.bucket
            )
            slices: list[list[tuple]] = []
            truncated = False
            for p in split.partitions:
                if p >= len(r.partition_locations):
                    v.append(PlanViolation(
                        "skew-cover", st.stage_id,
                        f"split of bucket {split.bucket} names slice "
                        f"partition {p} but a reader of stage {src} only has "
                        f"{len(r.partition_locations)} partition lists"))
                    truncated = True
                    break
                slices.append([(l.map_partition, l.path)
                               for l in r.partition_locations[p]])
            if truncated:
                continue
            if want and all(s == want for s in slices):
                continue  # duplicated join build side: every slice sees it all
            got = [t for s in slices for t in s]
            if got == want:
                continue  # clean slicing: cover, no overlap, in order
            if sorted(got) == want:
                v.append(PlanViolation(
                    "skew-order", st.stage_id,
                    f"split of bucket {split.bucket} (stage {src} input) "
                    f"covers the bucket but permutes its map outputs; only "
                    f"in-order concatenation is byte-identical"))
            else:
                missing = len(set(want) - set(got))
                v.append(PlanViolation(
                    "skew-cover", st.stage_id,
                    f"split of bucket {split.bucket} (stage {src} input) "
                    f"does not partition the bucket's map outputs: "
                    f"{len(got)} slice locations vs {len(want)} produced "
                    f"({missing} missing); every map output must be read "
                    f"exactly once across the slices"))
    return v


def verify_grace(report) -> list[PlanViolation]:
    """Postconditions of a grace-partitioned join execution (an
    `hbm.GraceReport`). The executor checks these after every grace run —
    a violation demotes the stage to the CPU engine instead of serving a
    result the verifier can't vouch for:

    - **cover**: the run + empty sub-bucket sets partition exactly
      [0, n_buckets) — every build row's bucket was visited once, so no
      probe match was dropped or double-counted;
    - **order**: the sub-runs reunified in producer row order (probe rows
      are never permuted; "producer-order" is the only merge the
      byte-identity argument covers);
    - **depth**: recursion depth ≤ the configured cap, and the bucket
      count is exactly fanout**depth (the iterative-deepening contract —
      past the cap the ladder must land on cpu_demote, not a wider split).
    """
    v: list[PlanViolation] = []

    def bad(code: str, message: str) -> None:
        v.append(PlanViolation(code, 0, f"[{report.stage_tag}] {message}"))

    run = set(report.buckets_run)
    empty = set(report.buckets_empty)
    if run & empty:
        bad("grace-cover", f"buckets {sorted(run & empty)} were reported "
            f"both run and empty")
    if run | empty != set(range(report.n_buckets)):
        bad("grace-cover",
            f"sub-buckets {sorted(run | empty)} do not cover "
            f"[0, {report.n_buckets}); the split must visit every bucket "
            f"exactly once")
    if report.merge != "producer-order":
        bad("grace-order", f"sub-runs merged as {report.merge!r}; only "
            f"producer-order reunification is byte-identical")
    if report.depth > report.max_depth:
        bad("grace-depth", f"recursion depth {report.depth} exceeds the "
            f"cap {report.max_depth}")
    if report.depth < 1:
        bad("grace-depth", f"grace ran with depth {report.depth}; a split "
            f"plan implies depth >= 1")
    if report.fanout < 2:
        bad("grace-depth", f"fanout {report.fanout} cannot split anything")
    elif report.n_buckets != report.fanout ** max(report.depth, 0):
        bad("grace-depth",
            f"{report.n_buckets} sub-buckets != fanout {report.fanout} ** "
            f"depth {report.depth}")
    return v


def check_grace(report) -> list[PlanViolation]:
    """verify_grace, returned (not raised): the executor turns violations
    into a CPU demotion, the analysis CLI renders them."""
    return verify_grace(report)


def verify_lease_bands(leases) -> list[PlanViolation]:
    """Direct-dispatch band invariants over a set of `ExecutorLease`s
    (live or historical). A lease hands a client a private task-id range;
    byte-identity of direct results depends on those ids never colliding
    with scheduler-assigned ids (graph tasks < FAST_TASK_ID_BASE, fast
    jobs < DIRECT_TASK_ID_BASE) or with each other:

    - **floor**: every band starts at or above `DIRECT_TASK_ID_BASE`;
    - **disjoint**: no two bands overlap (the registry allocates them
      monotonically — an overlap means two clients can mint the same id
      at one executor);
    - **cursor**: a lease's allocation cursor stays within its band
      (`0 <= next_offset <= band_size`).
    """
    from ballista_tpu.serving.lease import DIRECT_TASK_ID_BASE

    v: list[PlanViolation] = []

    def bad(code: str, lease, message: str) -> None:
        v.append(PlanViolation(code, 0, f"[lease {lease.lease_id}] {message}"))

    ranges = []
    for lease in leases:
        start, size = lease.band_start, lease.band_size
        if size <= 0:
            bad("lease-band", lease, f"band_size={size}; an empty band can "
                f"never admit a task")
            continue
        if start < DIRECT_TASK_ID_BASE:
            bad("lease-band", lease,
                f"band [{start}, {start + size}) starts below "
                f"DIRECT_TASK_ID_BASE={DIRECT_TASK_ID_BASE}; direct ids "
                f"would collide with scheduler-assigned task ids")
        cursor = getattr(lease, "next_offset", 0)
        if not 0 <= cursor <= size:
            bad("lease-band", lease,
                f"allocation cursor next_offset={cursor} is outside "
                f"[0, band_size={size}]; ids minted past the band spill "
                f"into a neighbouring lease's range")
        ranges.append((start, start + size, lease))
    ranges.sort(key=lambda r: r[0])
    for (a_lo, a_hi, a), (b_lo, b_hi, b) in zip(ranges, ranges[1:]):
        if b_lo < a_hi:
            bad("lease-band", b,
                f"band [{b_lo}, {b_hi}) overlaps lease {a.lease_id}'s "
                f"band [{a_lo}, {a_hi}); two clients could mint the same "
                f"task id at one executor")
    return v


def check_lease_bands(leases) -> None:
    violations = verify_lease_bands(leases)
    if violations:
        raise PlanVerificationError(violations)


def check_stages(stages) -> None:
    violations = verify_stages(stages)
    if violations:
        raise PlanVerificationError(violations)


def check_graph(graph) -> None:
    violations = verify_graph(graph)
    if violations:
        raise PlanVerificationError(violations)
