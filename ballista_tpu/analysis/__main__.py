"""CLI: `python -m ballista_tpu.analysis`.

Exit codes: 0 clean (or fully baselined/suppressed), 1 actionable findings
or stale baseline entries, 2 usage error.
"""

from __future__ import annotations

import argparse
import sys

from ballista_tpu.analysis.core import Analyzer, save_baseline


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ballista_tpu.analysis",
        description="Run the engine invariant analyzer over the repo.",
    )
    ap.add_argument("--root", default=None, help="repo root (default: auto-detect)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: dev/analysis_baseline.json); "
                         "pass an empty string to ignore the baseline")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write all current findings to the baseline file and exit 0 "
                         "(each entry still needs a hand-written reason before review)")
    ap.add_argument("--pass", dest="passes", action="append", default=None,
                    metavar="PASS_ID", help="run only this pass (repeatable)")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also list suppressed and baselined findings")
    args = ap.parse_args(argv)

    baseline = args.baseline
    analyzer = Analyzer(root=args.root,
                        baseline_path="/dev/null" if baseline == "" else baseline)
    report = analyzer.run(pass_ids=args.passes)

    if args.update_baseline:
        combined = report.findings + [f for f, _ in report.baselined]
        reasons = {f.key(): r for f, r in report.baselined}
        save_baseline(analyzer.baseline_path, combined, reasons)
        print(f"wrote {len(combined)} entr(ies) to {analyzer.baseline_path}")
        return 0

    try:
        if args.json:
            print(report.to_json())
        else:
            print(report.render())
            if args.verbose:
                for f, sup in report.suppressed:
                    print(f"(suppressed: {sup.reason}) {f.render()}")
                for f, reason in report.baselined:
                    print(f"(baselined: {reason}) {f.render()}")
    except BrokenPipeError:  # e.g. piped into `head`
        pass
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
