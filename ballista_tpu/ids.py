"""Typed string ids.

The reference macro-generates newtype ids (`JobId`, `JobName`,
ballista/core/src/ids.rs:59,118) so a job id can never be passed where a
stage key is expected. Python's analog: tiny str subclasses (zero-cost at
runtime, checkable by type checkers and by `isinstance` asserts in tests)
plus the id-minting helpers the scheduler uses.
"""

from __future__ import annotations

import random
import string
import time

_ALPHANUM = string.ascii_lowercase + string.digits


class JobId(str):
    __slots__ = ()


class JobName(str):
    __slots__ = ()


class ExecutorId(str):
    __slots__ = ()


class SessionId(str):
    __slots__ = ()


def new_job_id(rng: random.Random | None = None) -> JobId:
    """Sortable-ish unique job id: time prefix + random suffix.

    The reference uses a purely random 7-char id; we prefix a time component
    so `ls` of the shuffle work dir sorts by submission order, which the
    reference's own docs note is useful when debugging work-dir leaks.
    """
    r = rng or random
    t = int(time.time()) % (36**4)
    prefix = _b36(t, 4)
    suffix = "".join(r.choice(_ALPHANUM) for _ in range(6))
    return JobId(prefix + suffix)


def new_session_id(rng: random.Random | None = None) -> SessionId:
    r = rng or random
    return SessionId("".join(r.choice(_ALPHANUM) for _ in range(16)))


def new_executor_id(rng: random.Random | None = None) -> ExecutorId:
    r = rng or random
    return ExecutorId("".join(r.choice(_ALPHANUM) for _ in range(12)))


def _b36(n: int, width: int) -> str:
    digits = "0123456789abcdefghijklmnopqrstuvwxyz"
    out = []
    for _ in range(width):
        out.append(digits[n % 36])
        n //= 36
    return "".join(reversed(out))
