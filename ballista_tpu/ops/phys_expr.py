"""Physical expressions: index-bound, evaluated over Arrow RecordBatches.

`bind_expr` compiles a logical Expr against a DFSchema into a PhysicalExpr
tree whose `evaluate(batch)` returns a pyarrow Array (CPU engine path).
The TPU engine compiles the same logical exprs to jax instead
(ops/tpu/stage_compiler.py); keeping binding separate per engine is the
moral equivalent of the reference's create_physical_expr seam.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ballista_tpu.errors import ExecutionError, PlanningError
from ballista_tpu.plan.expressions import (
    Alias,
    Between,
    BinaryExpr,
    Case,
    Cast,
    Column,
    Expr,
    InList,
    IsNotNull,
    IsNull,
    Like,
    Literal,
    Negative,
    Not,
    ScalarFunction,
)
from ballista_tpu.plan.schema import DFSchema


class PhysicalExpr:
    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        raise NotImplementedError

    def __str__(self) -> str:
        return type(self).__name__


@dataclass
class Col(PhysicalExpr):
    index: int
    name: str

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        return batch.column(self.index)

    def __str__(self) -> str:
        return f"{self.name}@{self.index}"


@dataclass
class Lit(PhysicalExpr):
    value: Any

    def evaluate(self, batch: pa.RecordBatch):
        return pa.scalar(self.value)

    def __str__(self) -> str:
        return repr(self.value)


_ARITH = {
    "+": pc.add_checked if hasattr(pc, "add_checked") else pc.add,
    "-": pc.subtract,
    "*": pc.multiply,
    "/": pc.divide,
    "%": lambda a, b: pc.subtract(a, pc.multiply(pc.floor(pc.divide(a, b)), b)),
}
_CMP = {
    "=": pc.equal, "<>": pc.not_equal, "<": pc.less,
    "<=": pc.less_equal, ">": pc.greater, ">=": pc.greater_equal,
}


@dataclass
class BinOp(PhysicalExpr):
    left: PhysicalExpr
    op: str
    right: PhysicalExpr
    # planned arith result type (decimal policy): stamped by bind_expr so
    # runtime coercion reproduces exactly what the planner typed; None for
    # comparisons/bools and pre-decimal callers
    out_type: pa.DataType | None = None

    def evaluate(self, batch: pa.RecordBatch):
        l = self.left.evaluate(batch)
        r = self.right.evaluate(batch)
        if self.op in _CMP:
            return _CMP[self.op](l, r)
        if self.op == "and":
            return pc.and_kleene(l, r)
        if self.op == "or":
            return pc.or_kleene(l, r)
        if pa.types.is_decimal(_type_of(l)) or pa.types.is_decimal(_type_of(r)):
            return _decimal_binop(self.op, l, r, self.out_type)
        if self.op == "+":
            return pc.add(l, r)
        if self.op == "-":
            return pc.subtract(l, r)
        if self.op == "*":
            return pc.multiply(l, r)
        if self.op == "/":
            if pa.types.is_integer(_type_of(l)):
                l = pc.cast(l, pa.float64())
            return pc.divide(l, r)
        if self.op == "%":
            return _ARITH["%"](l, r)
        raise ExecutionError(f"bad op {self.op}")

    def __str__(self) -> str:
        return f"({self.left} {self.op} {self.right})"


def _type_of(v) -> pa.DataType:
    return v.type


def _decimal_binop(op: str, l, r, planned: pa.DataType | None):
    """Exact decimal arithmetic mirroring decimal_arith_type's branches
    (plan/expressions.py). Planned float64 ⇒ compute in float (division,
    float operands, precision overflow past decimal256). Planned decimal ⇒
    re-type integer-literal scalars tightly, lift decimal128 inputs to
    decimal256 when the planned type is, and pin the kernel's result to the
    planned type so batch schemas never drift from the plan."""
    import decimal as _d

    if planned is None and op in ("/", "%"):
        # a pre-decimal caller (bind-time typing failed): these ops always
        # compute in float under the exact policy anyway
        planned = pa.float64()
    if planned is not None and pa.types.is_floating(planned):
        if pa.types.is_decimal(l.type):
            l = pc.cast(l, pa.float64())
        if pa.types.is_decimal(r.type):
            r = pc.cast(r, pa.float64())
        if op == "/" and pa.types.is_integer(l.type):
            l = pc.cast(l, pa.float64())
        return _ARITH[op](l, r) if op == "%" else {
            "+": pc.add, "-": pc.subtract, "*": pc.multiply, "/": pc.divide}[op](l, r)

    def tighten(v):
        # integer literal scalar → minimal decimal (the planner's
        # _effective_decimal counterpart)
        if isinstance(v, pa.Scalar) and pa.types.is_integer(v.type):
            return pa.scalar(_d.Decimal(v.as_py()))
        return v

    l, r = tighten(l), tighten(r)
    if planned is not None and pa.types.is_decimal256(planned):
        if pa.types.is_decimal128(l.type):
            l = pc.cast(l, pa.decimal256(l.type.precision, l.type.scale))
        if pa.types.is_decimal128(r.type):
            r = pc.cast(r, pa.decimal256(r.type.precision, r.type.scale))
    out = {"+": pc.add, "-": pc.subtract, "*": pc.multiply}[op](l, r)
    if planned is not None and out.type != planned:
        out = pc.cast(out, planned)
    return out


@dataclass
class NotOp(PhysicalExpr):
    child: PhysicalExpr

    def evaluate(self, batch):
        return pc.invert(self.child.evaluate(batch))


@dataclass
class NegOp(PhysicalExpr):
    child: PhysicalExpr

    def evaluate(self, batch):
        return pc.negate(self.child.evaluate(batch))


@dataclass
class IsNullOp(PhysicalExpr):
    child: PhysicalExpr

    def evaluate(self, batch):
        return pc.is_null(self.child.evaluate(batch))


@dataclass
class IsNotNullOp(PhysicalExpr):
    child: PhysicalExpr

    def evaluate(self, batch):
        return pc.is_valid(self.child.evaluate(batch))


@dataclass
class CastOp(PhysicalExpr):
    child: PhysicalExpr
    to: pa.DataType

    def evaluate(self, batch):
        return pc.cast(self.child.evaluate(batch), self.to)


@dataclass
class LikeOp(PhysicalExpr):
    child: PhysicalExpr
    pattern: str
    negated: bool

    def evaluate(self, batch):
        out = pc.match_like(self.child.evaluate(batch), self.pattern)
        return pc.invert(out) if self.negated else out


@dataclass
class InListOp(PhysicalExpr):
    child: PhysicalExpr
    values: tuple
    negated: bool

    def evaluate(self, batch):
        arr = self.child.evaluate(batch)
        vs = pa.array(list(self.values))
        try:
            vs = vs.cast(arr.type)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
            pass
        out = pc.is_in(arr, value_set=vs)
        return pc.invert(out) if self.negated else out


@dataclass
class BetweenOp(PhysicalExpr):
    child: PhysicalExpr
    low: PhysicalExpr
    high: PhysicalExpr
    negated: bool

    def evaluate(self, batch):
        v = self.child.evaluate(batch)
        out = pc.and_(pc.greater_equal(v, self.low.evaluate(batch)),
                      pc.less_equal(v, self.high.evaluate(batch)))
        return pc.invert(out) if self.negated else out


@dataclass
class CaseOp(PhysicalExpr):
    branches: tuple  # ((when, then), ...)
    else_expr: PhysicalExpr | None
    out_type: pa.DataType

    def evaluate(self, batch):
        n = batch.num_rows
        if self.else_expr is not None:
            result = self.else_expr.evaluate(batch)
            if isinstance(result, pa.Scalar):
                result = pa.array([py_for_type(result.as_py(), self.out_type)] * n, self.out_type)
            else:
                result = result.cast(self.out_type)
        else:
            result = pa.nulls(n, self.out_type)
        decided = pa.array(np.zeros(n, dtype=bool))
        # first-match-wins: apply branches in order, masking decided rows
        for when, then in self.branches:
            cond = when.evaluate(batch)
            if isinstance(cond, pa.Scalar):
                cond = pa.array([bool(cond.as_py())] * n)
            cond = pc.and_(pc.fill_null(cond, False), pc.invert(decided))
            tv = then.evaluate(batch)
            if isinstance(tv, pa.Scalar):
                tv = pa.array([py_for_type(tv.as_py(), self.out_type)] * n, self.out_type)
            else:
                tv = tv.cast(self.out_type)
            result = pc.if_else(cond, tv, result)
            decided = pc.or_(decided, cond)
        return result


@dataclass
class DateAddOp(PhysicalExpr):
    """date column ± interval literal (days/months/years)."""

    child: PhysicalExpr
    n: int
    unit: str
    sign: int

    def evaluate(self, batch):
        arr = self.child.evaluate(batch)
        if pa.types.is_timestamp(arr.type):  # joins may surface dates as ts
            arr = arr.cast(pa.date32())
        n = self.n * self.sign
        if self.unit == "day":
            return pc.add(arr.cast(pa.int32()), pa.scalar(n, pa.int32())).cast(pa.date32())
        np_days = arr.cast(pa.int32()).to_numpy(zero_copy_only=False)
        dates = np_days.astype("datetime64[D]")
        months = n * 12 if self.unit == "year" else n
        out = (dates.astype("datetime64[M]") + months).astype("datetime64[D]") + (
            dates - dates.astype("datetime64[M]").astype("datetime64[D]")
        )
        return pa.array(out).cast(pa.date32())


@dataclass
class ScalarFnOp(PhysicalExpr):
    name: str
    args: tuple

    def evaluate(self, batch):
        n = self.name
        a = [x.evaluate(batch) for x in self.args]
        if n == "extract_year":
            return pc.cast(pc.year(a[0]), pa.int64())
        if n == "extract_month":
            return pc.cast(pc.month(a[0]), pa.int64())
        if n == "extract_day":
            return pc.cast(pc.day(a[0]), pa.int64())
        if n == "substr":
            start = _as_py(a[1])
            if len(a) == 3:
                return pc.utf8_slice_codeunits(a[0], start - 1, start - 1 + _as_py(a[2]))
            return pc.utf8_slice_codeunits(a[0], start - 1)
        if n == "strpos":
            return pc.cast(pc.add(pc.find_substring(a[0], pattern=_as_py(a[1])), 1), pa.int64())
        if n == "length":
            return pc.cast(pc.utf8_length(a[0]), pa.int64())
        if n == "upper":
            return pc.utf8_upper(a[0])
        if n == "lower":
            return pc.utf8_lower(a[0])
        if n == "trim":
            return pc.utf8_trim_whitespace(a[0])
        if n == "concat":
            return pc.binary_join_element_wise(*a, "")
        if n == "abs":
            return pc.abs(a[0])
        if n == "sqrt":
            return pc.sqrt(pc.cast(a[0], pa.float64()))
        if n == "round":
            ndigits = _as_py(a[1]) if len(a) > 1 else 0
            return pc.round(a[0], ndigits=ndigits)
        if n == "ceil":
            return pc.ceil(a[0])
        if n == "floor":
            return pc.floor(a[0])
        if n == "coalesce":
            return pc.coalesce(*a)
        from ballista_tpu import udf

        u = udf.resolve(n)
        if u is not None:
            arrays = [
                x if not isinstance(x, pa.Scalar)
                else pa.array([x.as_py()] * batch.num_rows, x.type)
                for x in a
            ]
            out = u.fn(*arrays)
            if not isinstance(out, (pa.Array, pa.ChunkedArray, pa.Scalar)):
                out = pa.array(out, u.return_type)
            return out
        raise ExecutionError(f"unknown scalar function {n}")


def _as_py(v):
    return v.as_py() if isinstance(v, pa.Scalar) else v


def py_for_type(v, t: pa.DataType):
    """Coerce a Python literal for materialization as type `t`: exact-policy
    decimal literals flow into float/int slots (CASE branches, lag/lead
    defaults) that pyarrow refuses to convert implicitly."""
    import decimal as _d

    if isinstance(v, _d.Decimal):
        if pa.types.is_floating(t):
            return float(v)
        if pa.types.is_integer(t):
            return int(v)
    elif isinstance(v, (int, float)) and not isinstance(v, bool) and pa.types.is_decimal(t):
        # float/int branch into a decimal slot (e.g. a sci-notation literal
        # in a CASE whose other branches are decimal)
        return _d.Decimal(str(v))
    return v


def bind_expr(e: Expr, schema: DFSchema) -> PhysicalExpr:
    if isinstance(e, Alias):
        return bind_expr(e.expr, schema)
    if isinstance(e, Column):
        i = schema.index_of(e.name, e.qualifier)
        return Col(i, e.name)
    if isinstance(e, Literal):
        v = e.value
        if isinstance(v, tuple):
            raise PlanningError("bare interval literal outside date arithmetic")
        return Lit(v)
    if isinstance(e, BinaryExpr):
        # date ± interval over a column
        if isinstance(e.right, Literal) and isinstance(e.right.value, tuple) and e.op in ("+", "-"):
            n, unit = e.right.value
            return DateAddOp(bind_expr(e.left, schema), n, unit, -1 if e.op == "-" else 1)
        out_type = None
        if e.op in ("+", "-", "*", "/", "%"):
            try:
                out_type = e.data_type(schema)  # decimal coercion contract
            except Exception:  # noqa: BLE001 — typing is advisory for non-decimals
                out_type = None
        return BinOp(bind_expr(e.left, schema), e.op, bind_expr(e.right, schema), out_type)
    if isinstance(e, Not):
        return NotOp(bind_expr(e.expr, schema))
    if isinstance(e, Negative):
        return NegOp(bind_expr(e.expr, schema))
    if isinstance(e, IsNull):
        return IsNullOp(bind_expr(e.expr, schema))
    if isinstance(e, IsNotNull):
        return IsNotNullOp(bind_expr(e.expr, schema))
    if isinstance(e, Cast):
        return CastOp(bind_expr(e.expr, schema), e.to)
    if isinstance(e, Like):
        return LikeOp(bind_expr(e.expr, schema), e.pattern, e.negated)
    if isinstance(e, InList):
        return InListOp(bind_expr(e.expr, schema), e.values, e.negated)
    if isinstance(e, Between):
        return BetweenOp(
            bind_expr(e.expr, schema), bind_expr(e.low, schema), bind_expr(e.high, schema), e.negated
        )
    if isinstance(e, Case):
        out_type = e.data_type(schema)
        return CaseOp(
            tuple((bind_expr(w, schema), bind_expr(t, schema)) for w, t in e.branches),
            bind_expr(e.else_expr, schema) if e.else_expr is not None else None,
            out_type,
        )
    if isinstance(e, ScalarFunction):
        return ScalarFnOp(e.name, tuple(bind_expr(a, schema) for a in e.args))
    raise PlanningError(f"cannot bind {type(e).__name__}: {e}")


def evaluate_to_array(pe: PhysicalExpr, batch: pa.RecordBatch) -> pa.Array:
    out = pe.evaluate(batch)
    if isinstance(out, pa.Scalar):
        out = pa.array([out.as_py()] * batch.num_rows, out.type)
    if isinstance(out, pa.ChunkedArray):
        out = out.combine_chunks()
    return out
