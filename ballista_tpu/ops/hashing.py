"""Deterministic row hashing for repartitioning.

THE WIRE CONTRACT: the hash of a row must be identical no matter which
engine (cpu numpy, tpu jax, native C++) computed it, or shuffled data lands
in the wrong partition. Mirrors the role of the reference's fixed-seed
ahash in RepartitionExec. Algorithm: per-column 64-bit mix (splitmix64 over
the canonical int64 encoding of the value), columns combined with a
boost-style hash_combine. Null hashes to a fixed tag.

The jax twin of this function lives in ops/tpu/kernels.py
(hash64/hash_combine) and tests assert they agree bit-for-bit.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

_NULL_TAG = np.uint64(0x9E3779B97F4A7C15)


def splitmix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer; x is uint64."""
    with np.errstate(over="ignore"):
        x = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
        x = ((x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)).astype(np.uint64)
        x = ((x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)).astype(np.uint64)
        return (x ^ (x >> np.uint64(31))).astype(np.uint64)


def hash_combine(h: np.ndarray, v: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        return (h ^ (v + np.uint64(0x9E3779B97F4A7C15) + (h << np.uint64(6)) + (h >> np.uint64(2)))).astype(np.uint64)


def _int64_encoding(arr: pa.Array) -> tuple[np.ndarray, np.ndarray | None]:
    """Canonical int64 view of an array + validity mask (None = all valid)."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    t = arr.type
    mask = None
    if arr.null_count:
        mask = np.asarray(arr.is_valid())
    if pa.types.is_dictionary(t):
        arr = arr.cast(t.value_type)
        return _int64_encoding(arr)
    # For every fixed-width branch: fill nulls BEFORE to_numpy. A nullable
    # array round-trips through float64 in to_numpy, which both loses int64
    # precision past 2^53 and (without an astype) would bit-reinterpret
    # float64 as uint64 — breaking the cross-engine wire contract with the
    # native router (ops/native.py fills then converts exactly). Null slots
    # are overridden to _NULL_TAG by the mask downstream, so the fill value
    # never reaches a hash.
    import pyarrow.compute as pc

    if pa.types.is_integer(t):
        filled = pc.fill_null(arr, 0) if arr.null_count else arr
        vals = filled.cast(pa.int64(), safe=False).to_numpy(zero_copy_only=False)
        return vals.astype(np.int64, copy=False).view(np.uint64), mask
    if pa.types.is_date(t):
        # date32 is days-int32, date64 is ms-int64; Arrow has no date64→int32
        as_int = arr.cast(pa.int32() if pa.types.is_date32(t) else pa.int64(), safe=False)
        filled = pc.fill_null(as_int, 0) if arr.null_count else as_int
        vals = filled.cast(pa.int64()).to_numpy(zero_copy_only=False)
        return vals.astype(np.int64, copy=False).view(np.uint64), mask
    if pa.types.is_boolean(t):
        filled = pc.fill_null(arr, False) if arr.null_count else arr
        vals = filled.cast(pa.int64()).to_numpy(zero_copy_only=False)
        return vals.astype(np.int64, copy=False).view(np.uint64), mask
    if pa.types.is_floating(t):
        filled = pc.fill_null(arr, 0.0) if arr.null_count else arr
        vals = filled.cast(pa.float64()).to_numpy(zero_copy_only=False)
        # normalize -0.0 to 0.0 so equal keys hash equal
        vals = np.where(vals == 0.0, 0.0, vals)
        return vals.view(np.uint64), mask
    if pa.types.is_decimal(t):
        # exact policy: decimal keys route by unscaled int64 when it fits;
        # wider decimals route by their float64 image (routing only needs
        # equal keys → equal hash, which a deterministic cast preserves)
        filled = pc.fill_null(arr, 0) if arr.null_count else arr
        if pa.types.is_decimal128(t) and t.precision <= 18:
            scaled = pc.multiply(filled, pa.scalar(10 ** t.scale, pa.int64())) \
                if t.scale else filled
            vals = pc.cast(scaled, pa.int64()).to_numpy(zero_copy_only=False)
            return vals.astype(np.int64, copy=False).view(np.uint64), mask
        vals = filled.cast(pa.float64()).to_numpy(zero_copy_only=False)
        vals = np.where(vals == 0.0, 0.0, vals)
        return vals.view(np.uint64), mask
    if pa.types.is_string(t) or pa.types.is_large_string(t) or pa.types.is_binary(t):
        # FNV-1a over utf8 bytes, vectorized via offsets
        data = arr.cast(pa.large_binary())
        buffers = data.buffers()
        offsets = np.frombuffer(buffers[1], dtype=np.int64, count=len(arr) + 1 + (data.offset))
        offsets = offsets[data.offset : data.offset + len(arr) + 1]
        raw = np.frombuffer(buffers[2], dtype=np.uint8) if buffers[2] is not None else np.zeros(0, np.uint8)
        return _fnv1a_segments(raw, offsets), mask
    raise TypeError(f"unhashable key type {t}")


def fnv1a_str(s: str) -> int:
    """Scalar FNV-1a over utf8 bytes — the per-dictionary-entry twin of
    _fnv1a_segments, used to build device hash LUTs for string keys."""
    h = 0xCBF29CE484222325
    for b in s.encode("utf-8"):
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def _fnv1a_segments(data: np.ndarray, offsets: np.ndarray) -> np.ndarray:
    """FNV-1a per segment. Vectorized over fixed byte positions: iterate
    max_len times over a (n,) lane, cheap because strings are short keys."""
    n = len(offsets) - 1
    lens = (offsets[1:] - offsets[:-1]).astype(np.int64)
    h = np.full(n, 0xCBF29CE484222325, dtype=np.uint64)
    max_len = int(lens.max()) if n else 0
    with np.errstate(over="ignore"):
        for i in range(max_len):
            sel = lens > i
            idx = offsets[:-1][sel] + i
            h_sel = h[sel]
            h_sel = ((h_sel ^ data[idx].astype(np.uint64)) * np.uint64(0x100000001B3)).astype(np.uint64)
            h[sel] = h_sel
    return h


def hash_arrays(arrays: list[pa.Array]) -> np.ndarray:
    """Combined row hash over multiple key columns → uint64[n]."""
    n = len(arrays[0])
    out = np.zeros(n, dtype=np.uint64)
    for arr in arrays:
        enc, mask = _int64_encoding(arr)
        hv = splitmix64(enc)
        if mask is not None:
            hv = np.where(mask, hv, _NULL_TAG)
        out = hash_combine(out, hv)
    return out


def partition_indices(arrays: list[pa.Array], num_partitions: int) -> np.ndarray:
    """Row → output partition id (uint64 % K, same as the jax kernel)."""
    return (hash_arrays(arrays) % np.uint64(num_partitions)).astype(np.int64)


def split_batch_by_partition(batch: pa.RecordBatch, key_arrays: list[pa.Array], k: int,
                             precomputed_pids: np.ndarray | None = None):
    """Route a batch's rows into K partition sub-batches in one pass.

    Uses the native C++ router (hash + counting-sort grouping, then a single
    Arrow take + zero-copy slices) when available; numpy otherwise. When the
    producer already computed partition ids (device-side routing: the TPU
    stage emits a __pid column via the jax hash twin), they feed the router
    directly — pid < k, so routing on h=pid with h%k is the identity.
    Yields (partition_id, sub_batch) for non-empty partitions.
    """
    from ballista_tpu.ops import native

    if precomputed_pids is not None:
        h = precomputed_pids.astype(np.uint64)
    else:
        h = native.hash_arrays_native(key_arrays)
        if h is None:
            h = hash_arrays(key_arrays)
    routed = native.route_native(h, k)
    if routed is not None:
        _, bounds, order = routed
        taken = batch.take(pa.array(order))
        for p in range(k):
            n = int(bounds[p + 1] - bounds[p])
            if n:
                yield p, taken.slice(int(bounds[p]), n)
        return
    pids = (h % np.uint64(k)).astype(np.int64)
    for p in np.unique(pids):
        sel = np.nonzero(pids == p)[0]
        yield int(p), batch.take(pa.array(sel))
