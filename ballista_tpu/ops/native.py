"""ctypes bindings for the native C++ runtime (native/row_router.cpp).

Loads native/libballista_native.so (built by native/build.sh; auto-built on
first use when a compiler is present). Falls back to the numpy
implementations transparently — the bit contract is identical and tested
(tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np
import pyarrow as pa

log = logging.getLogger(__name__)

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libballista_native.so")


def get_lib() -> ctypes.CDLL | None:
    global _lib, _tried
    # BALLISTA_NATIVE_LIB: explicit .so override (the sanitizer leg points
    # this at an ASAN/TSAN build of the same source)
    override = os.environ.get("BALLISTA_NATIVE_LIB")
    so_path = override or _SO_PATH
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not override and not os.path.exists(so_path):
            build = os.path.join(_NATIVE_DIR, "build.sh")
            if os.path.exists(build):
                try:
                    subprocess.run(["sh", build], check=True, capture_output=True, timeout=120)
                except Exception as e:  # noqa: BLE001
                    log.info("native build unavailable (%s); using numpy paths", e)
                    return None
        if not os.path.exists(so_path):
            if override:
                log.warning("BALLISTA_NATIVE_LIB=%s does not exist; numpy fallback", so_path)
            return None
        try:
            lib = ctypes.CDLL(so_path)
            u64p = ctypes.POINTER(ctypes.c_uint64)
            i64p = ctypes.POINTER(ctypes.c_int64)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            u32p = ctypes.POINTER(ctypes.c_uint32)
            f64p = ctypes.POINTER(ctypes.c_double)
            lib.hash_mix_i64.argtypes = [u64p, i64p, u8p, ctypes.c_int64]
            lib.hash_mix_f64.argtypes = [u64p, f64p, u8p, ctypes.c_int64]
            lib.hash_mix_bytes.argtypes = [u64p, u8p, i64p, u8p, ctypes.c_int64]
            lib.route.argtypes = [u64p, ctypes.c_int64, ctypes.c_uint32, u32p, i64p, u32p]
            lib.route.restype = ctypes.c_int
            _lib = lib
        except OSError as e:
            log.info("native lib load failed (%s); using numpy paths", e)
        return _lib


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def hash_arrays_native(arrays: list[pa.Array]) -> np.ndarray | None:
    """Native row hash; None when a column type is unsupported here."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(arrays[0])
    h = np.zeros(n, dtype=np.uint64)
    for arr in arrays:
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        t = arr.type
        valid = None
        if arr.null_count:
            valid = np.asarray(arr.is_valid()).astype(np.uint8)
        vp = _ptr(valid, ctypes.c_uint8) if valid is not None else None
        if pa.types.is_integer(t) or pa.types.is_boolean(t):
            import pyarrow.compute as pc

            fill = False if pa.types.is_boolean(t) else 0
            filled = pc.fill_null(arr, fill) if arr.null_count else arr
            v = np.ascontiguousarray(
                filled.cast(pa.int64(), safe=False).to_numpy(zero_copy_only=False).astype(np.int64)
            )
            lib.hash_mix_i64(_ptr(h, ctypes.c_uint64), _ptr(v, ctypes.c_int64), vp, n)
        elif pa.types.is_date(t):
            import pyarrow.compute as pc

            as_int = arr.cast(pa.int32() if pa.types.is_date32(t) else pa.int64(), safe=False)
            filled = pc.fill_null(as_int, 0) if arr.null_count else as_int
            v = np.ascontiguousarray(
                filled.cast(pa.int64()).to_numpy(zero_copy_only=False).astype(np.int64)
            )
            lib.hash_mix_i64(_ptr(h, ctypes.c_uint64), _ptr(v, ctypes.c_int64), vp, n)
        elif pa.types.is_floating(t):
            v = np.ascontiguousarray(arr.cast(pa.float64()).to_numpy(zero_copy_only=False))
            lib.hash_mix_f64(_ptr(h, ctypes.c_uint64), _ptr(v, ctypes.c_double), vp, n)
        elif pa.types.is_string(t) or pa.types.is_large_string(t) or pa.types.is_binary(t):
            data = arr.cast(pa.large_binary())
            bufs = data.buffers()
            offsets = np.frombuffer(bufs[1], dtype=np.int64, count=len(arr) + 1 + data.offset)
            offsets = np.ascontiguousarray(offsets[data.offset : data.offset + len(arr) + 1])
            raw = (
                np.frombuffer(bufs[2], dtype=np.uint8)
                if bufs[2] is not None
                else np.zeros(1, np.uint8)
            )
            lib.hash_mix_bytes(
                _ptr(h, ctypes.c_uint64), _ptr(np.ascontiguousarray(raw), ctypes.c_uint8),
                _ptr(offsets, ctypes.c_int64), vp, n,
            )
        else:
            if pa.types.is_dictionary(t):
                return hash_arrays_native([arr.cast(t.value_type)] ) if len(arrays) == 1 else None
            return None
    return h


def route_native(h: np.ndarray, k: int):
    """(pids, bounds, order): partition-grouped selection vectors in one pass."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(h)
    pids = np.empty(n, dtype=np.uint32)
    bounds = np.zeros(k + 1, dtype=np.int64)
    order = np.empty(n, dtype=np.uint32)
    lib.route(_ptr(np.ascontiguousarray(h), ctypes.c_uint64), n, k,
              _ptr(pids, ctypes.c_uint32), _ptr(bounds, ctypes.c_int64),
              _ptr(order, ctypes.c_uint32))
    return pids, bounds, order
