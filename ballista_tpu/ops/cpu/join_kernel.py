"""Vectorized equi-join matching on the host.

Strategy: encode the join keys of both sides into one composite int64 id
space (joint dictionary-encode per column, then mix), then sort-probe with
searchsorted. Handles duplicate keys (full match expansion), NULL keys
(never match), and multi-column keys. This same algorithm — sorted build
side + binary-search probe — is what the TPU engine expresses in jax
(ops/tpu/kernels.py), so CPU and TPU joins share shape and semantics.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc


def _combined_ids(build_cols: list[pa.Array], probe_cols: list[pa.Array]) -> tuple[np.ndarray, np.ndarray]:
    """Encode key columns of both sides into one id space.

    Returns (build_ids, probe_ids) int64, -1 marks NULL (never matches).
    """
    nb = len(build_cols[0])
    b_ids = np.zeros(nb, dtype=np.int64)
    p_ids = np.zeros(len(probe_cols[0]), dtype=np.int64)
    b_null = np.zeros(nb, dtype=bool)
    p_null = np.zeros(len(probe_cols[0]), dtype=bool)
    for bcol, pcol in zip(build_cols, probe_cols):
        if isinstance(bcol, pa.ChunkedArray):
            bcol = bcol.combine_chunks()
        if isinstance(pcol, pa.ChunkedArray):
            pcol = pcol.combine_chunks()
        if bcol.type != pcol.type:
            target = _common_type(bcol.type, pcol.type)
            bcol = bcol.cast(target)
            pcol = pcol.cast(target)
        both = pa.chunked_array([bcol, pcol]) if len(pcol) else pa.chunked_array([bcol])
        codes_arr = pc.dictionary_encode(both).combine_chunks()
        codes = codes_arr.indices.to_numpy(zero_copy_only=False)
        codes = np.where(np.isnan(codes), -1, codes).astype(np.int64) if codes.dtype.kind == "f" else codes.astype(np.int64)
        card = len(codes_arr.dictionary) + 1
        bc = codes[:nb]
        pc_ = codes[nb:] if len(pcol) else np.zeros(0, dtype=np.int64)
        b_null |= bc < 0
        p_null |= pc_ < 0
        b_ids = b_ids * card + (bc + 1)
        p_ids = p_ids * card + (pc_ + 1)
    b_ids[b_null] = -1
    p_ids[p_null] = -2  # distinct from build's null so they never match
    return b_ids, p_ids


def _common_type(a: pa.DataType, b: pa.DataType) -> pa.DataType:
    if pa.types.is_floating(a) or pa.types.is_floating(b):
        return pa.float64()
    if pa.types.is_integer(a) and pa.types.is_integer(b):
        return pa.int64()
    if pa.types.is_string(a) or pa.types.is_string(b):
        return pa.string()
    return a


def _key_np(arr: pa.Array, target: pa.DataType):
    """(numpy values, null mask|None) for a join key column; values are
    comparable within one column's space (null slots hold fills)."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if arr.type != target:
        arr = arr.cast(target, safe=False)
    null = np.asarray(pc.is_null(arr)) if arr.null_count else None
    t = arr.type
    if pa.types.is_date(t) or pa.types.is_timestamp(t):
        # pyarrow has no direct date32→int64 cast; hop through the storage int
        arr = arr.cast(pa.int32() if pa.types.is_date32(t) else pa.int64(), safe=False)
        t = arr.type
    if pa.types.is_integer(t) or pa.types.is_boolean(t):
        filled = pc.fill_null(arr, False if pa.types.is_boolean(t) else 0) if arr.null_count else arr
        return filled.cast(pa.int64(), safe=False).to_numpy(zero_copy_only=False), null
    if pa.types.is_floating(t):
        filled = pc.fill_null(arr, 0.0) if arr.null_count else arr
        return filled.cast(pa.float64()).to_numpy(zero_copy_only=False), null
    # strings / binary: object arrays (python compare); null slots fill ""
    filled = pc.fill_null(arr, "") if arr.null_count else arr
    return filled.to_numpy(zero_copy_only=False), null


class PreparedBuild:
    """Build side encoded + sorted ONCE, probed many times.

    The join executes per probe batch; re-encoding a multi-million-row
    build side (or rebuilding a hash set of it, as pyarrow's index_in does
    per call) for every batch dominated q21's runtime. Preparation sorts
    each key column's distinct values once; probe batches map in with a
    pure-numpy binary search — absent values get no code and never match."""

    def __init__(self, build_cols: list[pa.Array]):
        self.n = len(build_cols[0]) if build_cols else 0
        self.sorted_vals: list[np.ndarray] = []
        self.types: list[pa.DataType] = []
        self.cards: list[int] = []
        b_ids = np.zeros(self.n, dtype=np.int64)
        b_null = np.zeros(self.n, dtype=bool)
        for bcol in build_cols:
            t = bcol.type if not isinstance(bcol, pa.ChunkedArray) else bcol.combine_chunks().type
            vals, null = _key_np(bcol, t)
            self.types.append(t)
            uniq = np.unique(vals)
            codes = np.searchsorted(uniq, vals)
            card = len(uniq) + 1
            self.sorted_vals.append(uniq)
            self.cards.append(card)
            if null is not None:
                b_null |= null
            b_ids = b_ids * card + (codes + 1)
        b_ids[b_null] = -1
        order = np.argsort(b_ids, kind="stable")
        sorted_ids = b_ids[order]
        start_valid = np.searchsorted(sorted_ids, 0, side="left")  # ids >= 0
        self.sorted_valid = sorted_ids[start_valid:]
        self.order_valid = order[start_valid:]

    def probe_ids(self, probe_cols: list[pa.Array]) -> np.ndarray:
        p_ids = np.zeros(len(probe_cols[0]), dtype=np.int64)
        p_null = np.zeros(len(probe_cols[0]), dtype=bool)
        for pcol, uniq, card, t in zip(probe_cols, self.sorted_vals, self.cards, self.types):
            vals, null = _key_np(pcol, t)
            pos = np.searchsorted(uniq, vals)
            posc = np.clip(pos, 0, max(len(uniq) - 1, 0))
            if len(uniq):
                present = uniq[posc] == vals
            else:
                present = np.zeros(len(vals), dtype=bool)
            codes = np.where(present, posc, -1)
            if null is not None:
                codes = np.where(null, -1, codes)
            p_null |= codes < 0  # input NULL or value absent from the build
            p_ids = p_ids * card + (codes + 1)
        p_ids[p_null] = -2
        return p_ids

    def match(self, probe_cols: list[pa.Array]):
        """All matching (build_idx, probe_idx) pairs for one probe batch."""
        p_ids = self.probe_ids(probe_cols)
        lo = np.searchsorted(self.sorted_valid, p_ids, side="left")
        hi = np.searchsorted(self.sorted_valid, p_ids, side="right")
        counts = hi - lo
        total = int(counts.sum())
        if total == 0:
            return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64)
        probe_idx = np.repeat(np.arange(len(p_ids), dtype=np.int64), counts)
        # expand [lo, hi) ranges: standard cumsum trick
        offs = np.concatenate(([0], np.cumsum(counts)[:-1]))
        flat = np.arange(total, dtype=np.int64) - np.repeat(offs, counts) + np.repeat(lo, counts)
        build_idx = self.order_valid[flat]
        return build_idx, probe_idx


def match_pairs(build_cols: list[pa.Array], probe_cols: list[pa.Array]):
    """All matching (build_idx, probe_idx) pairs.

    Returns (build_idx int64[M], probe_idx int64[M]); NULL keys never match.
    One-shot form; executors that probe many batches against one build use
    PreparedBuild directly."""
    b_ids, p_ids = _combined_ids(build_cols, probe_cols)
    order = np.argsort(b_ids, kind="stable")
    sorted_ids = b_ids[order]
    # exclude nulls from the probe-able range
    start_valid = np.searchsorted(sorted_ids, 0, side="left")  # ids >= 0
    sorted_valid = sorted_ids[start_valid:]
    order_valid = order[start_valid:]

    lo = np.searchsorted(sorted_valid, p_ids, side="left")
    hi = np.searchsorted(sorted_valid, p_ids, side="right")
    counts = hi - lo
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64)
    probe_idx = np.repeat(np.arange(len(p_ids), dtype=np.int64), counts)
    # expand [lo, hi) ranges: standard cumsum trick
    offs = np.concatenate(([0], np.cumsum(counts)[:-1]))
    flat = np.arange(total, dtype=np.int64) - np.repeat(offs, counts) + np.repeat(lo, counts)
    build_idx = order_valid[flat]
    return build_idx, probe_idx
