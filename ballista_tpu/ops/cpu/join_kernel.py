"""Vectorized equi-join matching on the host.

Strategy: encode the join keys of both sides into one composite int64 id
space (joint dictionary-encode per column, then mix), then sort-probe with
searchsorted. Handles duplicate keys (full match expansion), NULL keys
(never match), and multi-column keys. This same algorithm — sorted build
side + binary-search probe — is what the TPU engine expresses in jax
(ops/tpu/kernels.py), so CPU and TPU joins share shape and semantics.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc


def _combined_ids(build_cols: list[pa.Array], probe_cols: list[pa.Array]) -> tuple[np.ndarray, np.ndarray]:
    """Encode key columns of both sides into one id space.

    Returns (build_ids, probe_ids) int64, -1 marks NULL (never matches).
    """
    nb = len(build_cols[0])
    b_ids = np.zeros(nb, dtype=np.int64)
    p_ids = np.zeros(len(probe_cols[0]), dtype=np.int64)
    b_null = np.zeros(nb, dtype=bool)
    p_null = np.zeros(len(probe_cols[0]), dtype=bool)
    for bcol, pcol in zip(build_cols, probe_cols):
        if isinstance(bcol, pa.ChunkedArray):
            bcol = bcol.combine_chunks()
        if isinstance(pcol, pa.ChunkedArray):
            pcol = pcol.combine_chunks()
        if bcol.type != pcol.type:
            target = _common_type(bcol.type, pcol.type)
            bcol = bcol.cast(target)
            pcol = pcol.cast(target)
        both = pa.chunked_array([bcol, pcol]) if len(pcol) else pa.chunked_array([bcol])
        codes_arr = pc.dictionary_encode(both).combine_chunks()
        codes = codes_arr.indices.to_numpy(zero_copy_only=False)
        codes = np.where(np.isnan(codes), -1, codes).astype(np.int64) if codes.dtype.kind == "f" else codes.astype(np.int64)
        card = len(codes_arr.dictionary) + 1
        bc = codes[:nb]
        pc_ = codes[nb:] if len(pcol) else np.zeros(0, dtype=np.int64)
        b_null |= bc < 0
        p_null |= pc_ < 0
        b_ids = b_ids * card + (bc + 1)
        p_ids = p_ids * card + (pc_ + 1)
    b_ids[b_null] = -1
    p_ids[p_null] = -2  # distinct from build's null so they never match
    return b_ids, p_ids


def _common_type(a: pa.DataType, b: pa.DataType) -> pa.DataType:
    if pa.types.is_floating(a) or pa.types.is_floating(b):
        return pa.float64()
    if pa.types.is_integer(a) and pa.types.is_integer(b):
        return pa.int64()
    if pa.types.is_string(a) or pa.types.is_string(b):
        return pa.string()
    return a


def match_pairs(build_cols: list[pa.Array], probe_cols: list[pa.Array]):
    """All matching (build_idx, probe_idx) pairs.

    Returns (build_idx int64[M], probe_idx int64[M]); NULL keys never match.
    """
    b_ids, p_ids = _combined_ids(build_cols, probe_cols)
    order = np.argsort(b_ids, kind="stable")
    sorted_ids = b_ids[order]
    # exclude nulls from the probe-able range
    start_valid = np.searchsorted(sorted_ids, 0, side="left")  # ids >= 0
    sorted_valid = sorted_ids[start_valid:]
    order_valid = order[start_valid:]

    lo = np.searchsorted(sorted_valid, p_ids, side="left")
    hi = np.searchsorted(sorted_valid, p_ids, side="right")
    counts = hi - lo
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64)
    probe_idx = np.repeat(np.arange(len(p_ids), dtype=np.int64), counts)
    # expand [lo, hi) ranges: standard cumsum trick
    offs = np.concatenate(([0], np.cumsum(counts)[:-1]))
    flat = np.arange(total, dtype=np.int64) - np.repeat(offs, counts) + np.repeat(lo, counts)
    build_idx = order_valid[flat]
    return build_idx, probe_idx
